"""Continuous-batching serving engine: paged KV pool + fused K-step decode.

The fixed-batch sampler (midgpt_tpu.sampling.generate) holds one ring
cache sized per request batch and dispatches every decode step; under real
traffic that leaves decode slots idle whenever requests finish early and
pays the full per-dispatch latency (+25-50 ms/launch on a bad relay day,
PERF.md r5) once per generated token. This engine replaces both:

- **Paged KV** (serving.paged): requests own page lists in a shared pool,
  so admission is a page allocation, eviction a free — no cache reshapes.
- **Prefix caching with copy-on-write sharing** (serving.paged): pages
  are refcounted and full pages are content-indexed by their token
  prefix chain; a new request's block table points straight at already-
  resident pages of any live or finished request, its prefill computes
  only the uncached suffix, and the one partially-shared page is copied
  before the request may append (never two writers on a page). Finished
  requests' pages go COLD (refcount 0, still resident) and serve future
  hits until page pressure reclaims them, LRU, leaves first.
- **Chunked prefill** (Sarathi-style): long prompts prefill in fixed-
  token-budget chunks interleaved between the fused decode windows
  instead of monopolizing one, bounding TTFT for co-scheduled requests;
  a chunk resumes mid-prompt from the partially-built block table
  (models.gpt.prefill_chunk_paged), so chunking is exact, not windowed.
- **Continuous batching**: a host-side scheduler admits queued requests
  into free decode slots at every window boundary, interleaves their
  prefills with decode, and evicts (re-queues with progress kept) under
  page pressure — slots stay full under mixed traffic.
- **Self-speculative decoding** (serving.speculate + the verify program
  below): a host-side n-gram proposer drafts up to ``speculate`` tokens
  per request from the request's OWN prompt+generated history (prompt-
  lookup style — no draft model, composes with every config), and one
  jitted pool/logits-donating dispatch scores all slots' ``spec_len+1``
  candidate rows in one joint-softmax multi-query pass whose arithmetic
  mirrors the decode window's op for op (gpt.verify_paged_at — bf16
  near-ties flip under any other dtype choreography).
  Acceptance is longest-prefix: argmax agreement at ``temperature ==
  0``, REJECTION SAMPLING at ``temperature > 0`` (accept draft t with
  probability ``min(1, p_target(t)/q_draft(t))`` against the decode
  sampler's own tempered/top-k distribution; on rejection the carried
  logits encode the normalized residual ``max(p - q, 0)`` so the next
  dispatch's row-0 draw IS the resample — see _build_verify_program).
  Each dispatch emits 1 + accepted tokens (the "+1" is the previous
  dispatch's bonus token, materialized from the carried logits).
  Rejected rows roll back via a per-slot write watermark: their K/V
  never lands in the pages, so the single-writer / refcount /
  prefix-index invariants are untouched. Greedy outputs are
  token-identical to the non-speculative engine, sampled outputs are
  distributed exactly as it and keep its bitwise scheduling invariance
  — speculation changes the dispatch count, not the stream contract.
- **Int8 quantized weight path** (``quant="int8"``, midgpt_tpu.quant):
  every program the engine compiles streams int8 per-output-channel
  weights with the dequantization fused into each matmul's epilogue —
  halving the per-token weight HBM stream that dominates the decode
  floor. Po2 scales keep greedy output token-identical to the engine
  running the dequantized weights; the programs take the model as an
  ENTRY PARAMETER (closed over, jax would bake the weights in as
  constants — and constant-fold the quantized dequant back to f32).
- **Fused multi-token dispatch** (the PR 2 design, ported to decode): one
  jitted, state-donating ``lax.scan`` runs K whole-model decode steps —
  all layers, sampling, and the bulk page flush — per XLA launch.
  Per-slot EOS/length masks are carried IN-SCAN: finished requests pad
  harmlessly (writes dropped, emissions masked) until the next host-side
  swap boundary. Dispatches per generated token drop from 1 per token to
  1/K per active batch.

- **Fused layer scan** (``layer_scan="on"``, models.gpt): every
  program's per-layer loop folds into ONE ``lax.scan`` over the stacked
  block params — one inlined layer body per program instead of L, the
  launch structure the decode residual over the HBM floor is made of.
  Bitwise the unrolled programs (the scan body calls the same per-layer
  methods on per-layer xs views), gated by the analysis.fusion
  scan-equivalence prover + the analysis.dispatch launch budgets.

Determinism contract: per-request sampling keys derive from
``fold_in(fold_in(key, request_seed), tokens_emitted_so_far)``
(sampling.derive_request_key) — the token stream of a request is a
function of the request alone, independent of which slot it lands in,
the window size K, batch composition, any mid-run eviction/re-admission,
prefix-cache hits, and prefill chunking. Speculation at temperature > 0
keeps the contract: its acceptance uniforms come from a SALTED substream
of the same per-position derived key (sampling.SPEC_ACCEPT_SALT), so
they too are functions of (request seed, stream position) only.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import time
import typing as tp

import jax
import jax.numpy as jnp
import numpy as np

from midgpt_tpu.models.gpt import (
    GPT,
    decode_step_paged,
    prefill_chunk_paged,
    verify_tokens_paged,
)
from midgpt_tpu.serving.faults import (
    AdmissionRejected,
    HandoffFailed,
    PoolOverloaded,
)
from midgpt_tpu.serving.speculate import NgramProposer, Proposer
from midgpt_tpu.serving.telemetry import (
    EngineTelemetry,
    MetricsRegistry,
    write_json,
)
from midgpt_tpu.serving.paged import (
    HostSpillStore,
    PageAllocator,
    PagedKVPool,
    PrefixIndex,
    copy_page,
    export_pages,
    flush_recent,
    import_pages,
    pages_needed,
    write_token_rows,
)

Array = jax.Array


# ---------------------------------------------------------------------------
# Compiled programs
# ---------------------------------------------------------------------------

# Program cache: since the model is an ENTRY PARAMETER (not a closure
# constant — see window_fn), a program factory's output depends only on
# the model CONFIG and the scalar geometry, so identical geometries
# share one jitted callable — and therefore one XLA compilation per
# model structure/dtype (jax.jit caches per wrapper; a fresh wrapper
# per ServingEngine would recompile the same program every time an
# engine is constructed, which the test suite does dozens of times).
_PROGRAM_CACHE: tp.Dict[tp.Tuple, tp.Any] = {}


def serving_logical_rules(prefill_sp: str = "off") -> tp.Dict[str, tp.Any]:
    """The activation logical-rule table the serving programs compile
    under: the training table with 'batch' and 'seq' unmapped. Inside
    ONE engine the slot dim is NEVER a sharded axis — data parallelism
    is shared-nothing engine replicas (serving.cluster), and a
    replica/fsdp axis on the engine's own mesh must ride replicated.
    (Left on the training mapping, the model's generic
    ``shard_act(x, 'batch', ...)`` tags would shard slots over
    'replica', and the partitioner then bounces every per-slot
    activation between sharded and replicated through the page
    gathers — the exact batch all-gather the
    no-batch-allgather-in-page-gather audit rule flags; found by that
    rule on the first tp=2,replica=2 audit.) 'seq' is unmapped for the
    same reason: decode is one token deep and a prefill chunk is one
    slot wide — there is nothing to shard.

    The one exception is the SP prefill-chunk program
    (``prefill_sp="on"``): a long-prompt chunk IS many tokens deep, and
    its replicated per-token segments shard their rows over 'tensor'
    through the dedicated 'sp' logical axis
    (models.gpt.prefill_chunk_paged sp=True). 'sp' stays unmapped for
    every other program — decode/verify never see the axis, so no
    decode bytes move when the knob flips."""
    from midgpt_tpu.parallel.sharding import DEFAULT_LOGICAL_RULES

    assert prefill_sp in ("on", "off"), prefill_sp
    rules = {**DEFAULT_LOGICAL_RULES, "batch": None, "seq": None}
    if prefill_sp == "on":
        rules["sp"] = "tensor"
    return rules


def _mesh_key(mesh) -> tp.Optional[tp.Tuple]:
    """Explicit cache fingerprint of a serving mesh: axis names/sizes AND
    the concrete device ids. Program identity depends on both — a tp=2
    engine must never reuse a tp=1 program (different partitioning), and
    two DP replicas pinned to disjoint device sets must not share a
    wrapper either (same geometry, different placement — jax.jit would
    recompile per sharding anyway, but sharing the wrapper would
    interleave two replicas' executable caches and hide placement bugs
    from the cache-distinctness test). ``None`` stays ``None`` (the
    single-chip path)."""
    if mesh is None:
        return None
    return (
        tuple(mesh.shape.items()),
        tuple(d.id for d in mesh.devices.flat),
    )


def _cached_program(key: tp.Tuple, build: tp.Callable[[], tp.Any]):
    fn = _PROGRAM_CACHE.get(key)
    if fn is None:
        fn = build()
        _PROGRAM_CACHE[key] = fn
    return fn


def make_decode_window(
    model: GPT,
    *,
    slots: int,
    window: int,
    pmax: int,
    rope_len: int,
    pad_id: int = 0,
    temperature: float = 0.0,
    top_k: tp.Optional[int] = None,
    mesh=None,
    paged_kernel: str = "xla",
    layer_scan: str = "off",
):
    # paged_kernel/layer_scan sit BEFORE the mesh fingerprint: the
    # fingerprint stays the key's last element (the cache-distinctness
    # test and any cache introspection key off that position)
    key = (
        "decode_window", model.config, slots, window, pmax, rope_len,
        pad_id, temperature, top_k, paged_kernel, layer_scan,
        _mesh_key(mesh),
    )
    return _cached_program(
        key,
        lambda: _build_decode_window(
            model.config, slots=slots, window=window, pmax=pmax,
            rope_len=rope_len, pad_id=pad_id, temperature=temperature,
            top_k=top_k, mesh=mesh, paged_kernel=paged_kernel,
            layer_scan=layer_scan,
        ),
    )


def _build_decode_window(
    cfg,
    *,
    slots: int,
    window: int,
    pmax: int,
    rope_len: int,
    pad_id: int,
    temperature: float,
    top_k: tp.Optional[int],
    mesh,
    paged_kernel: str = "xla",
    layer_scan: str = "off",
):
    """The fused K-step decode program: ONE jitted, pool/logits-donating
    ``lax.scan`` over ``window`` whole-model decode steps.
    ``layer_scan="on"`` additionally folds each step's layer loop into
    one inner ``lax.scan`` (models.gpt.decode_step_paged — bitwise the
    unrolled program, gated by the analysis.fusion scan-equivalence
    prover and the analysis.dispatch launch budgets).

    Per scan step: sample each slot's next token from the carried logits,
    mark slots that just hit EOS/length done, run the paged decode step
    (models.gpt.decode_step_paged) for all slots SIMD-style, and collect
    (token, emit-mask, write-mask) as scan outputs. After the scan the
    window's recent K/V rows flush into the pages in one bulk scatter —
    still inside the same compiled program, so steady-state decode is
    exactly one XLA dispatch per K generated tokens per active batch.

    Finished/empty slots ride along masked: they sample pad, their page
    writes route to the drop sentinel, and their emissions are masked out
    host-side — the scan shape never depends on traffic. Slots still
    mid-prefill ride the same way (``done`` carries them), so chunked
    prefill and decode interleave without a second program shape.
    """
    from midgpt_tpu.parallel.sharding import axis_rules, shard_act
    from midgpt_tpu.sampling import derive_request_key, sample_token

    rshape = (cfg.n_layer, slots, cfg.kv_heads, window, cfg.head_dim)

    def window_fn(
        model: GPT,  # ENTRY PARAMETER, not a closure constant: closed
        # over, jax bakes every weight into the executable as an HLO
        # constant — and for a quantized model XLA then CONSTANT-FOLDS
        # the dequant (convert + scale) into full f32 weight matrices,
        # silently doubling the weight stream the int8 path exists to
        # halve (caught by the no-dequant-materialization audit)
        pool: PagedKVPool,  # DONATED
        logits: Array,  # [S, V] f32 — per-slot next-token logits; DONATED
        bt: Array,  # [S, Pmax] int32 block tables
        pooled_len: Array,  # [S] int32 — tokens resident in the pool
        done: Array,  # [S] bool — finished or empty slot
        emitted: Array,  # [S] int32 — tokens emitted so far per request
        budget: Array,  # [S] int32 — max_new_tokens per request
        eos: Array,  # [S] int32 — per-request EOS id (-1 = none)
        seeds: Array,  # [S] int32 — per-request sampling seed
        key: Array,  # base PRNG key (engine-constant)
    ):
        assert bt.shape == (slots, pmax), (
            f"block table {bt.shape} != declared geometry ({slots}, {pmax})"
        )
        with axis_rules(mesh, serving_logical_rules()):
            # recent rows travel in the pool's ROW dtype: the pool dtype
            # for float pools, bf16 grid-rounded values for int8 pools
            # (PagedKVPool.row_dtype)
            rk = jnp.zeros(rshape, pool.row_dtype)
            rv = jnp.zeros(rshape, pool.row_dtype)

            def sample(lg, em):
                if temperature == 0.0:
                    return jnp.argmax(lg, axis=-1).astype(jnp.int32)
                # per-request key stream: (seed, emitted-count) — slot-,
                # window-, and eviction-invariant
                ks = jax.vmap(
                    lambda sd, ti: derive_request_key(key, sd, ti)
                )(seeds, em)
                return jax.vmap(
                    lambda l1, k1: sample_token(
                        l1[None], k1, temperature, top_k
                    )[0]
                )(lg, ks)

            def body(carry, r):
                logits, rk, rv, done, emitted = carry
                pre_done = done
                tok = sample(logits, emitted)
                tok = jnp.where(pre_done, jnp.int32(pad_id), tok)
                emitted = emitted + (~pre_done).astype(jnp.int32)
                hit_eos = (~pre_done) & (tok == eos)
                hit_len = (~pre_done) & (emitted >= budget)
                done = pre_done | hit_eos | hit_len
                # the just-sampled token is this step's model input; its
                # K/V row is only needed if a real token can follow it
                write_valid = ~done
                pos = pooled_len + r  # per-slot absolute position
                new_logits, rk, rv = decode_step_paged(
                    model, tok, pos, pool.k, pool.v, bt, rk, rv, r,
                    pooled_len, rope_len, pool_sk=pool.scale_k,
                    pool_sv=pool.scale_v, paged_kernel=paged_kernel,
                    layer_scan=layer_scan,
                )
                # the carry is f32 regardless of compute dtype (an exact
                # widening — sampling sees the same values either way)
                new_logits = new_logits.astype(logits.dtype)
                return (
                    (new_logits, rk, rv, done, emitted),
                    (tok, ~pre_done, write_valid),
                )

            (logits, rk, rv, done, emitted), (toks, emit, wvalid) = (
                jax.lax.scan(
                    body,
                    (logits, rk, rv, done, emitted),
                    jnp.arange(window, dtype=jnp.int32),
                )
            )
            pool = flush_recent(
                pool, rk, rv, bt, pooled_len, jnp.transpose(wvalid)
            )
            new_len = pooled_len + jnp.sum(wvalid.astype(jnp.int32), axis=0)
            # pin the donated logits carry vocab-sharded on the way out
            # (same spec the engine committed the input with — donation
            # silently drops if the output resharded)
            logits = shard_act(logits, None, "vocab")
        return pool, logits, toks, emit, done, new_len, emitted

    return jax.jit(window_fn, donate_argnums=(1, 2))


def make_prefill_chunk_program(
    model: GPT, *, chunk_len: int, pmax: int, rope_len: int, mesh=None,
    layer_scan: str = "off", prefill_sp: str = "off",
):
    key = (
        "prefill_chunk", model.config, chunk_len, pmax, rope_len,
        layer_scan, prefill_sp, _mesh_key(mesh),
    )
    return _cached_program(
        key,
        lambda: _build_prefill_chunk_program(
            model.config, chunk_len=chunk_len, pmax=pmax,
            rope_len=rope_len, mesh=mesh, layer_scan=layer_scan,
            prefill_sp=prefill_sp,
        ),
    )


def _build_prefill_chunk_program(
    cfg, *, chunk_len: int, pmax: int, rope_len: int, mesh,
    layer_scan: str = "off", prefill_sp: str = "off",
):
    """A prefill-chunk program for one padded chunk length: one forward
    over the chunk's tokens attending to the slot's already-resident
    pages (models.gpt.prefill_chunk_paged), a token-granular bulk page
    scatter, and the slot's logits row updated from the chunk's last
    real token — so the FINAL chunk of a prompt leaves exactly the
    logits a monolithic prefill would. Pool and logits are donated (the
    audit gates on it: a chunk runs between every pair of decode windows
    under chunked prefill, and an un-aliased pool would double KV HBM on
    the serving hot path). One compile per padded chunk length — the
    engine buckets chunks to powers-of-two page counts, and fixed-size
    chunking hits a single bucket in steady state."""
    from midgpt_tpu.parallel.sharding import axis_rules, shard_act

    assert chunk_len <= cfg.block_size, (chunk_len, cfg.block_size)

    def chunk_fn(
        model: GPT,  # entry parameter (same constant-folding trap as
        # the decode window — see make_decode_window)
        pool: PagedKVPool,  # DONATED
        logits: Array,  # [S, V] DONATED
        slot: Array,  # [] int32 — the prefilling slot
        tokens: Array,  # [1, chunk_len] int32 (right-padded)
        start: Array,  # [] int32 — absolute position of chunk token 0
        real_n: Array,  # [] int32 — real tokens in this chunk
        bt_row: Array,  # [pmax] int32 — the slot's block table
    ):
        with axis_rules(mesh, serving_logical_rules(prefill_sp)):
            h, ks, vs = prefill_chunk_paged(
                model, tokens, start, pool.k, pool.v, bt_row[None, :],
                rope_len, pool_sk=pool.scale_k, pool_sv=pool.scale_v,
                layer_scan=layer_scan, sp=(prefill_sp == "on"),
            )  # h: [1, T, D]; ks/vs: [L, 1, Hkv, T, C]
            h_last = jax.lax.dynamic_slice_in_dim(
                h, real_n - 1, 1, axis=1
            )[:, 0]  # [1, D]
            # vocab-sharded row update at vocab offset 0 (full-width on
            # the sharded dim: shard-local), keeping the donated logits
            # buffer on its committed sharding
            row = shard_act(model.project(h_last), None, "vocab")
            row = row.astype(logits.dtype)[0]
            logits = jax.lax.dynamic_update_slice(
                logits, row[None], (slot, jnp.zeros((), slot.dtype))
            )
            logits = shard_act(logits, None, "vocab")
            # page write AFTER the head projection (no data dependence
            # between them — a pure trace reorder): the lm head is the
            # trace's last weight projection in every serving program,
            # which is the layer-boundary structure the scan-equivalence
            # prover's per-layer segmentation keys on (an int8 pool's
            # page-birth quantization arithmetic would otherwise land
            # inside the LAST layer's segment and break homogeneity)
            pool = write_token_rows(
                pool, ks[:, 0], vs[:, 0], bt_row, start, real_n
            )
        return pool, logits

    return jax.jit(chunk_fn, donate_argnums=(1, 2))


def make_verify_program(
    model: GPT,
    *,
    slots: int,
    spec_len: int,
    pmax: int,
    rope_len: int,
    pad_id: int = 0,
    temperature: float = 0.0,
    top_k: tp.Optional[int] = None,
    soft_drafts: bool = False,
    mesh=None,
    paged_kernel: str = "xla",
    layer_scan: str = "off",
):
    # temperature == 0.0 builds the exact greedy program (same signature,
    # same arithmetic — no seeds/key entry args); sampling params join
    # the cache key only as the knobs they are. soft_drafts (a proposer
    # that supplies a dense draft distribution — the injectable test
    # path) is a distinct program SHAPE: it adds a [S, spec_len, V]
    # entry tensor the default one-hot path deliberately never
    # materializes (see _build_verify_program).
    key = (
        "verify", model.config, slots, spec_len, pmax, rope_len, pad_id,
        temperature, top_k, soft_drafts, paged_kernel, layer_scan,
        _mesh_key(mesh),
    )
    return _cached_program(
        key,
        lambda: _build_verify_program(
            model.config, slots=slots, spec_len=spec_len, pmax=pmax,
            rope_len=rope_len, pad_id=pad_id, temperature=temperature,
            top_k=top_k, soft_drafts=soft_drafts, mesh=mesh,
            paged_kernel=paged_kernel, layer_scan=layer_scan,
        ),
    )


def _build_verify_program(
    cfg,
    *,
    slots: int,
    spec_len: int,
    pmax: int,
    rope_len: int,
    pad_id: int,
    mesh,
    paged_kernel: str = "xla",
    layer_scan: str = "off",
    temperature: float = 0.0,
    top_k: tp.Optional[int] = None,
    soft_drafts: bool = False,
):
    """The speculative-decoding verification program: ONE jitted,
    pool/logits-donating dispatch that scores every slot's
    ``[T = spec_len + 1]`` candidate rows (the true next token,
    materialized in-program from the carried logits, followed by the
    host's drafts) against the resident paged KV via
    ``models.gpt.verify_tokens_paged``, computes longest-prefix
    acceptance, EOS/budget truncation, and the per-slot WRITE WATERMARK,
    and folds only the accepted rows' K/V into the pages (one bulk
    scatter — rejected rows route to the drop sentinel, which IS the
    rollback: stale speculation never becomes visible to the pool, the
    prefix index, or another block table).

    At ``temperature == 0`` acceptance is greedy argmax agreement: draft
    row j is accepted iff it equals the argmax after row j-1 — chained
    from row 0, exactly the token the plain engine would have produced
    there, so greedy speculation is token-identical to the plain window.

    At ``temperature > 0`` acceptance is REJECTION SAMPLING, still in
    the same single dispatch: row 0 is drawn by the very
    ``sampling.sample_token`` the decode window uses, under the same
    per-request ``derive_request_key(key, seed, emitted)`` — so sampled
    row 0 is bitwise what the plain window's first step would have
    drawn. Draft row j (token t, draft probability q(t)) is accepted iff
    ``u_j * q(t) <= p(t)`` where ``p = target_probs(logits after row
    j-1)`` is the decode sampler's own distribution (softmax of the
    SAME tempered/top-k-masked logits ``sample_token`` draws from) and
    ``u_j`` is a uniform keyed by a SALTED substream of the position's
    derived key — a function of (request seed, stream position) only,
    never slot/window/batch, so sampled streams keep the greedy path's
    bitwise scheduling invariance. n-gram drafts carry one-hot draft
    probabilities (``q(t) = 1``, built in-program — no dense tensor
    crosses the dispatch boundary; see serving.speculate), collapsing
    the test to ``u <= p(t)``; a ``soft_drafts`` proposer ships a dense
    ``[S, spec_len, V]`` distribution instead (the injectable test path
    that exercises the general acceptance ratio).

    On rejection the program does NOT emit a resample token in-dispatch
    (the rejected row's K/V encodes the DRAFT token — emitting anything
    else would corrupt the pool). Instead the carried logits become
    ``temperature * log(normalize(max(p - q, 0)))`` — the residual
    distribution, encoded so the NEXT dispatch's ordinary row-0
    ``sample_token`` at that position's derived key IS the residual
    draw (``sampling.residual_logits`` documents the exactness
    argument). On full acceptance (or EOS/budget truncation) the carry
    is the last emitted row's raw logits, as in the greedy program —
    the next row-0 draw is then the standard speculative-sampling bonus
    token from the full target distribution. Either way every dispatch
    emits ``1 + accepted`` tokens and the stream is distributed exactly
    as the non-speculative sampled engine (classic speculative-sampling
    exactness, statistically tested in tests/test_serving.py; the
    acceptance/residual dtype choreography is proven by
    analysis.choreo's sampled-verify checks).

    Slot semantics mirror :func:`make_decode_window` exactly: done/empty
    slots ride along masked (pad candidates, no emissions, no writes),
    budget counts emitted tokens, an emitted EOS is kept and everything
    after it dropped, and a terminal token's K/V row is not written (no
    real token can follow it)."""
    from midgpt_tpu import sampling as sampling_mod
    from midgpt_tpu.parallel.sharding import axis_rules, shard_act
    from midgpt_tpu.sampling import (
        SPEC_ACCEPT_SALT,
        derive_request_key,
        residual_logits,
        sample_token,
        target_probs,
    )

    assert spec_len >= 1, spec_len
    assert not (soft_drafts and temperature == 0.0), (
        "soft_drafts is a sampled-verify program shape; greedy "
        "acceptance never reads draft probabilities"
    )
    t = spec_len + 1

    def _verify_core(
        model: GPT,  # ENTRY PARAMETER (constant-folding trap, see
        # make_decode_window)
        pool: PagedKVPool,  # DONATED
        logits: Array,  # [S, V] f32 — per-slot next-token logits; DONATED
        bt: Array,  # [S, Pmax] int32 block tables
        pooled_len: Array,  # [S] int32 — write watermark (tokens resident)
        done: Array,  # [S] bool — finished or empty slot
        emitted: Array,  # [S] int32 — tokens emitted so far per request
        budget: Array,  # [S] int32 — max_new_tokens per request
        eos: Array,  # [S] int32 — per-request EOS id (-1 = none)
        drafts: Array,  # [S, spec_len] int32 — host n-gram drafts
        n_draft: Array,  # [S] int32 in [0, spec_len] — per-slot draft len
        seeds: tp.Optional[Array] = None,  # [S] int32 (sampled only)
        key: tp.Optional[Array] = None,  # base PRNG key (sampled only)
        draft_probs: tp.Optional[Array] = None,  # [S, spec_len, V]
        # (soft_drafts only) — the dense draft distribution
    ):
        assert bt.shape == (slots, pmax), (
            f"block table {bt.shape} != declared geometry ({slots}, {pmax})"
        )
        with axis_rules(mesh, serving_logical_rules()):
            # row 0: the true next token, materialized from the carried
            # logits — the same decision the plain window's step 0 takes
            # from the same logits (argmax at T=0, sample_token under
            # the position's derived key at T>0)
            if temperature == 0.0:
                t0 = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            else:
                ks0 = jax.vmap(
                    lambda sd, ti: derive_request_key(key, sd, ti)
                )(seeds, emitted)
                t0 = jax.vmap(
                    lambda l1, k1: sample_token(
                        l1[None], k1, temperature, top_k
                    )[0]
                )(logits, ks0)
            t0 = jnp.where(done, jnp.int32(pad_id), t0)
            cand = jnp.concatenate([t0[:, None], drafts], axis=1)  # [S, T]
            all_logits, ks, vs = verify_tokens_paged(
                model, cand, pooled_len, pool.k, pool.v, bt, rope_len,
                pool_sk=pool.scale_k, pool_sv=pool.scale_v,
                paged_kernel=paged_kernel, layer_scan=layer_scan,
            )  # all_logits: [S, T, V]; ks/vs: [L, S, Hkv, T, C]
            if temperature == 0.0:
                preds = jnp.argmax(all_logits, axis=-1).astype(jnp.int32)
                # draft row j (cand[:, j], j >= 1) matches iff it equals
                # the model's argmax after row j-1 and sits within the
                # slot's draft length; acceptance is the longest
                # matching PREFIX
                match = (cand[:, 1:] == preds[:, :-1]) & (
                    jnp.arange(spec_len)[None, :] < n_draft[:, None]
                )
                p = qf = None
            else:
                # the target distribution after each prefix row — BY
                # CONSTRUCTION what sample_token draws from at this
                # (temperature, top_k); f32 throughout (the acceptance
                # compare is the sampled path's near-tie surface, pinned
                # by the choreo prover)
                p = target_probs(
                    all_logits[:, :-1], temperature, top_k
                )  # [S, spec_len, V] f32
                p_sel = jnp.take_along_axis(
                    p, cand[:, 1:, None], axis=2
                )[..., 0]  # [S, spec_len] — p(draft token)
                if soft_drafts:
                    qf = draft_probs.astype(jnp.float32)
                    q_sel = jnp.take_along_axis(
                        qf, cand[:, 1:, None], axis=2
                    )[..., 0]
                else:
                    # one-hot n-gram drafts: q(draft token) = 1 — built
                    # in-program so no [S, spec_len, V] tensor crosses
                    # the dispatch boundary (the verify program's
                    # traffic budget cells stay exactly as greedy)
                    qf = None
                    q_sel = jnp.ones((slots, spec_len), jnp.float32)
                # acceptance uniforms: one per (request, stream
                # position), keyed by a salted substream of the
                # position's derived key — independent of the
                # categorical stream (a rejection at position i must
                # resample with position i's untouched categorical key)
                # and invariant to slot/window/batch/eviction
                pos = emitted[:, None] + jnp.arange(
                    1, spec_len + 1, dtype=jnp.int32
                )[None, :]
                u = jax.vmap(
                    jax.vmap(
                        lambda sd, ti: jax.random.uniform(
                            jax.random.fold_in(
                                derive_request_key(key, sd, ti),
                                SPEC_ACCEPT_SALT,
                            ),
                            (),
                            jnp.float32,
                        ),
                        in_axes=(None, 0),
                    )
                )(seeds, pos)  # [S, spec_len] f32
                match = sampling_mod.acceptance_mask(u, q_sel, p_sel) & (
                    jnp.arange(spec_len)[None, :] < n_draft[:, None]
                )
            acc = jnp.cumprod(match.astype(jnp.int32), axis=1) > 0
            ok = jnp.concatenate(
                [jnp.ones((slots, 1), bool), acc], axis=1
            )  # [S, T] — row 0 always a real emission for a live slot
            allowed = budget - emitted  # >= 1 for any live slot
            ok = ok & (jnp.arange(t)[None, :] < allowed[:, None])
            ok = ok & ~done[:, None]
            # an emitted EOS is kept; every row after it is dropped
            is_eos = ok & (cand == eos[:, None])
            eos_before = (
                jnp.cumsum(is_eos.astype(jnp.int32), axis=1)
                - is_eos.astype(jnp.int32)
            ) > 0
            emit = ok & ~eos_before  # [S, T] — always a contiguous prefix
            n_emit = jnp.sum(emit.astype(jnp.int32), axis=1)  # [S]
            new_emitted = emitted + n_emit
            hit_eos = jnp.any(emit & (cand == eos[:, None]), axis=1)
            new_done = done | hit_eos | (new_emitted >= budget)
            # write watermark: every emitted row's K/V is true context —
            # except a terminal row (EOS/budget), which no token follows
            # (same write_valid discipline as the decode window)
            n_write = n_emit - (new_done & ~done).astype(jnp.int32)
            n_write = jnp.maximum(n_write, 0)
            wvalid = jnp.arange(t)[None, :] < n_write[:, None]  # [S, T]
            pool = flush_recent(pool, ks, vs, bt, pooled_len, wvalid)
            new_len = pooled_len + n_write
            # carried logits: after the last emitted row (exact — its
            # whole prefix was accepted); done slots take row 0, which is
            # scratch until an admission overwrites the row. f32 widening
            # is exact, same as the decode window's carry.
            last = jnp.clip(n_emit - 1, 0, t - 1)
            base = jnp.take_along_axis(
                all_logits, last[:, None, None], axis=1
            )[:, 0]
            # accepted = drafts the MODEL agreed with (pre-EOS/budget
            # truncation): the honest acceptance signal for adaptation —
            # end-of-generation budget clipping is not a drafting miss
            n_acc = jnp.sum(acc.astype(jnp.int32), axis=1)
            if temperature == 0.0:
                new_logits = base.astype(logits.dtype)
            else:
                # rejection carry: when the emission prefix stopped at a
                # REJECTED draft (not EOS/budget truncation), the next
                # row-0 draw must come from the residual distribution
                # max(p - q, 0) at the rejected position — encoded as
                # logits so the next dispatch's ordinary sample_token at
                # that position's derived key IS the residual draw (see
                # sampling.residual_logits for the exactness argument).
                rej = jnp.clip(n_acc, 0, spec_len - 1)  # first rejected row
                p_carry = jnp.take_along_axis(
                    p, rej[:, None, None], axis=1
                )[:, 0]  # [S, V] — target probs at the rejected position
                if soft_drafts:
                    q_carry = jnp.take_along_axis(
                        qf, rej[:, None, None], axis=1
                    )[:, 0]
                else:
                    d_rej = jnp.take_along_axis(
                        drafts, rej[:, None], axis=1
                    )[:, 0]
                    q_carry = jax.nn.one_hot(
                        d_rej, cfg.vocab_size, dtype=jnp.float32
                    )
                resid_lg, mass = residual_logits(
                    p_carry, q_carry, temperature
                )
                # residual only when the prefix genuinely ended at a
                # rejection: some draft was rejected (n_acc < n_draft)
                # AND no EOS/budget clip shortened the prefix first
                # (n_emit == 1 + n_acc) AND the residual has mass (a
                # one-hot q fully inside p's top-k support can zero it —
                # then p == q at that token was impossible to reject,
                # but guard anyway and fall back to the full target)
                use_resid = (
                    (n_acc < n_draft)
                    & (n_emit == n_acc + 1)
                    & (mass > 0.0)
                )
                new_logits = jnp.where(
                    use_resid[:, None], resid_lg,
                    base.astype(jnp.float32),
                ).astype(logits.dtype)
            # the take_along_axis indexes the (replicated) row dim of a
            # vocab-sharded [S, T, V]; pin the carry so the donated
            # logits buffer keeps its committed sharding
            new_logits = shard_act(new_logits, None, "vocab")
        return (
            pool, new_logits, cand, emit, new_done, new_len, new_emitted,
            n_acc,
        )

    # the greedy wrapper keeps the pre-sampled 11-arg signature (and
    # arithmetic) byte-for-byte: existing greedy budgets, audits, and
    # bitwise stream tests see the exact same program. The sampled
    # shapes append only [S] seeds + the base key (control-stream
    # traffic) — and, for the soft-draft test variant, the dense draft
    # distribution.
    if temperature == 0.0:
        def verify_fn(
            model, pool, logits, bt, pooled_len, done, emitted, budget,
            eos, drafts, n_draft,
        ):
            return _verify_core(
                model, pool, logits, bt, pooled_len, done, emitted,
                budget, eos, drafts, n_draft,
            )
    elif not soft_drafts:
        def verify_fn(
            model, pool, logits, bt, pooled_len, done, emitted, budget,
            eos, drafts, n_draft, seeds, key,
        ):
            return _verify_core(
                model, pool, logits, bt, pooled_len, done, emitted,
                budget, eos, drafts, n_draft, seeds=seeds, key=key,
            )
    else:
        def verify_fn(
            model, pool, logits, bt, pooled_len, done, emitted, budget,
            eos, drafts, n_draft, seeds, key, draft_probs,
        ):
            return _verify_core(
                model, pool, logits, bt, pooled_len, done, emitted,
                budget, eos, drafts, n_draft, seeds=seeds, key=key,
                draft_probs=draft_probs,
            )

    return jax.jit(verify_fn, donate_argnums=(1, 2))


def trace_serving_programs(
    model: GPT,
    *,
    slots: int = 4,
    window: int = 4,
    spec_len: int = 4,
    chunk_len: int = 64,
    page_size: int = 16,
    num_pages: tp.Optional[int] = None,
    mesh=None,
    kv_quant: tp.Optional[str] = None,
    paged_kernel: str = "xla",
    layer_scan: str = "off",
    prefill_sp: str = "off",
    temperature: float = 0.0,
    top_k: tp.Optional[int] = None,
) -> tp.Dict[str, tp.Any]:
    """Abstractly trace the engine's three hot-path programs to jaxprs —
    the input of the arithmetic-choreography prover
    (:mod:`midgpt_tpu.analysis.choreo`). Returns
    ``{"decode_window": ClosedJaxpr, "prefill_chunk": ..., "verify": ...}``.
    ``temperature > 0`` traces the SAMPLED decode window and the
    rejection-sampling verify program (its signature grows the per-slot
    seeds + base key the sampled acceptance derives its streams from).

    Tracing goes through the very same jitted callables the engine
    launches (:func:`make_decode_window` et al.), so the prover sees the
    program the hardware runs — model as an entry parameter, the fused
    window scan, the in-program sampling/acceptance glue — not a
    hand-maintained replica of it. No compilation, no execution: a full
    three-program trace takes seconds on CPU at audit size."""
    from midgpt_tpu.serving.paged import pages_needed

    cfg = model.config
    pmax = pages_needed(cfg.block_size, page_size)
    if num_pages is None:
        num_pages = slots * pmax
    pool = jax.eval_shape(
        lambda: PagedKVPool.init(cfg, num_pages, page_size,
                                 kv_quant=kv_quant)
    )
    f32 = jnp.float32
    sds = jax.ShapeDtypeStruct
    logits = sds((slots, cfg.vocab_size), f32)
    i32 = lambda *s: sds(s, jnp.int32)  # noqa: E731
    pred = lambda *s: sds(s, jnp.bool_)  # noqa: E731

    window_fn = make_decode_window(
        model, slots=slots, window=window, pmax=pmax,
        rope_len=cfg.block_size, temperature=temperature, top_k=top_k,
        mesh=mesh, paged_kernel=paged_kernel, layer_scan=layer_scan,
    )
    decode_jaxpr = jax.make_jaxpr(window_fn)(
        model, pool, logits, i32(slots, pmax), i32(slots), pred(slots),
        i32(slots), i32(slots), i32(slots), i32(slots),
        sds((2,), jnp.uint32),
    )
    chunk_fn = make_prefill_chunk_program(
        model, chunk_len=chunk_len, pmax=pmax, rope_len=cfg.block_size,
        mesh=mesh, layer_scan=layer_scan, prefill_sp=prefill_sp,
    )
    chunk_jaxpr = jax.make_jaxpr(chunk_fn)(
        model, pool, logits, i32(), i32(1, chunk_len), i32(), i32(),
        i32(pmax),
    )
    verify_fn = make_verify_program(
        model, slots=slots, spec_len=spec_len, pmax=pmax,
        rope_len=cfg.block_size, temperature=temperature, top_k=top_k,
        mesh=mesh, paged_kernel=paged_kernel, layer_scan=layer_scan,
    )
    verify_args = [
        model, pool, logits, i32(slots, pmax), i32(slots), pred(slots),
        i32(slots), i32(slots), i32(slots), i32(slots, spec_len),
        i32(slots),
    ]
    if temperature > 0.0:
        verify_args += [i32(slots), sds((2,), jnp.uint32)]
    verify_jaxpr = jax.make_jaxpr(verify_fn)(*verify_args)
    return {
        "decode_window": decode_jaxpr,
        "prefill_chunk": chunk_jaxpr,
        "verify": verify_jaxpr,
    }


def make_copy_page_program():
    """The jitted copy-on-write primitive: duplicate one page so an
    admission landing on a partially-shared cached page gets a private
    copy to append into. Pool donated — the copy is in-place up to the
    one written page row. One shared wrapper (program cache): copy_page
    is model-free, so every engine reuses the same jit cache."""
    return _cached_program(
        ("copy_page",), lambda: jax.jit(copy_page, donate_argnums=(0,))
    )


# ---------------------------------------------------------------------------
# Requests + engine
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Request:
    """One generation request and its lifecycle record."""

    rid: int
    prompt: np.ndarray  # [p] int32 admission context (original prompt, or
    # prompt0 + generated-so-far after an eviction re-queue)
    max_new_tokens: int
    # the cropped ORIGINAL prompt — evictions rebuild the admission
    # context from this, never from an already-grown prompt
    prompt0: np.ndarray = dataclasses.field(
        default_factory=lambda: np.zeros((0,), np.int32)
    )
    eos_id: int = -1  # -1 = no EOS (run to max_new_tokens)
    seed: int = 0
    submit_time: float = 0.0
    first_token_time: tp.Optional[float] = None
    finish_time: tp.Optional[float] = None
    tokens: tp.List[int] = dataclasses.field(default_factory=list)
    evictions: int = 0
    cached_tokens: int = 0  # prompt tokens served from the prefix cache
    # (summed over admissions — re-admissions typically re-hit)
    # speculative decoding (engine speculate > 0): current adaptive draft
    # length, trailing acceptance EWMA, and lifetime draft accounting.
    # spec_k survives eviction/re-admission — the controller state is a
    # property of the request's text, not the slot it lands in.
    spec_k: int = 0
    spec_rate: float = 1.0
    spec_drafted: int = 0
    spec_accepted: int = 0
    # overload bookkeeping: tokens held at the LAST admission (progress
    # detector) and the count of consecutive evictions with zero progress
    # since then — the eviction-livelock guard parks the request at
    # ``park_threshold`` (two requests thrashing each other's pages
    # would otherwise re-prefill in a loop instead of one waiting)
    admit_tokens: int = 0
    thrash: int = 0
    # scheduling (the front-door admission policy, replacing FIFO):
    # higher ``priority`` dispatches first; ``deadline`` is an ABSOLUTE
    # engine-clock instant past which a still-undispatched request is
    # shed (None = no SLO). queue_seq/queue_step are the scheduler's
    # tie-break and aging bookkeeping: seq is the submission order
    # within a priority band, step the scheduler step of the last
    # enqueue (effective priority grows by ``priority_aging`` per step
    # queued — the starvation-freedom mechanism).
    priority: int = 0
    deadline: tp.Optional[float] = None
    queue_seq: int = 0
    queue_step: int = 0
    # terminal outcome: "pending" while live, then one of
    # "finished" | "cancelled" | "expired" (deadline shed)
    outcome: str = "pending"

    @property
    def done(self) -> bool:
        return self.finish_time is not None


@dataclasses.dataclass
class HandoffRecord:
    """A fully-prefilled request packaged for the prefill→decode page
    handoff (serving.cluster disaggregated pools): the live
    :class:`Request`, its context tokens, the block-table-addressed page
    payloads (+ int8 scale planes) as host arrays, and the CARRIED
    LOGITS ROW — exactly the row the final prefill chunk wrote, which is
    what a monolithic engine would decode its first token from, so the
    importing decode engine resumes the stream bit-identically. Built by
    :meth:`ServingEngine.export_request`, consumed by
    :meth:`ServingEngine.import_request`; everything here is host state,
    so the record crosses engines (and, in a multi-host deployment, the
    DCN wire) with no device aliasing."""

    req: Request
    ctx: tp.List[int]  # the slot's context tokens (== the prompt)
    resident: int  # pool-resident tokens (== len(ctx))
    logits_row: np.ndarray  # [V] f32 — the final prefill chunk's row
    n_pages: int
    k: np.ndarray  # [L, n_pages, Hkv, C, PS] pool dtype
    v: np.ndarray
    sk: tp.Optional[np.ndarray]  # [L, n_pages, Hkv] f32 (int8 pools)
    sv: tp.Optional[np.ndarray]

    @property
    def nbytes(self) -> int:
        """Handoff wire bytes (payload + scales + logits) — what
        ``serve_handoff_bytes`` accounts."""
        n = self.k.nbytes + self.v.nbytes + self.logits_row.nbytes
        if self.sk is not None:
            n += self.sk.nbytes + self.sv.nbytes
        return int(n)


# Registry-backed counter attributes of ServingEngine: every name here
# becomes a class-level property reading/writing the engine's
# MetricsRegistry Counter of the same name (attached right after the
# class body). The registry is the single source of truth; stats() and
# the metrics snapshot are two views of it.
_ENGINE_COUNTERS = (
    "decode_dispatches",
    "prefill_dispatches",
    "copy_dispatches",
    "tokens_generated",
    "windows",
    "occupancy_sum",
    "evictions",
    "prompt_tokens_total",
    "prompt_tokens_cached",
    "prefill_tokens_computed",
    "cold_reclaims",
    "spilled_pages",
    "spill_faultback_pages",
    "spill_prefetch_pages",
    "spill_readmissions",
    "spill_discards",
    "verify_dispatches",
    "spec_drafted",
    "spec_accepted",
    "admission_rejected",
    "shed_requests",
    "deferred_submits",
    "livelock_parks",
    "overload_parks",
    "cancelled_requests",
    "deadline_shed_requests",
    "faults_injected",
)


def _counter_property(name: str) -> property:
    def _get(self):
        return self.metrics.counter(name).value

    def _set(self, v):
        self.metrics.counter(name).value = v

    return property(
        _get, _set, doc=f"registry-backed counter {name!r} "
        "(serving.telemetry.MetricsRegistry)"
    )


class ServingEngine:
    """Continuous-batching scheduler over ``slots`` decode lanes.

    Every :meth:`step` is one scheduler window: admit queued requests
    into free slots (prefix-cache match + page allocation), run up to
    ``prefill_budget`` tokens of pending prefill chunks, top up page
    allocations for the coming K tokens (evicting the youngest request
    under pressure — its progress is kept and it re-queues with
    prompt+generated), launch ONE fused K-step decode dispatch for all
    decoding slots, then harvest emitted tokens / finished requests with
    a single device->host read.

    Prefix cache (``prefix_cache=True``): full pages are registered in a
    host-side content index as they fill; admission points the block
    table at matched pages (skipping their prefill compute entirely),
    copies the one partially-matched page (COW), and computes only the
    suffix — always at least the last prompt token, which is what
    produces the first decode logits. Finished requests' pages stay
    resident cold until page pressure reclaims them LRU. Token streams
    are identical with the cache on or off.

    Chunked prefill (``prefill_chunk=N``): prompts prefill N tokens at a
    time, at most ``prefill_budget`` tokens between consecutive decode
    windows, so a long prompt cannot stall co-scheduled decode slots for
    more than one chunk. ``prefill_chunk=None`` keeps the monolithic
    behavior (the whole uncached suffix in one dispatch).

    Self-speculative decoding (``speculate=N``, greedy only): every
    decode dispatch becomes a VERIFY dispatch — a host-side n-gram
    proposer (``serving.speculate.NgramProposer``, injectable via
    ``proposer=``) drafts up to N tokens per request from its own
    history, and one jitted program scores the ``N+1`` candidate rows of
    every slot against the resident pages, emitting ``1 + accepted``
    tokens per slot per dispatch. Draft length adapts per request to its
    trailing acceptance rate. Rejected rows' K/V never lands (the write
    scatter is masked at the per-slot watermark), so allocator/index
    invariants are untouched and greedy output is token-identical to
    ``speculate=0``.

    Capacity contract: a request must fit its context in ``block_size``
    (prompts are cropped to ``block_size - max_new_tokens`` like the
    reference sampler crops to the window, sample.py:74).

    Overload and faults degrade, they don't crash (serving.faults):
    unservable submissions raise typed, counted ``AdmissionRejected``;
    a full bounded queue (``max_queue``) sheds or defers per
    ``overload_policy``; pool pressure a lone request can't evict its
    way out of PARKS the request (progress kept) instead of raising
    ``MemoryError``; and the eviction-livelock guard parks a request
    evicted ``park_threshold`` times without progress. A scripted
    ``fault_hook`` (``FaultPlan.hook``) injects deterministic chaos at
    step boundaries; ``drain_requests``/``resubmit`` are the cluster's
    failover seam, and every degraded path preserves the bit-identical
    stream contract above.
    """

    def __init__(
        self,
        model: GPT,
        *,
        slots: int = 4,
        page_size: int = 16,  # tile-aligned at C=64; same default everywhere
        num_pages: tp.Optional[int] = None,
        window: int = 4,
        temperature: float = 0.0,
        top_k: tp.Optional[int] = None,
        cache_dtype=jnp.bfloat16,
        pad_id: int = 0,
        seed: int = 0,
        max_prefills_per_window: tp.Optional[int] = None,
        prefix_cache: bool = True,
        prefill_chunk: tp.Optional[int] = None,
        prefill_budget: tp.Optional[int] = None,
        speculate: int = 0,
        proposer: tp.Optional[Proposer] = None,
        quant: tp.Optional[str] = None,
        kv_quant: tp.Optional[str] = None,
        paged_kernel: str = "auto",
        layer_scan: str = "off",
        prefill_sp: str = "auto",
        spill: str = "off",
        spill_budget_pages: tp.Optional[int] = None,
        spill_prefetch: str = "on",
        mesh=None,
        clock: tp.Callable[[], float] = time.monotonic,
        max_queue: tp.Optional[int] = None,
        overload_policy: str = "defer",
        park_threshold: int = 2,
        priority_aging: float = 0.125,
        fault_hook: tp.Optional[tp.Callable[["ServingEngine"], None]] = None,
        telemetry: tp.Union[None, bool, EngineTelemetry] = None,
        role: str = "both",
    ):
        assert slots >= 1 and window >= 1 and page_size >= 1
        # replica class (serving.cluster disaggregated pools): "both" is
        # the monolithic engine; "prefill" runs chunked prefill to
        # completion and then PARKS the slot handoff-ready (it never
        # decodes — the cluster exports the pages to a decode-class
        # engine); "decode" is a routing label only — the engine is a
        # full engine (eviction re-queues must re-prefill locally, which
        # is what keeps post-handoff eviction bit-identical), the
        # cluster just never routes fresh submissions at it.
        assert role in ("both", "prefill", "decode"), role
        self.role = role
        # observability (serving.telemetry): the metrics registry is
        # ALWAYS on — the counter attributes below are properties over
        # it, so stats() is a façade over one source of truth — while
        # per-request lifecycle TRACING is opt-in (telemetry=True or an
        # EngineTelemetry instance). Tracing is deliberately NOT a
        # parameter of any program factory: an engine with tracing on
        # launches the identical cached jitted callables (proven by
        # analysis.harness.prove_telemetry_inert), every emission reads
        # host-side scheduler state only, and when disabled each site
        # costs one `is None` check — greedy streams are bitwise
        # identical either way (tests/test_telemetry.py).
        self.metrics = MetricsRegistry()
        if telemetry is True:
            telemetry = EngineTelemetry()
        elif not telemetry:
            # False and None both mean "tracing off" (bench_serving
            # passes the computed bool straight through)
            telemetry = None
        assert telemetry is None or isinstance(telemetry, EngineTelemetry), (
            f"telemetry must be None, a bool, or an EngineTelemetry, "
            f"got {telemetry!r}"
        )
        self.telemetry = telemetry
        # overload degradation knobs: max_queue bounds the wait queue
        # (None = unbounded, the library default); a submit hitting the
        # bound is SHED (AdmissionRejected, the request is dropped for
        # good) or DEFERRED (PoolOverloaded, the caller's backpressure
        # signal to retry later). park_threshold is the eviction-livelock
        # guard: a request evicted that many times in a row without
        # emitting a token parks until pages free up, instead of
        # re-prefilling in a thrash loop.
        assert overload_policy in ("defer", "shed"), overload_policy
        assert max_queue is None or max_queue >= 1, max_queue
        assert park_threshold >= 1, park_threshold
        self.max_queue = max_queue
        self.overload_policy = overload_policy
        self.park_threshold = park_threshold
        # priority admission (replaces FIFO): a queued request's
        # effective priority is ``priority + priority_aging * steps
        # queued`` — aging guarantees starvation-freedom (a request of
        # priority p outranks every FRESH priority-P arrival within
        # ceil((P - p) / priority_aging) scheduler steps of queue
        # residence, ties broken oldest-first; with all-default
        # priorities the order degenerates to exactly the old FIFO).
        # Keyed to SCHEDULER STEPS, not wall clock — the determinism
        # contract the front door's replay tests pin.
        assert priority_aging >= 0.0, priority_aging
        self.priority_aging = priority_aging
        # deterministic fault injection (serving.faults): called at the
        # top of every step() with this engine, AFTER fault_step
        # incremented and BEFORE any dispatch — zero-cost when absent
        # (one is-None check per scheduler window)
        self._fault_hook = fault_hook
        self.fault_step = 0
        # int8 quantized KV pool (serving.paged / quant.py's KV grid):
        # page payloads store int8 with one f32 po2 scale per
        # (page, KV-head) plane, halving the K+V HBM stream every decode
        # step pays — the largest remaining stream after the int8 weight
        # path (PERF.md). Greedy token streams stay invariant across the
        # whole feature matrix (cache x chunking x speculation x
        # eviction x tp): scales are fixed at page birth and every
        # in-dispatch reader sees grid-rounded rows.
        assert kv_quant in (None, "int8"), f"unknown kv_quant {kv_quant!r}"
        self.kv_quant = kv_quant
        # paged-attention backend: "pallas" = the ragged in-kernel
        # block-table walk (ops.paged_attn — pages stream once, no
        # gathered HBM intermediate; interpret-mode on CPU), "xla" = the
        # gather path, "auto" = pallas on TPU when the assembly fits
        # VMEM, xla otherwise (same dispatch philosophy as
        # ops/attention's flash-vs-naive)
        assert paged_kernel in ("auto", "pallas", "xla"), paged_kernel
        # fused layer loop (ROADMAP item 1): "on" folds every program's
        # per-layer loop into one lax.scan (models.gpt layer_scan=) —
        # bitwise the unrolled program (token-identity matrix), gated
        # statically by the analysis.fusion scan-equivalence prover and
        # the analysis.dispatch launch budgets. Default "off" until the
        # r6 hardware rungs measure the dispatch-overhead win (the
        # bench ladder runs both).
        assert layer_scan in ("on", "off"), layer_scan
        self.layer_scan = layer_scan
        # sequence-parallel prefill (ROADMAP item 4): "on" compiles the
        # SP prefill-chunk variant (models.gpt.prefill_chunk_paged
        # sp=True) whose replicated per-token segments shard the chunk's
        # rows over 'tensor' — bitwise the "off" program (the landing
        # gate), with the replicated O(T·D) work and activation traffic
        # scaled 1/tp on long prompts. "auto" = on exactly when the mesh
        # has a tensor axis to shard over; resolved below once tp is
        # known (a tp=1 "on" degenerates to "off": there is no axis, and
        # keeping the resolved value in the program-cache key stops a
        # no-op knob from forking compilations). Decode/verify programs
        # are untouched by construction — separate cache entries, and
        # the 'sp' logical axis is unmapped for them.
        assert prefill_sp in ("auto", "on", "off"), prefill_sp
        # quantized weight path (midgpt_tpu.quant): quant="int8" converts
        # the model to the int8 per-channel serving pytree here, so every
        # program this engine compiles (decode window, prefill chunk,
        # verify) streams int8 weights with the dequant fused into each
        # matmul. Passing an already-quantized model with quant=None is
        # equally valid — the programs accept either form through one
        # code path (GPT.project + the block projections).
        assert quant in (None, "int8"), f"unknown quant mode {quant!r}"
        if quant is not None:
            from midgpt_tpu.quant import is_quantized, quantize_model

            if not is_quantized(model):
                model = quantize_model(model)
        cfg = model.config
        # page grid must tile the context: otherwise a near-block prompt
        # padded up to the page grid exceeds block_size and prefill
        # cannot run (caught in code review)
        assert cfg.block_size % page_size == 0, (
            f"page_size {page_size} must divide block_size {cfg.block_size}"
        )
        assert prefill_chunk is None or prefill_chunk >= 1
        # tensor-parallel serving mesh: shard the model per
        # GPT_PARAM_RULES (column-parallel wqkv/w_up(/gate)/lm_head,
        # row-parallel wo/w_down, quant scales split with their out
        # dim), the KV pool by WHOLE KV HEADS, and the carried logits by
        # vocab. Sequence/pipeline axes have no serving decomposition
        # here (decode is one token deep; DP is shared-nothing engine
        # replicas — serving.cluster — not a sharded slot axis), so a
        # serving mesh is tensor-only (extra replica/fsdp axes are
        # tolerated but simply ride replicated).
        if paged_kernel == "auto":
            from midgpt_tpu.ops.paged_attn import supported as pk_supported
            from midgpt_tpu.utils.platform import is_tpu_backend

            itemsize = 1 if kv_quant == "int8" else jnp.dtype(
                cache_dtype
            ).itemsize
            # the kernel runs per TP shard (Hkv/tp heads in its VMEM
            # assembly), so the fit check must see the SHARD geometry —
            # the full-pool check would fall back to the XLA gather on
            # configs that fit fine once sharded (divisibility of
            # kv_heads by tp is asserted below)
            auto_tp = mesh.shape.get("tensor", 1) if mesh is not None else 1
            paged_kernel = (
                "pallas"
                if is_tpu_backend() and pk_supported(
                    pages_needed(cfg.block_size, page_size), page_size,
                    max(1, cfg.kv_heads // auto_tp), cfg.head_dim, itemsize,
                    groups=cfg.n_head // cfg.kv_heads,
                    spec_t=speculate + 1,
                )
                else "xla"
            )
        self.paged_kernel = paged_kernel
        self.tp = 1
        if mesh is not None:
            from midgpt_tpu.models.gpt import (
                GPT_PARAM_RULES,
                mlp_hidden_dim,
            )
            from midgpt_tpu.parallel.sharding import param_shardings

            assert mesh.shape.get("sequence", 1) == 1, (
                "serving meshes cannot shard 'sequence' (decode is one "
                "token deep); use a tensor-only mesh"
            )
            assert mesh.shape.get("pipeline", 1) == 1, (
                "serving meshes cannot shard 'pipeline'; use a "
                "tensor-only mesh"
            )
            tp_sz = mesh.shape.get("tensor", 1)
            assert (
                cfg.n_head % tp_sz == 0 and cfg.kv_heads % tp_sz == 0
            ), (
                f"tensor={tp_sz} must divide heads "
                f"({cfg.n_head}/{cfg.kv_heads}): the pool shards whole "
                "KV heads"
            )
            assert cfg.vocab_size % tp_sz == 0, (
                f"tensor={tp_sz} must divide vocab_size {cfg.vocab_size}"
            )
            assert mlp_hidden_dim(cfg) % tp_sz == 0, (
                f"tensor={tp_sz} must divide the MLP hidden width "
                f"{mlp_hidden_dim(cfg)}"
            )
            self.tp = tp_sz
            model = jax.device_put(
                model, param_shardings(mesh, model, GPT_PARAM_RULES)
            )
        self.prefill_sp = "on" if (
            prefill_sp in ("on", "auto") and self.tp > 1
        ) else "off"
        self.model = model
        self.slots = slots
        self.window = window
        self.page_size = page_size
        self.pad_id = pad_id
        self.clock = clock
        self.block = cfg.block_size
        self.pmax = pages_needed(self.block, page_size)
        if num_pages is None:
            num_pages = slots * self.pmax  # full occupancy, no eviction
        self.alloc = PageAllocator(num_pages)
        self.prefix_cache = prefix_cache
        self.index = PrefixIndex(page_size) if prefix_cache else None
        # cold-page host spill (ROADMAP item 4): under pool pressure,
        # cold (refcount-0 cached) pages move to host RAM — content,
        # int8 scale planes and prefix-index position preserved —
        # instead of being discarded, and fault back through the jitted
        # page-write path (import_pages) on a prefix hit or
        # re-admission. The HBM page id returns to the free list at
        # spill time (that is what frees capacity), so the allocator's
        # id-state identity free+held+cached+quarantined == num_pages is
        # untouched while `spilled` counts host-store entries — the
        # extended ledger the invariant tests check is
        # resident-indexed + spilled == indexed nodes, disjoint
        # (PrefixIndex.check with the store). Spill is a CACHE policy:
        # it needs the prefix index, and a request that cannot fault a
        # spilled node back (pool fully held) degrades to a shorter
        # match, never an error — parking/PoolOverloaded stay the
        # overload surface.
        assert spill in ("on", "off"), spill
        assert spill == "off" or prefix_cache, (
            "spill='on' requires prefix_cache=True: only indexed cold "
            "pages ever spill"
        )
        assert spill_budget_pages is None or spill_budget_pages >= 0
        # prefetch-on-queue (spill="on" only): each scheduler step
        # probes the wait-queue head's prompt against the prefix index
        # and fault-backs its matched SPILLED chain nodes BEFORE
        # admission, in ONE batched import_pages call (bounded per
        # step). "off" degrades to pure fault-on-match at admission —
        # same stream bytes, more import dispatches on the TTFT path.
        assert spill_prefetch in ("on", "off"), spill_prefetch
        self.spill_prefetch = spill_prefetch
        self.spill = spill
        self._spill_store = (
            HostSpillStore(budget_pages=spill_budget_pages)
            if spill == "on" else None
        )
        self.prefill_chunk = prefill_chunk
        # tokens of prefill work allowed between decode windows; the
        # first chunk always runs (progress guarantee), so the effective
        # floor is one chunk
        self.prefill_budget = (
            prefill_budget
            if prefill_budget is not None
            else prefill_chunk  # None (monolithic) -> unlimited
        )
        # sampling config: temperature == 0 is greedy, temperature > 0
        # samples — in BOTH the plain window and the speculative verify
        # program (rejection-sampling acceptance; see
        # _build_verify_program). A negative temperature is the only
        # genuinely unsupported sampling config: typed error, not assert
        # (callers surface it as a config problem, not a library bug).
        if temperature < 0.0:
            raise ValueError(
                f"temperature must be >= 0, got {temperature}"
            )
        if top_k is not None and top_k < 1:
            raise ValueError(f"top_k must be None or >= 1, got {top_k}")
        self.temperature = float(temperature)
        self.top_k = top_k
        assert speculate >= 0, speculate
        if speculate:
            assert speculate < self.block, speculate
        self.speculate = int(speculate)
        self.proposer: tp.Optional[Proposer] = (
            proposer
            if proposer is not None
            else (NgramProposer() if speculate else None)
        )
        # a soft proposer (SoftProposer protocol: soft=True +
        # propose_soft) ships a dense [S, spec_len, V] draft
        # distribution into the verify dispatch; n-gram drafts are
        # one-hot and never materialize it (see serving.speculate)
        self._soft_drafts = bool(
            self.speculate
            and temperature > 0.0
            and getattr(self.proposer, "soft", False)
        )
        # tokens a decode dispatch may write per slot: K for the plain
        # window, spec_len + 1 candidate rows for the verify program —
        # page growth provisions this many
        self._grow = (self.speculate + 1) if self.speculate else window
        self.pool = PagedKVPool.init(
            cfg, num_pages, page_size, cache_dtype, mesh=mesh,
            kv_quant=kv_quant,
        )
        self.logits = jnp.zeros((slots, cfg.vocab_size), jnp.float32)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            self.logits = jax.device_put(
                self.logits, NamedSharding(mesh, P(None, "tensor"))
            )
        self._key = jax.random.PRNGKey(seed)
        self._sentinel = num_pages
        self._mesh = mesh
        self._max_prefills = (
            max_prefills_per_window
            if max_prefills_per_window is not None
            else slots
        )

        # host-side slot state
        self.bt = np.full((slots, self.pmax), self._sentinel, np.int32)
        self.pooled_len = np.zeros((slots,), np.int32)
        self.done = np.ones((slots,), bool)  # empty slots ride as done
        self.prefilling = np.zeros((slots,), bool)
        # prefill-role engines: slot fully prefilled, parked awaiting the
        # cluster's page export (done stays True — no decode window ever
        # carries a handoff-ready slot)
        self.handoff_ready = np.zeros((slots,), bool)
        # scripted `handoff` fault (serving.faults): armed by the hook,
        # fires inside the next export_request
        self._handoff_poison = False
        self.emitted = np.zeros((slots,), np.int32)
        self.budget = np.zeros((slots,), np.int32)
        self.eos = np.full((slots,), -1, np.int32)
        self.seeds = np.zeros((slots,), np.int32)
        self.slot_pages: tp.List[tp.List[int]] = [[] for _ in range(slots)]
        self.slot_req: tp.List[tp.Optional[Request]] = [None] * slots
        # the slot's context tokens (prompt + generated) — what its page
        # contents encode; drives content registration in the index
        self.slot_ctx: tp.List[tp.List[int]] = [[] for _ in range(slots)]
        # pages of the slot already walked for registration (matched
        # pages count: they were indexed before admission)
        self.slot_registered: tp.List[int] = [0] * slots
        # the index node (page id, -1 = root) the slot's chain is at
        self.slot_node: tp.List[int] = [PrefixIndex._ROOT] * slots
        # extra refcounts the slot holds on CANONICAL pages it chains
        # through without owning (register() returned someone else's
        # identical-content page): pinned so LRU reclaim can never leave
        # slot_node/parent ids dangling in the index
        self.slot_pins: tp.List[tp.List[int]] = [[] for _ in range(slots)]
        # round-robin cursor over prefilling slots (persists across
        # windows so a one-chunk budget still alternates slots)
        self._prefill_rr = 0

        self.queue: tp.Deque[Request] = collections.deque()
        # overload parking lot: requests evicted by the livelock guard or
        # by single-slot pool exhaustion wait here (progress kept) until
        # a finish / quarantine release / idle engine un-parks them
        self.parked: tp.List[Request] = []
        self.finished: tp.Dict[int, Request] = {}
        # post-admission terminal outcomes that are NOT completions:
        # cancelled (submitter teardown) and expired (deadline shed
        # before dispatch) — separate dicts so goodput accounting and
        # the finished-equals-submitted test contracts stay exact
        self.cancelled: tp.Dict[int, Request] = {}
        self.expired: tp.Dict[int, Request] = {}
        self._next_rid = 0
        self._queue_seq = 0  # fresh-submission order (priority tie-break)
        # rid -> live Request (queued, parked, or in a slot): the O(1)
        # side of lookup() — the front door's per-round harvest reads
        # every live stream's progress through it, and a linear scan of
        # queue+parked+slots per stream would make each round O(n^2)
        # under a deep backlog
        self._live: tp.Dict[int, Request] = {}

        if self.speculate:
            # speculation REPLACES the K-step window: every decode
            # dispatch is a verify dispatch (1 + accepted tokens/slot)
            self._verify_fn = make_verify_program(
                model,
                slots=slots,
                spec_len=self.speculate,
                pmax=self.pmax,
                rope_len=self.block,
                pad_id=pad_id,
                temperature=temperature,
                top_k=top_k,
                soft_drafts=self._soft_drafts,
                mesh=mesh,
                paged_kernel=self.paged_kernel,
                layer_scan=self.layer_scan,
            )
            self._window_fn = None
        else:
            self._verify_fn = None
            self._window_fn = make_decode_window(
                model,
                slots=slots,
                window=window,
                pmax=self.pmax,
                rope_len=self.block,
                pad_id=pad_id,
                temperature=temperature,
                top_k=top_k,
                mesh=mesh,
                paged_kernel=self.paged_kernel,
                layer_scan=self.layer_scan,
            )
        self._chunk_fns: tp.Dict[int, tp.Any] = {}
        self._copy_fn = make_copy_page_program()

        # counters (bench_serving / tests): each name in
        # _ENGINE_COUNTERS is a class-level property over self.metrics
        # (serving.telemetry.MetricsRegistry), so `+= 1` here, the
        # bench's warmup `setattr(e, name, 0)` reset, and the metrics
        # snapshot all hit the SAME Counter objects — stats() keeps its
        # exact key inventory (telemetry.ENGINE_STATS_KEYS, pinned by
        # test) as a façade over the registry
        for _n in _ENGINE_COUNTERS:
            self.metrics.counter(_n)
        self.reject_reasons: tp.Dict[str, int] = {}
        self.metrics.attach_labels("reject_reasons", self.reject_reasons)
        # live-state gauges, evaluated lazily at snapshot time (no
        # mirrored writes on the scheduler hot path)
        g = self.metrics.gauge
        g("free_pages", lambda: self.alloc.free_pages)
        g("cached_pages", lambda: self.alloc.cached_pages)
        g("spill_resident_pages",
          lambda: len(self._spill_store)
          if self._spill_store is not None else 0)
        g("spill_resident_bytes",
          lambda: self._spill_store.nbytes
          if self._spill_store is not None else 0)
        g("pool_utilization",
          lambda: 1.0 - self.alloc.free_pages / max(1, self.alloc.num_pages))
        g("queue_depth", lambda: len(self.queue))
        g("parked_requests", lambda: len(self.parked))
        g("active_slots", lambda: len(self._active_slots()))
        g("slot_occupancy",
          lambda: self.occupancy_sum / max(1, self.windows * self.slots))
        g("prefix_hit_rate",
          lambda: self.prompt_tokens_cached
          / max(1, self.prompt_tokens_total))
        g("spec_acceptance_rate",
          lambda: self.spec_accepted / max(1, self.spec_drafted))
        g("tokens_per_dispatch",
          lambda: self.tokens_generated / max(1, self.decode_dispatches))
        # fixed-bucket latency histograms: queue_delay/ttft/e2e observe
        # from the scheduler's own clock reads (always on — no device
        # access); tbt/dispatch need token timestamps, so they populate
        # only under tracing
        for _h in ("queue_delay_s", "ttft_s", "e2e_s", "tbt_s",
                   "dispatch_s"):
            self.metrics.histogram(_h)

    # -- submission ---------------------------------------------------------

    def _reject(self, reason: str, message: str) -> tp.NoReturn:
        """Typed, counted admission rejection (machine-readable reason)."""
        self.admission_rejected += 1
        self.reject_reasons[reason] = self.reject_reasons.get(reason, 0) + 1
        raise AdmissionRejected(reason, message)

    def submit(
        self,
        prompt: np.ndarray,
        max_new_tokens: int,
        *,
        eos_id: tp.Optional[int] = None,
        seed: int = 0,
        priority: int = 0,
        deadline_s: tp.Optional[float] = None,
        deadline: tp.Optional[float] = None,
    ) -> int:
        """Queue a request; returns its id. Prompts are cropped to the last
        ``block_size - max_new_tokens`` tokens so the whole context fits.

        ``priority`` (higher dispatches first; aged by
        ``priority_aging`` per queued scheduler step so low priorities
        provably cannot starve) and a deadline (``deadline_s`` relative
        to now on this engine's clock, or ``deadline`` as an absolute
        clock instant — the cluster's cold-failover record uses the
        absolute form so a re-served request keeps its ORIGINAL SLO)
        feed the admission policy: a request whose deadline passes
        while still queued/parked is shed before dispatch
        (``Request.outcome == "expired"``, the ``deadline_shed`` event,
        the ``deadline_shed_requests`` counter — serving.faults
        ``DeadlineExceeded`` is the exception form the front door
        raises).

        Unservable requests raise :class:`AdmissionRejected` (permanent:
        a bad budget, an empty prompt, or a lifetime page demand larger
        than the whole pool — nothing the engine does later can serve
        it); a full bounded wait queue raises AdmissionRejected under
        ``overload_policy="shed"`` or :class:`PoolOverloaded` under
        ``"defer"`` (transient — the caller's cue to back off and
        resubmit; the front door turns it into awaitable backpressure).
        Both are counted in :meth:`stats` — overload must show up in
        telemetry, not as a crash."""
        if max_new_tokens < 1:
            self._reject("bad_budget", f"max_new_tokens {max_new_tokens} < 1")
        if max_new_tokens >= self.block:
            self._reject(
                "budget_exceeds_block",
                f"max_new_tokens {max_new_tokens} must leave room for at "
                f"least one prompt token in block_size {self.block}",
            )
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            self._reject("empty_prompt", "prompt has no tokens")
        keep = self.block - max_new_tokens
        if prompt.size > keep:
            prompt = prompt[-keep:]
        lifetime = pages_needed(
            int(prompt.size) + max_new_tokens, self.page_size
        )
        if lifetime > self.alloc.num_pages:
            self._reject(
                "lifetime_exceeds_pool",
                f"request needs {lifetime} pages over its lifetime but the "
                f"pool holds {self.alloc.num_pages}; raise num_pages",
            )
        if self.max_queue is not None and len(self.queue) >= self.max_queue:
            if self.overload_policy == "shed":
                self.shed_requests += 1
                self._emit("shed", reason="queue_full")
                self._reject(
                    "queue_full",
                    f"wait queue at max_queue={self.max_queue}; shed",
                )
            self.deferred_submits += 1
            self._emit("deferred", reason="queue_full")
            raise PoolOverloaded(
                "queue_full",
                f"wait queue at max_queue={self.max_queue}; retry later",
            )
        if deadline is None and deadline_s is not None:
            deadline = self.clock() + deadline_s
        req = self.make_request(
            prompt, max_new_tokens, eos_id=eos_id, seed=seed,
            priority=priority, deadline=deadline,
        )
        # the rid resubmit is about to assign — emitted here so the
        # lifecycle reads submit -> queued in order. Scheduling fields
        # ride in the event data only when non-default, so existing
        # replay signatures are untouched (priority is a deterministic
        # caller input; the absolute deadline is a clock value and
        # stays out — has_deadline is the deterministic projection).
        extra: tp.Dict[str, tp.Any] = {}
        if priority:
            extra["priority"] = int(priority)
        if deadline is not None:
            extra["has_deadline"] = True
        self._emit(
            "submit", rid=self._next_rid, t=req.submit_time,
            prompt_tokens=int(req.prompt.size), budget=int(max_new_tokens),
            **extra,
        )
        return self.resubmit(req)

    def make_request(
        self,
        prompt: np.ndarray,
        max_new_tokens: int,
        *,
        eos_id: tp.Optional[int] = None,
        seed: int = 0,
        priority: int = 0,
        deadline: tp.Optional[float] = None,
    ) -> Request:
        """Build a :class:`Request` exactly as :meth:`submit` would —
        crop included — WITHOUT admission control or queueing. The
        cluster's cold-failover path uses this + :meth:`resubmit` to
        re-serve an already-accepted request from scratch (``deadline``
        is absolute, so the re-served request keeps its original
        SLO)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        keep = self.block - max_new_tokens
        if prompt.size > keep:
            prompt = prompt[-keep:]
        return Request(
            rid=-1,  # assigned at resubmit
            prompt=prompt,
            prompt0=prompt,
            max_new_tokens=max_new_tokens,
            eos_id=-1 if eos_id is None else int(eos_id),
            seed=seed,
            submit_time=self.clock(),
            spec_k=self.speculate,
            priority=int(priority),
            deadline=deadline,
        )

    def resubmit(self, req: Request) -> int:
        """Failover re-admission (serving.cluster): enqueue an already-
        accepted :class:`Request` — typically drained off a dead replica
        — under a fresh engine-local id, progress preserved. Bypasses
        the bounded-queue admission control on purpose: this is work the
        cluster already accepted, not new load."""
        rid = self._next_rid
        self._next_rid += 1
        req.rid = rid
        req.queue_seq = self._queue_seq
        self._queue_seq += 1
        req.queue_step = self.fault_step  # aging baseline
        self._live[rid] = req
        self.queue.append(req)
        self._emit(
            "queued", rid=rid, prompt_tokens=int(req.prompt.size),
            tokens_emitted=len(req.tokens),
        )
        return rid

    def drain_requests(self) -> tp.List[Request]:
        """Hand every live request off this engine (failover): in-flight
        slots are converted exactly like an eviction (context rebuilt
        from the original prompt + all emitted tokens, budget intact),
        then the wait queue and the parking lot follow. The engine is
        left empty; its pages return to the allocator. Because requests
        carry only really-emitted tokens — faults fire at step
        boundaries, before any dispatch mutates state — a survivor
        resuming a drained request continues the stream bit-identically
        (the eviction/re-admission contract, plus placement invariance
        across replicas)."""
        out: tp.List[Request] = []
        for s in self._active_slots():
            req = self.slot_req[s]
            req.prompt = np.concatenate(
                [req.prompt0, np.asarray(req.tokens, np.int32)]
            )
            self._emit("evicted", rid=req.rid, slot=s, drained=True)
            self._release_slot(s)
            out.append(req)
        out.extend(self.queue)
        self.queue.clear()
        out.extend(self.parked)
        self.parked.clear()
        self._live.clear()  # every live request just left this engine
        return out

    # -- page handoff (the disaggregated cluster's seam) --------------------

    def handoff_ready_slots(self) -> tp.List[int]:
        """Slots whose prompt is fully prefilled and parked for export
        (prefill-role engines only; always empty elsewhere)."""
        return [s for s in range(self.slots) if self.handoff_ready[s]]

    def export_request(self, s: int) -> HandoffRecord:
        """Package handoff-ready slot ``s`` for a decode-class engine:
        page payloads (+ int8 scale planes) and the carried logits row
        leave as host arrays, then the slot releases through the normal
        path — indexed pages retire COLD, so this prefill replica's
        prefix cache keeps serving hits on the exported chain (that is
        what makes prefix-affinity routing to prefill replicas pay).

        Raises :class:`HandoffFailed` when a scripted ``handoff`` fault
        is armed — BEFORE any state leaves the slot, so the request is
        still intact here and the cluster can abandon this copy and
        re-serve cold from its submission record (streams bit-identical
        by the determinism contract)."""
        req = self.slot_req[s]
        assert req is not None and bool(self.handoff_ready[s]), (s, req)
        if self._handoff_poison:
            self._handoff_poison = False
            raise HandoffFailed(
                f"scripted handoff fault exporting rid {req.rid} "
                f"(slot {s})"
            )
        p = int(self.pooled_len[s])
        n_pages = pages_needed(p, self.page_size)
        ids = [int(x) for x in self.bt[s, :n_pages]]
        k, v, sk, sv = export_pages(self.pool, ids)
        rec = HandoffRecord(
            req=req,
            ctx=list(self.slot_ctx[s]),
            resident=p,
            # the final prefill chunk wrote exactly the logits a
            # monolithic prefill would leave; carrying this row is what
            # makes the first decoded token bit-identical
            logits_row=np.asarray(self.logits[s], np.float32),
            n_pages=n_pages,
            k=k, v=v, sk=sk, sv=sv,
        )
        self._emit(
            "handoff", rid=req.rid, slot=s, direction="export",
            pages=n_pages,
        )
        self._live.pop(req.rid, None)
        self._release_slot(s)
        return rec

    def import_request(self, rec: HandoffRecord) -> tp.Optional[int]:
        """Land a :class:`HandoffRecord` in a free slot of THIS engine:
        alias whatever full-page prefix this pool's index already holds
        (same match-pin discipline as admission — capped at the last
        prompt token, so the append page is always private), import the
        remaining pages' payloads byte-exactly, point the block table at
        them, set the carried logits row, and re-register the chain in
        this pool's prefix index so the handed-off prefix serves future
        hits here too. Returns the fresh engine-local rid, or None when
        no slot or no page capacity is available right now (the cluster
        keeps the record and retries next step).

        The slot resumes decoding exactly where a local prefill would
        have left it (same pooled_len, same logits row, same request
        seed), so the stream is bit-identical to the monolithic engine —
        and a later eviction under pressure re-prefills locally through
        the ordinary (also bit-identical) eviction path."""
        free = [s for s in range(self.slots) if self.slot_req[s] is None]
        if not free:
            return None
        s = free[0]
        ctx = rec.ctx
        p = rec.resident
        assert p == len(ctx) and rec.n_pages == pages_needed(
            p, self.page_size
        ), (p, len(ctx), rec.n_pages)
        req = rec.req
        full: tp.List[int] = []
        if self.index is not None:
            full, _, _ = self.index.match(ctx[: p - 1])
            # a match can walk onto HOST-SPILLED nodes (virtual ids, a
            # suffix of the chain) — no fault-back here: the record
            # already carries those pages' bytes, so truncate and
            # import them; _register_pages re-adopts the spilled nodes
            # through the ordinary re-admission path (payload dropped,
            # no import dispatch wasted)
            for i, pg in enumerate(full):
                if self.index.is_spilled(pg):
                    full = full[:i]
                    break
        for pg in full:
            self.alloc.incref(pg)
            self.index.revive(pg)
        need = rec.n_pages - len(full)
        if not self._try_reserve(need):
            self._release_pages(full)
            return None
        fresh = self.alloc.alloc(need)
        pages = full + fresh
        # payload lands only on the non-aliased pages (the exported
        # stack is block-table-ordered, so the aliased prefix occupies
        # positions 0..len(full)-1 and already holds identical bytes by
        # the content-chain contract)
        self.pool = import_pages(
            self.pool, fresh,
            rec.k[:, len(full):], rec.v[:, len(full):],
            None if rec.sk is None else rec.sk[:, len(full):],
            None if rec.sv is None else rec.sv[:, len(full):],
        )
        rid = self._next_rid
        self._next_rid += 1
        req.rid = rid
        self._live[rid] = req
        self.slot_req[s] = req
        self.slot_pages[s] = list(pages)
        self.slot_pins[s] = []
        self.bt[s, :] = self._sentinel
        self.bt[s, : rec.n_pages] = pages
        self.pooled_len[s] = p
        self.done[s] = False
        self.prefilling[s] = False
        self.handoff_ready[s] = False
        self.emitted[s] = len(req.tokens)
        self.budget[s] = req.max_new_tokens
        self.eos[s] = req.eos_id
        self.seeds[s] = req.seed
        self.slot_ctx[s] = [int(t) for t in ctx]
        self.slot_registered[s] = len(full)
        self.slot_node[s] = full[-1] if full else PrefixIndex._ROOT
        self.logits = self.logits.at[s].set(
            jnp.asarray(rec.logits_row, jnp.float32)
        )
        self._register_pages(s)
        self._emit(
            "handoff", rid=rid, slot=s, direction="import",
            pages=rec.n_pages, aliased=len(full), imported=need,
        )
        return rid

    # -- cancellation + lookup (the front door's seams) ---------------------

    def cancel(self, rid: int) -> bool:
        """Tear a live request down: queued/parked entries leave their
        waiting structure, an in-flight slot is reclaimed IMMEDIATELY
        (this is a host-side scheduler mutation — the next window simply
        no longer carries the slot) and its pages release through the
        same path a finish takes, so indexed pages retire COLD and
        future prefix hits survive the cancellation. Mid-speculation
        the per-slot write watermark already guarantees no stale draft
        K/V ever landed in the pages, and COW refcounts unwind through
        ``_release_slot``'s pins — the allocator/index invariants hold
        after every cancel (property-tested by the front-door suite).

        Returns True when ``rid`` was live; False for unknown or
        already-terminal ids (idempotent — a double cancel is a no-op).
        The outcome is recorded (``Request.outcome = "cancelled"``, the
        ``cancelled`` event, the ``cancelled_requests`` counter), never
        raised — :class:`~midgpt_tpu.serving.faults.Cancelled` is the
        front door's exception form."""
        for i, req in enumerate(self.queue):
            if req.rid == rid:
                del self.queue[i]
                self._cancelled(req, where="queued")
                return True
        for i, req in enumerate(self.parked):
            if req.rid == rid:
                self.parked.pop(i)
                self._cancelled(req, where="parked")
                return True
        for s in self._active_slots():
            req = self.slot_req[s]
            if req.rid == rid:
                self._cancelled(req, where="slot", slot=s)
                self._release_slot(s)
                if self.parked:
                    self._unpark()  # freed pages: parked work retries
                return True
        return False

    def _cancelled(self, req: Request, **data) -> None:
        req.outcome = "cancelled"
        self.cancelled_requests += 1
        self._live.pop(req.rid, None)
        self.cancelled[req.rid] = req
        self._emit(
            "cancelled", rid=req.rid, tokens_emitted=len(req.tokens),
            **data,
        )

    def _expire(self, req: Request, **data) -> None:
        """Deadline shed: the request's deadline passed while it was
        still waiting for dispatch (queued, or parked at release
        time) — drop it before spending compute it can no longer bank
        under the SLO."""
        req.outcome = "expired"
        self.deadline_shed_requests += 1
        self._live.pop(req.rid, None)
        self.expired[req.rid] = req
        self._emit(
            "deadline_shed", rid=req.rid, tokens_emitted=len(req.tokens),
            **data,
        )

    def lookup(self, rid: int) -> tp.Optional[Request]:
        """The :class:`Request` for an engine-local id, wherever its
        lifecycle has it (queued, parked, in a slot, or terminal);
        None for an unknown id. O(1) — the front door's harvest reads
        every live stream's token progress through this each round.
        The object is stable across evictions/parks within one engine,
        so a cursor over ``req.tokens`` streams exactly the emitted
        tokens."""
        return (
            self._live.get(rid)
            or self.finished.get(rid)
            or self.cancelled.get(rid)
            or self.expired.get(rid)
        )

    # -- internals ----------------------------------------------------------

    def _emit(self, kind: str, rid=None, t=None, **data) -> None:
        """One lifecycle event into the attached telemetry — a no-op
        `is None` check when tracing is off (the clock is not even
        read). Data fields must be deterministic under replay; wall
        clock rides only in ``t`` (serving.telemetry.Event)."""
        tele = self.telemetry
        if tele is None:
            return
        tele.emit(
            kind, step=self.fault_step,
            t=self.clock() if t is None else t, rid=rid, **data,
        )

    def _active_slots(self) -> tp.List[int]:
        return [s for s in range(self.slots) if self.slot_req[s] is not None]

    def _decoding_slots(self) -> tp.List[int]:
        return [
            s
            for s in range(self.slots)
            if self.slot_req[s] is not None
            and not self.prefilling[s]
            and not self.handoff_ready[s]
        ]

    def _prefill_bucket(self, p: int) -> int:
        """Padded chunk length: pages rounded up to a power of two, so the
        number of compiled prefill programs is O(log(block/page_size))."""
        n = pages_needed(p, self.page_size)
        n = 1 << (n - 1).bit_length()
        return min(n * self.page_size, self.pmax * self.page_size)

    # -- page accounting with cold-cache spill ------------------------------

    def _try_reserve(
        self, n: int, protect: tp.Optional[tp.AbstractSet[int]] = None
    ) -> bool:
        """Make ``n`` pages allocatable, reclaiming cold cached prefixes
        LRU-leaf-first under pressure; False when the pool genuinely
        cannot produce them. refcount>0 pages are never touched, which is
        why callers PIN (incref) any matched chain before reserving —
        attempt-based rather than counting-based, because a cold page is
        only reclaimable once no held page chains through it.

        With ``spill="on"`` the same LRU-leaf-first order SPILLS instead
        of discarding: the victim's payload (all layers + int8 scale
        planes) exports to the host store, the index re-keys the node
        virtual (still matchable), and only then does the HBM id return
        to the free list. Past ``spill_budget_pages`` the oldest spilled
        prefixes are forgotten outright — bounded host residency, with
        plain reclaim as the degradation floor. ``protect`` names
        spilled vids an in-flight fault-back still needs: budget
        enforcement skips them (host residency may transiently overshoot
        until the fault-back pops them itself) rather than dropping a
        chain node mid-materialization, which would strand a virtual id
        in the slot's block table."""
        while not self.alloc.can_alloc(n):
            if self.index is None:
                return False
            if self._spill_store is not None:
                victim = self.index.coldest_leaf()
                if victim is None:
                    return False
                payload = export_pages(self.pool, [victim])
                vid = self.index.spill(victim)
                self._spill_store.put(vid, payload)
                self.alloc.reclaim(victim)
                self.spilled_pages += 1
                while self._spill_store.over_budget:
                    dropped = self.index.discard_spilled_oldest(protect)
                    if dropped is None:
                        # every discardable node is protected: carry the
                        # overshoot; the fault-back pops them shortly
                        assert protect, "over budget with nothing spilled"
                        break
                    self._spill_store.pop(dropped)
                    self.spill_discards += 1
            else:
                victim = self.index.evict_cold_leaf()
                if victim is None:
                    return False
                self.alloc.reclaim(victim)
                self.cold_reclaims += 1
        return True

    def _fault_back(
        self,
        vid: int,
        protect: tp.Optional[tp.AbstractSet[int]] = None,
    ) -> tp.Optional[int]:
        """Restore one spilled node to a freshly allocated resident page
        through the jitted page-write path (import_pages — byte-exact,
        so the revived prefix reads back bit-identically). Returns the
        new page id at refcount 1 (the caller's pin), or None when the
        pool cannot produce a page even by spilling others — the caller
        degrades to a shorter prefix match instead of wedging.
        ``protect`` (which must cover ``vid`` and every other spilled
        node of the chain being materialized) keeps the reservation's
        own budget-discard pass from dropping the payloads this
        fault-back is about to import."""
        assert self._spill_store is not None and self.index is not None
        if not self._try_reserve(1, protect=protect):
            return None
        [page] = self.alloc.alloc(1)
        k, v, sk, sv = self._spill_store.pop(vid)
        self.pool = import_pages(self.pool, [page], k, v, sk, sv)
        self.index.unspill(vid, page)
        self.spill_faultback_pages += 1
        return page

    def _fault_back_matched(
        self,
        full: tp.List[int],
        cow_src: tp.Optional[int],
        matched: int,
        protect: tp.Optional[tp.AbstractSet[int]] = None,
    ) -> tp.Tuple[tp.List[int], tp.Optional[int], int, tp.Set[int]]:
        """Materialize any spilled nodes a prefix match walked onto.
        Spilled subtrees are closed downward, so the spilled nodes of a
        matched chain form a SUFFIX of ``full`` (plus possibly the COW
        source, a child of the tail): fault them back in chain order —
        each parent must be resident before its child re-keys under it.
        ``protect`` must hold the chain's spilled vids so no fault-back's
        reservation can budget-discard a later node of the same chain.
        Returns the match with virtual ids replaced by resident page
        ids, plus the set of pages already holding their pin (alloc at
        refcount 1 — the pin loop must not incref them again). A failed
        fault-back truncates the match at that node — the dropped
        tokens recompute, the stream is unchanged."""
        prepinned: tp.Set[int] = set()
        if self._spill_store is None:
            return full, cow_src, matched, prepinned
        for i, node in enumerate(full):
            if not self.index.is_spilled(node):
                continue
            page = self._fault_back(node, protect=protect)
            if page is None:
                # drop the spilled suffix (and the COW source — it
                # chains under the tail); those tokens just recompute
                full = full[:i]
                return full, None, len(full) * self.page_size, prepinned
            full[i] = page
            prepinned.add(page)
        if cow_src is not None and self.index.is_spilled(cow_src):
            page = self._fault_back(cow_src, protect=protect)
            if page is None:
                return full, None, len(full) * self.page_size, prepinned
            cow_src = page
            prepinned.add(page)
        return full, cow_src, matched, prepinned

    # pages faulted back per scheduler step by prefetch-on-queue; one
    # batched import_pages dispatch covers the whole bound, so raising
    # it trades step-time import bytes against extra queue-wait steps
    _SPILL_PREFETCH_BOUND = 8

    def _spill_prefetch(self) -> None:
        """Prefetch-on-queue: probe the wait-queue HEAD's prompt against
        the prefix index and fault back the matched chain's spilled
        nodes BEFORE admission — one batched :func:`import_pages` call
        per step (bounded), instead of one import dispatch per node at
        admit time. Prefetched pages park cold-resident at refcount 0,
        so the admission that follows pins them through the ordinary
        resident-chain path; byte-exact imports keep the stream bitwise
        identical to fault-on-match, only the dispatch count on the
        TTFT path drops.

        Discipline mirrors :meth:`_admit`: the chain's RESIDENT nodes
        are pinned first so the reservation can never spill a parent out
        from under a child about to unspill, and the chain's spilled
        vids ride the reservation's protect-set (the PR 19 fix) so the
        budget-discard pass cannot drop the payloads being prefetched.
        A failed reservation degrades to fault-on-match at admission —
        never an error."""
        if (
            self._spill_store is None
            or self.spill_prefetch != "on"
            or not self.queue
            or self.index is None
            or not len(self._spill_store)
        ):
            return
        req = self.queue[self._select_queued()]
        p = int(req.prompt.size)
        full, cow_src, _ = self.index.match(req.prompt[: p - 1])
        cand = list(full) + ([cow_src] if cow_src is not None else [])
        spilled = [pg for pg in cand if self.index.is_spilled(pg)]
        if not spilled:
            return
        # chain order: full's spilled nodes are a suffix of the chain,
        # the COW source chains under its tail — truncating to a PREFIX
        # of that list keeps every parent ahead of its child
        vids = spilled[: self._SPILL_PREFETCH_BOUND]
        pinned = [pg for pg in cand if pg not in set(spilled)]
        for pg in pinned:
            self.alloc.incref(pg)
            self.index.revive(pg)
        if not self._try_reserve(len(vids), protect=set(vids)):
            self._release_pages(pinned)
            return
        pages = self.alloc.alloc(len(vids))
        payloads = [self._spill_store.pop(v) for v in vids]
        k = np.concatenate([pl[0] for pl in payloads], axis=1)
        v = np.concatenate([pl[1] for pl in payloads], axis=1)
        sk = sv = None
        if payloads[0][2] is not None:
            sk = np.concatenate([pl[2] for pl in payloads], axis=1)
            sv = np.concatenate([pl[3] for pl in payloads], axis=1)
        self.pool = import_pages(self.pool, pages, k, v, sk, sv)
        for vid, page in zip(vids, pages):
            self.index.unspill(vid, page)
        self.spill_faultback_pages += len(pages)
        self.spill_prefetch_pages += len(pages)
        # decref to 0 → cold-resident and matchable: admission pins them
        self._release_pages(pinned + list(pages))

    def _release_pages(self, pages: tp.Iterable[int]) -> None:
        """Decref a request's pages: indexed ones retire to the cold
        prefix cache (still matchable), private ones free outright."""
        for p in pages:
            cached = self.index is not None and p in self.index
            if self.alloc.decref(p, cache=cached) == 0 and cached:
                self.index.touch_cold(p)

    # -- admission ----------------------------------------------------------

    def _shed_expired_queued(self) -> None:
        """Drop every queued request whose deadline already passed —
        BEFORE dispatch, so no window is spent on tokens the SLO can no
        longer bank. Zero-cost without deadlines: the clock is read
        only when a deadline-carrying request is actually queued."""
        now: tp.Optional[float] = None
        for req in [r for r in self.queue if r.deadline is not None]:
            if now is None:
                now = self.clock()
            if now > req.deadline:
                self.queue.remove(req)
                self._expire(req, where="queued")

    def _select_queued(self) -> int:
        """Index of the next request to admit. Two bands:

        1. RESUMED work (``evictions > 0`` — eviction/park re-queues
           with progress kept) goes first, in queue order: it holds an
           in-flight budget promise and re-prefills mostly from cache,
           and this reproduces the old appendleft-FIFO discipline
           exactly.
        2. Fresh submissions by aged effective priority
           ``priority + priority_aging * (steps queued)``, FIFO
           (``queue_seq``) within a band — so equal priorities ARE the
           old FIFO, and a starved low priority provably ages past any
           fixed higher priority (the front-door starvation test pins
           the bound).

        Deterministic: every key component is a scheduler-step or
        submission-order quantity, never wall clock."""
        best, best_key = 0, None
        for i, req in enumerate(self.queue):
            if req.evictions > 0:
                key: tp.Tuple = (0, i)
            else:
                eff = req.priority + self.priority_aging * (
                    self.fault_step - req.queue_step
                )
                key = (1, -eff, req.queue_seq)
            if best_key is None or key < best_key:
                best, best_key = i, key
        return best

    def _admit(self) -> None:
        self._shed_expired_queued()
        admitted = 0
        for s in range(self.slots):
            if not self.queue or admitted >= self._max_prefills:
                break
            if self.slot_req[s] is not None:
                continue
            qi = self._select_queued()
            req = self.queue[qi]
            p = int(req.prompt.size)
            # prefix-cache match, capped at p-1: the last prompt token is
            # ALWAYS recomputed — its forward pass is what produces the
            # first decode logits (and, page-granularly, guarantees the
            # slot's append page is never a shared one)
            full: tp.List[int] = []
            cow_src: tp.Optional[int] = None
            matched = 0
            if self.index is not None:
                full, cow_src, matched = self.index.match(req.prompt[: p - 1])
            # PIN the matched chain (and the COW source, until its copy
            # lands) before reserving: revived out of the LRU, the
            # reservation below can never reclaim (or spill) them out
            # from under us. Spilled nodes — virtual ids forming a
            # suffix of the chain (spilled subtrees are closed
            # downward), possibly plus the COW source — cannot be
            # increfed: they fault back AFTER the resident pins land,
            # each returning a fresh page already carrying its pin at
            # refcount 1.
            cand = list(full) + ([cow_src] if cow_src is not None else [])
            spilled_vids = (
                {pg for pg in cand if self.index.is_spilled(pg)}
                if self.index is not None else set()
            )
            pinned = [pg for pg in cand if pg not in spilled_vids]
            for pg in pinned:
                self.alloc.incref(pg)
                self.index.revive(pg)
            # Reserve the WHOLE demand — fresh pages plus one per
            # spilled chain node — BEFORE any fault-back import: a
            # head-of-line block must cost zero import_pages dispatches
            # (pages imported first would unpin straight back to cold
            # and re-spill on every retry of a blocked large request).
            # The chain's spilled vids are protected so the
            # reservation's own budget-discard pass cannot drop the
            # payloads about to be materialized.
            need = pages_needed(p, self.page_size) - len(full)
            if not self._try_reserve(
                need + len(spilled_vids), protect=spilled_vids
            ):
                # head-of-line blocks: unpin and wait for pages to free
                # (deliberately no skip-ahead to a smaller request —
                # bypassing the selected head would starve large ones)
                self._release_pages(pinned)
                break
            if self._spill_store is not None:
                full, cow_src, matched, prepinned = self._fault_back_matched(
                    full, cow_src, matched, protect=spilled_vids
                )
                pinned.extend(sorted(prepinned))
                # a no-op can_alloc check unless a fault-back truncated
                # the match (impossible after the reservation above, but
                # the degradation path stays honest)
                need = pages_needed(p, self.page_size) - len(full)
                if not self._try_reserve(need):
                    self._release_pages(pinned)
                    break
            del self.queue[qi]
            fresh = self.alloc.alloc(need)
            pages = full + fresh
            if cow_src is not None:
                # fresh[0] becomes the private copy-on-write page holding
                # the partial tail of the matched prefix
                dst = fresh[0]
                self.pool = self._copy_fn(
                    self.pool,
                    jnp.asarray(cow_src, jnp.int32),
                    jnp.asarray(dst, jnp.int32),
                )
                self.copy_dispatches += 1
                self._release_pages([cow_src])  # back to cold (or shared)
            n_pages = len(pages)
            self.slot_req[s] = req
            self.slot_pages[s] = list(pages)
            self.bt[s, :] = self._sentinel
            self.bt[s, :n_pages] = pages
            self.pooled_len[s] = matched
            self.done[s] = True  # not decodable until prefill completes
            self.prefilling[s] = True
            self.emitted[s] = len(req.tokens)
            self.budget[s] = req.max_new_tokens
            self.eos[s] = req.eos_id
            self.seeds[s] = req.seed
            self.slot_ctx[s] = [int(t) for t in req.prompt]
            self.slot_registered[s] = len(full)
            self.slot_node[s] = full[-1] if full else PrefixIndex._ROOT
            self.prompt_tokens_total += p
            self.prompt_tokens_cached += matched
            req.cached_tokens += matched
            req.admit_tokens = len(req.tokens)  # livelock-guard baseline
            now = self.clock()
            if not req.tokens and req.evictions == 0:
                # first admission of a fresh request: the wait it just
                # paid IS the queue delay (re-admissions are eviction
                # stall, tracked by telemetry's derived metrics)
                self.metrics.histogram("queue_delay_s").observe(
                    now - req.submit_time
                )
            self._emit(
                "admitted", rid=req.rid, t=now, slot=s, prompt_tokens=p,
                cached_tokens=matched, pages=n_pages,
            )
            admitted += 1

    # -- chunked prefill ----------------------------------------------------

    def _prefill_one_chunk(self, s: int) -> bool:
        """Run ONE prefill chunk for slot ``s``; returns True when the
        slot's prompt is fully resident (the slot becomes decodable)."""
        req = self.slot_req[s]
        assert req is not None and self.prefilling[s]
        p = len(self.slot_ctx[s])  # == req.prompt.size at admission
        start = int(self.pooled_len[s])
        remaining = p - start
        assert remaining >= 1, (s, p, start)
        clen = (
            remaining
            if self.prefill_chunk is None
            else min(self.prefill_chunk, remaining)
        )
        bucket = self._prefill_bucket(clen)
        toks = np.full((1, bucket), self.pad_id, np.int32)
        toks[0, :clen] = req.prompt[start : start + clen]
        if bucket not in self._chunk_fns:
            self._chunk_fns[bucket] = make_prefill_chunk_program(
                self.model,
                chunk_len=bucket,
                pmax=self.pmax,
                rope_len=self.block,
                mesh=self._mesh,
                layer_scan=self.layer_scan,
                prefill_sp=self.prefill_sp,
            )
        tele = self.telemetry
        t0 = self.clock() if tele is not None else 0.0
        self.pool, self.logits = self._chunk_fns[bucket](
            self.model,
            self.pool,
            self.logits,
            jnp.asarray(s, jnp.int32),
            jnp.asarray(toks),
            jnp.asarray(start, jnp.int32),
            jnp.asarray(clen, jnp.int32),
            jnp.asarray(self.bt[s]),
        )
        self.prefill_dispatches += 1
        self.prefill_tokens_computed += clen
        if tele is not None:
            t1 = self.clock()
            tele.record_dispatch(
                "prefill_chunk", step=self.fault_step, t=t0, dur=t1 - t0,
                rids=(req.rid,), tokens=0, slot=s, start=start,
                chunk=clen, bucket=bucket,
            )
            tele.emit(
                "prefill_chunk", step=self.fault_step, t=t1, rid=req.rid,
                slot=s, start=start, chunk=clen, bucket=bucket,
            )
        self.pooled_len[s] = start + clen
        self._register_pages(s)
        if start + clen >= p:
            self.prefilling[s] = False
            if self.role == "prefill":
                # disaggregated pools: the slot parks fully-prefilled
                # (done stays True, so no decode window ever carries
                # it) until the cluster exports its pages to a
                # decode-class engine
                self.handoff_ready[s] = True
            else:
                self.done[s] = False  # decodable from the next window on
            return True
        return False

    def _run_prefills(self) -> None:
        """Sarathi-style chunk scheduling: round-robin one chunk per
        prefilling slot until the per-window token budget is spent (the
        first chunk always runs, so prefill can never starve). The
        rotation cursor persists ACROSS windows — with the default
        one-chunk budget, restarting at slot 0 every window would feed
        slot 0's whole prompt before a second prefilling slot saw its
        first chunk, exactly the TTFT starvation chunking exists to
        bound."""
        spent = 0
        while True:
            pending = [s for s in range(self.slots) if self.prefilling[s]]
            if not pending:
                return
            pending.sort(
                key=lambda s: (s - self._prefill_rr) % self.slots
            )
            for s in pending:
                if not self.prefilling[s]:
                    continue
                before = self.prefill_tokens_computed
                self._prefill_one_chunk(s)
                self._prefill_rr = (s + 1) % self.slots
                spent += self.prefill_tokens_computed - before
                if self.prefill_budget is not None and (
                    spent >= self.prefill_budget
                ):
                    return

    # -- prefix-index registration ------------------------------------------

    def _register_pages(self, s: int) -> None:
        """Index every newly-FULL page of slot ``s`` by its content chain.
        Full pages are immutable (append-only pool), so once indexed they
        may be aliased into any other block table."""
        if self.index is None:
            return
        ps = self.page_size
        ctx = self.slot_ctx[s]
        resident = int(self.pooled_len[s])
        while (self.slot_registered[s] + 1) * ps <= resident:
            i = self.slot_registered[s]
            page = int(self.bt[s, i])
            chunk = ctx[i * ps : (i + 1) * ps]
            canonical = self.index.register(self.slot_node[s], chunk, page)
            if canonical != page and self.index.is_spilled(canonical):
                # re-admission of a spilled prefix: identical content was
                # just recomputed into a resident page, so adopt OUR page
                # as the node (re-key, byte-identical by the chain hash)
                # and drop the host payload — no import dispatch needed
                self._spill_store.pop(canonical)
                self.index.unspill(canonical, page)
                self.spill_readmissions += 1
                canonical = page
            if canonical != page:
                # identical content was indexed first by someone else: our
                # page stays private (freed, not cached, at release) and
                # the chain continues through the canonical id — which we
                # must PIN (we hold no ref on it via slot_pages), or cold
                # LRU reclaim could free it while it is still this slot's
                # chain parent, leaving a dangling id in the index
                self.alloc.incref(canonical)
                self.index.revive(canonical)
                self.slot_pins[s].append(canonical)
            self.slot_node[s] = canonical
            self.slot_registered[s] += 1

    # -- release / eviction -------------------------------------------------

    def _release_slot(self, s: int) -> None:
        self._release_pages(self.slot_pages[s])
        self._release_pages(self.slot_pins[s])
        self.slot_pages[s] = []
        self.slot_pins[s] = []
        self.slot_req[s] = None
        self.bt[s, :] = self._sentinel
        self.pooled_len[s] = 0
        self.done[s] = True
        self.prefilling[s] = False
        self.handoff_ready[s] = False
        self.slot_ctx[s] = []
        self.slot_registered[s] = 0
        self.slot_node[s] = PrefixIndex._ROOT

    def _evict(self, s: int, park: bool = False) -> None:
        """Preempt slot ``s``: keep its progress (prompt grows by the
        generated tokens, budget shrinks to the remainder) and re-queue it
        at the FRONT so it resumes as soon as pages free up. Its pages
        retire to the cold prefix cache, so re-admission typically
        re-prefills via cache hits — same tokens, a fraction of the
        FLOPs, and still bit-identical.

        Livelock guard: a request evicted ``park_threshold`` times in a
        row WITHOUT emitting a token since its admission is thrashing —
        two requests repeatedly trading the same pages would re-prefill
        each other forever — so it PARKS (``self.parked``) until a
        finish, a quarantine release, or an idle engine un-parks it,
        instead of spinning through admission again. ``park=True``
        (single-slot pool exhaustion) parks unconditionally. Parking
        rides the same progress-preserving path as eviction, so parked
        streams resume bit-identically too."""
        req = self.slot_req[s]
        assert req is not None
        progressed = len(req.tokens) > req.admit_tokens
        req.thrash = 0 if progressed else req.thrash + 1
        # rebuild from the ORIGINAL prompt (a second eviction appending to
        # an already-grown prompt would duplicate the first eviction's
        # tokens — caught in code review). prompt0 <= block - max_new, so
        # prompt0 + generated always fits block - remaining: no cropping,
        # and the continuation is identical to the un-evicted run
        req.prompt = np.concatenate(
            [req.prompt0, np.asarray(req.tokens, np.int32)]
        )
        req.evictions += 1
        self._emit(
            "evicted", rid=req.rid, slot=s, progressed=bool(progressed),
            evictions=req.evictions,
        )
        self._release_slot(s)
        self.evictions += 1
        if park:
            self.overload_parks += 1
            self.parked.append(req)
            self._emit("parked", rid=req.rid, reason="overload")
        elif req.thrash >= self.park_threshold:
            self.livelock_parks += 1
            self.parked.append(req)
            self._emit("parked", rid=req.rid, reason="livelock")
        else:
            self.queue.appendleft(req)

    def _unpark(self) -> None:
        """Release every parked request back onto the wait queue.
        Called when pages may have come back: a request finished, a
        fault-injected quarantine lifted, or the engine went otherwise
        idle (nothing else will ever free pages, so parked work must
        retry).

        Un-parking used to be blind FIFO; now (a) ordering is the
        admission selector's job — released requests re-enter the queue
        and ``_select_queued`` ranks them with everyone else (parked
        work always carries ``evictions > 0``, so it rides the resumed
        band and still beats fresh submissions, in park order), and
        (b) a parked request whose deadline passed while it waited is
        SHED here instead of re-queued — re-prefilling a request that
        can no longer meet its SLO would burn exactly the pages its
        peers are starved for (counted ``deadline_shed_requests``,
        evented ``deadline_shed`` with ``where="parked"``)."""
        now: tp.Optional[float] = None
        while self.parked:
            req = self.parked.pop(0)
            if req.deadline is not None:
                if now is None:
                    now = self.clock()
                if now > req.deadline:
                    self._expire(req, where="parked")
                    continue
            self._emit("resumed", rid=req.rid)
            req.queue_step = self.fault_step  # aging restarts at release
            self.queue.append(req)

    def _ensure_growth(self) -> None:
        """Before the window, every decoding slot needs pages for up to K
        more tokens; allocate on demand, evicting the youngest request (by
        admission recency ~ least progress) under pool pressure."""
        for s in self._decoding_slots():
            if self.slot_req[s] is None:
                continue  # evicted by an earlier slot's pressure this pass
            # growth is capped at the request's REMAINING budget, not the
            # raw window: near end-of-generation pooled_len + window can
            # point past the request's lifetime (and past the block
            # table), and demanding those pages would crash or evict
            # healthy requests for tokens that will never be written
            remaining = int(self.budget[s]) - int(self.emitted[s])
            tokens = int(self.pooled_len[s]) + min(self._grow, remaining)
            need = min(
                pages_needed(tokens, self.page_size), self.pmax
            ) - len(self.slot_pages[s])
            parked_self = False
            while need > 0 and not self._try_reserve(need):
                others = [v for v in self._active_slots() if v != s]
                if not others:
                    # even the lone request cannot grow (fault-injected
                    # quarantine, or a pool transiently starved of cold
                    # pages): PARK it with progress kept instead of the
                    # old hard MemoryError — it resumes when pages come
                    # back, and overload shows up as a counter, not a
                    # crash
                    self._evict(s, park=True)
                    parked_self = True
                    break
                # least progress loses: cheapest re-prefill on re-admission
                self._evict(min(others, key=lambda v: len(self.slot_req[v].tokens)))
            if parked_self:
                continue
            if need > 0:
                pages = self.alloc.alloc(need)
                start = len(self.slot_pages[s])
                self.slot_pages[s].extend(pages)
                self.bt[s, start : start + need] = pages

    # -- speculative drafting -----------------------------------------------

    def _draft(
        self, decoding: tp.List[int]
    ) -> tp.Tuple[np.ndarray, np.ndarray, tp.Optional[np.ndarray]]:
        """Host-side n-gram drafts for this verify dispatch: up to
        ``req.spec_k`` (the slot's ADAPTIVE draft length) guesses for the
        tokens FOLLOWING the pending next token, suffix-matched from the
        request's own prompt+generated history. Slots with no usable
        match ride with ``n_draft = 0`` — the dispatch degrades to plain
        one-token decode for them, never stalls them.

        Returns ``(drafts, n_draft, draft_probs)``; ``draft_probs`` is
        the dense ``[S, spec_len, V]`` draft distribution when the
        proposer is soft (SoftProposer protocol), else ``None`` — the
        default n-gram path is one-hot IN-PROGRAM and never builds it
        (zero rows are safe: every row past ``n_draft`` is masked out of
        acceptance and the residual carry)."""
        drafts = np.zeros((self.slots, self.speculate), np.int32)
        n_draft = np.zeros((self.slots,), np.int32)
        probs = (
            np.zeros(
                (self.slots, self.speculate, self.model.config.vocab_size),
                np.float32,
            )
            if self._soft_drafts
            else None
        )
        for s in decoding:
            req = self.slot_req[s]
            # clamp to the remaining budget: row 0 takes one of the
            # request's `remaining` tokens, so only remaining-1 drafts
            # can ever be emitted — rows past that would run the full
            # model and be discarded by the in-program budget mask
            remaining = int(self.budget[s]) - int(self.emitted[s])
            k = min(req.spec_k, self.speculate, remaining - 1)
            if k < 1:
                continue
            if probs is not None:
                # the request seed rides along: honest soft drafting
                # needs per-request entropy (see SoftProposer — a
                # ctx-only-derandomized "sample" is a point mass and
                # breaks rejection-sampling exactness across requests)
                got, q = self.proposer.propose_soft(
                    self.slot_ctx[s], k, req.seed
                )
                got = list(got)[: self.speculate]
                if len(got):
                    probs[s, : len(got)] = np.asarray(
                        q, np.float32
                    )[: len(got)]
            else:
                got = self.proposer.propose(self.slot_ctx[s], k)
                got = got[: self.speculate]
            drafts[s, : len(got)] = got
            n_draft[s] = len(got)
        return drafts, n_draft, probs

    def _adapt_spec(self, req: Request, drafted: int, accepted: int) -> None:
        """Per-request draft-length controller: track a trailing
        acceptance-rate EWMA and size the next draft to it — a request in
        a repetitive region climbs back to the full ``speculate``, one in
        novel text decays toward 1 (cheap single-draft probes keep the
        estimate live, so recovery is automatic)."""
        self.spec_drafted += drafted
        self.spec_accepted += accepted
        req.spec_drafted += drafted
        req.spec_accepted += accepted
        if drafted < 1:
            return
        rate = accepted / drafted
        req.spec_rate = 0.5 * req.spec_rate + 0.5 * rate
        req.spec_k = max(
            1,
            min(
                self.speculate,
                int(round(1 + req.spec_rate * (self.speculate - 1))),
            ),
        )

    def _run_verify(self, decoding: tp.List[int]) -> None:
        """One speculative verify dispatch + harvest (the spec-mode
        replacement for the K-step decode window)."""
        drafts, n_draft, draft_probs = self._draft(decoding)
        tele = self.telemetry
        if tele is not None:
            t0 = self.clock()
            rids = tuple(self.slot_req[s].rid for s in decoding)
        args = [
            self.model,
            self.pool,
            self.logits,
            jnp.asarray(self.bt),
            jnp.asarray(self.pooled_len),
            jnp.asarray(self.done),
            jnp.asarray(self.emitted),
            jnp.asarray(self.budget),
            jnp.asarray(self.eos),
            jnp.asarray(drafts),
            jnp.asarray(n_draft),
        ]
        if self.temperature > 0.0:
            # sampled verify: per-slot request seeds + the engine's base
            # key — the program derives every categorical/acceptance
            # stream from (seed, stream position) alone, so the same
            # discipline that makes the plain sampled window scheduling-
            # invariant carries over to speculation unchanged
            args += [jnp.asarray(self.seeds), self._key]
            if draft_probs is not None:
                args.append(jnp.asarray(draft_probs))
        (
            self.pool, self.logits, cand, emit, done_d, new_len,
            emitted_d, n_acc,
        ) = self._verify_fn(*args)
        self.decode_dispatches += 1
        self.verify_dispatches += 1
        self.windows += 1
        self.occupancy_sum += len(decoding)

        # ONE device->host sync per dispatch: the [S, T] outputs
        cand_h = np.asarray(cand)
        emit_h = np.asarray(emit)
        n_acc_h = np.asarray(n_acc)
        self.done = np.array(done_d)
        self.pooled_len = np.array(new_len, np.int32)
        self.emitted = np.array(emitted_d, np.int32)
        now = self.clock()
        if tele is not None:
            # timestamped at the existing harvest sync — tracing adds
            # no device round-trip of its own
            n_window = int(emit_h[np.asarray(decoding)].sum())
            tele.record_dispatch(
                "verify_dispatch", step=self.fault_step, t=t0,
                dur=now - t0, rids=rids, tokens=n_window,
                drafted=int(np.asarray(n_draft)[np.asarray(decoding)].sum()),
                accepted=int(n_acc_h[np.asarray(decoding)].sum()),
            )
            self.metrics.histogram("dispatch_s").observe(now - t0)
            tele.emit(
                "verify_dispatch", step=self.fault_step, t=now,
                slots=len(decoding), tokens=n_window,
            )
        finished_any = False
        for s in decoding:
            req = self.slot_req[s]
            new = [
                int(cand_h[s, j])
                for j in range(self.speculate + 1)
                if emit_h[s, j]
            ]
            if new and req.first_token_time is None:
                req.first_token_time = now
            req.tokens.extend(new)
            self.slot_ctx[s].extend(new)
            self.tokens_generated += len(new)
            self._adapt_spec(req, int(n_draft[s]), int(n_acc_h[s]))
            self._register_pages(s)
            if tele is not None:
                tele.emit(
                    "tokens", step=self.fault_step, t=now, rid=req.rid,
                    n=len(new), total=len(req.tokens), slot=s,
                )
            if self.done[s]:
                self._finish_request(req, now, s)
                finished_any = True
        if finished_any and self.parked:
            self._unpark()  # freed pages: parked requests get another shot

    def _finish_request(self, req: Request, now: float, slot: int) -> None:
        """Retire a finished request from its slot and observe the
        finish-time histograms — TTFT/e2e always (the scheduler already
        holds both timestamps), per-token TBT only under tracing (it
        needs the telemetry token timeline)."""
        req.finish_time = now
        req.outcome = "finished"
        self._live.pop(req.rid, None)
        self.finished[req.rid] = req
        if req.first_token_time is not None:
            self.metrics.histogram("ttft_s").observe(
                req.first_token_time - req.submit_time
            )
        self.metrics.histogram("e2e_s").observe(now - req.submit_time)
        tele = self.telemetry
        if tele is not None:
            ts = tele.token_times(req.rid)
            h = self.metrics.histogram("tbt_s")
            for a, b in zip(ts, ts[1:]):
                h.observe(b - a)
            tele.emit(
                "finished", step=self.fault_step, t=now, rid=req.rid,
                tokens=len(req.tokens), evictions=req.evictions,
            )
        self._release_slot(slot)

    @property
    def has_work(self) -> bool:
        """Queued, parked, or in-flight requests remain."""
        return bool(self.queue or self.parked or self._active_slots())

    def step(self) -> bool:
        """One scheduler window. Returns True while there is (or was) work.

        May raise a scripted :mod:`~midgpt_tpu.serving.faults` fault when
        a ``fault_hook`` is installed — always BEFORE any dispatch, so
        the engine's request state stays consistent and drainable."""
        self.fault_step += 1
        if self.telemetry is not None:
            # optional jax.profiler window (telemetry.profile_steps):
            # host-driven start/stop at step boundaries, no effect on
            # the compiled programs
            self.telemetry.maybe_profile(self.fault_step)
        if self._fault_hook is not None:
            self._fault_hook(self)
        if self.parked and not self.queue and not self._active_slots():
            # nothing else can free pages — parked work must retry now
            self._unpark()
        self._spill_prefetch()
        self._admit()
        self._run_prefills()
        decoding = self._decoding_slots()
        if not decoding:
            # progress was prefill-only (or nothing runnable yet)
            return self.has_work
        self._ensure_growth()
        decoding = self._decoding_slots()  # eviction may have changed it
        if not decoding:
            return True

        if self.speculate:
            self._run_verify(decoding)
            return True

        tele = self.telemetry
        if tele is not None:
            t0 = self.clock()
            rids = tuple(self.slot_req[s].rid for s in decoding)
        (
            self.pool, self.logits, toks, emit, done_d, new_len, emitted_d
        ) = self._window_fn(
            self.model,
            self.pool,
            self.logits,
            jnp.asarray(self.bt),
            jnp.asarray(self.pooled_len),
            jnp.asarray(self.done),
            jnp.asarray(self.emitted),
            jnp.asarray(self.budget),
            jnp.asarray(self.eos),
            jnp.asarray(self.seeds),
            self._key,
        )
        self.decode_dispatches += 1
        self.windows += 1
        self.occupancy_sum += len(decoding)

        # ONE device->host sync per window: the stacked [K, S] outputs
        toks_h = np.asarray(toks)
        emit_h = np.asarray(emit)
        # np.array (copy): zero-copy views of jax buffers are read-only,
        # and the scheduler mutates these in place
        self.done = np.array(done_d)
        self.pooled_len = np.array(new_len, np.int32)
        self.emitted = np.array(emitted_d, np.int32)
        now = self.clock()
        if tele is not None:
            # timestamped at the existing harvest sync — tracing adds
            # no device round-trip of its own
            n_window = int(emit_h[:, np.asarray(decoding)].sum())
            tele.record_dispatch(
                "decode_window", step=self.fault_step, t=t0, dur=now - t0,
                rids=rids, tokens=n_window, window=self.window,
            )
            self.metrics.histogram("dispatch_s").observe(now - t0)
            tele.emit(
                "decode_window", step=self.fault_step, t=now,
                slots=len(decoding), tokens=n_window,
            )
        finished_any = False
        for s in decoding:
            req = self.slot_req[s]
            new = [int(t) for r in range(self.window)
                   for t in [toks_h[r, s]] if emit_h[r, s]]
            if new and req.first_token_time is None:
                req.first_token_time = now
            req.tokens.extend(new)
            self.slot_ctx[s].extend(new)
            self.tokens_generated += len(new)
            # generated tokens fill pages too — register them so shared-
            # context traffic (multi-turn chat) hits on earlier turns
            self._register_pages(s)
            if tele is not None:
                tele.emit(
                    "tokens", step=self.fault_step, t=now, rid=req.rid,
                    n=len(new), total=len(req.tokens), slot=s,
                )
            if self.done[s]:
                self._finish_request(req, now, s)
                finished_any = True
        if finished_any and self.parked:
            self._unpark()  # freed pages: parked requests get another shot
        return True

    def warm_prefill(self, max_tokens: int) -> tp.List[int]:
        """Pre-compile every prefill-chunk bucket a trace of prompts up
        to ``max_tokens`` (suffix) tokens can dispatch — all powers-of-
        two page counts up to the largest single chunk. With the prefix
        cache on, admissions prefill arbitrary SUFFIX lengths (and
        chunking caps them at ``prefill_chunk``), so warming only the
        full-prompt buckets leaves compiles inside the measured region
        on exactly the cache-hit/chunked paths. Each bucket runs one
        pad-token no-op chunk: an all-sentinel block table drops the
        page writes, and the engine must be idle (slot 0's logits row is
        scratch). Returns the warmed bucket lengths."""
        assert not self._active_slots(), "warm_prefill needs an idle engine"
        cap = min(
            max_tokens
            if self.prefill_chunk is None
            else min(self.prefill_chunk, max_tokens),
            self.pmax * self.page_size,
        )
        buckets = sorted(
            {self._prefill_bucket(n) for n in range(1, cap + 1)}
        )
        sentinel_row = jnp.full((self.pmax,), self._sentinel, jnp.int32)
        for b in buckets:
            if b not in self._chunk_fns:
                self._chunk_fns[b] = make_prefill_chunk_program(
                    self.model,
                    chunk_len=b,
                    pmax=self.pmax,
                    rope_len=self.block,
                    mesh=self._mesh,
                    layer_scan=self.layer_scan,
                    prefill_sp=self.prefill_sp,
                )
            self.pool, self.logits = self._chunk_fns[b](
                self.model,
                self.pool,
                self.logits,
                jnp.asarray(0, jnp.int32),
                jnp.full((1, b), self.pad_id, jnp.int32),
                jnp.asarray(0, jnp.int32),
                jnp.asarray(b, jnp.int32),
                sentinel_row,
            )
        return buckets

    def clear_prefix_cache(self) -> int:
        """Reclaim every COLD cached page (refcount-0 resident prefixes)
        AND forget every host-spilled prefix; returns the total dropped.
        Live slots' pages are untouched. Benchmarks call this after
        warmup so measured hit rates (and spill counts) come from the
        measured trace alone."""
        n = 0
        if self.index is None:
            return n
        # spilled nodes first: they hang below cold resident pages, and
        # evict_cold_leaf skips any page with children (even virtual)
        if self._spill_store is not None:
            while True:
                vid = self.index.discard_spilled_oldest()
                if vid is None:
                    break
                self._spill_store.pop(vid)
                n += 1
        while True:
            victim = self.index.evict_cold_leaf()
            if victim is None:
                break
            self.alloc.reclaim(victim)
            n += 1
        return n

    def run(self, max_windows: int = 100_000) -> tp.Dict[int, Request]:
        """Drive :meth:`step` until queue and slots drain; returns the
        finished requests by id."""
        try:
            for _ in range(max_windows):
                if not self.has_work:
                    break
                self.step()
            else:
                raise RuntimeError(
                    f"engine did not drain in {max_windows} windows"
                )
        finally:
            if self.telemetry is not None:
                # a workload draining before the configured profiler
                # stop step must still finalize the trace
                self.telemetry.stop_profiling()
        return self.finished

    # -- reporting ----------------------------------------------------------

    def metrics_snapshot(self) -> tp.Dict[str, tp.Any]:
        """The full JSON-exportable registry view (counters, labeled
        families, live gauges, fixed-bucket histograms) —
        :meth:`stats` is the stable façade selecting from the same
        registry (telemetry.ENGINE_STATS_KEYS contract)."""
        return self.metrics.snapshot()

    def flight_dump(
        self,
        reason: str,
        path: tp.Optional[str] = None,
        extra: tp.Optional[tp.Dict[str, tp.Any]] = None,
    ) -> tp.Dict[str, tp.Any]:
        """The flight-recorder artifact: the bounded event + dispatch
        rings (when tracing is on), the metrics snapshot, and the stats
        façade, as one JSON-able record — what the cluster's fault
        paths and bench_serving's whole-trace watchdog persist so a
        wedged run still yields a timeline (the r4/r5 lesson). Reads
        host-side state only; safe to call best-effort from another
        thread (the cold-failover case — see
        telemetry.EngineTelemetry.flight_payload)."""
        rec: tp.Dict[str, tp.Any] = {
            "reason": reason,
            "fault_step": self.fault_step,
            "stats": self.stats(),
            "metrics": self.metrics_snapshot(),
            "telemetry": (
                self.telemetry.flight_payload()
                if self.telemetry is not None
                else None
            ),
        }
        if extra:
            rec.update(extra)
        if path is not None:
            rec["path"] = os.path.abspath(path)
            write_json(path, rec)
        return rec

    def stats(self) -> tp.Dict[str, float]:
        occ = self.occupancy_sum / max(1, self.windows * self.slots)
        return {
            "tp": self.tp,
            "decode_dispatches": self.decode_dispatches,
            "prefill_dispatches": self.prefill_dispatches,
            "copy_dispatches": self.copy_dispatches,
            "tokens_generated": self.tokens_generated,
            "windows": self.windows,
            "slot_occupancy": round(occ, 4),
            "evictions": self.evictions,
            "free_pages": self.alloc.free_pages,
            "cached_pages": self.alloc.cached_pages,
            "cold_reclaims": self.cold_reclaims,
            # cold-page host spill (spill="on"; all zero otherwise)
            "spilled_pages": self.spilled_pages,
            "spill_faultback_pages": self.spill_faultback_pages,
            "spill_prefetch_pages": self.spill_prefetch_pages,
            "spill_readmissions": self.spill_readmissions,
            "spill_discards": self.spill_discards,
            "spill_resident_pages": (
                len(self._spill_store)
                if self._spill_store is not None else 0
            ),
            "prompt_tokens_total": self.prompt_tokens_total,
            "prefill_tokens_saved": self.prompt_tokens_cached,
            "prefill_tokens_computed": self.prefill_tokens_computed,
            "prefix_hit_rate": round(
                self.prompt_tokens_cached / max(1, self.prompt_tokens_total),
                4,
            ),
            "tokens_per_dispatch": round(
                self.tokens_generated / max(1, self.decode_dispatches), 2
            ),
            "verify_dispatches": self.verify_dispatches,
            "spec_drafted_tokens": self.spec_drafted,
            "spec_accepted_tokens": self.spec_accepted,
            "spec_acceptance_rate": round(
                self.spec_accepted / max(1, self.spec_drafted), 4
            ),
            # fault tolerance / overload degradation (serving.faults)
            "admission_rejected": self.admission_rejected,
            "reject_reasons": dict(self.reject_reasons),
            "shed_requests": self.shed_requests,
            "deferred_submits": self.deferred_submits,
            "livelock_parks": self.livelock_parks,
            "overload_parks": self.overload_parks,
            "parked_requests": len(self.parked),
            # front-door outcomes (serving.frontdoor): submitter
            # cancellations and pre-dispatch deadline sheds
            "cancelled_requests": self.cancelled_requests,
            "deadline_shed_requests": self.deadline_shed_requests,
            "faults_injected": self.faults_injected,
        }


# Attach the registry-backed counter properties (data descriptors, so
# `engine.decode_dispatches += 1` and the bench's `setattr(e, name, 0)`
# reset both route through the registry's Counter objects).
for _name in _ENGINE_COUNTERS:
    setattr(ServingEngine, _name, _counter_property(_name))
del _name
