"""Shared-nothing data-parallel serving: N independent engine replicas
under one admission scheduler, with failover.

Tensor parallelism (``ServingEngine(mesh=...)``) scales a single engine
DOWN the latency axis — the model's weights and KV pool split over the
'tensor' axis of ONE mesh, every dispatch runs collectives. Data
parallelism scales UP the throughput axis, and for serving the right
shape is SHARED-NOTHING: each replica is a complete ``ServingEngine``
owning its own devices (or mesh slice), page pool, prefix cache, and
scheduler state, with no collective ever crossing replicas — a replica
failure or a slow request affects only its own slots, and replicas can
be added/removed without recompiling anything (the Gemma-on-TPU serving
comparison and the pjit scaling study, PAPERS.md, both benchmark exactly
this TPxDP composition).

:class:`ServingCluster` is the scheduler above the replicas:

- **Least-loaded admission**: ``submit`` routes each request to the
  healthy replica with the smallest backlog (queued + active requests;
  deterministic lowest-index tie-break). Because every engine's token
  stream is a function of the request alone (the determinism contract in
  ``serving.engine``), placement NEVER changes a request's tokens — only
  its latency — which the cluster test asserts directly.
- **Per-replica health + failover** (serving.faults): every replica is
  ``healthy``, ``suspect``, or ``dead``. A wall-clock dispatch watchdog
  (``dispatch_timeout_s``) catches the wedged-relay case (the r4/r5
  BENCH post-mortems: a dispatch that never returns); a
  ``TransientDispatchError`` is retried on the same replica with capped
  exponential backoff (``max_retries``/``backoff_s``/``backoff_cap_s``,
  suspect while retrying); a ``ReplicaCrash``, a watchdog trip, or
  exhausted retries mark the replica DEAD and its backlog fails over —
  WARM when the replica's step thread provably completed by raising
  (the engine drains exactly: in-flight slots convert through the
  bit-identical eviction path, progress preserved), COLD on a watchdog
  trip (the thread may still be running, so the engine is never
  touched again and its requests re-serve from scratch off the
  cluster's submission record). Failures are processed only after
  every replica's step has settled, so failover never mutates an
  engine mid-step. **Failover replay is bit-identical** either way:
  scripted faults fire at step boundaries (before any dispatch mutates
  state), re-queueing rides the eviction path or the determinism
  contract, and placement invariance makes the surviving stream equal
  to the fault-free run token for token — the chaos suite proves it,
  not just asserts it plausible.
- **Per-replica prefix caches**: no cross-replica page sharing (pages
  live in per-replica pools on disjoint devices). A shared-prefix mix
  therefore hits best when co-located; the least-loaded policy is
  deliberately content-blind — smarter affinity routing is a policy
  plug-in point, not an engine change.
- **Aggregated stats**: :meth:`stats` sums the per-engine counters and
  keeps the per-replica breakdown, in the same key layout as
  ``ServingEngine.stats`` (bench_serving emits it unchanged), plus the
  cluster-level failover counters (watchdog trips, retries, failovers,
  re-queued requests, replica health).

This is the seam the async front door (serving.frontdoor, ROADMAP
item 3 — shipped) slots into: streaming/cancellation/priorities wrap
``submit``/``step``/``cancel``/``lookup`` here without touching the
engines — and the health/failover layer beneath it is what lets that
front door promise SLOs.
"""

from __future__ import annotations

import concurrent.futures
import os
import sys
import time
import typing as tp

import numpy as np

from midgpt_tpu.serving.engine import Request, ServingEngine
from midgpt_tpu.serving.telemetry import EngineTelemetry
from midgpt_tpu.serving.faults import (
    AdmissionRejected,
    ClusterUnavailable,
    FaultPlan,
    PoolOverloaded,
    ReplicaCrash,
    TransientDispatchError,
    WedgedDispatch,
)


class _WatchdogTrip(Exception):
    """Internal marker: the cluster's wall-clock wait on a replica step
    expired with the step thread STILL RUNNING. Never raised by engine
    code — it exists to distinguish a true watchdog trip (cold,
    engine-abandoning failover) from an organic ``TimeoutError`` raised
    inside step(), which on Python 3.11+ is the same class as
    ``concurrent.futures.TimeoutError`` (thread completed → warm
    failover, like any crash)."""


def serving_meshes(
    tp_size: int = 1,
    dp_replicas: int = 1,
    devices: tp.Optional[tp.Sequence] = None,
) -> tp.List:
    """Disjoint tensor-only meshes for a TPxDP serving deployment: the
    first ``tp_size * dp_replicas`` devices split into ``dp_replicas``
    contiguous groups of ``tp_size`` (contiguous = ICI-adjacent under the
    standard device enumeration, the layout the pjit scaling study uses
    for its TP groups). ``tp_size == 1`` with one replica returns
    ``[None]`` — the engine's single-chip fast path, no mesh machinery at
    all; multi-replica tp=1 gets real 1-device meshes so each replica's
    arrays COMMIT to its own device instead of piling onto device 0."""
    import jax

    from midgpt_tpu.config import MeshConfig
    from midgpt_tpu.parallel.mesh import create_mesh

    assert tp_size >= 1 and dp_replicas >= 1, (tp_size, dp_replicas)
    if tp_size == 1 and dp_replicas == 1:
        return [None]
    devices = list(devices) if devices is not None else jax.devices()
    need = tp_size * dp_replicas
    assert len(devices) >= need, (
        f"tp={tp_size} x dp_replicas={dp_replicas} needs {need} devices, "
        f"have {len(devices)}"
    )
    cfg = MeshConfig(replica=1, fsdp=1, sequence=1, tensor=tp_size)
    return [
        create_mesh(cfg, devices=devices[i * tp_size : (i + 1) * tp_size])
        for i in range(dp_replicas)
    ]


class ServingCluster:
    """N shared-nothing :class:`ServingEngine` replicas + least-loaded
    admission + health-tracked failover. The cluster's request ids are
    its own (monotone, globally unique); per-replica ids stay internal.

    ``meshes`` pins each replica to its own mesh (``serving_meshes``
    builds the standard TPxDP split); ``replicas=N`` without meshes runs
    N schedulers on the default device — still useful: it is the
    scheduler-correctness configuration the tests drive, and the
    single-host shape the async front door (serving.frontdoor)
    multiplexes. All other keyword arguments go to every engine
    verbatim.

    Fault-tolerance knobs:

    - ``dispatch_timeout_s`` — wall-clock watchdog per replica step;
      ``None`` (default) disables it. A trip marks the replica dead
      (its dispatch may never return — re-using it would double-serve)
      and fails its backlog over.
    - ``max_retries`` / ``backoff_s`` / ``backoff_cap_s`` — capped
      exponential backoff for :class:`TransientDispatchError`
      (``sleep(min(backoff_s * 2**attempt, backoff_cap_s))`` before each
      retry); the replica rides ``suspect`` while retrying and returns
      ``healthy`` on success.
    - ``fault_plan`` — a :class:`~midgpt_tpu.serving.faults.FaultPlan`;
      each replica gets its own scripted hook
      (``plan.hook(replica_index)``), making whole-cluster chaos runs
      replayable bit for bit.
    """

    def __init__(
        self,
        model,
        *,
        replicas: tp.Optional[int] = None,
        meshes: tp.Optional[tp.Sequence] = None,
        fault_plan: tp.Optional[FaultPlan] = None,
        dispatch_timeout_s: tp.Optional[float] = None,
        max_retries: int = 3,
        backoff_s: float = 0.05,
        backoff_cap_s: float = 1.0,
        flight_dir: tp.Optional[str] = None,
        **engine_kwargs,
    ):
        if meshes is None:
            assert replicas is not None and replicas >= 1, (
                "need replicas=N or an explicit meshes= list"
            )
            meshes = [None] * replicas
        else:
            meshes = list(meshes)
            assert replicas is None or replicas == len(meshes), (
                f"replicas={replicas} contradicts {len(meshes)} meshes"
            )
        assert len(meshes) >= 1
        assert max_retries >= 0 and backoff_s >= 0.0, (
            max_retries, backoff_s,
        )
        # telemetry rides through engine_kwargs: telemetry=True gives
        # every replica its OWN EngineTelemetry (each engine constructs
        # one); a shared instance across replicas would interleave
        # event streams from concurrently-stepping threads, so it is
        # rejected here
        assert not (
            isinstance(engine_kwargs.get("telemetry"), EngineTelemetry)
            and len(meshes) > 1
        ), (
            "pass telemetry=True for a multi-replica cluster — each "
            "replica needs its own EngineTelemetry instance"
        )
        # flight_dir: where dead-replica flight-recorder artifacts land
        # (crash / watchdog trip / exhausted retries — every terminal
        # path dumps; paths collected in self.flight_dumps). None
        # disables the dumps.
        self.flight_dir = flight_dir
        self.flight_dumps: tp.List[str] = []
        self.engines: tp.List[ServingEngine] = []
        for i, m in enumerate(meshes):
            kw = dict(engine_kwargs)
            if fault_plan is not None:
                kw["fault_hook"] = fault_plan.hook(i)
            self.engines.append(ServingEngine(model, mesh=m, **kw))
        self.dispatch_timeout_s = dispatch_timeout_s
        self.max_retries = max_retries
        self.backoff_s = backoff_s
        self.backoff_cap_s = backoff_cap_s
        # per-replica health: healthy -> suspect (retrying a transient)
        # -> healthy, or -> dead (crash / watchdog trip / retries
        # exhausted). Dead is terminal: the backlog failed over, and a
        # wedged dispatch may still hold the old engine's buffers.
        self.health: tp.List[str] = ["healthy"] * len(self.engines)
        self.health_reason: tp.List[tp.Optional[str]] = (
            [None] * len(self.engines)
        )
        self.watchdog_trips = 0
        self.retries = 0
        self.failovers = 0
        self.requeued_requests = 0
        self.first_fault_time: tp.Optional[float] = None
        # global rid -> (replica index, engine-local rid)
        self._route: tp.Dict[int, tp.Tuple[int, int]] = {}
        # global rid -> (prompt, max_new_tokens, eos_id, seed): the cold
        # failover record (dropped at harvest)
        self._submitted: tp.Dict[int, tp.Tuple] = {}
        self._next_rid = 0
        self.finished: tp.Dict[int, Request] = {}
        # post-admission terminal outcomes that are not completions
        # (mirrors the per-engine dicts; harvested like finished)
        self.cancelled: tp.Dict[int, Request] = {}
        self.expired: tp.Dict[int, Request] = {}
        # one stepping thread per replica: ServingEngine.step blocks on
        # its window's device->host read, and a sequential loop would
        # keep replica B's devices idle while replica A's window
        # computes — time-multiplexing the "parallel" replicas. Engines
        # share no state (that is the design), jax dispatch/blocking
        # reads release the GIL, and each engine only ever runs on ONE
        # thread at a time (submit/step/run are driven from the caller's
        # thread; the pool just fans one step() per engine out). The
        # watchdog also needs the pool (a timeout requires stepping on a
        # thread the caller can abandon), so a single replica gets one
        # when dispatch_timeout_s is set. Workers are over-provisioned:
        # a wedged step occupies its worker until the stall ends, and
        # retries/failover must still find a free thread meanwhile.
        self._pool = (
            concurrent.futures.ThreadPoolExecutor(
                max_workers=max(4, 2 * len(self.engines)),
                thread_name_prefix="serving-replica",
            )
            if len(self.engines) > 1 or dispatch_timeout_s is not None
            else None
        )

    @property
    def replicas(self) -> int:
        return len(self.engines)

    def _alive(self) -> tp.List[int]:
        return [
            i for i in range(len(self.engines)) if self.health[i] != "dead"
        ]

    @property
    def has_work(self) -> bool:
        """Un-harvested cluster requests remain. Routes outlive replica
        deaths (failover re-points them at survivors), so this is the
        drain condition even mid-failover."""
        return bool(self._route) or any(
            self.engines[i].has_work for i in self._alive()
        )

    def _load(self, e: ServingEngine) -> int:
        """Backlog of one replica: queued + parked + in-flight requests.
        Counting requests (not tokens) keeps admission O(1) and
        deterministic; remaining-token estimates are a policy refinement
        the seam allows."""
        return len(e.queue) + len(e.parked) + len(e._active_slots())

    def _least_loaded(self, alive: tp.Sequence[int]) -> int:
        return min(alive, key=lambda j: (self._load(self.engines[j]), j))

    def submit(
        self,
        prompt: np.ndarray,
        max_new_tokens: int,
        *,
        eos_id: tp.Optional[int] = None,
        seed: int = 0,
        priority: int = 0,
        deadline_s: tp.Optional[float] = None,
        deadline: tp.Optional[float] = None,
    ) -> int:
        """Admit onto the least-loaded HEALTHY replica (lowest index on
        ties — deterministic, so a test trace routes identically every
        run); returns the cluster-global request id. Raises
        :class:`ClusterUnavailable` when every replica is dead, and
        passes the engine's typed admission outcomes
        (``AdmissionRejected``/``PoolOverloaded``) through to the
        caller — a rejection burns no cluster rid.

        A ``queue_full`` outcome SPILLS OVER: the routing metric (queue
        + parked + active) is not the metric the bound is enforced on
        (queue alone), so the least-loaded replica's full queue must
        not shed a request another healthy replica has room for — the
        remaining replicas are tried in load order and the overload
        outcome raises only when every queue is full. (Per-engine
        ``queue_full`` counters therefore count per-replica admission
        attempts; the request is only actually shed/deferred when the
        LAST replica refuses.) Permanent rejections are identical on
        every replica and re-raise immediately."""
        alive = self._alive()
        if not alive:
            raise ClusterUnavailable("every replica is dead")
        order = sorted(
            alive, key=lambda j: (self._load(self.engines[j]), j)
        )
        # the ABSOLUTE deadline is fixed here, at first cluster
        # admission (unless the caller anchored it earlier — e.g. the
        # front door at ARRIVAL time), and rides the submission record:
        # a cold-failover re-serve must keep the ORIGINAL SLO, exactly
        # like it keeps the original submit time (priority rides the
        # same way)
        if deadline is None and deadline_s is not None:
            deadline = self.engines[order[0]].clock() + deadline_s
        local = None
        for n, i in enumerate(order):
            try:
                local = self.engines[i].submit(
                    prompt, max_new_tokens, eos_id=eos_id, seed=seed,
                    priority=priority, deadline=deadline,
                )
                break
            except (AdmissionRejected, PoolOverloaded) as exc:
                if exc.reason != "queue_full" or n == len(order) - 1:
                    raise
        assert local is not None
        rid = self._next_rid
        self._next_rid += 1
        self._route[rid] = (i, local)
        # submission record for COLD failover: a watchdog-tripped
        # replica's step thread may still be running, so its engine can
        # never be touched again — surviving requests are then re-served
        # from scratch from this record (same tokens, by the determinism
        # contract; only the already-emitted progress is recomputed).
        # The ORIGINAL submit time rides along so a re-served request's
        # TTFT still measures from first submission — hiding the outage
        # the watchdog just detected would defeat the metric.
        self._submitted[rid] = (
            np.asarray(prompt, np.int32).reshape(-1).copy(),
            max_new_tokens, eos_id, seed, self.engines[i].clock(),
            priority, deadline,
        )
        return rid

    def cancel(self, rid: int) -> bool:
        """Cancellation routing: tear the cluster-global request down on
        whichever replica currently serves it (the route survives
        failover, so this follows the request). Idempotent; returns
        True when the request was live. The submission record drops
        with the route — a cancelled request must never be re-served by
        a later cold failover."""
        route = self._route.get(rid)
        if route is None:
            return False
        i, local = route
        req = self.engines[i].lookup(local)
        if self.health[i] == "dead" or req is None:
            if req is not None and req.outcome != "pending":
                # already terminal on the dead replica: harvest under
                # its REAL outcome instead of relabeling it cancelled
                dest = {
                    "finished": self.finished,
                    "cancelled": self.cancelled,
                    "expired": self.expired,
                }[req.outcome]
                dest[rid] = req
                del self._route[rid]
                self._submitted.pop(rid, None)
                return req.outcome == "cancelled"
            # a cold-abandoned replica's engine is never touched again;
            # the request exists only as the submission record now —
            # dropping route + record IS the cancellation (it was going
            # to be re-served from scratch)
            req = self.engines[i].make_request(
                self._submitted[rid][0], self._submitted[rid][1],
                eos_id=self._submitted[rid][2],
                seed=self._submitted[rid][3],
            )
            req.rid = local
            req.outcome = "cancelled"
            self.cancelled[rid] = req
            del self._route[rid]
            self._submitted.pop(rid, None)
            return True
        ok = self.engines[i].cancel(local)
        if ok:
            self.cancelled[rid] = self.engines[i].cancelled[local]
            del self._route[rid]
            self._submitted.pop(rid, None)
        return ok

    def lookup(self, rid: int) -> tp.Optional[Request]:
        """The live or terminal :class:`Request` for a cluster-global
        id (the front door's harvest seam). After a COLD failover the
        returned object is the survivor's fresh re-serve — its token
        list regrows the same stream from zero (determinism contract),
        which is exactly what the front door's per-stream cursor
        needs."""
        for d in (self.finished, self.cancelled, self.expired):
            req = d.get(rid)
            if req is not None:
                return req
        route = self._route.get(rid)
        if route is None:
            return None
        i, local = route
        if self.health[i] == "dead":
            return None  # between death and failover re-pointing
        return self.engines[i].lookup(local)

    def _harvest(self) -> None:
        for rid, (i, local) in list(self._route.items()):
            e = self.engines[i]
            req = e.finished.get(local)
            dest = self.finished
            if req is None:
                req = e.cancelled.get(local)
                dest = self.cancelled
            if req is None:
                req = e.expired.get(local)
                dest = self.expired
            if req is not None:
                dest[rid] = req
                del self._route[rid]
                self._submitted.pop(rid, None)

    # -- failure handling ---------------------------------------------------

    def _mark_dead(self, i: int, reason: str) -> None:
        self.health[i] = "dead"
        self.health_reason[i] = reason
        if self.first_fault_time is None:
            self.first_fault_time = time.monotonic()
        if self.flight_dir is not None:
            self._flight_dump(i, reason)

    def _flight_dump(self, i: int, reason: str) -> None:
        """Persist replica ``i``'s flight recorder on the one choke
        point every terminal failure crosses (crash, watchdog trip,
        exhausted retries all land in ``_mark_dead``). Best-effort BY
        DESIGN: on a watchdog trip the step thread may still be
        appending to the rings (snapshot-copied under the GIL), and a
        dump failure must never mask the failover it documents — it
        degrades to a stderr line."""
        path = os.path.join(
            self.flight_dir, f"flight_replica{i}_{reason}.json"
        )
        try:
            rec = self.engines[i].flight_dump(
                reason, path=path, extra={"replica": i},
            )
            self.flight_dumps.append(rec["path"])
        except Exception as e:  # noqa: BLE001 — see docstring
            print(
                f"flight-recorder dump for replica {i} ({reason}) "
                f"failed: {e}",
                file=sys.stderr,
            )

    def _failover(self, i: int, cold: bool = False) -> None:
        """Fail dead replica ``i``'s backlog over to the survivors;
        cluster rids keep pointing at the same logical requests — only
        the (replica, local-rid) route changes. Two modes:

        - WARM (default; the replica's step thread provably completed
          by raising): the engine drains — in-flight slots convert
          through the (bit-identical) eviction path, then queue and
          parking lot — and the survivors resume with progress kept.
        - COLD (``cold=True``; a watchdog trip — the step thread may
          still be running inside the runtime): the engine is never
          touched again (draining it would race live slot/page
          mutations). Every request still routed to it re-serves FROM
          SCRATCH off the cluster's submission record — the same stream
          by the determinism contract, with only the un-harvested
          progress recomputed, and the ORIGINAL submit time kept so
          TTFT still shows the outage.

        ``resubmit`` (not ``submit``) either way: already-accepted work
        bypasses the bounded-queue admission control."""
        self._harvest()  # dict reads are GIL-safe; scoop what finished
        self.failovers += 1
        drained = (
            None if cold
            else {r.rid: r for r in self.engines[i].drain_requests()}
        )
        mine = [g for g, (ri, _) in self._route.items() if ri == i]
        n_moved = len(mine) if cold else len(drained)
        self.requeued_requests += n_moved
        alive = self._alive()
        if not alive:
            if self._route:
                raise ClusterUnavailable(
                    f"replica {i} died ({self.health_reason[i]}) with "
                    f"{n_moved} requests to fail over and no survivors"
                )
            return
        for grid in mine:
            if cold:
                prompt, n, eos_id, seed, t0, prio, deadline = (
                    self._submitted[grid]
                )
                j = self._least_loaded(alive)
                req = self.engines[j].make_request(
                    prompt, n, eos_id=eos_id, seed=seed, priority=prio,
                    deadline=deadline,
                )
                req.submit_time = t0
            else:
                req = drained.pop(self._route[grid][1], None)
                if req is None:
                    continue  # finished and harvested above
                j = self._least_loaded(alive)
            self._route[grid] = (j, self.engines[j].resubmit(req))
        assert cold or not drained, (
            f"drained requests {sorted(drained)} had no cluster route"
        )

    @staticmethod
    def _classify(exc: BaseException) -> tp.Tuple[str, bool]:
        """(death reason, cold failover?) for a terminal step fault. A
        watchdog trip is the ONLY cold case — every other fault is a
        raise out of the step thread, which proves it completed (a
        scripted wedge's stall, in particular, has already ended)."""
        if isinstance(exc, _WatchdogTrip):
            return "wedged", True
        if isinstance(exc, WedgedDispatch):
            return "wedged", False
        return "crashed", False

    def _mark_terminal(self, i: int, exc: BaseException) -> bool:
        """Classify a terminal fault, count it, mark the replica dead;
        returns whether its failover must run COLD. Split from the
        failover itself so step() can mark ALL of a round's faults dead
        before any backlog moves."""
        reason, cold = self._classify(exc)
        if reason == "wedged":
            self.watchdog_trips += 1
        self._mark_dead(i, reason)
        return cold

    def _terminal_failure(self, i: int, exc: BaseException) -> None:
        """The one dead/failover transition: classify, mark dead, fail
        the backlog over."""
        self._failover(i, cold=self._mark_terminal(i, exc))

    @staticmethod
    def _settle(f, timeout: tp.Optional[float]) -> bool:
        """Wait for one replica-step future. Raises :class:`_WatchdogTrip`
        ONLY when the wait expires with the step thread still running —
        on Python 3.11+ ``concurrent.futures.TimeoutError`` IS the
        builtin ``TimeoutError``, so one raised organically INSIDE
        step() (thread completed) must NOT classify as a trip (a trip
        triggers the cold, engine-abandoning failover; a completed
        thread permits the warm drain)."""
        try:
            return bool(f.result(timeout=timeout))
        except concurrent.futures.TimeoutError:
            if not f.done():
                raise _WatchdogTrip() from None
            exc = f.exception()
            if exc is None:
                return bool(f.result())  # completed right at the deadline
            raise exc

    def _step_one(self, i: int, timeout: tp.Optional[float]) -> bool:
        """One replica step, on the pool when there is one (so the wait
        can be abandoned); raises the step's fault, if any."""
        if self._pool is None:
            return bool(self.engines[i].step())
        return self._settle(self._pool.submit(self.engines[i].step), timeout)

    def _recover(self, i: int) -> None:
        """Retry replica ``i`` after a transient failure: capped
        exponential backoff, suspect while retrying, healthy on success,
        dead + failover when the retries exhaust (or the retry hits a
        harder fault). The backoff sleeps run INLINE in the cluster's
        scheduling thread — deliberate: the retry must re-enter the
        replica's step() before the next scheduler round so scripted
        transient sequences stay replayable (``backoff_cap_s`` bounds
        the stall the other replicas see)."""
        self.health[i] = "suspect"
        self.health_reason[i] = "transient"
        for attempt in range(self.max_retries):
            time.sleep(
                min(self.backoff_s * (2 ** attempt), self.backoff_cap_s)
            )
            self.retries += 1
            try:
                self._step_one(i, self.dispatch_timeout_s)
            except TransientDispatchError:
                continue
            except self._STEP_FAULTS as exc:
                self._terminal_failure(i, exc)
                return
            self.health[i] = "healthy"
            self.health_reason[i] = None
            return
        self._mark_dead(i, "transient_exhausted")
        self._failover(i)

    # every fault class a replica step can surface; anything else is a
    # real bug and propagates. concurrent.futures.TimeoutError is listed
    # separately for Python < 3.11, where it is not the builtin
    # TimeoutError (organic timeouts classify as crashes either way —
    # _settle converts genuine wait-expiries to _WatchdogTrip first)
    _STEP_FAULTS = (
        TransientDispatchError,
        WedgedDispatch,
        ReplicaCrash,
        TimeoutError,
        concurrent.futures.TimeoutError,
        _WatchdogTrip,
    )

    def step(self) -> bool:
        """One scheduler window on EVERY live replica, dispatched
        CONCURRENTLY (one thread per engine): each engine's step blocks
        on its own device->host read, so the threads overlap the
        replicas' windows on their disjoint devices — aggregate
        throughput scales with replicas instead of time-multiplexing
        them. Replica failures route through the health state machine
        (watchdog / retry / failover) instead of propagating — in two
        phases: every replica's future SETTLES (completes, raises, or
        times out) before any failure is processed, so failover
        re-queueing never mutates an engine whose own step is still in
        flight (each engine stays single-threaded, and the chaos replay
        contract stays exact). Returns True while any replica has (or
        had) work; raises :class:`ClusterUnavailable` if every replica
        is dead with requests still pending."""
        alive = self._alive()
        if not alive:
            if self._route:
                raise ClusterUnavailable(
                    "every replica is dead with requests pending"
                )
            return False
        progressed = False
        faults: tp.List[tp.Tuple[int, BaseException]] = []
        if self._pool is None:
            try:
                progressed = bool(self.engines[alive[0]].step())
            except self._STEP_FAULTS as exc:
                faults.append((alive[0], exc))
        else:
            futs = [
                (i, self._pool.submit(self.engines[i].step)) for i in alive
            ]
            # ONE deadline for the whole round, from dispatch: the
            # futures run concurrently, so waiting them out in sequence
            # against per-wait timeouts would detect a wedge on the
            # last replica up to N*timeout late
            deadline = (
                None if self.dispatch_timeout_s is None
                else time.monotonic() + self.dispatch_timeout_s
            )
            for i, f in futs:
                try:
                    r = self._settle(
                        f,
                        None if deadline is None
                        else max(0.0, deadline - time.monotonic()),
                    )
                    progressed = r or progressed
                except self._STEP_FAULTS as exc:
                    faults.append((i, exc))
        # a fault is progress: its backlog moved or retried, and a
        # drained cluster never re-steps
        progressed = progressed or bool(faults)
        # mark EVERY terminal fault dead before running ANY failover:
        # two replicas faulting in the same round must not fail over
        # onto each other (a crash's warm drain re-queued onto a
        # watchdog-tripped engine whose step thread is still running
        # would violate the never-mutate-mid-step contract)
        terminal = [
            (i, self._mark_terminal(i, exc))
            for i, exc in faults
            if not isinstance(exc, TransientDispatchError)
        ]
        # retries next (the replica heals or joins the dead set), then
        # the failovers — every target is settled and provably alive
        for i, exc in faults:
            if isinstance(exc, TransientDispatchError):
                self._recover(i)
        for i, cold in terminal:
            self._failover(i, cold=cold)
        self._harvest()
        return progressed

    def run(self, max_windows: int = 100_000) -> tp.Dict[int, Request]:
        """Drive :meth:`step` until every live replica drains; returns
        the finished requests by cluster-global id."""
        for _ in range(max_windows):
            if not self.has_work:
                break
            self.step()
        else:
            raise RuntimeError(
                f"cluster did not drain in {max_windows} windows"
            )
        self._harvest()
        return self.finished

    def stats(self) -> tp.Dict[str, tp.Any]:
        """Summed engine counters (ServingEngine.stats key layout) plus
        ``dp_replicas``, the ``per_replica`` breakdown, and the
        cluster-level failover counters."""
        per = [e.stats() for e in self.engines]
        agg: tp.Dict[str, tp.Any] = {}
        for k in per[0]:
            if k in ("slot_occupancy", "prefix_hit_rate",
                     "tokens_per_dispatch", "spec_acceptance_rate"):
                agg[k] = round(sum(s[k] for s in per) / len(per), 4)
            elif k == "tp":
                agg[k] = per[0][k]
            elif isinstance(per[0][k], dict):
                merged: tp.Dict[str, int] = {}
                for s in per:
                    for kk, vv in s[k].items():
                        merged[kk] = merged.get(kk, 0) + vv
                agg[k] = merged
            else:
                agg[k] = sum(s[k] for s in per)
        agg["dp_replicas"] = len(per)
        agg["watchdog_trips"] = self.watchdog_trips
        agg["retries"] = self.retries
        agg["failovers"] = self.failovers
        agg["requeued_requests"] = self.requeued_requests
        agg["dead_replicas"] = self.health.count("dead")
        agg["replica_health"] = list(self.health)
        agg["replica_health_reason"] = list(self.health_reason)
        agg["per_replica"] = per
        return agg

    @property
    def telemetries(self) -> tp.List[tp.Optional[EngineTelemetry]]:
        """The per-replica telemetry instances (None entries when
        tracing is off) — bench_serving merges their derived request
        metrics and writes one timeline artifact per replica."""
        return [e.telemetry for e in self.engines]

    def metrics_snapshot(self) -> tp.Dict[str, tp.Any]:
        """Cluster-level registry export: the failover counters and
        health state next to every replica's full
        ``ServingEngine.metrics_snapshot()`` — the JSON artifact the r6
        queue stores beside its bench rows. ``stats()`` remains the
        stable façade (telemetry.CLUSTER_STATS_KEYS contract)."""
        return {
            "cluster": {
                "dp_replicas": len(self.engines),
                "watchdog_trips": self.watchdog_trips,
                "retries": self.retries,
                "failovers": self.failovers,
                "requeued_requests": self.requeued_requests,
                "dead_replicas": self.health.count("dead"),
                "replica_health": list(self.health),
                "replica_health_reason": list(self.health_reason),
                "flight_dumps": list(self.flight_dumps),
            },
            "replicas": [e.metrics_snapshot() for e in self.engines],
        }
