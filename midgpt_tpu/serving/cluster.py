"""Shared-nothing data-parallel serving: N independent engine replicas
under one admission scheduler.

Tensor parallelism (``ServingEngine(mesh=...)``) scales a single engine
DOWN the latency axis — the model's weights and KV pool split over the
'tensor' axis of ONE mesh, every dispatch runs collectives. Data
parallelism scales UP the throughput axis, and for serving the right
shape is SHARED-NOTHING: each replica is a complete ``ServingEngine``
owning its own devices (or mesh slice), page pool, prefix cache, and
scheduler state, with no collective ever crossing replicas — a replica
failure or a slow request affects only its own slots, and replicas can
be added/removed without recompiling anything (the Gemma-on-TPU serving
comparison and the pjit scaling study, PAPERS.md, both benchmark exactly
this TPxDP composition).

:class:`ServingCluster` is the scheduler above the replicas:

- **Least-loaded admission**: ``submit`` routes each request to the
  replica with the smallest backlog (queued + active requests;
  deterministic lowest-index tie-break). Because every engine's token
  stream is a function of the request alone (the determinism contract in
  ``serving.engine``), placement NEVER changes a request's tokens — only
  its latency — which the cluster test asserts directly.
- **Per-replica prefix caches**: no cross-replica page sharing (pages
  live in per-replica pools on disjoint devices). A shared-prefix mix
  therefore hits best when co-located; the least-loaded policy is
  deliberately content-blind — smarter affinity routing is a policy
  plug-in point, not an engine change.
- **Aggregated stats**: :meth:`stats` sums the per-engine counters and
  keeps the per-replica breakdown, in the same key layout as
  ``ServingEngine.stats`` (bench_serving emits it unchanged).

This is the seam the async front door (ROADMAP item 5) slots into:
streaming/cancellation/priorities wrap ``submit``/``step`` here without
touching the engines.
"""

from __future__ import annotations

import concurrent.futures
import typing as tp

import numpy as np

from midgpt_tpu.serving.engine import Request, ServingEngine


def serving_meshes(
    tp_size: int = 1,
    dp_replicas: int = 1,
    devices: tp.Optional[tp.Sequence] = None,
) -> tp.List:
    """Disjoint tensor-only meshes for a TPxDP serving deployment: the
    first ``tp_size * dp_replicas`` devices split into ``dp_replicas``
    contiguous groups of ``tp_size`` (contiguous = ICI-adjacent under the
    standard device enumeration, the layout the pjit scaling study uses
    for its TP groups). ``tp_size == 1`` with one replica returns
    ``[None]`` — the engine's single-chip fast path, no mesh machinery at
    all; multi-replica tp=1 gets real 1-device meshes so each replica's
    arrays COMMIT to its own device instead of piling onto device 0."""
    import jax

    from midgpt_tpu.config import MeshConfig
    from midgpt_tpu.parallel.mesh import create_mesh

    assert tp_size >= 1 and dp_replicas >= 1, (tp_size, dp_replicas)
    if tp_size == 1 and dp_replicas == 1:
        return [None]
    devices = list(devices) if devices is not None else jax.devices()
    need = tp_size * dp_replicas
    assert len(devices) >= need, (
        f"tp={tp_size} x dp_replicas={dp_replicas} needs {need} devices, "
        f"have {len(devices)}"
    )
    cfg = MeshConfig(replica=1, fsdp=1, sequence=1, tensor=tp_size)
    return [
        create_mesh(cfg, devices=devices[i * tp_size : (i + 1) * tp_size])
        for i in range(dp_replicas)
    ]


class ServingCluster:
    """N shared-nothing :class:`ServingEngine` replicas + least-loaded
    admission. The cluster's request ids are its own (monotone, globally
    unique); per-replica ids stay internal.

    ``meshes`` pins each replica to its own mesh (``serving_meshes``
    builds the standard TPxDP split); ``replicas=N`` without meshes runs
    N schedulers on the default device — still useful: it is the
    scheduler-correctness configuration the tests drive, and the
    single-host shape the async front door (ROADMAP item 5) will
    multiplex. All other keyword arguments go to every engine verbatim.
    """

    def __init__(
        self,
        model,
        *,
        replicas: tp.Optional[int] = None,
        meshes: tp.Optional[tp.Sequence] = None,
        **engine_kwargs,
    ):
        if meshes is None:
            assert replicas is not None and replicas >= 1, (
                "need replicas=N or an explicit meshes= list"
            )
            meshes = [None] * replicas
        else:
            meshes = list(meshes)
            assert replicas is None or replicas == len(meshes), (
                f"replicas={replicas} contradicts {len(meshes)} meshes"
            )
        assert len(meshes) >= 1
        self.engines: tp.List[ServingEngine] = [
            ServingEngine(model, mesh=m, **engine_kwargs) for m in meshes
        ]
        # global rid -> (replica index, engine-local rid)
        self._route: tp.Dict[int, tp.Tuple[int, int]] = {}
        self._next_rid = 0
        self.finished: tp.Dict[int, Request] = {}
        # one stepping thread per replica: ServingEngine.step blocks on
        # its window's device->host read, and a sequential loop would
        # keep replica B's devices idle while replica A's window
        # computes — time-multiplexing the "parallel" replicas. Engines
        # share no state (that is the design), jax dispatch/blocking
        # reads release the GIL, and each engine only ever runs on ONE
        # thread at a time (submit/step/run are driven from the caller's
        # thread; the pool just fans one step() per engine out).
        self._pool = (
            concurrent.futures.ThreadPoolExecutor(
                max_workers=len(self.engines),
                thread_name_prefix="serving-replica",
            )
            if len(self.engines) > 1
            else None
        )

    @property
    def replicas(self) -> int:
        return len(self.engines)

    def _load(self, e: ServingEngine) -> int:
        """Backlog of one replica: queued + in-flight requests. Counting
        requests (not tokens) keeps admission O(1) and deterministic;
        remaining-token estimates are a policy refinement the seam
        allows."""
        return len(e.queue) + len(e._active_slots())

    def submit(
        self,
        prompt: np.ndarray,
        max_new_tokens: int,
        *,
        eos_id: tp.Optional[int] = None,
        seed: int = 0,
    ) -> int:
        """Admit onto the least-loaded replica (lowest index on ties —
        deterministic, so a test trace routes identically every run);
        returns the cluster-global request id."""
        i = min(
            range(len(self.engines)),
            key=lambda j: (self._load(self.engines[j]), j),
        )
        local = self.engines[i].submit(
            prompt, max_new_tokens, eos_id=eos_id, seed=seed
        )
        rid = self._next_rid
        self._next_rid += 1
        self._route[rid] = (i, local)
        return rid

    def _harvest(self) -> None:
        for rid, (i, local) in list(self._route.items()):
            req = self.engines[i].finished.get(local)
            if req is not None:
                self.finished[rid] = req
                del self._route[rid]

    def step(self) -> bool:
        """One scheduler window on EVERY replica, dispatched
        CONCURRENTLY (one thread per engine): each engine's step blocks
        on its own device->host read, so the threads overlap the
        replicas' windows on their disjoint devices — aggregate
        throughput scales with replicas instead of time-multiplexing
        them. Returns True while any replica has (or had) work."""
        if self._pool is None:
            progressed = self.engines[0].step()
        else:
            progressed = any(
                list(self._pool.map(lambda e: e.step(), self.engines))
            )
        self._harvest()
        return progressed

    def run(self, max_windows: int = 100_000) -> tp.Dict[int, Request]:
        """Drive :meth:`step` until every replica drains; returns the
        finished requests by cluster-global id."""
        for _ in range(max_windows):
            if not any(
                e.queue or e._active_slots() for e in self.engines
            ):
                break
            self.step()
        else:
            raise RuntimeError(
                f"cluster did not drain in {max_windows} windows"
            )
        self._harvest()
        return self.finished

    def stats(self) -> tp.Dict[str, tp.Any]:
        """Summed engine counters (ServingEngine.stats key layout) plus
        ``dp_replicas`` and the ``per_replica`` breakdown."""
        per = [e.stats() for e in self.engines]
        agg: tp.Dict[str, tp.Any] = {}
        for k in per[0]:
            if k in ("slot_occupancy", "prefix_hit_rate",
                     "tokens_per_dispatch", "spec_acceptance_rate"):
                agg[k] = round(sum(s[k] for s in per) / len(per), 4)
            elif k == "tp":
                agg[k] = per[0][k]
            else:
                agg[k] = sum(s[k] for s in per)
        agg["dp_replicas"] = len(per)
        agg["per_replica"] = per
        return agg
