"""Shared-nothing data-parallel serving: N independent engine replicas
under one admission scheduler, with failover.

Tensor parallelism (``ServingEngine(mesh=...)``) scales a single engine
DOWN the latency axis — the model's weights and KV pool split over the
'tensor' axis of ONE mesh, every dispatch runs collectives. Data
parallelism scales UP the throughput axis, and for serving the right
shape is SHARED-NOTHING: each replica is a complete ``ServingEngine``
owning its own devices (or mesh slice), page pool, prefix cache, and
scheduler state, with no collective ever crossing replicas — a replica
failure or a slow request affects only its own slots, and replicas can
be added/removed without recompiling anything (the Gemma-on-TPU serving
comparison and the pjit scaling study, PAPERS.md, both benchmark exactly
this TPxDP composition).

:class:`ServingCluster` is the scheduler above the replicas:

- **Least-loaded admission**: ``submit`` routes each request to the
  healthy replica with the smallest backlog (queued + active requests;
  deterministic lowest-index tie-break). Because every engine's token
  stream is a function of the request alone (the determinism contract in
  ``serving.engine``), placement NEVER changes a request's tokens — only
  its latency — which the cluster test asserts directly.
- **Per-replica health + failover** (serving.faults): every replica is
  ``healthy``, ``suspect``, or ``dead``. A wall-clock dispatch watchdog
  (``dispatch_timeout_s``) catches the wedged-relay case (the r4/r5
  BENCH post-mortems: a dispatch that never returns); a
  ``TransientDispatchError`` is retried on the same replica with capped
  exponential backoff (``max_retries``/``backoff_s``/``backoff_cap_s``,
  suspect while retrying); a ``ReplicaCrash``, a watchdog trip, or
  exhausted retries mark the replica DEAD and its backlog fails over —
  WARM when the replica's step thread provably completed by raising
  (the engine drains exactly: in-flight slots convert through the
  bit-identical eviction path, progress preserved), COLD on a watchdog
  trip (the thread may still be running, so the engine is never
  touched again and its requests re-serve from scratch off the
  cluster's submission record). Failures are processed only after
  every replica's step has settled, so failover never mutates an
  engine mid-step. **Failover replay is bit-identical** either way:
  scripted faults fire at step boundaries (before any dispatch mutates
  state), re-queueing rides the eviction path or the determinism
  contract, and placement invariance makes the surviving stream equal
  to the fault-free run token for token — the chaos suite proves it,
  not just asserts it plausible.
- **Per-replica prefix caches + affinity routing**: no cross-replica
  page sharing (pages live in per-replica pools on disjoint devices),
  so a shared-prefix mix hits best when co-located. ``affinity=True``
  turns admission content-aware: the cluster probes each candidate
  replica's :class:`PrefixIndex` (``match`` is read-only — probing
  perturbs nothing) and routes to the longest resident-prefix overlap,
  bounded by a load-imbalance cap (``affinity_max_imbalance``) so
  affinity can never starve a replica; zero overlap falls back to
  least-loaded. Placement still never changes tokens — only hit rate
  and latency — so every determinism/failover contract is untouched.
- **Disaggregated prefill/decode pools**
  (``prefill_replicas=``/``decode_replicas=``): the first P replicas
  run ``role="prefill"`` engines (chunked prefill to completion, then
  the slot parks handoff-ready), the next D run ``role="decode"``.
  After every scheduler round the cluster PUMPS handoffs: each ready
  slot exports its block-table pages + carried logits row
  (``engine.export_request`` → :class:`HandoffRecord`, host arrays —
  the honest DCN wire model) and imports into the least-loaded decode
  replica (``engine.import_request``), which aliases whatever prefix
  its own index already holds and resumes decoding bit-identically.
  Admission and failover target the prefill pool (decode replicas
  receive work only via handoff), degrading to any alive replica when
  the whole prefill pool is dead. A scripted ``handoff`` fault raises
  :class:`HandoffFailed` at export — the source copy is abandoned and
  the request re-serves COLD from the submission record, same stream.
- **Aggregated stats**: :meth:`stats` sums the per-engine counters and
  keeps the per-replica breakdown, in the same key layout as
  ``ServingEngine.stats`` (bench_serving emits it unchanged), plus the
  cluster-level failover counters (watchdog trips, retries, failovers,
  re-queued requests, replica health).

This is the seam the async front door (serving.frontdoor, ROADMAP
item 3 — shipped) slots into: streaming/cancellation/priorities wrap
``submit``/``step``/``cancel``/``lookup`` here without touching the
engines — and the health/failover layer beneath it is what lets that
front door promise SLOs.
"""

from __future__ import annotations

import concurrent.futures
import os
import sys
import time
import typing as tp

import numpy as np

from midgpt_tpu.serving.engine import HandoffRecord, Request, ServingEngine
from midgpt_tpu.serving.telemetry import EngineTelemetry
from midgpt_tpu.serving.faults import (
    AdmissionRejected,
    ClusterUnavailable,
    FaultPlan,
    HandoffFailed,
    PoolOverloaded,
    ReplicaCrash,
    TransientDispatchError,
    WedgedDispatch,
)


class _WatchdogTrip(Exception):
    """Internal marker: the cluster's wall-clock wait on a replica step
    expired with the step thread STILL RUNNING. Never raised by engine
    code — it exists to distinguish a true watchdog trip (cold,
    engine-abandoning failover) from an organic ``TimeoutError`` raised
    inside step(), which on Python 3.11+ is the same class as
    ``concurrent.futures.TimeoutError`` (thread completed → warm
    failover, like any crash)."""


def serving_meshes(
    tp_size: int = 1,
    dp_replicas: int = 1,
    devices: tp.Optional[tp.Sequence] = None,
) -> tp.List:
    """Disjoint tensor-only meshes for a TPxDP serving deployment: the
    first ``tp_size * dp_replicas`` devices split into ``dp_replicas``
    contiguous groups of ``tp_size`` (contiguous = ICI-adjacent under the
    standard device enumeration, the layout the pjit scaling study uses
    for its TP groups). ``tp_size == 1`` with one replica returns
    ``[None]`` — the engine's single-chip fast path, no mesh machinery at
    all; multi-replica tp=1 gets real 1-device meshes so each replica's
    arrays COMMIT to its own device instead of piling onto device 0."""
    import jax

    from midgpt_tpu.config import MeshConfig
    from midgpt_tpu.parallel.mesh import create_mesh

    assert tp_size >= 1 and dp_replicas >= 1, (tp_size, dp_replicas)
    if tp_size == 1 and dp_replicas == 1:
        return [None]
    devices = list(devices) if devices is not None else jax.devices()
    need = tp_size * dp_replicas
    assert len(devices) >= need, (
        f"tp={tp_size} x dp_replicas={dp_replicas} needs {need} devices, "
        f"have {len(devices)}"
    )
    cfg = MeshConfig(replica=1, fsdp=1, sequence=1, tensor=tp_size)
    return [
        create_mesh(cfg, devices=devices[i * tp_size : (i + 1) * tp_size])
        for i in range(dp_replicas)
    ]


class ServingCluster:
    """N shared-nothing :class:`ServingEngine` replicas + least-loaded
    admission + health-tracked failover. The cluster's request ids are
    its own (monotone, globally unique); per-replica ids stay internal.

    ``meshes`` pins each replica to its own mesh (``serving_meshes``
    builds the standard TPxDP split); ``replicas=N`` without meshes runs
    N schedulers on the default device — still useful: it is the
    scheduler-correctness configuration the tests drive, and the
    single-host shape the async front door (serving.frontdoor)
    multiplexes. All other keyword arguments go to every engine
    verbatim.

    Disaggregation + routing knobs:

    - ``prefill_replicas=P, decode_replicas=D`` — disaggregated pools:
      the first P replicas run ``role="prefill"`` (chunked prefill to
      completion, then the slot parks handoff-ready), the last D run
      ``role="decode"``; the cluster pumps page handoffs between them
      after every scheduler round. Pool split never changes tokens —
      the disagg test matrix proves 1+1 / 2+1 / 2+2 bit-identical to
      the monolithic engine.
    - ``affinity=True`` — prefix-affinity admission: route to the
      replica whose :class:`PrefixIndex` holds the longest resident
      prefix of the prompt, bounded by ``affinity_max_imbalance``
      (max backlog gap vs the least-loaded replica a hit may justify;
      zero overlap falls back to pure least-loaded). Off by default:
      placement order is part of the replay-determinism surface the
      existing tests pin, so content-aware routing is opt-in.

    Fault-tolerance knobs:

    - ``dispatch_timeout_s`` — wall-clock watchdog per replica step;
      ``None`` (default) disables it. A trip marks the replica dead
      (its dispatch may never return — re-using it would double-serve)
      and fails its backlog over.
    - ``max_retries`` / ``backoff_s`` / ``backoff_cap_s`` — capped
      exponential backoff for :class:`TransientDispatchError`
      (``sleep(min(backoff_s * 2**attempt, backoff_cap_s))`` before each
      retry); the replica rides ``suspect`` while retrying and returns
      ``healthy`` on success.
    - ``fault_plan`` — a :class:`~midgpt_tpu.serving.faults.FaultPlan`;
      each replica gets its own scripted hook
      (``plan.hook(replica_index)``), making whole-cluster chaos runs
      replayable bit for bit.
    """

    def __init__(
        self,
        model,
        *,
        replicas: tp.Optional[int] = None,
        meshes: tp.Optional[tp.Sequence] = None,
        prefill_replicas: tp.Optional[int] = None,
        decode_replicas: tp.Optional[int] = None,
        affinity: bool = False,
        affinity_max_imbalance: int = 4,
        fault_plan: tp.Optional[FaultPlan] = None,
        dispatch_timeout_s: tp.Optional[float] = None,
        max_retries: int = 3,
        backoff_s: float = 0.05,
        backoff_cap_s: float = 1.0,
        flight_dir: tp.Optional[str] = None,
        **engine_kwargs,
    ):
        # disaggregated mode: the first P replicas prefill, the next D
        # decode (replica index order = [prefill pool | decode pool],
        # so meshes= pins pools to device groups positionally)
        roles: tp.Optional[tp.List[str]] = None
        if prefill_replicas is not None or decode_replicas is not None:
            assert (
                prefill_replicas is not None and prefill_replicas >= 1
                and decode_replicas is not None and decode_replicas >= 1
            ), (
                "disaggregated mode needs BOTH prefill_replicas>=1 and "
                f"decode_replicas>=1, got {prefill_replicas}+"
                f"{decode_replicas}"
            )
            total = prefill_replicas + decode_replicas
            assert replicas is None or replicas == total, (
                f"replicas={replicas} contradicts "
                f"{prefill_replicas}+{decode_replicas} pools"
            )
            replicas = total
            roles = (
                ["prefill"] * prefill_replicas
                + ["decode"] * decode_replicas
            )
        if meshes is None:
            assert replicas is not None and replicas >= 1, (
                "need replicas=N, prefill_replicas=P + decode_replicas=D, "
                "or an explicit meshes= list"
            )
            meshes = [None] * replicas
        else:
            meshes = list(meshes)
            assert replicas is None or replicas == len(meshes), (
                f"replicas={replicas} contradicts {len(meshes)} meshes"
            )
        assert len(meshes) >= 1
        assert max_retries >= 0 and backoff_s >= 0.0, (
            max_retries, backoff_s,
        )
        # telemetry rides through engine_kwargs: telemetry=True gives
        # every replica its OWN EngineTelemetry (each engine constructs
        # one); a shared instance across replicas would interleave
        # event streams from concurrently-stepping threads, so it is
        # rejected here
        assert not (
            isinstance(engine_kwargs.get("telemetry"), EngineTelemetry)
            and len(meshes) > 1
        ), (
            "pass telemetry=True for a multi-replica cluster — each "
            "replica needs its own EngineTelemetry instance"
        )
        # flight_dir: where dead-replica flight-recorder artifacts land
        # (crash / watchdog trip / exhausted retries — every terminal
        # path dumps; paths collected in self.flight_dumps). None
        # disables the dumps.
        self.flight_dir = flight_dir
        self.flight_dumps: tp.List[str] = []
        self.engines: tp.List[ServingEngine] = []
        for i, m in enumerate(meshes):
            kw = dict(engine_kwargs)
            if roles is not None:
                kw["role"] = roles[i]
            if fault_plan is not None:
                kw["fault_hook"] = fault_plan.hook(i)
            self.engines.append(ServingEngine(model, mesh=m, **kw))
        # pool topology + routing policy
        self.disaggregated = roles is not None
        self.prefill_replicas = int(prefill_replicas or 0)
        self.decode_replicas = int(decode_replicas or 0)
        self._prefill_pool = (
            list(range(self.prefill_replicas)) if self.disaggregated
            else list(range(len(self.engines)))
        )
        self._decode_pool = (
            list(range(self.prefill_replicas, len(self.engines)))
            if self.disaggregated else []
        )
        self.affinity = bool(affinity)
        self.affinity_max_imbalance = int(affinity_max_imbalance)
        assert self.affinity_max_imbalance >= 0
        self.dispatch_timeout_s = dispatch_timeout_s
        self.max_retries = max_retries
        self.backoff_s = backoff_s
        self.backoff_cap_s = backoff_cap_s
        # per-replica health: healthy -> suspect (retrying a transient)
        # -> healthy, or -> dead (crash / watchdog trip / retries
        # exhausted). Dead is terminal: the backlog failed over, and a
        # wedged dispatch may still hold the old engine's buffers.
        self.health: tp.List[str] = ["healthy"] * len(self.engines)
        self.health_reason: tp.List[tp.Optional[str]] = (
            [None] * len(self.engines)
        )
        self.watchdog_trips = 0
        self.retries = 0
        self.failovers = 0
        self.requeued_requests = 0
        # disaggregation + routing counters (CLUSTER_STATS_KEYS)
        self.handoffs = 0
        self.handoff_pages_moved = 0
        self.handoff_bytes = 0
        self.handoff_failures = 0
        self.prefix_affinity_hits = 0
        self.routed_fallback = 0
        self.first_fault_time: tp.Optional[float] = None
        # global rid -> (replica index, engine-local rid)
        self._route: tp.Dict[int, tp.Tuple[int, int]] = {}
        # global rid -> (prompt, max_new_tokens, eos_id, seed, submit
        # time, priority, deadline, routing decision): the cold failover
        # record (dropped at harvest)
        self._submitted: tp.Dict[int, tp.Tuple] = {}
        # global rid -> HandoffRecord: exported off a prefill replica,
        # awaiting a decode-pool slot (the route re-points on import; a
        # record in limbo is self-contained host data, so it survives
        # the death of its source replica)
        self._handoff: tp.Dict[int, HandoffRecord] = {}
        self._next_rid = 0
        self.finished: tp.Dict[int, Request] = {}
        # post-admission terminal outcomes that are not completions
        # (mirrors the per-engine dicts; harvested like finished)
        self.cancelled: tp.Dict[int, Request] = {}
        self.expired: tp.Dict[int, Request] = {}
        # one stepping thread per replica: ServingEngine.step blocks on
        # its window's device->host read, and a sequential loop would
        # keep replica B's devices idle while replica A's window
        # computes — time-multiplexing the "parallel" replicas. Engines
        # share no state (that is the design), jax dispatch/blocking
        # reads release the GIL, and each engine only ever runs on ONE
        # thread at a time (submit/step/run are driven from the caller's
        # thread; the pool just fans one step() per engine out). The
        # watchdog also needs the pool (a timeout requires stepping on a
        # thread the caller can abandon), so a single replica gets one
        # when dispatch_timeout_s is set. Workers are over-provisioned:
        # a wedged step occupies its worker until the stall ends, and
        # retries/failover must still find a free thread meanwhile.
        self._pool = (
            concurrent.futures.ThreadPoolExecutor(
                max_workers=max(4, 2 * len(self.engines)),
                thread_name_prefix="serving-replica",
            )
            if len(self.engines) > 1 or dispatch_timeout_s is not None
            else None
        )

    @property
    def replicas(self) -> int:
        return len(self.engines)

    def _alive(self) -> tp.List[int]:
        return [
            i for i in range(len(self.engines)) if self.health[i] != "dead"
        ]

    @property
    def has_work(self) -> bool:
        """Un-harvested cluster requests remain. Routes outlive replica
        deaths (failover re-points them at survivors) and a pending
        handoff record is a live request between pools, so this is the
        drain condition even mid-failover/mid-handoff."""
        return bool(self._route) or bool(self._handoff) or any(
            self.engines[i].has_work for i in self._alive()
        )

    def _load(self, e: ServingEngine) -> int:
        """Backlog of one replica: queued + parked + in-flight requests.
        Counting requests (not tokens) keeps admission O(1) and
        deterministic; remaining-token estimates are a policy refinement
        the seam allows."""
        return len(e.queue) + len(e.parked) + len(e._active_slots())

    def _least_loaded(self, alive: tp.Sequence[int]) -> int:
        return min(alive, key=lambda j: (self._load(self.engines[j]), j))

    def _submit_targets(self) -> tp.List[int]:
        """Replicas admission (and cold re-serve) may target: the alive
        prefill pool when disaggregated — decode replicas only receive
        work via handoff — degrading to ANY alive replica when the
        whole prefill pool is dead (a decode-class engine is a full
        engine: it can prefill and decode, just off its roofline)."""
        alive = self._alive()
        if not self.disaggregated:
            return alive
        pool = [i for i in self._prefill_pool if self.health[i] != "dead"]
        return pool or alive

    def _affinity_overlap(self, j: int, toks: tp.Sequence[int]) -> int:
        """Longest resident-prefix overlap (in tokens) replica ``j``
        holds for this prompt — the per-replica sketch the affinity
        router reads is the engine's own :class:`PrefixIndex`, probed
        directly: ``match`` is read-only (no LRU mutation), so probing
        every candidate perturbs nothing and needs no shadow state that
        could drift from the pool it describes."""
        idx = self.engines[j].index
        if idx is None or not toks:
            return 0
        return int(idx.match(list(toks))[2])

    def _route_order(
        self,
        cands: tp.Sequence[int],
        prompt: np.ndarray,
        max_new_tokens: int,
    ) -> tp.Tuple[tp.List[int], int]:
        """Candidate replicas in admission-preference order, plus the
        best resident-prefix overlap (0 when affinity is off or
        nothing matched). Affinity picks the longest overlap among
        replicas within ``affinity_max_imbalance`` of the minimum load
        (ties: least loaded, then lowest index) and puts it FIRST —
        the least-loaded order follows as the spillover tail, so a
        full queue on the affinity target degrades exactly like the
        blind policy. The overlap probe crops the prompt exactly like
        ``engine.submit`` will (block - max_new window, last-prompt
        token excluded), so it scores the tokens the engine would
        actually admit against its cache."""
        loads = {j: self._load(self.engines[j]) for j in cands}
        order = sorted(cands, key=lambda j: (loads[j], j))
        if not self.affinity:
            return order, 0
        pp = np.asarray(prompt, np.int32).reshape(-1)
        keep = self.engines[order[0]].block - max_new_tokens
        if 0 < keep < pp.size:
            pp = pp[-keep:]
        toks = [int(t) for t in pp[:-1]] if pp.size > 1 else []
        cap = loads[order[0]] + self.affinity_max_imbalance
        eligible = [j for j in cands if loads[j] <= cap]
        best = max(
            eligible,
            key=lambda j: (self._affinity_overlap(j, toks), -loads[j], -j),
        )
        overlap = self._affinity_overlap(best, toks)
        if overlap > 0:
            order = [best] + [j for j in order if j != best]
        return order, overlap

    def submit(
        self,
        prompt: np.ndarray,
        max_new_tokens: int,
        *,
        eos_id: tp.Optional[int] = None,
        seed: int = 0,
        priority: int = 0,
        deadline_s: tp.Optional[float] = None,
        deadline: tp.Optional[float] = None,
    ) -> int:
        """Admit onto the least-loaded HEALTHY replica (lowest index on
        ties — deterministic, so a test trace routes identically every
        run); with ``affinity=True`` the replica with the longest
        resident-prefix overlap is preferred within the load-imbalance
        cap, and in disaggregated mode only the prefill pool is
        targeted. Returns the cluster-global request id. Raises
        :class:`ClusterUnavailable` when every replica is dead, and
        passes the engine's typed admission outcomes
        (``AdmissionRejected``/``PoolOverloaded``) through to the
        caller — a rejection burns no cluster rid.

        A ``queue_full`` outcome SPILLS OVER: the routing metric (queue
        + parked + active) is not the metric the bound is enforced on
        (queue alone), so the least-loaded replica's full queue must
        not shed a request another healthy replica has room for — the
        remaining replicas are tried in load order and the overload
        outcome raises only when every queue is full. (Per-engine
        ``queue_full`` counters therefore count per-replica admission
        attempts; the request is only actually shed/deferred when the
        LAST replica refuses.) Permanent rejections are identical on
        every replica and re-raise immediately."""
        if not self._alive():
            raise ClusterUnavailable("every replica is dead")
        order, overlap = self._route_order(
            self._submit_targets(), prompt, max_new_tokens
        )
        # the ABSOLUTE deadline is fixed here, at first cluster
        # admission (unless the caller anchored it earlier — e.g. the
        # front door at ARRIVAL time), and rides the submission record:
        # a cold-failover re-serve must keep the ORIGINAL SLO, exactly
        # like it keeps the original submit time (priority rides the
        # same way)
        if deadline is None and deadline_s is not None:
            deadline = self.engines[order[0]].clock() + deadline_s
        local = None
        for n, i in enumerate(order):
            try:
                local = self.engines[i].submit(
                    prompt, max_new_tokens, eos_id=eos_id, seed=seed,
                    priority=priority, deadline=deadline,
                )
                break
            except (AdmissionRejected, PoolOverloaded) as exc:
                if exc.reason != "queue_full" or n == len(order) - 1:
                    raise
        assert local is not None
        # the routing decision is scored at the replica that actually
        # admitted: a queue_full spillover off the affinity target is a
        # fallback even when the probe matched
        routed = "least_loaded"
        if self.affinity:
            if overlap > 0 and n == 0:
                routed = "affinity"
                self.prefix_affinity_hits += 1
                self.engines[i]._emit(
                    "routed_affinity", rid=local, overlap=overlap,
                    replica=i,
                )
            else:
                routed = "fallback"
                self.routed_fallback += 1
                self.engines[i]._emit(
                    "routed_fallback", rid=local, replica=i,
                )
        rid = self._next_rid
        self._next_rid += 1
        self._route[rid] = (i, local)
        # submission record for COLD failover: a watchdog-tripped
        # replica's step thread may still be running, so its engine can
        # never be touched again — surviving requests are then re-served
        # from scratch from this record (same tokens, by the determinism
        # contract; only the already-emitted progress is recomputed).
        # The ORIGINAL submit time rides along so a re-served request's
        # TTFT still measures from first submission — hiding the outage
        # the watchdog just detected would defeat the metric. The
        # routing decision rides too (front door/failover observability).
        self._submitted[rid] = (
            np.asarray(prompt, np.int32).reshape(-1).copy(),
            max_new_tokens, eos_id, seed, self.engines[i].clock(),
            priority, deadline, routed,
        )
        return rid

    def cancel(self, rid: int) -> bool:
        """Cancellation routing: tear the cluster-global request down on
        whichever replica currently serves it (the route survives
        failover, so this follows the request). Idempotent; returns
        True when the request was live. The submission record drops
        with the route — a cancelled request must never be re-served by
        a later cold failover."""
        rec = self._handoff.pop(rid, None)
        if rec is not None:
            # caught between pools: the exported record IS the request
            # now (the source slot already released); dropping it is
            # the cancellation — no engine holds any state to tear down
            rec.req.outcome = "cancelled"
            self.cancelled[rid] = rec.req
            self._submitted.pop(rid, None)
            return True
        route = self._route.get(rid)
        if route is None:
            return False
        i, local = route
        req = self.engines[i].lookup(local)
        if self.health[i] == "dead" or req is None:
            if req is not None and req.outcome != "pending":
                # already terminal on the dead replica: harvest under
                # its REAL outcome instead of relabeling it cancelled
                dest = {
                    "finished": self.finished,
                    "cancelled": self.cancelled,
                    "expired": self.expired,
                }[req.outcome]
                dest[rid] = req
                del self._route[rid]
                self._submitted.pop(rid, None)
                return req.outcome == "cancelled"
            # a cold-abandoned replica's engine is never touched again;
            # the request exists only as the submission record now —
            # dropping route + record IS the cancellation (it was going
            # to be re-served from scratch)
            req = self.engines[i].make_request(
                self._submitted[rid][0], self._submitted[rid][1],
                eos_id=self._submitted[rid][2],
                seed=self._submitted[rid][3],
            )
            req.rid = local
            req.outcome = "cancelled"
            self.cancelled[rid] = req
            del self._route[rid]
            self._submitted.pop(rid, None)
            return True
        ok = self.engines[i].cancel(local)
        if ok:
            self.cancelled[rid] = self.engines[i].cancelled[local]
            del self._route[rid]
            self._submitted.pop(rid, None)
        return ok

    def lookup(self, rid: int) -> tp.Optional[Request]:
        """The live or terminal :class:`Request` for a cluster-global
        id (the front door's harvest seam). After a COLD failover the
        returned object is the survivor's fresh re-serve — its token
        list regrows the same stream from zero (determinism contract),
        which is exactly what the front door's per-stream cursor
        needs."""
        for d in (self.finished, self.cancelled, self.expired):
            req = d.get(rid)
            if req is not None:
                return req
        rec = self._handoff.get(rid)
        if rec is not None:
            return rec.req  # mid-handoff: live, tokens pending
        route = self._route.get(rid)
        if route is None:
            return None
        i, local = route
        if self.health[i] == "dead":
            return None  # between death and failover re-pointing
        return self.engines[i].lookup(local)

    def _harvest(self) -> None:
        for rid, (i, local) in list(self._route.items()):
            e = self.engines[i]
            req = e.finished.get(local)
            dest = self.finished
            if req is None:
                req = e.cancelled.get(local)
                dest = self.cancelled
            if req is None:
                req = e.expired.get(local)
                dest = self.expired
            if req is not None:
                dest[rid] = req
                del self._route[rid]
                self._submitted.pop(rid, None)

    # -- failure handling ---------------------------------------------------

    def _mark_dead(self, i: int, reason: str) -> None:
        self.health[i] = "dead"
        self.health_reason[i] = reason
        if self.first_fault_time is None:
            self.first_fault_time = time.monotonic()
        if self.flight_dir is not None:
            self._flight_dump(i, reason)

    def _flight_dump(self, i: int, reason: str) -> None:
        """Persist replica ``i``'s flight recorder on the one choke
        point every terminal failure crosses (crash, watchdog trip,
        exhausted retries all land in ``_mark_dead``). Best-effort BY
        DESIGN: on a watchdog trip the step thread may still be
        appending to the rings (snapshot-copied under the GIL), and a
        dump failure must never mask the failover it documents — it
        degrades to a stderr line."""
        path = os.path.join(
            self.flight_dir, f"flight_replica{i}_{reason}.json"
        )
        try:
            rec = self.engines[i].flight_dump(
                reason, path=path, extra={"replica": i},
            )
            self.flight_dumps.append(rec["path"])
        except Exception as e:  # noqa: BLE001 — see docstring
            print(
                f"flight-recorder dump for replica {i} ({reason}) "
                f"failed: {e}",
                file=sys.stderr,
            )

    def _failover(self, i: int, cold: bool = False) -> None:
        """Fail dead replica ``i``'s backlog over to the survivors;
        cluster rids keep pointing at the same logical requests — only
        the (replica, local-rid) route changes. Two modes:

        - WARM (default; the replica's step thread provably completed
          by raising): the engine drains — in-flight slots convert
          through the (bit-identical) eviction path, then queue and
          parking lot — and the survivors resume with progress kept.
        - COLD (``cold=True``; a watchdog trip — the step thread may
          still be running inside the runtime): the engine is never
          touched again (draining it would race live slot/page
          mutations). Every request still routed to it re-serves FROM
          SCRATCH off the cluster's submission record — the same stream
          by the determinism contract, with only the un-harvested
          progress recomputed, and the ORIGINAL submit time kept so
          TTFT still shows the outage.

        ``resubmit`` (not ``submit``) either way: already-accepted work
        bypasses the bounded-queue admission control."""
        self._harvest()  # dict reads are GIL-safe; scoop what finished
        self.failovers += 1
        drained = (
            None if cold
            else {r.rid: r for r in self.engines[i].drain_requests()}
        )
        mine = [g for g, (ri, _) in self._route.items() if ri == i]
        n_moved = len(mine) if cold else len(drained)
        self.requeued_requests += n_moved
        if not self._alive():
            if self._route or self._handoff:
                raise ClusterUnavailable(
                    f"replica {i} died ({self.health_reason[i]}) with "
                    f"{n_moved} requests to fail over and no survivors"
                )
            return
        # disaggregated: failed-over work re-enters through the prefill
        # pool (it re-prefills — possibly via cache hits — then hands
        # off again), keeping the pool discipline; a drained request
        # resubmitted anywhere still yields the same stream
        targets = self._submit_targets()
        for grid in mine:
            if cold:
                prompt, n, eos_id, seed, t0, prio, deadline, _routed = (
                    self._submitted[grid]
                )
                j = self._least_loaded(targets)
                req = self.engines[j].make_request(
                    prompt, n, eos_id=eos_id, seed=seed, priority=prio,
                    deadline=deadline,
                )
                req.submit_time = t0
            else:
                req = drained.pop(self._route[grid][1], None)
                if req is None:
                    continue  # finished and harvested above
                j = self._least_loaded(targets)
            self._route[grid] = (j, self.engines[j].resubmit(req))
        assert cold or not drained, (
            f"drained requests {sorted(drained)} had no cluster route"
        )

    # -- the prefill -> decode handoff pump ---------------------------------

    def _requeue_cold(self, grid: int) -> None:
        """Re-serve one cluster request from scratch off its submission
        record, onto the least-loaded submit target (prefill pool when
        disaggregated). Same stream by the determinism contract; the
        ORIGINAL submit time / priority / deadline ride along — this is
        the single-request version of a cold failover, used when a
        handoff export fails."""
        prompt, n, eos_id, seed, t0, prio, deadline, _routed = (
            self._submitted[grid]
        )
        targets = self._submit_targets()
        if not targets:
            raise ClusterUnavailable(
                f"no replica alive to re-serve request {grid}"
            )
        j = self._least_loaded(targets)
        req = self.engines[j].make_request(
            prompt, n, eos_id=eos_id, seed=seed, priority=prio,
            deadline=deadline,
        )
        req.submit_time = t0
        self._route[grid] = (j, self.engines[j].resubmit(req))
        self.requeued_requests += 1

    def _pump_handoffs(self) -> None:
        """Move every handoff-ready slot from the prefill pool to the
        decode pool: export (pages + scale planes + carried logits row
        leave as host arrays — the honest DCN wire model), then import
        into the least-loaded alive decode replica. Runs at the END of
        each scheduler round, after every replica's step has settled —
        the pump is a cluster action on engines that are provably not
        mid-step, the same invariant failover relies on.

        A full decode pool keeps the record pending (retried next
        round; ``has_work`` counts it). A dead decode pool degrades to
        importing into alive prefill replicas — a prefill-role engine
        decodes an IMPORTED slot normally (the role only parks its own
        prefill completions), so the cluster limps instead of
        deadlocking. A scripted export fault (:class:`HandoffFailed`)
        abandons the source copy and re-serves COLD from the
        submission record — bit-identical, chaos-replayed."""
        if not self.disaggregated:
            return
        rev = {route: g for g, route in self._route.items()}
        for i in self._prefill_pool:
            if self.health[i] == "dead":
                continue
            eng = self.engines[i]
            for s in eng.handoff_ready_slots():
                req = eng.slot_req[s]
                grid = rev.get((i, req.rid))
                if grid is None:
                    continue  # not cluster-routed (direct engine use)
                t0 = eng.clock()
                try:
                    rec = eng.export_request(s)
                except HandoffFailed:
                    self.handoff_failures += 1
                    # the export raised BEFORE any state left the slot:
                    # abandon this copy (pages release through the
                    # normal path — no cancel, the request is not
                    # cancelled) and re-serve cold
                    eng._live.pop(req.rid, None)
                    eng._release_slot(s)
                    del self._route[grid]
                    self._requeue_cold(grid)
                    continue
                if eng.telemetry is not None:
                    eng.telemetry.record_dispatch(
                        "handoff", step=eng.fault_step, t=t0,
                        dur=eng.clock() - t0, rids=(req.rid,), tokens=0,
                        pages=rec.n_pages, bytes=rec.nbytes,
                    )
                del self._route[grid]
                self._handoff[grid] = rec
        for grid in list(self._handoff):
            rec = self._handoff[grid]
            targets = [
                j for j in self._decode_pool if self.health[j] != "dead"
            ] or [
                j for j in self._prefill_pool if self.health[j] != "dead"
            ]
            for j in sorted(
                targets, key=lambda j: (self._load(self.engines[j]), j)
            ):
                local = self.engines[j].import_request(rec)
                if local is not None:
                    self._route[grid] = (j, local)
                    del self._handoff[grid]
                    self.handoffs += 1
                    self.handoff_pages_moved += rec.n_pages
                    self.handoff_bytes += rec.nbytes
                    break

    @staticmethod
    def _classify(exc: BaseException) -> tp.Tuple[str, bool]:
        """(death reason, cold failover?) for a terminal step fault. A
        watchdog trip is the ONLY cold case — every other fault is a
        raise out of the step thread, which proves it completed (a
        scripted wedge's stall, in particular, has already ended)."""
        if isinstance(exc, _WatchdogTrip):
            return "wedged", True
        if isinstance(exc, WedgedDispatch):
            return "wedged", False
        return "crashed", False

    def _mark_terminal(self, i: int, exc: BaseException) -> bool:
        """Classify a terminal fault, count it, mark the replica dead;
        returns whether its failover must run COLD. Split from the
        failover itself so step() can mark ALL of a round's faults dead
        before any backlog moves."""
        reason, cold = self._classify(exc)
        if reason == "wedged":
            self.watchdog_trips += 1
        self._mark_dead(i, reason)
        return cold

    def _terminal_failure(self, i: int, exc: BaseException) -> None:
        """The one dead/failover transition: classify, mark dead, fail
        the backlog over."""
        self._failover(i, cold=self._mark_terminal(i, exc))

    @staticmethod
    def _settle(f, timeout: tp.Optional[float]) -> bool:
        """Wait for one replica-step future. Raises :class:`_WatchdogTrip`
        ONLY when the wait expires with the step thread still running —
        on Python 3.11+ ``concurrent.futures.TimeoutError`` IS the
        builtin ``TimeoutError``, so one raised organically INSIDE
        step() (thread completed) must NOT classify as a trip (a trip
        triggers the cold, engine-abandoning failover; a completed
        thread permits the warm drain)."""
        try:
            return bool(f.result(timeout=timeout))
        except concurrent.futures.TimeoutError:
            if not f.done():
                raise _WatchdogTrip() from None
            exc = f.exception()
            if exc is None:
                return bool(f.result())  # completed right at the deadline
            raise exc

    def _step_one(self, i: int, timeout: tp.Optional[float]) -> bool:
        """One replica step, on the pool when there is one (so the wait
        can be abandoned); raises the step's fault, if any."""
        if self._pool is None:
            return bool(self.engines[i].step())
        return self._settle(self._pool.submit(self.engines[i].step), timeout)

    def _recover(self, i: int) -> None:
        """Retry replica ``i`` after a transient failure: capped
        exponential backoff, suspect while retrying, healthy on success,
        dead + failover when the retries exhaust (or the retry hits a
        harder fault). The backoff sleeps run INLINE in the cluster's
        scheduling thread — deliberate: the retry must re-enter the
        replica's step() before the next scheduler round so scripted
        transient sequences stay replayable (``backoff_cap_s`` bounds
        the stall the other replicas see)."""
        self.health[i] = "suspect"
        self.health_reason[i] = "transient"
        for attempt in range(self.max_retries):
            time.sleep(
                min(self.backoff_s * (2 ** attempt), self.backoff_cap_s)
            )
            self.retries += 1
            try:
                self._step_one(i, self.dispatch_timeout_s)
            except TransientDispatchError:
                continue
            except self._STEP_FAULTS as exc:
                self._terminal_failure(i, exc)
                return
            self.health[i] = "healthy"
            self.health_reason[i] = None
            return
        self._mark_dead(i, "transient_exhausted")
        self._failover(i)

    # every fault class a replica step can surface; anything else is a
    # real bug and propagates. concurrent.futures.TimeoutError is listed
    # separately for Python < 3.11, where it is not the builtin
    # TimeoutError (organic timeouts classify as crashes either way —
    # _settle converts genuine wait-expiries to _WatchdogTrip first)
    _STEP_FAULTS = (
        TransientDispatchError,
        WedgedDispatch,
        ReplicaCrash,
        TimeoutError,
        concurrent.futures.TimeoutError,
        _WatchdogTrip,
    )

    def step(self) -> bool:
        """One scheduler window on EVERY live replica, dispatched
        CONCURRENTLY (one thread per engine): each engine's step blocks
        on its own device->host read, so the threads overlap the
        replicas' windows on their disjoint devices — aggregate
        throughput scales with replicas instead of time-multiplexing
        them. Replica failures route through the health state machine
        (watchdog / retry / failover) instead of propagating — in two
        phases: every replica's future SETTLES (completes, raises, or
        times out) before any failure is processed, so failover
        re-queueing never mutates an engine whose own step is still in
        flight (each engine stays single-threaded, and the chaos replay
        contract stays exact). Returns True while any replica has (or
        had) work; raises :class:`ClusterUnavailable` if every replica
        is dead with requests still pending."""
        alive = self._alive()
        if not alive:
            if self._route or self._handoff:
                raise ClusterUnavailable(
                    "every replica is dead with requests pending"
                )
            return False
        progressed = False
        faults: tp.List[tp.Tuple[int, BaseException]] = []
        if self._pool is None:
            try:
                progressed = bool(self.engines[alive[0]].step())
            except self._STEP_FAULTS as exc:
                faults.append((alive[0], exc))
        else:
            futs = [
                (i, self._pool.submit(self.engines[i].step)) for i in alive
            ]
            # ONE deadline for the whole round, from dispatch: the
            # futures run concurrently, so waiting them out in sequence
            # against per-wait timeouts would detect a wedge on the
            # last replica up to N*timeout late
            deadline = (
                None if self.dispatch_timeout_s is None
                else time.monotonic() + self.dispatch_timeout_s
            )
            for i, f in futs:
                try:
                    r = self._settle(
                        f,
                        None if deadline is None
                        else max(0.0, deadline - time.monotonic()),
                    )
                    progressed = r or progressed
                except self._STEP_FAULTS as exc:
                    faults.append((i, exc))
        # a fault is progress: its backlog moved or retried, and a
        # drained cluster never re-steps
        progressed = progressed or bool(faults)
        # mark EVERY terminal fault dead before running ANY failover:
        # two replicas faulting in the same round must not fail over
        # onto each other (a crash's warm drain re-queued onto a
        # watchdog-tripped engine whose step thread is still running
        # would violate the never-mutate-mid-step contract)
        terminal = [
            (i, self._mark_terminal(i, exc))
            for i, exc in faults
            if not isinstance(exc, TransientDispatchError)
        ]
        # retries next (the replica heals or joins the dead set), then
        # the failovers — every target is settled and provably alive
        for i, exc in faults:
            if isinstance(exc, TransientDispatchError):
                self._recover(i)
        for i, cold in terminal:
            self._failover(i, cold=cold)
        # handoffs pump AFTER failures settle: every engine touched is
        # provably not mid-step, and a slot that went handoff-ready
        # this round reaches its decode replica before the next one
        self._pump_handoffs()
        self._harvest()
        return progressed

    def run(self, max_windows: int = 100_000) -> tp.Dict[int, Request]:
        """Drive :meth:`step` until every live replica drains; returns
        the finished requests by cluster-global id."""
        for _ in range(max_windows):
            if not self.has_work:
                break
            self.step()
        else:
            raise RuntimeError(
                f"cluster did not drain in {max_windows} windows"
            )
        self._harvest()
        return self.finished

    def stats(self) -> tp.Dict[str, tp.Any]:
        """Summed engine counters (ServingEngine.stats key layout) plus
        ``dp_replicas``, the ``per_replica`` breakdown, and the
        cluster-level failover counters."""
        per = [e.stats() for e in self.engines]
        agg: tp.Dict[str, tp.Any] = {}
        for k in per[0]:
            if k in ("slot_occupancy", "prefix_hit_rate",
                     "tokens_per_dispatch", "spec_acceptance_rate"):
                agg[k] = round(sum(s[k] for s in per) / len(per), 4)
            elif k == "tp":
                agg[k] = per[0][k]
            elif isinstance(per[0][k], dict):
                merged: tp.Dict[str, int] = {}
                for s in per:
                    for kk, vv in s[k].items():
                        merged[kk] = merged.get(kk, 0) + vv
                agg[k] = merged
            else:
                agg[k] = sum(s[k] for s in per)
        agg["dp_replicas"] = len(per)
        agg["prefill_replicas"] = self.prefill_replicas
        agg["decode_replicas"] = self.decode_replicas
        agg["watchdog_trips"] = self.watchdog_trips
        agg["retries"] = self.retries
        agg["failovers"] = self.failovers
        agg["requeued_requests"] = self.requeued_requests
        agg["handoffs"] = self.handoffs
        agg["handoff_pages_moved"] = self.handoff_pages_moved
        agg["handoff_bytes"] = self.handoff_bytes
        agg["handoff_failures"] = self.handoff_failures
        agg["prefix_affinity_hits"] = self.prefix_affinity_hits
        agg["routed_fallback"] = self.routed_fallback
        agg["dead_replicas"] = self.health.count("dead")
        agg["replica_health"] = list(self.health)
        agg["replica_health_reason"] = list(self.health_reason)
        agg["per_replica"] = per
        return agg

    @property
    def telemetries(self) -> tp.List[tp.Optional[EngineTelemetry]]:
        """The per-replica telemetry instances (None entries when
        tracing is off) — bench_serving merges their derived request
        metrics and writes one timeline artifact per replica."""
        return [e.telemetry for e in self.engines]

    def metrics_snapshot(self) -> tp.Dict[str, tp.Any]:
        """Cluster-level registry export: the failover counters and
        health state next to every replica's full
        ``ServingEngine.metrics_snapshot()`` — the JSON artifact the r6
        queue stores beside its bench rows. ``stats()`` remains the
        stable façade (telemetry.CLUSTER_STATS_KEYS contract)."""
        return {
            "cluster": {
                "dp_replicas": len(self.engines),
                "prefill_replicas": self.prefill_replicas,
                "decode_replicas": self.decode_replicas,
                "watchdog_trips": self.watchdog_trips,
                "retries": self.retries,
                "failovers": self.failovers,
                "requeued_requests": self.requeued_requests,
                "handoffs": self.handoffs,
                "handoff_pages_moved": self.handoff_pages_moved,
                "handoff_bytes": self.handoff_bytes,
                "handoff_failures": self.handoff_failures,
                "prefix_affinity_hits": self.prefix_affinity_hits,
                "routed_fallback": self.routed_fallback,
                "dead_replicas": self.health.count("dead"),
                "replica_health": list(self.health),
                "replica_health_reason": list(self.health_reason),
                "flight_dumps": list(self.flight_dumps),
            },
            "replicas": [e.metrics_snapshot() for e in self.engines],
        }
