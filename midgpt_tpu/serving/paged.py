"""Paged KV-cache pool: fixed page arrays + per-request block tables.

The serving replacement for per-request ring caches (midgpt_tpu.sampling):
one shared pool of fixed-size pages per layer, and each live request owns
an ordered list of page ids (its *block table*). Memory scales with the
tokens actually resident — a request holding 37 tokens at page_size=16
pins 3 pages, not a whole ``[B, Hkv, C, block_size]`` ring — which is what
lets the continuous-batching scheduler keep decode slots full under mixed
prompt/generation lengths (vLLM's PagedAttention / the TPU-native Ragged
Paged Attention formulation, PAPERS.md).

Layout: ``[L, num_pages, Hkv, C, page_size]`` — time is the minor dim
inside a page for the same reason KVCache keeps it minor globally (full
(8, 128) tiles when C = 64; see models.gpt.KVCache). Device-side reads go
through a block-table gather (models.gpt.Attention.decode_paged_at);
device-side writes are bulk scatters at window/prefill boundaries only
(:func:`flush_recent`, :func:`write_prompt_pages`), so the pool stays
read-only inside the fused decode scan. Out-of-range page ids (== the
dedicated ``num_pages`` sentinel) drop their writes — that is how padded
block-table tails and finished/inactive slots pad harmlessly.

The allocator (:class:`PageAllocator`) is host-side and pure-Python: page
accounting is control flow, not math, and it runs once per scheduler
window, never inside jit.
"""

from __future__ import annotations

import typing as tp

import jax
import jax.numpy as jnp

from midgpt_tpu.config import ModelConfig
from midgpt_tpu.pytree import module, static

Array = jax.Array


@module
class PagedKVPool:
    """The shared page pool; leaves carry a leading n_layer axis like the
    scan-stacked block params (and KVCache)."""

    k: Array  # [L, NP, Hkv, C, PS]
    v: Array  # [L, NP, Hkv, C, PS]
    page_size: int = static()

    @staticmethod
    def init(
        cfg: ModelConfig, num_pages: int, page_size: int, dtype=jnp.bfloat16
    ) -> "PagedKVPool":
        assert num_pages >= 1 and page_size >= 1, (num_pages, page_size)
        shape = (cfg.n_layer, num_pages, cfg.kv_heads, cfg.head_dim, page_size)
        return PagedKVPool(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            page_size=page_size,
        )

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]


class PageAllocator:
    """Host-side free-list allocator over pool page ids.

    Invariants (tested): a page is held by at most one owner; ``free +
    held == num_pages`` at all times; double-free and foreign-free raise.
    Allocation is LIFO so a request that frees and re-allocates under
    light load reuses hot pages (better HBM locality than FIFO cycling
    through the whole pool)."""

    def __init__(self, num_pages: int):
        assert num_pages >= 1, num_pages
        self.num_pages = num_pages
        self._free: tp.List[int] = list(range(num_pages - 1, -1, -1))
        self._held: tp.Set[int] = set()

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def held_pages(self) -> int:
        return len(self._held)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> tp.List[int]:
        """Pop ``n`` pages off the free list; raises MemoryError when the
        pool can't satisfy the request (the scheduler's cue to evict)."""
        assert n >= 0, n
        if n > len(self._free):
            raise MemoryError(
                f"page pool exhausted: want {n}, free {len(self._free)} "
                f"of {self.num_pages}"
            )
        pages = [self._free.pop() for _ in range(n)]
        self._held.update(pages)
        return pages

    def free(self, pages: tp.Iterable[int]) -> None:
        for p in pages:
            if p not in self._held:
                raise ValueError(f"freeing page {p} that is not held")
            self._held.remove(p)
            self._free.append(p)

    def check(self) -> None:
        """Assert the structural invariants (tests call this after every
        mutation sequence)."""
        assert len(self._free) + len(self._held) == self.num_pages
        assert len(set(self._free)) == len(self._free), "free-list dup"
        assert not (set(self._free) & self._held), "page both free and held"


def pages_needed(tokens: int, page_size: int) -> int:
    """ceil(tokens / page_size) — pages a request at ``tokens`` resident
    tokens pins."""
    return -(-tokens // page_size)


def flush_recent(
    pool: PagedKVPool,
    rk: Array,  # [L, S, Hkv, K, C] — the window's recent rows (time-major)
    rv: Array,
    bt: Array,  # [S, Pmax] int32 block tables
    start_len: Array,  # [S] int32 — pool-resident tokens at window start
    valid: Array,  # [S, K] bool — row j is a real token for slot s
) -> PagedKVPool:
    """Fold the decode window's recent rows into each slot's pages — one
    bulk scatter per pool array, inside the same compiled window program.

    Row j of slot s holds the K/V of position ``start_len[s] + j`` (valid
    rows form a prefix: the window carries monotone done flags, so a
    finished slot's tail rows are pad). Invalid rows are routed to the
    out-of-range page sentinel and dropped by ``mode="drop"`` — finished
    and empty slots cost nothing and corrupt nothing."""
    l, s, hkv, kk, c = rk.shape
    ps = pool.page_size
    pmax = bt.shape[1]
    np_sentinel = pool.num_pages
    pos = start_len[:, None] + jnp.arange(kk)[None, :]  # [S, K]
    page_idx = jnp.clip(pos // ps, 0, pmax - 1)
    page = jnp.take_along_axis(bt, page_idx, axis=1)  # [S, K]
    page = jnp.where(valid, page, np_sentinel)
    off = pos % ps
    # advanced indices at axes 1 and 4 are non-adjacent, so the broadcast
    # [S*K] index dim moves to the FRONT of the updated slice: vals must
    # arrive [S*K, L, Hkv, C]
    vals_k = jnp.transpose(rk, (1, 3, 0, 2, 4)).reshape(s * kk, l, hkv, c)
    vals_v = jnp.transpose(rv, (1, 3, 0, 2, 4)).reshape(s * kk, l, hkv, c)
    pg, of = page.reshape(-1), off.reshape(-1)
    return PagedKVPool(
        k=pool.k.at[:, pg, :, :, of].set(
            vals_k.astype(pool.k.dtype), mode="drop"
        ),
        v=pool.v.at[:, pg, :, :, of].set(
            vals_v.astype(pool.v.dtype), mode="drop"
        ),
        page_size=ps,
    )


def write_prompt_pages(
    pool: PagedKVPool,
    ks: Array,  # [L, Hkv, P, C] — prompt K from prefill (post-rope)
    vs: Array,  # [L, Hkv, P, C]
    page_rows: Array,  # [P // PS] int32 — target pages (pad = sentinel)
) -> PagedKVPool:
    """Write a prefilled prompt's K/V into its allocated pages — one bulk
    scatter per array, page-granular. P must be a multiple of page_size
    (the engine pads prompts up to the page grid); the pad tail beyond the
    real prompt length lands in the last allocated page as garbage that
    ``pooled_len`` masking never reads, and pages beyond the allocation
    carry the out-of-range sentinel and drop."""
    l, hkv, p, c = ks.shape
    ps = pool.page_size
    assert p % ps == 0, f"prompt length {p} not a multiple of page_size {ps}"
    n = p // ps
    # [L, Hkv, P, C] -> time-minor page blocks [L, n, Hkv, C, PS]
    def to_pages(a):
        a = jnp.transpose(a, (0, 1, 3, 2))  # [L, Hkv, C, P]
        a = a.reshape(l, hkv, c, n, ps)
        return jnp.transpose(a, (0, 3, 1, 2, 4))  # [L, n, Hkv, C, PS]

    return PagedKVPool(
        k=pool.k.at[:, page_rows].set(
            to_pages(ks).astype(pool.k.dtype), mode="drop"
        ),
        v=pool.v.at[:, page_rows].set(
            to_pages(vs).astype(pool.v.dtype), mode="drop"
        ),
        page_size=ps,
    )
