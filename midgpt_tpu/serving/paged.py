"""Paged KV-cache pool: fixed page arrays + per-request block tables.

The serving replacement for per-request ring caches (midgpt_tpu.sampling):
one shared pool of fixed-size pages per layer, and each live request owns
an ordered list of page ids (its *block table*). Memory scales with the
tokens actually resident — a request holding 37 tokens at page_size=16
pins 3 pages, not a whole ``[B, Hkv, C, block_size]`` ring — which is what
lets the continuous-batching scheduler keep decode slots full under mixed
prompt/generation lengths (vLLM's PagedAttention / the TPU-native Ragged
Paged Attention formulation, PAPERS.md).

Layout: ``[L, num_pages, Hkv, C, page_size]`` — time is the minor dim
inside a page for the same reason KVCache keeps it minor globally (full
(8, 128) tiles when C = 64; see models.gpt.KVCache). Device-side reads go
through a block-table gather (models.gpt.Attention.decode_paged_at);
device-side writes are bulk scatters at window/prefill boundaries only
(:func:`flush_recent`, :func:`write_prompt_pages`), so the pool stays
read-only inside the fused decode scan. Out-of-range page ids (== the
dedicated ``num_pages`` sentinel) drop their writes — that is how padded
block-table tails and finished/inactive slots pad harmlessly.

The allocator (:class:`PageAllocator`) is host-side and pure-Python: page
accounting is control flow, not math, and it runs once per scheduler
window, never inside jit.
"""

from __future__ import annotations

import collections
import typing as tp

import jax
import jax.numpy as jnp
import numpy as np

from midgpt_tpu.config import ModelConfig
from midgpt_tpu.parallel.sharding import shard_act
from midgpt_tpu.pytree import module, static
from midgpt_tpu.quant import (
    kv_scale_from_absmax,
    quantize_kv_rows,
    round_kv_rows_to_grid,
)

Array = jax.Array

# mesh layout of the pool arrays [L, NP, Hkv, C, PS] under tensor
# parallelism: WHOLE-KV-HEAD sharding — pages, the in-page time dim and
# head_dim stay intact per shard, so block-table gathers (an index into
# the replicated page dim) and page scatters are shard-local; only the
# head dim splits. Batch/page index arrays (block tables, pooled_len,
# masks) are replicated.
POOL_SPEC_AXES = (None, None, "kv_heads", None, None)
# the per-(page, KV-head) scale planes [L, NP, Hkv] of an int8 pool
# shard with their heads, like the payload
SCALE_SPEC_AXES = (None, None, "kv_heads")


@module
class PagedKVPool:
    """The shared page pool; leaves carry a leading n_layer axis like the
    scan-stacked block params (and KVCache).

    ``kv_quant="int8"`` (init) stores the payload int8 with one f32
    power-of-two scale per (page, KV-head) plane (``scale_k`` /
    ``scale_v`` — K and V quantize independently), halving the KV HBM
    stream serving decode pays every step. Scales are fixed at PAGE
    BIRTH from the page's first row and travel with the page through
    copy-on-write duplication, prefix-cache aliasing and cold
    retirement — a page's payload and its scale are one atomic unit
    (a stale scale on an aliased page is silent corruption; see
    :func:`copy_page`). Exactness contract in midgpt_tpu.quant (the KV
    grid section): dequantization is bitwise, so an int8 pool behaves
    like a bf16 pool whose values lie on the grid."""

    k: Array  # [L, NP, Hkv, C, PS] (pool dtype; int8 when quantized)
    v: Array  # [L, NP, Hkv, C, PS]
    page_size: int = static()
    scale_k: tp.Optional[Array] = None  # [L, NP, Hkv] f32 (int8 pools)
    scale_v: tp.Optional[Array] = None

    @staticmethod
    def init(
        cfg: ModelConfig, num_pages: int, page_size: int, dtype=jnp.bfloat16,
        mesh=None, kv_quant: tp.Optional[str] = None,
    ) -> "PagedKVPool":
        """``mesh`` (a serving TP mesh): commit the pool KV-head-sharded
        over the 'tensor' axis — each shard holds every page of its own
        Hkv/tp heads (POOL_SPEC_AXES), which is what keeps the serving
        programs' block-table gathers collective-free. ``kv_quant="int8"``
        stores the payload int8 with per-(page, KV-head) po2 scale
        planes (sharded with their heads)."""
        assert num_pages >= 1 and page_size >= 1, (num_pages, page_size)
        assert kv_quant in (None, "int8"), f"unknown kv_quant {kv_quant!r}"
        shape = (cfg.n_layer, num_pages, cfg.kv_heads, cfg.head_dim, page_size)
        if kv_quant == "int8":
            dtype = jnp.int8
        k = jnp.zeros(shape, dtype)
        v = jnp.zeros(shape, dtype)
        scale_k = scale_v = None
        if kv_quant == "int8":
            # scale 1.0 on unwritten pages is inert: a page's scale is
            # overwritten by its birth write before pooled_len ever
            # exposes the page to a read
            scale_k = jnp.ones(shape[:3], jnp.float32)
            scale_v = jnp.ones(shape[:3], jnp.float32)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from midgpt_tpu.parallel.sharding import (
                DEFAULT_LOGICAL_RULES,
            )

            def commit(a, axes):
                spec = P(*[
                    DEFAULT_LOGICAL_RULES.get(x) if x is not None else None
                    for x in axes
                ])
                return jax.device_put(a, NamedSharding(mesh, spec))

            k = commit(k, POOL_SPEC_AXES)
            v = commit(v, POOL_SPEC_AXES)
            if scale_k is not None:
                scale_k = commit(scale_k, SCALE_SPEC_AXES)
                scale_v = commit(scale_v, SCALE_SPEC_AXES)
        return PagedKVPool(
            k=k, v=v, page_size=page_size, scale_k=scale_k, scale_v=scale_v
        )

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]

    @property
    def quantized(self) -> bool:
        return self.scale_k is not None

    @property
    def row_dtype(self):
        """The dtype K/V ROWS travel in before they land in pages (the
        decode window's recent buffers, chunk/verify row outputs). For a
        float pool this is the pool dtype; for an int8 pool it is bf16 —
        rows are rounded through the page grid in-dispatch, and grid
        values (|code| <= 127 times a po2 scale) are exact in bf16, so
        nothing is lost between the rounding and the page write."""
        return jnp.bfloat16 if self.quantized else self.k.dtype


class PageAllocator:
    """Host-side refcounting allocator over pool page ids.

    A page is in exactly one of three states:

    - **free** — on the free list, contents meaningless;
    - **held** — refcount >= 1: referenced by one or more live requests
      (prefix sharing is an :meth:`incref`, not a second owner);
    - **cached** — refcount 0 but still resident: a cold prefix-cache
      page whose KV is kept for future hits until page pressure reclaims
      it (:meth:`reclaim`). Never written while cached.

    A fourth state exists only under fault injection
    (serving.faults, the ``exhaust`` event): **quarantined** — taken off
    the free list to simulate allocator exhaustion, returned verbatim by
    :meth:`release_quarantined`. Normal operation never quarantines.

    Invariants (tested): ``free + held + cached + quarantined ==
    num_pages`` (quarantined is 0 outside chaos runs, so the classic
    three-way identity holds there); a refcount is never negative
    (decref of a free/cached page raises); double-free and foreign-free
    raise. Allocation is LIFO so a request that frees and re-allocates
    under light load reuses hot pages (better HBM locality than FIFO
    cycling through the whole pool)."""

    def __init__(self, num_pages: int):
        assert num_pages >= 1, num_pages
        self.num_pages = num_pages
        self._free: tp.List[int] = list(range(num_pages - 1, -1, -1))
        self._ref: tp.Dict[int, int] = {}
        self._cached: tp.Set[int] = set()
        self._quarantined: tp.List[int] = []

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def held_pages(self) -> int:
        return len(self._ref)

    @property
    def cached_pages(self) -> int:
        return len(self._cached)

    @property
    def quarantined_pages(self) -> int:
        return len(self._quarantined)

    def quarantine(self, n: int = -1) -> int:
        """Fault injection (serving.faults ``exhaust``): pull up to ``n``
        FREE pages (-1 = all of them) out of circulation — held and
        cached pages are untouched, so live requests keep their pages
        and the prefix cache keeps serving hits; only new allocation
        feels the pressure. Returns the count actually quarantined."""
        if n < 0:
            n = len(self._free)
        n = min(n, len(self._free))
        for _ in range(n):
            self._quarantined.append(self._free.pop())
        return n

    def release_quarantined(self) -> int:
        """Undo :meth:`quarantine`: every quarantined page returns to
        the free list. Returns the count released."""
        n = len(self._quarantined)
        self._free.extend(self._quarantined)
        self._quarantined.clear()
        return n

    def refcount(self, p: int) -> int:
        return self._ref.get(p, 0)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> tp.List[int]:
        """Pop ``n`` pages off the free list at refcount 1; raises
        MemoryError when the pool can't satisfy the request (the
        scheduler's cue to reclaim cold cache pages, then evict)."""
        assert n >= 0, n
        if n > len(self._free):
            raise MemoryError(
                f"page pool exhausted: want {n}, free {len(self._free)} "
                f"of {self.num_pages}"
            )
        pages = [self._free.pop() for _ in range(n)]
        self._ref.update((p, 1) for p in pages)
        return pages

    def incref(self, p: int) -> None:
        """Share a page: held -> refcount + 1, or revive a cold cached
        page to refcount 1 (a prefix-cache hit)."""
        if p in self._cached:
            self._cached.remove(p)
            self._ref[p] = 1
        elif p in self._ref:
            self._ref[p] += 1
        else:
            raise ValueError(f"incref of free page {p}")

    def decref(self, p: int, cache: bool = False) -> int:
        """Drop one reference; returns the new refcount. At zero the page
        leaves the held set — to the cold cache when ``cache`` (the
        prefix index still maps its contents) else to the free list."""
        if p not in self._ref:
            raise ValueError(f"freeing page {p} that is not held")
        self._ref[p] -= 1
        n = self._ref[p]
        if n == 0:
            del self._ref[p]
            if cache:
                self._cached.add(p)
            else:
                self._free.append(p)
        return n

    def free(self, pages: tp.Iterable[int]) -> None:
        """Decref each page straight to the free list at zero (the
        no-prefix-cache path)."""
        for p in pages:
            self.decref(p, cache=False)

    def reclaim(self, p: int) -> None:
        """Cold cache -> free list (the prefix index evicted ``p``)."""
        if p not in self._cached:
            raise ValueError(f"reclaiming page {p} that is not cached")
        self._cached.remove(p)
        self._free.append(p)

    def check(self) -> None:
        """Assert the structural invariants (tests call this after every
        mutation sequence)."""
        assert (
            len(self._free) + len(self._ref) + len(self._cached)
            + len(self._quarantined)
            == self.num_pages
        )
        assert len(set(self._free)) == len(self._free), "free-list dup"
        held = set(self._ref)
        quarantined = set(self._quarantined)
        assert len(quarantined) == len(self._quarantined), "quarantine dup"
        assert not (set(self._free) & held), "page both free and held"
        assert not (set(self._free) & self._cached), "page both free/cached"
        assert not (held & self._cached), "page both held and cached"
        assert not (
            quarantined & (set(self._free) | held | self._cached)
        ), "quarantined page also free/held/cached"
        assert all(n >= 1 for n in self._ref.values()), "refcount < 1"


class PrefixIndex:
    """Host-side page-granular prefix index: content-addressed lookup of
    resident KV pages by the token prefix they encode.

    A page holding the KV of context positions ``[i*PS, (i+1)*PS)`` is
    keyed by ``(parent_page, chunk)`` where ``chunk`` is that page's PS
    tokens and ``parent_page`` is the indexed page of the preceding chunk
    (-1 at the root) — the chain hash: KV at position j depends on the
    whole prefix 0..j, so two pages are interchangeable iff their entire
    token prefixes match, which the parent link encodes. Only FULL pages
    are indexed (their contents are final: pages are append-only), so an
    indexed page is immutable and safe to alias into any block table.

    Refcounts live in :class:`PageAllocator`; the index only tracks the
    content->page map, the parent/children tree, and an LRU order over
    COLD pages (refcount 0, kept resident by the engine until page
    pressure). Eviction is leaf-first: a page is reclaimable only when no
    indexed child chains through it — ancestors of a held page are held
    (matching shares whole chains from the root), so cold subtrees are
    closed downward and a reclaimable leaf always exists while any cold
    page does."""

    _ROOT = -1

    def __init__(self, page_size: int):
        assert page_size >= 1, page_size
        self.page_size = page_size
        # (parent_page, chunk-tuple) -> page id
        self._by_key: tp.Dict[tp.Tuple[int, tp.Tuple[int, ...]], int] = {}
        # page id -> (parent_page, chunk-tuple)
        self._meta: tp.Dict[int, tp.Tuple[int, tp.Tuple[int, ...]]] = {}
        self._children: tp.Dict[int, tp.Set[int]] = {}
        # cold (refcount-0) pages in LRU order; values unused
        self._lru: "collections.OrderedDict[int, None]" = (
            collections.OrderedDict()
        )
        # spilled nodes (host-RAM resident, no HBM page): VIRTUAL ids
        # <= -2 (the root is -1, real pages are >= 0), in spill order —
        # oldest-first is the discard order under a host budget. A
        # spilled node keeps its (parent, chunk) identity, so match()
        # walks onto and THROUGH it like any resident page and the
        # engine faults it back (import_pages under a fresh id) before
        # use. Spill proceeds deepest-first: a page is spill-eligible
        # once every child is already spilled, so whole cold chains
        # drain to host tail-to-root and spilled SUBTREES are closed
        # downward (every child of a spilled node is spilled — a
        # resident page never chains under a virtual id, because new
        # children only register under a slot's current node, which
        # fault-back keeps resident). check() asserts the closure.
        self._spilled: "collections.OrderedDict[int, None]" = (
            collections.OrderedDict()
        )
        self._next_spill = -2

    def __len__(self) -> int:
        return len(self._meta)

    def __contains__(self, page: int) -> bool:
        return page in self._meta

    @property
    def cold_pages(self) -> int:
        return len(self._lru)

    def lookup(self, parent: int, chunk: tp.Sequence[int]) -> tp.Optional[int]:
        """The indexed page for ``chunk`` under ``parent`` (-1 = root),
        or None."""
        return self._by_key.get((parent, tuple(int(t) for t in chunk)))

    def match(
        self, tokens: tp.Sequence[int]
    ) -> tp.Tuple[tp.List[int], tp.Optional[int], int]:
        """Longest cached prefix of ``tokens``: ``(full_pages, cow_src,
        matched)`` — the chain of fully-matched page ids, an optional
        page whose chunk *extends* the remaining partial tail (the
        copy-on-write candidate), and the total matched token count.
        ``tokens`` should already be capped below the full prompt (the
        engine always recomputes at least the last prompt token, which
        is how the first decode logits are produced)."""
        ps = self.page_size
        toks = [int(t) for t in tokens]
        full: tp.List[int] = []
        parent = self._ROOT
        i = 0
        while i + ps <= len(toks):
            page = self._by_key.get((parent, tuple(toks[i : i + ps])))
            if page is None:
                break
            full.append(page)
            parent = page
            i += ps
        rem = tuple(toks[i:])  # < ps after a full-match walk stops
        cow = None
        if rem:
            for child in self._children.get(parent, ()):
                _, chunk = self._meta[child]
                if chunk[: len(rem)] == rem:
                    cow = child
                    break
        matched = i + (len(rem) if cow is not None else 0)
        return full, cow, matched

    def register(
        self, parent: int, chunk: tp.Sequence[int], page: int
    ) -> int:
        """Index ``page`` as holding ``chunk`` under ``parent``; returns
        the CANONICAL page for that content — ``page`` itself normally,
        or the already-indexed page when another request registered
        identical content first (the duplicate stays private and
        unindexed; callers chain future registrations through the
        canonical id)."""
        key = (parent, tuple(int(t) for t in chunk))
        existing = self._by_key.get(key)
        if existing is not None:
            return existing
        assert page not in self._meta, f"page {page} indexed twice"
        self._by_key[key] = page
        self._meta[page] = key
        self._children.setdefault(parent, set()).add(page)
        return page

    def touch_cold(self, page: int) -> None:
        """Mark an indexed page cold (refcount hit 0) or refresh its LRU
        position."""
        assert page in self._meta, page
        self._lru[page] = None
        self._lru.move_to_end(page)

    def revive(self, page: int) -> None:
        """A cold page got a hit (refcount 0 -> 1): leave the LRU."""
        self._lru.pop(page, None)

    def evict_cold_leaf(self) -> tp.Optional[int]:
        """Drop the least-recently-used cold page that no indexed child
        chains through; returns its id (caller reclaims it in the
        allocator) or None when nothing is reclaimable."""
        for page in self._lru:
            if not self._children.get(page):
                self._drop(page)
                return page
        return None

    # -- host spill (ServingEngine spill="on") ------------------------------

    @property
    def spilled_count(self) -> int:
        return len(self._spilled)

    def is_spilled(self, node: int) -> bool:
        """True for a virtual spilled-node id (match() can return them
        as a suffix of the chain, or as the COW source)."""
        return node in self._spilled

    def coldest_leaf(self) -> tp.Optional[int]:
        """The LRU cold page with no RESIDENT descendants — the next
        spill victim, returned without dropping it (the spill path must
        export the page's contents while the index still maps them).
        Spilled children don't block: chains drain to host
        deepest-first, so a reclaimable victim exists while any cold
        page does."""
        for page in self._lru:
            kids = self._children.get(page)
            if not kids or all(k in self._spilled for k in kids):
                return page
        return None

    def _rekey(self, old: int, new: int) -> None:
        """Move a node between ids, preserving its (parent, chunk)
        identity, its position under the parent, and its CHILDREN's keys
        (a child's content key embeds the parent id, so every child
        re-keys with it)."""
        parent, chunk = self._meta.pop(old)
        self._by_key[(parent, chunk)] = new
        self._meta[new] = (parent, chunk)
        siblings = self._children.get(parent)
        if siblings is not None:
            siblings.discard(old)
            siblings.add(new)
        kids = self._children.pop(old, None)
        if kids:
            self._children[new] = kids
            for c in kids:
                _, cchunk = self._meta[c]
                del self._by_key[(old, cchunk)]
                self._by_key[(new, cchunk)] = c
                self._meta[c] = (new, cchunk)

    def spill(self, page: int) -> int:
        """Re-key a cold page to a fresh virtual spilled-node id: the
        (parent, chunk) identity survives — still matchable — while the
        HBM page id detaches (the caller reclaims it in the allocator
        and stores the exported payload under the returned id). Only
        pages whose children are all already spilled are eligible
        (:meth:`coldest_leaf`), so spilled subtrees stay closed."""
        assert page in self._meta and page in self._lru, page
        kids = self._children.get(page)
        assert not kids or all(k in self._spilled for k in kids), (
            f"spilling page {page} with resident children"
        )
        vid = self._next_spill
        self._next_spill -= 1
        self._rekey(page, vid)
        self._lru.pop(page)
        self._spilled[vid] = None
        return vid

    def unspill(self, vid: int, page: int) -> None:
        """Fault-back re-keying: the spilled node becomes resident page
        ``page`` (freshly allocated, refcount 1 — the caller imported
        the stored payload into it). The inverse of :meth:`spill` up to
        the physical id; any still-spilled children re-key under the
        new page id with it."""
        assert vid in self._spilled, vid
        assert page >= 0 and page not in self._meta, page
        self._rekey(vid, page)
        del self._spilled[vid]

    def discard_spilled_oldest(
        self, protect: tp.Optional[tp.AbstractSet[int]] = None
    ) -> tp.Optional[int]:
        """Forget the oldest CHILDLESS spilled node outright (host
        budget overflow, or a cache clear): returns its virtual id so
        the caller drops the stored payload, or None when nothing is
        discardable. Leaf-first like eviction — dropping a mid-chain
        node would orphan its descendants' keys. True reclaim resumes
        here: the prefix is simply no longer cached anywhere.

        ``protect`` exempts vids from discard: an in-flight fault-back
        reserves pages (which may spill victims past the host budget),
        and budget enforcement must not drop the very chain it is
        materializing — deepest-first spill makes the matched chain's
        childless tail precisely the likely oldest entry."""
        for vid in self._spilled:
            if self._children.get(vid):
                continue
            if protect is not None and vid in protect:
                continue
            parent, chunk = self._meta.pop(vid)
            del self._by_key[(parent, chunk)]
            self._children.get(parent, set()).discard(vid)
            self._children.pop(vid, None)
            del self._spilled[vid]
            return vid
        return None

    def _drop(self, page: int) -> None:
        parent, chunk = self._meta.pop(page)
        del self._by_key[(parent, chunk)]
        self._children.get(parent, set()).discard(page)
        self._children.pop(page, None)
        self._lru.pop(page, None)

    def check(
        self,
        alloc: tp.Optional[PageAllocator] = None,
        spill_store: tp.Optional["HostSpillStore"] = None,
    ) -> None:
        """Structural invariants (property tests call this after every
        scheduler step). With ``spill_store`` the extended spill ledger
        is checked too: every indexed node is EITHER a resident page
        (held or cold-cached in ``alloc`` — the classic
        free+held+cached+quarantined == num_pages identity covers those
        ids) OR a spilled virtual node with exactly one host-store
        payload; the two sets are disjoint and spilled subtrees are
        closed downward (every child of a spilled node is spilled)."""
        assert len(self._by_key) == len(self._meta)
        for page, (parent, chunk) in self._meta.items():
            assert self._by_key[(parent, chunk)] == page
            assert parent == self._ROOT or parent in self._meta, (
                f"page {page} chains through unindexed parent {parent}"
            )
            if parent != self._ROOT:
                assert page in self._children[parent]
        for page in self._lru:
            assert page in self._meta
            assert page >= 0, f"virtual node {page} in the cold LRU"
        for vid in self._spilled:
            assert vid <= -2 and vid in self._meta, vid
            assert all(
                c in self._spilled for c in self._children.get(vid, ())
            ), f"spilled node {vid} has resident children"
        if alloc is not None:
            for page in self._meta:
                if page in self._spilled:
                    continue
                # indexed resident pages: held or cold-cached
                assert page >= 0, f"node {page} neither page nor spilled"
                assert alloc.refcount(page) > 0 or page in alloc._cached
            for page in self._lru:
                assert alloc.refcount(page) == 0, (
                    f"LRU page {page} still referenced"
                )
        if spill_store is not None:
            assert set(self._spilled) == set(spill_store.nodes()), (
                "spill store and index disagree on spilled nodes"
            )


class HostSpillStore:
    """Host-RAM payload store for spilled cold pages (ServingEngine
    ``spill="on"``): one :func:`export_pages` single-page payload —
    ``(k, v, sk, sv)`` numpy arrays, all L layers plus the int8 scale
    planes — per spilled prefix-index node, keyed by the node's virtual
    id (:meth:`PrefixIndex.spill`). Deliberately the same host-array
    wire format as the disaggregated page handoff: the spill-out /
    fault-back round trip is byte-preserving through
    :func:`import_pages`, which is what keeps spilled-then-revived
    streams bitwise identical.

    ``budget_pages`` caps host residency — the engine discards
    oldest-spilled-first past it (true reclaim resumes; the prefix is
    then cached nowhere). None = unbounded (host RAM is the capacity
    the feature buys; a 100k-token prompt's KV at int8 is ~2·L·Hkv·C
    bytes/token, far below typical host memory)."""

    def __init__(self, budget_pages: tp.Optional[int] = None):
        assert budget_pages is None or budget_pages >= 0, budget_pages
        self.budget_pages = budget_pages
        self._store: tp.Dict[int, tp.Tuple] = {}
        self._nbytes = 0

    def __len__(self) -> int:
        return len(self._store)

    def __contains__(self, node: int) -> bool:
        return node in self._store

    def nodes(self) -> tp.Iterable[int]:
        return self._store.keys()

    @staticmethod
    def _payload_nbytes(payload: tp.Tuple) -> int:
        return sum(a.nbytes for a in payload if a is not None)

    def put(self, node: int, payload: tp.Tuple) -> None:
        assert node not in self._store, f"node {node} spilled twice"
        self._store[node] = payload
        self._nbytes += self._payload_nbytes(payload)

    def pop(self, node: int) -> tp.Tuple:
        payload = self._store.pop(node)
        self._nbytes -= self._payload_nbytes(payload)
        return payload

    @property
    def over_budget(self) -> bool:
        return (
            self.budget_pages is not None
            and len(self._store) > self.budget_pages
        )

    @property
    def nbytes(self) -> int:
        """Host bytes resident (payloads + scale planes) — a running
        counter maintained by put/pop, so the per-step telemetry gauge
        stays O(1) instead of walking every payload array of every
        spilled page on each sample."""
        return int(self._nbytes)


def pages_needed(tokens: int, page_size: int) -> int:
    """ceil(tokens / page_size) — pages a request at ``tokens`` resident
    tokens pins."""
    return -(-tokens // page_size)


def kv_row_scales(
    rows_k: Array,  # [S, Hkv, T, C] — contiguous K rows (float dtype)
    rows_v: Array,  # [S, Hkv, T, C]
    base: Array,  # [S] int32 — absolute position of row 0 per slot
    bt: Array,  # [S, Pmax] int32 block tables
    scale_k_l: Array,  # [NP, Hkv] f32 — ONE layer's pool scale planes
    scale_v_l: Array,
    page_size: int,
) -> tp.Tuple[Array, Array]:
    """The per-row page-grid scales for a contiguous run of K/V rows:
    row ``j`` (absolute position ``base + j``) quantizes under the scale
    of its page, which is (a) derived from the page's BIRTH row when
    that row sits inside this very batch (positions fill contiguously,
    so a page entered at ``pos % PS == 0`` was entered by a batch row),
    else (b) the already-recorded pool scale (the page was born by an
    earlier dispatch). Returns ``(sk, sv)`` as ``[S, Hkv, T]`` f32.

    This single lookup rule is what makes int8-KV token streams
    invariant to window size, chunk size, speculation and eviction: a
    page's scale is a pure function of its birth row's values, and
    derivation is ROUNDING-STABLE (quant.py), so deriving from rows
    that were already rounded through their own grid — the state every
    write path sees — reproduces the original scale bit-for-bit."""
    s_, hkv, t, c = rows_k.shape
    ps = page_size
    pmax = bt.shape[1]
    npool = scale_k_l.shape[0]
    pos = base[:, None] + jnp.arange(t, dtype=base.dtype)  # [S, T]
    page_idx = pos // ps
    derived_k = kv_scale_from_absmax(
        jnp.max(jnp.abs(rows_k.astype(jnp.float32)), axis=-1)
    )  # [S, Hkv, T]
    derived_v = kv_scale_from_absmax(
        jnp.max(jnp.abs(rows_v.astype(jnp.float32)), axis=-1)
    )
    # in-batch birth row index of row j's page (negative = pre-batch)
    jb = page_idx * ps - base[:, None]  # [S, T]
    in_batch = (jb >= 0)[:, None, :]  # [S, 1, T]
    jb_idx = jnp.broadcast_to(
        jnp.clip(jb, 0, t - 1)[:, None, :], (s_, hkv, t)
    )
    from_batch_k = jnp.take_along_axis(derived_k, jb_idx, axis=-1)
    from_batch_v = jnp.take_along_axis(derived_v, jb_idx, axis=-1)
    pg = jnp.take_along_axis(bt, jnp.clip(page_idx, 0, pmax - 1), axis=1)
    pg = jnp.clip(pg, 0, npool - 1)  # sentinel pads clip like the gather
    pool_k_s = jnp.transpose(scale_k_l[pg], (0, 2, 1))  # [S, Hkv, T]
    pool_v_s = jnp.transpose(scale_v_l[pg], (0, 2, 1))
    sk = jnp.where(in_batch, from_batch_k, pool_k_s)
    sv = jnp.where(in_batch, from_batch_v, pool_v_s)
    return sk, sv


def _quantize_rows_at_pages(
    rk: Array,  # [L, S, Hkv, T, C] — contiguous rows, slot-batched
    rv: Array,
    scale_k: Array,  # [L, NP, Hkv] f32 — pool scale planes
    scale_v: Array,
    base: Array,  # [S] int32 — absolute position of row 0 per slot
    bt: Array,  # [S, Pmax] int32 block tables
    pos: Array,  # [S, T] int32 — base[:, None] + arange(T)
    valid: Array,  # [S, T] bool — row is a real token
    page_raw: Array,  # [S, T] int32 — row's page (pre sentinel routing)
    sentinel: int,
    ps: int,
) -> tp.Tuple[Array, Array, Array, Array]:
    """The quantized-write core shared by :func:`flush_recent`,
    :func:`write_prompt_pages` and :func:`write_token_rows`: derive each
    row's page-grid scale (``kv_row_scales`` — page-birth rows derive
    their own, rounding-stable, so rows already rounded in-dispatch
    re-derive the identical scale; pages continued from an earlier
    dispatch or a COW copy reuse their recorded pool scale), quantize
    the rows to exact int8 codes, and scatter the scale planes of pages
    BORN by this write atomically with their payload — birth rows
    routed through the same drop sentinel as the payload scatter.
    Returns ``(qk, qv, scale_k, scale_v)``. Single-slot callers pass
    S=1 views. This is THE page-birth scale rule: change it here, not
    in a per-caller copy (a write path quantizing under a divergent
    rule breaks the scheduling-invariance contract)."""
    l, s, hkv, t, c = rk.shape
    sk, sv = jax.vmap(
        lambda a, b, pk, pv: kv_row_scales(a, b, base, bt, pk, pv, ps)
    )(rk, rv, scale_k, scale_v)  # [L, S, Hkv, T]
    qk = quantize_kv_rows(rk, sk)
    qv = quantize_kv_rows(rv, sv)
    birth = jnp.where(
        valid & (pos % ps == 0), page_raw, sentinel
    ).reshape(-1)  # [S*T]
    sk_vals = jnp.transpose(sk, (0, 1, 3, 2)).reshape(l, s * t, hkv)
    sv_vals = jnp.transpose(sv, (0, 1, 3, 2)).reshape(l, s * t, hkv)
    sk_vals = shard_act(sk_vals, None, None, "kv_heads")
    sv_vals = shard_act(sv_vals, None, None, "kv_heads")
    scale_k = shard_act(
        scale_k.at[:, birth].set(sk_vals, mode="drop"), *SCALE_SPEC_AXES
    )
    scale_v = shard_act(
        scale_v.at[:, birth].set(sv_vals, mode="drop"), *SCALE_SPEC_AXES
    )
    return qk, qv, scale_k, scale_v


def flush_recent(
    pool: PagedKVPool,
    rk: Array,  # [L, S, Hkv, K, C] — the window's recent rows (time-major)
    rv: Array,
    bt: Array,  # [S, Pmax] int32 block tables
    start_len: Array,  # [S] int32 — pool-resident tokens at window start
    valid: Array,  # [S, K] bool — row j is a real token for slot s
) -> PagedKVPool:
    """Fold the decode window's recent rows into each slot's pages — one
    bulk scatter per pool array, inside the same compiled window program.

    Row j of slot s holds the K/V of position ``start_len[s] + j`` (valid
    rows form a prefix: the window carries monotone done flags, so a
    finished slot's tail rows are pad). Invalid rows are routed to the
    out-of-range page sentinel and dropped by ``mode="drop"`` — finished
    and empty slots cost nothing and corrupt nothing.

    This valid-prefix mask is also the speculative-decoding WRITE
    WATERMARK (serving.engine.make_verify_program): a verify dispatch
    computes K/V for all ``spec_len + 1`` candidate rows but passes
    ``valid`` rows only up to the accepted count, so a rejected draft's
    K/V is dropped right here — it never reaches a page, the pool's
    resident length (``start_len``) only ever advances over verified
    context, and the prefix index (which registers pages strictly below
    that watermark) can never serve speculative garbage to another
    request."""
    l, s, hkv, kk, c = rk.shape
    ps = pool.page_size
    pmax = bt.shape[1]
    np_sentinel = pool.num_pages
    pos = start_len[:, None] + jnp.arange(kk)[None, :]  # [S, K]
    page_idx = jnp.clip(pos // ps, 0, pmax - 1)
    page_raw = jnp.take_along_axis(bt, page_idx, axis=1)  # [S, K]
    page = jnp.where(valid, page_raw, np_sentinel)
    off = pos % ps
    scale_k, scale_v = pool.scale_k, pool.scale_v
    if pool.quantized:
        rk, rv, scale_k, scale_v = _quantize_rows_at_pages(
            rk, rv, scale_k, scale_v, start_len, bt, pos, valid,
            page_raw, np_sentinel, ps
        )
    # advanced indices at axes 1 and 4 are non-adjacent, so the broadcast
    # [S*K] index dim moves to the FRONT of the updated slice: vals must
    # arrive [S*K, L, Hkv, C]
    vals_k = jnp.transpose(rk, (1, 3, 0, 2, 4)).reshape(s * kk, l, hkv, c)
    vals_v = jnp.transpose(rv, (1, 3, 0, 2, 4)).reshape(s * kk, l, hkv, c)
    # TP: rows scatter per shard into its own heads' pages (the head dim
    # is untouched by the scatter indices); pin values + result so the
    # donated pool's sharding survives the window (no-op without a mesh)
    vals_k = shard_act(vals_k, None, None, "kv_heads", None)
    vals_v = shard_act(vals_v, None, None, "kv_heads", None)
    pg, of = page.reshape(-1), off.reshape(-1)
    return PagedKVPool(
        k=shard_act(pool.k.at[:, pg, :, :, of].set(
            vals_k.astype(pool.k.dtype), mode="drop"
        ), *POOL_SPEC_AXES),
        v=shard_act(pool.v.at[:, pg, :, :, of].set(
            vals_v.astype(pool.v.dtype), mode="drop"
        ), *POOL_SPEC_AXES),
        page_size=ps,
        scale_k=scale_k,
        scale_v=scale_v,
    )


def write_prompt_pages(
    pool: PagedKVPool,
    ks: Array,  # [L, Hkv, P, C] — prompt K from prefill (post-rope)
    vs: Array,  # [L, Hkv, P, C]
    page_rows: Array,  # [P // PS] int32 — target pages (pad = sentinel)
) -> PagedKVPool:
    """Write a prefilled prompt's K/V into its allocated pages — one bulk
    scatter per array, page-granular. P must be a multiple of page_size
    (the engine pads prompts up to the page grid); the pad tail beyond the
    real prompt length lands in the last allocated page as garbage that
    ``pooled_len`` masking never reads, and pages beyond the allocation
    carry the out-of-range sentinel and drop."""
    l, hkv, p, c = ks.shape
    ps = pool.page_size
    assert p % ps == 0, f"prompt length {p} not a multiple of page_size {ps}"
    n = p // ps
    scale_k, scale_v = pool.scale_k, pool.scale_v
    if pool.quantized:
        # page-aligned writes: every written page's birth row is its row
        # 0, and all births are in-batch (base 0); pages beyond the
        # allocation already carry the sentinel in page_rows and drop
        pos = jnp.arange(p, dtype=jnp.int32)
        page_raw = page_rows[pos // ps]
        qk, qv, scale_k, scale_v = _quantize_rows_at_pages(
            ks[:, None], vs[:, None], scale_k, scale_v,
            jnp.zeros((1,), jnp.int32), page_rows[None], pos[None],
            jnp.ones((1, p), bool), page_raw[None], pool.num_pages, ps
        )
        ks, vs = qk[:, 0], qv[:, 0]

    # [L, Hkv, P, C] -> time-minor page blocks [L, n, Hkv, C, PS]
    def to_pages(a):
        a = jnp.transpose(a, (0, 1, 3, 2))  # [L, Hkv, C, P]
        a = a.reshape(l, hkv, c, n, ps)
        return jnp.transpose(a, (0, 3, 1, 2, 4))  # [L, n, Hkv, C, PS]

    return PagedKVPool(
        k=shard_act(pool.k.at[:, page_rows].set(
            to_pages(ks).astype(pool.k.dtype), mode="drop"
        ), *POOL_SPEC_AXES),
        v=shard_act(pool.v.at[:, page_rows].set(
            to_pages(vs).astype(pool.v.dtype), mode="drop"
        ), *POOL_SPEC_AXES),
        page_size=ps,
        scale_k=scale_k,
        scale_v=scale_v,
    )


def write_token_rows(
    pool: PagedKVPool,
    ks: Array,  # [L, Hkv, T, C] — chunk K from a suffix prefill (post-rope)
    vs: Array,  # [L, Hkv, T, C]
    bt_row: Array,  # [Pmax] int32 — the slot's block table (pad = sentinel)
    start: Array,  # [] int32 — absolute position of chunk token 0
    n_valid: Array,  # [] int32 — real tokens in the chunk (rest is pad)
) -> PagedKVPool:
    """Scatter a prefill chunk's K/V rows into the slot's pages at
    positions ``start + j`` — token-granular (chunk boundaries need not
    align to the page grid: a copy-on-write page hands the suffix an
    mid-page start offset). Same non-adjacent-advanced-index layout as
    :func:`flush_recent`; rows ``j >= n_valid`` route to the out-of-range
    sentinel and drop."""
    l, hkv, t, c = ks.shape
    ps = pool.page_size
    pmax = bt_row.shape[0]
    pos = start + jnp.arange(t)  # [T]
    valid = jnp.arange(t) < n_valid
    page_idx = jnp.clip(pos // ps, 0, pmax - 1)
    page_raw = bt_row[page_idx]
    page = jnp.where(valid, page_raw, pool.num_pages)
    off = pos % ps
    scale_k, scale_v = pool.scale_k, pool.scale_v
    if pool.quantized:
        # chunk boundaries need not page-align: a page born mid-chunk
        # derives from its in-batch birth row, a page continued from an
        # earlier chunk (or a COW copy) reuses its recorded pool scale
        qk, qv, scale_k, scale_v = _quantize_rows_at_pages(
            ks[:, None], vs[:, None], scale_k, scale_v,
            start[None].astype(jnp.int32), bt_row[None], pos[None],
            valid[None], page_raw[None], pool.num_pages, ps
        )
        ks, vs = qk[:, 0], qv[:, 0]
    # advanced indices at axes 1 and 4 are non-adjacent: the broadcast
    # [T] index dim moves to the FRONT — vals arrive [T, L, Hkv, C]
    vals_k = shard_act(jnp.transpose(ks, (2, 0, 1, 3)), None, None,
                       "kv_heads", None)
    vals_v = shard_act(jnp.transpose(vs, (2, 0, 1, 3)), None, None,
                       "kv_heads", None)
    return PagedKVPool(
        k=shard_act(pool.k.at[:, page, :, :, off].set(
            vals_k.astype(pool.k.dtype), mode="drop"
        ), *POOL_SPEC_AXES),
        v=shard_act(pool.v.at[:, page, :, :, off].set(
            vals_v.astype(pool.v.dtype), mode="drop"
        ), *POOL_SPEC_AXES),
        page_size=ps,
        scale_k=scale_k,
        scale_v=scale_v,
    )


def copy_page(pool: PagedKVPool, src: Array, dst: Array) -> PagedKVPool:
    """Copy one page's K/V to another page — the copy-on-write primitive:
    a request admitted onto a partially-shared cached page gets a private
    copy it may append into, leaving the shared original untouched. One
    dynamic slice + update per pool array; donate the pool when jitting
    (the engine's compiled wrapper does).

    Int8 pools: the per-(page, KV-head) scale rows copy IN THE SAME
    jitted program as the payload — a page and its scale are one atomic
    unit. Copying only the codes would leave the destination decoding
    the cached prefix's values under a stale scale, and because rounding
    is deterministic, the corruption would be silent and bit-stable
    (tests pin the prefix-cache-hit-under-kv-quant identity). The COW
    destination also inherits the source's scale for the rows the
    admitted request APPENDS into the copied page — correct by the
    page-birth contract: a page's scale is fixed at birth, and the copy
    shares the original's birth row."""
    # no shard_act pins here: the engine jits copy_page OUTSIDE any
    # axis_rules scope (one mesh-free wrapper shared by every engine),
    # where shard_act is a no-op by construction. Sharding under TP
    # rides GSPMD propagation instead, which is airtight for this op:
    # both slice and update index the replicated page dim, so the
    # result carries the committed input pool's sharding — and the
    # donated buffer aliases because nothing reshards.
    k_row = jax.lax.dynamic_slice_in_dim(pool.k, src, 1, axis=1)
    v_row = jax.lax.dynamic_slice_in_dim(pool.v, src, 1, axis=1)
    scale_k, scale_v = pool.scale_k, pool.scale_v
    if pool.quantized:
        sk_row = jax.lax.dynamic_slice_in_dim(scale_k, src, 1, axis=1)
        sv_row = jax.lax.dynamic_slice_in_dim(scale_v, src, 1, axis=1)
        scale_k = jax.lax.dynamic_update_slice_in_dim(
            scale_k, sk_row, dst, axis=1
        )
        scale_v = jax.lax.dynamic_update_slice_in_dim(
            scale_v, sv_row, dst, axis=1
        )
    return PagedKVPool(
        k=jax.lax.dynamic_update_slice_in_dim(pool.k, k_row, dst, axis=1),
        v=jax.lax.dynamic_update_slice_in_dim(pool.v, v_row, dst, axis=1),
        page_size=pool.page_size,
        scale_k=scale_k,
        scale_v=scale_v,
    )


def export_pages(
    pool: PagedKVPool, page_ids: tp.Sequence[int]
) -> tp.Tuple:
    """Pull ``n`` pages' K/V payloads (plus int8 scale planes) out of the
    pool as HOST arrays — the disaggregated cluster's page-handoff wire
    format (serving.cluster): a prefill-class engine exports a finished
    prompt's block-table-addressed pages, and a decode-class engine
    :func:`import_pages` them into ITS pool under freshly allocated ids.

    Returns ``(k, v, sk, sv)`` with ``k``/``v`` shaped
    ``[L, n, Hkv, C, PS]`` in the pool dtype (bf16 survives the numpy
    round-trip via ml_dtypes) and ``sk``/``sv`` the ``[L, n, Hkv]`` f32
    scale planes, or None for float pools. Payload and scales travel
    TOGETHER — a page and its scale are one atomic unit (copy_page's
    contract), and splitting them across the handoff would decode the
    moved prefix under a stale scale on the far side.

    Host round-trip on purpose: replica pools live on disjoint
    devices/meshes, so a device-to-device alias cannot cross them, and
    the numpy hop is the honest model of the DCN wire a multi-host
    deployment pays. Pages are COPIED, not moved — the source engine
    releases its ids through the normal cold-retire path afterwards, so
    its prefix cache keeps serving hits on the exported chain."""
    ids = jnp.asarray(list(page_ids), jnp.int32)
    # take() along the replicated page dim is shard-local under TP —
    # each shard gathers its own heads — and np.asarray gathers the
    # full [L, n, Hkv, C, PS] host copy across shards
    k = np.asarray(jnp.take(pool.k, ids, axis=1))
    v = np.asarray(jnp.take(pool.v, ids, axis=1))
    sk = sv = None
    if pool.quantized:
        sk = np.asarray(jnp.take(pool.scale_k, ids, axis=1))
        sv = np.asarray(jnp.take(pool.scale_v, ids, axis=1))
    return k, v, sk, sv


def import_pages(
    pool: PagedKVPool,
    page_ids: tp.Sequence[int],
    k,
    v,
    sk=None,
    sv=None,
) -> PagedKVPool:
    """Write :func:`export_pages` payloads into ``pool`` at
    ``page_ids`` — the receiving half of the page handoff. Payload and
    scale planes land in one logical update (both or neither), the
    byte-exact inverse of the export: no arithmetic touches the values,
    so the imported pages read back bit-identically to the source pool
    (the disaggregated bit-identity gate rests on this).

    Runs eagerly (a handoff is once per request, not per dispatch);
    under TP the page dim is replicated and the head dim sharded, so
    the scatter is shard-local and GSPMD propagation keeps the pool's
    committed sharding, exactly like :func:`copy_page`."""
    n = len(list(page_ids))
    assert k.shape[1] == n and v.shape[1] == n, (k.shape, n)
    ids = jnp.asarray(list(page_ids), jnp.int32)
    new_k = pool.k.at[:, ids].set(jnp.asarray(k, pool.k.dtype))
    new_v = pool.v.at[:, ids].set(jnp.asarray(v, pool.v.dtype))
    scale_k, scale_v = pool.scale_k, pool.scale_v
    if pool.quantized:
        assert sk is not None and sv is not None, (
            "int8 pool import needs the exported scale planes — payload "
            "and scale are one atomic unit"
        )
        scale_k = scale_k.at[:, ids].set(jnp.asarray(sk, jnp.float32))
        scale_v = scale_v.at[:, ids].set(jnp.asarray(sv, jnp.float32))
    return PagedKVPool(
        k=new_k,
        v=new_v,
        page_size=pool.page_size,
        scale_k=scale_k,
        scale_v=scale_v,
    )
