"""Serving telemetry: per-request lifecycle tracing, a metrics
registry, and a fault flight recorder.

The serving stack through PR 11 is feature-rich and machine-audited but
blind at runtime: ``ServingEngine.stats()`` was a flat counter dict,
per-request latency existed only as bench_serving's aggregate TTFT
percentiles, and the two wedged hardware sessions (r4/r5) produced *no*
timing data at all. This module is the observability substrate — four
pieces, one design constraint:

1. **Per-request lifecycle tracing** (:class:`EngineTelemetry`): typed
   events — ``submit``, ``queued``, ``admitted``, ``prefill_chunk``,
   ``decode_window``, ``verify_dispatch``, ``tokens``, ``evicted``,
   ``parked``, ``resumed``, ``finished``, ``shed``, ``deferred``,
   ``fault`` — keyed to *engine-local scheduler steps* (the FaultPlan
   convention: a chaos replay produces the identical event *sequence*)
   with monotonic wall-clock annotations from the engine's injectable
   ``clock``. Wall-clock lives ONLY in the ``t``/``dur`` fields, never
   in ``data``, so :meth:`EngineTelemetry.sequence_signature` (events
   minus wall-clock) is replay-deterministic and directly comparable
   across runs. Derived per-request metrics
   (:meth:`EngineTelemetry.request_metrics`): queue delay, TTFT,
   per-token TBT, eviction-stall time, tokens-per-dispatch.

2. **A metrics registry** (:class:`MetricsRegistry`): counters, gauges
   (callback-evaluated at snapshot), and fixed-bucket histograms. The
   engine's ad-hoc counter attributes are registry-backed (properties
   over :class:`Counter` objects), so the registry is the single source
   and ``stats()`` is a stable façade over it — the exact key inventory
   is the :data:`ENGINE_STATS_KEYS`/:data:`CLUSTER_STATS_KEYS` contract,
   pinned by test. ``snapshot()`` is JSON-exportable.

3. **A flight recorder**: a bounded ring of recent events plus the last
   N dispatch records, dumped as a structured JSON artifact
   (``ServingEngine.flight_dump``) from the cluster's fault paths
   (replica crash, watchdog trip, exhausted retries — see
   ``ServingCluster(flight_dir=...)``) and from bench_serving's
   whole-trace watchdog — so a wedged hardware run yields a timeline,
   not a bare ``{"status": "watchdog"}`` row.

4. **Timeline export** in Chrome trace-event format
   (:meth:`EngineTelemetry.chrome_trace` — request lanes + dispatch
   lanes, openable in Perfetto / chrome://tracing), plus optional
   ``jax.profiler`` start/stop hooks around a selected scheduler-step
   window (``profile_dir``/``profile_steps``).

**The hard constraint**: tracing must not perturb the dispatch
pipeline. Telemetry is NOT a parameter of any program factory — an
engine with tracing on selects the *identical cached jitted callables*
(asserted by ``analysis.harness.prove_telemetry_inert`` and the
``--telemetry`` audit leg), every emission reads only host-side state
the scheduler already holds (no device access, no new syncs), and
dispatch durations are stamped at the window's *existing* device->host
harvest read. When disabled, each emission site costs one ``is None``
check. Greedy streams with telemetry on are bitwise identical to
telemetry off across the whole feature matrix (tests/test_telemetry.py).

Granularity honesty: the engine emits tokens in window batches (K per
dispatch), so per-token TBT is the gap between consecutive *harvest*
timestamps — within one window the gap is 0, across windows it is the
window's wall time. The percentiles therefore describe the cadence a
streaming client would actually see from this engine, not a smoothed
per-token rate.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import typing as tp

__all__ = [
    "CLUSTER_STATS_KEYS",
    "Counter",
    "DispatchRecord",
    "ENGINE_STATS_KEYS",
    "EngineTelemetry",
    "Event",
    "EVENT_KINDS",
    "Gauge",
    "Histogram",
    "LATENCY_BUCKETS_S",
    "MetricsRegistry",
    "chrome_trace",
    "percentile",
]


# ---------------------------------------------------------------------------
# The stats() façade contract (satellite: pinned by tests/test_telemetry.py)
# ---------------------------------------------------------------------------

#: The exact key inventory of ``ServingEngine.stats()``. bench_serving
#: and the r6 hardware queue read these keys by name; the registry
#: refactor (counters behind properties) must never drop or rename one.
ENGINE_STATS_KEYS: tp.Tuple[str, ...] = (
    "tp",
    "decode_dispatches",
    "prefill_dispatches",
    "copy_dispatches",
    "tokens_generated",
    "windows",
    "slot_occupancy",
    "evictions",
    "free_pages",
    "cached_pages",
    "cold_reclaims",
    "prompt_tokens_total",
    "prefill_tokens_saved",
    "prefill_tokens_computed",
    "prefix_hit_rate",
    "tokens_per_dispatch",
    "verify_dispatches",
    "spec_drafted_tokens",
    "spec_accepted_tokens",
    "spec_acceptance_rate",
    "admission_rejected",
    "reject_reasons",
    "shed_requests",
    "deferred_submits",
    "livelock_parks",
    "overload_parks",
    "parked_requests",
    "cancelled_requests",
    "deadline_shed_requests",
    "faults_injected",
)

#: ``ServingCluster.stats()`` = the summed engine inventory plus these
#: cluster-level keys (aggregation: sums, except the documented means).
CLUSTER_STATS_KEYS: tp.Tuple[str, ...] = ENGINE_STATS_KEYS + (
    "dp_replicas",
    "watchdog_trips",
    "retries",
    "failovers",
    "requeued_requests",
    "dead_replicas",
    "replica_health",
    "replica_health_reason",
    "per_replica",
)


# ---------------------------------------------------------------------------
# Metrics registry
# ---------------------------------------------------------------------------

#: Fixed latency buckets (seconds) shared by every latency histogram:
#: sub-ms through 10 s, roughly x2.5 per step. Fixed (not adaptive) so
#: snapshots from different runs/replicas merge bucket-for-bucket.
LATENCY_BUCKETS_S: tp.Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)


class Counter:
    """A monotone-by-convention integer metric. ``value`` is plainly
    assignable (the bench's warmup reset relies on it)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str, value: int = 0):
        self.name = name
        self.value = value

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """A point-in-time reading: either ``set()`` explicitly or backed by
    a zero-arg callback evaluated at snapshot time (the registry's way
    of exporting live engine state — pool occupancy, queue depth —
    without mirroring writes into the hot path)."""

    __slots__ = ("name", "fn", "value")

    def __init__(self, name: str, fn: tp.Optional[tp.Callable[[], float]] = None):
        self.name = name
        self.fn = fn
        self.value: float = 0.0

    def set(self, v: float) -> None:
        self.value = v

    def read(self) -> float:
        return self.fn() if self.fn is not None else self.value


class Histogram:
    """A fixed-bucket histogram: ``counts[i]`` counts observations
    ``<= bounds[i]``, with one overflow bucket at the end. Bounds are
    immutable after construction so snapshots merge across replicas."""

    __slots__ = ("name", "bounds", "counts", "total", "count")

    def __init__(self, name: str, bounds: tp.Sequence[float] = LATENCY_BUCKETS_S):
        assert list(bounds) == sorted(bounds), "bucket bounds must ascend"
        self.name = name
        self.bounds = tuple(float(b) for b in bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.total = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        i = 0
        for b in self.bounds:
            if v <= b:
                break
            i += 1
        self.counts[i] += 1
        self.total += v
        self.count += 1

    def reset(self) -> None:
        self.counts = [0] * (len(self.bounds) + 1)
        self.total = 0.0
        self.count = 0

    def to_dict(self) -> tp.Dict[str, tp.Any]:
        return {
            "buckets": list(self.bounds),
            "counts": list(self.counts),
            "count": self.count,
            "sum": self.total,
        }


class MetricsRegistry:
    """Counters + gauges + histograms under get-or-create names, with a
    JSON-exportable :meth:`snapshot`. ``attach_labels`` registers a
    labeled counter family *by reference* (e.g. the engine's
    ``reject_reasons`` dict) so the owner keeps mutating its own dict
    and the snapshot sees it live."""

    def __init__(self) -> None:
        self.counters: tp.Dict[str, Counter] = {}
        self.gauges: tp.Dict[str, Gauge] = {}
        self.histograms: tp.Dict[str, Histogram] = {}
        self._labels: tp.Dict[str, tp.Dict[str, int]] = {}

    def counter(self, name: str) -> Counter:
        c = self.counters.get(name)
        if c is None:
            c = self.counters[name] = Counter(name)
        return c

    def gauge(
        self, name: str, fn: tp.Optional[tp.Callable[[], float]] = None
    ) -> Gauge:
        g = self.gauges.get(name)
        if g is None:
            g = self.gauges[name] = Gauge(name, fn)
        elif fn is not None:
            g.fn = fn
        return g

    def histogram(
        self, name: str, bounds: tp.Sequence[float] = LATENCY_BUCKETS_S
    ) -> Histogram:
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = Histogram(name, bounds)
        return h

    def attach_labels(self, name: str, labels: tp.Dict[str, int]) -> None:
        self._labels[name] = labels

    def reset_histograms(self) -> None:
        """Zero every histogram in place (bounds kept) — bench_serving's
        post-warmup reset, next to the counter zeroing."""
        for h in self.histograms.values():
            h.reset()

    def snapshot(self) -> tp.Dict[str, tp.Any]:
        """One JSON-able view of everything: counters by value, gauges
        evaluated now, histograms with bucket arrays, labeled families
        copied. This is the superset ``stats()`` selects its façade
        from."""
        return {
            "counters": {k: c.value for k, c in sorted(self.counters.items())},
            "labeled": {k: dict(v) for k, v in sorted(self._labels.items())},
            "gauges": {k: g.read() for k, g in sorted(self.gauges.items())},
            "histograms": {
                k: h.to_dict() for k, h in sorted(self.histograms.items())
            },
        }


def percentile(sorted_vals: tp.Sequence[float], q: float) -> tp.Optional[float]:
    """Nearest-rank percentile over an ascending list (None when empty)
    — the same convention bench_serving's TTFT percentiles use."""
    if not sorted_vals:
        return None
    return sorted_vals[min(len(sorted_vals) - 1, int(q * len(sorted_vals)))]


# ---------------------------------------------------------------------------
# Events
# ---------------------------------------------------------------------------

#: The lifecycle taxonomy. ``submit`` = accepted by admission control;
#: ``queued`` = entered the wait queue (also fired by failover
#: resubmission); ``admitted`` = took a decode slot; ``prefill_chunk`` /
#: ``decode_window`` / ``verify_dispatch`` = one compiled-program launch
#: (dispatch lanes); ``tokens`` = one slot's harvest from one dispatch;
#: ``evicted``/``parked``/``resumed`` = the preemption/overload paths;
#: ``finished`` = the request completed; ``shed``/``deferred`` =
#: bounded-queue overload outcomes; ``fault`` = a scripted FaultPlan
#: injection firing; ``cancelled`` = the submitter tore the request
#: down (slot reclaimed, pages released — serving.frontdoor);
#: ``deadline_shed`` = the scheduler dropped a queued/parked request
#: whose deadline passed before dispatch (the pre-dispatch SLO shed).
EVENT_KINDS: tp.Tuple[str, ...] = (
    "submit",
    "queued",
    "admitted",
    "prefill_chunk",
    "decode_window",
    "verify_dispatch",
    "tokens",
    "evicted",
    "parked",
    "resumed",
    "finished",
    "shed",
    "deferred",
    "fault",
    "cancelled",
    "deadline_shed",
)


@dataclasses.dataclass
class Event:
    """One lifecycle event. ``step`` is the engine-local scheduler-step
    counter (``engine.fault_step`` — the FaultPlan key space) and ``seq``
    the per-telemetry emission index; both are replay-deterministic.
    ``t`` is the engine clock's monotonic reading and is the ONLY
    wall-clock field — ``data`` carries deterministic values (slots,
    counts, reasons) exclusively, which is what makes
    :meth:`EngineTelemetry.sequence_signature` exact across replays."""

    seq: int
    step: int
    kind: str
    rid: tp.Optional[int]
    t: float
    data: tp.Dict[str, tp.Any] = dataclasses.field(default_factory=dict)

    def signature(self) -> tp.Tuple:
        return (
            self.seq, self.step, self.kind, self.rid,
            tuple(sorted(self.data.items())),
        )

    def to_json(self) -> tp.Dict[str, tp.Any]:
        return {
            "seq": self.seq,
            "step": self.step,
            "kind": self.kind,
            "rid": self.rid,
            "t": self.t,
            **self.data,
        }


@dataclasses.dataclass
class DispatchRecord:
    """One compiled-program launch, as the scheduler saw it: ``t`` is
    the pre-dispatch clock reading and ``dur`` runs to the window's
    existing device->host harvest read (decode/verify) or the program
    call's return (prefill — an enqueue under async dispatch; exact on
    the synchronous CPU test backend). No syncs are added either way."""

    seq: int
    step: int
    kind: str  # decode_window | verify_dispatch | prefill_chunk
    t: float
    dur: float
    rids: tp.Tuple[int, ...]
    tokens: int
    data: tp.Dict[str, tp.Any] = dataclasses.field(default_factory=dict)

    def to_json(self) -> tp.Dict[str, tp.Any]:
        return {
            "seq": self.seq,
            "step": self.step,
            "kind": self.kind,
            "t": self.t,
            "dur": self.dur,
            "rids": list(self.rids),
            "tokens": self.tokens,
            **self.data,
        }


# ---------------------------------------------------------------------------
# EngineTelemetry
# ---------------------------------------------------------------------------


class EngineTelemetry:
    """Per-engine event log + flight-recorder rings.

    Two views of one stream: ``request_log`` keeps every event per
    request id (the timeline / derived-metrics view, bounded per
    request), while ``events`` is the bounded *recency* ring the flight
    recorder dumps (``ring`` events). ``dispatches`` is the companion
    ring of the last ``dispatch_ring`` compiled-program launches.

    ``profile_dir`` + ``profile_steps=(start, stop)`` arm the optional
    ``jax.profiler`` hooks: the engine starts a profiler trace at the
    top of scheduler step ``start`` and stops it at the top of ``stop``
    — a bounded window around exactly the steps under investigation,
    host-driven, with no effect on the compiled programs.
    """

    def __init__(
        self,
        *,
        ring: int = 4096,
        dispatch_ring: int = 512,
        per_request_cap: int = 4096,
        profile_dir: tp.Optional[str] = None,
        profile_steps: tp.Optional[tp.Tuple[int, int]] = None,
    ):
        assert ring >= 1 and dispatch_ring >= 1 and per_request_cap >= 1
        if profile_steps is not None:
            assert profile_dir is not None, "profile_steps needs profile_dir"
            assert profile_steps[0] < profile_steps[1], profile_steps
        self.ring_capacity = ring
        self.dispatch_ring_capacity = dispatch_ring
        self.per_request_cap = per_request_cap
        self.profile_dir = profile_dir
        self.profile_steps = profile_steps
        self._profiling = False
        self.events: tp.Deque[Event] = collections.deque(maxlen=ring)
        self.dispatches: tp.Deque[DispatchRecord] = collections.deque(
            maxlen=dispatch_ring
        )
        self.request_log: tp.Dict[int, tp.List[Event]] = {}
        self._seq = 0

    # -- recording ---------------------------------------------------------

    def emit(
        self,
        kind: str,
        *,
        step: int,
        t: float,
        rid: tp.Optional[int] = None,
        **data,
    ) -> Event:
        assert kind in EVENT_KINDS, kind
        ev = Event(self._seq, step, kind, rid, t, data)
        self._seq += 1
        self.events.append(ev)
        if rid is not None:
            log = self.request_log.setdefault(rid, [])
            if len(log) < self.per_request_cap:
                log.append(ev)
        return ev

    def record_dispatch(
        self,
        kind: str,
        *,
        step: int,
        t: float,
        dur: float,
        rids: tp.Sequence[int],
        tokens: int,
        **data,
    ) -> DispatchRecord:
        rec = DispatchRecord(
            self._seq, step, kind, t, dur, tuple(rids), tokens, data
        )
        # dispatch records share the event seq space so the flight dump
        # interleaves them unambiguously
        self._seq += 1
        self.dispatches.append(rec)
        return rec

    def reset(self) -> None:
        """Drop everything recorded so far (bench_serving calls this
        after warmup, next to re-arming the fault hooks, so the measured
        trace's events start at seq 0 like its fault_steps do)."""
        self.events.clear()
        self.dispatches.clear()
        self.request_log.clear()
        self._seq = 0

    # -- optional jax.profiler window --------------------------------------

    def maybe_profile(self, step: int) -> None:
        """Called by the engine at the top of each scheduler step (only
        when telemetry is attached). Starts/stops a ``jax.profiler``
        trace at the configured step boundaries; no-op without
        ``profile_steps``."""
        if self.profile_steps is None:
            return
        import jax

        start, stop = self.profile_steps
        if not self._profiling and step == start:
            jax.profiler.start_trace(self.profile_dir)
            self._profiling = True
        elif self._profiling and step >= stop:
            self.stop_profiling()

    def stop_profiling(self) -> None:
        """Stop an in-flight ``jax.profiler`` trace (idempotent). The
        engine calls this when it drains, so a workload finishing
        before the configured ``stop`` step still finalizes the trace
        to ``profile_dir`` instead of leaving the profiler armed (a
        dangling trace is unwritten AND makes the next ``start_trace``
        in the process raise). Callers driving ``step()`` manually past
        a drain should call it too."""
        if not self._profiling:
            return
        import jax

        jax.profiler.stop_trace()
        self._profiling = False

    # -- replay determinism -------------------------------------------------

    def sequence_signature(self) -> tp.Tuple[tp.Tuple, ...]:
        """The event stream minus wall-clock: what a chaos replay must
        reproduce exactly (the FaultPlan convention — events are keyed
        to scheduler steps, and every ``data`` field is deterministic
        under the engine's replay contract). Ring-bounded: compare runs
        whose event count fits ``ring``."""
        return tuple(ev.signature() for ev in self.events)

    # -- derived per-request metrics ---------------------------------------

    def token_times(self, rid: int) -> tp.List[float]:
        """Each emitted token's harvest timestamp (a ``tokens`` event
        with ``n`` tokens contributes ``n`` copies of its ``t``)."""
        out: tp.List[float] = []
        for ev in self.request_log.get(rid, ()):
            if ev.kind == "tokens":
                out.extend([ev.t] * ev.data.get("n", 0))
        return out

    def request_metrics(self, rid: int) -> tp.Optional[tp.Dict[str, tp.Any]]:
        """Derived lifecycle metrics for one request (None if the rid
        was never seen): queue delay (submit -> first admission), TTFT
        (submit -> first token), the per-token TBT series (consecutive
        harvest-timestamp gaps — see the module docstring's granularity
        note), eviction-stall time (eviction/park -> re-admission, summed
        over preemptions), tokens, and tokens-per-dispatch (dispatches =
        harvests that included this request)."""
        evs = self.request_log.get(rid)
        if not evs:
            return None
        submit_t: tp.Optional[float] = None
        first_admit_t: tp.Optional[float] = None
        finish_t: tp.Optional[float] = None
        stall = 0.0
        stall_since: tp.Optional[float] = None
        dispatches = 0
        evictions = 0
        for ev in evs:
            if ev.kind in ("submit", "queued") and submit_t is None:
                submit_t = ev.t
            elif ev.kind == "admitted":
                if first_admit_t is None:
                    first_admit_t = ev.t
                if stall_since is not None:
                    stall += ev.t - stall_since
                    stall_since = None
            elif ev.kind in ("evicted", "parked"):
                if ev.kind == "evicted":
                    evictions += 1
                if stall_since is None:
                    stall_since = ev.t
            elif ev.kind == "tokens":
                dispatches += 1
            elif ev.kind == "finished":
                finish_t = ev.t
        tok_ts = self.token_times(rid)
        tbt = [b - a for a, b in zip(tok_ts, tok_ts[1:])]
        return {
            "rid": rid,
            "queue_delay_s": (
                first_admit_t - submit_t
                if submit_t is not None and first_admit_t is not None
                else None
            ),
            "ttft_s": (
                tok_ts[0] - submit_t
                if submit_t is not None and tok_ts
                else None
            ),
            "tbt_s": tbt,
            "eviction_stall_s": stall,
            "evictions": evictions,
            "tokens": len(tok_ts),
            "dispatches": dispatches,
            "tokens_per_dispatch": (
                len(tok_ts) / dispatches if dispatches else None
            ),
            "e2e_s": (
                finish_t - submit_t
                if submit_t is not None and finish_t is not None
                else None
            ),
            "finished": finish_t is not None,
        }

    def finished_request_metrics(self) -> tp.List[tp.Dict[str, tp.Any]]:
        """Derived metrics for every request whose log ends in
        ``finished`` — the population bench_serving's TBT/queue-delay
        percentiles are computed over."""
        out = []
        for rid in self.request_log:
            m = self.request_metrics(rid)
            if m is not None and m["finished"]:
                out.append(m)
        return out

    # -- flight recorder ----------------------------------------------------

    def flight_payload(self) -> tp.Dict[str, tp.Any]:
        """The ring contents as JSON-able structures. Snapshot-copies
        under the GIL, so it is safe to call from another thread
        best-effort (the cluster's cold watchdog path — the wedged step
        thread may still append, and a dump that misses its last event
        beats no dump, which is the r4/r5 lesson this exists for)."""
        return {
            "ring_capacity": self.ring_capacity,
            "events": [ev.to_json() for ev in list(self.events)],
            "dispatches": [d.to_json() for d in list(self.dispatches)],
        }


# ---------------------------------------------------------------------------
# Chrome trace-event export (Perfetto / chrome://tracing)
# ---------------------------------------------------------------------------

_REQ_PID = 1
_DISPATCH_PID = 2
_ENGINE_PID = 3
_SPAN_FOR = {
    # state entered at this event kind -> span name closed by the next
    # lifecycle transition
    "queued": "queued",
    "admitted": "active",
    "evicted": "requeued",
    "parked": "parked",
    "resumed": "queued",
}
_CLOSERS = (
    "queued", "admitted", "evicted", "parked", "resumed", "finished",
    "cancelled", "deadline_shed",  # terminal like finished: close the
    # open span, open nothing (absent from _SPAN_FOR)
)


def _span(name: str, t0: float, t1: float, tid: int, base: float, **args):
    return {
        "name": name,
        "ph": "X",
        "pid": _REQ_PID,
        "tid": tid,
        "ts": (t0 - base) * 1e6,
        "dur": max(0.0, (t1 - t0)) * 1e6,
        "args": args,
    }


def chrome_trace(tele: EngineTelemetry) -> tp.Dict[str, tp.Any]:
    """Export a telemetry log as a Chrome trace-event JSON object
    (``json.dump`` it to a file and open in Perfetto). Layout: one
    process of request lanes (tid = request id; spans for the
    queued/active/requeued/parked phases, instants for tokens and
    faults) and one process of dispatch lanes (one lane per dispatch
    kind, spans from the dispatch ring). Timestamps are microseconds
    relative to the earliest recorded event."""
    events: tp.List[tp.Dict[str, tp.Any]] = []
    all_ts = [ev.t for evs in tele.request_log.values() for ev in evs]
    all_ts += [d.t for d in tele.dispatches]
    all_ts += [ev.t for ev in tele.events if ev.rid is None]
    base = min(all_ts) if all_ts else 0.0

    events.append({
        "ph": "M", "pid": _REQ_PID, "name": "process_name",
        "args": {"name": "requests"},
    })
    events.append({
        "ph": "M", "pid": _DISPATCH_PID, "name": "process_name",
        "args": {"name": "dispatches"},
    })

    for rid, evs in sorted(tele.request_log.items()):
        events.append({
            "ph": "M", "pid": _REQ_PID, "tid": rid, "name": "thread_name",
            "args": {"name": f"request {rid}"},
        })
        open_name: tp.Optional[str] = None
        open_t = 0.0
        last_t = evs[-1].t if evs else 0.0
        for ev in evs:
            if ev.kind in _CLOSERS:
                if open_name is not None:
                    events.append(_span(open_name, open_t, ev.t, rid, base))
                open_name = _SPAN_FOR.get(ev.kind)
                open_t = ev.t
            if ev.kind in ("tokens", "submit", "finished", "cancelled",
                           "deadline_shed"):
                events.append({
                    "name": ev.kind,
                    "ph": "i",
                    "s": "t",
                    "pid": _REQ_PID,
                    "tid": rid,
                    "ts": (ev.t - base) * 1e6,
                    "args": dict(ev.data, step=ev.step),
                })
        if open_name is not None:
            events.append(_span(open_name, open_t, last_t, rid, base))

    # rid-less lifecycle events (shed/deferred at rejection time — no
    # rid ever exists — and scripted fault injections) live only on the
    # recency ring; render them as instants on an engine lane so
    # overload and chaos show up in Perfetto next to the lanes they
    # explain. (Window-summary events are rid-less too but already
    # render as spans on the dispatch lanes — excluded here.)
    ridless = [
        ev for ev in tele.events
        if ev.rid is None and ev.kind in ("shed", "deferred", "fault")
    ]
    if ridless:
        events.append({
            "ph": "M", "pid": _ENGINE_PID, "name": "process_name",
            "args": {"name": "engine"},
        })
        for ev in ridless:
            events.append({
                "name": ev.kind,
                "ph": "i",
                "s": "p",
                "pid": _ENGINE_PID,
                "tid": 0,
                "ts": (ev.t - base) * 1e6,
                "args": dict(ev.data, step=ev.step),
            })

    lanes = {"decode_window": 0, "verify_dispatch": 1, "prefill_chunk": 2}
    for kind, tid in lanes.items():
        events.append({
            "ph": "M", "pid": _DISPATCH_PID, "tid": tid,
            "name": "thread_name", "args": {"name": kind},
        })
    for d in tele.dispatches:
        events.append({
            "name": d.kind,
            "ph": "X",
            "pid": _DISPATCH_PID,
            "tid": lanes.get(d.kind, 3),
            "ts": (d.t - base) * 1e6,
            "dur": max(0.0, d.dur) * 1e6,
            "args": dict(d.data, step=d.step, tokens=d.tokens,
                         rids=list(d.rids)),
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_json(path: str, payload: tp.Dict[str, tp.Any]) -> str:
    """Write a JSON artifact, creating parent directories; returns the
    absolute path (what watchdog rows and flight dumps record
    in-band)."""
    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(payload, f, indent=1)
    return path
