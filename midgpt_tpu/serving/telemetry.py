"""Serving telemetry: per-request lifecycle tracing, a metrics
registry, and a fault flight recorder.

The serving stack through PR 11 is feature-rich and machine-audited but
blind at runtime: ``ServingEngine.stats()`` was a flat counter dict,
per-request latency existed only as bench_serving's aggregate TTFT
percentiles, and the two wedged hardware sessions (r4/r5) produced *no*
timing data at all. This module is the serving half of the observability
substrate — the registry/event-ring/flight-recorder core now lives in
:mod:`midgpt_tpu.telemetry` (shared with the training loop's
:mod:`midgpt_tpu.train_telemetry`) and is re-exported here unchanged.
Four pieces, one design constraint:

1. **Per-request lifecycle tracing** (:class:`EngineTelemetry`): typed
   events — ``submit``, ``queued``, ``admitted``, ``prefill_chunk``,
   ``decode_window``, ``verify_dispatch``, ``tokens``, ``evicted``,
   ``parked``, ``resumed``, ``finished``, ``shed``, ``deferred``,
   ``fault`` — keyed to *engine-local scheduler steps* (the FaultPlan
   convention: a chaos replay produces the identical event *sequence*)
   with monotonic wall-clock annotations from the engine's injectable
   ``clock``. Wall-clock lives ONLY in the ``t``/``dur`` fields, never
   in ``data``, so :meth:`EngineTelemetry.sequence_signature` (events
   minus wall-clock) is replay-deterministic and directly comparable
   across runs. Derived per-request metrics
   (:meth:`EngineTelemetry.request_metrics`): queue delay, TTFT,
   per-token TBT, eviction-stall time, tokens-per-dispatch.

2. **A metrics registry** (:class:`MetricsRegistry`): counters, gauges
   (callback-evaluated at snapshot), and fixed-bucket histograms. The
   engine's ad-hoc counter attributes are registry-backed (properties
   over :class:`Counter` objects), so the registry is the single source
   and ``stats()`` is a stable façade over it — the exact key inventory
   is the :data:`ENGINE_STATS_KEYS`/:data:`CLUSTER_STATS_KEYS` contract,
   pinned by test. ``snapshot()`` is JSON-exportable, and
   :func:`midgpt_tpu.telemetry.prometheus_text` renders it in Prometheus
   text exposition format (``bench_serving --metrics_out``).

3. **A flight recorder**: a bounded ring of recent events plus the last
   N dispatch records, dumped as a structured JSON artifact
   (``ServingEngine.flight_dump``) from the cluster's fault paths
   (replica crash, watchdog trip, exhausted retries — see
   ``ServingCluster(flight_dir=...)``) and from bench_serving's
   whole-trace watchdog — so a wedged hardware run yields a timeline,
   not a bare ``{"status": "watchdog"}`` row.

4. **Timeline export** in Chrome trace-event format
   (:meth:`EngineTelemetry.chrome_trace` — request lanes + dispatch
   lanes, openable in Perfetto / chrome://tracing), plus optional
   ``jax.profiler`` start/stop hooks around a selected scheduler-step
   window (``profile_dir``/``profile_steps``).

**The hard constraint**: tracing must not perturb the dispatch
pipeline. Telemetry is NOT a parameter of any program factory — an
engine with tracing on selects the *identical cached jitted callables*
(asserted by ``analysis.harness.prove_telemetry_inert`` and the
``--telemetry`` audit leg), every emission reads only host-side state
the scheduler already holds (no device access, no new syncs), and
dispatch durations are stamped at the window's *existing* device->host
harvest read. When disabled, each emission site costs one ``is None``
check. Greedy streams with telemetry on are bitwise identical to
telemetry off across the whole feature matrix (tests/test_telemetry.py).

Granularity honesty: the engine emits tokens in window batches (K per
dispatch), so per-token TBT is the gap between consecutive *harvest*
timestamps — within one window the gap is 0, across windows it is the
window's wall time. The percentiles therefore describe the cadence a
streaming client would actually see from this engine, not a smoothed
per-token rate.
"""

from __future__ import annotations

import typing as tp

from midgpt_tpu.telemetry import (  # noqa: F401 — the shared substrate,
    # re-exported so every pre-split import path keeps working
    Counter,
    DispatchRecord,
    Event,
    Gauge,
    Histogram,
    LATENCY_BUCKETS_S,
    MetricsRegistry,
    TelemetryLog,
    percentile,
    prometheus_text,
    write_json,
)

__all__ = [
    "CLUSTER_STATS_KEYS",
    "Counter",
    "DispatchRecord",
    "ENGINE_STATS_KEYS",
    "EngineTelemetry",
    "Event",
    "EVENT_KINDS",
    "Gauge",
    "Histogram",
    "LATENCY_BUCKETS_S",
    "MetricsRegistry",
    "chrome_trace",
    "percentile",
]


# ---------------------------------------------------------------------------
# The stats() façade contract (satellite: pinned by tests/test_telemetry.py)
# ---------------------------------------------------------------------------

#: The exact key inventory of ``ServingEngine.stats()``. bench_serving
#: and the r6 hardware queue read these keys by name; the registry
#: refactor (counters behind properties) must never drop or rename one.
ENGINE_STATS_KEYS: tp.Tuple[str, ...] = (
    "tp",
    "decode_dispatches",
    "prefill_dispatches",
    "copy_dispatches",
    "tokens_generated",
    "windows",
    "slot_occupancy",
    "evictions",
    "free_pages",
    "cached_pages",
    "cold_reclaims",
    "spilled_pages",
    "spill_faultback_pages",
    "spill_prefetch_pages",
    "spill_readmissions",
    "spill_discards",
    "spill_resident_pages",
    "prompt_tokens_total",
    "prefill_tokens_saved",
    "prefill_tokens_computed",
    "prefix_hit_rate",
    "tokens_per_dispatch",
    "verify_dispatches",
    "spec_drafted_tokens",
    "spec_accepted_tokens",
    "spec_acceptance_rate",
    "admission_rejected",
    "reject_reasons",
    "shed_requests",
    "deferred_submits",
    "livelock_parks",
    "overload_parks",
    "parked_requests",
    "cancelled_requests",
    "deadline_shed_requests",
    "faults_injected",
)

#: ``ServingCluster.stats()`` = the summed engine inventory plus these
#: cluster-level keys (aggregation: sums, except the documented means).
CLUSTER_STATS_KEYS: tp.Tuple[str, ...] = ENGINE_STATS_KEYS + (
    "dp_replicas",
    "prefill_replicas",
    "decode_replicas",
    "watchdog_trips",
    "retries",
    "failovers",
    "requeued_requests",
    "handoffs",
    "handoff_pages_moved",
    "handoff_bytes",
    "handoff_failures",
    "prefix_affinity_hits",
    "routed_fallback",
    "dead_replicas",
    "replica_health",
    "replica_health_reason",
    "per_replica",
)


# ---------------------------------------------------------------------------
# Events
# ---------------------------------------------------------------------------

#: The lifecycle taxonomy. ``submit`` = accepted by admission control;
#: ``queued`` = entered the wait queue (also fired by failover
#: resubmission); ``admitted`` = took a decode slot; ``prefill_chunk`` /
#: ``decode_window`` / ``verify_dispatch`` = one compiled-program launch
#: (dispatch lanes); ``tokens`` = one slot's harvest from one dispatch;
#: ``evicted``/``parked``/``resumed`` = the preemption/overload paths;
#: ``finished`` = the request completed; ``shed``/``deferred`` =
#: bounded-queue overload outcomes; ``fault`` = a scripted FaultPlan
#: injection firing; ``cancelled`` = the submitter tore the request
#: down (slot reclaimed, pages released — serving.frontdoor);
#: ``deadline_shed`` = the scheduler dropped a queued/parked request
#: whose deadline passed before dispatch (the pre-dispatch SLO shed);
#: ``handoff`` = a prefill→decode page move (direction="export" on the
#: source engine, "import" on the destination — disaggregated pools);
#: ``routed_affinity`` / ``routed_fallback`` = the cluster's admission
#: decision (prefix-affinity hit vs least-loaded fallback), emitted on
#: the chosen replica's telemetry.
EVENT_KINDS: tp.Tuple[str, ...] = (
    "submit",
    "queued",
    "admitted",
    "prefill_chunk",
    "decode_window",
    "verify_dispatch",
    "tokens",
    "evicted",
    "parked",
    "resumed",
    "finished",
    "shed",
    "deferred",
    "fault",
    "cancelled",
    "deadline_shed",
    "handoff",
    "routed_affinity",
    "routed_fallback",
)


# ---------------------------------------------------------------------------
# EngineTelemetry
# ---------------------------------------------------------------------------


class EngineTelemetry(TelemetryLog):
    """Per-engine event log + flight-recorder rings (the serving
    specialization of :class:`midgpt_tpu.telemetry.TelemetryLog`:
    the serving lifecycle taxonomy plus derived per-request metrics).

    Two views of one stream: ``request_log`` keeps every event per
    request id (the timeline / derived-metrics view, bounded per
    request), while ``events`` is the bounded *recency* ring the flight
    recorder dumps (``ring`` events). ``dispatches`` is the companion
    ring of the last ``dispatch_ring`` compiled-program launches.

    ``profile_dir`` + ``profile_steps=(start, stop)`` arm the optional
    ``jax.profiler`` hooks: the engine starts a profiler trace at the
    top of scheduler step ``start`` and stops it at the top of ``stop``
    — a bounded window around exactly the steps under investigation,
    host-driven, with no effect on the compiled programs.
    """

    event_kinds = EVENT_KINDS

    # -- derived per-request metrics ---------------------------------------

    def token_times(self, rid: int) -> tp.List[float]:
        """Each emitted token's harvest timestamp (a ``tokens`` event
        with ``n`` tokens contributes ``n`` copies of its ``t``)."""
        out: tp.List[float] = []
        for ev in self.request_log.get(rid, ()):
            if ev.kind == "tokens":
                out.extend([ev.t] * ev.data.get("n", 0))
        return out

    def request_metrics(self, rid: int) -> tp.Optional[tp.Dict[str, tp.Any]]:
        """Derived lifecycle metrics for one request (None if the rid
        was never seen): queue delay (submit -> first admission), TTFT
        (submit -> first token), the per-token TBT series (consecutive
        harvest-timestamp gaps — see the module docstring's granularity
        note), eviction-stall time (eviction/park -> re-admission, summed
        over preemptions), tokens, and tokens-per-dispatch (dispatches =
        harvests that included this request)."""
        evs = self.request_log.get(rid)
        if not evs:
            return None
        submit_t: tp.Optional[float] = None
        first_admit_t: tp.Optional[float] = None
        finish_t: tp.Optional[float] = None
        stall = 0.0
        stall_since: tp.Optional[float] = None
        dispatches = 0
        evictions = 0
        for ev in evs:
            if ev.kind in ("submit", "queued") and submit_t is None:
                submit_t = ev.t
            elif ev.kind == "admitted":
                if first_admit_t is None:
                    first_admit_t = ev.t
                if stall_since is not None:
                    stall += ev.t - stall_since
                    stall_since = None
            elif ev.kind in ("evicted", "parked"):
                if ev.kind == "evicted":
                    evictions += 1
                if stall_since is None:
                    stall_since = ev.t
            elif ev.kind == "tokens":
                dispatches += 1
            elif ev.kind == "finished":
                finish_t = ev.t
        tok_ts = self.token_times(rid)
        tbt = [b - a for a, b in zip(tok_ts, tok_ts[1:])]
        return {
            "rid": rid,
            "queue_delay_s": (
                first_admit_t - submit_t
                if submit_t is not None and first_admit_t is not None
                else None
            ),
            "ttft_s": (
                tok_ts[0] - submit_t
                if submit_t is not None and tok_ts
                else None
            ),
            "tbt_s": tbt,
            "eviction_stall_s": stall,
            "evictions": evictions,
            "tokens": len(tok_ts),
            "dispatches": dispatches,
            "tokens_per_dispatch": (
                len(tok_ts) / dispatches if dispatches else None
            ),
            "e2e_s": (
                finish_t - submit_t
                if submit_t is not None and finish_t is not None
                else None
            ),
            "finished": finish_t is not None,
        }

    def finished_request_metrics(self) -> tp.List[tp.Dict[str, tp.Any]]:
        """Derived metrics for every request whose log ends in
        ``finished`` — the population bench_serving's TBT/queue-delay
        percentiles are computed over."""
        out = []
        for rid in self.request_log:
            m = self.request_metrics(rid)
            if m is not None and m["finished"]:
                out.append(m)
        return out


# ---------------------------------------------------------------------------
# Chrome trace-event export (Perfetto / chrome://tracing)
# ---------------------------------------------------------------------------

_REQ_PID = 1
_DISPATCH_PID = 2
_ENGINE_PID = 3
_SPAN_FOR = {
    # state entered at this event kind -> span name closed by the next
    # lifecycle transition
    "queued": "queued",
    "admitted": "active",
    "evicted": "requeued",
    "parked": "parked",
    "resumed": "queued",
}
_CLOSERS = (
    "queued", "admitted", "evicted", "parked", "resumed", "finished",
    "cancelled", "deadline_shed",  # terminal like finished: close the
    # open span, open nothing (absent from _SPAN_FOR)
)


def _span(name: str, t0: float, t1: float, tid: int, base: float, **args):
    return {
        "name": name,
        "ph": "X",
        "pid": _REQ_PID,
        "tid": tid,
        "ts": (t0 - base) * 1e6,
        "dur": max(0.0, (t1 - t0)) * 1e6,
        "args": args,
    }


def chrome_trace(tele: EngineTelemetry) -> tp.Dict[str, tp.Any]:
    """Export a telemetry log as a Chrome trace-event JSON object
    (``json.dump`` it to a file and open in Perfetto). Layout: one
    process of request lanes (tid = request id; spans for the
    queued/active/requeued/parked phases, instants for tokens and
    faults) and one process of dispatch lanes (one lane per dispatch
    kind, spans from the dispatch ring). Timestamps are microseconds
    relative to the earliest recorded event."""
    events: tp.List[tp.Dict[str, tp.Any]] = []
    all_ts = [ev.t for evs in tele.request_log.values() for ev in evs]
    all_ts += [d.t for d in tele.dispatches]
    all_ts += [ev.t for ev in tele.events if ev.rid is None]
    base = min(all_ts) if all_ts else 0.0

    events.append({
        "ph": "M", "pid": _REQ_PID, "name": "process_name",
        "args": {"name": "requests"},
    })
    events.append({
        "ph": "M", "pid": _DISPATCH_PID, "name": "process_name",
        "args": {"name": "dispatches"},
    })

    for rid, evs in sorted(tele.request_log.items()):
        events.append({
            "ph": "M", "pid": _REQ_PID, "tid": rid, "name": "thread_name",
            "args": {"name": f"request {rid}"},
        })
        open_name: tp.Optional[str] = None
        open_t = 0.0
        last_t = evs[-1].t if evs else 0.0
        for ev in evs:
            if ev.kind in _CLOSERS:
                if open_name is not None:
                    events.append(_span(open_name, open_t, ev.t, rid, base))
                open_name = _SPAN_FOR.get(ev.kind)
                open_t = ev.t
            if ev.kind in ("tokens", "submit", "finished", "cancelled",
                           "deadline_shed"):
                events.append({
                    "name": ev.kind,
                    "ph": "i",
                    "s": "t",
                    "pid": _REQ_PID,
                    "tid": rid,
                    "ts": (ev.t - base) * 1e6,
                    "args": dict(ev.data, step=ev.step),
                })
        if open_name is not None:
            events.append(_span(open_name, open_t, last_t, rid, base))

    # rid-less lifecycle events (shed/deferred at rejection time — no
    # rid ever exists — and scripted fault injections) live only on the
    # recency ring; render them as instants on an engine lane so
    # overload and chaos show up in Perfetto next to the lanes they
    # explain. (Window-summary events are rid-less too but already
    # render as spans on the dispatch lanes — excluded here.)
    ridless = [
        ev for ev in tele.events
        if ev.rid is None and ev.kind in ("shed", "deferred", "fault")
    ]
    if ridless:
        events.append({
            "ph": "M", "pid": _ENGINE_PID, "name": "process_name",
            "args": {"name": "engine"},
        })
        for ev in ridless:
            events.append({
                "name": ev.kind,
                "ph": "i",
                "s": "p",
                "pid": _ENGINE_PID,
                "tid": 0,
                "ts": (ev.t - base) * 1e6,
                "args": dict(ev.data, step=ev.step),
            })

    lanes = {
        "decode_window": 0, "verify_dispatch": 1, "prefill_chunk": 2,
        "handoff": 3,
    }
    for kind, tid in lanes.items():
        events.append({
            "ph": "M", "pid": _DISPATCH_PID, "tid": tid,
            "name": "thread_name", "args": {"name": kind},
        })
    for d in tele.dispatches:
        events.append({
            "name": d.kind,
            "ph": "X",
            "pid": _DISPATCH_PID,
            "tid": lanes.get(d.kind, 4),
            "ts": (d.t - base) * 1e6,
            "dur": max(0.0, d.dur) * 1e6,
            "args": dict(d.data, step=d.step, tokens=d.tokens,
                         rids=list(d.rids)),
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}
