"""Deterministic fault injection + the typed failure surface of serving.

Two things live here, and they are one design:

1. **The typed exceptions** every runtime failure of the serving stack
   degrades through. The engine never raises a bare ``assert``/
   ``MemoryError`` at a request anymore: admission failures are
   :class:`AdmissionRejected` (permanent — this request can never be
   served here, with a machine-readable ``reason``) or
   :class:`PoolOverloaded` (transient backpressure — retry later);
   replica-level failures the cluster recovers from are
   :class:`ReplicaCrash` / :class:`WedgedDispatch` /
   :class:`TransientDispatchError`; :class:`ClusterUnavailable` is the
   end of the line (every replica dead with work still pending).
   :class:`Cancelled` and :class:`DeadlineExceeded` are the two
   POST-ADMISSION terminal outcomes the async front door
   (serving.frontdoor) surfaces to a caller awaiting a stream's result:
   the engine records them as ``Request.outcome`` (``"cancelled"`` /
   ``"expired"``) plus counters and lifecycle events — a cancel or a
   pre-dispatch deadline shed is a scheduled outcome, not a crash —
   and the front door raises the exception form only from
   ``TokenStream.result()``.

2. **A scripted, replayable chaos harness.** A :class:`FaultPlan` is an
   ordered list of :class:`FaultEvent` s keyed to *engine-local
   scheduler-step counters* — NOT wall clock — so a chaos run is a pure
   function of (trace, plan): replaying the same plan over the same
   request trace reproduces the same admissions, evictions, failovers
   and (by the engine's determinism contract) the same token streams
   bit for bit. Events fire at the TOP of ``ServingEngine.step`` via
   the ``fault_hook`` seam, BEFORE any dispatch mutates engine or pool
   state — which is exactly what makes failover replay exact: a
   crashed/wedged replica's requests carry only really-emitted tokens,
   and re-queueing them is the (already bit-identical) eviction path.

The hook is zero-cost when absent: an engine without a plan pays one
``is None`` check per scheduler window, nothing else.

Event kinds:

- ``crash``     — the replica dies on the spot (:class:`ReplicaCrash`):
                  the cluster marks it dead and fails its requests over.
- ``wedge``     — the dispatch stalls (``seconds`` of simulated stall,
                  then :class:`WedgedDispatch`): the cluster's
                  wall-clock watchdog trips and abandons the replica —
                  the r4/r5 wedged-TPU-relay shape, scripted.
- ``transient`` — one retriable dispatch failure
                  (:class:`TransientDispatchError`): the cluster
                  retries the same replica with capped exponential
                  backoff; consecutive events exhaust the retries into
                  a failover.
- ``exhaust``   — allocator pressure: quarantine ``pages`` free pages
                  (-1 = all) for ``hold_steps`` scheduler steps —
                  drives the engine's overload paths (eviction,
                  parking) without any device-side fault at all.
- ``handoff``   — poison the NEXT page handoff off this replica
                  (:class:`HandoffFailed` at export time, not at the
                  step top): the disaggregated cluster's prefill→decode
                  page move fails mid-flight and the request re-serves
                  cold from the submission record. Armed at the step
                  the event names; fires when the cluster next exports.

Compact spec grammar (the ``--fault_plan`` CLI flag)::

    STEP:KIND[@REPLICA][:ARG[:ARG2]] [; ...]

    "6:crash@1"            replica 1 crashes at its 6th step
    "4:wedge@0:0.5"        replica 0 stalls 0.5 s, watchdog territory
    "3:transient"          replica 0, one retriable failure at step 3
    "2:exhaust@0:all:3"    quarantine all free pages for 3 steps
    "2:handoff@0"          replica 0's next page export fails
"""

from __future__ import annotations

import dataclasses
import time
import typing as tp

__all__ = [
    "AdmissionRejected",
    "Cancelled",
    "ClusterUnavailable",
    "DeadlineExceeded",
    "FaultEvent",
    "FaultPlan",
    "HandoffFailed",
    "PoolOverloaded",
    "ReplicaCrash",
    "ServingFault",
    "TransientDispatchError",
    "WedgedDispatch",
]


class ServingFault(Exception):
    """Base of every typed serving failure (injected or organic)."""


class ReplicaCrash(ServingFault):
    """The replica process/device is gone; its engine must not be
    stepped again. The cluster marks it dead and fails over."""


class WedgedDispatch(ServingFault):
    """A dispatch stalled past any useful deadline (the wedged-relay
    case). Raised by the scripted wedge after its stall; in production
    the wall-clock watchdog usually trips first and the replica is
    abandoned mid-flight."""


class TransientDispatchError(ServingFault):
    """A retriable dispatch failure (flaky interconnect, preempted
    runtime): the same replica may well succeed on retry."""


class _ReasonedFault(ServingFault):
    def __init__(self, reason: str, message: str):
        self.reason = reason
        super().__init__(f"[{reason}] {message}")


class AdmissionRejected(_ReasonedFault):
    """Permanent admission failure: this request can never be served by
    this engine (``reason`` is machine-readable — e.g.
    ``lifetime_exceeds_pool``, ``budget_exceeds_block``,
    ``empty_prompt``, ``bad_budget``, ``queue_full`` under the shed
    policy). Counted in engine and cluster ``stats()``."""


class PoolOverloaded(_ReasonedFault):
    """Transient overload backpressure: the request was NOT accepted
    but may be resubmitted later (``reason="queue_full"`` under the
    defer policy — the bounded wait queue is full right now)."""


class Cancelled(ServingFault):
    """The request was cancelled by its submitter after admission
    (``ServingEngine.cancel`` / ``TokenStream.cancel``): its slot was
    reclaimed and its pages released at the next scheduler boundary.
    Never raised by the engine itself — the scheduler records the
    outcome (``Request.outcome == "cancelled"``, the ``cancelled``
    lifecycle event, the ``cancelled_requests`` counter); the async
    front door raises this from ``TokenStream.result()`` so a caller
    awaiting a full completion gets a typed outcome."""

    def __init__(self, rid: int, tokens_emitted: int = 0):
        self.rid = rid
        self.tokens_emitted = tokens_emitted
        super().__init__(
            f"request {rid} cancelled after {tokens_emitted} tokens"
        )


class DeadlineExceeded(ServingFault):
    """The request's deadline passed while it was still waiting for
    dispatch (queued or parked), so the scheduler SHED it before
    spending any more compute on it — tokens it would have emitted past
    the deadline count for nothing under an SLO, and serving them
    starves requests that can still meet theirs. Recorded as
    ``Request.outcome == "expired"`` + the ``deadline_shed`` event +
    the ``deadline_shed_requests`` counter; raised only by
    ``TokenStream.result()``. A request already IN a decode slot is
    never shed mid-flight — it finishes late and the bench counts it
    deadline-missed instead."""

    def __init__(self, rid: int, tokens_emitted: int = 0):
        self.rid = rid
        self.tokens_emitted = tokens_emitted
        super().__init__(
            f"request {rid} shed: deadline passed before dispatch "
            f"({tokens_emitted} tokens emitted)"
        )


class HandoffFailed(ServingFault):
    """A prefill→decode page handoff failed mid-flight (the replica
    crashed or the page move was poisoned by a scripted ``handoff``
    fault) BEFORE the exported state left the source engine. The slot
    is still intact on the prefill replica; the cluster abandons that
    copy and re-serves the request COLD from its submission record —
    the same stream by the determinism contract. Never surfaces to a
    submitter: it is a cluster-internal failover trigger, counted in
    ``handoff_failures``."""


class ClusterUnavailable(ServingFault):
    """Every replica is dead and requests are still pending — the one
    failure the cluster cannot degrade through."""


_KINDS = ("crash", "wedge", "transient", "exhaust", "handoff")


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One scripted fault, keyed to a replica's scheduler-step counter.

    ``step`` is 1-based and engine-local: the event fires at the top of
    that replica's ``step()`` call number ``step`` (retries count — a
    cluster retry re-enters ``step()``, so consecutive ``transient``
    events model consecutive failures of one logical dispatch)."""

    step: int
    kind: str
    replica: int = 0
    seconds: float = 0.25  # wedge: simulated stall before the raise
    pages: int = -1  # exhaust: free pages to quarantine (-1 = all)
    hold_steps: int = 1  # exhaust: scheduler steps until auto-release

    def __post_init__(self):
        assert self.kind in _KINDS, f"unknown fault kind {self.kind!r}"
        assert self.step >= 1, f"steps are 1-based, got {self.step}"
        assert self.replica >= 0, self.replica
        assert self.hold_steps >= 1, self.hold_steps

    def spec(self) -> str:
        base = f"{self.step}:{self.kind}@{self.replica}"
        if self.kind == "wedge":
            return f"{base}:{self.seconds:g}"
        if self.kind == "exhaust":
            pages = "all" if self.pages < 0 else str(self.pages)
            return f"{base}:{pages}:{self.hold_steps}"
        return base


class FaultPlan:
    """An ordered, replayable fault script over a (multi-replica)
    serving deployment. Build from events or :meth:`parse` a compact
    spec string; install per replica via
    ``ServingEngine(fault_hook=plan.hook(i))`` (the cluster does this
    for you: ``ServingCluster(..., fault_plan=plan)``)."""

    def __init__(self, events: tp.Iterable[FaultEvent]):
        evs = list(events)
        # stable order: by step, then original position — events of one
        # (replica, step) fire in authoring order
        self.events: tp.Tuple[FaultEvent, ...] = tuple(
            sorted(evs, key=lambda e: e.step)  # sorted() is stable
        )
        self._by_key: tp.Dict[tp.Tuple[int, int], tp.List[FaultEvent]] = {}
        for ev in self.events:
            self._by_key.setdefault((ev.replica, ev.step), []).append(ev)

    def __len__(self) -> int:
        return len(self.events)

    def __iter__(self):
        return iter(self.events)

    @property
    def replicas(self) -> tp.Set[int]:
        return {ev.replica for ev in self.events}

    def events_for(self, replica: int, step: int) -> tp.List[FaultEvent]:
        return self._by_key.get((replica, step), [])

    def spec(self) -> str:
        """The compact string form; ``FaultPlan.parse(plan.spec())``
        reproduces the plan (roundtrip-tested)."""
        return ";".join(ev.spec() for ev in self.events)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        events = []
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            fields = part.split(":")
            assert len(fields) >= 2, f"malformed fault event {part!r}"
            step = int(fields[0])
            head = fields[1]
            if "@" in head:
                kind, rep = head.split("@", 1)
                replica = int(rep)
            else:
                kind, replica = head, 0
            kw: tp.Dict[str, tp.Any] = {}
            if kind == "wedge" and len(fields) > 2:
                kw["seconds"] = float(fields[2])
            if kind == "exhaust":
                if len(fields) > 2:
                    kw["pages"] = -1 if fields[2] == "all" else int(fields[2])
                if len(fields) > 3:
                    kw["hold_steps"] = int(fields[3])
            events.append(
                FaultEvent(step=step, kind=kind, replica=replica, **kw)
            )
        return cls(events)

    def hook(self, replica: int = 0) -> "_EngineFaultHook":
        """The per-engine injection callable for ``replica`` — stateful
        (it tracks pending quarantine releases), so take a fresh hook
        per engine instance."""
        return _EngineFaultHook(self, replica)


class _EngineFaultHook:
    """Installed as ``ServingEngine(fault_hook=...)``; called at the top
    of every ``step()`` with the engine, after ``engine.fault_step`` was
    incremented. Raises the scripted typed faults; mutates only the
    host-side allocator (quarantine) — never device state — so every
    injection point leaves the engine resumable/drainable."""

    def __init__(self, plan: FaultPlan, replica: int):
        self._plan = plan
        self._replica = replica
        self._release_at: tp.Optional[int] = None

    def __call__(self, engine) -> None:
        step = engine.fault_step
        if self._release_at is not None and step >= self._release_at:
            engine.alloc.release_quarantined()
            self._release_at = None
            engine._unpark()  # quarantine-parked requests may fit again
        for ev in self._plan.events_for(self._replica, step):
            engine.faults_injected += 1
            if engine.telemetry is not None:
                # the injection itself is telemetry (the flight recorder
                # must show WHAT fired before the timeline goes quiet);
                # spec() is deterministic, so replays keep identical
                # event sequences
                engine.telemetry.emit(
                    "fault", step=step, t=engine.clock(),
                    fault=ev.kind, spec=ev.spec(),
                )
            if ev.kind == "exhaust":
                engine.alloc.quarantine(ev.pages)
                due = step + ev.hold_steps
                self._release_at = (
                    due if self._release_at is None
                    else max(self._release_at, due)
                )
            elif ev.kind == "handoff":
                # armed, not raised: the fault fires inside the NEXT
                # export_request off this engine (the page move is a
                # cluster action, not a step-top dispatch)
                engine._handoff_poison = True
            elif ev.kind == "crash":
                raise ReplicaCrash(f"scripted crash at step {step}")
            elif ev.kind == "transient":
                raise TransientDispatchError(
                    f"scripted transient dispatch error at step {step}"
                )
            elif ev.kind == "wedge":
                time.sleep(ev.seconds)
                raise WedgedDispatch(
                    f"scripted {ev.seconds:g}s wedge at step {step}"
                )
