"""Serving subsystem: paged KV cache + continuous-batching engine.

Public surface:

- :class:`~midgpt_tpu.serving.paged.PagedKVPool`,
  :class:`~midgpt_tpu.serving.paged.PageAllocator`,
  :class:`~midgpt_tpu.serving.paged.PrefixIndex` — the page pool, its
  host-side refcounting allocator, and the content-addressed prefix
  index behind copy-on-write page sharing.
- :class:`~midgpt_tpu.serving.engine.ServingEngine` — the scheduler:
  ``submit()`` requests, ``run()`` to drain, per-request
  :class:`~midgpt_tpu.serving.engine.Request` records with TTFT/latency
  timestamps. ``prefix_cache=True`` shares already-resident pages across
  requests (prefill skips the cached prefix); ``prefill_chunk=N``
  prefills Sarathi-style in N-token chunks interleaved with decode.
- :func:`~midgpt_tpu.serving.engine.make_decode_window`,
  :func:`~midgpt_tpu.serving.engine.make_prefill_chunk_program` — the
  fused K-step decode program and the suffix-prefill chunk program
  (both audited for donation and host-sync regressions:
  ``python -m midgpt_tpu.analysis --serving``).
- :func:`~midgpt_tpu.serving.engine.make_verify_program`,
  :class:`~midgpt_tpu.serving.speculate.NgramProposer` — self-speculative
  decoding: draft-model-free n-gram drafting plus the single-dispatch
  paged verification program (``ServingEngine(speculate=N)``; audited
  next to the other two serving programs).
- :class:`~midgpt_tpu.serving.cluster.ServingCluster`,
  :func:`~midgpt_tpu.serving.cluster.serving_meshes` — TPxDP: the engine
  shards its model/KV pool over a tensor-only mesh
  (``ServingEngine(mesh=...)``, whole-KV-head pool sharding), and the
  cluster runs N shared-nothing engine replicas (least-loaded admission,
  per-replica prefix caches, aggregated stats) above it — with
  per-replica health, a dispatch watchdog, transient-error retry, and
  bit-identical failover of a dead replica's backlog. Disaggregated
  serving rides the same seam:
  ``ServingCluster(prefill_replicas=P, decode_replicas=D)`` splits the
  pools by roofline (compute-bound prefill vs HBM-bound decode), pages
  hand off between them via
  :func:`~midgpt_tpu.serving.paged.export_pages` /
  :func:`~midgpt_tpu.serving.paged.import_pages`
  (:class:`~midgpt_tpu.serving.engine.HandoffRecord` carries payloads,
  int8 scale planes, and the final prefill logits row — decode resumes
  bit-identically), and ``affinity=True`` routes admission to the
  replica with the longest resident-prefix overlap (load-imbalance
  capped; :class:`~midgpt_tpu.serving.faults.HandoffFailed` is the
  typed fault for a handoff that dies mid-flight).
- :class:`~midgpt_tpu.serving.faults.FaultPlan` and the typed failure
  surface (:class:`~midgpt_tpu.serving.faults.AdmissionRejected`,
  :class:`~midgpt_tpu.serving.faults.PoolOverloaded`, the replica fault
  exceptions) — deterministic, scripted chaos injection keyed to
  scheduler-step boundaries, replayable bit for bit.
- :class:`~midgpt_tpu.serving.telemetry.EngineTelemetry`,
  :class:`~midgpt_tpu.serving.telemetry.MetricsRegistry`,
  :func:`~midgpt_tpu.serving.telemetry.chrome_trace` — the observability
  layer: per-request lifecycle tracing keyed to scheduler steps
  (``ServingEngine(telemetry=True)``; zero program perturbation — the
  traced engine launches the identical cached jitted callables and
  greedy streams are bitwise identical either way), the registry behind
  ``stats()`` (``ENGINE_STATS_KEYS``/``CLUSTER_STATS_KEYS`` pin the
  façade's key contract), the fault flight recorder
  (``ServingEngine.flight_dump``, ``ServingCluster(flight_dir=...)``),
  and Perfetto-loadable timeline export.
- :class:`~midgpt_tpu.serving.frontdoor.AsyncFrontDoor`,
  :class:`~midgpt_tpu.serving.frontdoor.TokenStream`,
  :class:`~midgpt_tpu.serving.frontdoor.VirtualClock` — the asyncio
  streaming front door (ROADMAP item 3): per-request async token
  streams at the window-harvest cadence, cancellation-safe teardown
  (slot reclaim + cold page retire, invariants property-checked),
  priority/deadline admission with awaitable backpressure, and a
  manual-pump determinism seam (streams bit-identical to the
  synchronous loop; chaos replays event-sequence-identical). The
  engine-side policy underneath: ``submit(priority=, deadline_s=)``,
  aging starvation-proof admission, pre-dispatch deadline sheds
  (:class:`~midgpt_tpu.serving.faults.DeadlineExceeded`), and
  ``cancel()`` (:class:`~midgpt_tpu.serving.faults.Cancelled`).
- :func:`generate_served` — one-shot batch generation through the engine
  (the ``sample.py --serve`` path).
"""

from __future__ import annotations

import typing as tp

import numpy as np

from midgpt_tpu.serving.cluster import ServingCluster, serving_meshes
from midgpt_tpu.serving.faults import (
    AdmissionRejected,
    Cancelled,
    ClusterUnavailable,
    DeadlineExceeded,
    FaultEvent,
    FaultPlan,
    HandoffFailed,
    PoolOverloaded,
    ReplicaCrash,
    ServingFault,
    TransientDispatchError,
    WedgedDispatch,
)
from midgpt_tpu.serving.frontdoor import (
    AsyncFrontDoor,
    TokenStream,
    VirtualClock,
)
from midgpt_tpu.serving.engine import (
    HandoffRecord,
    Request,
    ServingEngine,
    make_copy_page_program,
    make_decode_window,
    make_prefill_chunk_program,
    make_verify_program,
)
from midgpt_tpu.serving.speculate import NgramProposer, Proposer
from midgpt_tpu.serving.telemetry import (
    CLUSTER_STATS_KEYS,
    ENGINE_STATS_KEYS,
    EngineTelemetry,
    MetricsRegistry,
    chrome_trace,
)
from midgpt_tpu.serving.paged import (
    PageAllocator,
    PagedKVPool,
    PrefixIndex,
    copy_page,
    export_pages,
    flush_recent,
    import_pages,
    pages_needed,
    write_prompt_pages,
    write_token_rows,
)

__all__ = [
    "AdmissionRejected",
    "AsyncFrontDoor",
    "CLUSTER_STATS_KEYS",
    "Cancelled",
    "ClusterUnavailable",
    "DeadlineExceeded",
    "ENGINE_STATS_KEYS",
    "EngineTelemetry",
    "FaultEvent",
    "FaultPlan",
    "HandoffFailed",
    "HandoffRecord",
    "MetricsRegistry",
    "NgramProposer",
    "PageAllocator",
    "PagedKVPool",
    "PoolOverloaded",
    "PrefixIndex",
    "Proposer",
    "ReplicaCrash",
    "Request",
    "ServingCluster",
    "ServingEngine",
    "ServingFault",
    "TokenStream",
    "TransientDispatchError",
    "VirtualClock",
    "WedgedDispatch",
    "chrome_trace",
    "copy_page",
    "serving_meshes",
    "export_pages",
    "flush_recent",
    "generate_served",
    "import_pages",
    "make_copy_page_program",
    "make_decode_window",
    "make_prefill_chunk_program",
    "make_verify_program",
    "pages_needed",
    "write_prompt_pages",
    "write_token_rows",
]


def generate_served(
    model,
    prompts: tp.Sequence[np.ndarray],
    max_new_tokens: int,
    *,
    eos_id: tp.Optional[int] = None,
    temperature: float = 0.0,
    top_k: tp.Optional[int] = None,
    slots: tp.Optional[int] = None,
    window: int = 4,
    page_size: int = 16,
    cache_dtype=None,
    seed: int = 0,
    prefix_cache: bool = True,
    prefill_chunk: tp.Optional[int] = None,
    prefill_budget: tp.Optional[int] = None,
    speculate: int = 0,
    quant: tp.Optional[str] = None,
    kv_quant: tp.Optional[str] = None,
    paged_kernel: str = "auto",
    layer_scan: str = "off",
    mesh=None,
) -> tp.List[np.ndarray]:
    """One-shot batch generation routed through the serving engine: submit
    every prompt, drain, return the generated token arrays in submission
    order. The engine path to the fixed-batch ``sampling.generate`` —
    same greedy tokens, 1/K the decode dispatches, and per-request early
    exit at ``eos_id``. ``speculate=N`` turns decode dispatches into
    n-gram-drafted verify dispatches emitting ``1 + accepted`` tokens
    each — at ``temperature == 0`` acceptance is argmax agreement (same
    tokens, fewer launches); at ``temperature > 0`` it is rejection
    sampling against the decode sampler's own distribution (same token
    DISTRIBUTION and the same per-request key-derivation determinism,
    fewer launches).
    ``quant="int8"`` serves the int8 per-channel quantized weight path
    (midgpt_tpu.quant: dequant fused into each matmul — halves the
    per-token weight stream; po2 scales keep greedy output token-
    identical to the engine running the dequantized weights)."""
    import jax.numpy as jnp

    eng = ServingEngine(
        model,
        slots=slots if slots is not None else max(1, min(8, len(prompts))),
        page_size=page_size,
        window=window,
        temperature=temperature,
        top_k=top_k,
        cache_dtype=cache_dtype if cache_dtype is not None else jnp.bfloat16,
        seed=seed,
        prefix_cache=prefix_cache,
        prefill_chunk=prefill_chunk,
        prefill_budget=prefill_budget,
        speculate=speculate,
        quant=quant,
        kv_quant=kv_quant,
        paged_kernel=paged_kernel,
        layer_scan=layer_scan,
        mesh=mesh,
    )
    rids = [
        eng.submit(p, max_new_tokens, eos_id=eos_id, seed=i)
        for i, p in enumerate(prompts)
    ]
    finished = eng.run()
    return [np.asarray(finished[r].tokens, np.int32) for r in rids]
