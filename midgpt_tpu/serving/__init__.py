"""Serving subsystem: paged KV cache + continuous-batching engine.

Public surface:

- :class:`~midgpt_tpu.serving.paged.PagedKVPool`,
  :class:`~midgpt_tpu.serving.paged.PageAllocator` — the page pool and
  its host-side free-list allocator.
- :class:`~midgpt_tpu.serving.engine.ServingEngine` — the scheduler:
  ``submit()`` requests, ``run()`` to drain, per-request
  :class:`~midgpt_tpu.serving.engine.Request` records with TTFT/latency
  timestamps.
- :func:`~midgpt_tpu.serving.engine.make_decode_window` — the fused
  K-step decode program (also what the analysis CLI audits for donation
  and host-sync regressions: ``python -m midgpt_tpu.analysis --serving``).
- :func:`generate_served` — one-shot batch generation through the engine
  (the ``sample.py --serve`` path).
"""

from __future__ import annotations

import typing as tp

import numpy as np

from midgpt_tpu.serving.engine import (
    Request,
    ServingEngine,
    make_decode_window,
    make_prefill_program,
)
from midgpt_tpu.serving.paged import (
    PageAllocator,
    PagedKVPool,
    flush_recent,
    pages_needed,
    write_prompt_pages,
)

__all__ = [
    "PageAllocator",
    "PagedKVPool",
    "Request",
    "ServingEngine",
    "flush_recent",
    "generate_served",
    "make_decode_window",
    "make_prefill_program",
    "pages_needed",
    "write_prompt_pages",
]


def generate_served(
    model,
    prompts: tp.Sequence[np.ndarray],
    max_new_tokens: int,
    *,
    eos_id: tp.Optional[int] = None,
    temperature: float = 0.0,
    top_k: tp.Optional[int] = None,
    slots: tp.Optional[int] = None,
    window: int = 4,
    page_size: int = 16,
    cache_dtype=None,
    seed: int = 0,
    mesh=None,
) -> tp.List[np.ndarray]:
    """One-shot batch generation routed through the serving engine: submit
    every prompt, drain, return the generated token arrays in submission
    order. The engine path to the fixed-batch ``sampling.generate`` —
    same greedy tokens, 1/K the decode dispatches, and per-request early
    exit at ``eos_id``."""
    import jax.numpy as jnp

    eng = ServingEngine(
        model,
        slots=slots if slots is not None else max(1, min(8, len(prompts))),
        page_size=page_size,
        window=window,
        temperature=temperature,
        top_k=top_k,
        cache_dtype=cache_dtype if cache_dtype is not None else jnp.bfloat16,
        seed=seed,
        mesh=mesh,
    )
    rids = [
        eng.submit(p, max_new_tokens, eos_id=eos_id, seed=i)
        for i, p in enumerate(prompts)
    ]
    finished = eng.run()
    return [np.asarray(finished[r].tokens, np.int32) for r in rids]
