"""Draft-model-free speculative drafting: prompt-lookup n-gram proposer.

Speculative decoding raises tokens-per-forward by guessing a short
continuation cheaply and letting the model VERIFY all guesses in one
multi-query dispatch (serving.engine.make_verify_program). The classic
formulation needs a second, smaller draft model; this module implements
the draft-model-free variant (prompt lookup / self-speculation): the
draft for a request is read out of the request's OWN token history —
find the most recent earlier occurrence of the current context suffix
and propose the tokens that followed it.

Why this works on serving traffic: the workloads worth speculating on
are exactly the ones with internal repetition — summarization and
code-edit loops quoting their input, chat turns echoing the system
prompt, grammar-constrained output, greedy models falling into refrains.
On such text the n-gram continuation matches the model's own argmax for
several tokens at a stretch; on novel text it misses and the engine's
adaptive controller shrinks the draft to a cheap 1-token probe. Either
way the proposal is free of model FLOPs and composes with every config —
there is no second model to shard, checkpoint, or keep in HBM.

Determinism: proposals are a pure function of the context token list, so
the engine's output is token-identical to the non-speculative path at
``temperature == 0`` (acceptance verifies against the model's own
argmax) and distributed EXACTLY as the non-speculative sampled path at
``temperature > 0`` (rejection-sampling acceptance against the model's
own target distribution, residual resample on rejection — see
serving.engine._build_verify_program). A bad proposer costs throughput,
never correctness (property-tested with an adversarial proposer in
tests/test_serving.py).

Draft probabilities: rejection sampling accepts draft token ``t`` drawn
from a draft distribution ``q`` with probability ``min(1,
p_target(t) / q(t))``. An n-gram proposal is DETERMINISTIC given the
context — the "distribution" it samples from is the point mass on the
proposed token, so its draft probabilities are exactly one-hot
(``q(t) = 1``), the acceptance test collapses to ``u <= p_target(t)``,
and the residual ``max(p - q, 0)`` is the target with the drafted
token's mass removed. Because of this the engine never materializes a
dense ``[S, spec_len, V]`` probability tensor for n-gram drafts — the
one-hot is reconstructed IN-PROGRAM from the draft token ids, keeping
the verify dispatch's entry-parameter traffic identical to the greedy
program's. Proposers that genuinely sample (a real draft model) opt in
to the dense path via the SoftProposer protocol below.
"""

from __future__ import annotations

import typing as tp


class Proposer(tp.Protocol):
    """Drafting interface the engine calls once per verify dispatch."""

    def propose(
        self, ctx: tp.Sequence[int], n: int
    ) -> tp.List[int]:
        """Up to ``n`` draft tokens for context positions ``len(ctx)+1,
        len(ctx)+2, ...`` — i.e. the tokens FOLLOWING the pending next
        token (the engine materializes position ``len(ctx)`` itself, in-
        program, from the carried logits). Fewer than ``n`` (including
        zero) is fine: the verify dispatch masks the missing rows."""
        ...


class SoftProposer(tp.Protocol):
    """A proposer that SAMPLES its drafts from a genuine distribution.

    Marked by ``soft = True``; the engine then calls ``propose_soft``
    and ships the returned ``[n_drafted, V]`` float32 probability rows
    into the sampled verify dispatch as a dense entry tensor, so the
    acceptance ratio ``u * q(t) <= p(t)`` and the residual
    ``max(p - q, 0)`` see the proposer's true ``q``. Rejection-sampling
    exactness is conditional on honesty: row j must be the distribution
    token j was actually drawn from. The n-gram proposer never uses
    this path (its q is one-hot by construction — see module
    docstring); the dense path exists for draft-model proposers and for
    the faithfulness tests' injectable soft-distribution proposers.

    Why ``seed``: serving determinism requires drafting be a pure
    function of the request — but honesty requires the draft actually
    be DISTRIBUTED as q. A proposer derandomized by context alone is a
    point mass given ctx (its true q is one-hot, whatever it claims):
    two same-prompt requests would receive the identical "sample" and
    the ensemble statistics rejection sampling relies on collapse. The
    per-request sampling ``seed`` is exactly the entropy that resolves
    this — derive the draft rng from ``(seed, ctx)`` and drafts stay
    bitwise scheduling-invariant per request while remaining honest
    draws from q across requests (the same contract the engine's own
    sampler satisfies via derive_request_key)."""

    soft: bool

    def propose_soft(
        self, ctx: tp.Sequence[int], n: int, seed: int
    ) -> tp.Tuple[tp.List[int], tp.Any]:
        """Like ``Proposer.propose`` but returns ``(tokens, probs)``
        with ``probs`` array-like ``[len(tokens), V]`` — row j the draft
        distribution token j was sampled from (rows must sum to 1).
        ``seed`` is the request's sampling seed; the draft rng MUST be
        derived from it (plus ctx), never from global state."""
        ...


class NgramProposer:
    """Prompt-lookup drafting: suffix-match the context against itself.

    ``propose`` scans for the most recent PRIOR occurrence of the
    longest context suffix of length ``max_ngram`` down to ``min_ngram``
    and returns the tokens that followed that occurrence. The first
    continuation token is skipped — it is the proposer's implicit guess
    for the pending next token, whose true value the verify program
    computes itself (argmax of the carried logits) and uses as candidate
    row 0; the returned drafts fill rows 1..n. When the guess is wrong
    the drafts simply fail verification — alignment is a throughput bet,
    not a correctness assumption.

    Pure host-side string matching over a few thousand ints per request
    per dispatch — O(len(ctx) * max_ngram) worst case, microseconds next
    to an XLA launch. No state is kept between calls, so eviction and
    re-admission need no bookkeeping here.
    """

    def __init__(self, max_ngram: int = 4, min_ngram: int = 1):
        assert max_ngram >= min_ngram >= 1, (max_ngram, min_ngram)
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def propose(self, ctx: tp.Sequence[int], n: int) -> tp.List[int]:
        assert n >= 1, n
        toks = [int(t) for t in ctx]
        l = len(toks)
        for k in range(min(self.max_ngram, l - 1), self.min_ngram - 1, -1):
            suffix = toks[l - k :]
            best: tp.List[int] = []
            # scan match starts right to left (recency wins ties),
            # excluding the suffix's own position; a match whose
            # continuation fills the whole draft returns immediately,
            # otherwise the longest partial continuation at this k wins
            for i in range(l - k - 1, -1, -1):
                if toks[i : i + k] == suffix:
                    # continuation after the match; [0] is the pending
                    # next token's position (row 0 of the verify
                    # dispatch) — drafts start one past it
                    cont = toks[i + k : i + k + n + 1]
                    if len(cont) == n + 1:
                        return cont[1:]
                    if len(cont) > len(best):
                        best = cont
            if len(best) >= 2:
                return best[1 : n + 1]
        return []
