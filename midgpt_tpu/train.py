"""Training engine: optimizer chain, jitted donated train step with
grad-accumulation scan, eval loop, and the train orchestrator.

Capability parity with /root/reference/src/train.py, redesigned:

- params are the model pytree itself (no partition/combine);
- grads re-constrained to the declarative rule table every microstep so
  accumulated grads stay FSDP/TP-sharded (parity: train.py:87);
- LR is read from the schedule at the current step — no fragile
  ``opt_state[3].count`` probing (train.py:150-152);
- batches come from the seeded, checkpointable Loader (midgpt_tpu.data);
- loss/LR host syncs happen only on logging steps (the reference synced
  every step, train.py:216-217);
- throughput + MFU computed in-train (midgpt_tpu.utils.metrics).
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
import time
import typing as tp

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from midgpt_tpu.checkpoint import Checkpointer, config_fingerprint
from midgpt_tpu.config import (
    ExperimentConfig,
    resolve_dispatch_intervals,
    to_dict,
)
from midgpt_tpu.data import Loader, PrefetchLoader, load_shard
from midgpt_tpu.models.gpt import GPT, GPT_PARAM_RULES, count_params
from midgpt_tpu.parallel.mesh import create_mesh
from midgpt_tpu.parallel.sharding import (
    axis_rules,
    constrain_params,
    make_global_array,
)
from midgpt_tpu.pytree import cast_floating, module
from midgpt_tpu.utils.metrics import MetricLogger, mfu, train_floor

Array = jax.Array


@module
class TrainState:
    params: GPT
    opt_state: tp.Any
    step: Array  # int32 scalar


def make_lr_schedule(cfg: ExperimentConfig) -> optax.Schedule:
    """warmup 0 -> lr, cosine decay lr -> min_lr (parity: train.py:147-149)."""
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=cfg.learning_rate,
        warmup_steps=cfg.warmup_steps,
        decay_steps=cfg.lr_decay_steps,
        end_value=cfg.min_lr,
    )


def make_optimizer(cfg: ExperimentConfig) -> tp.Tuple[optax.GradientTransformation, optax.Schedule]:
    """clip -> adam -> independent weight decay -> schedule -> -1
    (parity: train.py:153-159, incl. the wd/lr "independent weight decay"
    scaling from the small-scale-proxies recipe)."""
    schedule = make_lr_schedule(cfg)
    wd = (
        cfg.weight_decay / cfg.learning_rate
        if cfg.independent_wd
        else cfg.weight_decay
    )
    tx = optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip),
        optax.scale_by_adam(b1=cfg.beta1, b2=cfg.beta2),
        optax.add_decayed_weights(wd),
        optax.scale_by_schedule(schedule),
        optax.scale(-1.0),
    )
    return tx, schedule


def _dtype(name: str):
    return jnp.dtype(name)


def loss_fn(
    model: GPT,
    x: Array,  # [B, T] int32
    y: Array,  # [B, T] int32
    key: tp.Optional[Array],
    deterministic: bool,
    loss_chunk: tp.Optional[int] = None,
    loss_chunk_unroll: tp.Union[bool, int] = False,
    pp_mesh=None,
    pp_microbatches: int = 0,
    pp_boundary_dtype: tp.Optional[str] = None,
    include_moe_aux: bool = True,
) -> Array:
    """Batched xent; logits in f32 (parity: train.py:72-77). With
    ``loss_chunk``, the head projection + xent run T-chunk by T-chunk
    (ops/loss.py) so the [B,T,V] f32 logits never materialize — same math,
    ~T/chunk less peak loss memory. With ``pp_mesh``, the block stack runs
    pipelined over the mesh's 'pipeline' axis (parallel.pipeline)."""
    aux = None
    if pp_mesh is not None:
        from midgpt_tpu.parallel.pipeline import gpt_pipeline_hidden

        assert model.config.mlp != "moe", (
            "MoE is not supported under pipeline parallelism (v1): the "
            "aux loss rides the layer scan, which PP replaces"
        )
        h = gpt_pipeline_hidden(
            model, x, pp_mesh, n_micro=pp_microbatches, key=key,
            deterministic=deterministic, boundary_dtype=pp_boundary_dtype,
        )
    elif model.config.mlp == "moe":
        h, aux = model.hidden(
            x, key=key, deterministic=deterministic, return_aux=True
        )
    else:
        h = model.hidden(x, key=key, deterministic=deterministic)
    if loss_chunk is not None:
        from midgpt_tpu.ops.loss import chunked_softmax_xent

        xent = chunked_softmax_xent(
            h, model.head_weight(h.dtype), y, chunk_t=loss_chunk,
            unroll=loss_chunk_unroll,
        )
    else:
        from midgpt_tpu.parallel.sharding import shard_act

        logits = h @ model.head_weight(h.dtype)  # [B, T, V]
        logits = shard_act(
            logits, "batch", "seq", "vocab"
        ).astype(jnp.float32)
        xent = optax.softmax_cross_entropy_with_integer_labels(
            logits, y
        ).mean()
    if aux is not None and include_moe_aux:
        # the OPTIMIZED loss; eval passes include_moe_aux=False so
        # reported train/val losses stay pure cross-entropy, comparable
        # to dense baselines (code review r5)
        xent = xent + model.config.moe_aux_weight * aux
    return xent


def _effective_loss_chunk(cfg: ExperimentConfig, mesh) -> tp.Optional[int]:
    """cfg.loss_chunk, disabled only when T doesn't divide by the chunk.
    A sharded sequence axis no longer disables chunking: the loss runs the
    chunk scan per sequence shard under a partial-manual shard_map
    (ops/loss.py) — the ring/long-context configs are exactly where the
    [B, T, V] f32 logits the chunking avoids are biggest."""
    chunk = cfg.loss_chunk
    if chunk is None:
        return None
    if cfg.model.block_size % chunk != 0:
        return None
    return chunk


def _cfg_param_rules(cfg: ExperimentConfig):
    from midgpt_tpu.models.gpt import gpt_param_rules

    return gpt_param_rules(pipeline=cfg.mesh.pipeline > 1)


def _make_step_core(
    cfg: ExperimentConfig,
    tx: optax.GradientTransformation,
    mesh,
    param_rules=None,
):
    """The un-jitted single-step body shared by :func:`make_train_step`
    (K=1, one dispatch per step) and :func:`make_train_window` (K steps
    fused into one dispatch).

    Returns ``step_fn(state, x, y, key) -> (new_state, aux)`` with
    ``aux = {"loss", "grad_norm", "lr"}`` — per-step scalars cheap to
    emit (the grad norm is CSE'd with the clip's internal computation,
    the lr re-reads the schedule at ``state.step``). Callers that only
    return the loss get the extras dead-code-eliminated, so the K=1
    program is unchanged."""
    compute_dtype = _dtype(cfg.compute_dtype)
    param_dtype = _dtype(cfg.param_dtype)
    has_dropout = cfg.model.dropout > 0.0
    loss_chunk = _effective_loss_chunk(cfg, mesh)
    if param_rules is None:
        param_rules = _cfg_param_rules(cfg)
    pp_mesh = mesh if cfg.mesh.pipeline > 1 else None
    schedule = make_lr_schedule(cfg)

    def step_fn(state: TrainState, x: Array, y: Array, key: Array):
        # x, y: [G, B, T]
        params_c = cast_floating(state.params, compute_dtype)
        g = cfg.g_accum_iters
        keys = jax.random.split(key, g)

        def microstep(carry, xs):
            grad_acc, loss_acc = carry
            x_mb, y_mb, k = xs
            loss, grads = jax.value_and_grad(loss_fn)(
                params_c, x_mb, y_mb,
                k if has_dropout else None,
                not has_dropout,
                loss_chunk,
                cfg.loss_chunk_unroll,
                pp_mesh,
                cfg.mesh.pp_microbatches,
                cfg.mesh.pp_boundary_dtype,
            )
            # keep accumulated grads sharded like params (train.py:87)
            grads = constrain_params(grads, mesh, param_rules)
            grad_acc = jax.tree.map(jnp.add, grad_acc, grads)
            return (grad_acc, loss_acc + loss), None

        if g == 1:
            # no accumulation: skip the zeros-init + add passes (a full
            # read+write of the f32 grad tree each)
            loss_sum, grads = jax.value_and_grad(loss_fn)(
                params_c, x[0], y[0],
                keys[0] if has_dropout else None,
                not has_dropout,
                loss_chunk,
                cfg.loss_chunk_unroll,
                pp_mesh,
                cfg.mesh.pp_microbatches,
                cfg.mesh.pp_boundary_dtype,
            )
            grads = constrain_params(grads, mesh, param_rules)
        else:
            grad_init = jax.tree.map(jnp.zeros_like, params_c)
            (grads, loss_sum), _ = jax.lax.scan(
                microstep, (grad_init, jnp.zeros((), jnp.float32)), (x, y, keys)
            )
        loss = loss_sum / g
        # average + promote to param dtype for the f32 optimizer update
        grads = jax.tree.map(lambda gr: (gr / g).astype(param_dtype), grads)
        grad_norm = optax.global_norm(grads)  # CSE'd with clip_by_global_norm
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        # constrain the NEW opt state like params (the Adam moments are
        # param-shaped subtrees, so the same rule table resolves them;
        # re.search matches the param path inside the opt-state path).
        # Without this, GSPMD may give the output moments a different
        # sharding than the input ones and jit silently DROPS their
        # donation — the step then holds two copies of m/v in HBM
        # (found by the analysis subsystem's donation-intact rule).
        new_opt = constrain_params(new_opt, mesh, param_rules)
        new_params = optax.apply_updates(state.params, updates)
        new_params = constrain_params(new_params, mesh, param_rules)
        aux = {
            "loss": loss,
            "grad_norm": grad_norm,
            "lr": schedule(state.step).astype(jnp.float32),
        }
        return (
            TrainState(
                params=new_params, opt_state=new_opt, step=state.step + 1
            ),
            aux,
        )

    return step_fn


def make_train_step(
    cfg: ExperimentConfig,
    tx: optax.GradientTransformation,
    mesh,
    param_rules=None,
):
    """The jitted, donated train step (parity: train.py:79-97)."""
    step_fn = _make_step_core(cfg, tx, mesh, param_rules)

    def wrapped(state, x, y, key):
        with axis_rules(mesh):
            new_state, aux = step_fn(state, x, y, key)
        return new_state, aux["loss"]

    return jax.jit(wrapped, donate_argnums=(0,))


def make_train_window(
    cfg: ExperimentConfig,
    tx: optax.GradientTransformation,
    mesh,
    k: int,
    param_rules=None,
):
    """K full optimizer steps fused into ONE jitted, state-donating
    ``lax.scan`` dispatch (cfg.steps_per_dispatch; PERF.md r5: a fixed
    +25-50 ms/step per-dispatch latency on the relay amortizes K-fold).

    Takes a device-resident window of K batches ``xs/ys [K, G, B, T]``
    and the run's base PRNG key; each scanned step derives its key as
    ``fold_in(key, state.step)`` — the same derivation the K=1 loop does
    host-side with the loop index, so the per-step key stream (and hence
    the loss sequence) is bit-identical to K=1. Per-step (loss, grad-norm,
    lr) come back STACKED ``[K]`` as scan outputs: logging stays per-step
    exact with zero extra host syncs (one device->host read per logging
    window, not per step)."""
    assert k >= 1, k
    step_fn = _make_step_core(cfg, tx, mesh, param_rules)

    def window_fn(state: TrainState, xs: Array, ys: Array, key: Array):
        # xs, ys: [K, G, B, T]
        with axis_rules(mesh):
            def body(s, xy):
                x, y = xy
                step_key = jax.random.fold_in(key, s.step)
                s2, aux = step_fn(s, x, y, step_key)
                return s2, aux

            state, stacked = jax.lax.scan(body, state, (xs, ys))
        return state, stacked  # each aux leaf stacked to [K]

    return jax.jit(window_fn, donate_argnums=(0,))


# ---------------------------------------------------------------------------
# Module-level window-program cache (the train-side inertness seam)
# ---------------------------------------------------------------------------

#: One jitted window program per (program-relevant config, mesh, K).
#: Mirrors the serving engine's module-level program cache: telemetry,
#: rundirs, logging cadence etc. are NOT part of the key, so two train
#: drives differing only in observability knobs resolve to the
#: ``is``-identical cached callable — which is how
#: tests/test_train_telemetry.py proves tracing cannot perturb the
#: dispatch pipeline (the serving inertness contract, mirrored).
_WINDOW_PROGRAMS: tp.Dict[tp.Tuple, tp.Any] = {}

#: ExperimentConfig fields that can NOT change the traced program:
#: paths, run length, logging/eval/ckpt cadence, seeds (keys are entry
#: arguments), and the observability knobs. Everything else — model,
#: batch geometry, optimizer hyperparameters (traced into the update),
#: dtypes, loss chunking, mesh config — is part of the key, and fields
#: added to the config later are conservatively included by default.
_NON_PROGRAM_FIELDS = (
    "rundir", "data_dir", "max_steps", "eval_interval", "eval_batches",
    "eval_fixed", "log_interval", "ckpt_interval", "ckpt_keep", "seed",
    "data_seed", "use_wandb", "debug", "steps_per_dispatch",
    "train_telemetry",
)


def _program_key(cfg: ExperimentConfig, mesh, k: int) -> tp.Tuple:
    d = {
        name: v for name, v in to_dict(cfg).items()
        if name not in _NON_PROGRAM_FIELDS
    }
    return (
        json.dumps(d, sort_keys=True),
        tuple(mesh.axis_names),
        tuple(mesh.devices.shape),
        tuple(dev.id for dev in mesh.devices.flat),
        int(k),
    )


def get_train_window(cfg: ExperimentConfig, mesh, k: int):
    """Memoized :func:`make_train_window`: one compile per (config
    geometry, mesh, K). Builds its own optimizer chain from ``cfg``
    (``make_optimizer`` — the only tx every in-repo caller uses), so a
    cache hit is exactly the program a fresh trace would produce.
    Callers with a custom ``tx`` must use :func:`make_train_window`
    directly."""
    key = _program_key(cfg, mesh, k)
    prog = _WINDOW_PROGRAMS.get(key)
    if prog is None:
        tx, _ = make_optimizer(cfg)
        prog = _WINDOW_PROGRAMS[key] = make_train_window(cfg, tx, mesh, k)
    return prog


def make_eval_step(cfg: ExperimentConfig, mesh):
    """Non-donating eval sweep (parity: train.py:99-103).

    Takes STACKED batches ``xs/ys [N, B, T]`` and returns their mean loss
    from one ``lax.scan`` — one dispatch per eval interval per split
    instead of N sequential jit calls (VERDICT r4 Weak #6: the old
    per-batch loop put ~200 dispatches per interval on the critical
    path; the sweep also lets XLA pipeline the batches back-to-back)."""
    compute_dtype = _dtype(cfg.compute_dtype)
    loss_chunk = _effective_loss_chunk(cfg, mesh)
    pp_mesh = mesh if cfg.mesh.pipeline > 1 else None

    def eval_fn(params: GPT, xs: Array, ys: Array) -> Array:
        with axis_rules(mesh):
            params_c = cast_floating(params, compute_dtype)

            from midgpt_tpu.parallel.sharding import shard_act

            def body(acc, xy):
                x, y = xy
                x = shard_act(x, "batch", "seq")
                y = shard_act(y, "batch", "seq")
                loss = loss_fn(
                    params_c, x, y, None, True, loss_chunk,
                    cfg.loss_chunk_unroll, pp_mesh, cfg.mesh.pp_microbatches,
                    cfg.mesh.pp_boundary_dtype, include_moe_aux=False,
                )
                return acc + loss, None

            total, _ = jax.lax.scan(
                body, jnp.zeros((), jnp.float32), (xs, ys)
            )
            return total / xs.shape[0]

    return jax.jit(eval_fn)


def init_state(
    cfg: ExperimentConfig, mesh, tx, key: Array, param_rules=None,
    abstract: bool = False,
) -> TrainState:
    """Init under jit with sharding constraints so params materialize
    directly sharded (parity: train.py:163-177).

    ``abstract=True`` returns the same pytree as sharding-annotated
    ``ShapeDtypeStruct``s without allocating any device buffers (the init
    program is compiled, never executed) — enough to ``.lower()`` the
    train step for the HLO audit without paying full-size params + Adam
    moments in HBM."""
    if param_rules is None:
        param_rules = _cfg_param_rules(cfg)

    def init_fn(k):
        model = GPT.init(k, cfg.model)
        model = constrain_params(model, mesh, param_rules)
        # same explicit shardings the train step constrains the updated
        # opt state to — donation requires input/output shardings to match
        opt_state = constrain_params(tx.init(model), mesh, param_rules)
        return TrainState(
            params=model, opt_state=opt_state, step=jnp.zeros((), jnp.int32)
        )

    from contextlib import nullcontext

    with jax.sharding.use_mesh(mesh) if hasattr(jax.sharding, "use_mesh") else nullcontext():
        if abstract:
            shardings = jax.jit(init_fn).lower(key).compile().output_shardings
            shapes = jax.eval_shape(init_fn, key)
            return jax.tree.map(
                lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
                shapes, shardings,
            )
        return jax.jit(init_fn)(key)


def evaluate(
    eval_step, params: GPT, loader: Loader, mesh,
    n_batches: int, seed_offset: int = 0,
) -> float:
    """Mean loss over n_batches random batches (parity: train.py:107-117).

    All batches assemble host-side up front, transfer in one device_put
    pair, and sweep in ONE jitted scan call (make_eval_step) — the eval
    interval costs a single dispatch per split instead of n_batches.
    EVERY microbatch of each peeked batch feeds the sweep (the scan runs
    n_batches * G bodies), so the evaluated token count per interval
    matches the reference's full-batch eval (train.py:110-114; VERDICT r5
    Next #6 — the old sweep took ``x[0]`` and silently evaluated 1/G of
    the tokens when the eval loaders carried accumulation microbatches)."""
    spec = P(None, ("replica", "fsdp"), "sequence")
    pairs = [
        loader.peek(10_000_000 + seed_offset + i)  # disjoint from train steps
        for i in range(n_batches)
    ]
    # [n_batches * G, B, T]: microbatches are leading-axis scan bodies
    xs = np.concatenate([x for x, _ in pairs])
    ys = np.concatenate([y for _, y in pairs])
    xg = make_global_array(xs, mesh, spec)
    yg = make_global_array(ys, mesh, spec)
    return float(eval_step(params, xg, yg))


def _ckpt_items(state: TrainState) -> tp.Dict[str, tp.Any]:
    """The named checkpoint items for a TrainState (single source of truth
    for save AND restore templates)."""
    return {
        "params": state.params,
        "opt_state": state.opt_state,
        "extra": {"step": state.step},
    }


def estimate_hbm_fill(cfg: ExperimentConfig, n_devices: int,
                      hbm_bytes: int) -> float:
    """Estimated fraction of per-device HBM filled by f32 params + Adam
    state + remat='none' activations (the fit model behind
    resolve_auto_knobs; factored out so the threshold behavior is
    directly testable)."""
    m = cfg.model
    from midgpt_tpu.models.gpt import mlp_hidden_dim

    c, hkv = m.head_dim, m.kv_heads
    f = (m.n_head + 2 * hkv) * c
    mh = mlp_hidden_dim(m)
    hidden = 2 * mh if m.mlp == "swiglu" else mh
    mlp_mult = m.moe_experts if m.mlp == "moe" else 1
    per_layer_params = (
        m.n_embd * f + m.n_head * c * m.n_embd
        + mlp_mult * (3 if m.mlp == "swiglu" else 2) * m.n_embd * mh
    )
    n_params = m.n_layer * per_layer_params + 2 * m.vocab_size * m.n_embd
    state_bytes = n_params * 12  # f32 params + Adam m,v (donated step)

    # tokens are sharded over the DATA axes only (batch over replica*fsdp,
    # T over sequence); TP shards the hidden/head dims of each token's
    # activations instead (ADVICE r3: dividing by ALL devices undercounted
    # per-device activations by tensor_sz on TP meshes)
    try:
        pp_sz, rep_sz, fsdp_sz, seq_sz, tensor_sz = cfg.mesh.sizes(n_devices)
    except AssertionError:
        pp_sz, rep_sz, fsdp_sz, seq_sz, tensor_sz = (
            1, 1, max(1, n_devices), 1, 1,
        )
    data_shards = max(1, rep_sz * fsdp_sz * seq_sz)
    tokens_per_dev = cfg.microbatch_size * m.block_size / data_shards
    # each pipeline stage holds (and saves activations for) n_layer/pp
    per_token_act = (
        m.n_layer / max(1, pp_sz)
        * (4 * m.n_embd + (f + m.n_head * c + hidden) / max(1, tensor_sz))
        * 2
    )
    act_none = tokens_per_dev * per_token_act
    # params/optimizer state shard over the fsdp AND tensor axes
    # (GPT_PARAM_RULES)
    state_shards = max(1, fsdp_sz * tensor_sz)
    return (state_bytes / state_shards + act_none) / hbm_bytes


def resolve_auto_knobs(cfg: ExperimentConfig, n_devices: int,
                       hbm_bytes: tp.Optional[int] = None) -> ExperimentConfig:
    """Resolve remat="auto" / scan_unroll=0 into concrete perf knobs by a
    coarse HBM-fit estimate, so the shipped configs run at bench speed by
    default instead of remat=full (VERDICT r2 Weak #4; the measured ladder
    is in PERF.md: remat=none + fully-unrolled scan is 1.5-2.6x faster
    than remat=full whenever it fits).

    The estimate is deliberately coarse (donated train step ~= 12 bytes of
    persistent state per param + bf16 activations saved across the scan at
    remat=none); the thresholds are calibrated against the measured fit
    points on a 16G v5e: 124M B=24 none-ok, B=48 none-OOM, XL-L6 B=16
    none-ok, llama-L2 B=8 none-ok. Users can always pin the knobs."""
    m = cfg.model
    if m.remat != "auto" and m.scan_unroll != 0:
        return cfg

    if hbm_bytes is None:
        try:
            stats = jax.devices()[0].memory_stats() or {}
            hbm_bytes = int(stats.get("bytes_limit", 16e9))
        except Exception:  # pragma: no cover — backend without memory_stats
            hbm_bytes = int(16e9)

    remat = m.remat
    if remat == "auto":
        fill = estimate_hbm_fill(cfg, n_devices, hbm_bytes)
        # calibration on a 16G v5e (PERF.md r3): fill 0.77 (llama-L2 B=8)
        # runs at remat=none; fill 0.80 (124M B=48) fails to compile.
        # On OTHER chip classes (HBM far from the calibrated 16G) the
        # thresholds are an unmeasured extrapolation — lean OPTIMISTIC
        # there (+0.06 band): the first-step OOM step-down ladder
        # (exec_step) corrects a too-aggressive pick at the cost of one
        # recompile, while nothing ever corrects a too-conservative one
        # (VERDICT r4 Weak #7).
        margin = 0.0 if abs(hbm_bytes - 16e9) / 16e9 < 0.25 else 0.06
        if fill <= 0.78 + margin:
            remat = "none"
        elif fill <= 0.92 + margin:
            remat = "dots"
        else:
            remat = "full"
    unroll = m.scan_unroll
    if unroll == 0:
        if m.remat == "auto":
            # full unroll kills the DUS stacking + XLA remat-compression
            # copies (PERF.md r2), but only pays off with remat=none
            unroll = m.n_layer if remat == "none" else 1
        else:
            unroll = m.n_layer  # documented semantics: 0 = full unroll
    resolved = dataclasses.replace(
        cfg, model=dataclasses.replace(m, remat=remat, scan_unroll=unroll)
    )
    if jax.process_index() == 0 and (remat, unroll) != (m.remat, m.scan_unroll):
        fill = estimate_hbm_fill(cfg, n_devices, hbm_bytes)
        print(
            f"auto knobs: remat={remat} scan_unroll={unroll} "
            f"(est. fill {fill:.2f} of {hbm_bytes/1e9:.1f}G HBM)"
        )
    return resolved


def window_plan(first_step: int, max_steps: int, k: int) -> tp.List[int]:
    """Per-dispatch window sizes covering steps [first_step, max_steps).

    Windows align to the absolute K grid: a resume landing mid-grid (e.g.
    a K=1 checkpoint resumed with K=4) gets a shorter FIRST window so every
    later window start is a multiple of K — eval/ckpt intervals (validated
    multiples of K) then always land on window boundaries. The final
    window is shorter when max_steps is off-grid; steady state is
    ceil(steps / K) dispatches."""
    assert k >= 1, k
    plan = []
    s = first_step
    while s < max_steps:
        w = min(k - (s % k), max_steps - s)
        plan.append(w)
        s += w
    return plan


def train(cfg: ExperimentConfig) -> tp.Dict[str, float]:
    """The orchestrator (parity: train.py:127-225). Returns final metrics.

    Preemption-safe: on SIGTERM (the TPU-VM maintenance/preemption signal)
    the loop finishes the in-flight step, force-saves a checkpoint, and
    returns cleanly — resume loses at most one step instead of
    ``ckpt_interval`` steps. The reference's recovery story is
    restart-from-last-interval-checkpoint only (SURVEY.md 5.3)."""
    import signal

    assert cfg.rundir, "rundir required"
    # fail fast on eval/ckpt intervals misaligned with steps_per_dispatch
    # (before any mesh/data/compile work)
    cfg = resolve_dispatch_intervals(cfg)
    stop_requested = {"flag": False}
    prev_handler = None

    def _on_sigterm(signum, frame):
        stop_requested["flag"] = True

    try:
        prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:  # non-main thread (tests driving train() directly)
        prev_handler = None
    try:
        _remat_was_auto = cfg.model.remat == "auto"
        cfg = resolve_auto_knobs(cfg, jax.device_count())
        mesh = create_mesh(cfg.mesh)
        n_proc = jax.process_count()
        proc = jax.process_index()

        # per-process local batch (global batch split over processes)
        assert cfg.batch_size % (cfg.g_accum_iters * n_proc) == 0
        local_b = cfg.batch_size // (cfg.g_accum_iters * n_proc)
        t = cfg.model.block_size

        train_loader = Loader(
            shard=load_shard(os.path.join(cfg.data_dir, "train.bin"), proc, n_proc),
            block_size=t,
            batch_shape=(cfg.g_accum_iters, local_b),
            seed=cfg.data_seed,
            process_index=proc,
        )
        # eval loaders carry the FULL (g_accum, B) batch shape: evaluate()
        # feeds every microbatch through the single-dispatch sweep scan, so
        # the evaluated token count per interval is eval_batches * G * B * T
        # — statistically matching the reference's full-batch eval
        # (train.py:110-114)
        val_loader = Loader(
            shard=load_shard(os.path.join(cfg.data_dir, "val.bin"), proc, n_proc),
            block_size=t,
            batch_shape=(cfg.g_accum_iters, local_b),
            seed=cfg.data_seed,
            process_index=proc,
            stream=1,
        )
        train_eval_loader = Loader(
            shard=train_loader.shard,
            block_size=t,
            batch_shape=(cfg.g_accum_iters, local_b),
            seed=cfg.data_seed,
            process_index=proc,
            stream=2,
        )

        tx, schedule = make_optimizer(cfg)
        k_disp = cfg.steps_per_dispatch
        # K=1 keeps today's one-dispatch-per-step path and jitted step
        # object; K>1 runs fused windows built lazily per length (steady
        # state compiles one K-step program; an off-grid first/last window
        # compiles its own shorter one)
        train_step = make_train_step(cfg, tx, mesh) if k_disp == 1 else None

        def _get_window_prog(kk: int):
            # module-level cache: the program key excludes observability
            # knobs (telemetry, rundir, logging cadence), so repeated
            # drives share the identical jitted callable — and a remat
            # step-down (which edits cfg.model) lands on a fresh key
            # automatically
            return get_train_window(cfg, mesh, kk)

        eval_step = make_eval_step(cfg, mesh)

        # MoE router telemetry (VERDICT r5 Next #7): aux-loss value and
        # dropped-claim fraction once per eval interval. Routing collapse
        # is invisible in the loss curve (dropped tokens ride the
        # residual), so it gets its own metrics keys via MetricLogger.
        moe_stats_fn = None
        if cfg.model.mlp == "moe":
            compute_dtype = _dtype(cfg.compute_dtype)

            def _moe_stats(params, x):
                with axis_rules(mesh):
                    from midgpt_tpu.parallel.sharding import shard_act

                    params_c = cast_floating(params, compute_dtype)
                    return params_c.moe_stats(shard_act(x, "batch", "seq"))

            moe_stats_fn = jax.jit(_moe_stats)

        def moe_telemetry(step: int, params) -> tp.Dict[str, float]:
            """{"moe/aux", "moe/dropped_frac"} on one val microbatch; {}
            for dense models."""
            if moe_stats_fn is None:
                return {}
            x, _ = val_loader.peek(
                10_000_000 + (0 if cfg.eval_fixed else step)
            )
            xg = make_global_array(
                x[0], mesh, P(("replica", "fsdp"), "sequence")
            )
            from midgpt_tpu.utils.metrics import moe_router_metrics

            return moe_router_metrics(moe_stats_fn(params, xg))

        # resolve_auto_knobs' HBM-fit estimate is calibrated on one chip
        # class (PERF.md); when it over-reaches on an unmeasured chip, the
        # FIRST step OOMs — step down the remat ladder instead of crashing
        # (ADVICE r3). The first step is synced inside the guard so the
        # failure surfaces here (not at a later async host read); retry is
        # only attempted while the donated state buffers are still alive
        # (compile-time OOM raises before donation consumes them — a
        # runtime OOM that already ate the state re-raises with the
        # original error).
        _first_step_done = {"done": not _remat_was_auto}
        # window programs are compiled per LENGTH — each length's first
        # dispatch gets its own ladder guard (a short off-grid first
        # window succeeding must not disarm the guard for the bigger
        # full-K program, whose deeper batch window is what OOMs)
        _warm_window_lens: tp.Set[int] = set()

        def _try_remat_step_down(e, state) -> bool:
            """Shared OOM ladder for exec_step/exec_window: True after
            stepping cfg one rung down the remat ladder, False when the
            failure isn't a recoverable first-dispatch OOM (non-OOM error,
            ladder exhausted, or the donated state is already consumed)."""
            nonlocal cfg
            nxt = {"none": "dots", "dots": "full"}.get(cfg.model.remat)
            state_alive = not any(
                getattr(a, "is_deleted", lambda: False)()
                for a in (
                    jax.tree.leaves(state.params)
                    + jax.tree.leaves(state.opt_state)
                )
            )
            if (
                "RESOURCE_EXHAUSTED" not in str(e)
                or nxt is None
                or not state_alive
            ):
                return False
            if proc == 0:
                print(
                    f"first-step OOM at remat={cfg.model.remat}; "
                    f"retrying with remat={nxt}"
                )
            cfg = dataclasses.replace(
                cfg,
                model=dataclasses.replace(
                    cfg.model, remat=nxt, scan_unroll=1
                ),
            )
            return True

        def exec_step(state, xg, yg, k):
            nonlocal train_step
            if _first_step_done["done"]:
                return train_step(state, xg, yg, k)
            while True:
                try:
                    out = train_step(state, xg, yg, k)
                    jax.block_until_ready(out)
                    _first_step_done["done"] = True
                    return out
                except Exception as e:  # noqa: BLE001 — filtered in helper
                    if not _try_remat_step_down(e, state):
                        raise
                    train_step = make_train_step(cfg, tx, mesh)

        def exec_window(kk, state, xs, ys, k):
            if not _remat_was_auto or kk in _warm_window_lens:
                return _get_window_prog(kk)(state, xs, ys, k)
            while True:
                try:
                    out = _get_window_prog(kk)(state, xs, ys, k)
                    jax.block_until_ready(out)
                    _warm_window_lens.add(kk)
                    return out
                except Exception as e:  # noqa: BLE001 — filtered in helper
                    if not _try_remat_step_down(e, state):
                        raise
                    # the stepped-down cfg.model lands on a fresh cache
                    # key, so programs rebuild lazily; previously warm
                    # lengths re-guard too (their programs changed)
                    _warm_window_lens.clear()

        ckpt = Checkpointer(
            cfg.rundir,
            keep=cfg.ckpt_keep,
            save_interval_steps=(
                cfg.ckpt_interval if cfg.ckpt_interval is not None else cfg.eval_interval
            ),
            async_save=not cfg.debug,
        )
        # roofline context (analysis/traffic.train_floor_decomposition via
        # utils.metrics.train_floor): every logging step that carries
        # tokens_per_sec also carries step_ms, the HBM/compute floors and
        # attainment_frac = floor / measured — MFU's sibling, so the
        # logged series is self-interpreting against the hardware ceiling
        logger = MetricLogger(
            cfg.rundir, cfg, use_wandb=cfg.use_wandb,
            floor=train_floor(cfg, jax.device_count()),
        )

        # training-loop telemetry (midgpt_tpu.train_telemetry): lifecycle
        # tracing is opt-in (cfg.train_telemetry) and proc-0 only; the
        # anomaly monitors are ALWAYS on — they consume only scalars the
        # logging path already pulled to the host. Tracing is loop-side
        # exclusively: the jitted window resolves through
        # get_train_window's module-level cache, whose key excludes every
        # observability knob, so telemetry on/off selects the identical
        # cached callable (tests/test_train_telemetry.py).
        from midgpt_tpu.train_telemetry import (
            AnomalyMonitors,
            TrainTelemetry,
            chrome_trace_train,
        )

        _local_rundir = (
            cfg.rundir
            if cfg.rundir and not cfg.rundir.startswith("gs://")
            else None
        )
        tele = (
            TrainTelemetry() if cfg.train_telemetry and proc == 0 else None
        )
        monitors = AnomalyMonitors(
            telemetry=tele,
            flight_dir=_local_rundir if proc == 0 else None,
        )
        if tele is not None:
            tele.emit("run_start", step=0, t=time.perf_counter())

        def _report_trips(trips, metrics, step) -> None:
            """Shared trip reporting for the window and K=1 logging
            paths: flag the step's metrics row + proc-0 stderr-visible
            print (the monitors never raise — observe, don't decide)."""
            for trip in trips:
                metrics[f"anomaly/{trip['kind']}"] = 1.0
                if proc == 0:
                    print(
                        f"ANOMALY {trip['kind']} at step {step}: "
                        f"{trip['detail']}"
                    )

        def _finalize_tele(last_step: int) -> None:
            final["anomalies"] = len(monitors.trips)
            if tele is None:
                return
            tele.emit("run_end", step=last_step, t=time.perf_counter())
            if _local_rundir is not None:
                from midgpt_tpu.telemetry import write_json

                write_json(
                    os.path.join(_local_rundir, "train_timeline.json"),
                    chrome_trace_train(tele),
                )
                tele.flight_dump(
                    "run_end",
                    path=os.path.join(
                        _local_rundir, "train_telemetry.json"
                    ),
                )

        if ckpt.latest_step() is not None:
            # adapt to the checkpoint's actual MLP width BEFORE building any
            # state: configs with mlp_hidden=None saved under the old
            # fractional-width rule would otherwise resolve to the rounded
            # width and fail restore with a shape mismatch (ADVICE r3)
            from midgpt_tpu.models.gpt import pin_mlp_hidden_from_ckpt

            pinned = pin_mlp_hidden_from_ckpt(cfg.model, ckpt)
            if pinned is not cfg.model and proc == 0:
                print(f"restore: pinned mlp_hidden={pinned.mlp_hidden} "
                      "to match the checkpoint's stored width")
            cfg = dataclasses.replace(cfg, model=pinned)
        # fingerprint covers only fields that change the math/parameters —
        # runtime implementation knobs (kernel choice, remat, unroll) may vary
        # freely between save and resume; mlp_hidden is normalized to the
        # RESOLVED width so a pinned width and a ratio resolving to the same
        # width fingerprint identically. Checkpoints saved before the
        # normalization hashed the RAW mlp_hidden (usually None) — those
        # hashes are accepted on restore so old runs still resume.
        from midgpt_tpu.models.gpt import mlp_hidden_dim

        # moe_aux_weight is a pure TRAINING knob (no effect on the
        # parameter tree) — changing it must not block resume
        _impl_knobs = (
            "attn_impl", "norm_impl", "remat", "scan_unroll",
            "moe_aux_weight",
        )
        _fp_dict = {
            k: v for k, v in to_dict(cfg.model).items() if k not in _impl_knobs
        }
        _fp_dict["mlp_hidden"] = mlp_hidden_dim(cfg.model)
        fingerprint = config_fingerprint(_fp_dict)
        accepted_fingerprints = {fingerprint}
        for legacy_mh in {None, cfg.model.mlp_hidden}:
            accepted_fingerprints.add(
                config_fingerprint({**_fp_dict, "mlp_hidden": legacy_mh})
            )
        # forward-compat for fields added to ModelConfig after v1:
        # checkpoints hashed before a field existed lack it in their
        # fingerprint. Accept the stripped hash ONLY when the current
        # value equals the legacy-implicit default (so a run that
        # actually changes the architecture still fails loudly).
        _legacy_strips = []
        if cfg.model.mlp != "moe":
            # pre-r5 checkpoints predate every moe field (dense only)
            _legacy_strips.append(("moe_experts", "moe_capacity", "moe_top_k"))
        if cfg.model.moe_top_k == 1:
            # early-r5 checkpoints predate moe_top_k (implicitly 1)
            _legacy_strips.append(("moe_top_k",))
        for strip in _legacy_strips:
            _legacy = {k: v for k, v in _fp_dict.items() if k not in strip}
            accepted_fingerprints.add(config_fingerprint(_legacy))
            for legacy_mh in {None, cfg.model.mlp_hidden}:
                accepted_fingerprints.add(
                    config_fingerprint({**_legacy, "mlp_hidden": legacy_mh})
                )

        key = jax.random.PRNGKey(cfg.seed)
        state = init_state(cfg, mesh, tx, key)
        if proc == 0:
            n_params = count_params(state.params)
            print(f"parameters (non-embedding): {n_params/1e6:.2f}M")

        first_step = 0
        if ckpt.latest_step() is not None:
            items, meta = ckpt.restore(_ckpt_items(state))
            state = TrainState(
                params=items["params"],
                opt_state=items["opt_state"],
                step=items["extra"]["step"],
            )
            assert meta.get("model_fingerprint") in accepted_fingerprints, (
                "checkpoint was trained with a different model config"
            )
            train_loader.load_state_dict(meta["loader"])
            first_step = int(meta["step"]) + 1
            if tele is not None:
                tele.emit("resume", step=first_step, t=time.perf_counter())
            if proc == 0:
                print(f"resumed from step {meta['step']}")

        batch_spec = P(None, ("replica", "fsdp"), "sequence")
        # next batch is gathered + device_put on a background thread while the
        # current step runs (the reference pays this on the critical path,
        # train.py:203-207)
        if k_disp > 1:
            # window mode: the prefetch thread stacks each dispatch's K
            # batches into one [K, G, B, T] global array (leading window
            # axis unsharded) — a K-deep batch window resident in HBM
            plan = window_plan(first_step, cfg.max_steps, k_disp)
            window_spec = P(None, *batch_spec)
            prefetch = PrefetchLoader(
                train_loader,
                transform=lambda x, y: (
                    make_global_array(x, mesh, window_spec),
                    make_global_array(y, mesh, window_spec),
                ),
                window=k_disp,
                window_plan=plan,
            ).start()
        else:
            prefetch = PrefetchLoader(
                train_loader,
                transform=lambda x, y: (
                    make_global_array(x, mesh, batch_spec),
                    make_global_array(y, mesh, batch_spec),
                ),
            ).start()
        tokens_per_step = cfg.batch_size * t
        last_log_time, last_log_step = time.time(), first_step
        final: tp.Dict[str, float] = {}

        dispatch_count = 0
        ckpt_every = (
            cfg.ckpt_interval
            if cfg.ckpt_interval is not None
            else cfg.eval_interval
        )

        def _run_window_loop(state):
            """steps_per_dispatch > 1: one fused K-step dispatch per
            window. Interval handling happens at window granularity —
            window boundaries are exact optimizer-step boundaries, and
            eval/ckpt intervals were validated as multiples of K, so the
            eval/ckpt cadence lands exactly where the K=1 loop puts it."""
            nonlocal dispatch_count, last_log_time, last_log_step
            try:
                from tqdm import tqdm

                wbar = tqdm(
                    total=cfg.max_steps, initial=first_step,
                    disable=proc != 0,
                )
            except ImportError:  # pragma: no cover
                wbar = None
            w_start = first_step
            for wi, k_eff in enumerate(plan):
                if w_start % cfg.eval_interval == 0 or w_start == first_step:
                    n_eval = 1 if cfg.debug else cfg.eval_batches
                    eoff = 0 if cfg.eval_fixed else w_start
                    # evaluate() ends in a float() host read either way —
                    # the span's clock stamps add no sync
                    t_ev = time.perf_counter()
                    train_loss = evaluate(
                        eval_step, state.params, train_eval_loader, mesh,
                        n_eval, eoff,
                    )
                    val_loss = evaluate(
                        eval_step, state.params, val_loader, mesh, n_eval,
                        eoff,
                    )
                    if tele is not None:
                        tele.metrics.counter("evals").inc()
                        tele.span(
                            "eval_pause", step=w_start, t=t_ev,
                            dur=time.perf_counter() - t_ev,
                            batches=n_eval,
                        )
                    logger.log(
                        w_start,
                        {
                            "loss/train": train_loss,
                            "loss/val": val_loss,
                            **moe_telemetry(w_start, state.params),
                        },
                    )
                    final.update(
                        {"train_loss": train_loss, "val_loss": val_loss}
                    )

                # prefetch.next() is the loop's existing host block on the
                # loader queue; timing it classifies who owned the wait
                t_pf = time.perf_counter()
                xs, ys = prefetch.next()  # [k_eff, G, B, T] global arrays
                t_launch = time.perf_counter()
                if tele is not None:
                    tele.prefetch_wait(
                        step=w_start, t=t_pf, dur=t_launch - t_pf
                    )
                    tele.emit(
                        "window_launch", step=w_start, t=t_launch, k=k_eff
                    )
                    tele.metrics.counter("windows_dispatched").inc()
                    tele.metrics.counter("steps_completed").inc(k_eff)
                if (
                    cfg.debug and wi == 1
                    and not cfg.rundir.startswith("gs://")
                ):
                    # profile exactly one post-warmup window
                    with jax.profiler.trace(
                        os.path.join(cfg.rundir, "profile")
                    ):
                        state, wout = exec_window(k_eff, state, xs, ys, key)
                        jax.block_until_ready(wout["loss"])
                else:
                    state, wout = exec_window(k_eff, state, xs, ys, key)
                dispatch_count += 1
                w_end = w_start + k_eff - 1
                if wbar is not None:
                    wbar.update(k_eff)

                log_steps = [
                    s
                    for s in range(w_start, w_start + k_eff)
                    if s % cfg.log_interval == 0 and s > 0
                ]
                if log_steps:
                    # per-step (loss, grad-norm, lr) come out of the scan
                    # STACKED; they cross to the host once per logging
                    # window — no added syncs vs the K=1 loop
                    losses_h = np.asarray(wout["loss"])
                    lrs_h = np.asarray(wout["lr"])
                    gnorms_h = np.asarray(wout["grad_norm"])
                    now = time.time()
                    # THE existing device->host harvest read: the only
                    # place window wall time legitimately exists
                    t_harvest = time.perf_counter()
                    if tele is not None:
                        tele.emit(
                            "window_harvest", step=w_end, t=t_harvest,
                            k=k_eff,
                        )
                        tele.span(
                            "train_window", step=w_start, t=t_launch,
                            dur=t_harvest - t_launch, k=k_eff,
                        )
                    for s in log_steps:
                        i = s - w_start
                        loss_v = float(losses_h[i])
                        metrics = {
                            "loss/optimized": loss_v,
                            "lr": float(lrs_h[i]),
                            "grad_norm": float(gnorms_h[i]),
                        }
                        _report_trips(
                            monitors.observe_step(
                                s, loss_v, float(gnorms_h[i]), t=t_harvest
                            ),
                            metrics, s,
                        )
                        if s == log_steps[-1]:
                            # throughput is host-clocked: it exists at
                            # window, not step, granularity
                            tps = (
                                tokens_per_step
                                * (s - last_log_step)
                                / max(now - last_log_time, 1e-9)
                            )
                            last_log_time, last_log_step = now, s
                            metrics["tokens_per_sec"] = tps
                            metrics["mfu"] = mfu(
                                tps, cfg.model, jax.device_count()
                            )
                            final["tokens_per_sec"] = tps
                            final["mfu"] = metrics["mfu"]
                            _report_trips(
                                monitors.observe_throughput(
                                    s, tps, t=t_harvest
                                ),
                                metrics, s,
                            )
                        logger.log(s, metrics)
                        final["loss"] = loss_v
                    if wbar is not None and hasattr(wbar, "set_postfix"):
                        wbar.set_postfix(loss=f"{final['loss']:.3f}")

                if not cfg.debug and (
                    (wi == 0 and first_step == 0)
                    or (w_end + 1) % ckpt_every == 0
                    or stop_requested["flag"]
                ):
                    # window ends sit on the K grid, never on orbax's
                    # step % interval == 0 grid — interval saves are gated
                    # here (ckpt_every is a validated multiple of K) and
                    # forced through the manager. A SIGTERM force-save
                    # lands on the completed window: an exact step
                    # boundary, so resume replays nothing partially.
                    t_ck = time.perf_counter()
                    ckpt.save(
                        w_end,
                        _ckpt_items(state),
                        meta={
                            "step": w_end,
                            "loader": prefetch.state_dict(),
                            "model_fingerprint": fingerprint,
                            "config": to_dict(cfg),
                        },
                        force=True,
                    )
                    if tele is not None:
                        # async_save: dur covers the enqueue (exact only
                        # in cfg.debug's synchronous mode); the flush
                        # wait lands on the ckpt_wait span at close
                        tele.metrics.counter("ckpt_saves").inc()
                        tele.span(
                            "ckpt_save", step=w_end, t=t_ck,
                            dur=time.perf_counter() - t_ck,
                        )
                if stop_requested["flag"]:
                    if tele is not None:
                        tele.emit(
                            "interrupt", step=w_end, t=time.perf_counter()
                        )
                    if proc == 0:
                        print(f"SIGTERM: checkpointed step {w_end}, exiting")
                    final["interrupted_at"] = w_end
                    break
                w_start += k_eff
            if wbar is not None:
                wbar.close()
            return state

        if k_disp > 1:
            state = _run_window_loop(state)
            pbar = ()  # the per-step loop below is the K=1 path
        else:
            try:
                from tqdm import tqdm

                pbar = tqdm(
                    range(first_step, cfg.max_steps),
                    initial=first_step,
                    total=cfg.max_steps,
                    disable=proc != 0,
                )
            except ImportError:  # pragma: no cover
                pbar = range(first_step, cfg.max_steps)

        loss = None
        for itr in pbar:
            # evaluate whenever the interval hits — including step 0 and the
            # first step after a resume, so the loss series always has a
            # pre-training / post-restore point (parity: train.py:195-201)
            if itr % cfg.eval_interval == 0 or itr == first_step:
                n_eval = 1 if cfg.debug else cfg.eval_batches
                eoff = 0 if cfg.eval_fixed else itr
                t_ev = time.perf_counter()
                train_loss = evaluate(
                    eval_step, state.params, train_eval_loader, mesh, n_eval, eoff
                )
                val_loss = evaluate(eval_step, state.params, val_loader, mesh, n_eval, eoff)
                if tele is not None:
                    tele.metrics.counter("evals").inc()
                    tele.span(
                        "eval_pause", step=itr, t=t_ev,
                        dur=time.perf_counter() - t_ev, batches=n_eval,
                    )
                logger.log(
                    itr,
                    {
                        "loss/train": train_loss,
                        "loss/val": val_loss,
                        **moe_telemetry(itr, state.params),
                    },
                )
                final.update({"train_loss": train_loss, "val_loss": val_loss})

            t_pf = time.perf_counter()
            xg, yg = prefetch.next()
            t_launch = time.perf_counter()
            if tele is not None:
                tele.prefetch_wait(step=itr, t=t_pf, dur=t_launch - t_pf)
                tele.emit("window_launch", step=itr, t=t_launch, k=1)
                tele.metrics.counter("windows_dispatched").inc()
                tele.metrics.counter("steps_completed").inc()
            step_key = jax.random.fold_in(key, itr)

            if cfg.debug and itr == first_step + 1 and not cfg.rundir.startswith("gs://"):
                # profile exactly one post-warmup step (parity: train.py:205-211)
                with jax.profiler.trace(os.path.join(cfg.rundir, "profile")):
                    state, loss = exec_step(state, xg, yg, step_key)
                    jax.block_until_ready(loss)
            else:
                state, loss = exec_step(state, xg, yg, step_key)
            dispatch_count += 1

            if itr % cfg.log_interval == 0 and itr > 0:
                loss_v = float(loss)  # THE existing host read (K=1 path)
                t_harvest = time.perf_counter()
                now = time.time()
                tps = tokens_per_step * (itr - last_log_step) / max(now - last_log_time, 1e-9)
                last_log_time, last_log_step = now, itr
                metrics = {
                    "loss/optimized": loss_v,
                    "lr": float(schedule(itr)),
                    "tokens_per_sec": tps,
                    "mfu": mfu(tps, cfg.model, jax.device_count()),
                }
                if tele is not None:
                    tele.emit("window_harvest", step=itr, t=t_harvest, k=1)
                    tele.span(
                        "train_window", step=itr, t=t_launch,
                        dur=t_harvest - t_launch, k=1,
                    )
                # the K=1 path logs no grad_norm (it rides the window
                # scan outputs only) — the monitors skip that detector
                _report_trips(
                    monitors.observe_step(itr, loss_v, None, t=t_harvest)
                    + monitors.observe_throughput(itr, tps, t=t_harvest),
                    metrics, itr,
                )
                logger.log(itr, metrics)
                if hasattr(pbar, "set_postfix"):
                    pbar.set_postfix(
                        loss=f"{loss_v:.3f}",
                        tps=f"{tps:,.0f}",
                        mfu=f"{metrics['mfu']:.1%}",
                    )
                final["loss"] = loss_v
                final["tokens_per_sec"] = tps
                final["mfu"] = metrics["mfu"]

            if not cfg.debug:
                # force on preemption: the completed step becomes durable
                # even off the save interval (Checkpointer no-ops the force
                # when the interval save already owns this step)
                ckpt.save(
                    itr,
                    _ckpt_items(state),
                    meta={
                        "step": itr,
                        "loader": prefetch.state_dict(),
                        "model_fingerprint": fingerprint,
                        "config": to_dict(cfg),
                    },
                    force=stop_requested["flag"],
                )

            if stop_requested["flag"]:
                if tele is not None:
                    tele.emit("interrupt", step=itr, t=time.perf_counter())
                if proc == 0:
                    print(f"SIGTERM: checkpointed step {itr}, exiting")
                final["interrupted_at"] = itr
                break

        prefetch.stop()
        # steady-state launch count: ceil(steps / K) fused dispatches
        # (tested by tests/test_train_window.py)
        final["train_dispatches"] = dispatch_count
        if "interrupted_at" in final:
            # preempted: the in-loop force-save owns the last completed step;
            # a max_steps-1 save here would mislabel partial progress
            t_cw = time.perf_counter()
            ckpt.close()  # async-save flush: the real checkpoint wait
            if tele is not None:
                tele.span(
                    "ckpt_wait", step=int(final["interrupted_at"]),
                    t=t_cw, dur=time.perf_counter() - t_cw,
                )
            _finalize_tele(int(final["interrupted_at"]))
            logger.close()
            return final

        # final eval + forced save of the last completed step (max_steps - 1;
        # the in-loop convention is "meta step == completed itr")
        n_eval = 1 if cfg.debug else cfg.eval_batches
        final["val_loss"] = evaluate(
            eval_step, state.params, val_loader, mesh, n_eval,
            0 if cfg.eval_fixed else cfg.max_steps,
        )
        logger.log(cfg.max_steps, {"loss/val": final["val_loss"]})
        if (
            not cfg.debug
            and cfg.max_steps > first_step
            and ckpt.latest_step() != cfg.max_steps - 1  # in-loop save may own it
        ):
            t_ck = time.perf_counter()
            ckpt.save(
                cfg.max_steps - 1,
                _ckpt_items(state),
                meta={
                    "step": cfg.max_steps - 1,
                    "loader": prefetch.state_dict(),
                    "model_fingerprint": fingerprint,
                    "config": to_dict(cfg),
                },
                force=True,
            )
            if tele is not None:
                tele.metrics.counter("ckpt_saves").inc()
                tele.span(
                    "ckpt_save", step=cfg.max_steps - 1, t=t_ck,
                    dur=time.perf_counter() - t_ck,
                )
        t_cw = time.perf_counter()
        ckpt.close()  # async-save flush: the real checkpoint wait
        if tele is not None:
            tele.span(
                "ckpt_wait", step=cfg.max_steps, t=t_cw,
                dur=time.perf_counter() - t_cw,
            )
        _finalize_tele(cfg.max_steps)
        logger.close()
        return final
    finally:
        # restore the previous handler only once everything that must
        # complete under our protection (async checkpoint flush in
        # ckpt.close()) is done — a second SIGTERM mid-flush must not
        # kill the process through a prematurely restored default
        if prev_handler is not None:
            signal.signal(signal.SIGTERM, prev_handler)
