"""Sampling CLI (parity: /root/reference/sample.py).

    python sample.py --ckpt_dir=outputs/run [--start="text" | --start=FILE:f]
                     [--num_samples=3] [--max_new_tokens=200]
                     [--temperature=0.8] [--top_k=...] [--seed=0]

Loads config.json + the latest checkpoint from the rundir, tokenizes with
the dataset's meta.pkl char map if present else tiktoken GPT-2
(sample.py:143-159), and generates with the KV-cached sampler."""

from __future__ import annotations

import argparse
import json
import os
import pickle


def load_run_config(ckpt_dir: str):
    """Read <ckpt_dir>/config.json, via gcsfs for gs:// rundirs (parity:
    /root/reference/sample.py:39-46 — the reference switches to gcsfs when
    the dir is a bucket path; Checkpointer already handles gs:// itself)."""
    from midgpt_tpu.config import from_dict
    from midgpt_tpu.utils.fsio import open_path

    with open_path(os.path.join(ckpt_dir, "config.json")) as f:
        return from_dict(json.load(f))


def get_tokenizer(data_dir: str):
    meta_path = os.path.join(data_dir, "meta.pkl") if data_dir else ""
    if meta_path and os.path.exists(meta_path):
        with open(meta_path, "rb") as f:
            meta = pickle.load(f)
        stoi, itos = meta["stoi"], meta["itos"]
        # models whose vocab_size exceeds the charset (padded for MXU/TP
        # alignment) can emit unmapped ids when undertrained — render those
        # as U+FFFD instead of crashing the CLI
        return (
            lambda s: [stoi[c] for c in s],
            lambda ids: "".join(itos.get(int(i), "�") for i in ids),
        )
    try:
        import tiktoken

        enc = tiktoken.get_encoding("gpt2")
        return (
            lambda s: enc.encode(s, allowed_special={"<|endoftext|>"}),
            lambda ids: enc.decode([int(i) for i in ids]),
        )
    except Exception:
        # zero-egress fallback: raw token ids
        return (
            lambda s: [int(tok) for tok in s.split()],
            lambda ids: " ".join(str(int(i)) for i in ids),
        )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt_dir", required=True)
    ap.add_argument("--start", default="\n", help='prompt text or "FILE:path"')
    ap.add_argument("--num_samples", type=int, default=3)
    ap.add_argument("--max_new_tokens", type=int, default=200)
    ap.add_argument("--temperature", type=float, default=0.8)
    ap.add_argument("--top_k", type=int, default=None)
    ap.add_argument("--seed", type=int, default=0)
    def _positive_int(v: str) -> int:
        n = int(v)
        if n < 1:
            raise argparse.ArgumentTypeError("must be >= 1")
        return n

    ap.add_argument(
        "--chunk_len", type=_positive_int, default=64,
        help="decode chunk length (recent-KV buffer rows; perf knob)",
    )
    ap.add_argument(
        "--serve", action="store_true",
        help="route generation through the continuous-batching serving "
        "engine (midgpt_tpu.serving): paged KV + fused K-step decode "
        "dispatch; one request per sample, early exit at --eos_id. "
        "NOTE: the engine's context is capped at block_size (prompts "
        "crop to block_size - max_new_tokens; no sliding window)",
    )
    ap.add_argument(
        "--serve_window", type=_positive_int, default=8,
        help="decode steps fused per XLA dispatch in --serve mode",
    )
    ap.add_argument(
        "--serve_page_size", type=_positive_int, default=16,
        help="KV page size (tokens) in --serve mode",
    )
    ap.add_argument(
        "--serve_prefill_chunk", type=_positive_int, default=None,
        help="chunked-prefill chunk size (tokens) in --serve mode; "
        "default monolithic",
    )
    ap.add_argument(
        "--serve_spec", type=_positive_int, default=None,
        help="self-speculative decoding draft length in --serve mode "
        "(n-gram prompt-lookup drafts verified in one dispatch; argmax "
        "acceptance at --temperature 0, rejection-sampling acceptance "
        "at --temperature > 0 — same stream contract either way). "
        "Default off.",
    )
    ap.add_argument(
        "--serve_tp", type=_positive_int, default=None,
        help="tensor-parallel degree in --serve mode: restore + serve on "
        "a tensor-only mesh over the first N devices (column/row-"
        "parallel weights, KV pool sharded by whole KV heads, vocab-"
        "sharded logits). 1 forces the single-chip engine on a "
        "multi-chip host. Default: the config mesh itself when it is "
        "serving-compatible (no sequence/pipeline axes — fsdp/replica "
        "restore sharding is preserved), else a tensor-only mesh at "
        "the config's tensor degree.",
    )
    ap.add_argument(
        "--no_prefix_cache", action="store_true",
        help="disable prefix-cache page sharing in --serve mode",
    )
    ap.add_argument(
        "--quant", choices=("int8",), default=None,
        help="serve the int8 per-channel quantized weight path "
        "(midgpt_tpu.quant): restores a pre-quantized params_q8 item "
        "when the checkpoint has one (scripts/quantize_ckpt.py), else "
        "quantizes the restored bf16 params on the fly; dequant is "
        "fused into every matmul, halving the per-token weight stream",
    )
    ap.add_argument(
        "--eos_id", type=int, default=None,
        help="stop a request early at this token id (--serve mode only)",
    )
    from midgpt_tpu.utils.platform_pin import add_platform_arg, apply_platform

    add_platform_arg(ap)
    args = ap.parse_args()

    import jax

    apply_platform(args.platform)
    import jax.numpy as jnp
    import numpy as np

    from midgpt_tpu.checkpoint import Checkpointer
    from midgpt_tpu.pytree import cast_floating
    from midgpt_tpu.sampling import make_sampler

    cfg = load_run_config(args.ckpt_dir)

    ckpt = Checkpointer(args.ckpt_dir, save_interval_steps=1)
    from midgpt_tpu.quant import QUANT_ITEM, abstract_quantized

    # pre-quantized serving checkpoint (scripts/quantize_ckpt.py): restore
    # the params_q8 item — the int8 weights land directly, no f32 staging
    use_q8 = bool(args.quant) and ckpt.has_item(QUANT_ITEM)
    import dataclasses

    from midgpt_tpu.models.gpt import pin_mlp_hidden_from_ckpt

    if not use_q8:
        # pre-256-rounding checkpoints hold the legacy fractional SwiGLU
        # width — pin to whatever the checkpoint actually stores (no-op
        # otherwise). A params_q8 checkpoint has no "params" metadata to
        # read; quantize_ckpt.py pins the width into its config.json
        cfg = dataclasses.replace(
            cfg, model=pin_mlp_hidden_from_ckpt(cfg.model, ckpt)
        )

    # params-only restore: checkpoints store params / opt_state as separate
    # items, so sampling never materializes Adam moments (the reference
    # rebuilds a dummy optimizer just to match the tree, sample.py:111-131)
    def init_fn(key):
        from midgpt_tpu.models.gpt import GPT

        return GPT.init(key, cfg.model)

    item = QUANT_ITEM if use_q8 else "params"
    abstract_params = (
        abstract_quantized(cfg.model)
        if use_q8
        else jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    )

    # multi-chip: restore straight into mesh shardings and decode
    # distributed (the reference replicates fully, sample.py:177-182).
    # --serve --serve_tp N picks a tensor-only SERVING mesh over the
    # first N devices (the geometry ServingEngine shards its KV pool
    # and programs on); otherwise the config's training mesh is used as
    # before. The rules match quantized leaves too (same `.../weight`
    # paths, plus the explicit `.../scale` rules splitting each
    # per-channel scale vector with its weight's out dim)
    mesh = None
    if args.serve:
        from midgpt_tpu.serving import serving_meshes

        if args.serve_tp:
            # explicit TP degree: tensor-only mesh over the first N
            # devices (None when N == 1 — the single-chip engine)
            mesh = serving_meshes(tp_size=args.serve_tp)[0]
        elif jax.device_count() > 1:
            # default: the config mesh itself WHEN the engine can serve
            # on it (no sequence/pipeline axes — fsdp/replica restore
            # sharding is preserved, the engine tolerates those axes as
            # replicated/contraction-sharded); a training config with
            # sequence/pipeline parallelism falls back to a tensor-only
            # mesh at its tensor degree (there is nothing to
            # sequence-shard one decode token deep)
            from midgpt_tpu.parallel.mesh import create_mesh

            try:
                mesh = create_mesh(cfg.mesh)
            except (AssertionError, ValueError):
                mesh = None
            if mesh is not None and (
                mesh.shape.get("sequence", 1) > 1
                or mesh.shape.get("pipeline", 1) > 1
            ):
                tp_deg = (
                    cfg.mesh.tensor
                    if 1 <= cfg.mesh.tensor <= jax.device_count()
                    else 1
                )
                mesh = serving_meshes(tp_size=tp_deg)[0]
    elif jax.device_count() > 1:
        from midgpt_tpu.parallel.mesh import create_mesh

        try:
            mesh = create_mesh(cfg.mesh)
        except (AssertionError, ValueError):
            mesh = None  # config mesh doesn't fit this host's devices
    if mesh is not None:
        from midgpt_tpu.models.gpt import GPT_PARAM_RULES
        from midgpt_tpu.parallel.sharding import param_shardings

        shardings = param_shardings(mesh, abstract_params, GPT_PARAM_RULES)
        abstract_params = jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            abstract_params,
            shardings,
        )

    items, meta = ckpt.restore({item: abstract_params})
    model = items[item]
    print(
        f"restored step {meta['step']}"
        + (f" (pre-quantized {QUANT_ITEM})" if use_q8 else "")
        + f" from {args.ckpt_dir}"
    )

    encode, decode = get_tokenizer(cfg.data_dir)
    start = args.start
    if start.startswith("FILE:"):
        with open(start[5:]) as f:
            start = f.read()
    prompt = np.asarray(encode(start), dtype=np.int32)
    prompt = np.tile(prompt[None, :], (args.num_samples, 1))

    model = cast_floating(model, jnp.bfloat16)
    if args.quant:
        from midgpt_tpu.quant import is_quantized, quantize_model

        if not is_quantized(model):
            model = quantize_model(model)  # on-the-fly from a bf16 ckpt
    if args.serve:
        from midgpt_tpu.serving import generate_served

        outs = generate_served(
            model,
            [prompt[i] for i in range(args.num_samples)],
            args.max_new_tokens,
            eos_id=args.eos_id,
            temperature=args.temperature,
            top_k=args.top_k,
            window=args.serve_window,
            page_size=args.serve_page_size,
            prefix_cache=not args.no_prefix_cache,
            prefill_chunk=args.serve_prefill_chunk,
            speculate=args.serve_spec or 0,
            seed=args.seed,
            mesh=mesh,
        )
        for i in range(args.num_samples):
            print("-" * 40)
            print(start + decode(outs[i]))
        return
    sampler = make_sampler(
        args.max_new_tokens,
        mesh=mesh,
        temperature=args.temperature,
        top_k=args.top_k,
        chunk_len=args.chunk_len,
    )
    toks = sampler(model, jnp.asarray(prompt), jax.random.PRNGKey(args.seed))
    for i in range(args.num_samples):
        print("-" * 40)
        print(start + decode(np.asarray(toks[i])))


if __name__ == "__main__":
    main()
