"""CLI launcher (parity: /root/reference/launch.py).

    python launch.py --config=shakespeare_char [--rundir=...] [--debug]
                     [--multihost] [--set key=value ...]

Improvements over the reference: any ExperimentConfig field can be
overridden from the CLI with --set (dotted paths reach nested configs,
e.g. --set model.n_layer=4 mesh.tensor=2); config provenance is dumped to
<rundir>/config.json and verified on resume via a model fingerprint.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import time


def _parse_value(s: str):
    try:
        return json.loads(s)
    except json.JSONDecodeError:
        return s


def apply_overrides(cfg, overrides):
    """dotted-path replace on nested frozen dataclasses."""
    for item in overrides:
        path, _, raw = item.partition("=")
        assert _, f"--set expects key=value, got {item!r}"
        value = _parse_value(raw)
        keys = path.split(".")

        def rec(obj, keys):
            if len(keys) == 1:
                return dataclasses.replace(obj, **{keys[0]: value})
            return dataclasses.replace(
                obj, **{keys[0]: rec(getattr(obj, keys[0]), keys[1:])}
            )

        cfg = rec(cfg, keys)
    return cfg


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", required=True, help="named config")
    parser.add_argument("--rundir", default=None)
    parser.add_argument("--debug", action="store_true")
    parser.add_argument("--multihost", action="store_true")
    parser.add_argument(
        "--set", nargs="*", default=[], metavar="KEY=VALUE",
        help="config field overrides, dotted paths allowed",
    )
    from midgpt_tpu.utils.platform_pin import add_platform_arg, apply_platform

    add_platform_arg(parser)
    args = parser.parse_args()

    import jax

    apply_platform(args.platform)

    if args.multihost:
        jax.distributed.initialize()  # (parity: launch.py:22-23)

    from midgpt_tpu.config import get_config, to_json

    cfg = get_config(args.config)
    cfg = apply_overrides(cfg, args.set)

    rundir = args.rundir or cfg.rundir
    if not rundir:
        assert not args.multihost, "--multihost requires an explicit --rundir"
        rundir = os.path.join("outputs", time.strftime("%Y%m%d-%H%M%S"))
    cfg = dataclasses.replace(cfg, rundir=rundir, debug=args.debug or cfg.debug)

    if jax.process_index() == 0:
        from midgpt_tpu.utils.fsio import open_path

        with open_path(os.path.join(rundir, "config.json"), "w") as f:
            f.write(to_json(cfg))
        print(to_json(cfg))

    if args.multihost:
        from jax.experimental.multihost_utils import sync_global_devices

        sync_global_devices("config_written")  # (parity: launch.py:69-70)

    from midgpt_tpu.train import train

    final = train(cfg)
    if jax.process_index() == 0:
        print("final:", json.dumps(final))


if __name__ == "__main__":
    main()
