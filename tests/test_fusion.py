"""Scan-equivalence prover + dispatch/launch auditor tests
(analysis/fusion.py, analysis/dispatch.py — the sixth audit family).

The prover must (a) PASS on the shipped tree — the unrolled serving
programs are layer-homogeneous and the fused (``layer_scan="on"``) scan
bodies are op-for-op the per-layer traces — and (b) FAIL on injected
faults, mirroring test_choreo.py's re-injection style:

- a deliberately layer-HETEROGENEOUS model (one layer's arithmetic
  differs) must fail the homogeneity check — the precondition that
  makes the fold legal at all;
- a re-unrolled program must fail the "on" dispatch budget (zero byte
  movement, so only the launch structure sees it);
- a dtype drift that exists ONLY on the scan path (the class of bug a
  fused rewrite can introduce while the unrolled path stays green) must
  fail the scan-body ≡ per-layer trace equality.
"""

import jax
import jax.numpy as jnp
import pytest

from midgpt_tpu.analysis.budgets import (
    DISPATCH_BUDGETS,
    check_dispatch_budget,
    dispatch_budget_for,
)
from midgpt_tpu.analysis.fusion import layer_segments
from midgpt_tpu.analysis.harness import (
    audit_serving_dispatch,
    prove_scan_equivalence,
    serving_dispatch_reports,
)
from midgpt_tpu.models.gpt import Attention
from midgpt_tpu.serving import engine as engine_mod


@pytest.fixture(scope="module")
def healthy_report():
    return prove_scan_equivalence("openwebtext")


def _checks(report):
    return {c.name: c.ok for c in report.checks}


# ---------------------------------------------------------------------------
# the prover passes on the shipped tree
# ---------------------------------------------------------------------------


def test_prover_passes_on_current_tree(healthy_report):
    assert healthy_report.ok, "\n".join(
        f"{c.name}: {c.detail}"
        for c in healthy_report.checks
        if not c.ok
    )
    # every program contributes its full check set
    names = [c.name for c in healthy_report.checks]
    for prog in ("decode_window", "prefill_chunk", "verify"):
        assert any(n.startswith(prog) for n in names), prog


def test_prover_passes_on_quant_kv_kernel_cell():
    """The far corner of the cell matrix (int8 weights + int8 KV +
    Pallas kernel traces); the full 8-cell grid runs in the CI
    serving-choreo job via ``--fusion --precision both --kv-quant
    both``."""
    rep = prove_scan_equivalence(
        "openwebtext", quant=True, kv_quant=True, paged_kernel="pallas"
    )
    assert rep.ok, "\n".join(
        f"{c.name}: {c.detail}" for c in rep.checks if not c.ok
    )


def test_layer_segments_unit():
    proj = ("proj", ("bfloat16", "bfloat16"), ("float32",))
    a = ("add", ("float32", "float32"), ("float32",))
    m = ("mul", ("float32", "float32"), ("float32",))
    # 2 layers x 2 projs each + 1 head proj; identical layer bodies
    trace = [proj, a, proj, m, proj, a, proj, m, proj, a]
    segs = layer_segments(trace, 2)
    assert segs is not None and len(segs) == 2
    assert segs[0] == segs[1] == (proj, a, proj, m)
    # head/tail records outside the boundaries are excluded
    assert layer_segments([a] + trace, 2) == segs
    # non-dividing proj structure -> None (a failed check, never vacuous)
    assert layer_segments(trace[:-1] + [proj], 2) is None
    assert layer_segments([], 2) is None


# ---------------------------------------------------------------------------
# dispatch/launch auditor + budgets
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def dispatch_on():
    return serving_dispatch_reports("openwebtext", layer_scan="on")


@pytest.fixture(scope="module")
def dispatch_off():
    return serving_dispatch_reports("openwebtext", layer_scan="off")


def test_dispatch_budgets_pass_both_ways(dispatch_on, dispatch_off):
    for ls, reports in (("on", dispatch_on), ("off", dispatch_off)):
        for name, rep in reports.items():
            budget = dispatch_budget_for(name, ls)
            assert budget is not None, (name, ls)
            assert not check_dispatch_budget(rep, budget), (name, ls)


def test_fused_decode_window_structure(dispatch_on):
    """The fused decode window: ONE launch per K-token window, the
    layer loop as a scan of trip n_layer NESTED inside the window scan,
    one inlined layer body, zero host transfers."""
    rep = dispatch_on["decode_window"]
    assert rep.launches_per_window == 1
    assert rep.inlined_layer_bodies == 1
    assert rep.layer_scan_length == 2  # audit shrink depth
    assert rep.host_transfers == 0
    depths = {s.depth for s in rep.scans}
    assert depths == {0, 1}  # window scan at 0, layer scan inside
    layer = [s for s in rep.scans if s.is_layer_scan]
    assert len(layer) == 1 and layer[0].depth == 1


def test_unrolled_trace_fails_the_fused_budget(dispatch_off):
    """Re-unrolling the layer loop moves ZERO bytes (the byte budgets
    stay green) but flips the launch structure — the 'on' budget cells
    must catch exactly that."""
    for name, rep in dispatch_off.items():
        assert rep.layer_scan_length == 0
        bad = check_dispatch_budget(rep, DISPATCH_BUDGETS[(name, "on")])
        assert bad, name
        assert any("inlined_layer_bodies" in v for v in bad), bad
    # ... and a fused trace fails the 'off' cells symmetrically (a
    # half-migrated audit can't silently pass the wrong leg)
    fused = serving_dispatch_reports("openwebtext", layer_scan="on")
    assert check_dispatch_budget(
        fused["decode_window"], DISPATCH_BUDGETS[("decode_window", "off")]
    )


def test_dispatch_sees_callbacks_inside_cond_branches():
    """The host-transfer gate must not be blind to sub-jaxprs stored in
    TUPLE params: ``lax.cond``'s branches are a plain tuple of
    ClosedJaxprs, which a bare hasattr walk over params.values() skips
    — a callback hidden in a branch would pass the budget vacuously
    (caught in code review)."""
    from midgpt_tpu.analysis.dispatch import dispatch_report

    def traced(x):
        def branch(v):
            jax.debug.callback(lambda a: None, v)
            return v * 2.0

        return jax.lax.cond(x[0] > 0, branch, lambda v: v, x)

    cj = jax.make_jaxpr(traced)(jnp.zeros((2,), jnp.float32))
    rep = dispatch_report(cj, program="probe")
    assert rep.host_transfers >= 1


def test_audit_serving_dispatch_end_to_end():
    reports, violations = audit_serving_dispatch(
        "openwebtext", layer_scan="on"
    )
    assert set(reports) == {
        "decode_window", "prefill_chunk", "verify_program"
    }
    assert violations == []


# ---------------------------------------------------------------------------
# fault injection: a layer-heterogeneous model must fail homogeneity
# ---------------------------------------------------------------------------


def test_prover_catches_layer_heterogeneity(monkeypatch):
    """A model whose layers do NOT share one arithmetic (here: layer 1
    — the middle layer of the depth-3 trace — runs its attention output
    through an f32 round-trip) is not legally foldable; the homogeneity
    check must fail. The fault is injected the way a real regression
    would arrive: a depth-dependent special case inside the per-layer
    attention method."""
    orig = Attention.decode_paged_at

    def hetero(self, x, pool_k, pool_v, bt, rk, rv, layer, r, *a, **kw):
        out, rk, rv = orig(
            self, x, pool_k, pool_v, bt, rk, rv, layer, r, *a, **kw
        )
        if isinstance(layer, int) and layer == 1:
            out = out.astype(jnp.float32).astype(out.dtype)
        return out, rk, rv

    engine_mod._PROGRAM_CACHE.clear()
    monkeypatch.setattr(Attention, "decode_paged_at", hetero)
    try:
        rep = prove_scan_equivalence("openwebtext")
    finally:
        engine_mod._PROGRAM_CACHE.clear()
    assert not rep.ok
    checks = _checks(rep)
    assert checks[
        "decode_window: unrolled layers are homogeneous (full trace)"
    ] is False
    # the other two programs (their loops untouched) stay green
    assert checks[
        "prefill_chunk: unrolled layers are homogeneous (full trace)"
    ] is True


# ---------------------------------------------------------------------------
# fault injection: a scan-body-only dtype drift must fail trace equality
# ---------------------------------------------------------------------------


def test_prover_catches_scan_body_drift(monkeypatch):
    """A dtype drift that exists ONLY on the fused path — the scan body
    upcasts its input through f32 while the unrolled path stays exactly
    as shipped. The scan branch calls the same per-layer method on a
    [1, ...] per-layer pool view (the unrolled branch passes the full
    [L, ...] pool), which is where a fused-path-only regression would
    live; the scan-body ≡ per-layer equality must turn red."""
    orig = Attention.decode_paged_at

    def drifted(self, x, pool_k, pool_v, *a, **kw):
        out, rk, rv = orig(self, x, pool_k, pool_v, *a, **kw)
        if pool_k.shape[0] == 1:  # the scan body's per-layer view
            out = out.astype(jnp.float32).astype(out.dtype)
        return out, rk, rv

    engine_mod._PROGRAM_CACHE.clear()
    monkeypatch.setattr(Attention, "decode_paged_at", drifted)
    try:
        rep = prove_scan_equivalence("openwebtext")
    finally:
        engine_mod._PROGRAM_CACHE.clear()
    assert not rep.ok
    checks = _checks(rep)
    assert checks[
        "decode_window: scan body equals the per-layer trace "
        "(full segment)"
    ] is False
    # the unrolled trace is untouched: homogeneity stays green
    assert checks[
        "decode_window: unrolled layers are homogeneous (full trace)"
    ] is True
