"""The async streaming front door (midgpt_tpu.serving.frontdoor):
per-request token streams bit-identical to the synchronous loop across
the feature matrix, cancellation-safe teardown (allocator + PrefixIndex
invariants property-checked after every scheduler step; pages retire
cold so prefix hits survive; survivors bit-identical to a
never-submitted run), priority admission with a PROVEN aging starvation
bound, pre-dispatch deadline sheds (typed outcome, virtual clock),
awaitable defer backpressure, deterministic cluster tie-breaks, and the
chaos composition acceptance gate: cancel + crash + deadline-shed in
one scripted plan with replay-identical event sequences."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.config import ModelConfig
from midgpt_tpu.models.gpt import GPT
from midgpt_tpu.serving import (
    AdmissionRejected,
    AsyncFrontDoor,
    Cancelled,
    DeadlineExceeded,
    FaultPlan,
    PoolOverloaded,
    ServingCluster,
    ServingEngine,
    VirtualClock,
)

CFG = ModelConfig(
    block_size=64, vocab_size=96, n_layer=2, n_head=4, n_embd=32,
    dropout=0.0, attn_impl="naive", remat="none",
)

_KW = dict(
    slots=2, page_size=8, window=4, temperature=0.0,
    cache_dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def model():
    return GPT.init(jax.random.PRNGKey(0), CFG)


def _prompts(n, base_len=5, stride=3):
    return [
        np.asarray(
            jax.random.randint(
                jax.random.PRNGKey(100 + i), (base_len + stride * i,), 0,
                CFG.vocab_size,
            )
        )
        for i in range(n)
    ]


def run(coro):
    """Drive an async test body (no pytest-asyncio dependency)."""
    return asyncio.run(coro)


def _sync_refs(model, prompts, n_new=8, kw=None, seeds=None):
    eng = ServingEngine(model, **(kw or _KW))
    seeds = seeds if seeds is not None else list(range(len(prompts)))
    rids = [
        eng.submit(p, n_new, seed=s) for p, s in zip(prompts, seeds)
    ]
    fin = eng.run()
    return [list(map(int, fin[r].tokens)) for r in rids]


async def _drain_all(fd):
    while await fd.pump():
        pass


# ---------------------------------------------------------------------------
# VirtualClock
# ---------------------------------------------------------------------------


def test_virtual_clock():
    c = VirtualClock()
    assert c() == 0.0 and c() == 0.0  # tick=0: reads don't advance
    assert c.advance(2.5) == 2.5 and c() == 2.5
    t = VirtualClock(start=1.0, tick=0.5)
    assert t() == 1.0 and t() == 1.5  # tick: deterministic auto-advance


# ---------------------------------------------------------------------------
# Bit-identity: streams through the front door == the synchronous loop
# ---------------------------------------------------------------------------


def test_stream_tokens_match_sync_loop(model):
    """Manual-pump drive, default feature combo: every stream's tokens
    are bitwise the synchronous ``run()`` harvest, invariants checked
    after every scheduler round."""
    prompts = _prompts(4)
    refs = _sync_refs(model, prompts)

    async def go():
        eng = ServingEngine(model, **_KW)
        fd = AsyncFrontDoor(eng, check_invariants=True)
        streams = [
            await fd.submit(p, 8, seed=i) for i, p in enumerate(prompts)
        ]
        await _drain_all(fd)
        return streams

    streams = run(go())
    assert [s.tokens for s in streams] == refs
    assert [s.outcome for s in streams] == ["finished"] * 4


def test_background_driver_streams_match_sync_loop(model):
    """The real serving mode: background driver task (step in a worker
    thread), tokens consumed with ``async for`` — same streams."""
    prompts = _prompts(4)
    refs = _sync_refs(model, prompts)

    async def go():
        eng = ServingEngine(model, **_KW)
        async with AsyncFrontDoor(eng) as fd:
            streams = [
                await fd.submit(p, 8, seed=i)
                for i, p in enumerate(prompts)
            ]

            async def consume(st):
                return [t async for t in st]

            got = await asyncio.gather(*(consume(s) for s in streams))
        return got

    assert run(go()) == refs


_MATRIX = [
    # (prefix_cache, chunk, spec, kvq, layer_scan)
    (False, None, 0, None, "off"),
    (True, 8, 0, None, "off"),
    (True, 8, 4, None, "on"),
    (True, None, 4, "int8", "off"),
    (False, 8, 0, "int8", "on"),
]


@pytest.mark.slow
@pytest.mark.parametrize(
    "cache,chunk,spec,kvq,ls", _MATRIX,
    ids=["nocache", "cache-chunk", "chunk-spec-ls", "spec-kvq8",
         "nocache-chunk-kvq8-ls"],
)
def test_stream_identity_matrix(model, cache, chunk, spec, kvq, ls):
    """The acceptance bit-identity gate across cache x chunk x spec x
    kv-quant x layer_scan: front-door streams == synchronous loop."""
    kw = dict(
        _KW, prefix_cache=cache, prefill_chunk=chunk, speculate=spec,
        kv_quant=kvq, layer_scan=ls,
    )
    prompts = _prompts(5, base_len=5, stride=2)
    refs = _sync_refs(model, prompts, n_new=12, kw=kw)

    async def go():
        eng = ServingEngine(model, **kw)
        fd = AsyncFrontDoor(eng, check_invariants=True)
        streams = [
            await fd.submit(p, 12, seed=i) for i, p in enumerate(prompts)
        ]
        await _drain_all(fd)
        return streams

    streams = run(go())
    assert [s.tokens for s in streams] == refs


@pytest.mark.slow
def test_telemetry_inert_through_frontdoor(model):
    """Tracing through the front door changes nothing: identical
    streams with telemetry on vs off, and the traced run produced
    events (cancellation included in the taxonomy)."""
    prompts = _prompts(3)

    async def go(telemetry):
        eng = ServingEngine(model, telemetry=telemetry, **_KW)
        fd = AsyncFrontDoor(eng)
        streams = [
            await fd.submit(p, 8, seed=i) for i, p in enumerate(prompts)
        ]
        streams[1].cancel()
        await _drain_all(fd)
        return eng, [s.tokens for s in streams]

    eng_on, on = run(go(True))
    _, off = run(go(False))
    assert on == off
    kinds = {ev.kind for ev in eng_on.telemetry.events}
    assert "cancelled" in kinds


# ---------------------------------------------------------------------------
# Cancellation-safe teardown
# ---------------------------------------------------------------------------


def test_cancel_releases_slot_and_pages_cold(model):
    """Cancel mid-decode: the slot frees immediately at the boundary,
    the allocator identity holds, and the cancelled request's pages
    retired COLD — a follow-up request with the same prompt hits the
    prefix cache on them."""
    prompts = _prompts(2, base_len=17, stride=0)

    async def go():
        eng = ServingEngine(model, **_KW)
        fd = AsyncFrontDoor(eng, check_invariants=True)
        st = await fd.submit(prompts[0], 16, seed=0)
        for _ in range(3):
            await fd.pump()
        assert st.tokens, "request should be mid-decode"
        st.cancel()
        await fd.pump()
        assert st.outcome == "cancelled"
        assert eng._active_slots() == [], "slot must be reclaimed"
        assert eng.alloc.held_pages == 0
        assert eng.alloc.cached_pages > 0, "pages must retire cold"
        with pytest.raises(Cancelled):
            await st.result()
        # same prompt again: the cold pages serve prefix hits
        st2 = await fd.submit(prompts[0], 8, seed=0)
        await _drain_all(fd)
        assert st2.outcome == "finished"
        assert eng.prompt_tokens_cached > 0, (
            "prefix hits must survive the cancellation"
        )
        st3 = await fd.submit(prompts[0], 8, seed=0)  # idempotent cancel
        st3.cancel()
        st3.cancel()
        await _drain_all(fd)
        assert eng.stats()["cancelled_requests"] == 2
        return True

    assert run(go())


def _never_submitted_ref(model, kw, survivor_prompt, n_new):
    eng = ServingEngine(model, **kw)
    rid = eng.submit(survivor_prompt, n_new, seed=1)
    return list(map(int, eng.run()[rid].tokens))


def test_cancel_during_prefill_chunk(model):
    """Satellite: cancel a request midway through CHUNKED prefill (some
    chunks resident, prompt incomplete). Allocator + PrefixIndex
    invariants hold, and the co-scheduled survivor's stream is
    bit-identical to a run where the victim was never submitted."""
    kw = dict(_KW, prefill_chunk=4, prefill_budget=4)
    victim = _prompts(1, base_len=24, stride=0)[0]
    survivor = _prompts(2, base_len=7, stride=0)[1]
    ref = _never_submitted_ref(model, kw, survivor, 10)

    async def go():
        eng = ServingEngine(model, **kw)
        fd = AsyncFrontDoor(eng, check_invariants=True)
        v = await fd.submit(victim, 8, seed=0)
        s = await fd.submit(survivor, 10, seed=1)
        await fd.pump()  # victim admitted, one 4-token chunk resident
        vs = [
            sl for sl in eng._active_slots()
            if eng.slot_req[sl].rid == v.rid
        ]
        assert vs and eng.prefilling[vs[0]], (
            "victim must be mid-prefill when cancelled"
        )
        v.cancel()
        await _drain_all(fd)
        assert v.outcome == "cancelled" and v.tokens == []
        assert s.outcome == "finished"
        assert eng.alloc.held_pages == 0
        eng.alloc.check()
        eng.index.check(eng.alloc)
        return s.tokens

    assert run(go()) == ref


@pytest.mark.slow
def test_cancel_mid_verify_dispatch(model):
    """Satellite: cancel a SPECULATING request between verify
    dispatches (drafts pending, carried logits live). The write
    watermark already rolled back rejected rows, so teardown leaves the
    allocator identity and the index single-writer/refcount invariants
    intact; the survivor matches a never-submitted run bit for bit and
    the victim's partial stream is a prefix of its solo reference."""
    kw = dict(_KW, speculate=4)
    prompts = _prompts(2, base_len=9, stride=2)
    ref_survivor = _never_submitted_ref(model, kw, prompts[1], 12)
    solo_victim = _sync_refs(model, [prompts[0]], n_new=12, kw=kw,
                             seeds=[0])[0]

    async def go():
        eng = ServingEngine(model, **kw)
        fd = AsyncFrontDoor(eng, check_invariants=True)
        v = await fd.submit(prompts[0], 12, seed=0)
        s = await fd.submit(prompts[1], 12, seed=1)
        while not v.tokens:
            await fd.pump()
        assert eng.verify_dispatches >= 1, "must be mid-speculation"
        v.cancel()
        await _drain_all(fd)
        assert v.outcome == "cancelled"
        assert s.outcome == "finished"
        assert eng.alloc.held_pages == 0
        eng.alloc.check()
        eng.index.check(eng.alloc)
        return v.tokens, s.tokens

    v_toks, s_toks = run(go())
    assert s_toks == ref_survivor
    assert v_toks == solo_victim[: len(v_toks)] and v_toks


def test_cancel_queued_and_parked(model):
    """Cancelling work that never reached a slot: a queued request
    leaves the queue; a parked request leaves the parking lot — both
    typed, counted, and invariant-clean."""

    async def go():
        eng = ServingEngine(model, **_KW)
        fd = AsyncFrontDoor(eng, check_invariants=True)
        a = await fd.submit(_prompts(1)[0], 8, seed=0)
        b = await fd.submit(_prompts(2)[1], 8, seed=1)
        c = await fd.submit(_prompts(3)[2], 8, seed=2)
        # nothing stepped yet: c is queued; cancel applies immediately
        c.cancel()
        assert c.outcome == "cancelled" and not any(
            r.rid == c.rid for r in eng.queue
        )
        # park b manually through the engine's own path, then cancel it
        await fd.pump()
        await _drain_all(fd)
        assert a.outcome == "finished" and b.outcome == "finished"
        assert eng.stats()["cancelled_requests"] == 1
        return True

    assert run(go())


# ---------------------------------------------------------------------------
# Priority + deadline admission
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_priority_dispatch_order(model):
    """With one slot, a high-priority later submission dispatches
    before a low-priority earlier one (fresh band is priority-ordered),
    while default priorities keep exact FIFO."""

    async def go():
        eng = ServingEngine(model, slots=1, page_size=8, window=4,
                            temperature=0.0, cache_dtype=jnp.float32)
        fd = AsyncFrontDoor(eng)
        filler = await fd.submit(_prompts(1)[0], 4, seed=0)
        lo = await fd.submit(_prompts(2)[1], 4, seed=1, priority=0)
        hi = await fd.submit(_prompts(3)[2], 4, seed=2, priority=5)
        order = []

        async def consume(name, st):
            async for _ in st:
                pass
            order.append(name)

        fd.start()
        await asyncio.gather(
            consume("filler", filler), consume("lo", lo),
            consume("hi", hi),
        )
        await fd.close()
        return order

    order = run(go())
    assert order.index("hi") < order.index("lo"), order


def test_aging_starvation_bound(model):
    """The adversarial starvation gate: slots=1, a fresh priority-10
    request arrives EVERY scheduler step, and a priority-0 request must
    still dispatch within the provable bound — with priority_aging=1.0
    its effective priority outranks every fresh arrival after 10 queued
    steps (ties break oldest-first), so it must be running by a small
    constant past that."""
    bound_steps = 10 + 6  # (P_hi - P_lo) / aging + slot-turnover slack

    async def go():
        eng = ServingEngine(model, slots=1, page_size=8, window=4,
                            temperature=0.0, cache_dtype=jnp.float32,
                            priority_aging=1.0)
        fd = AsyncFrontDoor(eng)
        flood_prompts = _prompts(6, base_len=5, stride=0)
        low = await fd.submit(_prompts(1)[0], 4, seed=0, priority=0)
        admitted_at = None
        for step in range(40):
            await fd.submit(
                flood_prompts[step % 6], 4, seed=step + 1, priority=10
            )
            await fd.pump()
            if admitted_at is None and (
                low.tokens
                or any(
                    eng.slot_req[s].rid == low.rid
                    for s in eng._active_slots()
                )
            ):
                admitted_at = step + 1
                break
        return admitted_at

    admitted_at = run(go())
    assert admitted_at is not None and admitted_at <= bound_steps, (
        f"low-priority request starved: not dispatched within "
        f"{bound_steps} steps (got {admitted_at})"
    )


def test_deadline_shed_before_dispatch(model):
    """A queued request whose deadline passes (virtual clock) is shed
    BEFORE any dispatch: typed outcome, counter, zero tokens, event
    recorded; in-flight requests are never shed mid-decode."""

    async def go():
        clk = VirtualClock()
        eng = ServingEngine(model, slots=1, page_size=8, window=4,
                            temperature=0.0, cache_dtype=jnp.float32,
                            clock=clk, telemetry=True)
        fd = AsyncFrontDoor(eng, check_invariants=True)
        a = await fd.submit(_prompts(1)[0], 8, seed=0, deadline_s=100.0)
        b = await fd.submit(_prompts(2)[1], 8, seed=1, deadline_s=5.0)
        await fd.pump()  # a admitted (slots=1), b queued
        clk.advance(10.0)  # b expires queued; a's SLO still holds
        await _drain_all(fd)
        assert a.outcome == "finished"
        assert b.outcome == "expired" and b.tokens == []
        with pytest.raises(DeadlineExceeded):
            await b.result()
        st = eng.stats()
        assert st["deadline_shed_requests"] == 1
        assert st["cancelled_requests"] == 0
        kinds = [ev.kind for ev in eng.telemetry.events]
        assert "deadline_shed" in kinds
        assert b.rid in eng.expired
        return True

    assert run(go())


def test_unpark_sheds_expired_and_keeps_priority_order(model):
    """Satellite (the old FIFO ``_unpark``): a parked request past its
    deadline sheds ON RELEASE (counted + evented) instead of
    re-queuing, and released survivors re-enter through the priority
    selector rather than blind FIFO."""
    clk = VirtualClock()
    eng = ServingEngine(model, slots=1, page_size=8, window=4,
                        temperature=0.0, cache_dtype=jnp.float32,
                        clock=clk, telemetry=True)
    expired = eng.lookup(eng.submit(_prompts(1)[0], 8, deadline_s=5.0))
    alive = eng.lookup(eng.submit(_prompts(2)[1], 8, deadline_s=100.0))
    # park both through the engine's own bookkeeping (progress-free
    # park, as the livelock guard would)
    eng.queue.clear()
    expired.evictions = alive.evictions = 1
    eng.parked.extend([expired, alive])
    clk.advance(10.0)
    eng._unpark()
    assert [r.rid for r in eng.queue] == [alive.rid]
    assert expired.outcome == "expired"
    assert eng.stats()["deadline_shed_requests"] == 1
    ev = [e for e in eng.telemetry.events if e.kind == "deadline_shed"]
    assert ev and ev[0].data.get("where") == "parked"
    # released survivors ride the resumed band: a later fresh
    # high-priority submission does NOT overtake them
    fresh_rid = eng.submit(_prompts(3)[2], 8, priority=99)
    qi = eng._select_queued()
    assert eng.queue[qi].rid == alive.rid, (
        "resumed (progress-holding) work must outrank fresh submissions"
    )
    assert fresh_rid != alive.rid


def test_backpressure_defer_awaits_and_shed_raises(model):
    """PR 10's overload outcomes through the front door: defer =
    SUSPENDED submission that completes once the queue drains (the
    awaitable retry-after), shed = immediate typed raise."""

    async def go():
        eng = ServingEngine(model, slots=1, page_size=8, window=4,
                            temperature=0.0, cache_dtype=jnp.float32,
                            max_queue=1, overload_policy="defer")
        fd = AsyncFrontDoor(eng)
        s1 = await fd.submit(_prompts(1)[0], 4, seed=0)
        await fd.pump()  # s1 takes the slot; the queue is empty again
        t2 = asyncio.create_task(fd.submit(_prompts(2)[1], 4, seed=1))
        await asyncio.sleep(0)
        t3 = asyncio.create_task(fd.submit(_prompts(3)[2], 4, seed=2))
        await asyncio.sleep(0)
        # t2 filled the queue; t3 must be suspended on backpressure
        assert t2.done() and not t3.done(), "defer must suspend, not raise"
        deferred_before = eng.stats()["deferred_submits"]
        for _ in range(60):
            await fd.pump()
            if t3.done():
                break
        s3 = await t3
        await _drain_all(fd)
        assert deferred_before >= 1
        assert [s1.outcome, (await t2).outcome, s3.outcome] == (
            ["finished"] * 3
        )
        # raise mode surfaces the typed outcome instead of waiting
        eng2 = ServingEngine(model, slots=1, page_size=8, window=4,
                             temperature=0.0, cache_dtype=jnp.float32,
                             max_queue=1, overload_policy="defer")
        fd2 = AsyncFrontDoor(eng2, backpressure="raise")
        await fd2.submit(_prompts(1)[0], 4, seed=0)
        await fd2.pump()  # first request into the slot
        await fd2.submit(_prompts(2)[1], 4, seed=1)
        with pytest.raises(PoolOverloaded):
            await fd2.submit(_prompts(3)[2], 4, seed=2)
        # shed policy: AdmissionRejected raises through either mode
        eng3 = ServingEngine(model, slots=1, page_size=8, window=4,
                             temperature=0.0, cache_dtype=jnp.float32,
                             max_queue=1, overload_policy="shed")
        fd3 = AsyncFrontDoor(eng3)
        await fd3.submit(_prompts(1)[0], 4, seed=0)
        await fd3.pump()
        await fd3.submit(_prompts(2)[1], 4, seed=1)
        with pytest.raises(AdmissionRejected):
            await fd3.submit(_prompts(3)[2], 4, seed=2)
        await _drain_all(fd2)
        await _drain_all(fd3)
        return True

    assert run(go())


# ---------------------------------------------------------------------------
# Cluster: deterministic tie-breaks + cancellation routing
# ---------------------------------------------------------------------------


def test_cluster_tiebreak_deterministic_and_placement_pinned(model):
    """Satellite: least-loaded admission tie-breaks are deterministic
    (equal load -> lowest replica index), so a trace's placement
    replays identically through the front door — pinned by routing the
    same trace twice and comparing every route."""
    prompts = _prompts(6, base_len=4, stride=1)

    def routes():
        cl = ServingCluster(model, replicas=3, **_KW)
        for i, p in enumerate(prompts):
            cl.submit(p, 6, seed=i)
        return [cl._route[g][0] for g in sorted(cl._route)]

    r1, r2 = routes(), routes()
    assert r1 == r2, "placement must be replay-deterministic"
    # equal-load start: the first three go 0, 1, 2 by the lowest-index
    # tie-break, round-robin while loads stay equal
    assert r1[:3] == [0, 1, 2], r1


@pytest.mark.slow
def test_cluster_cancel_routes_to_owner(model):
    """Cluster-global cancellation follows the route to the owning
    replica; terminal dicts mirror at cluster level and the route
    drops (no later failover can re-serve cancelled work)."""

    async def go():
        cl = ServingCluster(model, replicas=2, **_KW)
        fd = AsyncFrontDoor(cl, check_invariants=True)
        prompts = _prompts(4)
        streams = [
            await fd.submit(p, 16, seed=i)
            for i, p in enumerate(prompts)
        ]
        while not streams[2].tokens:
            await fd.pump()
        streams[2].cancel()
        await _drain_all(fd)
        assert streams[2].outcome == "cancelled"
        assert streams[2].rid in cl.cancelled
        assert streams[2].rid not in cl._route
        assert [streams[i].outcome for i in (0, 1, 3)] == (
            ["finished"] * 3
        )
        assert cl.stats()["cancelled_requests"] == 1
        return True

    assert run(go())


# ---------------------------------------------------------------------------
# The chaos composition acceptance gate
# ---------------------------------------------------------------------------


def _frontdoor_chaos_run(model, prompts, plan, cancel_at, deadline_s):
    """One deterministic front-door chaos drive: submissions before any
    step, scripted cancels keyed to harvested token counts, deadlines
    on a shared virtual clock advanced one unit per pump."""

    async def go():
        clk = VirtualClock()
        cl = ServingCluster(
            model, replicas=3, fault_plan=plan, telemetry=True,
            clock=clk, backoff_s=0.0, max_retries=2, **_KW,
        )
        fd = AsyncFrontDoor(cl, check_invariants=True)
        streams = []
        for i, p in enumerate(prompts):
            streams.append(await fd.submit(
                p, 8, seed=i,
                deadline_s=deadline_s.get(i),
                priority=i % 2,
            ))
        cancelled = set()
        for _ in range(200):
            alive = await fd.pump()
            clk.advance(1.0)
            for i, at in cancel_at.items():
                if i not in cancelled and len(streams[i].tokens) >= at:
                    streams[i].cancel()
                    cancelled.add(i)
            if not alive:
                break
        assert all(s.outcome is not None for s in streams), [
            s.outcome for s in streams
        ]
        sigs = tuple(
            t.sequence_signature() for t in cl.telemetries
            if t is not None
        )
        return streams, cl, sigs

    return run(go())


def test_chaos_cancel_crash_deadline_composite(model):
    """Acceptance: one scripted plan drives a replica crash while
    cancellations and deadline sheds flow through the front door.
    Untouched survivors stay bit-identical to the fault-free
    synchronous run, cancelled streams are exact prefixes, expired
    requests emit nothing after shed, and the whole composition
    REPLAYS with identical per-replica event sequences."""
    prompts = _prompts(6, base_len=5, stride=2)
    refs = _sync_refs(model, prompts)
    plan = FaultPlan.parse("2:crash@0")
    cancel_at = {1: 2}          # cancel stream 1 after 2 tokens
    deadline_s = {4: 3.0}       # stream 4 expires while queued/evicted

    first = _frontdoor_chaos_run(model, prompts, plan, cancel_at,
                                 deadline_s)
    streams, cl, sigs = first
    outcomes = [s.outcome for s in streams]
    assert outcomes[1] == "cancelled"
    for i, s in enumerate(streams):
        if s.outcome == "finished":
            assert s.tokens == refs[i], f"survivor {i} diverged"
        elif s.outcome == "cancelled":
            assert s.tokens == refs[i][: len(s.tokens)]
        else:
            assert s.outcome == "expired"
            assert s.tokens == refs[i][: len(s.tokens)]
    assert "dead" in cl.health
    st = cl.stats()
    assert st["cancelled_requests"] >= 1
    assert st["failovers"] >= 1
    for i in cl._alive():
        cl.engines[i].alloc.check()

    # replay: same plan, same trace, same cancel/deadline script —
    # identical outcomes, streams, AND event sequences
    streams2, cl2, sigs2 = _frontdoor_chaos_run(
        model, prompts, plan, cancel_at, deadline_s
    )
    assert [s.outcome for s in streams2] == outcomes
    assert [s.tokens for s in streams2] == [s.tokens for s in streams]
    assert sigs2 == sigs, (
        "chaos + cancel + deadline replay must reproduce every "
        "replica's event sequence exactly"
    )
    assert cl2.health == cl.health


@pytest.mark.slow
def test_chaos_composite_matrix_cache_chunk_spec(model):
    """Slow tier: the same cancel + crash + deadline composition over
    the cache+chunk+spec feature combo."""
    kw = dict(_KW, prefill_chunk=8, speculate=4)
    prompts = _prompts(6, base_len=5, stride=2)
    refs = _sync_refs(model, prompts, kw=kw)
    plan = FaultPlan.parse("2:crash@0")

    async def go():
        clk = VirtualClock()
        cl = ServingCluster(
            model, replicas=3, fault_plan=plan, telemetry=True,
            clock=clk, backoff_s=0.0, max_retries=2, **kw,
        )
        fd = AsyncFrontDoor(cl, check_invariants=True)
        streams = [
            await fd.submit(p, 8, seed=i, deadline_s=(
                3.0 if i == 4 else None
            ))
            for i, p in enumerate(prompts)
        ]
        cancelled = False
        for _ in range(200):
            alive = await fd.pump()
            clk.advance(1.0)
            if not cancelled and len(streams[1].tokens) >= 2:
                streams[1].cancel()
                cancelled = True
            if not alive:
                break
        return streams

    streams = run(go())
    for i, s in enumerate(streams):
        assert s.outcome is not None
        if s.outcome == "finished":
            assert s.tokens == refs[i], f"survivor {i} diverged"
        else:
            assert s.tokens == refs[i][: len(s.tokens)]
    assert streams[1].outcome == "cancelled"


# ---------------------------------------------------------------------------
# Stats façade
# ---------------------------------------------------------------------------


def test_frontdoor_stats(model):
    async def go():
        eng = ServingEngine(model, **_KW)
        fd = AsyncFrontDoor(eng)
        await fd.submit(_prompts(1)[0], 4, seed=0)
        await _drain_all(fd)
        st = fd.stats()
        assert st["frontdoor_steps"] == fd.steps >= 1
        assert st["frontdoor_live_streams"] == 0
        assert st["cancelled_requests"] == 0
        assert st["deadline_shed_requests"] == 0
        return True

    assert run(go())
