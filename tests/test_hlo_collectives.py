"""Compiled-HLO collective audit for the shipped sharded configs.

SURVEY.md §7 lists "verifying with compiler comms reports" as a hard part:
loss-parity dryruns prove the sharded step is *correct*, not that GSPMD
produced the intended collectives. These tests compile the real train step
(audit-shrunk layer/seq/vocab sizes, same mesh axes and code paths) on the
8-device CPU mesh and evaluate each config's declared ruleset from
``midgpt_tpu.analysis`` — the parsing/rule machinery itself has fast
fixture-based unit tests in test_analysis.py; what THESE tests pin is the
real compiled artifacts of the shipped configs:

- **No batch-dim all-gather of activations** in any sharded config. The
  known trap class: an opaque boundary (e.g. a bare ``pallas_call``)
  makes the partitioner gather the full batch onto every device. Feature
  -dim activation all-gathers are legitimate TP traffic and are allowed.
- **Multislice DCN contract** (SURVEY.md §2.6: DP-only across slices):
  cross-slice traffic is all-reduce-only, and the cross-slice gradient
  all-reduce must EXIST.
- **Ring attention** moves K/V by collective-permute hops, never by
  reconstituting the full sequence (SURVEY.md §5.7).
- **Donation sticks**: the donated train state is fully aliased
  input->output (the rule that caught the dropped Adam-moment donation).

Caveat: Mosaic kernels don't lower on CPU, so the pallas path itself is
exercised by the shard_map parity tests (test_fused_attn.py); this audit
guards the partitioner's output for everything GSPMD handles.
"""

import dataclasses

import pytest

from midgpt_tpu.analysis import MeshInfo, StepAnalysis, rules_for_config
from midgpt_tpu.analysis.harness import (
    analyze_train_step,
    compile_eval_sweep,
    shrink_for_audit,
)
from midgpt_tpu.analysis.rules import NoBatchAllGather
from midgpt_tpu.config import get_config


def _audit(cfg):
    """(analysis, report) for a config's audit-shrunk train step."""
    analysis = analyze_train_step(cfg)
    report = rules_for_config(cfg, analysis.mesh).evaluate(analysis)
    return analysis, report


def _assert_ok(report):
    assert report.ok, "\n".join(str(v) for v in report.violations)


@pytest.mark.slow
@pytest.mark.parametrize("name", ["openwebtext_xl", "llama_7b"])
def test_sharded_config_has_no_batch_allgather(name):
    cfg = get_config(name)
    analysis, report = _audit(cfg)
    assert analysis.mesh.shape["tensor"] == 4  # the shipped FSDP x TP shape
    _assert_ok(report)


@pytest.mark.slow
def test_ring_config_permutes_instead_of_gathering_seq():
    """A sequence-sharded ring-attention train step must move K/V with
    collective-permutes (the ring hops), never by all-gathering the full
    sequence onto every device — the anti-pattern ring attention exists
    to avoid (SURVEY.md §5.7). rules_for_config adds the ring rules
    (seq-permute-not-gather + expect-collective-permute) for this config."""
    cfg = get_config("openwebtext")
    cfg = dataclasses.replace(
        cfg,
        model=dataclasses.replace(cfg.model, attn_impl="ring"),
        mesh=dataclasses.replace(
            cfg.mesh, replica=1, fsdp=2, sequence=4, tensor=1
        ),
    )
    analysis, report = _audit(cfg)
    assert {r.rule for r in report.results} >= {
        "seq-permute-not-gather", "expect-collective-permute"
    }
    _assert_ok(report)


@pytest.mark.slow
def test_multislice_dcn_contract():
    cfg = get_config("openwebtext_xl_multislice")
    analysis, report = _audit(cfg)
    assert analysis.mesh.shape["replica"] == 2
    assert analysis.mesh.num_slices == 2
    assert {r.rule for r in report.results} >= {
        "dcn-allreduce-only", "cross-slice-grad-allreduce"
    }
    _assert_ok(report)


@pytest.mark.slow
def test_eval_sweep_has_no_batch_allgather():
    """The r5 eval sweep (make_eval_step: all eval batches through one
    lax.scan) must shard like the train step — a batch-dim gather inside
    the scan body would cost eval_batches x the train-step trap."""
    cfg = shrink_for_audit(get_config("openwebtext_xl"))
    hlo, mesh = compile_eval_sweep(cfg, n_eval=3)
    analysis = StepAnalysis.from_text(
        hlo,
        MeshInfo.from_mesh(mesh),
        global_batch=cfg.microbatch_size,
        block=cfg.model.block_size,
    )
    assert not NoBatchAllGather().check(analysis)


@pytest.mark.slow
def test_moe_ep_step_has_no_batch_allgather():
    """MoE under fsdp x tensor (expert parallelism): the one-hot
    dispatch/combine einsums must not make GSPMD gather full activations
    — batch stays sharded; the expert contraction's psum is the only
    intended cross-'tensor' traffic (rules_for_config adds the
    expect-all-reduce rule for MoE configs)."""
    cfg = get_config("openwebtext")
    cfg = dataclasses.replace(
        cfg,
        model=dataclasses.replace(
            cfg.model, mlp="moe", moe_experts=4, attn_impl="naive"
        ),
        mesh=dataclasses.replace(
            cfg.mesh, replica=1, fsdp=2, sequence=1, tensor=4
        ),
    )
    analysis, report = _audit(cfg)
    assert "expect-all-reduce" in {r.rule for r in report.results}
    _assert_ok(report)
