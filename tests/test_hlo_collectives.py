"""Compiled-HLO collective audit for the shipped sharded configs.

SURVEY.md §7 lists "verifying with compiler comms reports" as a hard part:
loss-parity dryruns prove the sharded step is *correct*, not that GSPMD
produced the intended collectives. These tests compile the real train step
(shrunk layer/seq/vocab sizes, same mesh axes and code paths) on the
8-device CPU mesh and parse ``.lower().compile().as_text()``:

- **No batch-dim all-gather of activations** in any sharded config. The
  known trap class: an opaque boundary (e.g. a bare ``pallas_call``)
  makes the partitioner gather the full batch onto every device. Feature
  -dim activation all-gathers are legitimate TP traffic and are allowed.
- **Multislice DCN contract** (SURVEY.md §2.6: DP-only across slices):
  every collective whose device group crosses the replica (slice) axis
  must be an all-reduce (gradient/loss sums) with no activation-shaped
  operand — FSDP/TP gathers and permutes must stay inside a slice. The
  cross-slice gradient all-reduce must also EXIST (a step with no
  replica sync at all would silently train divergent replicas).

Caveat: Mosaic kernels don't lower on CPU, so the pallas path itself is
exercised by the shard_map parity tests (test_fused_attn.py); this audit
guards the partitioner's output for everything GSPMD handles.
"""

import dataclasses
import re

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from midgpt_tpu.config import get_config
from midgpt_tpu.parallel.mesh import create_mesh
from midgpt_tpu.parallel.sharding import make_global_array
from midgpt_tpu.train import init_state, make_optimizer, make_train_step

BLOCK = 256
BATCH = 8

_COLL = re.compile(
    r"\b(all-gather|all-reduce|reduce-scatter|collective-permute|all-to-all)"
    r"(?:-start)?\("
)
_GROUPS = re.compile(
    r"replica_groups=(\{\{.*?\}\}|\[[^\]]*\]<=\[[^\]]*\](?:T\([^)]*\))?)"
)
_PAIRS = re.compile(r"source_target_pairs=(\{\{.*?\}\})")
_SHAPE = re.compile(r"[a-z0-9]+\[([0-9,]*)\]")
_DIMS = re.compile(r"dimensions=\{([0-9,]+)\}")


def _parse_groups(spec: str):
    """replica_groups / source_target_pairs -> list of device-id groups."""
    if spec.startswith("{{"):
        return [
            [int(x) for x in g.split(",") if x.strip() != ""]
            for g in re.findall(r"\{([0-9,]+)\}", spec)
        ]
    # iota form: [G,S]<=[N...] optionally with a transpose suffix
    m = re.match(r"\[([0-9,]+)\]<=\[([0-9,]+)\](T\(([0-9,]+)\))?", spec)
    assert m, f"unparsed replica_groups {spec!r}"
    gshape = [int(x) for x in m.group(1).split(",")]
    rshape = [int(x) for x in m.group(2).split(",")]
    ids = np.arange(int(np.prod(rshape))).reshape(rshape)
    if m.group(3):
        ids = np.transpose(ids, [int(x) for x in m.group(4).split(",")])
    ids = ids.reshape(gshape)
    return [list(map(int, row)) for row in ids]


def _collectives(hlo: str):
    """[(kind, line, groups, out_shapes, gather_dims)] for every collective."""
    out = []
    for line in hlo.splitlines():
        m = _COLL.search(line)
        if m is None or "=" not in line:
            continue
        kind = m.group(1)
        gm = _GROUPS.search(line)
        pm = _PAIRS.search(line)
        if gm:
            groups = _parse_groups(gm.group(1))
        elif pm:
            # each {src,dst} pair is a 2-device "group" for crossing checks
            groups = _parse_groups(pm.group(1))
        else:
            groups = []
        # result shapes live between "=" and the op keyword (handles both
        # scalar `f32[..] all-reduce(` and variadic `(f32[..], ..) all-reduce(`)
        head = line[: m.start()]
        head = head.split(" = ", 1)[1] if " = " in head else head
        shapes = [
            tuple(int(x) for x in s.split(",") if x != "")
            for s in _SHAPE.findall(head)
        ]
        dm = _DIMS.search(line)
        dims = [int(x) for x in dm.group(1).split(",")] if dm else []
        out.append((kind, line.strip(), groups, shapes, dims))
    return out


def _shrunk(name: str):
    cfg = get_config(name)
    model = dataclasses.replace(
        cfg.model,
        n_layer=2,
        block_size=BLOCK,
        vocab_size=1024,
        remat="none",
        scan_unroll=1,
    )
    return dataclasses.replace(
        cfg,
        model=model,
        batch_size=BATCH,
        g_accum_iters=1,
        loss_chunk=128,  # 2 chunks: keeps the chunked-loss path in the audit
    )


def _compile_cfg(cfg):
    mesh = create_mesh(cfg.mesh)
    tx, _ = make_optimizer(cfg)
    state = init_state(cfg, mesh, tx, jax.random.PRNGKey(0))
    step = make_train_step(cfg, tx, mesh)
    x = np.zeros((1, BATCH, BLOCK), np.int32)
    spec = P(None, ("replica", "fsdp"), "sequence")
    xg = make_global_array(x, mesh, spec)
    txt = step.lower(state, xg, xg, jax.random.PRNGKey(1)).compile().as_text()
    return txt, mesh


def _compile_step(name: str):
    return _compile_cfg(_shrunk(name))


def _local_batch(mesh) -> int:
    shape = dict(mesh.shape)
    return BATCH // (shape.get("replica", 1) * shape.get("fsdp", 1))


def _local_t(mesh) -> int:
    return BLOCK // dict(mesh.shape).get("sequence", 1)


def _assert_no_batch_gather(colls, mesh):
    """No all-gather over dim 0 of a [B_local, T_local, ...] activation."""
    b_local = _local_batch(mesh)
    t_local = _local_t(mesh)
    for kind, line, _, shapes, dims in colls:
        if kind != "all-gather":
            continue
        for shape in shapes:
            # activations are rank>=3 [B, T, ...]; rank-2 gathers are FSDP
            # param shards (legitimate), feature-dim gathers are TP. The
            # sequence dim carries T_local on sequence-sharded meshes.
            if (
                len(shape) >= 3
                and 0 in dims
                and shape[1] in (t_local, BLOCK)
                and shape[0] >= b_local
            ):
                raise AssertionError(
                    f"batch-dim all-gather of an activation:\n{line}"
                )


@pytest.mark.slow
@pytest.mark.parametrize("name", ["openwebtext_xl", "llama_7b"])
def test_sharded_config_has_no_batch_allgather(name):
    hlo, mesh = _compile_step(name)
    assert dict(mesh.shape)["tensor"] == 4  # the shipped FSDP x TP shape
    _assert_no_batch_gather(_collectives(hlo), mesh)


@pytest.mark.slow
def test_ring_config_permutes_instead_of_gathering_seq():
    """A sequence-sharded ring-attention train step must move K/V with
    collective-permutes (the ring hops), never by all-gathering the full
    sequence onto every device — the anti-pattern ring attention exists
    to avoid (SURVEY.md §5.7)."""
    cfg = _shrunk("openwebtext")
    cfg = dataclasses.replace(
        cfg,
        model=dataclasses.replace(cfg.model, attn_impl="ring"),
        mesh=dataclasses.replace(
            cfg.mesh, replica=1, fsdp=2, sequence=4, tensor=1
        ),
    )
    hlo, mesh = _compile_cfg(cfg)

    colls = _collectives(hlo)
    assert any(k == "collective-permute" for k, *_ in colls), (
        "no collective-permute found — the ring schedule is not in the "
        "compiled step"
    )
    for kind, line, _, shapes, dims in colls:
        if kind != "all-gather":
            continue
        for shape in shapes:
            # no rank>=3 activation gather that reconstitutes the full T:
            # a gathered dim (ANY position >= 1 — K/V sit at [B,H,T,C] with
            # T at dim 2 inside attention) reaching full BLOCK size
            if len(shape) >= 3 and any(
                d >= 1 and d < len(shape) and shape[d] == BLOCK for d in dims
            ):
                raise AssertionError(
                    f"full-sequence all-gather of an activation:\n{line}"
                )
    _assert_no_batch_gather(colls, mesh)


@pytest.mark.slow
def test_multislice_dcn_contract():
    hlo, mesh = _compile_step("openwebtext_xl_multislice")
    colls = _collectives(hlo)
    shape = dict(mesh.shape)
    assert shape["replica"] == 2

    # device id -> slice (replica coordinate): logical ids in the HLO are
    # positions in the mesh device assignment
    devs = mesh.devices
    rep_axis = mesh.axis_names.index("replica")
    flat_ids = np.vectorize(lambda d: d.id)(devs).flatten()
    coords = {
        int(flat_ids[i]): int(np.unravel_index(i, devs.shape)[rep_axis])
        for i in range(flat_ids.size)
    }

    def crosses(groups):
        return any(len({coords[d] for d in g}) > 1 for g in groups if g)

    b_local = _local_batch(mesh)
    saw_cross_reduce = False
    for kind, line, groups, shapes, _ in colls:
        if not crosses(groups):
            continue
        # DP-only over DCN: the only traffic allowed across slices is
        # all-reduce (grad/loss sums) of non-activation operands
        assert kind == "all-reduce", (
            f"{kind} crosses the slice boundary (DCN):\n{line}"
        )
        for shape in shapes:
            assert not (len(shape) >= 2 and shape[:2] == (b_local, BLOCK)), (
                f"activation-shaped all-reduce crosses slices:\n{line}"
            )
        if any(len(s) >= 2 for s in shapes):
            saw_cross_reduce = True  # param-shaped gradient sync
    assert saw_cross_reduce, (
        "no cross-slice gradient all-reduce found — replicas would train "
        "divergently (DP sync missing from the compiled step)"
    )

    _assert_no_batch_gather(colls, mesh)


@pytest.mark.slow
def test_eval_sweep_has_no_batch_allgather():
    """The r5 eval sweep (make_eval_step: all eval batches through one
    lax.scan) must shard like the train step — a batch-dim gather inside
    the scan body would cost eval_batches x the train-step trap."""
    from midgpt_tpu.train import make_eval_step

    cfg = _shrunk("openwebtext_xl")
    mesh = create_mesh(cfg.mesh)
    tx, _ = make_optimizer(cfg)
    state = init_state(cfg, mesh, tx, jax.random.PRNGKey(0))
    sweep = make_eval_step(cfg, mesh)
    n_eval = 3
    x = np.zeros((n_eval, BATCH, BLOCK), np.int32)
    spec = P(None, ("replica", "fsdp"), "sequence")
    xg = make_global_array(x, mesh, spec)
    hlo = sweep.lower(state.params, xg, xg).compile().as_text()
    _assert_no_batch_gather(_collectives(hlo), mesh)


@pytest.mark.slow
def test_moe_ep_step_has_no_batch_allgather():
    """MoE under fsdp x tensor (expert parallelism): the one-hot
    dispatch/combine einsums must not make GSPMD gather full activations
    — batch stays sharded; the expert contraction's psum is the only
    intended cross-'tensor' traffic."""
    cfg = _shrunk("openwebtext")
    cfg = dataclasses.replace(
        cfg,
        model=dataclasses.replace(
            cfg.model, mlp="moe", moe_experts=4, attn_impl="naive"
        ),
        mesh=dataclasses.replace(
            cfg.mesh, replica=1, fsdp=2, sequence=1, tensor=4
        ),
    )
    hlo, mesh = _compile_cfg(cfg)
    colls = _collectives(hlo)
    _assert_no_batch_gather(colls, mesh)
    assert any(k == "all-reduce" for k, *_ in colls), (
        "no all-reduce found — the expert-combine psum is missing"
    )
