"""Training-engine tests: optimizer parity, loss decreases end-to-end on
the 8-device mesh, checkpoint round-trip + exact resume continuity."""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.config import ExperimentConfig, MeshConfig, ModelConfig
from midgpt_tpu.data import write_tokens
from midgpt_tpu.train import train, make_optimizer, make_lr_schedule


def _tiny_cfg(tmp_path, **kw) -> ExperimentConfig:
    data_dir = str(tmp_path / "data")
    os.makedirs(data_dir, exist_ok=True)
    rng = np.random.default_rng(0)
    # highly-learnable stream: repeated ramps with noise
    base = np.tile(np.arange(64), 4000)
    noise = rng.integers(0, 64, size=base.shape)
    toks = np.where(rng.random(base.shape) < 0.05, noise, base)
    write_tokens(os.path.join(data_dir, "train.bin"), toks)
    write_tokens(os.path.join(data_dir, "val.bin"), toks[:40_000])
    defaults = dict(
        model=ModelConfig(
            block_size=32, vocab_size=64, n_layer=2, n_head=2, n_embd=64,
            dropout=0.0, attn_impl="naive", remat="none",
        ),
        rundir=str(tmp_path / "run"),
        data_dir=data_dir,
        learning_rate=1e-2, min_lr=1e-3, warmup_steps=5,
        lr_decay_steps=30, max_steps=30,
        batch_size=8, g_accum_iters=2,
        beta2=0.99, weight_decay=1e-4,
        eval_interval=15, eval_batches=2, log_interval=5,
        mesh=MeshConfig(replica=1, fsdp=2, sequence=2, tensor=2),
    )
    defaults.update(kw)
    return ExperimentConfig(**defaults)


def test_lr_schedule_shape():
    config = ExperimentConfig(
        model=ModelConfig(block_size=8, vocab_size=8, n_layer=1, n_head=1, n_embd=8),
        learning_rate=1e-3, min_lr=1e-4, warmup_steps=10, lr_decay_steps=100,
    )
    sched = make_lr_schedule(config)
    assert float(sched(0)) == 0.0
    np.testing.assert_allclose(float(sched(10)), 1e-3, rtol=1e-6)
    np.testing.assert_allclose(float(sched(100)), 1e-4, rtol=1e-5)


def test_independent_weight_decay_scaling():
    config = ExperimentConfig(
        model=ModelConfig(block_size=8, vocab_size=8, n_layer=1, n_head=1, n_embd=8),
        learning_rate=1e-3, weight_decay=1e-4,
    )
    tx, _ = make_optimizer(config)
    # decay applied as wd/lr (parity: train.py:156); verify via a single
    # update on a 1-param tree with zero grads past warmup
    params = {"w": jnp.ones((4,))}
    state = tx.init(params)
    grads = {"w": jnp.zeros((4,))}
    # run enough updates to get a nonzero schedule value
    for _ in range(20):
        updates, state = tx.update(grads, state, params)
    # update = -schedule * (adam(0) + wd/lr * w); adam(0)=0
    sched = make_lr_schedule(config)
    expected = -float(sched(19)) * (config.weight_decay / config.learning_rate)
    np.testing.assert_allclose(np.asarray(updates["w"]), expected, rtol=1e-4)


@pytest.mark.slow
def test_train_loss_decreases(tmp_path):
    cfg = _tiny_cfg(tmp_path)
    final = train(cfg)
    assert final["loss"] < 2.0, f"loss did not decrease: {final}"
    assert final["val_loss"] < 2.5
    # metrics file written
    assert os.path.exists(os.path.join(cfg.rundir, "metrics.jsonl"))


@pytest.mark.slow
def test_final_checkpoint_saved_off_interval(tmp_path):
    """Regression: max_steps not a multiple of the save interval must still
    leave an end-of-run checkpoint (forced save at max_steps - 1)."""
    from midgpt_tpu.checkpoint import Checkpointer

    cfg = _tiny_cfg(
        tmp_path, rundir=str(tmp_path / "run_off"), max_steps=17,
        eval_interval=10, ckpt_interval=10,
    )
    train(cfg)
    ckpt = Checkpointer(cfg.rundir, save_interval_steps=10)
    assert ckpt.latest_step() == 16


@pytest.mark.slow
def test_resume_continuity(tmp_path):
    """Train 30 steps straight vs 15 + resume 15: identical data order and
    near-identical final loss (bf16 reductions aren't bitwise across
    restarts)."""
    cfg_full = _tiny_cfg(tmp_path, rundir=str(tmp_path / "run_full"))
    final_full = train(cfg_full)

    cfg_a = _tiny_cfg(
        tmp_path, rundir=str(tmp_path / "run_resume"), max_steps=15,
        ckpt_interval=15,
    )
    train(cfg_a)
    cfg_b = dataclasses.replace(cfg_a, max_steps=30)
    final_b = train(cfg_b)

    assert abs(final_b["val_loss"] - final_full["val_loss"]) < 0.15, (
        f"resume diverged: {final_b['val_loss']} vs {final_full['val_loss']}"
    )


@pytest.mark.slow
def test_llama_family_trains_sharded(tmp_path):
    """The Llama-style path (SwiGLU + GQA) must compose with the full
    DP x FSDP x SP x TP mesh — exercises the w_gate partition rule and
    grouped-KV sharding through a real train step."""
    cfg = _tiny_cfg(
        tmp_path,
        model=ModelConfig(
            block_size=32, vocab_size=64, n_layer=2, n_head=4, n_kv_head=2,
            n_embd=64, dropout=0.0, mlp="swiglu", mlp_ratio=2.0,
            attn_impl="naive", remat="full",
        ),
        max_steps=20, lr_decay_steps=20, eval_interval=10,
    )
    final = train(cfg)
    assert final["loss"] < 3.0, f"loss did not decrease: {final}"


@pytest.mark.slow
def test_train_orchestrator_with_pipeline_mesh(tmp_path):
    """Full train() loop (loader, eval, checkpointing) on a
    pipeline=2 x fsdp=2 x tensor=2 mesh: loss decreases and the PP param
    rules survive checkpoint save (SURVEY 2.6 PP row, end to end)."""
    cfg = _tiny_cfg(
        tmp_path,
        rundir=str(tmp_path / "run_pp"),
        mesh=MeshConfig(pipeline=2, replica=1, fsdp=2, sequence=1, tensor=2),
        max_steps=20, lr_decay_steps=20, eval_interval=10,
        g_accum_iters=1,
    )
    final = train(cfg)
    assert final["loss"] < 3.5, f"PP loss did not decrease: {final}"


@pytest.mark.slow
def test_sigterm_checkpoints_and_resumes(tmp_path):
    """Preemption safety: SIGTERM mid-run force-saves the completed step
    and the same rundir resumes from it (the reference loses everything
    since the last eval_interval checkpoint, SURVEY 5.3)."""
    import signal
    import subprocess
    import sys
    import textwrap
    import time as _time

    data_dir = str(tmp_path / "data")
    os.makedirs(data_dir, exist_ok=True)
    toks = np.tile(np.arange(64), 4000).astype(np.uint16)
    write_tokens(os.path.join(data_dir, "train.bin"), toks)
    write_tokens(os.path.join(data_dir, "val.bin"), toks[:40_000])
    rundir = str(tmp_path / "run_sigterm")

    script = textwrap.dedent(f"""
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        import dataclasses
        from midgpt_tpu.config import ExperimentConfig, MeshConfig, ModelConfig
        from midgpt_tpu.train import train
        cfg = ExperimentConfig(
            model=ModelConfig(
                block_size=32, vocab_size=64, n_layer=2, n_head=2, n_embd=64,
                dropout=0.0, attn_impl="naive", remat="none",
            ),
            rundir={rundir!r}, data_dir={data_dir!r},
            learning_rate=1e-2, min_lr=1e-3, warmup_steps=5,
            lr_decay_steps=5000, max_steps=5000,  # far more than we let run
            batch_size=8, g_accum_iters=1,
            eval_interval=1000000, eval_batches=1, log_interval=1000000,
            ckpt_interval=1000000,  # interval saves never fire
            mesh=MeshConfig(replica=1, fsdp=-1, sequence=1, tensor=1),
        )
        print("TRAIN_START", flush=True)
        final = train(cfg)
        print("INTERRUPTED_AT", final.get("interrupted_at"), flush=True)
    """)
    import select

    proc = subprocess.Popen(
        [sys.executable, "-c", script],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    # wait for the loop to start (bounded: select with a real deadline, and
    # bail if the child died early), let it take steps, then TERM
    deadline = _time.time() + 300
    started = False
    while _time.time() < deadline:
        if proc.poll() is not None:
            break
        ready, _, _ = select.select([proc.stdout], [], [], 5)
        if not ready:
            continue
        line = proc.stdout.readline()
        if not line:
            break
        if "TRAIN_START" in line:
            started = True
            break
    assert started, f"trainer never started (rc={proc.poll()})"
    _time.sleep(15)  # let it compile + run some steps
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=240)
    assert proc.returncode == 0, out[-2000:]
    assert "INTERRUPTED_AT" in out, out[-2000:]
    interrupted_at = int(out.split("INTERRUPTED_AT")[1].split()[0])

    from midgpt_tpu.checkpoint import Checkpointer

    ckpt = Checkpointer(rundir, save_interval_steps=1)
    step = ckpt.latest_step()
    ckpt.close()
    # the force-save must own the LAST COMPLETED step, not just orbax's
    # automatic step-0 save
    assert step == interrupted_at, (step, interrupted_at)

    # and the same rundir resumes from it
    from midgpt_tpu.train import train as _train

    resume_cfg = ExperimentConfig(
        model=ModelConfig(
            block_size=32, vocab_size=64, n_layer=2, n_head=2, n_embd=64,
            dropout=0.0, attn_impl="naive", remat="none",
        ),
        rundir=rundir, data_dir=data_dir,
        learning_rate=1e-2, min_lr=1e-3, warmup_steps=5,
        lr_decay_steps=5000, max_steps=interrupted_at + 3,
        batch_size=8, g_accum_iters=1,
        eval_interval=1000000, eval_batches=1, log_interval=1000000,
        ckpt_interval=1000000,
        mesh=MeshConfig(replica=1, fsdp=-1, sequence=1, tensor=1),
    )
    final = _train(resume_cfg)
    assert "interrupted_at" not in final
    assert np.isfinite(final["val_loss"])


def test_fixed_eval_sweep_is_deterministic(tmp_path, mesh8):
    """eval_fixed=True must evaluate the identical held-out sweep every
    interval: evaluate() at seed_offset 0 twice gives bit-equal losses,
    while a different offset (the fresh-random default) does not."""
    from midgpt_tpu.data import Loader, load_shard
    from midgpt_tpu.train import (
        evaluate, init_state, make_eval_step, make_optimizer,
    )

    cfg = _tiny_cfg(tmp_path)
    tx, _ = make_optimizer(cfg)
    state = init_state(cfg, mesh8, tx, jax.random.PRNGKey(0))
    eval_step = make_eval_step(cfg, mesh8)
    loader = Loader(
        shard=load_shard(os.path.join(cfg.data_dir, "val.bin"), 0, 1),
        block_size=cfg.model.block_size,
        batch_shape=(1, 4),
        seed=cfg.data_seed,
        stream=1,
    )
    a = evaluate(eval_step, state.params, loader, mesh8, 3, 0)
    b = evaluate(eval_step, state.params, loader, mesh8, 3, 0)
    c = evaluate(eval_step, state.params, loader, mesh8, 3, 7)
    assert a == b
    assert a != c
