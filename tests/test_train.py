"""Training-engine tests: optimizer parity, loss decreases end-to-end on
the 8-device mesh, checkpoint round-trip + exact resume continuity."""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.config import ExperimentConfig, MeshConfig, ModelConfig
from midgpt_tpu.data import write_tokens
from midgpt_tpu.train import train, make_optimizer, make_lr_schedule


def _tiny_cfg(tmp_path, **kw) -> ExperimentConfig:
    data_dir = str(tmp_path / "data")
    os.makedirs(data_dir, exist_ok=True)
    rng = np.random.default_rng(0)
    # highly-learnable stream: repeated ramps with noise
    base = np.tile(np.arange(64), 4000)
    noise = rng.integers(0, 64, size=base.shape)
    toks = np.where(rng.random(base.shape) < 0.05, noise, base)
    write_tokens(os.path.join(data_dir, "train.bin"), toks)
    write_tokens(os.path.join(data_dir, "val.bin"), toks[:40_000])
    defaults = dict(
        model=ModelConfig(
            block_size=32, vocab_size=64, n_layer=2, n_head=2, n_embd=64,
            dropout=0.0, attn_impl="naive", remat="none",
        ),
        rundir=str(tmp_path / "run"),
        data_dir=data_dir,
        learning_rate=1e-2, min_lr=1e-3, warmup_steps=5,
        lr_decay_steps=30, max_steps=30,
        batch_size=8, g_accum_iters=2,
        beta2=0.99, weight_decay=1e-4,
        eval_interval=15, eval_batches=2, log_interval=5,
        mesh=MeshConfig(replica=1, fsdp=2, sequence=2, tensor=2),
    )
    defaults.update(kw)
    return ExperimentConfig(**defaults)


def test_lr_schedule_shape():
    config = ExperimentConfig(
        model=ModelConfig(block_size=8, vocab_size=8, n_layer=1, n_head=1, n_embd=8),
        learning_rate=1e-3, min_lr=1e-4, warmup_steps=10, lr_decay_steps=100,
    )
    sched = make_lr_schedule(config)
    assert float(sched(0)) == 0.0
    np.testing.assert_allclose(float(sched(10)), 1e-3, rtol=1e-6)
    np.testing.assert_allclose(float(sched(100)), 1e-4, rtol=1e-5)


def test_independent_weight_decay_scaling():
    config = ExperimentConfig(
        model=ModelConfig(block_size=8, vocab_size=8, n_layer=1, n_head=1, n_embd=8),
        learning_rate=1e-3, weight_decay=1e-4,
    )
    tx, _ = make_optimizer(config)
    # decay applied as wd/lr (parity: train.py:156); verify via a single
    # update on a 1-param tree with zero grads past warmup
    params = {"w": jnp.ones((4,))}
    state = tx.init(params)
    grads = {"w": jnp.zeros((4,))}
    # run enough updates to get a nonzero schedule value
    for _ in range(20):
        updates, state = tx.update(grads, state, params)
    # update = -schedule * (adam(0) + wd/lr * w); adam(0)=0
    sched = make_lr_schedule(config)
    expected = -float(sched(19)) * (config.weight_decay / config.learning_rate)
    np.testing.assert_allclose(np.asarray(updates["w"]), expected, rtol=1e-4)


@pytest.mark.slow
def test_train_loss_decreases(tmp_path):
    cfg = _tiny_cfg(tmp_path)
    final = train(cfg)
    assert final["loss"] < 2.0, f"loss did not decrease: {final}"
    assert final["val_loss"] < 2.5
    # metrics file written
    assert os.path.exists(os.path.join(cfg.rundir, "metrics.jsonl"))


@pytest.mark.slow
def test_final_checkpoint_saved_off_interval(tmp_path):
    """Regression: max_steps not a multiple of the save interval must still
    leave an end-of-run checkpoint (forced save at max_steps - 1)."""
    from midgpt_tpu.checkpoint import Checkpointer

    cfg = _tiny_cfg(
        tmp_path, rundir=str(tmp_path / "run_off"), max_steps=17,
        eval_interval=10, ckpt_interval=10,
    )
    train(cfg)
    ckpt = Checkpointer(cfg.rundir, save_interval_steps=10)
    assert ckpt.latest_step() == 16


@pytest.mark.slow
def test_resume_continuity(tmp_path):
    """Train 30 steps straight vs 15 + resume 15: identical data order and
    near-identical final loss (bf16 reductions aren't bitwise across
    restarts)."""
    cfg_full = _tiny_cfg(tmp_path, rundir=str(tmp_path / "run_full"))
    final_full = train(cfg_full)

    cfg_a = _tiny_cfg(
        tmp_path, rundir=str(tmp_path / "run_resume"), max_steps=15,
        ckpt_interval=15,
    )
    train(cfg_a)
    cfg_b = dataclasses.replace(cfg_a, max_steps=30)
    final_b = train(cfg_b)

    assert abs(final_b["val_loss"] - final_full["val_loss"]) < 0.15, (
        f"resume diverged: {final_b['val_loss']} vs {final_full['val_loss']}"
    )


@pytest.mark.slow
def test_llama_family_trains_sharded(tmp_path):
    """The Llama-style path (SwiGLU + GQA) must compose with the full
    DP x FSDP x SP x TP mesh — exercises the w_gate partition rule and
    grouped-KV sharding through a real train step."""
    cfg = _tiny_cfg(
        tmp_path,
        model=ModelConfig(
            block_size=32, vocab_size=64, n_layer=2, n_head=4, n_kv_head=2,
            n_embd=64, dropout=0.0, mlp="swiglu", mlp_ratio=2.0,
            attn_impl="naive", remat="full",
        ),
        max_steps=20, lr_decay_steps=20, eval_interval=10,
    )
    final = train(cfg)
    assert final["loss"] < 3.0, f"loss did not decrease: {final}"
