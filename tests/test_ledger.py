"""Perf-trajectory ledger (midgpt_tpu.analysis.ledger + the --ledger
CLI): trajectory ingestion, the static/wall-clock gating split,
watchdog-row exclusion, the key-inventory gate, the markdown trend
report, suite-timing ingestion — and the two acceptance gates: the CLI
exits NONZERO on a doctored regression record and GREEN on the shipped
BENCH_r*.json trajectory.

jax-free module: these tests run in milliseconds.
"""

import json
import os

import pytest

from midgpt_tpu.analysis.__main__ import main
from midgpt_tpu.analysis.ledger import (
    Row,
    diff_record,
    load_trajectory,
    markdown_report,
    parse_multichip_record,
    row_hardware,
    row_kind,
    row_ok,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Fixture trajectories
# ---------------------------------------------------------------------------

_HW_TRAIN = {
    "metric": "openwebtext_xl_family_L6_train_mfu",
    "value": 0.60,
    "unit": "fraction_of_peak",
    "vs_baseline": 1.25,
    "tokens_per_sec_per_chip": 48000.0,
    "step_ms": 340.0,
    "device": "TPU v5 lite",
    "n_devices": 1,
    "model_flops_per_token": 2.5e9,
    "gpt2s_metric": "openwebtext_124m_train_mfu",
    "gpt2s_mfu": 0.40,
    "status": "ok",
}

_SERVE = {
    "device": "TPU v5 lite",
    "status": "ok",
    "serve_shape": "124m S=8 K=8",
    "serve_tok_s": 1100.0,
    "serve_ms_per_tok": 0.9,
    "serve_bytes_per_token_static": 33000000,
    "serve_hbm_floor_ms_static": 0.33,
    "serve_floor_ms_per_tok_static": 0.041,
    "serve_attainment_frac": 0.046,
    "serve_mfu": 0.01,
    "serve_goodput_slo_tok_s": 1000.0,
}


def _write_trajectory(tmp_path, records):
    d = tmp_path / "traj"
    d.mkdir(exist_ok=True)
    for i, rec in enumerate(records, start=1):
        (d / f"BENCH_r{i:02d}.json").write_text(
            json.dumps({"n": i, "rc": 0, "parsed": rec})
        )
    return str(d)


def _write_record(tmp_path, rec, name="current.json"):
    p = tmp_path / name
    p.write_text(json.dumps(rec))
    return str(p)


# ---------------------------------------------------------------------------
# Row classification
# ---------------------------------------------------------------------------


def test_row_classification():
    assert row_kind(_HW_TRAIN) == "train"
    assert row_kind(_SERVE) == "serving"
    assert row_kind({"kind": "suite", "suite_total_call_s": 100}) == "suite"
    assert row_ok(_HW_TRAIN)
    assert not row_ok({"metric": "bench_error", "status": "error"})
    assert not row_ok({**_HW_TRAIN, "status": "watchdog"})
    assert not row_ok({**_HW_TRAIN, "partial": True})
    assert row_hardware(_HW_TRAIN)
    assert not row_hardware({**_HW_TRAIN, "device": "cpu"})


# ---------------------------------------------------------------------------
# Gating semantics (library level)
# ---------------------------------------------------------------------------


def _rows(*recs):
    return [Row(f"r{i}", i, rec) for i, rec in enumerate(recs, start=1)]


def test_hardware_wallclock_regression_is_hard():
    cur = {**_HW_TRAIN, "value": 0.40}  # -33% MFU
    findings = diff_record(cur, _rows(_HW_TRAIN))
    hard = [f for f in findings if f.severity == "hard"]
    assert any(f.key == "value" for f in hard)


def test_cpu_wallclock_regression_is_informational():
    cur = {**_HW_TRAIN, "device": "cpu", "value": 0.40}
    findings = diff_record(cur, _rows({**_HW_TRAIN, "device": "cpu"}))
    assert findings and all(f.severity == "info" for f in findings)


def test_small_drift_inside_band_is_clean():
    cur = {**_HW_TRAIN, "value": 0.58}  # -3.3%: inside the 10% band
    assert diff_record(cur, _rows(_HW_TRAIN)) == []


def test_static_key_drift_is_hard_even_on_cpu():
    ref = {**_SERVE, "device": "cpu"}
    cur = {**ref, "serve_bytes_per_token_static": 34000000}
    findings = diff_record(cur, _rows(ref))
    assert any(
        f.severity == "hard" and f.key == "serve_bytes_per_token_static"
        for f in findings
    )


def test_headline_keys_compare_only_within_same_metric():
    # the rung ladder changed shape: value halves but the metric name
    # differs, so there is no comparable reference — clean
    cur = {**_HW_TRAIN, "metric": "openwebtext_124m_train_mfu",
           "value": 0.30, "model_flops_per_token": 8e8}
    assert diff_record(cur, _rows(_HW_TRAIN)) == []


def test_serving_rows_compare_only_within_same_shape():
    cur = {**_SERVE, "serve_shape": "124m S=16 K=8",
           "serve_tok_s": 500.0, "serve_bytes_per_token_static": 1}
    assert diff_record(cur, _rows(_SERVE)) == []


def test_watchdog_current_row_is_never_a_regression():
    cur = {**_HW_TRAIN, "status": "watchdog", "value": 0.0}
    findings = diff_record(cur, _rows(_HW_TRAIN))
    assert all(f.severity == "info" for f in findings)


def test_watchdog_rows_excluded_from_reference():
    wedge = {**_HW_TRAIN, "status": "watchdog", "value": 0.01}
    cur = dict(_HW_TRAIN)
    # the wedge row (newest) must NOT become the reference: comparing
    # 0.60 against 0.01 would report a huge "improvement"; comparing a
    # later regression against 0.01 would hide it
    findings = diff_record(
        {**cur, "value": 0.40}, _rows(_HW_TRAIN, wedge)
    )
    assert any(
        f.key == "value" and f.reference == 0.60 for f in findings
    )


def test_serving_inventory_shrink_is_hard():
    cur = dict(_SERVE)
    del cur["serve_goodput_slo_tok_s"]
    findings = diff_record(cur, _rows(_SERVE))
    assert any(
        f.severity == "hard" and f.key == "serve_goodput_slo_tok_s"
        for f in findings
    )


def test_train_inventory_shrink_is_informational():
    ref = {**_HW_TRAIN, "llama_mfu": 0.6, "llama_metric": "llama_L2"}
    cur = {**_HW_TRAIN, "llama_error": "OOM"}
    findings = diff_record(cur, _rows(ref))
    assert findings and all(f.severity == "info" for f in findings)


def test_markdown_report_renders_tables_and_findings():
    rows = _rows(_HW_TRAIN, _SERVE)
    findings = diff_record({**_HW_TRAIN, "value": 0.40}, rows)
    text = markdown_report(rows, [("cur.json", _HW_TRAIN)], findings)
    assert "## train trajectory" in text
    assert "## serving trajectory" in text
    assert "openwebtext_xl_family_L6_train_mfu" in text
    assert "## Findings" in text and "[hard] value" in text
    assert "**cur.json** (current)" in text


# ---------------------------------------------------------------------------
# CLI acceptance gates
# ---------------------------------------------------------------------------


def test_cli_green_on_shipped_trajectory(capsys):
    """Acceptance: `python -m midgpt_tpu.analysis --ledger` over the
    repo's own BENCH_r*.json rounds is green — the r4/r5 watchdog rows
    are wedges, not regressions, and r3 holds the trajectory's best
    numbers."""
    rc = main(["--ledger"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["ok"] is True
    assert out["trajectory_rows"] >= 5
    # the self-check picked a real OK row, not a wedge
    assert "BENCH_r03" in out["records"][0]


def test_cli_nonzero_on_injected_regression(tmp_path, capsys):
    """Acceptance: a doctored record (hardware row, gpt2s MFU down 30%)
    exits nonzero with the finding on stderr and in the report."""
    traj = _write_trajectory(tmp_path, [_HW_TRAIN])
    bad = _write_record(
        tmp_path, {**_HW_TRAIN, "gpt2s_mfu": 0.28}, "doctored.json"
    )
    report = str(tmp_path / "report.md")
    rc = main([
        "--ledger", "--trajectory", traj, "--record", bad,
        "--report", report,
    ])
    captured = capsys.readouterr()
    assert rc == 1
    assert json.loads(captured.out)["hard"] >= 1
    assert "gpt2s_mfu" in captured.err
    assert "[hard] gpt2s_mfu" in open(report).read()


def test_cli_green_on_faithful_record(tmp_path, capsys):
    traj = _write_trajectory(tmp_path, [_HW_TRAIN])
    good = _write_record(
        tmp_path, {**_HW_TRAIN, "value": 0.61}, "good.json"
    )
    rc = main(["--ledger", "--trajectory", traj, "--record", good])
    assert rc == 0
    capsys.readouterr()


def test_cli_static_regression_in_record_dir_reference(tmp_path, capsys):
    """Bench record dirs ingest as reference rows: a current serving
    record whose static bytes drifted against the archived row fails."""
    traj = _write_trajectory(tmp_path, [_HW_TRAIN])
    d = tmp_path / "records"
    d.mkdir()
    (d / "serving_a.json").write_text(
        json.dumps({**_SERVE, "device": "cpu"})
    )
    cur = _write_record(
        tmp_path,
        {**_SERVE, "device": "cpu", "serve_bytes_per_token_static": 1},
        "cur.json",
    )
    rc = main([
        "--ledger", "--trajectory", traj, "--records-dir", str(d),
        "--record", cur,
    ])
    assert rc == 1
    capsys.readouterr()


def test_cli_hardware_override_gates_cpu_rows(tmp_path, capsys):
    """--hardware on turns a CPU wall-clock drop into a hard gate (the
    r6 queue uses it when the device field is a relay alias)."""
    traj = _write_trajectory(
        tmp_path, [{**_HW_TRAIN, "device": "cpu"}]
    )
    bad = _write_record(
        tmp_path, {**_HW_TRAIN, "device": "cpu", "value": 0.40}
    )
    assert main([
        "--ledger", "--trajectory", traj, "--record", bad,
    ]) == 0
    capsys.readouterr()
    assert main([
        "--ledger", "--trajectory", traj, "--record", bad,
        "--hardware", "on",
    ]) == 1
    capsys.readouterr()


def test_cli_suite_timing_ingested(tmp_path, capsys):
    traj = _write_trajectory(tmp_path, [_HW_TRAIN])
    st = tmp_path / "suite_timing.json"
    st.write_text(json.dumps({
        "kind": "suite", "suite_total_call_s": 431.5,
        "suite_n_calls": 415,
        "slowest": [{"nodeid": "tests/test_x.py::t", "s": 19.0}],
    }))
    report = str(tmp_path / "report.md")
    rc = main([
        "--ledger", "--trajectory", traj, "--suite-timing", str(st),
        "--record", _write_record(tmp_path, _HW_TRAIN),
        "--report", report,
    ])
    assert rc == 0
    assert "## suite trajectory" in open(report).read()
    assert "431.5" in open(report).read()
    capsys.readouterr()


def test_load_trajectory_orders_and_tolerates_junk(tmp_path):
    traj = _write_trajectory(tmp_path, [_HW_TRAIN, _SERVE])
    (tmp_path / "traj" / "BENCH_r10.json").write_text("not json {")
    rows = load_trajectory(str(tmp_path / "traj"))
    assert [r.index for r in rows] == [1, 2]
    d = tmp_path / "extra"
    d.mkdir()
    (d / "a.json").write_text(json.dumps(_SERVE))
    rows = load_trajectory(str(tmp_path / "traj"), [str(d)])
    assert len(rows) == 3 and rows[-1].index == 3


def test_suite_timing_artifact_from_conftest_schema(tmp_path):
    """The conftest SUITE_TIMING_OUT artifact parses as a ledger suite
    row (schema lockstep between the two sides)."""
    import subprocess
    import sys

    out = str(tmp_path / "suite.json")
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", SUITE_TIMING_OUT=out,
    )
    r = subprocess.run(
        [sys.executable, "-m", "pytest",
         "tests/test_ledger.py::test_row_classification", "-q",
         "-p", "no:cacheprovider"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300,
    )
    assert r.returncode == 0, r.stdout[-2000:]
    rec = json.load(open(out))
    assert rec["kind"] == "suite"
    assert row_kind(rec) == "suite"
    assert rec["suite_n_calls"] >= 1
    assert rec["suite_total_call_s"] >= 0
    assert rec["slowest"]


def test_train_rows_compare_only_within_same_device_population():
    """The static train floors embed peak FLOPs and chip count: a CPU
    smoke row must never hard-gate a TPU round's floors (code review
    PR 15) — different device/n_devices means no comparison at all."""
    ref = {**_HW_TRAIN, "train_hbm_floor_ms": 0.5,
           "train_compute_floor_ms": 1.0}
    cur = {**ref, "device": "cpu", "n_devices": 8,
           "train_hbm_floor_ms": 99.0, "value": 0.01}
    assert diff_record(cur, _rows(ref)) == []


def test_serving_rows_compare_only_at_same_offered_load():
    """serve_shape omits --rate/--requests; two rungs at different
    offered loads legitimately differ several-fold on wall-clock keys
    and must not gate each other (code review PR 15)."""
    ref = {**_SERVE, "serve_rate_req_s": 8.0, "serve_requests": 64}
    cur = {**ref, "serve_rate_req_s": 2.0, "serve_tok_s": 300.0,
           "serve_ms_per_tok": 4.0}
    assert diff_record(cur, _rows(ref)) == []
    # same load: the regression IS gated
    same = {**ref, "serve_tok_s": 300.0}
    assert any(
        f.key == "serve_tok_s" and f.severity == "hard"
        for f in diff_record(same, _rows(ref))
    )


# ---------------------------------------------------------------------------
# MULTICHIP ingestion
# ---------------------------------------------------------------------------

_MULTICHIP_RAW = {
    "n_devices": 8,
    "rc": 0,
    "ok": True,
    "skipped": False,
    "tail": (
        "dryrun_multichip(8): mesh {'replica': 1, 'fsdp': 2}, "
        "loss=6.0479 OK\n"
        "dryrun multi-slice (2 slices over DCN, mesh {'replica': 2}): "
        "loss=6.0844 OK\n"
        "dryrun GPT pipeline (4 stages): loss=5.9629 (matches non-PP "
        "5.9631, diff 2.0e-04) OK\n"
        "dryrun pipeline(4 stages): loss=330.5806 OK\n"
    ),
}


def test_multichip_record_parses_tail_losses():
    rec = parse_multichip_record(_MULTICHIP_RAW)
    assert row_kind(rec) == "multichip"
    assert row_ok(rec)
    assert rec["n_devices"] == 8
    assert rec["multichip_mesh_loss"] == pytest.approx(6.0479)
    assert rec["multichip_multi_slice_loss"] == pytest.approx(6.0844)
    # "GPT pipeline" and the seed-sum "pipeline" line are distinct keys
    assert rec["multichip_gpt_pipeline_loss"] == pytest.approx(5.9629)
    assert rec["multichip_pipeline_loss"] == pytest.approx(330.5806)


def test_multichip_wedge_row_excluded():
    """A non-ok/skipped wrapper is a wedge (status='error'), excluded
    from the reference exactly like the r4/r5 BENCH watchdog rows."""
    rec = parse_multichip_record({**_MULTICHIP_RAW, "ok": False, "rc": 1})
    assert not row_ok(rec)
    rec = parse_multichip_record({**_MULTICHIP_RAW, "skipped": True})
    assert not row_ok(rec)


def test_multichip_loss_drift_is_hard_static():
    ref = parse_multichip_record(_MULTICHIP_RAW)
    cur = {**ref, "multichip_multi_slice_loss": 6.5}  # ~7% drift
    findings = diff_record(cur, _rows(ref))
    assert any(
        f.severity == "hard" and f.key == "multichip_multi_slice_loss"
        for f in findings
    )
    # inside the 5% band: clean
    near = {**ref, "multichip_multi_slice_loss": 6.10}
    assert diff_record(near, _rows(ref)) == []


def test_multichip_rows_compare_only_within_same_device_count():
    ref = parse_multichip_record(_MULTICHIP_RAW)
    cur = {**ref, "n_devices": 4, "multichip_mesh_loss": 99.0}
    assert diff_record(cur, _rows(ref)) == []


def test_multichip_inventory_shrink_is_hard():
    ref = parse_multichip_record(_MULTICHIP_RAW)
    cur = dict(ref)
    del cur["multichip_gpt_pipeline_loss"]
    findings = diff_record(cur, _rows(ref))
    assert any(
        f.severity == "hard" and f.key == "multichip_gpt_pipeline_loss"
        for f in findings
    )


def test_load_trajectory_ingests_multichip_rounds(tmp_path):
    traj = _write_trajectory(tmp_path, [_HW_TRAIN])
    d = tmp_path / "traj"
    (d / "MULTICHIP_r01.json").write_text(json.dumps(_MULTICHIP_RAW))
    (d / "MULTICHIP_r02.json").write_text(
        json.dumps({**_MULTICHIP_RAW, "ok": False, "rc": 1})
    )
    rows = load_trajectory(str(d))
    kinds = [row_kind(r.record) for r in rows]
    assert kinds == ["train", "multichip", "multichip"]
    # indices continue past the BENCH rounds, in round order
    assert [r.index for r in rows] == [1, 2, 3]
    assert row_ok(rows[1].record) and not row_ok(rows[2].record)


def test_cli_self_check_covers_multichip_family(capsys):
    """Acceptance: the shipped MULTICHIP_r*.json rounds join the
    trajectory, the per-kind self-check diffs the newest OK multichip
    round against its predecessors, and the whole ledger stays green
    (train's newest OK row stays the FIRST record — BENCH_r03)."""
    rc = main(["--ledger"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["ok"] is True
    assert out["trajectory_rows"] >= 10
    assert "BENCH_r03" in out["records"][0]
    assert any("MULTICHIP_r05" in r for r in out["records"])


def test_multichip_trend_section_in_report(tmp_path, capsys):
    traj = _write_trajectory(tmp_path, [_HW_TRAIN])
    d = tmp_path / "traj"
    (d / "MULTICHIP_r01.json").write_text(json.dumps(_MULTICHIP_RAW))
    report = str(tmp_path / "report.md")
    rc = main([
        "--ledger", "--trajectory", str(d),
        "--record", _write_record(tmp_path, _HW_TRAIN),
        "--report", report,
    ])
    assert rc == 0
    text = open(report).read()
    assert "## multichip trajectory" in text
    assert "6.084" in text  # multichip_multi_slice_loss column
    capsys.readouterr()
