"""Train-window traffic + dispatch gates (the --train-audit family).

Three layers, cheapest first:

- **Fixture tests** (milliseconds, no jax tracing): a hand-written
  2-slice train-window HLO pair under ``tests/fixtures/`` with
  hand-computed wire bytes drives ``cost.py``'s
  ``collective_crosses_slice`` ICI/DCN split and ``check_train_budget``
  — including the cross-slice-re-gather fault, whose only symptom is
  FSDP gather bytes migrating from the ICI tier to DCN.
- **Checker unit tests** (jax-free dict/dataclass inputs) for
  ``check_train_budget`` / ``check_train_dispatch_budget`` /
  ``train_geometry_key`` and the K-invariance of the checked-in cells.
- **Compile/trace-backed tests** against the real fused window at the
  audit geometry: the fsdp and dcn2 K=1 cells must match the
  checked-in budgets exactly, and each injected fault must fail ONLY
  its own gate (cross-slice re-gather -> traffic; re-unrolled
  grad-accum scan -> dispatch) while the other gates stay green.
"""

import pathlib

import pytest

from midgpt_tpu.analysis import MeshInfo, StepAnalysis, cost_report
from midgpt_tpu.analysis.budgets import (
    TRAIN_AUDIT_GEOMETRIES,
    TRAIN_BUDGETS,
    check_train_budget,
    check_train_dispatch_budget,
    train_budget_for,
    train_geometry_key,
)
from midgpt_tpu.analysis.dispatch import TrainDispatchReport
from midgpt_tpu.analysis.traffic import train_budget_table_markdown
from midgpt_tpu.config import get_config

FIXTURES = pathlib.Path(__file__).parent / "fixtures"

# the fixtures' mesh: 8 devices as (pipeline, replica, fsdp, seq, tensor),
# replica split across 2 slices (slice id == replica coordinate)
MESH_2SLICE = MeshInfo(
    axis_names=("pipeline", "replica", "fsdp", "sequence", "tensor"),
    axis_sizes=(1, 2, 2, 1, 2),
    num_slices=2,
)

# hand-computed budget for train_multislice_window.hlo (ring arithmetic):
#   bf16[16,32] fsdp param all-gather, g=2:  16*32*2 * 1/2 =  512 B (ICI)
#   f32[16,32] fsdp grad reduce-scatter g=2: 16*32*4 * 1/2 = 1024 B (ICI)
#   f32[8,32] cross-slice all-reduce g=2:  2* 8*32*4 * 1/2 = 1024 B (DCN)
FIXTURE_BUDGET = {
    "ici_bytes": 1536,
    "dcn_bytes": 1024,
    "by_axis": {"fsdp": 1536, "replica": 1024},
}


def _fixture_report(name: str):
    a = StepAnalysis.from_text(
        (FIXTURES / name).read_text(), MESH_2SLICE, global_batch=8, block=256
    )
    return cost_report(a)


# ---------------------------------------------------------------------------
# fixtures: the collective_crosses_slice split, no compilation
# ---------------------------------------------------------------------------


def test_train_window_fixture_matches_hand_computed_bytes():
    rep = _fixture_report("train_multislice_window.hlo")
    assert rep["value"] == 2560
    assert rep["ici_bytes"] == 1536
    assert rep["dcn_bytes"] == 1024
    assert rep["by_axis"] == {"fsdp": 1536, "replica": 1024}
    media = [(c["kind"], c["medium"]) for c in rep["collectives"]]
    assert media == [
        ("all-gather", "ici"),
        ("reduce-scatter", "ici"),
        ("all-reduce", "dcn"),
    ]


def test_cross_slice_gather_fault_moves_bytes_to_dcn():
    """The bad fixture's only change: the fsdp param gather's groups
    span both slices ({{0,2,4,6},{1,3,5,7}}), so its bytes grow
    (g=2 -> g=4 over a doubled result) AND land on DCN under the
    replica+fsdp axis pair — the exact signature the compiled fault
    test below reproduces on a real mesh."""
    rep = _fixture_report("train_multislice_badgather.hlo")
    assert rep["ici_bytes"] == 1024
    assert rep["dcn_bytes"] == 2560
    assert rep["by_axis"] == {
        "replica+fsdp": 1536, "fsdp": 1024, "replica": 1024,
    }
    gather = rep["collectives"][0]
    assert gather["kind"] == "all-gather"
    assert gather["medium"] == "dcn"
    assert gather["mesh_axes"] == ["replica", "fsdp"]


def test_check_train_budget_green_on_good_fixture():
    assert check_train_budget(
        _fixture_report("train_multislice_window.hlo"),
        FIXTURE_BUDGET,
        geometry="fixture2slice",
    ) == []


def test_check_train_budget_flags_cross_slice_regather():
    vs = check_train_budget(
        _fixture_report("train_multislice_badgather.hlo"),
        FIXTURE_BUDGET,
        geometry="fixture2slice",
    )
    joined = " | ".join(vs)
    assert any("dcn_bytes" in v for v in vs), vs
    assert "unexpected collective axis 'replica+fsdp'" in joined
    # the gather's ICI bytes vanished too — bands work both ways
    assert any("axis 'fsdp'" in v for v in vs), vs


def test_zero_dcn_budget_trips_on_a_single_byte():
    vs = check_train_budget(
        {"ici_bytes": 1000, "dcn_bytes": 1, "by_axis": {"fsdp": 1000}},
        {"ici_bytes": 1000, "dcn_bytes": 0, "by_axis": {"fsdp": 1000}},
    )
    assert len(vs) == 1 and "cross-slice re-gather" in vs[0]


# ---------------------------------------------------------------------------
# checker units (jax-free)
# ---------------------------------------------------------------------------


def _dispatch_report(**over):
    kw = dict(
        program="train_window",
        window_steps=4,
        g_accum_iters=2,
        window_scan_length=4,
        accum_scan_length=2,
        accum_carry_leaves=9,
        host_transfers=0,
    )
    kw.update(over)
    return TrainDispatchReport(**kw)


def test_dispatch_budget_green():
    rep = _dispatch_report()
    assert rep.launches_per_window == 1
    assert check_train_dispatch_budget(rep, aliased_leaves=27) == []


def test_dispatch_budget_flags_lost_window_scan():
    rep = _dispatch_report(window_scan_length=0)
    assert rep.launches_per_window == 4
    vs = check_train_dispatch_budget(rep, aliased_leaves=27)
    assert len(vs) == 1 and "dispatch latency" in vs[0]


def test_dispatch_budget_flags_reunrolled_accum():
    vs = check_train_dispatch_budget(
        _dispatch_report(accum_scan_length=0), aliased_leaves=27
    )
    assert len(vs) == 1 and "re-unrolled" in vs[0]


def test_dispatch_budget_flags_host_transfer_and_lost_donation():
    vs = check_train_dispatch_budget(
        _dispatch_report(host_transfers=2), aliased_leaves=19
    )
    joined = " | ".join(vs)
    assert "host callback" in joined and "HBM residency" in joined


def test_train_geometry_key_reverse_lookup():
    assert train_geometry_key(
        dict(replica=1, fsdp=8, sequence=1, tensor=1)
    ) == "fsdp"
    assert train_geometry_key(
        dict(replica=2, fsdp=4, sequence=1, tensor=1, num_slices=2)
    ) == "dcn2"
    # a 2-slice shape WITHOUT the num_slices marker is not dcn2
    assert train_geometry_key(dict(replica=2, fsdp=4)) is None
    assert train_geometry_key(dict(fsdp=2, tensor=4)) is None


def test_train_budget_cells_are_k_invariant():
    """cost.py counts a scan-body collective once per dispatch, so the
    fused window's static bytes must NOT grow with K — the checked-in
    cells pin that identity."""
    for geom in TRAIN_AUDIT_GEOMETRIES:
        assert TRAIN_BUDGETS[(geom, 1)] == TRAIN_BUDGETS[(geom, 4)], geom
    assert train_budget_for("fsdp", 1) is TRAIN_BUDGETS[("fsdp", 1)]
    assert train_budget_for("fsdp", 3) is None


def test_train_budget_table_renders_all_cells():
    md = train_budget_table_markdown(TRAIN_BUDGETS)
    lines = md.splitlines()
    assert lines[0].startswith("| geometry | K |")
    assert len(lines) == 2 + len(TRAIN_BUDGETS)
    assert any(l.startswith("| dcn2 | 1 ") and "14.2" in l for l in lines)
    assert any(l.startswith("| fsdp | 4 ") for l in lines)


# ---------------------------------------------------------------------------
# compile/trace-backed: real window at the audit geometry
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def base_cfg():
    return get_config("openwebtext")


def test_fsdp_cell_matches_checked_in_budget(base_cfg):
    from midgpt_tpu.analysis.harness import train_traffic_cell

    cell = train_traffic_cell(base_cfg, "fsdp", 1)
    assert check_train_budget(
        cell, train_budget_for("fsdp", 1), geometry="fsdp"
    ) == []
    assert cell["dcn_bytes"] == 0
    # donation accounting off the same executable: every donated train
    # state leaf is input/output-aliased
    assert cell["aliased_leaves"] == cell["donated_leaves"] == 27
    assert check_train_dispatch_budget(
        _dispatch_report(window_steps=1, window_scan_length=1),
        aliased_leaves=cell["aliased_leaves"],
    ) == []


def test_dcn2_cell_matches_checked_in_budget(base_cfg):
    from midgpt_tpu.analysis.harness import train_traffic_cell

    cell = train_traffic_cell(base_cfg, "dcn2", 1)
    assert check_train_budget(
        cell, train_budget_for("dcn2", 1), geometry="dcn2"
    ) == []
    # the 2-slice mesh has real DCN traffic — and only on the grad-sync
    # axes, never the fsdp param gathers
    assert cell["dcn_bytes"] > 0
    assert set(cell["by_axis"]) == {"fsdp", "replica+fsdp", "replica"}


def test_cross_slice_regather_fault_trips_traffic_gate_only(base_cfg):
    """Widen every fsdp param axis to (replica, fsdp) on the dcn2 mesh:
    GSPMD re-gathers params across the slice boundary, so the gather
    bytes move wholesale from ICI to DCN (the fixture fault, on a real
    compile). The traffic gate must go red; the choreography prover and
    the dispatch gate — which see dtypes and launch structure, both
    untouched — must stay green."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from midgpt_tpu.analysis.cost import cost_report as cost
    from midgpt_tpu.analysis.dispatch import train_dispatch_report
    from midgpt_tpu.analysis.harness import (
        compile_train_window,
        shrink_for_train_audit,
    )
    from midgpt_tpu.analysis.train_choreo import prove_window_choreography
    from midgpt_tpu.models.gpt import gpt_param_rules
    from midgpt_tpu.parallel.mesh import create_mesh
    from midgpt_tpu.train import init_state, make_optimizer, make_train_window

    def widen(spec):
        return P(*[
            ("replica", "fsdp") if a == "fsdp" else a for a in spec
        ])

    bad_rules = tuple(
        (pat, widen(spec)) for pat, spec in gpt_param_rules()
    )
    audit = shrink_for_train_audit(base_cfg, "dcn2")

    hlo, mesh, donated, aliased = compile_train_window(
        audit, 1, param_rules=bad_rules
    )
    rep = cost(StepAnalysis.from_text(
        hlo,
        MeshInfo.from_mesh(mesh, num_slices=audit.mesh.num_slices),
        global_batch=audit.batch_size,
        block=audit.model.block_size,
    ))
    vs = check_train_budget(
        rep, train_budget_for("dcn2", 1), geometry="dcn2"
    )
    assert vs, "widened param specs must trip the traffic gate"
    assert any("dcn_bytes" in v for v in vs), vs
    # the fsdp-only gathers are gone: their ICI bytes vanished
    assert rep["dcn_bytes"] > train_budget_for("dcn2", 1)["dcn_bytes"]

    # ...while the other two gates stay green on the same faulty window
    tx, _ = make_optimizer(audit)
    state = init_state(
        audit, mesh, tx, jax.random.PRNGKey(0), abstract=True,
        param_rules=bad_rules,
    )
    prog = make_train_window(audit, tx, mesh, 1, param_rules=bad_rules)
    xs = jax.ShapeDtypeStruct(
        (1, audit.g_accum_iters, audit.microbatch_size,
         audit.model.block_size),
        jnp.int32,
    )
    key = jax.random.PRNGKey(1)
    closed = jax.make_jaxpr(prog)(state, xs, xs, key)
    out_tree = jax.eval_shape(prog, state, xs, xs, key)
    prover = prove_window_choreography(
        closed, out_tree, window_steps=1,
        g_accum_iters=audit.g_accum_iters,
    )
    assert prover.ok, prover.to_dict()
    disp = train_dispatch_report(
        closed, window_steps=1, g_accum_iters=audit.g_accum_iters
    )
    assert check_train_dispatch_budget(disp, aliased_leaves=aliased) == []


def test_reunrolled_accum_fault_trips_dispatch_gate_only(
    base_cfg, monkeypatch
):
    """Unroll ONLY the grad-accum scan (its carry signature — a 2-tuple
    of (grad tree, f32 scalar loss accumulator) — identifies it; the
    window scan carries a TrainState, the layer scan a single array).
    The dispatch gate must flag accum_scan_length 0 with the re-unroll
    hint; the choreography prover DEFERS (its grad-accum clause reports
    'no grad-accum scan in trace') rather than double-reporting."""
    import jax
    import jax.numpy as jnp

    from midgpt_tpu.analysis.dispatch import train_dispatch_report
    from midgpt_tpu.analysis.harness import (
        shrink_for_train_audit,
        trace_train_window,
    )
    from midgpt_tpu.analysis.train_choreo import prove_window_choreography

    real_scan = jax.lax.scan

    def unrolling_scan(f, init, xs=None, **kw):
        is_accum = (
            isinstance(init, tuple)
            and len(init) == 2
            and hasattr(init[1], "dtype")
            and str(getattr(init[1], "dtype", "")) == "float32"
            and getattr(init[1], "shape", None) == ()
        )
        if not is_accum:
            return real_scan(f, init, xs, **kw)
        carry = init
        for i in range(jax.tree.leaves(xs)[0].shape[0]):
            carry, _ = f(carry, jax.tree.map(lambda a: a[i], xs))
        return carry, None

    monkeypatch.setattr(jax.lax, "scan", unrolling_scan)

    audit = shrink_for_train_audit(base_cfg, "fsdp")
    # use_cache=False: the poisoned trace must not land in the shared
    # train.get_train_window cache other tests resolve through
    closed, out_tree = trace_train_window(audit, 4, use_cache=False)
    disp = train_dispatch_report(
        closed, window_steps=4, g_accum_iters=audit.g_accum_iters
    )
    assert disp.accum_scan_length == 0
    assert disp.window_scan_length == 4  # the window scan survived
    vs = check_train_dispatch_budget(disp, aliased_leaves=27)
    assert len(vs) == 1 and "re-unrolled" in vs[0], vs

    prover = prove_window_choreography(
        closed, out_tree, window_steps=4,
        g_accum_iters=audit.g_accum_iters,
    )
    by_name = {c.name: c for c in prover.checks}
    accum = by_name["grad-accum-carry"]
    assert accum.ok and "no grad-accum scan in trace" in accum.detail
    assert prover.ok, prover.to_dict()


@pytest.mark.slow
def test_audit_train_full_matrix(base_cfg):
    """The whole CI matrix in one test: all three geometries, K=1 and
    K=4 — prover + traffic + dispatch green everywhere."""
    from midgpt_tpu.analysis.harness import audit_train

    for geom in TRAIN_AUDIT_GEOMETRIES:
        report = audit_train(base_cfg, geom)
        assert report["ok"], (geom, report["violations"])
        assert [c["window_steps"] for c in report["cells"]] == [1, 4]
        for cell in report["cells"]:
            assert cell["choreography"]["ok"]
            assert cell["dispatch"]["launches_per_window"] == 1
