"""Genuine multi-process distributed tests: two OS processes join a JAX
coordination service on CPU and run (a) the per-process data-feed +
global-array assembly path and (b) a full train() with shared-rundir
checkpointing (parity target: the reference's multihost mechanisms,
/root/reference/launch.py:22-23 jax.distributed.initialize +
src/sharding.py:33-42 per-host batch assembly + src/train.py:127-225)."""

import os
import re
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
import jax

jax.config.update("jax_platforms", "cpu")
proc_id = int(sys.argv[1])
coord = sys.argv[2]
jax.distributed.initialize(
    coordinator_address=coord, num_processes=2, process_id=proc_id
)
assert jax.process_count() == 2
assert jax.device_count() == 4  # 2 local CPU devices per process

import numpy as np
from jax.sharding import PartitionSpec as P

from midgpt_tpu.config import MeshConfig
from midgpt_tpu.data import Loader, load_shard
from midgpt_tpu.parallel.mesh import create_mesh
from midgpt_tpu.parallel.sharding import make_global_array

mesh = create_mesh(MeshConfig(replica=1, fsdp=-1, sequence=1, tensor=1))

# per-process contiguous shard of one token stream
path = sys.argv[3]
shard = load_shard(path, proc_id, 2)
loader = Loader(shard=shard, block_size=16, batch_shape=(4,), seed=7,
                process_index=proc_id)
x, y = loader.next()
xg = make_global_array(x, mesh, P(("replica", "fsdp"), None))
assert xg.shape == (8, 16), xg.shape  # global batch = 2 procs x 4

# a cross-process collective: global mean must agree on both processes
total = jax.jit(lambda a: a.sum())(xg)
from jax.experimental.multihost_utils import sync_global_devices
sync_global_devices("end")  # (parity: launch.py:69-70)
print(f"OK proc={proc_id} total={int(total)}")
"""

_TRAIN_WORKER = r"""
import os, sys
import jax

jax.config.update("jax_platforms", "cpu")
proc_id = int(sys.argv[1])
jax.distributed.initialize(
    coordinator_address=sys.argv[2], num_processes=2, process_id=proc_id
)

from midgpt_tpu.config import ExperimentConfig, MeshConfig, ModelConfig
from midgpt_tpu.train import train

cfg = ExperimentConfig(
    model=ModelConfig(
        block_size=32, vocab_size=64, n_layer=2, n_head=2, n_embd=64,
        dropout=0.0, attn_impl="naive", remat="none",
    ),
    rundir=sys.argv[3],
    data_dir=sys.argv[4],
    learning_rate=1e-2, min_lr=1e-3, warmup_steps=5,
    lr_decay_steps=20, max_steps=20,
    batch_size=8, g_accum_iters=2,
    eval_interval=10, eval_batches=2, log_interval=5,
    mesh=MeshConfig(replica=1, fsdp=-1, sequence=1, tensor=1),
)
final = train(cfg)
print(f"FINAL proc={proc_id} val={final['val_loss']:.6f}")
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_workers(worker_path: str, argv_tail_fn, attempts: int = 2):
    """Launch the 2-process worker pair; retry once with a fresh
    coordinator port (the free-port probe can race other processes under a
    loaded full-suite run). ``argv_tail_fn(attempt)`` supplies per-attempt
    args so retries never reuse stateful paths (e.g. a rundir with a
    half-written checkpoint)."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("JAX_NUM_PROCESSES", None)

    last = None
    for attempt in range(attempts):
        coord = f"localhost:{_free_port()}"
        procs = [
            subprocess.Popen(
                [sys.executable, worker_path, str(i), coord,
                 *argv_tail_fn(attempt)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, cwd=repo_root,
            )
            for i in range(2)
        ]
        try:
            outs = [p.communicate(timeout=600)[0] for p in procs]
        except subprocess.TimeoutExpired:
            for p in procs:  # a wedged sibling must not outlive the test
                p.kill()
            outs = [p.communicate()[0] for p in procs]
            last = "timeout:\n" + "\n".join(o[-2000:] for o in outs)
            continue
        if all(p.returncode == 0 for p in procs):
            return outs
        last = "\n".join(
            f"-- proc {i} rc={p.returncode} --\n{out[-3000:]}"
            for i, (p, out) in enumerate(zip(procs, outs))
        )
    raise AssertionError(f"workers failed after {attempts} attempts:\n{last}")


@pytest.mark.slow
def test_two_process_data_feed(tmp_path):
    import numpy as np

    from midgpt_tpu.data import write_tokens

    token_path = str(tmp_path / "train.bin")
    write_tokens(token_path, np.arange(10_000) % 251)

    worker = str(tmp_path / "worker.py")
    with open(worker, "w") as f:
        f.write(_WORKER)

    outs = _run_workers(worker, lambda attempt: [token_path])
    for i, out in enumerate(outs):
        assert f"OK proc={i}" in out, out
    # both processes computed the same global sum; parse the numeric token
    # only — Gloo banners can interleave onto the same stdout line
    # (observed flake, VERDICT r2 Weak #6)
    def _total(out: str) -> int:
        line = [l for l in out.splitlines() if l.startswith("OK")][0]
        m = re.search(r"total=(\d+)", line)
        assert m, line
        return int(m.group(1))

    assert _total(outs[0]) == _total(outs[1])


@pytest.mark.slow
def test_two_process_full_train(tmp_path):
    """Full train() across two processes: per-process data shards, a global
    mesh over both, distributed Orbax checkpointing to a shared rundir."""
    import numpy as np

    from midgpt_tpu.data import write_tokens

    data_dir = str(tmp_path / "data")
    os.makedirs(data_dir)
    rng = np.random.default_rng(0)
    base = np.tile(np.arange(64), 2000)
    toks = np.where(rng.random(base.shape) < 0.05,
                    rng.integers(0, 64, size=base.shape), base)
    write_tokens(os.path.join(data_dir, "train.bin"), toks)
    write_tokens(os.path.join(data_dir, "val.bin"), toks[:20_000])

    worker = str(tmp_path / "train_worker.py")
    with open(worker, "w") as f:
        f.write(_TRAIN_WORKER)

    # fresh rundir per attempt: a retry must not resume from a previous
    # attempt's checkpoint
    rundirs = [str(tmp_path / f"run{i}") for i in range(2)]
    used = []

    def tail(attempt):
        used.append(rundirs[attempt])
        return [rundirs[attempt], data_dir]

    outs = _run_workers(worker, tail)
    rundir = used[-1]
    finals = [
        [l for l in out.splitlines() if l.startswith("FINAL")][0]
        for out in outs
    ]
    # the global val loss must agree across processes
    assert finals[0].split("val=")[1] == finals[1].split("val=")[1], finals
    # shared-rundir checkpoint written
    from midgpt_tpu.checkpoint import Checkpointer

    ckpt = Checkpointer(rundir, save_interval_steps=10)
    assert ckpt.latest_step() == 19
