"""Genuine multi-process distributed test: two OS processes join a JAX
coordination service on CPU and run the per-process data-feed +
global-array assembly path (parity target: the reference's multihost
mechanisms, /root/reference/launch.py:22-23 jax.distributed.initialize +
src/sharding.py:33-42 per-host batch assembly)."""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
import jax

jax.config.update("jax_platforms", "cpu")
proc_id = int(sys.argv[1])
coord = sys.argv[2]
jax.distributed.initialize(
    coordinator_address=coord, num_processes=2, process_id=proc_id
)
assert jax.process_count() == 2
assert jax.device_count() == 4  # 2 local CPU devices per process

import numpy as np
from jax.sharding import PartitionSpec as P

from midgpt_tpu.config import MeshConfig
from midgpt_tpu.data import Loader, load_shard
from midgpt_tpu.parallel.mesh import create_mesh
from midgpt_tpu.parallel.sharding import make_global_array

mesh = create_mesh(MeshConfig(replica=1, fsdp=-1, sequence=1, tensor=1))

# per-process contiguous shard of one token stream
path = sys.argv[3]
shard = load_shard(path, proc_id, 2)
loader = Loader(shard=shard, block_size=16, batch_shape=(4,), seed=7,
                process_index=proc_id)
x, y = loader.next()
xg = make_global_array(x, mesh, P(("replica", "fsdp"), None))
assert xg.shape == (8, 16), xg.shape  # global batch = 2 procs x 4

# a cross-process collective: global mean must agree on both processes
total = jax.jit(lambda a: a.sum())(xg)
from jax.experimental.multihost_utils import sync_global_devices
sync_global_devices("end")  # (parity: launch.py:69-70)
print(f"OK proc={proc_id} total={int(total)}")
"""


@pytest.mark.slow
def test_two_process_data_feed(tmp_path):
    import numpy as np

    from midgpt_tpu.data import write_tokens

    token_path = str(tmp_path / "train.bin")
    write_tokens(token_path, np.arange(10_000) % 251)

    port = _free_port()
    coord = f"localhost:{port}"
    worker = str(tmp_path / "worker.py")
    with open(worker, "w") as f:
        f.write(_WORKER)

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("JAX_NUM_PROCESSES", None)
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), coord, token_path],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=repo_root,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out}"
        assert f"OK proc={i}" in out, out
    # both processes computed the same global sum
    t0 = [l for l in outs[0].splitlines() if l.startswith("OK")][0].split("total=")[1]
    t1 = [l for l in outs[1].splitlines() if l.startswith("OK")][0].split("total=")[1]
    assert t0 == t1


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port
