"""Data pipeline tests: determinism, checkpointable state, target shift,
process sharding."""

import numpy as np
import pytest

from midgpt_tpu.data import Loader, load_shard, sample_batch, write_tokens


@pytest.fixture
def token_file(tmp_path):
    path = str(tmp_path / "train.bin")
    write_tokens(path, np.arange(10_000) % 256)
    return path


def test_load_shard_full(token_file):
    shard = load_shard(token_file)
    assert len(shard.tokens) == 10_000
    assert shard.tokens.dtype == np.uint16


def test_load_shard_per_process(token_file):
    s0 = load_shard(token_file, 0, 4)
    s3 = load_shard(token_file, 3, 4)
    assert len(s0.tokens) == len(s3.tokens) == 2500
    assert s0.tokens[0] == 0
    assert s3.offset == 7500


def test_sample_batch_shift_and_shape(token_file):
    shard = load_shard(token_file)
    x, y = sample_batch(shard, 32, (2, 4), seed=1, step=0)
    assert x.shape == y.shape == (2, 4, 32)
    assert x.dtype == np.int32
    # y is x shifted by one
    np.testing.assert_array_equal(x[..., 1:], y[..., :-1])


def test_sample_batch_deterministic(token_file):
    shard = load_shard(token_file)
    x1, _ = sample_batch(shard, 32, (2, 4), seed=1, step=7)
    x2, _ = sample_batch(shard, 32, (2, 4), seed=1, step=7)
    np.testing.assert_array_equal(x1, x2)
    x3, _ = sample_batch(shard, 32, (2, 4), seed=1, step=8)
    assert not np.array_equal(x1, x3)
    x4, _ = sample_batch(shard, 32, (2, 4), seed=2, step=7)
    assert not np.array_equal(x1, x4)


def test_loader_resume_reproduces_sequence(token_file):
    """The key fix over the reference (SURVEY.md 2.3): resume-exact data
    order."""
    shard = load_shard(token_file)
    a = Loader(shard=shard, block_size=16, batch_shape=(2,), seed=5)
    seq_a = [a.next()[0] for _ in range(6)]

    b = Loader(shard=shard, block_size=16, batch_shape=(2,), seed=5)
    b.next(); b.next(); b.next()
    state = b.state_dict()

    c = Loader(shard=shard, block_size=16, batch_shape=(2,), seed=5)
    c.load_state_dict(state)
    for i in range(3, 6):
        np.testing.assert_array_equal(c.next()[0], seq_a[i])


def test_loader_seed_mismatch_rejected(token_file):
    shard = load_shard(token_file)
    a = Loader(shard=shard, block_size=16, batch_shape=(2,), seed=5)
    with pytest.raises(AssertionError):
        a.load_state_dict({"step": 3, "seed": 6})


def test_streams_are_independent(token_file):
    shard = load_shard(token_file)
    x1, _ = sample_batch(shard, 32, (4,), seed=1, step=0, stream=0)
    x2, _ = sample_batch(shard, 32, (4,), seed=1, step=0, stream=1)
    assert not np.array_equal(x1, x2)


def test_native_gather_matches_numpy(token_file):
    """The C++ gather (midgpt_tpu/native/gather.cpp) must be bit-identical
    to the numpy recipe (parity: reference train.py:61-62)."""
    from midgpt_tpu import native

    shard = load_shard(token_file)
    offsets = np.array([0, 17, 500, 9900 - 33], dtype=np.int64)
    xs, ys = native.gather_windows(shard.tokens, offsets, 32)
    # numpy oracle
    idx = offsets[:, None] + np.arange(33)[None, :]
    windows = np.take(shard.tokens, idx, axis=0).astype(np.int32)
    np.testing.assert_array_equal(xs, windows[:, :-1])
    np.testing.assert_array_equal(ys, windows[:, 1:])


def test_native_gather_bounds_check(token_file):
    from midgpt_tpu import native

    shard = load_shard(token_file)
    with pytest.raises(IndexError):
        native.gather_windows(
            shard.tokens, np.array([10_000 - 8], dtype=np.int64), 32
        )
    with pytest.raises(IndexError):
        native.gather_windows(shard.tokens, np.array([-1], dtype=np.int64), 32)


def test_native_library_builds():
    """The toolchain is baked into the image, so the native path (not the
    fallback) must be what tests exercise."""
    from midgpt_tpu import native

    assert native.native_available()


def test_prefetch_loader_matches_sync(token_file):
    from midgpt_tpu.data import PrefetchLoader

    shard = load_shard(token_file)
    sync = Loader(shard=shard, block_size=16, batch_shape=(2,), seed=9)
    expected = [sync.next() for _ in range(8)]

    pre = PrefetchLoader(
        Loader(shard=shard, block_size=16, batch_shape=(2,), seed=9)
    )
    try:
        for i in range(8):
            x, y = pre.next()
            np.testing.assert_array_equal(x, expected[i][0])
            np.testing.assert_array_equal(y, expected[i][1])
    finally:
        pre.stop()


def test_prefetch_window_stacks_consecutive_batches(token_file):
    """Window mode: each next() is K consecutive loader batches stacked
    along a new leading axis — the [K, ...] window the fused multi-step
    dispatch consumes."""
    from midgpt_tpu.data import PrefetchLoader

    shard = load_shard(token_file)
    sync = Loader(shard=shard, block_size=16, batch_shape=(2,), seed=9)
    expected = [sync.next() for _ in range(6)]

    pre = PrefetchLoader(
        Loader(shard=shard, block_size=16, batch_shape=(2,), seed=9),
        window=3,
    )
    try:
        for w in range(2):
            x, y = pre.next()
            assert x.shape == (3, 2, 16)
            for i in range(3):
                np.testing.assert_array_equal(x[i], expected[3 * w + i][0])
                np.testing.assert_array_equal(y[i], expected[3 * w + i][1])
    finally:
        pre.stop()


def test_prefetch_window_plan_partial_first_and_last(token_file):
    """An explicit window_plan (the trainer's dispatch plan after an
    off-grid resume) yields per-item stacks of the planned sizes, then the
    worker stops — no draws past the plan."""
    from midgpt_tpu.data import PrefetchLoader

    shard = load_shard(token_file)
    sync = Loader(shard=shard, block_size=16, batch_shape=(2,), seed=9)
    expected = [sync.next() for _ in range(6)]

    pre = PrefetchLoader(
        Loader(shard=shard, block_size=16, batch_shape=(2,), seed=9),
        window=3, window_plan=[2, 3, 1],
    ).start()
    try:
        seen = 0
        for w in [2, 3, 1]:
            x, _ = pre.next()
            assert x.shape == (w, 2, 16)
            for i in range(w):
                np.testing.assert_array_equal(x[i], expected[seen + i][0])
            seen += w
        assert pre.state_dict()["step"] == 6
        # past the plan: the worker published a terminal sentinel — one
        # more next() must RAISE, not block forever on an empty queue
        with pytest.raises(RuntimeError, match="window_plan exhausted"):
            pre.next()
    finally:
        pre.stop()


def test_prefetch_window_state_replays_unconsumed_mid_window(token_file):
    """Stop/resume mid-window (depth-aware): batches drawn into queued-but-
    unconsumed windows must NOT count as consumed — a resume from
    state_dict() replays every batch of every unconsumed window exactly
    (extends the generation-zombie tests above to window mode)."""
    import time

    from midgpt_tpu.data import PrefetchLoader

    shard = load_shard(token_file)
    pre = PrefetchLoader(
        Loader(shard=shard, block_size=16, batch_shape=(2,), seed=9),
        depth=3, window=2,
    ).start()
    try:
        consumed = [pre.next() for _ in range(2)]  # 2 windows = 4 batches
        time.sleep(0.2)  # let the worker queue more windows
        state = pre.state_dict()
        # only the consumed windows' batches count (2 windows x 2)
        assert state["step"] == 4
    finally:
        pre.stop()

    sync = Loader(shard=shard, block_size=16, batch_shape=(2,), seed=9)
    expected = [sync.next() for _ in range(6)]
    resumed = PrefetchLoader(
        Loader(shard=shard, block_size=16, batch_shape=(2,), seed=9),
        window=2,
    )
    resumed.load_state_dict(state)
    try:
        x, _ = resumed.next()  # replays batches 4 and 5 exactly
        np.testing.assert_array_equal(x[0], expected[4][0])
        np.testing.assert_array_equal(x[1], expected[5][0])
    finally:
        resumed.stop()
    del consumed


def test_prefetch_loader_state_excludes_unconsumed(token_file):
    """Checkpointed loader state must count only consumed batches, not ones
    sitting in the prefetch queue."""
    import time

    from midgpt_tpu.data import PrefetchLoader

    shard = load_shard(token_file)
    pre = PrefetchLoader(
        Loader(shard=shard, block_size=16, batch_shape=(2,), seed=9), depth=3
    ).start()
    try:
        consumed = [pre.next() for _ in range(2)]
        time.sleep(0.2)  # let the worker fill the queue
        state = pre.state_dict()
        assert state["step"] == 2
    finally:
        pre.stop()

    # resume from the state replays batch #2 next
    sync = Loader(shard=shard, block_size=16, batch_shape=(2,), seed=9)
    expected = [sync.next() for _ in range(3)]
    resumed = PrefetchLoader(
        Loader(shard=shard, block_size=16, batch_shape=(2,), seed=9)
    )
    resumed.load_state_dict(state)
    try:
        np.testing.assert_array_equal(resumed.next()[0], expected[2][0])
    finally:
        resumed.stop()
    del consumed
