"""Checkpoint restore across a mesh-shape change (SURVEY.md 7 'hard
parts': the reference never handles saving on one topology and resuming on
another — needed for e.g. 2x v5p-64 -> v5p-128 moves)."""

import jax
import numpy as np
import pytest

from midgpt_tpu.checkpoint import Checkpointer
from midgpt_tpu.config import ExperimentConfig, MeshConfig, ModelConfig
from midgpt_tpu.parallel.mesh import create_mesh
from midgpt_tpu.train import _ckpt_items, init_state, make_optimizer


def _cfg(mesh: MeshConfig) -> ExperimentConfig:
    return ExperimentConfig(
        model=ModelConfig(
            block_size=32, vocab_size=128, n_layer=2, n_head=4, n_embd=64,
        ),
        mesh=mesh,
    )


@pytest.fixture(scope="module")
def saved_mesh_a(tmp_path_factory):
    """State initialized + checkpointed on mesh A, shared by the migration
    tests (the 8-device init and save only run once per session)."""
    cfg_a = _cfg(MeshConfig(replica=1, fsdp=4, sequence=1, tensor=2))
    mesh_a = create_mesh(cfg_a.mesh)
    tx, _ = make_optimizer(cfg_a)
    state_a = init_state(cfg_a, mesh_a, tx, jax.random.PRNGKey(0))
    rundir = str(tmp_path_factory.mktemp("ckpt_mig") / "run")
    ckpt = Checkpointer(rundir, save_interval_steps=1)
    ckpt.save(0, _ckpt_items(state_a), meta={"step": 0}, force=True)
    ckpt.wait()
    yield state_a, tx, rundir
    ckpt.close()


@pytest.mark.slow
def test_restore_across_mesh_change(saved_mesh_a):
    state_a, tx, rundir = saved_mesh_a
    ckpt = Checkpointer(rundir, save_interval_steps=1)

    # new topology: fsdp halved, sequence axis introduced
    cfg_b = _cfg(MeshConfig(replica=1, fsdp=2, sequence=2, tensor=2))
    mesh_b = create_mesh(cfg_b.mesh)
    state_b = init_state(cfg_b, mesh_b, tx, jax.random.PRNGKey(7))  # diff init

    items, meta = ckpt.restore(_ckpt_items(state_b))
    restored = items["params"]

    # values come from mesh A's save...
    np.testing.assert_allclose(
        np.asarray(jax.device_get(restored.wte.weight)),
        np.asarray(jax.device_get(state_a.params.wte.weight)),
    )
    np.testing.assert_allclose(
        np.asarray(jax.device_get(restored.blocks.attn.wqkv.weight)),
        np.asarray(jax.device_get(state_a.params.blocks.attn.wqkv.weight)),
    )
    # ...but land sharded for mesh B (restore is sharding-aware, no host
    # staging into the old layout)
    assert restored.wte.weight.sharding.mesh.shape == dict(mesh_b.shape)
    assert (
        restored.blocks.attn.wqkv.weight.sharding
        == state_b.params.blocks.attn.wqkv.weight.sharding
    )
    # optimizer moments migrate too: values from mesh A, shardings mesh B
    mu_a = jax.tree.leaves(state_a.opt_state)
    mu_r = jax.tree.leaves(items["opt_state"])
    mu_b = jax.tree.leaves(state_b.opt_state)
    assert len(mu_a) == len(mu_r) == len(mu_b)
    for a, r, b in zip(mu_a, mu_r, mu_b):
        np.testing.assert_allclose(
            np.asarray(jax.device_get(r)), np.asarray(jax.device_get(a))
        )
        if hasattr(r, "sharding") and hasattr(b, "sharding"):
            assert r.sharding == b.sharding, (r.sharding, b.sharding)
    ckpt.close()


def test_restore_pins_legacy_mlp_width(tmp_path):
    """A SwiGLU checkpoint holding the legacy int(ratio*D) MLP width must
    restore into a config with mlp_hidden=None: maybe_pin_mlp_hidden reads
    the stored shapes (no array data) and pins the width (ADVICE r3 — the
    256-rounding change would otherwise shape-mismatch every old ckpt)."""
    import dataclasses

    from midgpt_tpu.models.gpt import GPT, maybe_pin_mlp_hidden, mlp_hidden_dim

    legacy = ModelConfig(
        block_size=32, vocab_size=128, n_layer=2, n_head=4, n_embd=64,
        mlp="swiglu", mlp_ratio=8 / 3, mlp_hidden=170,  # int(8/3 * 64)
    )
    params = GPT.init(jax.random.PRNGKey(0), legacy)
    ckpt = Checkpointer(str(tmp_path / "run"), save_interval_steps=1)
    ckpt.save(0, {"params": params}, meta={"step": 0}, force=True)
    ckpt.wait()

    new = dataclasses.replace(legacy, mlp_hidden=None)
    assert mlp_hidden_dim(new) == 256  # would mismatch without the pin
    pinned = maybe_pin_mlp_hidden(new, ckpt.item_metadata()["params"])
    assert pinned.mlp_hidden == 170
    # width already matching -> config returned unchanged
    assert maybe_pin_mlp_hidden(legacy, ckpt.item_metadata()["params"]) is legacy
    # the restore-time entry point applies the same pin (and no-ops when
    # the width is pinned or integral)
    from midgpt_tpu.models.gpt import pin_mlp_hidden_from_ckpt

    assert pin_mlp_hidden_from_ckpt(new, ckpt).mlp_hidden == 170
    assert pin_mlp_hidden_from_ckpt(legacy, ckpt) is legacy

    template = jax.eval_shape(lambda: GPT.init(jax.random.PRNGKey(1), pinned))
    items, _ = ckpt.restore({"params": template})
    np.testing.assert_allclose(
        np.asarray(jax.device_get(items["params"].blocks.mlp.w_down.weight)),
        np.asarray(jax.device_get(params.blocks.mlp.w_down.weight)),
    )
    ckpt.close()


@pytest.mark.slow
def test_restore_into_pipeline_topology(saved_mesh_a):
    """Save on a plain FSDP mesh, resume on a pipeline-parallel mesh: the
    stacked block params must land sharded over the 'pipeline' axis
    (GPT_PP_PARAM_RULES) with the saved values — the 'add PP mid-training'
    migration."""
    state_a, tx, rundir = saved_mesh_a
    ckpt = Checkpointer(rundir, save_interval_steps=1)

    cfg_b = _cfg(MeshConfig(pipeline=2, replica=1, fsdp=2, sequence=1, tensor=2))
    mesh_b = create_mesh(cfg_b.mesh)
    state_b = init_state(cfg_b, mesh_b, tx, jax.random.PRNGKey(7))
    # PP rules: stacked block leaves carry 'pipeline' on the layer axis
    spec_b = state_b.params.blocks.attn.wqkv.weight.sharding.spec
    assert spec_b[0] == "pipeline", spec_b

    items, _ = ckpt.restore(_ckpt_items(state_b))
    restored = items["params"]
    np.testing.assert_allclose(
        np.asarray(jax.device_get(restored.blocks.attn.wqkv.weight)),
        np.asarray(jax.device_get(state_a.params.blocks.attn.wqkv.weight)),
    )
    assert (
        restored.blocks.attn.wqkv.weight.sharding
        == state_b.params.blocks.attn.wqkv.weight.sharding
    )
    ckpt.close()
