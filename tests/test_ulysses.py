"""All-to-all sequence parallelism (parallel/ulysses.py) vs the full-
attention oracle on the simulated 8-device mesh."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from midgpt_tpu.config import ModelConfig
from midgpt_tpu.models.gpt import GPT
from midgpt_tpu.ops.attention import naive_attention
from midgpt_tpu.parallel.ulysses import ulysses_attention
from midgpt_tpu.parallel.sharding import axis_rules


def _qkv(key, b, h, hkv, t, c):
    k1, k2, k3 = jax.random.split(key, 3)
    return (
        jax.random.normal(k1, (b, h, t, c)),
        jax.random.normal(k2, (b, hkv, t, c)),
        jax.random.normal(k3, (b, hkv, t, c)),
    )


@pytest.fixture(scope="module")
def umesh():
    """sequence=2 without a tensor axis (ulysses v1 gates on tensor==1)."""
    from midgpt_tpu.config import MeshConfig
    from midgpt_tpu.parallel.mesh import create_mesh

    return create_mesh(MeshConfig(replica=1, fsdp=4, sequence=2, tensor=1))


def test_ulysses_matches_full_attention(umesh):
    q, k, v = _qkv(jax.random.PRNGKey(0), 4, 4, 4, 64, 16)
    out = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, umesh))(q, k, v)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_gqa(umesh):
    q, k, v = _qkv(jax.random.PRNGKey(1), 4, 4, 2, 64, 16)
    out = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, umesh))(q, k, v)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_grads_match(umesh):
    q, k, v = _qkv(jax.random.PRNGKey(2), 4, 2, 2, 32, 16)

    def loss_u(q, k, v):
        return jnp.sum(ulysses_attention(q, k, v, umesh) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(naive_attention(q, k, v, causal=True) ** 2)

    gu = jax.jit(jax.grad(loss_u, argnums=(0, 1, 2)))(q, k, v)
    gn = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gu, gn, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, err_msg=f"d{name}"
        )


def test_ulysses_dropout_matches_single_device_mask(umesh):
    """Ulysses dropout anchors the hash at global (batch*H+head) — the
    sharded pass must equal the dense oracle with the GLOBAL mask (the
    same property ring dropout has, with zero schedule restrictions)."""
    from midgpt_tpu.ops.flash import dropout_mask_reference

    b, h, t, c = 4, 4, 64, 16
    q, k, v = _qkv(jax.random.PRNGKey(3), b, h, h, t, c)
    seed = jnp.int32(2024)
    rate = 0.3
    out = jax.jit(
        lambda q, k, v: ulysses_attention(
            q, k, v, umesh, dropout_rate=rate, dropout_seed=seed
        )
    )(q, k, v)

    import math

    z = jnp.einsum(
        "bhqc,bhjc->bhqj", q, k, preferred_element_type=jnp.float32
    ) / math.sqrt(c)
    z = jnp.where(jnp.tril(jnp.ones((t, t), bool)), z, -1e30)
    p = jax.nn.softmax(z, axis=-1)
    keepm = dropout_mask_reference(seed, b, h, t, rate)
    p = jnp.where(keepm, p / (1.0 - rate), 0.0)
    ref = jnp.einsum("bhqj,bhjc->bhqc", p.astype(v.dtype), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_rejects_bad_shapes(umesh):
    q, k, v = _qkv(jax.random.PRNGKey(4), 4, 3, 3, 64, 16)  # H=3, S=2
    with pytest.raises(AssertionError, match="divisible"):
        ulysses_attention(q, k, v, umesh)


def test_model_with_ulysses_matches_naive(umesh):
    """Full GPT forward with attn_impl='ulysses' equals the naive model."""
    cfg = ModelConfig(
        block_size=64, vocab_size=128, n_layer=2, n_head=4, n_embd=32,
        dropout=0.0, attn_impl="ulysses", remat="none",
    )
    model = GPT.init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 64), 0, 128)
    with axis_rules(umesh):
        out_u = jax.jit(lambda m, t: m(t))(model, tokens)
    cfg_n = dataclasses.replace(cfg, attn_impl="naive")
    model_n = dataclasses.replace(model, config=cfg_n)
    out_n = jax.jit(lambda m, t: m(t))(model_n, tokens)
    np.testing.assert_allclose(
        np.asarray(out_u), np.asarray(out_n), atol=5e-4
    )


def test_model_ulysses_dropout_trains(umesh):
    """GPT + ulysses + dropout>0: runs, deterministic per key, varies
    across keys (native exact dropout — no schedule degradation)."""
    cfg = ModelConfig(
        block_size=64, vocab_size=128, n_layer=2, n_head=4, n_embd=32,
        dropout=0.3, attn_impl="ulysses", remat="none",
    )
    model = GPT.init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 64), 0, 128)

    def fwd(key):
        with axis_rules(umesh):
            return jax.jit(
                lambda m, t, k: m(t, key=k, deterministic=False)
            )(model, tokens, key)

    a = fwd(jax.random.PRNGKey(2))
    b = fwd(jax.random.PRNGKey(2))
    c = fwd(jax.random.PRNGKey(3))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.allclose(np.asarray(a), np.asarray(c))


def test_ulysses_dropout_gqa_matches_global_mask(umesh):
    """Dropout + GQA together: the local head block [i*H/S, (i+1)*H/S)
    is contiguous in global head order, so the naive oracle's
    (kv, group) head-id reshape must still land every local head on its
    global hash stream — verified against the dense global-mask oracle."""
    import math

    from midgpt_tpu.ops.flash import dropout_mask_reference

    b, h, hkv, t, c = 4, 4, 2, 64, 16
    q, k, v = _qkv(jax.random.PRNGKey(7), b, h, hkv, t, c)
    seed = jnp.int32(-31415)
    rate = 0.25
    out = jax.jit(
        lambda q, k, v: ulysses_attention(
            q, k, v, umesh, dropout_rate=rate, dropout_seed=seed
        )
    )(q, k, v)

    groups = h // hkv
    qg = q.reshape(b, hkv, groups, t, c)
    z = jnp.einsum(
        "bkgqc,bkjc->bkgqj", qg, k, preferred_element_type=jnp.float32
    ) / math.sqrt(c)
    z = jnp.where(jnp.tril(jnp.ones((t, t), bool)), z, -1e30)
    p = jax.nn.softmax(z, axis=-1)
    keepm = dropout_mask_reference(seed, b, h, t, rate).reshape(
        b, hkv, groups, t, t
    )
    p = jnp.where(keepm, p / (1.0 - rate), 0.0)
    ref = jnp.einsum("bkgqj,bkjc->bkgqc", p.astype(v.dtype), v).reshape(
        b, h, t, c
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_trained_config_samples(tmp_path):
    """Sampling from a ulysses-trained config must not crash: generation
    remaps attn_impl='ulysses' -> 'auto' like ring (code review r5)."""
    from midgpt_tpu.sampling import generate

    cfg = ModelConfig(
        block_size=32, vocab_size=64, n_layer=2, n_head=4, n_embd=32,
        dropout=0.0, attn_impl="ulysses", remat="none",
    )
    model = GPT.init(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 7), 0, 64)
    toks = generate(
        model, prompt, 9, key=jax.random.PRNGKey(2), temperature=0.0,
        cache_dtype=jnp.float32,
    )
    assert toks.shape == (2, 9)
