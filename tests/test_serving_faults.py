"""Fault tolerance of the serving stack (midgpt_tpu.serving.faults):
FaultPlan parse/spec roundtrip, allocator quarantine invariants, typed
admission rejection + bounded-queue shed/defer, pool-exhaustion edges
(single request parks instead of MemoryError; two-request eviction
thrash trips the livelock guard), and the cluster failover suite —
replica crash / wedged dispatch (wall-clock watchdog) / transient retry
with capped backoff — with the landing gate asserted directly: every
surviving request's greedy stream is BIT-IDENTICAL to the fault-free
run, and the allocator identity ``free + held + cached + quarantined ==
num_pages`` holds after every injected fault. The slow tier runs the
same composite chaos plan across the prefix-cache x chunked-prefill x
speculation x kv-quant matrix."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.config import ModelConfig
from midgpt_tpu.models.gpt import GPT
from midgpt_tpu.serving import (
    AdmissionRejected,
    ClusterUnavailable,
    FaultEvent,
    FaultPlan,
    PageAllocator,
    PoolOverloaded,
    ServingCluster,
    ServingEngine,
)

CFG = ModelConfig(
    block_size=64, vocab_size=96, n_layer=2, n_head=4, n_embd=32,
    dropout=0.0, attn_impl="naive", remat="none",
)


@pytest.fixture(scope="module")
def model():
    return GPT.init(jax.random.PRNGKey(0), CFG)


def _prompts(n, base_len=5, stride=3):
    return [
        np.asarray(
            jax.random.randint(
                jax.random.PRNGKey(100 + i), (base_len + stride * i,), 0,
                CFG.vocab_size,
            )
        )
        for i in range(n)
    ]


def _drive(obj, check_engines, max_steps=200):
    """Step ``obj`` (engine or cluster) to drain, re-checking the
    allocator identity on every live engine after every scheduler step —
    i.e. after every injected fault (events fire at step tops)."""
    for _ in range(max_steps):
        if not obj.has_work:
            return
        obj.step()
        for e in check_engines():
            e.alloc.check()
    raise AssertionError(f"did not drain in {max_steps} steps")


@pytest.fixture(scope="module")
def cluster_case(model):
    """One fault-free reference run: 4 requests through a single engine.
    Every chaos variant below must reproduce these streams bit-for-bit
    (and the ref run warms the program cache, so chaos steps are
    dispatch-only — which the watchdog tests rely on for timing)."""
    prompts = _prompts(4, base_len=5, stride=2)
    kw = dict(
        slots=2, page_size=8, window=4, temperature=0.0,
        cache_dtype=jnp.float32,
    )
    eng = ServingEngine(model, **kw)
    rids = [eng.submit(p, 8, seed=i) for i, p in enumerate(prompts)]
    fin = eng.run()
    refs = [list(map(int, fin[r].tokens)) for r in rids]
    return prompts, kw, refs


def _chaos_run(model, prompts, kw, plan, n_new=8, **cluster_kw):
    cl = ServingCluster(model, fault_plan=plan, **cluster_kw, **kw)
    rids = [cl.submit(p, n_new, seed=i) for i, p in enumerate(prompts)]
    _drive(cl, lambda: [cl.engines[i] for i in cl._alive()])
    fin = cl.finished
    assert sorted(fin) == sorted(rids), "every request must finish"
    return cl, [list(map(int, fin[r].tokens)) for r in rids]


# ---------------------------------------------------------------------------
# FaultPlan: spec grammar + determinism plumbing
# ---------------------------------------------------------------------------


def test_fault_plan_parse_spec_roundtrip():
    spec = "6:crash@1;4:wedge@0:0.5;3:transient;2:exhaust@0:all:3"
    plan = FaultPlan.parse(spec)
    assert len(plan) == 4
    # events sort by step, stably
    assert [ev.step for ev in plan] == [2, 3, 4, 6]
    assert plan.replicas == {0, 1}
    assert FaultPlan.parse(plan.spec()).spec() == plan.spec()
    ex = plan.events_for(0, 2)[0]
    assert ex.kind == "exhaust" and ex.pages == -1 and ex.hold_steps == 3
    assert plan.events_for(0, 4)[0].seconds == 0.5
    assert plan.events_for(1, 6)[0].kind == "crash"
    assert plan.events_for(1, 2) == []
    # a bounded-pages exhaust roundtrips its count too
    ev = FaultEvent(step=1, kind="exhaust", pages=2, hold_steps=2)
    assert FaultPlan.parse(FaultPlan([ev]).spec()).events[0].pages == 2


def test_fault_event_validation():
    with pytest.raises(AssertionError):
        FaultEvent(step=0, kind="crash")  # steps are 1-based
    with pytest.raises(AssertionError):
        FaultEvent(step=1, kind="meteor")


# ---------------------------------------------------------------------------
# Allocator quarantine (the `exhaust` fault's host-side mechanism)
# ---------------------------------------------------------------------------


def test_allocator_quarantine_invariants():
    a = PageAllocator(8)
    held = a.alloc(3)
    assert a.quarantine(2) == 2
    a.check()
    assert a.free_pages == 3 and a.quarantined_pages == 2
    assert a.quarantine() == 3  # -1 = the rest of the free list
    a.check()
    assert a.free_pages == 0 and a.quarantined_pages == 5
    # held pages are untouched; new allocation feels the pressure
    with pytest.raises(MemoryError):
        a.alloc(1)
    a.free(held)
    a.check()
    assert a.free_pages == 3  # frees bypass the quarantine
    assert a.release_quarantined() == 5
    a.check()
    assert a.free_pages == 8 and a.quarantined_pages == 0
    assert a.quarantine(99) == 8  # capped at the free list


# ---------------------------------------------------------------------------
# Typed admission + bounded-queue overload policy
# ---------------------------------------------------------------------------


def test_admission_rejections_typed_and_counted(model):
    eng = ServingEngine(
        model, slots=1, page_size=4, num_pages=2, window=2,
        cache_dtype=jnp.float32,
    )
    cases = [
        ("bad_budget", lambda: eng.submit(np.zeros(4, np.int32), 0)),
        ("budget_exceeds_block",
         lambda: eng.submit(np.zeros(4, np.int32), CFG.block_size)),
        ("empty_prompt", lambda: eng.submit(np.zeros(0, np.int32), 4)),
        # 4 prompt + 8 new = 3 pages over a 2-page pool: never servable
        ("lifetime_exceeds_pool",
         lambda: eng.submit(np.zeros(4, np.int32), 8)),
    ]
    for reason, call in cases:
        with pytest.raises(AdmissionRejected) as exc:
            call()
        assert exc.value.reason == reason
    st = eng.stats()
    assert st["admission_rejected"] == 4
    assert st["reject_reasons"] == {r: 1 for r, _ in cases}
    assert not eng.queue, "rejected requests must not be enqueued"


def test_cluster_passes_rejection_through_without_burning_rid(model):
    cl = ServingCluster(
        model, replicas=2, slots=1, page_size=4, num_pages=2, window=2,
        cache_dtype=jnp.float32,
    )
    with pytest.raises(AdmissionRejected):
        cl.submit(np.zeros(4, np.int32), 8)
    assert not cl._route and cl._next_rid == 0
    assert cl.stats()["reject_reasons"] == {"lifetime_exceeds_pool": 1}


def test_cluster_submit_spills_over_a_full_queue(model):
    """The routing metric (queue + parked + active) is not the metric
    the bound is enforced on (queue alone): when the least-loaded
    replica's queue is full, admission must spill to a replica with
    queue room instead of shedding — and shed only when EVERY healthy
    queue is full."""
    cl = ServingCluster(
        model, replicas=2, slots=2, page_size=8, window=4,
        cache_dtype=jnp.float32, max_queue=1, overload_policy="shed",
    )
    prompts = _prompts(4, base_len=4, stride=0)
    # replica 1: two ACTIVE requests (load 2, queue 0); replica 0: a
    # full queue (load 1) — least-loaded picks 0, but only 1 has room
    cl.engines[1].submit(prompts[0], 16)
    cl.engines[1].step()  # admit (the queue bound is on the queue alone)
    cl.engines[1].submit(prompts[1], 16)
    cl.engines[1].step()
    assert len(cl.engines[1]._active_slots()) == 2
    assert not cl.engines[1].queue
    cl.engines[0].submit(prompts[2], 8)
    rid = cl.submit(prompts[3], 8)
    assert cl._route[rid][0] == 1, "must spill to the replica with room"
    # now every queue is full: the overload outcome finally surfaces
    with pytest.raises(AdmissionRejected) as exc:
        cl.submit(prompts[3], 8)
    assert exc.value.reason == "queue_full"


def test_bounded_queue_defer_and_shed(model):
    prompts = _prompts(3, base_len=4, stride=0)
    defer = ServingEngine(
        model, slots=1, page_size=8, window=4, cache_dtype=jnp.float32,
        max_queue=2, overload_policy="defer",
    )
    rids = [defer.submit(p, 4) for p in prompts[:2]]
    with pytest.raises(PoolOverloaded) as exc:
        defer.submit(prompts[2], 4)
    assert exc.value.reason == "queue_full"
    st = defer.stats()
    assert st["deferred_submits"] == 1 and st["shed_requests"] == 0
    assert st["admission_rejected"] == 0, "defer is not a rejection"
    fin = defer.run()  # the queue drains; deferred work can resubmit
    assert sorted(fin) == sorted(rids)
    defer.submit(prompts[2], 4)  # backpressure lifted

    shed = ServingEngine(
        model, slots=1, page_size=8, window=4, cache_dtype=jnp.float32,
        max_queue=1, overload_policy="shed",
    )
    shed.submit(prompts[0], 4)
    with pytest.raises(AdmissionRejected) as exc:
        shed.submit(prompts[1], 4)
    assert exc.value.reason == "queue_full"
    st = shed.stats()
    assert st["shed_requests"] == 1
    assert st["reject_reasons"] == {"queue_full": 1}


# ---------------------------------------------------------------------------
# Pool-exhaustion edges: park instead of MemoryError; livelock guard
# ---------------------------------------------------------------------------


def test_single_request_pool_exhaustion_parks_and_recovers(model):
    """A lone request whose window growth hits an exhausted pool (all
    free pages quarantined mid-decode) PARKS with progress kept — the
    old hard ``MemoryError`` — and resumes bit-identically once pages
    come back."""
    kw = dict(
        slots=1, page_size=4, num_pages=4, window=4, temperature=0.0,
        cache_dtype=jnp.float32, prefix_cache=False,
    )
    prompt = _prompts(1, base_len=3)[0]
    ref_eng = ServingEngine(model, **kw)
    ref_rid = ref_eng.submit(prompt, 12)
    ref = list(map(int, ref_eng.run()[ref_rid].tokens))

    plan = FaultPlan([FaultEvent(step=2, kind="exhaust", hold_steps=2)])
    eng = ServingEngine(model, fault_hook=plan.hook(0), **kw)
    rid = eng.submit(prompt, 12)
    _drive(eng, lambda: [eng])
    assert list(map(int, eng.finished[rid].tokens)) == ref
    st = eng.stats()
    assert st["faults_injected"] == 1
    assert st["overload_parks"] >= 1, "the lone request must have parked"
    assert st["parked_requests"] == 0
    assert eng.alloc.held_pages == 0 and eng.alloc.quarantined_pages == 0


def test_eviction_thrash_livelock_guard(model):
    """Two requests whose window growth trades the same pages. The first
    growth pass evicts the just-prefilled loser at ZERO progress — the
    opening beat of an eviction livelock — and the guard parks it at
    ``park_threshold`` zero-progress evictions instead of letting it
    re-prefill in a loop. At the default threshold the same trace is
    allowed to keep trading (every later steal hits a victim that
    progressed, so thrash resets — that is productive preemption, not
    livelock). Both modes finish with streams bit-identical to
    uncontended runs."""
    kw = dict(
        slots=2, page_size=4, num_pages=5, window=4, temperature=0.0,
        cache_dtype=jnp.float32, prefix_cache=False,
    )
    prompts = _prompts(2, base_len=8, stride=0)
    # uncontended reference: same geometry (programs already compiled),
    # one request at a time so no eviction pressure exists
    ref_eng = ServingEngine(model, **kw)
    refs = []
    for i, p in enumerate(prompts):
        r = ref_eng.submit(p, 8, seed=i)
        refs.append(list(map(int, ref_eng.run()[r].tokens)))

    def contended(park_threshold):
        eng = ServingEngine(model, park_threshold=park_threshold, **kw)
        rids = [eng.submit(p, 8, seed=i) for i, p in enumerate(prompts)]
        _drive(eng, lambda: [eng])
        assert [
            list(map(int, eng.finished[r].tokens)) for r in rids
        ] == refs, f"park_threshold={park_threshold} diverged"
        assert eng.alloc.held_pages == 0
        return eng.stats()

    st = contended(park_threshold=1)
    assert st["livelock_parks"] >= 1, "the thrash guard must have fired"
    assert st["parked_requests"] == 0
    # default threshold: the trace's steals all made progress, so the
    # guard correctly stays out of the way
    st = contended(park_threshold=2)
    assert st["livelock_parks"] == 0
    assert st["evictions"] >= 2


# ---------------------------------------------------------------------------
# Cluster failover: crash / transient retry / wedge watchdog
# ---------------------------------------------------------------------------


def test_cluster_crash_failover_bit_identical(model, cluster_case):
    """Replica 0 crashes mid-decode (its requests have emitted tokens):
    the survivors finish EVERY request with streams bit-equal to the
    fault-free run — re-queueing is the eviction path, placement is
    invariant, so failover replay is exact."""
    prompts, kw, refs = cluster_case
    cl, got = _chaos_run(
        model, prompts, kw, FaultPlan.parse("2:crash@0"), replicas=2
    )
    assert got == refs
    assert cl.health == ["dead", "healthy"]
    assert cl.health_reason[0] == "crashed"
    st = cl.stats()
    assert st["failovers"] == 1 and st["dead_replicas"] == 1
    assert st["requeued_requests"] >= 1
    assert st["faults_injected"] == 1
    # the dead replica's emitted-so-far work was preserved, not redone
    assert cl.engines[0].tokens_generated >= 1


def test_cluster_transient_retry_same_replica(model, cluster_case):
    """One scripted transient dispatch error: the same replica retries
    (suspect -> healthy), no failover, streams identical."""
    prompts, kw, refs = cluster_case
    cl, got = _chaos_run(
        model, prompts, kw, FaultPlan.parse("2:transient@0"),
        replicas=2, backoff_s=0.0,
    )
    assert got == refs
    assert cl.health == ["healthy", "healthy"]
    st = cl.stats()
    assert st["retries"] == 1 and st["failovers"] == 0
    assert st["watchdog_trips"] == 0


def test_cluster_transient_exhaustion_fails_over(model, cluster_case):
    """max_retries consecutive transients exhaust the backoff ladder:
    the replica goes dead and its backlog fails over — still
    bit-identical."""
    prompts, kw, refs = cluster_case
    # step 2 raises; retries re-enter step() at fault_steps 3, 4, 5
    plan = FaultPlan.parse(
        "2:transient@0;3:transient@0;4:transient@0;5:transient@0"
    )
    cl, got = _chaos_run(
        model, prompts, kw, plan, replicas=2, max_retries=3, backoff_s=0.0,
    )
    assert got == refs
    assert cl.health[0] == "dead"
    assert cl.health_reason[0] == "transient_exhausted"
    st = cl.stats()
    assert st["retries"] == 3 and st["failovers"] == 1


def test_cluster_wedge_watchdog_failover(model, cluster_case):
    """The wedged-relay case (r4/r5 BENCH post-mortems), scripted: a
    dispatch stalls past the wall-clock watchdog; the replica is
    abandoned (dead, never re-stepped) and its backlog fails over
    bit-identically."""
    prompts, kw, refs = cluster_case
    cl, got = _chaos_run(
        model, prompts, kw, FaultPlan.parse("2:wedge@0:1.5"),
        replicas=2, dispatch_timeout_s=0.5,
    )
    assert got == refs
    assert cl.health == ["dead", "healthy"]
    assert cl.health_reason[0] == "wedged"
    st = cl.stats()
    assert st["watchdog_trips"] == 1 and st["failovers"] == 1
    # COLD failover: a watchdog trip means the wedged step thread may
    # still be running, so the engine is never drained — its slots stay
    # frozen and its requests were re-served from scratch on the
    # survivor (from the cluster's submission record)
    assert cl.engines[0]._active_slots(), (
        "a watchdog-tripped engine must not be drained"
    )
    assert st["requeued_requests"] >= 1


def test_all_replicas_dead_raises_cluster_unavailable(model, cluster_case):
    prompts, kw, _ = cluster_case
    cl = ServingCluster(
        model, replicas=2, fault_plan=FaultPlan.parse("1:crash@0;1:crash@1"),
        **kw,
    )
    for i, p in enumerate(prompts):
        cl.submit(p, 8, seed=i)
    with pytest.raises(ClusterUnavailable):
        cl.run()
    assert cl.health == ["dead", "dead"]
    with pytest.raises(ClusterUnavailable):
        cl.submit(prompts[0], 8)


# ---------------------------------------------------------------------------
# Disaggregated handoff faults (prefill -> decode page handoff)
# ---------------------------------------------------------------------------


def test_handoff_fault_reserves_cold_bit_identical(model, cluster_case):
    """A scripted ``handoff`` fault poisons the next export on the
    prefill replica: HandoffFailed fires BEFORE any state leaves the
    slot, the cluster abandons that copy and re-serves the request cold
    from its submission record — streams bit-identical, and the replica
    stays healthy (a dropped handoff is not a crash)."""
    prompts, kw, refs = cluster_case
    cl, got = _chaos_run(
        model, prompts, kw, FaultPlan.parse("2:handoff@0"),
        prefill_replicas=1, decode_replicas=1,
    )
    assert got == refs
    assert cl.health == ["healthy", "healthy"]
    st = cl.stats()
    assert st["handoff_failures"] == 1
    assert st["requeued_requests"] >= 1
    # the failed export never counted; the cold re-serve hands off fine
    assert st["handoffs"] == len(prompts)
    assert st["faults_injected"] == 1


def test_prefill_replica_crash_mid_disagg_failover(model, cluster_case):
    """A prefill-pool replica crashes with requests in flight: its
    backlog re-serves cold on the SURVIVING prefill replica (submission
    targets stay inside the prefill pool) and every stream is
    bit-identical — handoff adds no new failover state, and requests
    already imported into the decode pool are untouched."""
    prompts, kw, refs = cluster_case
    # step 1: the crash fires before the first handoff pump, so the
    # replica still owns its share of the backlog when it dies
    cl, got = _chaos_run(
        model, prompts, kw, FaultPlan.parse("1:crash@0"),
        prefill_replicas=2, decode_replicas=1,
    )
    assert got == refs
    assert cl.health == ["dead", "healthy", "healthy"]
    st = cl.stats()
    assert st["failovers"] == 1 and st["requeued_requests"] >= 1
    assert st["handoffs"] == len(prompts)
    assert st["handoff_failures"] == 0


def test_prefill_pool_death_degrades_to_decode_pool(model, cluster_case):
    """The ENTIRE prefill pool dies: submission targets degrade to the
    surviving decode pool — a decode-class engine is a full engine, so
    the re-served requests prefill and decode locally (no handoff) and
    the streams still match the monolithic reference."""
    prompts, kw, refs = cluster_case
    cl, got = _chaos_run(
        model, prompts, kw, FaultPlan.parse("2:crash@0"),
        prefill_replicas=1, decode_replicas=1,
    )
    assert got == refs
    assert cl.health == ["dead", "healthy"]
    st = cl.stats()
    assert st["failovers"] == 1
    assert st["handoff_failures"] == 0


# ---------------------------------------------------------------------------
# The chaos acceptance matrix
# ---------------------------------------------------------------------------

# one composite plan: transient (retried) then crash on replica 0,
# allocator exhaustion on the survivor, a wedge on replica 2 — every
# fault kind in one scripted, replayable run with replica 1 surviving
_CHAOS = "2:transient@0;4:crash@0;3:exhaust@1:all:2;3:wedge@2:1.5"


def _chaos_matrix_case(model, prefix_cache, chunk, spec, kvq):
    prompts = _prompts(6, base_len=5, stride=2)
    # a shared prefix on half the trace gives the cache something to hit
    prompts = [
        np.concatenate([prompts[0][:4], p]) if i % 2 else p
        for i, p in enumerate(prompts)
    ]
    kw = dict(
        slots=2, page_size=8, window=4, temperature=0.0,
        cache_dtype=jnp.float32, prefix_cache=prefix_cache,
        prefill_chunk=chunk, speculate=spec, kv_quant=kvq,
    )
    ref_eng = ServingEngine(model, **kw)
    rids = [ref_eng.submit(p, 16, seed=i) for i, p in enumerate(prompts)]
    fin = ref_eng.run()
    refs = [list(map(int, fin[r].tokens)) for r in rids]

    cl, got = _chaos_run(
        model, prompts, kw, FaultPlan.parse(_CHAOS),
        replicas=3, dispatch_timeout_s=0.5, max_retries=2, backoff_s=0.0,
        n_new=16,
    )
    assert got == refs, "surviving streams must be bit-identical"
    assert cl.health[1] == "healthy" and "dead" in cl.health
    st = cl.stats()
    assert st["failovers"] >= 1
    assert st["faults_injected"] >= 3
    for e in cl.engines:
        assert e.alloc.quarantined_pages == 0
    # replaying the same plan over the same trace is bit-identical too
    cl2, got2 = _chaos_run(
        model, prompts, kw, FaultPlan.parse(_CHAOS),
        replicas=3, dispatch_timeout_s=0.5, max_retries=2, backoff_s=0.0,
        n_new=16,
    )
    assert got2 == got
    assert cl2.health == cl.health


def test_chaos_composite_plan_bit_identical(model):
    """Acceptance (fast tier): crash mid-decode + wedged dispatch +
    transient error + pool exhaustion in ONE scripted plan — every
    request finishes, streams bit-equal the fault-free run, the run
    replays identically, and no fault path raises."""
    _chaos_matrix_case(model, True, None, 0, None)


def test_chaos_telemetry_flight_dumps_and_replay(model, cluster_case,
                                                 tmp_path):
    """Chaos + telemetry composition (serving.telemetry): one composite
    plan drives every terminal fault path — crash (warm failover),
    wedge past the watchdog (cold), and exhausted transient retries —
    with tracing ON and a flight_dir armed. Surviving streams stay
    bit-identical to the fault-free run, the replayed run produces
    IDENTICAL per-replica event sequences (wall-clock annotations
    excluded — Event.signature), and every dead replica left a
    flight-recorder artifact carrying its event/dispatch rings
    including the scripted injection that killed it."""
    import json
    import os

    prompts, kw, refs = cluster_case
    # replica 0 crashes, replica 1 wedges into the 0.5 s watchdog,
    # replica 2 exhausts max_retries=2 transients (steps 2, 3, 4 — the
    # retries re-enter step()), replica 3 survives and drains everything
    spec = "2:crash@0;2:wedge@1:1.5;2:transient@2;3:transient@2;4:transient@2"

    def run(sub):
        d = tmp_path / sub
        d.mkdir()
        cl = ServingCluster(
            model, replicas=4, fault_plan=FaultPlan.parse(spec),
            dispatch_timeout_s=0.5, max_retries=2, backoff_s=0.0,
            telemetry=True, flight_dir=str(d), **kw,
        )
        rids = [cl.submit(p, 8, seed=i) for i, p in enumerate(prompts)]
        _drive(cl, lambda: [cl.engines[i] for i in cl._alive()])
        return cl, [list(map(int, cl.finished[r].tokens)) for r in rids]

    cl, got = run("a")
    assert got == refs, "surviving streams must stay bit-identical"
    assert cl.health == ["dead", "dead", "dead", "healthy"]
    assert {os.path.basename(p) for p in cl.flight_dumps} == {
        "flight_replica0_crashed.json",
        "flight_replica1_wedged.json",
        "flight_replica2_transient_exhausted.json",
    }, "crash, watchdog, and exhausted-retry paths must all dump"
    for p in cl.flight_dumps:
        rec = json.load(open(p))
        assert rec["telemetry"]["events"], p
        assert any(
            e["kind"] == "fault" for e in rec["telemetry"]["events"]
        ), f"{p} must record the scripted injection"
        assert rec["stats"]["faults_injected"] >= 1

    sigs = [t.sequence_signature() for t in cl.telemetries]
    assert all(len(s) > 0 for s in sigs)
    cl2, got2 = run("b")
    assert got2 == got
    assert [t.sequence_signature() for t in cl2.telemetries] == sigs, (
        "replaying the same plan must reproduce every replica's event "
        "sequence exactly (wall clock excluded)"
    )


@pytest.mark.slow
@pytest.mark.parametrize(
    "prefix_cache,chunk,spec,kvq",
    [
        (False, None, 0, None),
        (False, 8, 0, None),
        (True, 8, 4, None),
        (True, None, 4, "int8"),
    ],
    ids=["nocache", "chunked", "cache-chunk-spec", "cache-spec-kvq8"],
)
def test_chaos_matrix_bit_identical(model, prefix_cache, chunk, spec, kvq):
    """Acceptance (slow tier): the same composite chaos plan across the
    prefix-cache x chunked-prefill x speculation x kv-quant matrix."""
    _chaos_matrix_case(model, prefix_cache, chunk, spec, kvq)
