"""Multi-slice hybrid mesh: pure layout function + create_mesh wiring +
an end-to-end train step over a simulated 2-slice mesh (SURVEY.md 2.6
"must build": DP-only over DCN, FSDP x TP within each slice)."""

import dataclasses
import types

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from midgpt_tpu.config import ExperimentConfig, MeshConfig, ModelConfig
from midgpt_tpu.parallel.mesh import (
    create_mesh,
    group_by_slice,
    hybrid_device_layout,
)
from midgpt_tpu.parallel.sharding import make_global_array
from midgpt_tpu.train import init_state, make_optimizer, make_train_step


def _fake_devices(n, slice_of=None):
    return [
        types.SimpleNamespace(
            id=i, slice_index=None if slice_of is None else slice_of(i)
        )
        for i in range(n)
    ]


def test_group_by_slice_contiguous_without_attr():
    devs = _fake_devices(8)
    g = group_by_slice(devs, 2)
    assert [[d.id for d in grp] for grp in g] == [[0, 1, 2, 3], [4, 5, 6, 7]]


def test_group_by_slice_uses_slice_index():
    # interleaved slice assignment: grouping must follow slice_index,
    # not listing order
    devs = _fake_devices(8, slice_of=lambda i: i % 2)
    g = group_by_slice(devs, 2)
    assert [[d.id for d in grp] for grp in g] == [[0, 2, 4, 6], [1, 3, 5, 7]]


def test_hybrid_layout_slice_on_outer_replica():
    devs = _fake_devices(8, slice_of=lambda i: i // 4)
    arr = hybrid_device_layout(devs, (1, 2, 2, 1, 2), num_slices=2)
    assert arr.shape == (1, 2, 2, 1, 2)
    # replica index 0 must be entirely slice 0, replica index 1 slice 1:
    # only the replica axis crosses DCN
    for r in range(2):
        slices = {d.slice_index for d in arr[0, r].flat}
        assert slices == {r}


def test_hybrid_layout_rejects_bad_replica():
    devs = _fake_devices(8)
    with pytest.raises(AssertionError):
        hybrid_device_layout(devs, (1, 1, 4, 1, 2), num_slices=2)


def test_create_mesh_num_slices_cpu(mesh8):
    # 8 simulated CPU devices (no slice_index) -> contiguous halves
    mesh = create_mesh(MeshConfig(replica=2, fsdp=2, sequence=1, tensor=2, num_slices=2))
    assert dict(mesh.shape) == {
        "pipeline": 1, "replica": 2, "fsdp": 2, "sequence": 1, "tensor": 2
    }
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    first_slice = set(ids[0, 0].flatten().tolist())
    second_slice = set(ids[0, 1].flatten().tolist())
    assert first_slice.isdisjoint(second_slice)
    # contiguous partition for simulated devices
    assert first_slice == set(range(min(first_slice), min(first_slice) + 4))


@pytest.mark.slow
def test_multislice_train_step_runs(mesh8):
    cfg = ExperimentConfig(
        model=ModelConfig(
            block_size=64, vocab_size=128, n_layer=2, n_head=4, n_embd=32,
            dropout=0.0, attn_impl="naive", remat="none",
        ),
        learning_rate=1e-3, warmup_steps=2, lr_decay_steps=10, max_steps=10,
        batch_size=8, g_accum_iters=2,
        mesh=MeshConfig(replica=2, fsdp=2, sequence=1, tensor=2, num_slices=2),
    )
    mesh = create_mesh(cfg.mesh)
    tx, _ = make_optimizer(cfg)
    state = init_state(cfg, mesh, tx, jax.random.PRNGKey(0))
    step = make_train_step(cfg, tx, mesh)
    rng = np.random.default_rng(0)
    x = rng.integers(0, 128, size=(2, 4, 64), dtype=np.int32)
    y = rng.integers(0, 128, size=(2, 4, 64), dtype=np.int32)
    spec = P(None, ("replica", "fsdp"), "sequence")
    xg, yg = make_global_array(x, mesh, spec), make_global_array(y, mesh, spec)
    state, loss = step(state, xg, yg, jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))

    # parity: the same problem on a single-slice mesh of the same shape
    # gives the same loss (the hybrid layout only permutes device placement)
    mesh1 = create_mesh(MeshConfig(replica=2, fsdp=2, sequence=1, tensor=2))
    state1 = init_state(cfg, mesh1, tx, jax.random.PRNGKey(0))
    step1 = make_train_step(cfg, tx, mesh1)
    xg1, yg1 = make_global_array(x, mesh1, spec), make_global_array(y, mesh1, spec)
    state1, loss1 = step1(state1, xg1, yg1, jax.random.PRNGKey(1))
    np.testing.assert_allclose(float(loss), float(loss1), rtol=1e-5)
