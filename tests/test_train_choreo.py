"""Mixed-precision choreography prover for the fused train window.

Scan-discovery units on tiny hand-built jaxprs, then the real proof:
the cached ``train.get_train_window`` trace at every audit geometry
must satisfy all seven contract clauses — and each injected precision
fault (bf16 Adam moments, f32 matmul operands) must fail EXACTLY its
own clause while every other clause stays green. Traces only, no XLA
compilation: the whole file runs in seconds.
"""

import jax
import jax.numpy as jnp
import optax
import pytest

from midgpt_tpu.analysis.budgets import TRAIN_AUDIT_GEOMETRIES
from midgpt_tpu.analysis.train_choreo import (
    ScanRec,
    collapse_dot_kinds,
    find_accum_scan,
    find_window_scan,
    prove_window_choreography,
    window_scans,
)
from midgpt_tpu.config import get_config

CHECK_NAMES = {
    "matmul-compute-dtype",
    "master-params-dtype",
    "adam-moments-dtype",
    "softmax-loss-f32",
    "grad-accum-carry",
    "window-scan-carry",
    "remat-recompute",
}


# ---------------------------------------------------------------------------
# scan discovery on hand-built jaxprs
# ---------------------------------------------------------------------------


def test_window_scans_depth_annotation():
    def inner(c, x):
        return c + x, x

    def outer(c, xs):
        c2, _ = jax.lax.scan(inner, c, xs)
        return c2, c2

    def prog(c, xss):
        return jax.lax.scan(outer, c, xss)

    closed = jax.make_jaxpr(prog)(
        jnp.zeros(()), jnp.zeros((4, 3))
    )
    scans = window_scans(closed)
    assert [(s.depth, s.length) for s in scans] == [(0, 4), (1, 3)]
    assert scans[0].carry_dtypes == ("float32",)
    assert scans[0].carry_shapes == ((),)


def test_window_scans_sees_through_pjit():
    """Call-like wrappers (jit) are depth-transparent: a scan inside a
    nested jit still reports depth 0."""

    @jax.jit
    def wrapped(c, xs):
        return jax.lax.scan(lambda c, x: (c + x, x), c, xs)

    closed = jax.make_jaxpr(lambda c, xs: wrapped(c, xs))(
        jnp.zeros(()), jnp.zeros((5,))
    )
    scans = window_scans(closed)
    assert [(s.depth, s.length) for s in scans] == [(0, 5)]


def test_find_window_scan_requires_int32_scalar_carry():
    opt = ScanRec(
        depth=0, length=4,
        carry_dtypes=("float32", "int32", "float32"),
        carry_shapes=((8, 8), (), (8,)),
    )
    data = ScanRec(
        depth=0, length=4,
        carry_dtypes=("float32",), carry_shapes=((8, 8),),
    )
    assert find_window_scan([data, opt], 4) is opt
    assert find_window_scan([data], 4) is None
    # wrong length: a layer scan of trip 4 is not the K=8 window
    assert find_window_scan([opt], 8) is None


def test_find_accum_scan_discriminates_layer_scan():
    layer = ScanRec(
        depth=1, length=2,
        carry_dtypes=("bfloat16",), carry_shapes=((2, 256, 64),),
    )
    accum = ScanRec(
        depth=1, length=2,
        carry_dtypes=("bfloat16", "bfloat16", "bfloat16", "float32"),
        carry_shapes=((8, 8), (8,), (8, 8), ()),
    )
    assert find_accum_scan([layer, accum], True) is accum
    # without a window scan the accum scan sits at depth 0
    assert find_accum_scan([layer, accum], False) is None


def test_collapse_dot_kinds_folds_projection_flavors():
    assert collapse_dot_kinds(("rope", ("bfloat16",), ("bfloat16",))) == (
        "dot", ("bfloat16",), ("bfloat16",)
    )
    assert collapse_dot_kinds(("exp", ("float32",), ("float32",)))[0] == "exp"


# ---------------------------------------------------------------------------
# the real window: green on every audit geometry
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def base_cfg():
    return get_config("openwebtext")


@pytest.mark.parametrize("geometry", sorted(TRAIN_AUDIT_GEOMETRIES))
def test_prover_green_on_cached_window(base_cfg, geometry):
    from midgpt_tpu.analysis.harness import prove_train_window_choreography

    report = prove_train_window_choreography(base_cfg, geometry, 1)
    assert report.ok, report.to_dict()
    by_name = {c.name: c for c in report.checks}
    assert set(by_name) == CHECK_NAMES
    # no vacuous pass: the grad-accum clause must have FOUND the scan
    # (deferral to the dispatch gate reads "no grad-accum scan")
    assert by_name["grad-accum-carry"].detail.startswith("found:")
    assert by_name["window-scan-carry"].detail.startswith(
        "window scan length=1"
    )
    assert report.programs == ("train_window", "train_window+remat")


def test_prover_green_at_k4(base_cfg):
    from midgpt_tpu.analysis.harness import prove_train_window_choreography

    report = prove_train_window_choreography(base_cfg, "fsdp", 4)
    assert report.ok, report.to_dict()
    by_name = {c.name: c for c in report.checks}
    assert "length=4" in by_name["window-scan-carry"].detail


# ---------------------------------------------------------------------------
# fault injection: each bug class fails exactly its own clause
# ---------------------------------------------------------------------------


def _trace_fsdp_window(cfg, tx=None):
    from midgpt_tpu.analysis.harness import (
        shrink_for_train_audit,
        trace_train_window,
    )

    audit = shrink_for_train_audit(cfg, "fsdp")
    return audit, trace_train_window(audit, 1, tx=tx, use_cache=False)


def _assert_only_red(report, bad_name):
    by_name = {c.name: c for c in report.checks}
    assert not by_name[bad_name].ok, by_name[bad_name]
    green = {n: c.ok for n, c in by_name.items() if n != bad_name}
    assert all(green.values()), green
    return by_name[bad_name]


def test_bf16_moments_fault_trips_only_adam_clause(base_cfg):
    """optax.scale_by_adam(mu_dtype=bfloat16) — the classic silent
    half-precision first moment. Only adam-moments-dtype may go red:
    matmuls, param masters, loss dtype and scan carries are all still
    correct."""
    from midgpt_tpu.analysis.harness import shrink_for_train_audit
    from midgpt_tpu.train import make_lr_schedule

    audit = shrink_for_train_audit(base_cfg, "fsdp")
    wd = (
        audit.weight_decay / audit.learning_rate
        if getattr(audit, "independent_wd", False)
        else audit.weight_decay
    )
    tx_bad = optax.chain(
        optax.clip_by_global_norm(audit.grad_clip),
        optax.scale_by_adam(
            b1=audit.beta1, b2=audit.beta2, mu_dtype=jnp.bfloat16
        ),
        optax.add_decayed_weights(wd),
        optax.scale_by_schedule(make_lr_schedule(audit)),
        optax.scale(-1.0),
    )
    _, (closed, out_tree) = _trace_fsdp_window(base_cfg, tx=tx_bad)
    report = prove_window_choreography(
        closed, out_tree, window_steps=1,
        g_accum_iters=audit.g_accum_iters,
    )
    bad = _assert_only_red(report, "adam-moments-dtype")
    assert "mu_dtype bug class" in bad.detail
    assert "bfloat16" in bad.detail


def test_f32_matmul_fault_trips_only_matmul_clause(base_cfg, monkeypatch):
    """Skip the cast_floating boundary inside the loss: every weight
    dot now runs on f32 operands (double the FLOP bytes, no accuracy
    win). Only matmul-compute-dtype may go red — the master params,
    moments and loss accumulation are still f32 as required."""
    from midgpt_tpu import train as train_mod
    from midgpt_tpu.analysis.harness import shrink_for_train_audit
    from midgpt_tpu.pytree import cast_floating

    orig_loss_fn = train_mod.loss_fn

    def f32_loss_fn(model, *args, **kw):
        return orig_loss_fn(
            cast_floating(model, jnp.float32), *args, **kw
        )

    monkeypatch.setattr(train_mod, "loss_fn", f32_loss_fn)
    audit = shrink_for_train_audit(base_cfg, "fsdp")
    _, (closed, out_tree) = _trace_fsdp_window(base_cfg)
    report = prove_window_choreography(
        closed, out_tree, window_steps=1,
        g_accum_iters=audit.g_accum_iters,
    )
    bad = _assert_only_red(report, "matmul-compute-dtype")
    assert "non-bfloat16 float operands" in bad.detail
