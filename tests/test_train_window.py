"""Fused multi-step dispatch (cfg.steps_per_dispatch / make_train_window):

- K=4 reproduces the K=1 per-step loss sequence BIT-FOR-BIT on the
  8-device CPU mesh (program-level and end-to-end through train(),
  including across a checkpoint resume landing mid-run). Bit-exactness is
  pinned at compute_dtype=float32: XLA CPU's bf16 loop codegen
  reassociates ~1 ULP inside multi-iteration while loops (the same
  backend artifact tests/test_train.py::test_resume_continuity notes for
  restarts), which a tolerance-free CPU gate can't distinguish from a
  real regression.
- per-step (loss, grad-norm, lr) come out of the scan as stacked [K]
  outputs with no host transfer during the dispatch;
- the steady-state loop issues ceil(steps / K) train launches;
- eval_interval misaligned with K fails fast at resolve time;
- K=1 bypasses the window machinery entirely.
"""

import dataclasses
import json
import os

import jax
import numpy as np
import pytest

from midgpt_tpu.config import (
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    resolve_dispatch_intervals,
)
from midgpt_tpu.data import write_tokens
from midgpt_tpu.train import (
    init_state,
    make_optimizer,
    make_train_step,
    make_train_window,
    train,
    window_plan,
)


def _base_cfg(**kw) -> ExperimentConfig:
    defaults = dict(
        model=ModelConfig(
            block_size=32, vocab_size=64, n_layer=2, n_head=2, n_embd=64,
            dropout=0.0, attn_impl="naive", remat="none",
        ),
        learning_rate=1e-2, min_lr=1e-3, warmup_steps=2,
        lr_decay_steps=16, max_steps=16,
        batch_size=8, g_accum_iters=2,
        compute_dtype="float32",
        eval_interval=8, eval_batches=1, log_interval=1,
        mesh=MeshConfig(replica=1, fsdp=2, sequence=2, tensor=2),
    )
    defaults.update(kw)
    return ExperimentConfig(**defaults)


def _data_dir(tmp_path) -> str:
    data_dir = str(tmp_path / "data")
    os.makedirs(data_dir, exist_ok=True)
    toks = np.tile(np.arange(64), 4000)
    write_tokens(os.path.join(data_dir, "train.bin"), toks)
    write_tokens(os.path.join(data_dir, "val.bin"), toks[:40_000])
    return data_dir


# ---------------------------------------------------------------------------
# window plan / interval resolution (millisecond tests)
# ---------------------------------------------------------------------------


def test_window_plan_ceil_and_grid_alignment():
    # fresh run: ceil(steps / K) windows, short final window off-grid
    assert window_plan(0, 16, 4) == [4, 4, 4, 4]
    assert window_plan(0, 10, 4) == [4, 4, 2]
    assert len(window_plan(0, 10, 4)) == -(-10 // 4)
    # off-grid resume (e.g. a K=1 checkpoint resumed with K=4): a short
    # FIRST window re-aligns all later window starts to the K grid
    assert window_plan(6, 16, 4) == [2, 4, 4]
    assert window_plan(3, 4, 4) == [1]
    assert window_plan(5, 5, 4) == []
    assert window_plan(0, 7, 1) == [1] * 7


def test_resolve_k1_is_identity():
    cfg = _base_cfg()
    assert resolve_dispatch_intervals(cfg) is cfg


def test_eval_interval_misaligned_fails_fast_with_actionable_message():
    cfg = _base_cfg(eval_interval=10, steps_per_dispatch=4)
    with pytest.raises(ValueError) as ei:
        resolve_dispatch_intervals(cfg)
    msg = str(ei.value)
    assert "eval_interval=10" in msg
    assert "steps_per_dispatch=4" in msg
    assert "8 or 12" in msg  # actionable: the nearest aligned values


def test_ckpt_interval_misaligned_fails_fast():
    cfg = _base_cfg(eval_interval=8, ckpt_interval=6, steps_per_dispatch=4)
    with pytest.raises(ValueError, match="ckpt_interval=6"):
        resolve_dispatch_intervals(cfg)


def test_train_fails_fast_before_any_heavy_work(tmp_path):
    """train() must reject a misaligned config at resolve time — before
    touching data, mesh, or compilation (data_dir doesn't even exist)."""
    cfg = _base_cfg(
        rundir=str(tmp_path / "run"), data_dir=str(tmp_path / "nonexistent"),
        eval_interval=10, steps_per_dispatch=4,
    )
    with pytest.raises(ValueError, match="eval_interval"):
        train(cfg)


# ---------------------------------------------------------------------------
# program-level: K=4 window vs K=1 step, bit for bit
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_window_reproduces_k1_losses_bitwise(mesh8):
    """8 steps as two K=4 fused windows reproduce the 8 single-dispatch
    steps bit-for-bit: per-step losses AND the full updated state."""
    from jax.sharding import PartitionSpec as P

    from midgpt_tpu.parallel.sharding import make_global_array

    cfg = _base_cfg()
    tx, _ = make_optimizer(cfg)
    key = jax.random.PRNGKey(0)
    base = jax.random.PRNGKey(7)
    rng = np.random.default_rng(0)
    n = 8
    xs = rng.integers(0, 64, size=(n, 2, 4, 32), dtype=np.int32)
    ys = rng.integers(0, 64, size=(n, 2, 4, 32), dtype=np.int32)
    spec = P(None, ("replica", "fsdp"), "sequence")
    wspec = P(None, *spec)

    state1 = init_state(cfg, mesh8, tx, key)
    step = make_train_step(cfg, tx, mesh8)
    losses1 = []
    for i in range(n):
        xg = make_global_array(xs[i], mesh8, spec)
        yg = make_global_array(ys[i], mesh8, spec)
        # the K=1 loop derives the step key host-side from the loop index
        state1, loss = step(state1, xg, yg, jax.random.fold_in(base, i))
        losses1.append(np.asarray(loss).copy())

    state2 = init_state(cfg, mesh8, tx, key)
    window = make_train_window(cfg, tx, mesh8, 4)
    losses2 = []
    for w in range(0, n, 4):
        xg = make_global_array(xs[w:w + 4], mesh8, wspec)
        yg = make_global_array(ys[w:w + 4], mesh8, wspec)
        # the window derives fold_in(base, state.step) inside the scan
        state2, out = window(state2, xg, yg, base)
        assert out["loss"].shape == (4,)
        assert out["grad_norm"].shape == (4,)
        assert out["lr"].shape == (4,)
        losses2.append(np.asarray(out["loss"]))

    l1 = np.array(losses1, np.float32)
    l2 = np.concatenate(losses2).astype(np.float32)
    np.testing.assert_array_equal(l1.view(np.uint32), l2.view(np.uint32))
    for a1, a2 in zip(jax.tree.leaves(state1.params),
                      jax.tree.leaves(state2.params)):
        assert bool(jax.numpy.all(a1 == a2)), "params diverged from K=1"
    assert int(state2.step) == n


def test_window_metrics_are_scan_outputs_no_host_sync(mesh8):
    """The per-step metrics come back as device-resident stacked scan
    outputs: the whole fused dispatch completes under a device->host
    transfer guard (a hidden float()/callback inside would trip it)."""
    from jax.sharding import PartitionSpec as P

    from midgpt_tpu.parallel.sharding import make_global_array

    cfg = _base_cfg()
    tx, _ = make_optimizer(cfg)
    state = init_state(cfg, mesh8, tx, jax.random.PRNGKey(0))
    window = make_train_window(cfg, tx, mesh8, 4)
    rng = np.random.default_rng(1)
    wspec = P(None, None, ("replica", "fsdp"), "sequence")
    xg = make_global_array(
        rng.integers(0, 64, size=(4, 2, 4, 32), dtype=np.int32), mesh8, wspec
    )
    yg = make_global_array(
        rng.integers(0, 64, size=(4, 2, 4, 32), dtype=np.int32), mesh8, wspec
    )
    with jax.transfer_guard_device_to_host("disallow"):
        state, out = window(state, xg, yg, jax.random.PRNGKey(7))
        jax.block_until_ready(out)
    assert isinstance(out["loss"], jax.Array) and out["loss"].shape == (4,)
    # one explicit host read drains ALL K steps' metrics at once
    assert np.isfinite(np.asarray(out["loss"])).all()


# ---------------------------------------------------------------------------
# static analysis of the fused program
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_cli_audit_of_fused_window_exits_zero(tmp_path, capsys):
    """Acceptance: the analysis CLI compiles the REAL fused K=4 window
    (make_train_window) for the shipped 124M config and every rule passes
    — in particular donation stays intact across the whole K-step window
    and no host sync hides inside it."""
    from midgpt_tpu.analysis.__main__ import main

    out = tmp_path / "report.json"
    rc = main([
        "--config", "openwebtext", "--mesh", "8",
        "--steps-per-dispatch", "4", "--json", str(out),
    ])
    assert rc == 0
    rep = json.loads(out.read_text())
    assert rep["ok"] is True
    assert rep["geometry"]["steps_per_dispatch"] == 4
    assert (
        rep["geometry"]["aliased_buffers"] == rep["geometry"]["donated_leaves"]
    )
    rules = {r["rule"]: r["ok"] for r in rep["rules"]}
    assert rules["donation-intact"] and rules["no-host-sync"]
    capsys.readouterr()  # swallow the JSON printed to stdout


def test_cli_steps_per_dispatch_usage_error(capsys):
    from midgpt_tpu.analysis.__main__ import main

    rc = main([
        "--config", "openwebtext", "--mesh", "8", "--steps-per-dispatch", "0",
    ])
    assert rc == 2
    assert "steps-per-dispatch" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# end-to-end through train(): loss parity, dispatch count, resume
# ---------------------------------------------------------------------------


def _logged_losses(rundir: str):
    out = {}
    with open(os.path.join(rundir, "metrics.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            if "loss/optimized" in rec:
                out[rec["step"]] = rec["loss/optimized"]
    return out


@pytest.mark.slow
def test_train_k4_matches_k1_bitwise_and_dispatch_count(tmp_path, monkeypatch):
    """train() end to end: K=4 logs the identical per-step loss sequence
    (log_interval=1 -> every step's loss rides the stacked scan outputs),
    issues ceil(steps / K) train launches, and the K=1 run never touches
    the window machinery (same per-step jitted path as today)."""
    import midgpt_tpu.train as train_mod

    data_dir = _data_dir(tmp_path)
    cfg1 = _base_cfg(
        rundir=str(tmp_path / "k1"), data_dir=data_dir, max_steps=10,
        lr_decay_steps=10,
    )
    # K=1 must bypass the window machinery entirely
    def _boom(*a, **kw):
        raise AssertionError("make_train_window called on the K=1 path")

    monkeypatch.setattr(train_mod, "make_train_window", _boom)
    final1 = train(cfg1)
    monkeypatch.undo()

    cfg4 = dataclasses.replace(
        cfg1, rundir=str(tmp_path / "k4"), steps_per_dispatch=4
    )
    final4 = train(cfg4)

    assert final1["train_dispatches"] == 10
    assert final4["train_dispatches"] == -(-10 // 4)  # ceil = 3

    l1, l4 = _logged_losses(cfg1.rundir), _logged_losses(cfg4.rundir)
    assert sorted(l1) == sorted(l4) == list(range(1, 10))
    for s in l1:
        assert l1[s] == l4[s], f"step {s}: {l1[s]} != {l4[s]}"
    # final eval sweeps see identical params
    assert final1["val_loss"] == final4["val_loss"]
    # window-mode logs carry per-step lr + grad_norm from the scan outputs
    with open(os.path.join(cfg4.rundir, "metrics.jsonl")) as f:
        recs = [json.loads(line) for line in f]
    steps_with_gnorm = {r["step"] for r in recs if "grad_norm" in r}
    assert set(range(1, 10)) <= steps_with_gnorm


@pytest.mark.slow
def test_train_k4_resume_mid_run_stays_bitexact(tmp_path):
    """A K=4 run interrupted at an off-grid step (max_steps=6 -> final
    save at step 5) and resumed to 12 reproduces the straight-through
    K=1 sequence bit-for-bit: the resume lands mid-grid, the short first
    window (steps 6-7) re-aligns, and the loader replays exactly."""
    data_dir = _data_dir(tmp_path)
    cfg1 = _base_cfg(
        rundir=str(tmp_path / "k1"), data_dir=data_dir, max_steps=12,
        lr_decay_steps=12,
    )
    final1 = train(cfg1)

    cfg4a = _base_cfg(
        rundir=str(tmp_path / "k4"), data_dir=data_dir, max_steps=6,
        lr_decay_steps=12, steps_per_dispatch=4,
    )
    train(cfg4a)
    cfg4b = dataclasses.replace(cfg4a, max_steps=12)
    final4 = train(cfg4b)

    l1, l4 = _logged_losses(cfg1.rundir), _logged_losses(cfg4a.rundir)
    assert sorted(l4) == list(range(1, 12))
    for s in l1:
        assert l1[s] == l4[s], f"step {s}: {l1[s]} != {l4[s]}"
    assert final1["val_loss"] == final4["val_loss"]
    # resumed leg: steps [6, 12) re-align via a short first window,
    # windows [2, 4] = 2 dispatches
    assert final4["train_dispatches"] == 2
