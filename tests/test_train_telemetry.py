"""Training-loop telemetry (midgpt_tpu.train_telemetry) + the train-side
inertness contract.

The hard gates, mirroring the serving telemetry suite:

- **Program identity**: the jitted K-step window resolves through
  ``train.get_train_window``'s module-level cache, whose key excludes
  every observability knob — so telemetry on/off (and rundir/logging
  cadence changes) select the ``is``-IDENTICAL cached callable, while a
  real program change (optimizer hyperparameters) does not.
- **Bitwise loss**: a K=4 drive with telemetry spans emitted around the
  cached program reproduces the plain drive's loss sequence bit for
  bit; end to end, two ``train()`` runs differing only in
  ``train_telemetry`` log identical loss sequences.
- **Anomaly monitors**: deterministic step-keyed trips (NaN sentinel,
  EWMA loss/grad-norm spikes) under injected spike series, the
  wall-informed throughput-drop detector, and the flight-record dump
  (recent history + telemetry rings) on trip.
"""

import dataclasses
import json
import os

import jax
import numpy as np
import pytest

import midgpt_tpu.train as train_mod
from midgpt_tpu.config import ExperimentConfig, MeshConfig, ModelConfig
from midgpt_tpu.data import write_tokens
from midgpt_tpu.train import (
    get_train_window,
    init_state,
    make_optimizer,
    train,
)
from midgpt_tpu.train_telemetry import (
    AnomalyMonitors,
    TRAIN_COUNTERS,
    TRAIN_EVENT_KINDS,
    TRAIN_SPAN_KINDS,
    TrainTelemetry,
    chrome_trace_train,
)


def _base_cfg(**kw) -> ExperimentConfig:
    defaults = dict(
        model=ModelConfig(
            block_size=32, vocab_size=64, n_layer=2, n_head=2, n_embd=64,
            dropout=0.0, attn_impl="naive", remat="none",
        ),
        learning_rate=1e-2, min_lr=1e-3, warmup_steps=2,
        lr_decay_steps=8, max_steps=8,
        batch_size=8, g_accum_iters=2,
        compute_dtype="float32",  # bitwise gates: see test_train_window
        eval_interval=8, eval_batches=1, log_interval=1,
        mesh=MeshConfig(replica=1, fsdp=2, sequence=2, tensor=2),
    )
    defaults.update(kw)
    return ExperimentConfig(**defaults)


def _data_dir(tmp_path) -> str:
    data_dir = str(tmp_path / "data")
    os.makedirs(data_dir, exist_ok=True)
    toks = np.tile(np.arange(64), 4000)
    write_tokens(os.path.join(data_dir, "train.bin"), toks)
    write_tokens(os.path.join(data_dir, "val.bin"), toks[:40_000])
    return data_dir


# ---------------------------------------------------------------------------
# TrainTelemetry units (no compilation)
# ---------------------------------------------------------------------------


def test_taxonomy_spans_and_starvation_counter():
    tele = TrainTelemetry(starvation_s=0.01)
    tele.emit("run_start", step=0, t=0.0)
    tele.span("eval_pause", step=0, t=0.1, dur=0.2, batches=1)
    # fast prefetch: counted, not starved
    tele.prefetch_wait(step=0, t=0.3, dur=0.001)
    # slow prefetch: starved — counter + event
    tele.prefetch_wait(step=4, t=0.4, dur=0.5)
    snap = tele.metrics_snapshot()
    assert snap["counters"]["prefetch_waits"] == 2
    assert snap["counters"]["prefetch_starved"] == 1
    assert [e.kind for e in tele.events] == [
        "run_start", "prefetch_starved"
    ]
    kinds = [d.kind for d in tele.dispatches]
    assert kinds == ["eval_pause", "prefetch_wait", "prefetch_wait"]
    assert snap["histograms"]["prefetch_wait_s"]["count"] == 2
    assert snap["histograms"]["eval_pause_s"]["count"] == 1
    # taxonomy is enforced both ways: serving kinds don't leak in
    with pytest.raises(AssertionError):
        tele.emit("decode_window", step=0, t=0.0)
    with pytest.raises(AssertionError):
        tele.span("decode_window", step=0, t=0.0, dur=0.0)
    for name in TRAIN_COUNTERS:
        assert name in snap["counters"], name


def test_chrome_trace_train_structure():
    tele = TrainTelemetry()
    tele.emit("run_start", step=0, t=1.0)
    tele.span("prefetch_wait", step=0, t=1.0, dur=0.1)
    tele.emit("window_launch", step=0, t=1.1, k=4)
    tele.span("train_window", step=0, t=1.1, dur=0.4, k=4)
    tele.emit("anomaly", step=3, t=1.6, kind_detail="loss_spike")
    tr = chrome_trace_train(tele)
    names = [e.get("name") for e in tr["traceEvents"]]
    lanes = {
        e["args"]["name"]
        for e in tr["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    assert set(TRAIN_SPAN_KINDS) <= lanes and "events" in lanes
    spans = [e for e in tr["traceEvents"] if e.get("ph") == "X"]
    assert {s["name"] for s in spans} == {"prefetch_wait", "train_window"}
    instants = [e for e in tr["traceEvents"] if e.get("ph") == "i"]
    assert {i["name"] for i in instants} == {"run_start", "anomaly"}
    assert "train_window" in names
    json.dumps(tr)  # Perfetto-loadable


def test_flight_dump_and_prometheus_export(tmp_path):
    from midgpt_tpu.telemetry import prometheus_text

    tele = TrainTelemetry()
    tele.emit("run_start", step=0, t=0.0)
    tele.metrics.counter("windows_dispatched").inc(3)
    path = str(tmp_path / "flight.json")
    rec = tele.flight_dump("test", path=path, extra={"round": 6})
    on_disk = json.load(open(path))
    assert on_disk["reason"] == "test" and on_disk["round"] == 6
    assert on_disk["telemetry"]["events"][0]["kind"] == "run_start"
    assert rec["metrics"]["counters"]["windows_dispatched"] == 3
    # the registry snapshot exports through the shared Prometheus path
    text = prometheus_text(tele.metrics_snapshot())
    for name in TRAIN_COUNTERS:
        assert f"midgpt_{name}_total" in text, name
    assert "midgpt_prefetch_wait_s_bucket" in text


# ---------------------------------------------------------------------------
# Anomaly monitors: deterministic step-keyed trips
# ---------------------------------------------------------------------------


def test_nan_sentinel_trips_immediately_and_skips_ewma():
    m = AnomalyMonitors()
    trips = m.observe_step(0, float("nan"), 1.0)
    assert [t["kind"] for t in trips] == ["nan"]
    trips = m.observe_step(1, 1.0, float("inf"))
    assert [t["kind"] for t in trips] == ["nan"]
    # the non-finite values must not have poisoned the spike EWMAs
    for s in range(2, 40):
        assert m.observe_step(s, 1.0, 1.0) == []


def test_loss_spike_trips_after_warmup_not_during():
    # a spike DURING warmup never trips (statistics still forming)
    m0 = AnomalyMonitors(warmup=10)
    assert m0.observe_step(0, 4.0, 1.0) == []
    assert m0.observe_step(1, 50.0, 1.0) == []
    # a smooth series, then a spike: trips at exactly the spike step
    m = AnomalyMonitors(warmup=10)
    for s in range(30):
        assert m.observe_step(s, 4.0 + 0.01 * (s % 3), 1.0) == []
    trips = m.observe_step(30, 40.0, 1.0)
    assert [t["kind"] for t in trips] == ["loss_spike"]
    assert trips[0]["step"] == 30
    assert trips[0]["detail"]["value"] == 40.0
    assert trips[0]["detail"]["threshold"] < 40.0


def test_grad_norm_spike_and_k1_none_skip():
    m = AnomalyMonitors(warmup=5)
    for s in range(20):
        m.observe_step(s, 4.0, 1.0)
    trips = m.observe_step(20, 4.0, 900.0)
    assert [t["kind"] for t in trips] == ["grad_norm_spike"]
    # the K=1 loop logs no grad norm: None skips the detector entirely
    m2 = AnomalyMonitors(warmup=5)
    for s in range(20):
        assert m2.observe_step(s, 4.0, None) == []


def test_monitors_are_deterministic_over_a_series():
    rng = np.random.default_rng(0)
    series = list(4.0 + 0.05 * rng.standard_normal(60))
    series[45] = 50.0

    def run():
        m = AnomalyMonitors(warmup=10)
        out = []
        for s, v in enumerate(series):
            out.extend(
                (t["kind"], t["step"]) for t in m.observe_step(s, v, 1.0)
            )
        return out

    first = run()
    assert ("loss_spike", 45) in first
    assert first == run()  # same series -> same trips, same steps


def test_throughput_drop_detector():
    m = AnomalyMonitors(tps_warmup=3)
    for s in range(5):
        assert m.observe_throughput(s, 1000.0) == []
    trips = m.observe_throughput(5, 300.0)
    assert [t["kind"] for t in trips] == ["throughput_drop"]


def test_trip_dumps_flight_record_with_history_and_cap(tmp_path):
    tele = TrainTelemetry()
    m = AnomalyMonitors(
        telemetry=tele, flight_dir=str(tmp_path), warmup=2, max_dumps=1
    )
    for s in range(5):
        m.observe_step(s, 4.0, 1.0)
    m.observe_step(5, float("nan"), 1.0)
    m.observe_step(6, float("nan"), 1.0)  # past max_dumps: no 2nd file
    assert len(m.trips) == 2 and len(m.dump_paths) == 1
    dump = json.load(open(m.dump_paths[0]))
    assert dump["reason"] == "anomaly:nan"
    assert dump["step"] == 5
    assert [h["step"] for h in dump["history"]][-1] == 5
    assert dump["telemetry"]["events"][-1]["kind"] == "anomaly"
    assert tele.metrics_snapshot()["counters"]["anomalies_tripped"] == 2
    assert len(list(tmp_path.glob("anomaly_*.json"))) == 1


# ---------------------------------------------------------------------------
# The inertness contract: program identity + bitwise loss
# ---------------------------------------------------------------------------


def test_window_cache_identity_excludes_observability_knobs(mesh8):
    """get_train_window resolves telemetry/rundir/logging variants to
    the IDENTICAL cached jitted callable (no compile happens here —
    jit wrappers build lazily), while a real program change (optimizer
    hyperparameter) gets its own program."""
    cfg = _base_cfg()
    w1 = get_train_window(cfg, mesh8, 4)
    observability_variant = dataclasses.replace(
        cfg, rundir="/tmp/elsewhere", train_telemetry=True,
        log_interval=7, max_steps=99, eval_interval=33, seed=5,
        data_seed=77,
    )
    assert get_train_window(observability_variant, mesh8, 4) is w1
    assert get_train_window(cfg, mesh8, 2) is not w1  # K is program shape
    program_variant = dataclasses.replace(cfg, learning_rate=5e-3)
    assert get_train_window(program_variant, mesh8, 4) is not w1


def test_window_drive_with_telemetry_attached_is_bitwise(mesh8):
    """Two K=4 drives of the SAME cached window program — one plain, one
    with TrainTelemetry emitting launch/harvest/span around every call —
    produce bitwise-identical per-step losses, and the telemetry
    actually recorded."""
    from jax.sharding import PartitionSpec as P

    from midgpt_tpu.parallel.sharding import make_global_array

    cfg = _base_cfg()
    tx, _ = make_optimizer(cfg)
    window = get_train_window(cfg, mesh8, 4)
    key = jax.random.PRNGKey(0)
    base = jax.random.PRNGKey(7)
    rng = np.random.default_rng(0)
    xs = rng.integers(0, 64, size=(8, 2, 4, 32), dtype=np.int32)
    ys = rng.integers(0, 64, size=(8, 2, 4, 32), dtype=np.int32)
    wspec = P(None, None, ("replica", "fsdp"), "sequence")

    def drive(tele):
        import time

        state = init_state(cfg, mesh8, tx, key)
        losses = []
        for w in range(0, 8, 4):
            xg = make_global_array(xs[w:w + 4], mesh8, wspec)
            yg = make_global_array(ys[w:w + 4], mesh8, wspec)
            t0 = time.perf_counter()
            if tele is not None:
                tele.emit("window_launch", step=w, t=t0, k=4)
            state, out = window(state, xg, yg, base)
            arr = np.asarray(out["loss"])
            if tele is not None:
                t1 = time.perf_counter()
                tele.emit("window_harvest", step=w + 3, t=t1, k=4)
                tele.span("train_window", step=w, t=t0, dur=t1 - t0, k=4)
            losses.append(arr)
        return np.concatenate(losses).astype(np.float32)

    plain = drive(None)
    tele = TrainTelemetry()
    traced = drive(tele)
    np.testing.assert_array_equal(
        plain.view(np.uint32), traced.view(np.uint32)
    )
    assert get_train_window(cfg, mesh8, 4) is window  # still the one
    assert [e.kind for e in tele.events] == [
        "window_launch", "window_harvest",
    ] * 2
    assert len(tele.dispatches) == 2


@pytest.mark.slow
def test_train_e2e_telemetry_on_off_bitwise_and_artifacts(tmp_path):
    """train() end to end, K=4: telemetry on vs off logs the identical
    per-step loss sequence, resolves the SAME cached window program
    (module-level cache gains no new entries on the second run), and
    the traced run writes the timeline + flight artifacts with the
    attainment keys riding every throughput record."""
    data_dir = _data_dir(tmp_path)
    cfg_off = _base_cfg(
        rundir=str(tmp_path / "off"), data_dir=data_dir,
        steps_per_dispatch=4,
    )
    train(cfg_off)
    after_off = dict(train_mod._WINDOW_PROGRAMS)
    assert after_off, "the K=4 drive must resolve through the cache"

    cfg_on = dataclasses.replace(
        cfg_off, rundir=str(tmp_path / "on"), train_telemetry=True
    )
    train(cfg_on)
    after_on = dict(train_mod._WINDOW_PROGRAMS)
    # inertness: the traced run compiled NOTHING new — every window
    # program it used is the is-identical cached callable (earlier
    # tests in this file may have pre-populated the same keys: the
    # cache deliberately ignores rundir/telemetry/logging knobs)
    assert set(after_on) == set(after_off)
    for k in after_off:
        assert after_on[k] is after_off[k]

    def logged(rundir):
        out = {}
        recs = []
        with open(os.path.join(rundir, "metrics.jsonl")) as f:
            for line in f:
                rec = json.loads(line)
                recs.append(rec)
                if "loss/optimized" in rec:
                    out[rec["step"]] = rec["loss/optimized"]
        return out, recs

    l_off, _ = logged(cfg_off.rundir)
    l_on, recs_on = logged(cfg_on.rundir)
    assert sorted(l_off) == sorted(l_on) == list(range(1, 8))
    for s in l_off:
        assert l_off[s] == l_on[s], f"step {s} diverged under tracing"

    # attainment rides every throughput record (MetricLogger floor)
    tps_recs = [r for r in recs_on if "tokens_per_sec" in r]
    assert tps_recs
    for r in tps_recs:
        assert r["train_attainment_frac"] > 0
        assert r["train_hbm_floor_ms"] > 0
        assert r["train_compute_floor_ms"] > 0
        assert r["step_ms"] > 0

    # the traced run leaves a Perfetto timeline + flight record
    tl = json.load(open(os.path.join(cfg_on.rundir, "train_timeline.json")))
    span_names = {
        e["name"] for e in tl["traceEvents"] if e.get("ph") == "X"
    }
    assert {"prefetch_wait", "train_window", "eval_pause"} <= span_names
    fl = json.load(
        open(os.path.join(cfg_on.rundir, "train_telemetry.json"))
    )
    assert fl["reason"] == "run_end"
    kinds = {e["kind"] for e in fl["telemetry"]["events"]}
    assert {"run_start", "window_launch", "window_harvest",
            "run_end"} <= kinds
    assert fl["metrics"]["counters"]["windows_dispatched"] == 2
    assert fl["metrics"]["counters"]["steps_completed"] == 8
    # healthy tiny run: monitors observed every step, tripped nothing
    assert fl["metrics"]["counters"]["anomalies_tripped"] == 0
    # the untraced run writes no telemetry artifacts
    assert not os.path.exists(
        os.path.join(cfg_off.rundir, "train_timeline.json")
    )
