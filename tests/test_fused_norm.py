"""Fused RMSNorm Pallas kernel vs the jnp oracle (CPU interpret mode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import midgpt_tpu.ops.fused_norm as fn
from midgpt_tpu.models.layers import RMSNorm


@pytest.fixture(autouse=True)
def _interpret_mode(pallas_interpret):
    yield


def _oracle(x, w, eps):
    out = x * jax.lax.rsqrt(jnp.mean(jnp.square(x), axis=-1, keepdims=True) + eps)
    return out * w if w is not None else out


@pytest.mark.parametrize("use_weight", [False, True])
def test_fused_forward_matches_oracle(use_weight):
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (4, 96, 256), jnp.float32)
    w = jnp.linspace(0.5, 1.5, 256) if use_weight else None
    out = fn.fused_rms_norm(x, w, 1e-6)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_oracle(x, w, 1e-6)), atol=1e-5
    )


def test_fused_forward_unaligned_rows():
    """Row count not a multiple of block_rows exercises the padding path."""
    x = jax.random.normal(jax.random.PRNGKey(1), (3, 37, 128), jnp.float32)
    out = fn.fused_rms_norm(x, None, 1e-6, 16)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_oracle(x, None, 1e-6)), atol=1e-5
    )


@pytest.mark.parametrize("use_weight", [False, True])
def test_fused_grad_matches_oracle(use_weight):
    key = jax.random.PRNGKey(2)
    x = jax.random.normal(key, (2, 64, 128), jnp.float32)
    w = 1.0 + 0.1 * jax.random.normal(jax.random.PRNGKey(3), (128,)) if use_weight else None

    def loss_fused(x, w):
        return jnp.sum(jnp.sin(fn.fused_rms_norm(x, w, 1e-6)))

    def loss_oracle(x, w):
        return jnp.sum(jnp.sin(_oracle(x, w, 1e-6)))

    if use_weight:
        gx, gw = jax.grad(loss_fused, argnums=(0, 1))(x, w)
        ox, ow = jax.grad(loss_oracle, argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(ow), atol=1e-4)
    else:
        gx = jax.grad(loss_fused)(x, w)
        ox = jax.grad(loss_oracle)(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(ox), atol=1e-4)


def test_rmsnorm_module_fused_impl_falls_back_off_tpu():
    """impl='fused' must degrade gracefully to the jnp path on non-TPU
    backends (the module's platform probe routes away from Pallas here);
    kernel-vs-oracle parity itself is covered by the direct tests above."""
    norm_f = RMSNorm.init(128, use_weight=True, impl="fused")
    norm_j = RMSNorm(weight=norm_f.weight, eps=norm_f.eps, impl="jnp")
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 32, 128), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(norm_f(x)), np.asarray(norm_j(x)), atol=1e-5
    )


def test_fused_bf16_precision():
    x = jax.random.normal(jax.random.PRNGKey(5), (8, 256), jnp.bfloat16)
    out = fn.fused_rms_norm(x, None, 1e-6)
    assert out.dtype == jnp.bfloat16
    ref = _oracle(x.astype(jnp.float32), None, 1e-6)
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), np.asarray(ref), atol=2e-2
    )
