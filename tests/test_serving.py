"""Serving engine (midgpt_tpu.serving): page-allocator invariants, paged
decode parity against the exact sampler, fused K-step window vs K=1
(including EOS inside a window), scheduler admit/evict behavior under
scripted traces, prefix-cache/chunked-prefill exactness, and
self-speculative decoding (n-gram drafting + single-dispatch
verification: token identity vs spec-off, dispatch accounting, and
watermark-rollback invariants under forced full rejection). Beyond the
reference (its sampler is fixed-batch, full-re-forward per token,
sample.py:68-95)."""

import dataclasses
import zlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.config import ModelConfig
from midgpt_tpu.models.gpt import (
    GPT,
    KVCache,
    decode_step,
    decode_step_paged,
    prefill,
)
from midgpt_tpu.sampling import generate
from midgpt_tpu.serving import (
    PageAllocator,
    PagedKVPool,
    PrefixIndex,
    ServingEngine,
    flush_recent,
    generate_served,
    pages_needed,
    write_prompt_pages,
)

CFG = ModelConfig(
    block_size=64, vocab_size=96, n_layer=2, n_head=4, n_embd=32,
    dropout=0.0, attn_impl="naive", remat="none",
)


def _model():
    return GPT.init(jax.random.PRNGKey(0), CFG)


def _prompts(n, base_len=5, stride=3):
    return [
        np.asarray(
            jax.random.randint(
                jax.random.PRNGKey(100 + i), (base_len + stride * i,), 0,
                CFG.vocab_size,
            )
        )
        for i in range(n)
    ]


def _exact(model, prompt, n_new):
    """The existing exact sampler, greedy, per request."""
    return np.asarray(
        generate(
            model, jnp.asarray(prompt)[None], n_new,
            key=jax.random.PRNGKey(9), temperature=0.0,
            cache_dtype=jnp.float32,
        )
    )[0]


@pytest.fixture(scope="module")
def shared_prefix_case():
    """Shared-prefix trace + exact-sampler refs, computed once: the
    prefix-cache/chunking identity test and the speculative identity
    matrix drive the same requests (each _exact call compiles its own
    sampler, so recomputing per test is pure wall-clock)."""
    model = _model()
    sys_prompt = _prompts(1, base_len=18)[0]
    tails = _prompts(4, base_len=3, stride=2)
    prompts = [np.concatenate([sys_prompt, t]) for t in tails]
    lens = [9, 12, 7, 10]
    refs = [_exact(model, p, n) for p, n in zip(prompts, lens)]
    return model, prompts, lens, refs


@pytest.fixture(scope="module")
def eviction_case():
    """Equal-length eviction-pressure trace + refs at the two generation
    lengths the eviction tests use (16 and 24), computed once."""
    model = _model()
    prompts = _prompts(4, base_len=6, stride=0)
    refs16 = [_exact(model, p, 16) for p in prompts]
    refs24 = [_exact(model, p, 24) for p in prompts]
    return model, prompts, refs16, refs24


# ---------------------------------------------------------------------------
# Page allocator invariants
# ---------------------------------------------------------------------------


def test_allocator_alloc_free_roundtrip():
    a = PageAllocator(8)
    p1 = a.alloc(3)
    p2 = a.alloc(5)
    a.check()
    assert a.free_pages == 0 and a.held_pages == 8
    assert len(set(p1) | set(p2)) == 8, "pages must be unique across owners"
    a.free(p1)
    a.check()
    assert a.free_pages == 3
    p3 = a.alloc(2)
    a.check()
    assert not set(p3) & set(p2), "freed-then-realloc'd pages stay disjoint"


def test_allocator_exhaustion_and_double_free():
    a = PageAllocator(4)
    held = a.alloc(4)
    with pytest.raises(MemoryError):
        a.alloc(1)
    a.free(held[:2])
    with pytest.raises(ValueError):
        a.free(held[:1])  # double free
    with pytest.raises(ValueError):
        a.free([99])  # foreign page
    a.check()


def test_allocator_fragmentation_reuse():
    """Interleaved alloc/free must never lose pages: after any sequence,
    free + held == num_pages and a full-pool alloc succeeds once all owners
    release."""
    a = PageAllocator(16)
    owners = [a.alloc(n) for n in (2, 3, 4, 7)]  # pool exactly full
    a.check()
    a.free(owners[1])
    a.free(owners[3])
    a.check()
    b = a.alloc(10)  # exactly the freed count
    a.check()
    assert a.free_pages == 0
    a.free(owners[0] + owners[2] + b)
    a.check()
    assert len(a.alloc(16)) == 16  # nothing leaked


def test_pages_needed():
    assert pages_needed(1, 8) == 1
    assert pages_needed(8, 8) == 1
    assert pages_needed(9, 8) == 2
    assert pages_needed(64, 16) == 4


# ---------------------------------------------------------------------------
# Paged decode parity (logits + tokens) vs the exact sampler / oracle
# ---------------------------------------------------------------------------


def test_paged_decode_logits_match_decode_step_oracle():
    """Teacher-forced: decode_step_paged against the per-token decode_step
    ring oracle at every position, across page boundaries."""
    model = _model()
    p, n_steps, ps = 5, 13, 4  # crosses several page boundaries
    total = p + n_steps
    tokens = jax.random.randint(
        jax.random.PRNGKey(4), (1, total), 0, CFG.vocab_size
    )

    cache = KVCache.init(CFG, 1, total, dtype=jnp.float32)
    _, cache = prefill(model, tokens[:, :p], cache)
    oracle = []
    for t in range(p, total):
        lo, cache = decode_step(
            model, tokens[:, t], jnp.asarray(t, jnp.int32), cache,
            rope_len=CFG.block_size,
        )
        oracle.append(np.asarray(lo))

    pmax = pages_needed(CFG.block_size, ps)
    pool = PagedKVPool.init(CFG, pmax, ps, dtype=jnp.float32)
    pad = pages_needed(p, ps) * ps
    h, (ks, vs) = model.hidden(
        jnp.pad(tokens[:, :p], ((0, 0), (0, pad - p))), return_kv=True
    )
    rows = np.full((pad // ps,), pool.num_pages, np.int32)
    rows[: pages_needed(p, ps)] = np.arange(pages_needed(p, ps))
    pool = write_prompt_pages(pool, ks[:, 0], vs[:, 0], jnp.asarray(rows))

    bt = np.full((1, pmax), pool.num_pages, np.int32)
    bt[0, :pmax] = np.arange(pmax)  # identity block table
    bt = jnp.asarray(bt)
    got = []
    base = p
    window = 4
    while base < total:
        k_eff = min(window, total - base)
        rshape = (CFG.n_layer, 1, CFG.kv_heads, window, CFG.head_dim)
        rk = jnp.zeros(rshape, jnp.float32)
        rv = jnp.zeros(rshape, jnp.float32)
        pooled = jnp.asarray([base], jnp.int32)
        for r in range(k_eff):
            t = base + r
            lg, rk, rv = decode_step_paged(
                model, tokens[:, t], jnp.asarray([t], jnp.int32),
                pool.k, pool.v, bt, rk, rv, jnp.asarray(r, jnp.int32),
                pooled, CFG.block_size,
            )
            got.append(np.asarray(lg))
        valid = jnp.ones((1, window), bool) & (
            jnp.arange(window)[None, :] < k_eff
        )
        pool = flush_recent(pool, rk, rv, bt, pooled, valid)
        base += k_eff

    for i, (a, b) in enumerate(zip(oracle, got)):
        np.testing.assert_allclose(
            a, b, atol=2e-4, err_msg=f"step {i} (pos {p + i})"
        )


def test_engine_matches_exact_sampler_per_request():
    """Greedy engine output == the existing exact sampler, per request,
    under mixed prompt lengths and full-batch continuous decode."""
    model = _model()
    prompts = _prompts(3)
    refs = [_exact(model, p, 12) for p in prompts]
    outs = generate_served(
        model, prompts, 12, window=4, page_size=8, cache_dtype=jnp.float32
    )
    for i, (r, o) in enumerate(zip(refs, outs)):
        np.testing.assert_array_equal(r, o, err_msg=f"request {i}")


def test_engine_admits_mid_run_with_parity():
    """More requests than slots: late requests are admitted mid-run as
    slots free, and every output still matches the exact sampler."""
    model = _model()
    prompts = _prompts(5, base_len=4, stride=2)
    lens = [6, 14, 9, 11, 7]  # staggered finish -> staggered admission
    refs = [_exact(model, p, n) for p, n in zip(prompts, lens)]
    eng = ServingEngine(
        model, slots=2, page_size=8, window=4, temperature=0.0,
        cache_dtype=jnp.float32,
    )
    rids = [eng.submit(p, n) for p, n in zip(prompts, lens)]
    fin = eng.run()
    for i, r in enumerate(rids):
        np.testing.assert_array_equal(
            np.asarray(fin[r].tokens), refs[i], err_msg=f"request {i}"
        )
    assert eng.stats()["slot_occupancy"] > 0.5
    eng.alloc.check()
    assert eng.alloc.held_pages == 0, "finished requests must free pages"


def test_fused_window_matches_k1_including_eos_mid_window():
    """K=4 fused decode reproduces the K=1 token stream exactly — with an
    EOS landing strictly inside a window (not on its boundary), after
    which the slot pads harmlessly to the boundary."""
    model = _model()
    prompt = _prompts(1)[0]
    ref = _exact(model, prompt, 16)
    # choose an EOS the greedy rollout actually emits at a non-boundary
    # step (r % 4 != 3); fall back to any emitted token
    eos, eos_pos = None, None
    for i, t in enumerate(ref.tolist()):
        if ref.tolist().index(t) == i and i % 4 not in (3,) and i > 0:
            eos, eos_pos = int(t), i
            break
    assert eos is not None, "degenerate rollout; adjust prompt seed"
    out_k4 = generate_served(
        model, [prompt], 16, eos_id=eos, window=4, page_size=8,
        cache_dtype=jnp.float32,
    )[0]
    out_k1 = generate_served(
        model, [prompt], 16, eos_id=eos, window=1, page_size=8,
        cache_dtype=jnp.float32,
    )[0]
    np.testing.assert_array_equal(out_k4, out_k1)
    assert out_k4.tolist() == ref.tolist()[: eos_pos + 1], (
        "sequence must stop at (and include) the first EOS"
    )


def test_engine_temperature_stream_invariant_to_window_and_slots():
    """Categorical sampling: a request's token stream derives from
    (seed, token-index) alone — identical across K, slot count, and batch
    composition."""
    model = _model()
    prompts = _prompts(3)

    def run(window, slots):
        eng = ServingEngine(
            model, slots=slots, page_size=8, window=window,
            temperature=0.8, top_k=20, cache_dtype=jnp.float32, seed=3,
        )
        rids = [eng.submit(p, 8, seed=i) for i, p in enumerate(prompts)]
        fin = eng.run()
        return [fin[r].tokens for r in rids]

    a = run(4, 3)
    b = run(1, 3)
    c = run(2, 1)  # serial slots: different batch composition entirely
    assert a == b == c


# ---------------------------------------------------------------------------
# Scheduler: scripted arrival trace, eviction, dispatch accounting
# ---------------------------------------------------------------------------


def test_scheduler_scripted_arrival_trace():
    """Requests arriving between windows are admitted at the next
    boundary; occupancy and lifecycle timestamps are recorded."""
    model = _model()
    prompts = _prompts(4, base_len=4, stride=1)
    refs = [_exact(model, p, 8) for p in prompts]
    fake_now = {"t": 0.0}
    eng = ServingEngine(
        model, slots=2, page_size=8, window=4, temperature=0.0,
        cache_dtype=jnp.float32, clock=lambda: fake_now["t"],
    )
    # t=0: two arrivals; after the first window two more arrive
    r0 = eng.submit(prompts[0], 8)
    r1 = eng.submit(prompts[1], 8)
    fake_now["t"] = 1.0
    eng.step()
    r2 = eng.submit(prompts[2], 8)
    r3 = eng.submit(prompts[3], 8)
    fin = eng.run()
    for i, r in enumerate([r0, r1, r2, r3]):
        np.testing.assert_array_equal(
            np.asarray(fin[r].tokens), refs[i], err_msg=f"request {i}"
        )
    # late arrivals were admitted mid-run: their TTFT clock starts at
    # submission, and first_token_time >= submit_time for everyone
    for r in (r0, r1, r2, r3):
        req = fin[r]
        assert req.first_token_time is not None
        assert req.first_token_time >= req.submit_time
        assert req.finish_time >= req.first_token_time


def test_scheduler_evicts_under_page_pressure_and_recovers(eviction_case):
    """A pool too small for all requests at once forces eviction; evicted
    requests re-queue with progress kept and still finish with exact
    parity."""
    model, prompts, refs, _ = eviction_case
    eng = ServingEngine(
        model, slots=2, page_size=8, num_pages=5, window=4,
        temperature=0.0, cache_dtype=jnp.float32,
    )
    rids = [eng.submit(p, 16) for p in prompts]
    fin = eng.run()
    assert eng.evictions > 0, "trace was sized to force eviction"
    for i, r in enumerate(rids):
        np.testing.assert_array_equal(
            np.asarray(fin[r].tokens), refs[i], err_msg=f"request {i}"
        )
    eng.alloc.check()
    assert eng.alloc.held_pages == 0


def test_steady_state_one_dispatch_per_k_tokens():
    """With all slots busy and no EOS, decode runs exactly one dispatch
    per K generated tokens per active batch."""
    model = _model()
    k, slots, n_new = 4, 2, 16
    prompts = _prompts(slots, base_len=5, stride=1)
    eng = ServingEngine(
        model, slots=slots, page_size=8, window=k, temperature=0.0,
        cache_dtype=jnp.float32,
    )
    for p in prompts:
        eng.submit(p, n_new)
    eng.run()
    st = eng.stats()
    assert st["decode_dispatches"] == n_new // k
    assert st["tokens_generated"] == slots * n_new
    assert st["tokens_per_dispatch"] == slots * k
    assert st["slot_occupancy"] == 1.0


def test_repeated_eviction_rebuilds_context_without_duplication(
    eviction_case,
):
    """Regression (code review): a request evicted TWICE must rebuild its
    admission context from the original prompt + all generated tokens —
    appending to an already-grown prompt duplicated the first eviction's
    tokens, corrupting the context and livelocking tight pools."""
    model, prompts, _, refs = eviction_case
    n_new = 24  # long generations -> many growth events -> re-evictions
    eng = ServingEngine(
        model, slots=2, page_size=8, num_pages=5, window=4,
        temperature=0.0, cache_dtype=jnp.float32,
    )
    rids = [eng.submit(p, n_new) for p in prompts]
    fin = eng.run()
    assert max(r.evictions for r in fin.values()) >= 2, (
        "trace was sized to evict some request at least twice; got "
        f"{[r.evictions for r in fin.values()]}"
    )
    for i, r in enumerate(rids):
        # the rebuilt context is prompt0 + a PREFIX of the generated
        # tokens (those emitted before the last eviction) — duplication
        # would break the prefix property
        pr = fin[r].prompt
        np.testing.assert_array_equal(pr[: prompts[i].size], prompts[i])
        tail = pr[prompts[i].size:]
        np.testing.assert_array_equal(
            tail, np.asarray(fin[r].tokens[: tail.size], np.int32),
            err_msg=f"request {i}: context not prompt0 + generated prefix",
        )
        np.testing.assert_array_equal(
            np.asarray(fin[r].tokens), refs[i], err_msg=f"request {i}"
        )
    eng.alloc.check()
    assert eng.alloc.held_pages == 0


def test_page_size_must_divide_block_size():
    """Regression (code review): a page grid that doesn't tile block_size
    would pad a near-block prompt past the model's context — reject at
    construction."""
    model = _model()
    with pytest.raises(AssertionError):
        ServingEngine(model, slots=1, page_size=12)  # 64 % 12 != 0


def test_growth_capped_at_remaining_budget():
    """Regression (code review): near end-of-generation, page growth must
    cap at the request's remaining budget — a 60-token prompt with
    max_new=4 exactly fills block_size=64, and demanding pages for
    pooled_len + window tokens would ask past the request's lifetime
    (MemoryError with one slot, spurious evictions under pressure)."""
    model = _model()
    prompt = _prompts(1, base_len=CFG.block_size - 4)[0]  # 60 tokens
    ref = _exact(model, prompt, 4)
    out = generate_served(
        model, [prompt], 4, window=8, page_size=8, slots=1,
        cache_dtype=jnp.float32,
    )[0]
    np.testing.assert_array_equal(out, ref)


def test_engine_rejects_oversized_requests():
    from midgpt_tpu.serving import AdmissionRejected

    model = _model()
    eng = ServingEngine(model, slots=1, page_size=8, window=2)
    with pytest.raises(AdmissionRejected) as exc:
        eng.submit(np.zeros((4,), np.int32), CFG.block_size)  # no room
    assert exc.value.reason == "budget_exceeds_block"
    assert eng.stats()["reject_reasons"] == {"budget_exceeds_block": 1}
    # long prompts crop to the last block_size - max_new tokens
    long_prompt = _prompts(1, base_len=CFG.block_size + 10)[0]
    rid = eng.submit(long_prompt, 4)
    assert eng.queue[-1].prompt.size == CFG.block_size - 4
    ref = _exact(model, long_prompt[-(CFG.block_size - 4):], 4)
    fin = eng.run()
    np.testing.assert_array_equal(np.asarray(fin[rid].tokens), ref)


# ---------------------------------------------------------------------------
# Prefix cache (copy-on-write page sharing) + chunked prefill
# ---------------------------------------------------------------------------


def test_prefix_cache_and_chunking_token_identity(shared_prefix_case):
    """Acceptance: greedy output is token-identical per request with the
    prefix cache on vs off and with chunked vs monolithic prefill —
    shared-prefix traffic, mid-run admission (more requests than slots),
    all against the exact fixed-batch sampler."""
    model, prompts, lens, refs = shared_prefix_case

    def run(prefix_cache, prefill_chunk):
        eng = ServingEngine(
            model, slots=2, page_size=8, window=4, temperature=0.0,
            cache_dtype=jnp.float32, prefix_cache=prefix_cache,
            prefill_chunk=prefill_chunk,
        )
        rids = [eng.submit(p, n) for p, n in zip(prompts, lens)]
        fin = eng.run()
        eng.alloc.check()
        if eng.index is not None:
            eng.index.check(eng.alloc)
        assert eng.alloc.held_pages == 0
        return [fin[r].tokens for r in rids], eng

    base, _ = run(False, None)
    for variant in [(True, None), (False, 8), (True, 8), (True, 5)]:
        toks, eng = run(*variant)
        assert toks == base, f"variant {variant} diverged"
    for i, r in enumerate(base):
        np.testing.assert_array_equal(np.asarray(r), refs[i], err_msg=f"req {i}")


def _run_layer_scan(model, prompts, lens, ls, **kw):
    kw.setdefault("cache_dtype", jnp.float32)
    eng = ServingEngine(
        model, slots=2, page_size=8, window=4, temperature=0.0,
        layer_scan=ls, **kw,
    )
    rids = [eng.submit(p, n) for p, n in zip(prompts, lens)]
    fin = eng.run()
    return [fin[r].tokens for r in rids]


def test_layer_scan_token_identity(shared_prefix_case):
    """Landing gate of the fused layer loop (ROADMAP item 1): greedy
    streams with ``layer_scan="on"`` are bit-identical to the unrolled
    engine AND to the exact fixed-batch sampler — mid-run admission,
    shared prefixes, speculation. The chunked / kv-quant / cache-off
    legs ride the slow tier below; tp=2/4 lives in
    test_serving_sharded.py."""
    model, prompts, lens, refs = shared_prefix_case
    for kw in (dict(), dict(speculate=3)):
        on = _run_layer_scan(model, prompts, lens, "on", **kw)
        off = _run_layer_scan(model, prompts, lens, "off", **kw)
        assert on == off, kw
    for i, r in enumerate(on):  # spec-on fused vs the exact sampler
        np.testing.assert_array_equal(np.asarray(r), refs[i])


@pytest.mark.slow
def test_layer_scan_token_identity_matrix_slow(shared_prefix_case):
    """The remaining single-chip layer_scan cells: chunked prefill,
    prefix-cache off, and the int8 KV pool (each a fresh fused-program
    compile)."""
    model, prompts, lens, _ = shared_prefix_case
    for kw in (
        dict(prefill_chunk=8),
        dict(prefix_cache=False),
        dict(kv_quant="int8", cache_dtype=jnp.bfloat16),
        dict(kv_quant="int8", cache_dtype=jnp.bfloat16, speculate=3,
             prefill_chunk=5),
    ):
        on = _run_layer_scan(model, prompts, lens, "on", **kw)
        off = _run_layer_scan(model, prompts, lens, "off", **kw)
        assert on == off, kw


def test_shared_prefix_skips_prefill_compute():
    """Acceptance: a two-request shared-prefix scenario demonstrably
    skips the shared pages' prefill — the second request computes only
    the uncached suffix (token count asserted) and the hit rate is
    positive."""
    model = _model()
    prompt = _prompts(1, base_len=24)[0]
    eng = ServingEngine(
        model, slots=1, page_size=8, window=4, temperature=0.0,
        cache_dtype=jnp.float32, prefix_cache=True,
    )
    r1 = eng.submit(prompt, 6)
    eng.run()
    computed_first = eng.prefill_tokens_computed
    assert computed_first == 24  # cold cache: the whole prompt
    r2 = eng.submit(prompt, 6)
    fin = eng.run()
    # the second admission recomputes ONLY the last prompt token (the
    # p-1 cap that produces the first decode logits); 16 tokens ride the
    # two full shared pages, 7 the copy-on-write partial page
    assert eng.prefill_tokens_computed - computed_first == 1
    assert eng.prompt_tokens_cached == 23
    assert eng.copy_dispatches == 1
    st = eng.stats()
    assert st["prefix_hit_rate"] > 0
    assert st["prefill_tokens_saved"] == 23
    np.testing.assert_array_equal(
        np.asarray(fin[r1].tokens), np.asarray(fin[r2].tokens)
    )
    ref = _exact(model, prompt, 6)
    np.testing.assert_array_equal(np.asarray(fin[r2].tokens), ref)


def test_multiturn_hits_decode_written_pages_with_parity():
    """Multi-turn shape: turn 2's prompt extends turn 1's prompt AND its
    GENERATED tokens, so the cache hit aliases pages whose K/V was
    written by the decode flush, not by prefill — the one page-content
    source the other exactness tests never exercise (decode and chunk
    prefill use different einsum arithmetic; reuse must still be
    token-identical to the cache-off recompute)."""
    model = _model()
    p0 = _prompts(1, base_len=12)[0]

    def run(cache):
        eng = ServingEngine(
            model, slots=1, page_size=8, window=4, temperature=0.0,
            cache_dtype=jnp.float32, prefix_cache=cache,
        )
        rA = eng.submit(p0, 10)
        finA = eng.run()
        turn2 = np.concatenate([
            p0, np.asarray(finA[rA].tokens, np.int32),
            np.asarray([7, 3], np.int32),  # the "user reply"
        ])
        rB = eng.submit(turn2, 10)
        finB = eng.run()
        return finA[rA].tokens, finB[rB].tokens, eng

    toks_a_on, toks_b_on, eng_on = run(True)
    toks_a_off, toks_b_off, _ = run(False)
    assert toks_a_on == toks_a_off and toks_b_on == toks_b_off
    # turn 2 really did alias decode-written pages: p0 is 12 tokens, so
    # any hit past page 1 (16 tokens) covers generated positions
    assert eng_on.prompt_tokens_cached > len(p0)
    ref = _exact(model, np.concatenate([
        p0, np.asarray(toks_a_on, np.int32), np.asarray([7, 3], np.int32)
    ]), 10)
    np.testing.assert_array_equal(np.asarray(toks_b_on), ref)


def test_eviction_readmission_rehits_cache_with_parity(eviction_case):
    """Under page pressure an evicted request's pages retire COLD; its
    re-admission re-prefills via cache hits (tokens saved > 0) and the
    output still matches the exact sampler bit-for-bit."""
    model, prompts, _, refs = eviction_case
    n_new = 24
    eng = ServingEngine(
        model, slots=2, page_size=8, num_pages=5, window=4,
        temperature=0.0, cache_dtype=jnp.float32, prefix_cache=True,
    )
    rids = [eng.submit(p, n_new) for p in prompts]
    fin = eng.run()
    assert eng.evictions > 0, "trace was sized to force eviction"
    assert eng.prompt_tokens_cached > 0, (
        "re-admissions should re-prefill via the cold prefix cache"
    )
    for i, r in enumerate(rids):
        np.testing.assert_array_equal(
            np.asarray(fin[r].tokens), refs[i], err_msg=f"request {i}"
        )
    eng.alloc.check()
    eng.index.check(eng.alloc)
    assert eng.alloc.held_pages == 0


def test_chunked_prefill_interleaves_with_decode():
    """Sarathi property: with a per-window token budget, a long prompt's
    prefill spreads over several windows while an already-running request
    keeps decoding — the long prompt never monopolizes a window."""
    model = _model()
    short = _prompts(1, base_len=4)[0]
    long = _prompts(1, base_len=48)[0]
    refs = [_exact(model, short, 16), _exact(model, long, 8)]
    eng = ServingEngine(
        model, slots=2, page_size=8, window=4, temperature=0.0,
        cache_dtype=jnp.float32, prefill_chunk=8, prefill_budget=8,
    )
    r_short = eng.submit(short, 16)
    eng.step()  # short is decoding
    req_short = next(
        r for r in eng.slot_req if r is not None and r.rid == r_short
    )
    tokens_before = len(req_short.tokens)
    r_long = eng.submit(long, 8)
    # the long prompt needs ceil(48/8)=6 chunks at 8 tokens/window: the
    # short request must make decode progress during that prefill
    eng.step()
    eng.step()
    assert any(
        eng.prefilling[s] for s in range(eng.slots)
    ), "long prompt should still be prefilling after 2 windows"
    assert len(req_short.tokens) > tokens_before, (
        "decode starved while the long prompt prefilled"
    )
    fin = eng.run()
    np.testing.assert_array_equal(np.asarray(fin[r_short].tokens), refs[0])
    np.testing.assert_array_equal(np.asarray(fin[r_long].tokens), refs[1])
    assert eng.prefill_dispatches >= 6


def test_sharing_invariants_property_loop():
    """Property-style allocator/index invariants under a busy shared-
    prefix trace with pressure: after EVERY scheduler step — refcounts
    never negative (alloc.check), free+held+cached == num_pages, COW/tail
    pages never aliased by two writers, shared pages only ever full
    (indexed) ones, LRU only holds refcount-0 pages."""
    model = _model()
    sys_prompt = _prompts(1, base_len=16)[0]
    tails = _prompts(6, base_len=2, stride=1)
    prompts = [np.concatenate([sys_prompt, t]) for t in tails]
    eng = ServingEngine(
        model, slots=2, page_size=8, num_pages=10, window=4,
        temperature=0.0, cache_dtype=jnp.float32, prefix_cache=True,
        prefill_chunk=8,
    )
    rids = [eng.submit(p, 10, seed=i) for i, p in enumerate(prompts)]
    steps = 0
    while (eng.queue or eng._active_slots()) and steps < 500:
        eng.step()
        steps += 1
        eng.alloc.check()
        eng.index.check(eng.alloc)
        ps = eng.page_size
        for s in eng._active_slots():
            n_pages = len(eng.slot_pages[s])
            pl = int(eng.pooled_len[s])
            for i, pg in enumerate(eng.slot_pages[s]):
                if pg in eng.index:
                    continue  # full + indexed: immutable, safely shared
                # private (writable) pages must have exactly one owner
                # and appear in exactly one block table
                assert eng.alloc.refcount(pg) == 1, (
                    f"writer page {pg} shared (ref "
                    f"{eng.alloc.refcount(pg)})"
                )
                owners = [
                    v for v in eng._active_slots()
                    if pg in eng.slot_pages[v]
                ]
                assert owners == [s], (
                    f"page {pg} aliased by slots {owners}"
                )
    assert steps < 500, "engine did not drain"
    assert eng.alloc.held_pages == 0
    # freeing a request decrefs exactly its pages: everything is now
    # free or cold-cached
    assert (
        eng.alloc.free_pages + eng.alloc.cached_pages
        == eng.alloc.num_pages
    )
    # all requests completed with the right token counts
    for r in rids:
        assert len(eng.finished[r].tokens) == 10


def test_cold_lru_eviction_only_reclaims_refcount_zero_leaves():
    """Unit-level: evict_cold_leaf never returns a page that is still
    referenced or that an indexed child chains through."""
    alloc = PageAllocator(8)
    index = PrefixIndex(4)
    # two chains: [a, b] and [c]; a/b retire cold, c stays held
    a, b, c = alloc.alloc(3)
    a = index.register(-1, [1, 2, 3, 4], a)
    b = index.register(a, [5, 6, 7, 8], b)
    c = index.register(-1, [9, 9, 9, 9], c)
    alloc.decref(a, cache=True)
    index.touch_cold(a)
    alloc.decref(b, cache=True)
    index.touch_cold(b)
    # a was touched first (LRU) but has child b -> b must evict first
    v1 = index.evict_cold_leaf()
    assert v1 == b
    alloc.reclaim(v1)
    v2 = index.evict_cold_leaf()
    assert v2 == a
    alloc.reclaim(v2)
    # c is held (refcount 1): never reclaimable
    assert index.evict_cold_leaf() is None
    assert alloc.refcount(c) == 1 and c in index
    alloc.check()
    index.check(alloc)


def test_allocator_refcount_never_negative():
    a = PageAllocator(4)
    (p,) = a.alloc(1)
    a.incref(p)
    assert a.refcount(p) == 2
    assert a.decref(p) == 1
    assert a.decref(p) == 0
    with pytest.raises(ValueError):
        a.decref(p)  # already free: refcount can never go negative
    with pytest.raises(ValueError):
        a.incref(p)  # free pages cannot be shared
    a.check()
    # cached pages revive through incref
    (q,) = a.alloc(1)
    a.decref(q, cache=True)
    assert a.cached_pages == 1
    a.incref(q)
    assert a.refcount(q) == 1 and a.cached_pages == 0
    a.check()


# ---------------------------------------------------------------------------
# Self-speculative decoding: n-gram drafting + single-dispatch verification
# ---------------------------------------------------------------------------


class _OracleProposer:
    """Test proposer that drafts the TRUE greedy continuation (known from
    a spec-off reference run) — every draft verifies, so dispatch counts
    hit their floor deterministically."""

    def __init__(self, seqs):
        # seqs: list of full token lists (prompt + greedy continuation)
        self.seqs = [[int(t) for t in s] for s in seqs]

    def propose(self, ctx, n):
        ctx = [int(t) for t in ctx]
        for full in self.seqs:
            if full[: len(ctx)] == ctx and len(full) > len(ctx) + 1:
                return full[len(ctx) + 1 : len(ctx) + 1 + n]
        return []


class _AntiOracleProposer(_OracleProposer):
    """Adversarial proposer: drafts are the true continuation shifted by
    one token id — every draft is guaranteed WRONG, so every verify
    dispatch fully rejects (the watermark-rollback worst case)."""

    def propose(self, ctx, n):
        good = super().propose(ctx, n)
        return [(t + 1) % CFG.vocab_size for t in good]


def test_ngram_proposer_periodic_and_no_match():
    from midgpt_tpu.serving import NgramProposer

    p = NgramProposer(max_ngram=3, min_ngram=1)
    # periodic context: the suffix [2, 3] recurs; the continuation chain
    # after the match predicts positions len(ctx)+1.. (the engine's row 0
    # covers position len(ctx) itself, so drafts skip one token)
    ctx = [1, 2, 3, 1, 2, 3, 1, 2, 3]
    # suffix match predicts next = 1 (skipped), then 2, 3, 1, ...
    assert p.propose(ctx, 4) == [2, 3, 1, 2]
    # all-distinct context: nothing recurs, no drafts
    assert p.propose(list(range(10, 30)), 4) == []
    # too-short context: no earlier occurrence exists
    assert p.propose([5], 4) == []
    # constant runs: drafts are read out of history verbatim (no
    # extrapolation), so a short run yields what the earliest match can
    # see and a long run fills the whole draft
    assert p.propose([7, 7, 7, 7], 3) == [7]
    assert p.propose([7] * 8, 3) == [7, 7, 7]


def test_spec_token_identity_matrix(shared_prefix_case):
    """Acceptance: greedy output with speculation on is token-identical
    to the non-speculative engine across prefix-cache on/off x chunked
    vs monolithic prefill — shared-prefix traffic, mid-run admission —
    and to the exact fixed-batch sampler."""
    model, prompts, lens, refs = shared_prefix_case

    def run(speculate, prefix_cache, prefill_chunk):
        eng = ServingEngine(
            model, slots=2, page_size=8, window=4, temperature=0.0,
            cache_dtype=jnp.float32, prefix_cache=prefix_cache,
            prefill_chunk=prefill_chunk, speculate=speculate,
        )
        rids = [eng.submit(p, n) for p, n in zip(prompts, lens)]
        fin = eng.run()
        eng.alloc.check()
        if eng.index is not None:
            eng.index.check(eng.alloc)
        assert eng.alloc.held_pages == 0
        return [fin[r].tokens for r in rids]

    # the spec-off engine == exact-sampler identity across these axes is
    # PR 4's test_prefix_cache_and_chunking_token_identity; here the
    # refs ARE the spec-off streams, so comparing each spec-on variant
    # to them is exactly spec-on vs spec-off (one engine run per variant)
    base = [list(map(int, r)) for r in refs]
    # two spec-on variants span both cache states and both prefill modes
    # (each distinct spec_len would compile its own verify program;
    # runtime draft-length variation is covered by the adaptive
    # controller, which the full-rejection test drives to its floor)
    for variant in [(4, True, None), (4, False, 8)]:
        assert run(*variant) == base, f"variant {variant} diverged"


def test_spec_identity_under_eviction_and_readmission(eviction_case):
    """Speculation x page pressure: evicted requests re-queue, re-admit
    (through the prefix cache), and keep speculating — output still
    matches the exact sampler bit-for-bit and pages all come home."""
    model, prompts, refs, _ = eviction_case
    n_new = 16  # 3 pages per request x 2 slots > the 5-page pool
    eng = ServingEngine(
        model, slots=2, page_size=8, num_pages=5, window=4,
        temperature=0.0, cache_dtype=jnp.float32, prefix_cache=True,
        speculate=4,
    )
    rids = [eng.submit(p, n_new) for p in prompts]
    fin = eng.run()
    assert eng.evictions > 0, "trace was sized to force eviction"
    for i, r in enumerate(rids):
        np.testing.assert_array_equal(
            np.asarray(fin[r].tokens), refs[i], err_msg=f"request {i}"
        )
    eng.alloc.check()
    eng.index.check(eng.alloc)
    assert eng.alloc.held_pages == 0


def test_spec_dispatch_accounting_on_repetitive_prompt():
    """Acceptance: on a repetitive-text prompt the n-gram proposer's
    drafts verify, so a single slot emits MORE than one token per decode
    dispatch — with the stream still identical to spec-off."""
    model = _model()
    pat = np.asarray(
        jax.random.randint(jax.random.PRNGKey(500), (4,), 0, CFG.vocab_size)
    )
    prompt = np.tile(pat, 6)  # 24 tokens of period-4 text
    n_new = 20
    ref = _exact(model, prompt, n_new)
    eng = ServingEngine(
        model, slots=1, page_size=8, window=4, temperature=0.0,
        cache_dtype=jnp.float32, speculate=4,
    )
    rid = eng.submit(prompt, n_new)
    fin = eng.run()
    np.testing.assert_array_equal(np.asarray(fin[rid].tokens), ref)
    st = eng.stats()
    assert st["tokens_generated"] == n_new
    assert st["decode_dispatches"] < n_new, st
    assert st["tokens_per_dispatch"] > 1.0, st
    assert st["spec_accepted_tokens"] > 0
    assert st["verify_dispatches"] == st["decode_dispatches"]
    # spec-off at window=1 pays exactly one dispatch per token: the
    # speculative engine provably beat one-token-per-forward
    assert st["decode_dispatches"] < len(ref)


@pytest.mark.slow
def test_spec_oracle_hits_dispatch_floor():
    """With a perfect proposer the dispatch count hits its deterministic
    floor: ceil(n_new / (spec_len + 1)) verify dispatches per request."""
    model = _model()
    prompts = _prompts(2, base_len=5, stride=0)  # equal length: 1 batch
    n_new, spec = 12, 4
    refs = np.asarray(
        generate(
            model, jnp.stack([jnp.asarray(p) for p in prompts]), n_new,
            key=jax.random.PRNGKey(9), temperature=0.0,
            cache_dtype=jnp.float32,
        )
    )
    seqs = [
        list(map(int, p)) + list(map(int, r)) for p, r in zip(prompts, refs)
    ]
    eng = ServingEngine(
        model, slots=2, page_size=8, temperature=0.0,
        cache_dtype=jnp.float32, speculate=spec,
        proposer=_OracleProposer(seqs),
    )
    rids = [eng.submit(p, n_new) for p in prompts]
    fin = eng.run()
    for i, r in enumerate(rids):
        np.testing.assert_array_equal(np.asarray(fin[r].tokens), refs[i])
    st = eng.stats()
    assert st["decode_dispatches"] == -(-n_new // (spec + 1))  # 12 -> 3
    assert st["tokens_per_dispatch"] == 2 * n_new / 3  # both slots
    assert st["spec_acceptance_rate"] == 1.0
    # full acceptance keeps every request's adaptive draft length maxed
    assert all(fin[r].spec_k == spec for r in rids)


def test_spec_full_rejection_watermark_property_loop():
    """Acceptance: forced FULL-REJECTION verify dispatches (adversarial
    proposer — every draft wrong) under page pressure, chunked prefill
    and the prefix cache. After every scheduler step the allocator/index
    invariants and the single-writer property must hold (rejected rows'
    K/V never lands, the watermark only advances over verified context),
    and the final streams still match the exact sampler: a hostile
    proposer costs throughput, never correctness."""
    model = _model()
    prompts = _prompts(4, base_len=6, stride=1)
    n_new = 12
    refs = [_exact(model, p, n_new) for p in prompts]
    seqs = [
        list(map(int, p)) + list(map(int, r)) for p, r in zip(prompts, refs)
    ]
    eng = ServingEngine(
        model, slots=2, page_size=8, num_pages=6, window=4,
        temperature=0.0, cache_dtype=jnp.float32, prefix_cache=True,
        prefill_chunk=8, speculate=4, proposer=_AntiOracleProposer(seqs),
    )
    rids = [eng.submit(p, n_new, seed=i) for i, p in enumerate(prompts)]
    steps = 0
    while (eng.queue or eng._active_slots()) and steps < 500:
        eng.step()
        steps += 1
        eng.alloc.check()
        eng.index.check(eng.alloc)
        for s in eng._active_slots():
            # the watermark never runs ahead of verified host-side
            # context (speculative rows beyond it were rolled back)
            assert int(eng.pooled_len[s]) <= len(eng.slot_ctx[s])
            for pg in eng.slot_pages[s]:
                if pg in eng.index:
                    continue  # full + indexed: immutable, safely shared
                assert eng.alloc.refcount(pg) == 1, (
                    f"writer page {pg} shared"
                )
                owners = [
                    v for v in eng._active_slots()
                    if pg in eng.slot_pages[v]
                ]
                assert owners == [s], f"page {pg} aliased by {owners}"
    assert steps < 500, "engine did not drain"
    assert eng.spec_drafted > 0, "adversarial drafts never ran"
    assert eng.spec_accepted == 0, "anti-oracle drafts must all reject"
    for i, r in enumerate(rids):
        np.testing.assert_array_equal(
            np.asarray(eng.finished[r].tokens), refs[i], err_msg=f"req {i}"
        )
    # full rejection decays every request's draft length to the floor
    assert all(eng.finished[r].spec_k == 1 for r in rids)
    eng.alloc.check()
    assert eng.alloc.held_pages == 0


@pytest.mark.slow
def test_spec_eos_mid_verify_matches_spec_off():
    """An EOS landing inside a verify dispatch (among the accepted rows)
    truncates the emission at the EOS — same stop point as spec-off."""
    model = _model()
    prompt = _prompts(1)[0]
    ref = _exact(model, prompt, 16)
    eos = int(ref[len(ref.tolist()) // 2])  # a token the rollout emits
    off = generate_served(
        model, [prompt], 16, eos_id=eos, window=4, page_size=8,
        cache_dtype=jnp.float32,
    )[0]
    on = generate_served(
        model, [prompt], 16, eos_id=eos, window=4, page_size=8,
        cache_dtype=jnp.float32, speculate=4,
    )[0]
    np.testing.assert_array_equal(on, off)
    assert int(on[-1]) == eos and eos not in on[:-1].tolist()


def test_sampling_config_typed_errors():
    """Sampled speculation is supported (the greedy-only assert is
    gone): the ctor builds the rejection-sampling verify program at
    temperature > 0. Only genuinely invalid sampling configs raise, and
    they raise TYPED errors."""
    model = _model()
    eng = ServingEngine(model, slots=1, temperature=0.8, speculate=4)
    assert eng.temperature == 0.8 and eng.speculate == 4
    with pytest.raises(ValueError, match="temperature"):
        ServingEngine(model, slots=1, temperature=-0.5)
    with pytest.raises(ValueError, match="top_k"):
        ServingEngine(model, slots=1, temperature=0.8, top_k=0)


@pytest.mark.slow
def test_spec_identity_with_bf16_cache_under_f32_model():
    """Regression (code review): the decode window reads even in-window
    K/V back through the CACHE-dtype recent buffer, so the verify
    program must round its in-dispatch self K/V to pool dtype before
    scoring — an f32 model over a bf16 pool would otherwise compare
    acceptance argmaxes against un-rounded keys (a far larger gap than
    the bf16 ulp flips the CLI drive catches). f32-model + bf16-cache is
    exactly the combination neither the f32/f32 fast tests nor the
    bf16/bf16 checkpoint drive covers."""
    model = _model()  # f32 params
    prompts = _prompts(2)
    outs = {}
    for spec in (0, 4):
        outs[spec] = generate_served(
            model, prompts, 12, window=4, page_size=8,
            cache_dtype=jnp.bfloat16, speculate=spec,
        )
    for a, b in zip(outs[0], outs[4]):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_verify_program_audit_donation_and_host_sync():
    """The compiled speculative verify program passes the serving
    invariants (pool + logits donation intact, no host sync) — with
    speculation on, every decode dispatch is this program."""
    from midgpt_tpu.analysis.harness import audit_verify_program
    from midgpt_tpu.config import get_config

    analysis, report = audit_verify_program(
        get_config("shakespeare_char"), slots=2, spec_len=4, page_size=8
    )
    assert report.ok, report.violations
    assert analysis.donated_leaves == 3  # pool.k, pool.v, logits
    assert len({e.param_number for e in analysis.aliases}) >= 3


@pytest.mark.slow
def test_prefill_chunk_audit_donation_and_host_sync():
    """The compiled suffix-prefill chunk program passes the serving
    invariants (donation intact, no host sync) — the program chunked
    prefill dispatches between every pair of decode windows."""
    from midgpt_tpu.analysis.harness import audit_prefill_chunk
    from midgpt_tpu.config import get_config

    analysis, report = audit_prefill_chunk(
        get_config("shakespeare_char"), chunk_len=32, page_size=8
    )
    assert report.ok, report.violations
    assert analysis.donated_leaves == 3  # pool.k, pool.v, logits


@pytest.mark.slow
def test_decode_window_audit_donation_and_host_sync():
    """The compiled K-step decode window passes the serving invariants:
    pool + logits donation intact, no host round-trips inside the window
    (the same two regressions the CI serving-audit job gates on)."""
    from midgpt_tpu.analysis.harness import audit_decode_window
    from midgpt_tpu.config import get_config

    analysis, report = audit_decode_window(
        get_config("shakespeare_char"), slots=2, window=4, page_size=8
    )
    assert report.ok, report.violations
    assert analysis.donated_leaves == 3  # pool.k, pool.v, logits
    assert len({e.param_number for e in analysis.aliases}) >= 3


# ---------------------------------------------------------------------------
# Sampled speculation (temperature > 0): rejection-sampling verify
# ---------------------------------------------------------------------------
#
# At temperature > 0 spec-on is NOT bitwise spec-off (accepted drafts are
# draws from the proposer's q, not fresh draws from p) — the contract is
# (a) SCHEDULING INVARIANCE: the sampled spec-on stream is a pure function
#     of (request seed, engine seed, sampling knobs), bitwise identical
#     across slots / window / batch composition / chunking / prefix cache /
#     eviction / layer_scan — within each arithmetic cell (kv-quant changes
#     the arithmetic, so cells are compared within themselves, exactly like
#     the greedy layer_scan matrix above);
# (b) DISTRIBUTIONAL EXACTNESS: accept-with-min(1, p/q) + residual
#     resample + bonus row reproduce the spec-off sampling distribution for
#     ANY honest proposer (statistical test below);
# (c) DEGENERATE ANCHOR: with no drafts the verify program IS the decode
#     sampler — bitwise spec-off.


def _rep_prompts(n, period=4, reps=6):
    """Repetitive-text prompts (the fixture the n-gram proposer can
    actually draft against)."""
    return [
        np.tile(
            np.asarray(
                jax.random.randint(
                    jax.random.PRNGKey(700 + i), (period,), 0,
                    CFG.vocab_size,
                )
            ),
            reps,
        )
        for i in range(n)
    ]


def _run_sampled(model, prompts, lens, **kw):
    """One sampled spec-on rollout; returns (streams, engine)."""
    kw.setdefault("cache_dtype", jnp.float32)
    kw.setdefault("page_size", 8)
    kw.setdefault("slots", 2)
    kw.setdefault("window", 4)
    kw.setdefault("speculate", 4)
    eng = ServingEngine(model, temperature=0.8, top_k=20, seed=3, **kw)
    rids = [
        eng.submit(p, n, seed=i)
        for i, (p, n) in enumerate(zip(prompts, lens))
    ]
    fin = eng.run()
    eng.alloc.check()
    assert eng.alloc.held_pages == 0
    return [list(map(int, fin[r].tokens)) for r in rids], eng


class _EmptyProposer:
    """Never drafts: every verify dispatch degenerates to row 0."""

    def propose(self, ctx, n):
        return []


class _SoftModelProposer:
    """Honest soft-distribution proposer (serving.speculate.SoftProposer
    protocol): each draft is genuinely SAMPLED from the claimed q row —
    the rejection-sampling exactness precondition — with q computed by
    the monolithic full-precision forward at ``q_temperature`` (defaults
    to the verify temperature: a near-oracle whose only p/q mismatch is
    the paged verify arithmetic; a flatter ``q_temperature`` forces
    heavy rejection and drives real mass through the residual resample
    without breaking exactness). Drafting is derandomized from
    (request seed, context) — crc32-seeded numpy rng, NOT Python
    ``hash`` (salted per process) — so drafts are a pure function of
    the request and cannot perturb scheduling invariance, while staying
    honest draws from q ACROSS requests (the seed is the per-request
    entropy; ctx alone would collapse same-prompt requests onto one
    deterministic draft and break rejection-sampling exactness — the
    reason propose_soft receives the seed at all)."""

    soft = True

    def __init__(self, model, temperature, top_k, q_temperature=None):
        self.model = model
        self.temperature = (
            temperature if q_temperature is None else q_temperature
        )
        self.top_k = top_k
        self._fwd = jax.jit(lambda m, x: m(x))

    def _dist(self, toks):
        from midgpt_tpu.sampling import target_probs

        toks = list(toks)[-CFG.block_size:]
        # fixed-shape forward: causal attention ignores the zero padding
        # after position len(toks) - 1, and one compile serves every call
        x = np.zeros((1, CFG.block_size), np.int32)
        x[0, : len(toks)] = toks
        logits = self._fwd(self.model, jnp.asarray(x))[0, len(toks) - 1]
        q = np.asarray(
            target_probs(logits, self.temperature, self.top_k), np.float64
        )
        return q / q.sum()

    def propose(self, ctx, n):  # greedy path: unused at temperature > 0
        return []

    def propose_soft(self, ctx, n, seed):
        if n <= 0:
            return [], np.zeros((0, CFG.vocab_size), np.float32)
        ctx = [int(t) for t in ctx]
        rng = np.random.default_rng(
            (seed, zlib.crc32(np.asarray(ctx, np.int64).tobytes()))
        )
        # drafts cover positions len(ctx)+1.. (verify row 0 samples
        # position len(ctx) itself), so guess the skipped token first —
        # a wrong guess only costs acceptance, never exactness
        skip = int(rng.choice(CFG.vocab_size, p=self._dist(ctx)))
        toks, qs = [], []
        for _ in range(n):
            q = self._dist(ctx + [skip] + toks)
            toks.append(int(rng.choice(CFG.vocab_size, p=q)))
            qs.append(q.astype(np.float32))
        return toks, np.stack(qs)


def test_spec_sampled_stream_invariant_to_scheduling():
    """Contract (a), fast tier: sampled spec-on streams are bitwise
    invariant to slots / prefix cache / chunked prefill — drafts ride
    the n-gram proposer against repetitive prompts, so acceptance AND
    rejection-residual paths both execute."""
    model = _model()
    prompts = _rep_prompts(3)
    lens = [10, 12, 8]
    a, ea = _run_sampled(model, prompts, lens, slots=2, prefix_cache=True)
    b, _ = _run_sampled(
        model, prompts, lens, slots=1, prefix_cache=False, prefill_chunk=8
    )
    assert a == b
    assert ea.spec_drafted > 0, "repetitive fixture must actually draft"
    assert all(len(t) == n for t, n in zip(a, lens))


def test_spec_sampled_no_drafts_is_bitwise_spec_off():
    """Contract (c): with a proposer that never drafts, every verify
    dispatch degenerates to the decode sampler — the sampled spec-on
    stream is BITWISE the spec-off stream (same derived per-request
    keys, same arithmetic). This anchors the verify program's row-0
    sampler to the plain window."""
    model = _model()
    prompts = _prompts(3)
    lens = [8, 10, 6]
    off, _ = _run_sampled(model, prompts, lens, speculate=0)
    on, eng = _run_sampled(
        model, prompts, lens, speculate=4, proposer=_EmptyProposer()
    )
    assert on == off
    assert eng.spec_drafted == 0


def test_spec_sampled_soft_proposer_dispatch_win():
    """The perf claim at temperature > 0: a near-oracle soft proposer
    (q ~= p) gets drafts ACCEPTED through the rejection sampler, so a
    single slot emits more than one token per decode dispatch on the
    repetitive-prompt fixture — E[accepted] + 1 per verify launch."""
    model = _model()
    prompt = _rep_prompts(1)[0]
    n_new = 16
    prop = _SoftModelProposer(model, 0.8, 20)
    eng = ServingEngine(
        model, slots=1, page_size=8, window=4, temperature=0.8, top_k=20,
        cache_dtype=jnp.float32, speculate=4, proposer=prop, seed=3,
    )
    rid = eng.submit(prompt, n_new, seed=0)
    fin = eng.run()
    assert len(fin[rid].tokens) == n_new
    st = eng.stats()
    assert st["spec_accepted_tokens"] > 0, st
    assert st["tokens_per_dispatch"] > 1.0, st
    assert st["decode_dispatches"] < n_new, st


@pytest.mark.slow
def test_spec_sampled_invariance_matrix_slow():
    """Contract (a), full single-chip matrix: within each arithmetic
    cell (f32 pool; int8-quantized bf16 pool) the sampled spec-on
    stream is bitwise identical across slots, prefix cache on/off,
    chunked prefill, page pressure with eviction/re-admission, and
    layer_scan on/off. Cross-cell equality is NOT asserted — kv-quant
    changes the arithmetic (same contract as the greedy layer_scan
    matrix). tp=2 rides test_serving_sharded.py."""
    model = _model()
    prompts = _rep_prompts(3)
    lens = [10, 12, 8]
    scheds = (
        dict(slots=2, prefix_cache=True),
        dict(slots=1, prefix_cache=False),
        dict(slots=3, prefill_chunk=8),
        dict(slots=2, prefill_chunk=5, num_pages=7, prefix_cache=True),
    )
    for arith in (
        dict(cache_dtype=jnp.float32),
        dict(kv_quant="int8", cache_dtype=jnp.bfloat16),
    ):
        base = None
        for ls in ("off", "on"):
            for sched in scheds:
                toks, eng = _run_sampled(
                    model, prompts, lens, layer_scan=ls, **arith, **sched
                )
                if "num_pages" in sched:
                    assert eng.evictions > 0, (
                        "pressure leg was sized to evict"
                    )
                if base is None:
                    base = toks
                assert toks == base, (arith, ls, sched)


@pytest.mark.slow
def test_spec_sampled_statistical_faithfulness_slow():
    """Contract (b): distributional exactness of accept / residual /
    bonus. The proposer claims a DELIBERATELY mismatched q (flatter:
    q_temperature 1.6 vs verify 0.8), so a large fraction of drafts
    reject and the residual resample carries real probability mass —
    exactness must come from the rejection arithmetic, not from q ~= p.
    Over a seed ensemble: position 0 is bitwise spec-off (same derived
    key, same carried prefill logits); later positions pass two-sample
    TV + pooled chi-square gates sized generously above the N-sample
    noise floor (expected TV ~ sqrt(k / (pi N)) ~= 0.13 at k = 20,
    N = 300; deterministic seeds, no flake)."""
    model = _model()
    prompt = _prompts(1, base_len=8)[0]
    N, n_new = 300, 3

    def ensemble(**kw):
        eng = ServingEngine(
            model, slots=4, page_size=8, window=4, temperature=0.8,
            top_k=20, cache_dtype=jnp.float32, prefix_cache=True, seed=3,
            **kw,
        )
        rids = [eng.submit(prompt, n_new, seed=i) for i in range(N)]
        fin = eng.run()
        return np.asarray([fin[r].tokens for r in rids]), eng

    off, _ = ensemble()
    on, eng = ensemble(
        speculate=3,
        proposer=_SoftModelProposer(model, 0.8, 20, q_temperature=1.6),
    )
    st = eng.stats()
    assert st["spec_drafted_tokens"] > 0
    # the mismatched q must actually reject (residual path under test)
    assert st["spec_acceptance_rate"] < 0.9, st
    np.testing.assert_array_equal(on[:, 0], off[:, 0])
    for j in range(1, n_new):
        ca = np.bincount(off[:, j], minlength=CFG.vocab_size)
        cb = np.bincount(on[:, j], minlength=CFG.vocab_size)
        tv = 0.5 * np.abs(ca / N - cb / N).sum()
        assert tv < 0.25, (j, tv)
        # pooled two-sample chi-square, no scipy: merge cells with < 10
        # pooled counts, stat ~ chi2(df) under H0, gate at ~4 sigma
        pooled = ca + cb
        big = pooled >= 10
        a = np.append(ca[big], ca[~big].sum()).astype(np.float64)
        b = np.append(cb[big], cb[~big].sum()).astype(np.float64)
        keep = (a + b) > 0
        a, b = a[keep], b[keep]
        stat = ((a - b) ** 2 / (a + b)).sum()
        df = max(len(a) - 1, 1)
        assert stat < df + 4.0 * np.sqrt(2.0 * df), (j, stat, df)
