"""resolve_auto_knobs: the HBM-fit resolution that makes the shipped
configs run at bench speed by default (VERDICT r2 Weak #4). Calibration
points are the measured fit boundary on a 16G v5e chip (PERF.md r3)."""

import dataclasses

from midgpt_tpu.config import get_config
from midgpt_tpu.train import resolve_auto_knobs

HBM = int(16e9)


def _owt(batch, accum=1):
    cfg = get_config("openwebtext")
    return dataclasses.replace(cfg, batch_size=batch, g_accum_iters=accum)


def test_124m_single_chip_resolves_none():
    cfg = resolve_auto_knobs(_owt(24), 1, hbm_bytes=HBM)
    assert cfg.model.remat == "none"
    assert cfg.model.scan_unroll == cfg.model.n_layer


def test_124m_oversized_batch_backs_off():
    cfg = resolve_auto_knobs(_owt(48), 1, hbm_bytes=HBM)
    assert cfg.model.remat != "none"  # B=48 at remat=none OOMs on the chip
    assert cfg.model.scan_unroll == 1  # unroll only pays off with none


def test_shipped_config_on_8_device_host_resolves_none():
    # the reference's single-host recipe: 2048 x 16 accum = microbatch 128,
    # 16 per device on 8 devices — the shape the config actually targets
    cfg = resolve_auto_knobs(get_config("openwebtext"), 8, hbm_bytes=HBM)
    assert cfg.model.remat == "none"


def test_llama_family_rung_resolves_none():
    cfg = get_config("llama_7b")
    cfg = dataclasses.replace(
        cfg,
        model=dataclasses.replace(cfg.model, n_layer=2),
        batch_size=8,
        mesh=dataclasses.replace(cfg.mesh, tensor=1),
    )
    assert resolve_auto_knobs(cfg, 1, hbm_bytes=HBM).model.remat == "none"


def test_explicit_knobs_untouched():
    cfg = get_config("openwebtext")
    cfg = dataclasses.replace(
        cfg, model=dataclasses.replace(cfg.model, remat="full", scan_unroll=1)
    )
    out = resolve_auto_knobs(cfg, 1, hbm_bytes=HBM)
    assert out.model.remat == "full" and out.model.scan_unroll == 1


def test_huge_model_resolves_full():
    cfg = get_config("llama_7b")  # full 32 layers, one device, batch 512
    cfg = dataclasses.replace(
        cfg, mesh=dataclasses.replace(cfg.mesh, tensor=1)
    )
    assert resolve_auto_knobs(cfg, 1, hbm_bytes=HBM).model.remat == "full"


def test_uncalibrated_chip_class_leans_optimistic():
    """On HBM sizes far from the calibrated 16G v5e, the fit thresholds
    are an extrapolation: resolve_auto_knobs widens the fast-knob band
    (+0.06) and relies on the first-step OOM step-down ladder to correct
    a miss — nothing ever corrects a too-conservative pick upward
    (VERDICT r4 Weak #7)."""
    from midgpt_tpu.train import estimate_hbm_fill

    conservative = resolve_auto_knobs(_owt(48), 1, hbm_bytes=HBM)
    assert conservative.model.remat != "none"
    # find a batch whose estimated fill on the big chip lands INSIDE the
    # optimism band (0.78, 0.84] — only the margin makes it resolve none
    big_hbm = int(95e9)
    batch = next(
        b for b in range(64, 4096, 16)
        if 0.78 < estimate_hbm_fill(_owt(b), 1, big_hbm) <= 0.84
    )
    optimistic = resolve_auto_knobs(_owt(batch), 1, hbm_bytes=big_hbm)
    assert optimistic.model.remat == "none"
    # the SAME fill on the calibrated class must stay conservative,
    # proving the margin (not the base threshold) did the work
    fill = estimate_hbm_fill(_owt(batch), 1, big_hbm)
    assert fill > 0.78
