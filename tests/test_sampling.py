"""KV-cache decode parity with the full forward, and generation sanity."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.config import ModelConfig
from midgpt_tpu.models.gpt import GPT, KVCache, decode_step, prefill
from midgpt_tpu.sampling import generate

CFG = ModelConfig(
    block_size=64, vocab_size=96, n_layer=2, n_head=4, n_embd=32,
    dropout=0.0, attn_impl="naive", remat="none",
)


def test_decode_matches_full_forward():
    """Stepping token-by-token through the cache must reproduce the full
    batched forward's last-position logits at every position."""
    model = GPT.init(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, CFG.vocab_size)

    full_logits = model(tokens)  # [B, T, V]

    cache = KVCache.init(CFG, batch=2, max_len=16, dtype=jnp.float32)
    for t in range(16):
        logits_t, cache = decode_step(
            model, tokens[:, t], jnp.asarray(t, jnp.int32), cache
        )
        np.testing.assert_allclose(
            np.asarray(logits_t),
            np.asarray(full_logits[:, t, :]),
            atol=2e-4,
            err_msg=f"position {t}",
        )


def test_prefill_matches_stepwise():
    model = GPT.init(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, CFG.vocab_size)
    cache = KVCache.init(CFG, batch=2, max_len=12, dtype=jnp.float32)
    logits, cache2 = prefill(model, tokens, cache)
    full = model(tokens)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, -1, :]), atol=2e-4
    )
    # caches populated only up to the prompt length (time-minor layout)
    assert not np.allclose(np.asarray(cache2.k[..., :8]), 0)
    np.testing.assert_array_equal(np.asarray(cache2.k[..., 8:]), 0)


def test_generate_shapes_and_determinism():
    model = GPT.init(jax.random.PRNGKey(0), CFG)
    prompt = jnp.zeros((3, 4), dtype=jnp.int32)
    out1 = generate(
        model, prompt, 8, key=jax.random.PRNGKey(5), temperature=1.0,
        cache_dtype=jnp.float32,
    )
    assert out1.shape == (3, 8)
    assert (np.asarray(out1) >= 0).all() and (np.asarray(out1) < CFG.vocab_size).all()
    out2 = generate(
        model, prompt, 8, key=jax.random.PRNGKey(5), temperature=1.0,
        cache_dtype=jnp.float32,
    )
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_generate_greedy_matches_argmax_rollout():
    model = GPT.init(jax.random.PRNGKey(0), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (1, 4), 0, CFG.vocab_size)
    out = generate(
        model, prompt, 6, key=jax.random.PRNGKey(0), temperature=0.0,
        cache_dtype=jnp.float32,
    )
    # manual greedy rollout with full forwards
    seq = np.asarray(prompt)
    for _ in range(6):
        logits = model(jnp.asarray(seq))
        nxt = int(np.argmax(np.asarray(logits[0, -1])))
        seq = np.concatenate([seq, [[nxt]]], axis=1)
    np.testing.assert_array_equal(np.asarray(out[0]), seq[0, 4:])


def test_generate_default_cache_dtype_with_f32_model():
    """Regression: bf16 cache + float32 params must not crash (decode casts
    K/V into the cache dtype)."""
    model = GPT.init(jax.random.PRNGKey(0), CFG)
    prompt = jnp.zeros((1, 4), dtype=jnp.int32)
    out = generate(model, prompt, 4, key=jax.random.PRNGKey(0))
    assert out.shape == (1, 4)


def test_generate_gqa_variant():
    cfg = dataclasses.replace(CFG, n_kv_head=2)
    model = GPT.init(jax.random.PRNGKey(0), cfg)
    prompt = jnp.zeros((2, 3), dtype=jnp.int32)
    out = generate(
        model, prompt, 5, key=jax.random.PRNGKey(1), cache_dtype=jnp.float32
    )
    assert out.shape == (2, 5)


def test_sharded_sampler_matches_unsharded(mesh8):
    """make_sampler under the 8-device mesh (TP-sharded params + cache)
    must reproduce single-device greedy generation exactly."""
    from jax.sharding import NamedSharding

    from midgpt_tpu.models.gpt import GPT_PARAM_RULES
    from midgpt_tpu.parallel.sharding import param_shardings
    from midgpt_tpu.sampling import make_sampler

    model = GPT.init(jax.random.PRNGKey(0), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, CFG.vocab_size)
    key = jax.random.PRNGKey(2)

    ref = generate(
        model, prompt, 12, key=key, temperature=0.0, cache_dtype=jnp.float32
    )

    shardings = param_shardings(mesh8, model, GPT_PARAM_RULES)
    sharded_model = jax.tree.map(jax.device_put, model, shardings)
    sampler = make_sampler(
        12, mesh=mesh8, temperature=0.0, cache_dtype=jnp.float32
    )
    out = sampler(sharded_model, prompt, key)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_batched_prefill_matches_stepwise_oracle():
    """One-pass prefill (batched forward collecting K/V from the block
    scan) vs the token-by-token decode_step oracle: same cache contents
    and same next-token logits."""
    from midgpt_tpu.models.gpt import prefill_stepwise

    model = GPT.init(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, CFG.vocab_size)

    cache_a = KVCache.init(CFG, batch=2, max_len=24, dtype=jnp.float32)
    logits_a, cache_a = prefill(model, tokens, cache_a)
    cache_b = KVCache.init(CFG, batch=2, max_len=24, dtype=jnp.float32)
    logits_b, cache_b = prefill_stepwise(model, tokens, cache_b)

    np.testing.assert_allclose(
        np.asarray(logits_a), np.asarray(logits_b), atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(cache_a.k), np.asarray(cache_b.k), atol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(cache_a.v), np.asarray(cache_b.v), atol=2e-5
    )


@pytest.mark.parametrize(
    "r_len,window,kv_heads",
    [
        (4, 16, None),  # normal: chunks shorter than the window
        (16, 8, None),  # chunk LONGER than the window: recent rows must
                        # evict mid-chunk too (r4 review — mask_rec bound)
        (4, 16, 2),     # GQA (llama-family serving shape)
    ],
)
def test_chunked_decode_matches_decode_step_oracle(r_len, window, kv_heads):
    """Teacher-forced logits parity: the chunked recent-buffer decode path
    (decode_step_recent + merge_recent, the serving hot path) must match
    the per-token decode_step oracle at every position — including across
    chunk merges, ring wrap, sliding-window eviction, and GQA."""
    from midgpt_tpu.models.gpt import decode_step_recent, merge_recent

    cfg = dataclasses.replace(CFG, n_kv_head=kv_heads)  # None = MHA default
    model = GPT.init(jax.random.PRNGKey(0), cfg)
    p, n_steps = 5, 17
    total = p + n_steps
    tokens = jax.random.randint(
        jax.random.PRNGKey(4), (2, total), 0, cfg.vocab_size
    )

    # oracle: plain ring decode at exactly `window` slots
    cache_o = KVCache.init(cfg, batch=2, max_len=window, dtype=jnp.float32)
    _, cache_o = prefill(model, tokens[:, :p], cache_o)
    oracle = []
    for t in range(p, total):
        lo, cache_o = decode_step(
            model, tokens[:, t], jnp.asarray(t, jnp.int32), cache_o,
            rope_len=total,
        )
        oracle.append(np.asarray(lo))

    # chunked: padded ring + recent buffers, merged every r_len steps
    wp = -(-window // r_len) * r_len
    cache = KVCache.init(cfg, batch=2, max_len=wp, dtype=jnp.float32)
    _, cache = prefill(model, tokens[:, :p], cache)
    got = []
    base = p
    while base < total:
        clen = min(r_len - base % r_len, total - base)
        rshape = (cfg.n_layer, 2, cfg.kv_heads, r_len, cfg.head_dim)
        rk = jnp.zeros(rshape, jnp.float32)
        rv = jnp.zeros(rshape, jnp.float32)
        for r in range(clen):
            t = base + r
            lg, rk, rv = decode_step_recent(
                model, tokens[:, t], jnp.asarray(t, jnp.int32), cache,
                rk, rv, jnp.asarray(r, jnp.int32), base, window, total,
            )
            got.append(np.asarray(lg))
        cache = merge_recent(cache, rk, rv, base % wp, clen)
        base += clen

    for i, (a, b) in enumerate(zip(oracle, got)):
        np.testing.assert_allclose(
            a, b, atol=2e-4, err_msg=f"step {i} (pos {p + i})"
        )


def test_generate_chunk_len_invariance():
    """Sampled tokens must not depend on the chunk length (greedy)."""
    model = GPT.init(jax.random.PRNGKey(0), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(6), (2, 5), 0, CFG.vocab_size)
    outs = [
        np.asarray(
            generate(
                model, prompt, 10, key=jax.random.PRNGKey(1),
                temperature=0.0, cache_dtype=jnp.float32, chunk_len=cl,
            )
        )
        for cl in (1, 3, 64)
    ]
    np.testing.assert_array_equal(outs[0], outs[1])
    np.testing.assert_array_equal(outs[0], outs[2])


def test_generate_kv_sliding_chunked_matches_oracle():
    """sliding='kv' generation (chunked ring + eviction) vs a manual greedy
    rollout through the decode_step oracle ring."""
    cfg_small = dataclasses.replace(CFG, block_size=12)  # slides early
    model = GPT.init(jax.random.PRNGKey(0), cfg_small)
    p, n = 6, 14  # total 20 > block 12 -> slides
    prompt = jax.random.randint(jax.random.PRNGKey(7), (1, p), 0, cfg_small.vocab_size)
    out = generate(
        model, prompt, n, key=jax.random.PRNGKey(0), temperature=0.0,
        cache_dtype=jnp.float32, sliding="kv", chunk_len=4,
    )

    w = cfg_small.block_size
    cache = KVCache.init(cfg_small, 1, w, dtype=jnp.float32)
    logits, cache = prefill(model, prompt, cache)
    toks = []
    pos = p
    tok = jnp.argmax(logits, -1).astype(jnp.int32)
    for _ in range(n):
        toks.append(int(tok[0]))
        logits, cache = decode_step(
            model, tok, jnp.asarray(pos, jnp.int32), cache, rope_len=p + n
        )
        tok = jnp.argmax(logits, -1).astype(jnp.int32)
        pos += 1
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(toks))


def test_generate_flash_configured_unaligned_prompt(pallas_interpret):
    """attn_impl='flash' models must still sample with prompts that don't
    divide the kernel block size (prefill remaps to the auto dispatch)."""
    cfg = dataclasses.replace(CFG, attn_impl="flash")
    model = GPT.init(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 13), 0, cfg.vocab_size)
    toks = generate(
        model, prompt, 4, key=jax.random.PRNGKey(2), temperature=0.0,
        cache_dtype=jnp.float32,
    )
    assert toks.shape == (1, 4)


@pytest.mark.slow  # >20 s (24 unjitted oracle forwards, one compile per
# growing crop shape) — moved off tier-1 per conftest's >20 s convention;
# CI home: hlo-audit's slow-tier step
def test_generate_past_block_size_matches_sliding_window_oracle():
    """Generation beyond block_size: the ring-buffer cache must reproduce
    the reference's sliding-window conditioning (sample.py:74
    ``idx[:, -block_size:]`` + full forward per token) token for token.
    Greedy decoding so any divergence is a hard mismatch."""
    cfg = dataclasses.replace(CFG, block_size=16)
    model = GPT.init(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 5), 0, cfg.vocab_size)
    n_new = 24  # 5 + 24 = 29 >> block_size 16

    toks = generate(
        model, prompt, n_new, key=jax.random.PRNGKey(4),
        temperature=0.0, cache_dtype=jnp.float32,
    )

    # reference-style oracle: crop to the last block_size tokens, full
    # forward, pluck the last real position, greedy argmax
    idx = np.asarray(prompt)
    for _ in range(n_new):
        idx_cond = idx[:, -cfg.block_size:]
        logits = np.asarray(model(jnp.asarray(idx_cond)))
        nxt = logits[:, idx_cond.shape[1] - 1, :].argmax(-1)
        idx = np.concatenate([idx, nxt[:, None].astype(idx.dtype)], axis=1)
    oracle = idx[:, 5:]

    np.testing.assert_array_equal(np.asarray(toks), oracle)


def test_generate_past_block_size_kv_mode_runs():
    """The fast ring-buffer sliding mode: O(W)/token, documented
    approximation — sanity only (it intentionally diverges from the
    recompute-the-window reference semantics)."""
    cfg = dataclasses.replace(CFG, block_size=16)
    model = GPT.init(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 5), 0, cfg.vocab_size)
    toks = generate(
        model, prompt, 24, key=jax.random.PRNGKey(4),
        temperature=0.0, cache_dtype=jnp.float32, sliding="kv",
    )
    assert toks.shape == (2, 24)
    assert (np.asarray(toks) >= 0).all() and (np.asarray(toks) < 96).all()


def test_generate_long_prompt_cropped_like_reference():
    """A prompt longer than block_size conditions on its last block_size
    tokens (sample.py:74)."""
    cfg = dataclasses.replace(CFG, block_size=16)
    model = GPT.init(jax.random.PRNGKey(0), cfg)
    long_prompt = jax.random.randint(
        jax.random.PRNGKey(5), (1, 23), 0, cfg.vocab_size
    )
    t1 = generate(
        model, long_prompt, 4, key=jax.random.PRNGKey(6),
        temperature=0.0, cache_dtype=jnp.float32,
    )
    t2 = generate(
        model, long_prompt[:, -16:], 4, key=jax.random.PRNGKey(6),
        temperature=0.0, cache_dtype=jnp.float32,
    )
    np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))
