"""Flash-attention kernel vs the naive oracle, on CPU via the Pallas
interpreter. Real-TPU parity is exercised by bench.py / tpu smoke runs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.ops.attention import naive_attention

# interpret-mode pallas on CPU (shared pallas_interpret fixture)
import midgpt_tpu.ops.flash as flash_mod


@pytest.fixture(autouse=True)
def _interpret_mode(pallas_interpret):
    yield


def _rand_qkv(key, b, h, hkv, t, c, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    q = jax.random.normal(k1, (b, h, t, c), dtype)
    k = jax.random.normal(k2, (b, hkv, t, c), dtype)
    v = jax.random.normal(k3, (b, hkv, t, c), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_flash_forward_matches_naive(causal):
    q, k, v = _rand_qkv(jax.random.PRNGKey(0), 2, 2, 2, 256, 32)
    out = flash_mod.flash_attention(q, k, v, causal, 128, 128)
    ref = naive_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_forward_gqa():
    q, k, v = _rand_qkv(jax.random.PRNGKey(1), 1, 4, 2, 256, 32)
    out = flash_mod.flash_attention(q, k, v, True, 128, 128)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_grad_matches_naive():
    q, k, v = _rand_qkv(jax.random.PRNGKey(2), 1, 2, 2, 256, 32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_mod.flash_attention(q, k, v, True, 128, 128) ** 2)

    def loss_naive(q, k, v):
        return jnp.sum(naive_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gn, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, err_msg=f"d{name}"
        )


def test_flash_grad_gqa():
    q, k, v = _rand_qkv(jax.random.PRNGKey(3), 1, 4, 2, 128, 32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_mod.flash_attention(q, k, v, True, 128, 128) ** 2)

    def loss_naive(q, k, v):
        return jnp.sum(naive_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gn, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, err_msg=f"d{name}"
        )


def test_flash_rejects_ragged_seq():
    q, k, v = _rand_qkv(jax.random.PRNGKey(4), 1, 1, 1, 192, 32)
    with pytest.raises(AssertionError):
        flash_mod.flash_attention(q, k, v, True, 128, 128)


def test_flash_lse_outputs_and_grads():
    """flash_attention_lse: lse matches the f32 oracle, and a loss that
    consumes BOTH outputs differentiates correctly (the lse cotangent is
    folded into the backward kernels as delta - dlse)."""
    import math

    q, k, v = _rand_qkv(jax.random.PRNGKey(7), 1, 2, 2, 256, 32)
    scale = 1.0 / math.sqrt(q.shape[-1])

    def oracle(q, k, v):
        z = jnp.einsum("bhqc,bhjc->bhqj", q, k).astype(jnp.float32)
        mask = jnp.tril(jnp.ones(z.shape[-2:], bool))
        z = jnp.where(mask, z, -jnp.inf) * 1.0
        z = z * scale
        lse = jax.scipy.special.logsumexp(z, axis=-1)
        out = jnp.einsum(
            "bhqj,bhjc->bhqc", jax.nn.softmax(z, axis=-1).astype(v.dtype), v
        )
        return out, lse

    out_f, lse_f = flash_mod.flash_attention_lse(q, k, v, True, 128, 128)
    out_o, lse_o = oracle(q, k, v)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_o), atol=2e-5)
    np.testing.assert_allclose(np.asarray(lse_f), np.asarray(lse_o), atol=2e-5)

    def loss_flash(q, k, v):
        out, lse = flash_mod.flash_attention_lse(q, k, v, True, 128, 128)
        return jnp.sum(out**2) + jnp.sum(jnp.sin(lse))

    def loss_oracle(q, k, v):
        out, lse = oracle(q, k, v)
        return jnp.sum(out**2) + jnp.sum(jnp.sin(lse))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    go = jax.grad(loss_oracle, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, go, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, err_msg=f"d{name}"
        )


def test_flash_sharded_wrapper_matches_unsharded(mesh8):
    """attention(impl='flash') under a live data+TP mesh must route through
    the shard_map wrapper (ops/attention._flash_sharded — VERDICT r3
    Missing #3: the bare pallas_call would make GSPMD gather the full
    batch) and reproduce the unsharded flash run, forward and grads."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from midgpt_tpu.ops.attention import attention
    from midgpt_tpu.parallel.sharding import axis_rules

    b, h, hkv, t, c = 4, 4, 2, 128, 16
    q, k, v = _rand_qkv(jax.random.PRNGKey(3), b, h, hkv, t, c)

    def loss(q, k, v):
        out = attention(q, k, v, impl="flash", causal=True)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    l_ref = jax.jit(loss)(q, k, v)
    g_ref = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)

    # mesh8 has sequence=2 -> wrapper declines (ring territory); use a
    # dedicated data+TP mesh for the wrapped run
    from midgpt_tpu.config import MeshConfig
    from midgpt_tpu.parallel.mesh import create_mesh

    mesh = create_mesh(MeshConfig(replica=2, fsdp=2, sequence=1, tensor=2))
    qs = jax.device_put(q, NamedSharding(mesh, P(("replica", "fsdp"), "tensor")))
    ks = jax.device_put(k, NamedSharding(mesh, P(("replica", "fsdp"), "tensor")))
    vs = jax.device_put(v, NamedSharding(mesh, P(("replica", "fsdp"), "tensor")))

    def wrapped_loss(q, k, v):
        with axis_rules(mesh):
            return loss(q, k, v)

    l_sh = jax.jit(wrapped_loss)(qs, ks, vs)
    g_sh = jax.jit(jax.grad(wrapped_loss, argnums=(0, 1, 2)))(qs, ks, vs)
    np.testing.assert_allclose(float(l_sh), float(l_ref), rtol=1e-5)
    for a, bb, name in zip(g_sh, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bb), atol=1e-4, err_msg=f"d{name}"
        )

    # under the sequence-sharded mesh8 the wrapper must decline (return
    # None path) yet the math must still hold via GSPMD
    from midgpt_tpu.ops.attention import _flash_sharded

    with axis_rules(mesh8):
        assert _flash_sharded(q, k, v, True) is None


def test_flash_dropout_matches_hash_oracle():
    """flash_attention_dropout vs a dense oracle built from the SAME
    counter-based hash (dropout_mask_reference): identical forward and
    q/k/v grads. The hash differs from jax.random.bernoulli by design —
    the oracle shares it, so this is exact parity, not statistical."""
    b, h, hkv, t, c = 2, 4, 2, 256, 16
    rate = 0.2
    q, k, v = _rand_qkv(jax.random.PRNGKey(5), b, h, hkv, t, c)
    seed = jnp.int32(12345)

    def oracle(q, k, v):
        groups = h // hkv
        qg = q.reshape(b, hkv, groups, t, c)
        z = jnp.einsum("bkgqc,bkjc->bkgqj", qg, k,
                       preferred_element_type=jnp.float32)
        mask = jnp.tril(jnp.ones((t, t), bool))
        z = jnp.where(mask, z, -1e30) / jnp.sqrt(c)
        p = jax.nn.softmax(z, axis=-1)  # undropped softmax
        keep = flash_mod.dropout_mask_reference(seed, b, h, t, rate)
        keep = keep.reshape(b, hkv, groups, t, t)
        pd = jnp.where(keep, p / (1.0 - rate), 0.0)
        out = jnp.einsum("bkgqj,bkjc->bkgqc", pd.astype(v.dtype), v)
        return out.reshape(b, h, t, c)

    got = flash_mod.flash_attention_dropout(q, k, v, seed, rate, True, 128, 128)
    want = oracle(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_mod.flash_attention_dropout(q, k, v, seed, rate, True, 128, 128)
            ** 2
        )

    def loss_oracle(q, k, v):
        return jnp.sum(oracle(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    go = jax.grad(loss_oracle, argnums=(0, 1, 2))(q, k, v)
    for a, bb, name in zip(gf, go, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bb), atol=2e-4, err_msg=f"d{name}"
        )


def test_flash_dropout_mask_statistics_and_determinism():
    t, rate = 256, 0.3
    m1 = flash_mod.dropout_mask_reference(jnp.int32(7), 2, 3, t, rate)
    m2 = flash_mod.dropout_mask_reference(jnp.int32(7), 2, 3, t, rate)
    m3 = flash_mod.dropout_mask_reference(jnp.int32(8), 2, 3, t, rate)
    np.testing.assert_array_equal(np.asarray(m1), np.asarray(m2))
    assert not np.array_equal(np.asarray(m1), np.asarray(m3))
    keep_rate = float(np.asarray(m1).mean())
    assert abs(keep_rate - (1 - rate)) < 0.01, keep_rate
    # per-head masks differ
    assert not np.array_equal(np.asarray(m1[0, 0]), np.asarray(m1[0, 1]))


def test_flash_dropout_through_dispatch():
    """attention(impl='flash', dropout...) routes to the dropout kernel and
    stays deterministic per key; rate=0 equals the plain kernel."""
    from midgpt_tpu.ops.attention import attention

    b, h, t, c = 2, 2, 128, 16
    q, k, v = _rand_qkv(jax.random.PRNGKey(9), b, h, h, t, c)
    key = jax.random.PRNGKey(3)
    o1 = attention(q, k, v, impl="flash", dropout_rate=0.25,
                   dropout_key=key, deterministic=False)
    o2 = attention(q, k, v, impl="flash", dropout_rate=0.25,
                   dropout_key=key, deterministic=False)
    o3 = attention(q, k, v, impl="flash", dropout_rate=0.25,
                   dropout_key=jax.random.PRNGKey(4), deterministic=False)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    assert not np.array_equal(np.asarray(o1), np.asarray(o3))
    plain = attention(q, k, v, impl="flash", dropout_rate=0.0)
    assert not np.array_equal(np.asarray(o1), np.asarray(plain))


def test_dropout_offsets_anchor_global_coordinates():
    """The (row_off, col_off, bh_off, n_head_total) anchors (r5, ring
    support): a call covering rows [r0, r0+t) x cols [c0, c0+t) of a
    larger virtual score matrix must drop exactly the corresponding
    sub-block of the GLOBAL mask — verified against the dense oracle of
    the full matrix."""
    b, h, t, c = 1, 2, 128, 16
    big_t = 256
    q, k, v = _rand_qkv(jax.random.PRNGKey(5), b, h, h, big_t, c)
    seed = jnp.int32(31337)
    rate = 0.3

    # global oracle over the full [big_t, big_t] coordinate space
    keepm = flash_mod.dropout_mask_reference(seed, b, h, big_t, rate)

    # the (row block 1, col block 0) off-diagonal tile: fully visible
    r0, c0 = t, 0
    qs = q[:, :, r0 : r0 + t]
    ks, vs = k[:, :, c0 : c0 + t], v[:, :, c0 : c0 + t]
    out, _ = flash_mod.flash_attention_dropout_lse(
        qs, ks, vs, seed, rate, causal=False,
        row_off=jnp.int32(r0), col_off=jnp.int32(c0),
    )

    # dense recomputation of the same tile with the global mask slice
    import math

    z = jnp.einsum(
        "bhqc,bhjc->bhqj", qs, ks, preferred_element_type=jnp.float32
    ) / math.sqrt(c)
    p = jax.nn.softmax(z, axis=-1)
    tile = keepm[:, :, r0 : r0 + t, c0 : c0 + t]
    p = jnp.where(tile, p / (1.0 - rate), 0.0)
    ref = jnp.einsum("bhqj,bhjc->bhqc", p.astype(vs.dtype), vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)


def test_dropout_bh_offset_selects_global_head_stream():
    """bh_off + n_head_total must reproduce the mask stream of the
    corresponding global (batch, head) slice — the property batch/head-
    sharded ring dropout relies on."""
    b, h, t, c = 2, 4, 128, 16
    q, k, v = _rand_qkv(jax.random.PRNGKey(6), b, h, h, t, c)
    seed = jnp.int32(-777)
    rate = 0.25

    full = flash_mod.flash_attention_dropout(q, k, v, seed, rate, True)
    # shard: second batch row, heads [2, 4) — its flat bh base is
    # (1*H + 2) with the GLOBAL head count as stride
    qs, ks, vs = (a[1:2, 2:4] for a in (q, k, v))
    shard, _ = flash_mod.flash_attention_dropout_lse(
        qs, ks, vs, seed, rate, True,
        bh_off=jnp.int32(1 * h + 2), n_head_total=h,
    )
    np.testing.assert_allclose(
        np.asarray(shard), np.asarray(full[1:2, 2:4]), atol=3e-5
    )
