"""Switch-style top-1 MoE (models/gpt.MoEMLP): routing/dispatch oracles,
load-balance aux, training integration, and expert parallelism on the
8-device CPU mesh. Beyond the reference (its MLP is dense)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from midgpt_tpu.config import ExperimentConfig, MeshConfig, ModelConfig
from midgpt_tpu.models.gpt import GPT, MLP, MoEMLP
from midgpt_tpu.parallel.sharding import axis_rules


def _cfg(**kw):
    base = dict(
        block_size=32, vocab_size=64, n_layer=2, n_head=2, n_embd=16,
        mlp="moe", moe_experts=4, moe_capacity=2.0, dropout=0.0,
        attn_impl="naive", remat="none",
    )
    base.update(kw)
    return ModelConfig(**base)


def test_moe_forward_shapes_and_determinism():
    cfg = _cfg()
    moe = MoEMLP.init(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 16))
    y1, aux1 = moe(x)
    y2, aux2 = moe(x)
    assert y1.shape == x.shape
    assert aux1.shape == ()
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
    assert float(aux1) == float(aux2)


def test_moe_identical_experts_match_dense_oracle():
    """With every expert holding the SAME weights and ample capacity, the
    MoE output must equal gate_prob * dense_mlp(x) — the Switch combine
    scales by the router prob (its gradient path)."""
    cfg = _cfg(moe_capacity=4.0)  # C = T: nothing can drop
    moe = MoEMLP.init(jax.random.PRNGKey(0), cfg)
    # copy expert 0 into all experts
    up0 = moe.expert_up[0]
    down0 = moe.expert_down[0]
    moe = dataclasses.replace(
        moe,
        expert_up=jnp.broadcast_to(up0, moe.expert_up.shape),
        expert_down=jnp.broadcast_to(down0, moe.expert_down.shape),
    )
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 32, 16))
    y, _ = moe(x)

    probs = jax.nn.softmax(moe.router(x.astype(jnp.float32)), axis=-1)
    gate = jnp.max(probs, axis=-1)[..., None]
    dense = jax.nn.gelu(x @ up0) @ down0
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(gate * dense), atol=1e-5
    )


def test_moe_capacity_drops_tokens():
    """capacity_factor -> tiny: overflowing tokens contribute ZERO (the
    block residual passes them through) — standard Switch semantics."""
    cfg = _cfg(moe_experts=2, moe_capacity=0.0625)  # C = ceil(.0625*32/2) = 1
    moe = MoEMLP.init(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(3), (1, 32, 16))
    y, _ = moe(x)
    # at most 2 experts x 1 slot = 2 tokens can have nonzero output
    nonzero_rows = int(jnp.sum(jnp.any(jnp.abs(y[0]) > 0, axis=-1)))
    assert nonzero_rows <= 2, nonzero_rows


def test_moe_aux_is_one_when_balanced():
    """A uniform router gives aux = E * sum_e (1/E)(1/E) * E = 1."""
    cfg = _cfg()
    moe = MoEMLP.init(jax.random.PRNGKey(0), cfg)
    moe = dataclasses.replace(
        moe,
        router=dataclasses.replace(
            moe.router, weight=jnp.zeros_like(moe.router.weight)
        ),
    )
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 32, 16))
    _, aux = moe(x)
    np.testing.assert_allclose(float(aux), 1.0, atol=1e-5)


def test_moe_balanced_router_drops_nothing():
    """Router telemetry (VERDICT r5 Next #7): an engineered perfectly
    balanced router (token t -> expert t % E, round-robin) must report a
    dropped-claim fraction of exactly 0 at capacity_factor >= 1 — and the
    aux loss must sit at its balanced optimum ~1.0."""
    cfg = _cfg(moe_capacity=1.25)
    e, d = cfg.moe_experts, cfg.n_embd
    moe = MoEMLP.init(jax.random.PRNGKey(0), cfg)
    # router reads the first E features; x rows one-hot by t % E
    w = np.zeros((d, e), np.float32)
    w[:e, :e] = 20.0 * np.eye(e)
    moe = dataclasses.replace(
        moe, router=dataclasses.replace(moe.router, weight=jnp.asarray(w))
    )
    t = 32
    x = np.zeros((2, t, d), np.float32)
    x[:, np.arange(t), np.arange(t) % e] = 1.0
    y, aux, dropped = moe(jnp.asarray(x), return_dropped=True)
    assert float(dropped) == 0.0, float(dropped)
    np.testing.assert_allclose(float(aux), 1.0, atol=1e-2)


def test_moe_overflow_reports_dropped_fraction():
    """The same telemetry must SEE drops: capacity 1 slot per expert with
    a collapsed (uniform -> argmax expert 0) router drops all but 1 claim
    per row."""
    cfg = _cfg(moe_experts=2, moe_capacity=0.0625)  # C = 1
    moe = MoEMLP.init(jax.random.PRNGKey(0), cfg)
    moe = dataclasses.replace(
        moe,
        router=dataclasses.replace(
            moe.router, weight=jnp.zeros_like(moe.router.weight)
        ),
    )
    x = jax.random.normal(jax.random.PRNGKey(3), (1, 32, 16))
    _, _, dropped = moe(x, return_dropped=True)
    # 32 claims, 1 kept (expert 0's single slot) -> 31/32 dropped
    np.testing.assert_allclose(float(dropped), 31 / 32, atol=1e-6)


@pytest.mark.slow
def test_moe_gpt_stats_pass():
    """GPT.moe_stats: one deterministic forward returning the summed aux
    and mean dropped fraction the trainer logs per eval interval."""
    cfg = _cfg()
    model = GPT.init(jax.random.PRNGKey(0), cfg)
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
    st = model.moe_stats(tok)
    assert set(st) == {"aux", "dropped_frac"}
    aux = float(st["aux"])
    dropped = float(st["dropped_frac"])
    assert np.isfinite(aux) and aux > 0
    assert 0.0 <= dropped <= 1.0
    # must agree with the training-path aux from hidden(return_aux=True)
    _, aux_train = model.hidden(tok, return_aux=True)
    np.testing.assert_allclose(aux, float(aux_train), rtol=1e-5)


def test_moe_gpt_forward_and_aux():
    cfg = _cfg()
    model = GPT.init(jax.random.PRNGKey(0), cfg)
    assert isinstance(jax.tree.leaves(model.blocks.mlp.expert_up)[0], jax.Array)
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
    h, aux = model.hidden(tok, return_aux=True)
    assert h.shape == (2, 32, 16)
    assert np.isfinite(float(aux)) and float(aux) > 0


@pytest.mark.slow
def test_moe_trains_and_router_gets_gradients():
    from midgpt_tpu.parallel.mesh import create_mesh
    from midgpt_tpu.parallel.sharding import make_global_array
    from midgpt_tpu.train import init_state, make_optimizer, make_train_step

    cfg = ExperimentConfig(
        model=_cfg(),
        learning_rate=1e-2, warmup_steps=2, lr_decay_steps=20, max_steps=20,
        batch_size=8, g_accum_iters=1,
        mesh=MeshConfig(replica=1, fsdp=1, sequence=1, tensor=1),
    )
    mesh = create_mesh(cfg.mesh, devices=jax.devices()[:1])
    tx, _ = make_optimizer(cfg)
    state = init_state(cfg, mesh, tx, jax.random.PRNGKey(0))
    step = make_train_step(cfg, tx, mesh)
    rng = np.random.default_rng(0)
    x = rng.integers(0, 64, size=(1, 8, 32), dtype=np.int32)
    spec = P(None, ("replica", "fsdp"), "sequence")
    xg = make_global_array(x, mesh, spec)
    r0 = np.asarray(state.params.blocks.mlp.router.weight).copy()
    losses = []
    for i in range(8):
        state, loss = step(state, xg, xg, jax.random.PRNGKey(i))
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]  # memorizing a fixed batch
    r1 = np.asarray(state.params.blocks.mlp.router.weight)
    assert not np.allclose(r0, r1)  # aux + gate path reach the router


@pytest.mark.slow
def test_moe_expert_parallel_matches_single_device(mesh8):
    """ep: experts sharded over 'tensor' (GPT_PARAM_RULES) — the sharded
    loss must match the unsharded one."""
    from midgpt_tpu.parallel.mesh import create_mesh
    from midgpt_tpu.parallel.sharding import make_global_array
    from midgpt_tpu.train import init_state, make_optimizer, make_train_step

    def run(mesh_cfg, n_dev):
        cfg = ExperimentConfig(
            model=_cfg(),
            learning_rate=1e-3, warmup_steps=2, lr_decay_steps=10,
            max_steps=10, batch_size=8, g_accum_iters=1, mesh=mesh_cfg,
        )
        mesh = create_mesh(cfg.mesh, devices=jax.devices()[:n_dev])
        tx, _ = make_optimizer(cfg)
        state = init_state(cfg, mesh, tx, jax.random.PRNGKey(0))
        step = make_train_step(cfg, tx, mesh)
        rng = np.random.default_rng(1)
        x = rng.integers(0, 64, size=(1, 8, 32), dtype=np.int32)
        spec = P(None, ("replica", "fsdp"), "sequence")
        xg = make_global_array(x, mesh, spec)
        _, loss = step(state, xg, xg, jax.random.PRNGKey(1))
        return float(loss)

    sharded = run(MeshConfig(replica=1, fsdp=2, sequence=1, tensor=2), 4)
    plain = run(MeshConfig(replica=1, fsdp=1, sequence=1, tensor=1), 1)
    # bf16 reduction order differs across the expert psum; the summed
    # (per-layer) aux term amplifies it slightly vs the dense-only paths
    np.testing.assert_allclose(sharded, plain, rtol=1.5e-3)


def test_moe_expert_sharding_placement(mesh8):
    """The expert dim actually lands on the 'tensor' mesh axis."""
    from midgpt_tpu.models.gpt import GPT_PARAM_RULES
    from midgpt_tpu.parallel.sharding import param_shardings

    cfg = _cfg()
    model = GPT.init(jax.random.PRNGKey(0), cfg)
    sh = param_shardings(mesh8, model, GPT_PARAM_RULES)
    spec = sh.blocks.mlp.expert_up.spec
    # [L, E, D, F] right-aligned ("tensor", "fsdp", None): E -> tensor
    assert spec[-3] == "tensor", spec


def test_moe_decode_matches_full_forward():
    """KV-cached decode with an MoE model: per-token routing (C=1) must
    reproduce the batched forward's logits at each position."""
    from midgpt_tpu.models.gpt import KVCache, decode_step, prefill

    cfg = _cfg(moe_capacity=4.0)
    model = GPT.init(jax.random.PRNGKey(0), cfg)
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 64)

    full_logits = model(tok)  # [B, 8, V]
    cache = KVCache.init(cfg, 2, 8, dtype=jnp.float32)
    logits_p, cache = prefill(model, tok[:, :7], cache)
    step_logits, _ = decode_step(model, tok[:, 7], jnp.int32(7), cache)
    np.testing.assert_allclose(
        np.asarray(step_logits), np.asarray(full_logits[:, 7]), atol=2e-2,
        rtol=2e-2,
    )


def test_moe_generate_runs():
    from midgpt_tpu.sampling import generate

    cfg = _cfg(block_size=32)
    model = GPT.init(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 64)
    toks = generate(
        model, prompt, 12, key=jax.random.PRNGKey(2), temperature=0.0,
        cache_dtype=jnp.float32,
    )
    assert toks.shape == (2, 12)
    assert np.asarray(toks).min() >= 0


@pytest.mark.slow
def test_dense_config_resumes_from_pre_moe_checkpoint(tmp_path):
    """END-TO-END: a dense run's checkpoint whose stored fingerprint was
    hashed WITHOUT the r5 moe_* fields must still resume (code review
    r5: adding the fields changed every config's fingerprint). Simulated
    by rewriting the stored meta to the legacy hash and re-running."""
    import glob
    import json as _json
    import os

    from midgpt_tpu.checkpoint import config_fingerprint
    from midgpt_tpu.config import ExperimentConfig, MeshConfig, to_dict
    from midgpt_tpu.models.gpt import mlp_hidden_dim
    from midgpt_tpu.train import train
    from midgpt_tpu.data import write_tokens

    datadir = str(tmp_path / "data")
    os.makedirs(datadir, exist_ok=True)
    rng = np.random.default_rng(0)
    write_tokens(
        os.path.join(datadir, "train.bin"),
        rng.integers(0, 64, size=20_000).astype(np.uint16),
    )
    write_tokens(
        os.path.join(datadir, "val.bin"),
        rng.integers(0, 64, size=4_000).astype(np.uint16),
    )

    cfg = ExperimentConfig(
        model=_cfg(mlp="gelu"),
        rundir=str(tmp_path / "run"), data_dir=datadir,
        learning_rate=1e-3, warmup_steps=2, lr_decay_steps=8, max_steps=4,
        batch_size=8, g_accum_iters=1, eval_interval=100, eval_batches=1,
        mesh=MeshConfig(replica=1, fsdp=-1, sequence=1, tensor=1),
        debug=False,
    )
    train(cfg)

    # rewrite the stored fingerprint to the PRE-MOE hash
    impl = ("attn_impl", "norm_impl", "remat", "scan_unroll", "moe_aux_weight")
    fp = {k: v for k, v in to_dict(cfg.model).items() if k not in impl}
    fp["mlp_hidden"] = mlp_hidden_dim(cfg.model)
    legacy = config_fingerprint(
        {k: v for k, v in fp.items()
         if k not in ("moe_experts", "moe_capacity", "moe_top_k")}
    )
    assert legacy != config_fingerprint(fp)
    metas = glob.glob(str(tmp_path / "run" / "**" / "meta" / "metadata"),
                      recursive=True)
    assert metas, "no checkpoint meta found"
    for m in metas:
        d = _json.load(open(m))
        d["model_fingerprint"] = legacy
        _json.dump(d, open(m, "w"))

    cfg2 = dataclasses.replace(cfg, max_steps=6)
    final = train(cfg2)  # must NOT trip the fingerprint assert
    assert np.isfinite(final["val_loss"])


def test_moe_top2_identical_experts_equal_dense_exactly():
    """K=2 renormalizes the chosen gates to sum 1 (GShard), so identical
    experts with ample capacity must reproduce the dense MLP EXACTLY —
    a stronger oracle than top-1's gate-scaled version."""
    cfg = _cfg(moe_capacity=8.0, moe_top_k=2)
    moe = MoEMLP.init(jax.random.PRNGKey(0), cfg)
    up0, down0 = moe.expert_up[0], moe.expert_down[0]
    moe = dataclasses.replace(
        moe,
        expert_up=jnp.broadcast_to(up0, moe.expert_up.shape),
        expert_down=jnp.broadcast_to(down0, moe.expert_down.shape),
    )
    x = jax.random.normal(jax.random.PRNGKey(5), (2, 32, 16))
    y, _ = moe(x)
    dense = jax.nn.gelu(x @ up0) @ down0
    np.testing.assert_allclose(np.asarray(y), np.asarray(dense), atol=1e-5)


def test_moe_top2_balanced_router_aux_is_one():
    """The K=2 aux loss (first-choice fractions) still normalizes to 1.0
    under a uniform router — guards the K>1 aux path specifically."""
    cfg = _cfg(moe_top_k=2)
    moe = MoEMLP.init(jax.random.PRNGKey(0), cfg)
    moe = dataclasses.replace(
        moe,
        router=dataclasses.replace(
            moe.router, weight=jnp.zeros_like(moe.router.weight)
        ),
    )
    x = jax.random.normal(jax.random.PRNGKey(6), (2, 32, 16))
    _, aux = moe(x)
    np.testing.assert_allclose(float(aux), 1.0, atol=1e-5)


@pytest.mark.slow
def test_moe_top2_trains_and_balances():
    from midgpt_tpu.parallel.mesh import create_mesh
    from midgpt_tpu.parallel.sharding import make_global_array
    from midgpt_tpu.train import init_state, make_optimizer, make_train_step

    cfg = ExperimentConfig(
        model=_cfg(moe_top_k=2),
        learning_rate=1e-2, warmup_steps=2, lr_decay_steps=20, max_steps=20,
        batch_size=8, g_accum_iters=1,
        mesh=MeshConfig(replica=1, fsdp=1, sequence=1, tensor=1),
    )
    mesh = create_mesh(cfg.mesh, devices=jax.devices()[:1])
    tx, _ = make_optimizer(cfg)
    state = init_state(cfg, mesh, tx, jax.random.PRNGKey(0))
    step = make_train_step(cfg, tx, mesh)
    rng = np.random.default_rng(0)
    x = rng.integers(0, 64, size=(1, 8, 32), dtype=np.int32)
    spec = P(None, ("replica", "fsdp"), "sequence")
    xg = make_global_array(x, mesh, spec)
    losses = []
    for i in range(6):
        state, loss = step(state, xg, xg, jax.random.PRNGKey(i))
        losses.append(float(loss))
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]


@pytest.mark.slow
def test_moe_top2_ep_parity(mesh8):
    """Top-2 under the expert-parallel mesh matches single-device."""
    from midgpt_tpu.parallel.mesh import create_mesh
    from midgpt_tpu.parallel.sharding import make_global_array
    from midgpt_tpu.train import init_state, make_optimizer, make_train_step

    def run(mesh_cfg, n_dev):
        cfg = ExperimentConfig(
            model=_cfg(moe_top_k=2),
            learning_rate=1e-3, warmup_steps=2, lr_decay_steps=10,
            max_steps=10, batch_size=8, g_accum_iters=1, mesh=mesh_cfg,
        )
        mesh = create_mesh(cfg.mesh, devices=jax.devices()[:n_dev])
        tx, _ = make_optimizer(cfg)
        state = init_state(cfg, mesh, tx, jax.random.PRNGKey(0))
        step = make_train_step(cfg, tx, mesh)
        rng = np.random.default_rng(1)
        x = rng.integers(0, 64, size=(1, 8, 32), dtype=np.int32)
        spec = P(None, ("replica", "fsdp"), "sequence")
        xg = make_global_array(x, mesh, spec)
        _, loss = step(state, xg, xg, jax.random.PRNGKey(1))
        return float(loss)

    sharded = run(MeshConfig(replica=1, fsdp=2, sequence=1, tensor=2), 4)
    plain = run(MeshConfig(replica=1, fsdp=1, sequence=1, tensor=1), 1)
    np.testing.assert_allclose(sharded, plain, rtol=1.5e-3)
