"""TPxDP sharded paged serving (midgpt_tpu.serving on a multi-chip mesh):
greedy token-identity of the tensor-parallel engine against the
single-chip engine across the serving feature matrix (prefix cache x
chunked prefill x speculation x eviction x int8 quant), program-cache
distinctness per mesh, the shared-nothing DP cluster's
replica-placement invariance, and the
no-batch-allgather-in-page-gather audit rule (canned-HLO fixtures +
the live sharded program audits).

The exactness chain: test_serving.py pins the single-chip engine to the
exact fixed-batch sampler; these tests pin the sharded engine to the
single-chip engine. Sharding only reorders the two row-parallel
reductions per layer (wo / w_down psums), so identity is a seeded
contract, same regime as every serving PR's greedy-identity matrix —
f32 cache dtype keeps the argmax margins wide."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.config import MeshConfig, ModelConfig
from midgpt_tpu.models.gpt import GPT
from midgpt_tpu.parallel.mesh import create_mesh
from midgpt_tpu.serving import (
    ServingCluster,
    ServingEngine,
    pages_needed,
    serving_meshes,
)
from midgpt_tpu.serving.engine import (
    _PROGRAM_CACHE,
    _mesh_key,
    make_decode_window,
)

# n_head=4 (MHA) and vocab 96 divide tp=2 and tp=4; same family as the
# test_serving.py model so failures triangulate
CFG = ModelConfig(
    block_size=64, vocab_size=96, n_layer=2, n_head=4, n_embd=32,
    dropout=0.0, attn_impl="naive", remat="none",
)


@pytest.fixture(scope="module")
def model():
    return GPT.init(jax.random.PRNGKey(0), CFG)


def _mesh(tp):
    return create_mesh(
        MeshConfig(replica=1, fsdp=1, sequence=1, tensor=tp),
        devices=jax.devices()[:tp],
    )


def _prompts(n, base_len=5, stride=3):
    return [
        np.asarray(
            jax.random.randint(
                jax.random.PRNGKey(100 + i), (base_len + stride * i,), 0,
                CFG.vocab_size,
            )
        )
        for i in range(n)
    ]


def _run(model, mesh, prompts, n_new, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("window", 4)
    kw.setdefault("cache_dtype", jnp.float32)
    eng = ServingEngine(model, mesh=mesh, **kw)
    rids = [eng.submit(p, n_new, seed=i) for i, p in enumerate(prompts)]
    finished = eng.run()
    return [finished[r].tokens for r in rids], eng


# the feature matrix both tp degrees run against the single-chip engine:
# (prefix_cache, prefill_chunk, speculate, quant). The fast tier covers
# every FEATURE on tp=2 plus the tp=4 baseline; the remaining
# geometry x feature cross-products ride the slow tier (the CI
# serving-audit job runs this file unfiltered) — each slow combo is a
# fresh sharded-program compile, the most expensive thing in the file.
MATRIX = [
    pytest.param(True, None, 0, None, id="cache"),
    pytest.param(False, None, 0, None, id="nocache"),
    pytest.param(True, None, 3, None, id="spec"),
    pytest.param(True, None, 3, "int8", id="spec-quant"),
]
MATRIX_SLOW = [
    pytest.param(True, 3, 0, None, id="chunked"),
    pytest.param(True, 3, 0, "int8", id="chunked-quant"),
]


@pytest.fixture(scope="module")
def matrix_refs(model):
    """Single-chip engine streams per matrix combo, computed LAZILY and
    memoized for the module: the sharded runs all compare against
    these, and the fast tier must not pay for slow-only combos."""
    prompts = _prompts(3)
    refs = {}

    def get(cache, chunk, spec, quant):
        key = (cache, chunk, spec, quant)
        if key not in refs:
            refs[key], _ = _run(
                model, None, prompts, 10, prefix_cache=cache,
                prefill_chunk=chunk, speculate=spec, quant=quant,
            )
        return refs[key]

    return prompts, get


def _assert_tp_identity(model, matrix_refs, tp, cache, chunk, spec, quant):
    prompts, ref = matrix_refs
    got, eng = _run(
        model, _mesh(tp), prompts, 10, prefix_cache=cache,
        prefill_chunk=chunk, speculate=spec, quant=quant,
    )
    assert got == ref(cache, chunk, spec, quant)
    assert eng.tp == tp
    if spec:
        assert eng.verify_dispatches > 0


@pytest.mark.parametrize("cache,chunk,spec,quant", MATRIX)
def test_tp2_token_identity_matrix(model, matrix_refs, cache, chunk, spec,
                                   quant):
    """tp=2 engine is greedy token-identical to the single-chip engine
    across cache on/off x chunked x speculation x quant=int8 — sharding
    splits the weights/KV per chip, never the token stream."""
    _assert_tp_identity(model, matrix_refs, 2, cache, chunk, spec, quant)


@pytest.mark.parametrize("cache,chunk,spec,quant", [MATRIX[0]])
def test_tp4_token_identity(model, matrix_refs, cache, chunk, spec, quant):
    """tp=4 (one KV head per shard — the SNIPPETS.md target geometry)
    stays token-identical on the baseline combo; the rest of the tp=4
    matrix is the slow-tier cross-product below."""
    _assert_tp_identity(model, matrix_refs, 4, cache, chunk, spec, quant)


@pytest.mark.slow
@pytest.mark.parametrize("cache,chunk,spec,quant", MATRIX_SLOW)
def test_tp2_token_identity_matrix_slow(model, matrix_refs, cache, chunk,
                                        spec, quant):
    _assert_tp_identity(model, matrix_refs, 2, cache, chunk, spec, quant)


@pytest.mark.slow
@pytest.mark.parametrize(
    "cache,chunk,spec,quant", MATRIX[1:] + MATRIX_SLOW
)
def test_tp4_token_identity_matrix_slow(model, matrix_refs, cache, chunk,
                                        spec, quant):
    """The full tp=4 feature cross-product (nocache / chunked / spec /
    quant combos) — compile-heavy, slow tier, unfiltered in the CI
    serving-audit job."""
    _assert_tp_identity(model, matrix_refs, 4, cache, chunk, spec, quant)


@pytest.mark.slow
@pytest.mark.parametrize("tp", [2, 4])
def test_tp_layer_scan_token_identity(model, matrix_refs, tp):
    """The fused layer loop under TP (ROADMAP item 1's landing gate,
    sharded leg): a tp=2/4 engine with ``layer_scan="on"`` stays greedy
    token-identical to the single-chip UNROLLED engine — proving
    on == off transitively through the existing sharded matrix — on
    the cache and chunked combos."""
    prompts, ref = matrix_refs
    for cache, chunk, spec, quant in (
        (True, None, 0, None), (True, 3, 0, None),
    ):
        got, eng = _run(
            model, _mesh(tp), prompts, 10, prefix_cache=cache,
            prefill_chunk=chunk, speculate=spec, quant=quant,
            layer_scan="on",
        )
        assert got == ref(cache, chunk, spec, quant), (tp, chunk)
        assert eng.layer_scan == "on" and eng.tp == tp


@pytest.mark.slow
def test_tp2_layer_scan_kv_quant_identity(model):
    """Fused layer loop x int8 KV pool x tp=2: the scan slices the
    pool's scale planes as per-layer xs — streams must stay identical
    to the unrolled single-chip engine with the same pool precision."""
    prompts = _prompts(3)
    kw = dict(kv_quant="int8", cache_dtype=jnp.bfloat16)
    base, _ = _run(model, None, prompts, 10, layer_scan="off", **kw)
    got, _ = _run(model, _mesh(2), prompts, 10, layer_scan="on", **kw)
    assert got == base


def test_tp2_eviction_readmission_identity(model):
    """Mid-run eviction + re-admission under page pressure on the
    sharded engine: same evictions, same streams as single-chip (the
    evicted request re-prefills through its own cached pages on both)."""
    prompts = _prompts(4, base_len=6, stride=0)
    ref, re_ = _run(model, None, prompts, 16, num_pages=5, page_size=8)
    got, ge = _run(model, _mesh(2), prompts, 16, num_pages=5, page_size=8)
    assert re_.evictions > 0, "trace must actually exercise eviction"
    assert ge.evictions == re_.evictions
    assert got == ref


def test_tp2_sampled_spec_token_identity(model):
    """Sampled speculation (temperature > 0: rejection-sampling verify)
    under tp=2: the spec-on sampled stream is bitwise the single-chip
    engine's on a repetitive-prompt trace (drafts, acceptance uniforms
    and residual resamples are all functions of (request seed, stream
    position) only — the mesh must not enter the stream). Same seeded-
    contract regime as the greedy matrix: sharding only reorders the
    two row-parallel psums, and the f32 cache keeps the sampled
    compare margins wide."""
    prompts = [
        np.tile(
            np.asarray(
                jax.random.randint(
                    jax.random.PRNGKey(700 + i), (4,), 0, CFG.vocab_size
                )
            ),
            6,
        )
        for i in range(3)
    ]
    kw = dict(temperature=0.8, top_k=20, speculate=3, seed=3)
    ref, re_ = _run(model, None, prompts, 10, **kw)
    got, ge = _run(model, _mesh(2), prompts, 10, **kw)
    assert got == ref
    assert ge.spec_drafted > 0, "repetitive trace must actually draft"
    assert ge.spec_drafted == re_.spec_drafted


def test_engine_rejects_unservable_meshes(model):
    """Serving meshes are tensor-only: sequence/pipeline axes and tp
    degrees that break whole-head or vocab divisibility are refused at
    construction, not at first dispatch."""
    seq_mesh = create_mesh(
        MeshConfig(replica=1, fsdp=1, sequence=2, tensor=1),
        devices=jax.devices()[:2],
    )
    with pytest.raises(AssertionError, match="sequence"):
        ServingEngine(model, mesh=seq_mesh)
    with pytest.raises(AssertionError, match="divide heads"):
        ServingEngine(model, mesh=_mesh(8))  # n_head=4 < 8


# ---------------------------------------------------------------------------
# program cache: one compiled program per mesh geometry/placement
# ---------------------------------------------------------------------------


def test_program_cache_distinct_entries_per_mesh(model):
    """A tp=2 engine must never reuse a tp=1 compiled program: the cache
    key carries the mesh axis sizes AND device ids, so None / tp=2 /
    tp=4 / same-geometry-different-devices all get distinct entries,
    while an equal mesh (same shape, same devices) is a cache HIT."""
    pmax = pages_needed(CFG.block_size, 16)
    # slots=2/window=4 matches the geometry every other test in this
    # module compiles, so the tp2/tp4 lookups here are cache HITS — the
    # only fresh compile is the disjoint-devices replica mesh
    mk = lambda mesh: make_decode_window(  # noqa: E731
        model, slots=2, window=4, pmax=pmax, rope_len=CFG.block_size,
        mesh=mesh,
    )
    fn_none = mk(None)
    fn_tp2 = mk(_mesh(2))
    fn_tp4 = mk(_mesh(4))
    # same geometry, disjoint devices (two DP replicas' meshes)
    m_a = create_mesh(
        MeshConfig(replica=1, fsdp=1, sequence=1, tensor=2),
        devices=jax.devices()[:2],
    )
    m_b = create_mesh(
        MeshConfig(replica=1, fsdp=1, sequence=1, tensor=2),
        devices=jax.devices()[2:4],
    )
    fn_a, fn_b = mk(m_a), mk(m_b)
    fns = [fn_none, fn_tp2, fn_tp4, fn_b]
    assert len({id(f) for f in fns}) == 4, "programs must not be shared"
    assert fn_a is fn_tp2, "equal mesh (shape + devices) must cache-hit"
    # the cache holds one entry per mesh fingerprint at this geometry
    # (earlier tests in this module may already have populated them —
    # that reuse is exactly what the cache exists for)
    dw_fingerprints = {
        k[-1] for k in _PROGRAM_CACHE
        if k[0] == "decode_window" and k[2:4] == (2, 4) and k[1] == CFG
    }
    assert len(dw_fingerprints) >= 4
    assert _mesh_key(m_a) == _mesh_key(_mesh(2))
    assert _mesh_key(m_a) != _mesh_key(m_b)
    assert _mesh_key(None) is None


# ---------------------------------------------------------------------------
# shared-nothing DP cluster
# ---------------------------------------------------------------------------


def test_cluster_streams_are_replica_placement_invariant(model):
    """The same trace through 1, 2, and 3 replicas (and through a TPxDP
    cluster of tp=2 replicas) yields bit-identical per-request streams:
    a request's tokens are a function of the request alone, so admission
    placement is a latency decision, never a correctness one."""
    prompts = _prompts(6, base_len=5, stride=2)
    kw = dict(slots=2, window=4, cache_dtype=jnp.float32)
    eng = ServingEngine(model, **kw)
    rids = [eng.submit(p, 8, seed=i) for i, p in enumerate(prompts)]
    fin = eng.run()
    ref = [fin[r].tokens for r in rids]

    for replicas in (2, 3):
        cl = ServingCluster(model, replicas=replicas, **kw)
        crids = [cl.submit(p, 8, seed=i) for i, p in enumerate(prompts)]
        got = cl.run()
        assert [got[r].tokens for r in crids] == ref, replicas
        # least-loaded admission actually spread the trace
        assert all(len(e.finished) > 0 for e in cl.engines)

    meshes = serving_meshes(tp_size=2, dp_replicas=2)
    assert len(meshes) == 2
    assert [_mesh_key(m) is not None for m in meshes] == [True, True]
    assert _mesh_key(meshes[0]) != _mesh_key(meshes[1]), "disjoint devices"
    cl = ServingCluster(model, meshes=meshes, **kw)
    crids = [cl.submit(p, 8, seed=i) for i, p in enumerate(prompts)]
    got = cl.run()
    assert [got[r].tokens for r in crids] == ref


def test_cluster_least_loaded_admission_and_stats(model):
    """Admission routes to the smallest backlog (lowest index on ties)
    and the aggregated stats sum the replica counters."""
    cl = ServingCluster(
        model, replicas=2, slots=1, window=4, cache_dtype=jnp.float32
    )
    prompts = _prompts(4, base_len=4, stride=1)
    for i, p in enumerate(prompts):
        cl.submit(p, 6, seed=i)
    # round-robin under equal load: 0, 1, 0, 1
    assert [len(e.queue) for e in cl.engines] == [2, 2]
    finished = cl.run()
    assert len(finished) == 4
    st = cl.stats()
    assert st["dp_replicas"] == 2
    assert st["tokens_generated"] == 4 * 6
    assert st["tokens_generated"] == sum(
        s["tokens_generated"] for s in st["per_replica"]
    )
    assert len(st["per_replica"]) == 2


# ---------------------------------------------------------------------------
# the no-batch-allgather-in-page-gather rule
# ---------------------------------------------------------------------------


def test_page_gather_allgather_rule_on_fixtures():
    """Rule semantics on canned HLO (jax-free, like the other rule
    units): a collective-free sharded program passes; fault injections —
    a pool-payload all-gather, a slot-batch all-gather — fail; the tiny
    argmax-combiner all-gather ([S, tp] float) and integer block-table
    gathers stay legal."""
    from midgpt_tpu.analysis.hlo import MeshInfo
    from midgpt_tpu.analysis.rules import (
        NoPageGatherAllGather,
        StepAnalysis,
    )

    mesh = MeshInfo(axis_names=("tensor",), axis_sizes=(2,))
    payload = {(2, 8, 6, 64, 8), (8, 6, 64, 8), (4, 8, 6, 64, 8),
               (4, 6, 64, 64), (2, 4, 6, 4, 64), (4, 6, 4, 64)}
    rule = NoPageGatherAllGather(payload, slots=4)

    def analyze(hlo):
        return rule.check(StepAnalysis.from_text(hlo, mesh))

    clean = """HloModule m
ENTRY %main (p0: bf16[2,8,3,64,8]) -> bf16[4,96] {
  %ar = f32[4,96]{1,0} all-reduce(f32[4,96]{1,0} %x), replica_groups={{0,1}}
  %ag0 = f32[4,2]{1,0} all-gather(f32[4,1]{1,0} %m), dimensions={1}, replica_groups={{0,1}}
  %ag1 = s32[4,8]{1,0} all-gather(s32[4,4]{1,0} %bt), dimensions={1}, replica_groups={{0,1}}
}
"""
    assert analyze(clean) == []
    pool_gather = clean + (
        "  %bad = bf16[4,6,64,64]{3,2,1,0} all-gather("
        "bf16[4,3,64,64]{3,2,1,0} %ck), dimensions={1}, "
        "replica_groups={{0,1}}\n"
    )
    found = analyze(pool_gather)
    assert len(found) == 1 and "pool-payload" in found[0].message
    batch_gather = clean + (
        "  %bad = f32[4,5,512]{2,1,0} all-gather(f32[2,5,512]{2,1,0} %h), "
        "dimensions={0}, replica_groups={{0,1}}\n"
    )
    found = analyze(batch_gather)
    assert len(found) == 1 and "slot/batch-dim" in found[0].message


@pytest.mark.slow
def test_sharded_serving_audits_pass():
    """The LIVE gate on the tp=2 geometry, bf16 AND quant: all three
    sharded serving programs keep donation 3/3, stay host-sync-free,
    stream int8 (quant), and contain no pool/batch all-gather through
    the page gathers. The replica=2 variant additionally proves an
    unused replica axis rides replicated (the serving_logical_rules
    contract — with 'batch' mapped onto it, the partitioner injected
    slot all-gathers, which THIS rule caught when the mesh support was
    first compiled)."""
    from midgpt_tpu.analysis.harness import (
        audit_decode_window,
        audit_prefill_chunk,
        audit_verify_program,
    )
    from midgpt_tpu.config import get_config

    cfg = get_config("shakespeare_char")
    for mesh_shape in ({"tensor": 2}, {"tensor": 2, "replica": 2}):
        for fn, kw in (
            (audit_decode_window, dict(slots=2, window=2, page_size=8)),
            (audit_prefill_chunk, dict(chunk_len=32, page_size=8)),
            (audit_verify_program, dict(slots=2, spec_len=2, page_size=8)),
        ):
            for quant in (False, True):
                analysis, report = fn(
                    cfg, quant=quant, mesh_shape=mesh_shape, **kw
                )
                assert report.ok, (mesh_shape, quant, report.violations)
                assert any(
                    r.rule == "no-batch-allgather-in-page-gather"
                    for r in report.results
                )
                assert len(
                    {e.param_number for e in analysis.aliases}
                ) >= 3, "pool + logits donation must survive sharding"
