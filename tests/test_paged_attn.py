"""Pallas ragged paged-attention kernel (ops/paged_attn) + int8 KV pool
(serving.paged kv_quant): the exactness contracts that make both landable.

- The kernel is BITWISE the XLA gather path — not close, equal: the
  serving suite's greedy token-identity matrix is the landing gate, and
  ulp-level drift flips near-tied argmaxes on real checkpoints (the PR
  4/PR 5 lesson). Asserted at the op level (decode + verify, ragged
  lengths, GQA, f32 comparison of the raw logits) and end-to-end
  (engine streams across cache x chunking x speculation x eviction).
- The int8 KV grid is bitwise-dequantizable (po2 page scales — the
  quant.py contract applied to the KV stream) and page scales are a
  pure function of the token stream, so int8-KV streams are INVARIANT
  to window size, chunk size, speculation, eviction, and the kernel
  backend — asserted pairwise across the feature matrix.
- Page scales travel atomically with page payloads through
  copy-on-write duplication and cold retirement (a stale scale on an
  aliased page is the silent-corruption case — deterministic, bit-
  stable, and wrong; the prefix-cache-hit identity test pins it).

Kernels execute through the Pallas CPU interpreter on this tier (the
same bodies the TPU runs — ops/paged_attn resolves ``interpret`` off
the backend)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.config import ModelConfig
from midgpt_tpu.models.gpt import GPT, decode_step_paged, verify_tokens_paged
from midgpt_tpu.quant import (
    kv_scale_from_absmax,
    po2_ceil_exact,
    quantize_kv_rows,
    round_kv_rows_to_grid,
)
from midgpt_tpu.sampling import generate
from midgpt_tpu.serving import PagedKVPool, ServingEngine, generate_served
from midgpt_tpu.serving.paged import kv_row_scales

CFG = ModelConfig(
    block_size=64, vocab_size=96, n_layer=2, n_head=4, n_embd=32,
    dropout=0.0, attn_impl="naive", remat="none",
)
# GQA shape: 4 query heads sharing 2 KV heads — the grouped walk
GQA_CFG = dataclasses.replace(CFG, n_kv_head=2)


def _model(cfg=CFG):
    return GPT.init(jax.random.PRNGKey(0), cfg)


def _prompts(n, base_len=5, stride=3):
    return [
        np.asarray(
            jax.random.randint(
                jax.random.PRNGKey(100 + i), (base_len + stride * i,), 0,
                CFG.vocab_size,
            )
        )
        for i in range(n)
    ]


# ---------------------------------------------------------------------------
# the po2 KV grid (quant.py): exactness units
# ---------------------------------------------------------------------------


def test_po2_ceil_exact_is_po2_and_tight():
    y = jnp.asarray(
        [1.0, 127.0, 0.5, 3.7, 2.0**-10, 126.99, 2.0**20], jnp.float32
    )
    s = np.asarray(po2_ceil_exact(y))
    assert np.all(np.log2(s) == np.round(np.log2(s))), "not powers of two"
    assert np.all(s >= np.asarray(y) * (1 - 1e-7))
    assert np.all(s < 2 * np.asarray(y) + 1e-30), "not the SMALLEST po2"
    # the boundary case log2-based derivations get wrong: exact po2 in
    assert float(po2_ceil_exact(jnp.float32(0.25))) == 0.25


def test_po2_ceil_exact_full_exponent_range():
    """Bit-exact over EVERY f32 exponent, not just the friendly middle
    band: jnp.exp2 is a polynomial approximation that is off by ulps at
    integer arguments outside roughly [-14, 28] (and flushes to 0 below
    ~-125 on XLA CPU), which is how an earlier exp2-based derivation
    produced non-po2 'po2' scales for any page with birth absmax below
    ~8e-3 — real checkpoints hit that immediately. po2_ceil_exact must
    land every exact power of two on itself and every other input on
    the next po2 up, across the whole normal + subnormal range."""
    import math

    # every exact po2 maps to itself
    for e in range(-149, 128):
        p = math.ldexp(1.0, e)
        assert float(po2_ceil_exact(jnp.float32(p))) == p, e
    # off-po2 inputs round UP to the adjacent po2, full exponent sweep
    for e in range(-148, 127):
        y = np.float32(1.5 * math.ldexp(1.0, e))
        if y <= 0:  # subnormal product underflow on the host — skip
            continue
        m, ee = np.frexp(y)
        want = math.ldexp(1.0, int(ee - 1) if m == 0.5 else int(ee))
        assert float(po2_ceil_exact(jnp.asarray(y))) == want, e
    # the review's repro: tiny absmax must still give a true po2 scale
    s = float(kv_scale_from_absmax(jnp.float32(1e-7)))
    assert s > 0 and math.log2(s) == int(math.log2(s)), s


def test_kv_scale_rounding_stable():
    """derive(round_to_grid(row, derive(row))) == derive(row) — the
    property that lets the bulk page writes re-derive scales from the
    already-rounded rows they receive (serving.paged docstring)."""
    for i in range(64):
        # magnitudes from 1e-36 (the KV_SCALE_MIN clamp band) to 1e20 —
        # stability and the bitwise grid must hold at EVERY magnitude,
        # not just the exp2-friendly middle (see
        # test_po2_ceil_exact_full_exponent_range)
        row = jax.random.normal(
            jax.random.PRNGKey(i), (64,), jnp.float32
        ) * (10.0 ** (i % 15 * 4 - 36))
        s0 = kv_scale_from_absmax(jnp.max(jnp.abs(row)))
        rounded = round_kv_rows_to_grid(row[None], s0[None])[0]
        s1 = kv_scale_from_absmax(jnp.max(jnp.abs(rounded)))
        assert float(s0) == float(s1), (i, float(s0), float(s1))
    # all-zero rows take the inert scale 1.0
    assert float(kv_scale_from_absmax(jnp.float32(0.0))) == 1.0


def test_page_level_bitwise_dequant_contract():
    """THE int8-KV exactness statement, at page granularity: attending
    int8 codes via ``f32(q) * scale`` is bitwise identical to attending
    a bf16 pool that holds the dequantized values — and those values
    round-trip bf16 exactly (|code| <= 127 times a po2 scale). An int8
    pool is a bf16 pool whose values lie on the grid; nothing more."""
    rows = jax.random.normal(
        jax.random.PRNGKey(3), (8, 16, 64), jnp.bfloat16
    )  # [Hkv, PS, C] one page of K rows
    scales = kv_scale_from_absmax(
        jnp.max(jnp.abs(rows.astype(jnp.float32)), axis=(1, 2))
    )  # [Hkv] — one scale per (page, KV-head) plane
    codes = quantize_kv_rows(rows, scales[:, None])
    assert codes.dtype == jnp.int8
    # dequantize-then-attend reference: grid values in a bf16 pool
    grid_bf16 = (
        codes.astype(jnp.float32) * scales[:, None, None]
    ).astype(jnp.bfloat16)
    a = grid_bf16.astype(jnp.float32)  # what the bf16 pool path streams
    b = codes.astype(jnp.float32) * scales[:, None, None]  # in-kernel
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the rounded rows every in-dispatch reader saw ARE those values
    in_dispatch = round_kv_rows_to_grid(rows, scales[:, None])
    np.testing.assert_array_equal(
        np.asarray(in_dispatch.astype(jnp.float32)), np.asarray(a)
    )


def test_kv_row_scales_page_birth_vs_pool_lookup():
    """Rows quantize under their page's BIRTH scale: in-batch birth rows
    derive it, rows on pages born earlier read the recorded plane."""
    ps, pmax, npool, hkv, c, t = 4, 4, 8, 2, 8, 6
    rows = jax.random.normal(jax.random.PRNGKey(0), (1, hkv, t, c))
    pool_scale = jnp.full((npool, hkv), 0.125, jnp.float32)
    bt = jnp.asarray([[3, 5, 1, 7]], jnp.int32)
    base = jnp.asarray([2], jnp.int32)  # rows at positions 2..7
    sk, sv = kv_row_scales(rows, rows, base, bt, pool_scale, pool_scale, ps)
    # positions 2,3 sit on page 0 (born pre-batch): the recorded 0.125
    np.testing.assert_array_equal(np.asarray(sk[0, :, :2]), 0.125)
    # position 4 = 1*ps births page 1 in-batch: derived from row j=2
    derived = kv_scale_from_absmax(
        jnp.max(jnp.abs(rows[0, :, 2, :].astype(jnp.float32)), axis=-1)
    )
    np.testing.assert_array_equal(
        np.asarray(sk[0, :, 2]), np.asarray(derived)
    )
    # positions 5..7 share page 1's birth scale
    for j in (3, 4, 5):
        np.testing.assert_array_equal(
            np.asarray(sk[0, :, j]), np.asarray(derived)
        )


# ---------------------------------------------------------------------------
# kernel vs XLA path: bitwise at the op level
# ---------------------------------------------------------------------------


def _decode_setup(cfg, kv_quant=None, seed=1):
    model = GPT.init(jax.random.PRNGKey(0), cfg)
    s, ps, pmax = 4, 8, 8
    npool = 24
    pool = PagedKVPool.init(cfg, npool, ps, jnp.float32, kv_quant=kv_quant)
    ks = jax.random.split(jax.random.PRNGKey(seed), 6)
    if kv_quant:
        codes = jax.random.randint(
            ks[0], pool.k.shape, -127, 128, jnp.int32
        ).astype(jnp.int8)
        pool = dataclasses.replace(
            pool, k=codes,
            v=jax.random.randint(
                ks[1], pool.v.shape, -127, 128, jnp.int32
            ).astype(jnp.int8),
            scale_k=jnp.exp2(jax.random.randint(
                ks[2], pool.scale_k.shape, -8, -2
            ).astype(jnp.float32)),
            scale_v=jnp.exp2(jax.random.randint(
                ks[3], pool.scale_v.shape, -8, -2
            ).astype(jnp.float32)),
        )
    else:
        pool = dataclasses.replace(
            pool,
            k=jax.random.normal(ks[0], pool.k.shape, jnp.float32),
            v=jax.random.normal(ks[1], pool.v.shape, jnp.float32),
        )
    bt = jax.random.randint(ks[4], (s, pmax), 0, npool).astype(jnp.int32)
    # ragged lengths: empty, partial page, page-aligned, full table
    pooled_len = jnp.asarray([0, 13, 32, pmax * ps], jnp.int32)
    tokens = jax.random.randint(ks[5], (s,), 0, cfg.vocab_size)
    return model, pool, bt, pooled_len, tokens.astype(jnp.int32)


@pytest.mark.parametrize("cfg", [CFG, GQA_CFG], ids=["mha", "gqa"])
@pytest.mark.parametrize("kv_quant", [None, "int8"], ids=["f32", "kv8"])
def test_decode_kernel_bitwise_vs_xla(cfg, kv_quant):
    """decode_step_paged with paged_kernel='pallas' returns BITWISE the
    XLA gather path's logits — ragged per-slot lengths (incl. an empty
    slot and a partial page), both pool precisions, MHA and GQA."""
    model, pool, bt, pooled_len, tokens = _decode_setup(cfg, kv_quant)
    l, s = cfg.n_layer, tokens.shape[0]
    rr = 4
    rk = jnp.zeros((l, s, cfg.kv_heads, rr, cfg.head_dim), pool.row_dtype)
    rv = jnp.zeros_like(rk)
    pos = pooled_len + 1  # one recent row already written
    rk = rk.at[:, :, :, 0, :].set(0.25)
    rv = rv.at[:, :, :, 0, :].set(-0.5)
    r = jnp.asarray(1, jnp.int32)
    outs = {}
    for kern in ("xla", "pallas"):
        logits, rko, rvo = jax.jit(
            lambda tk, pk, pv, b_, rk_, rv_, pl_, sk, sv: decode_step_paged(
                model, tk, pos, pk, pv, b_, rk_, rv_, r, pl_,
                cfg.block_size, pool_sk=sk, pool_sv=sv, paged_kernel=kern,
            )
        )(tokens, pool.k, pool.v, bt, rk, rv, pooled_len,
          pool.scale_k, pool.scale_v)
        outs[kern] = (
            np.asarray(logits, np.float32), np.asarray(rko, np.float32),
        )
    np.testing.assert_array_equal(outs["xla"][0], outs["pallas"][0])
    np.testing.assert_array_equal(outs["xla"][1], outs["pallas"][1])


@pytest.mark.parametrize("kv_quant", [None, "int8"], ids=["f32", "kv8"])
def test_verify_kernel_bitwise_vs_xla(kv_quant):
    """verify_tokens_paged: all candidate rows, joint pool+self softmax —
    kernel bitwise against the XLA path, and the returned K/V rows (what
    the watermark flush writes) equal too."""
    cfg = GQA_CFG
    model, pool, bt, pooled_len, _ = _decode_setup(cfg, kv_quant)
    s, t = 4, 3
    cand = jax.random.randint(
        jax.random.PRNGKey(9), (s, t), 0, cfg.vocab_size
    ).astype(jnp.int32)
    outs = {}
    for kern in ("xla", "pallas"):
        logits, ks, vs = jax.jit(
            lambda c_, pk, pv, b_, pl_, sk, sv: verify_tokens_paged(
                model, c_, pl_, pk, pv, b_, cfg.block_size,
                pool_sk=sk, pool_sv=sv, paged_kernel=kern,
            )
        )(cand, pool.k, pool.v, bt, pooled_len, pool.scale_k, pool.scale_v)
        outs[kern] = (
            np.asarray(logits, np.float32), np.asarray(ks, np.float32),
            np.asarray(vs, np.float32),
        )
    for a, b in zip(outs["xla"], outs["pallas"]):
        np.testing.assert_array_equal(a, b)


# f32-nb2 (2 bands) proves the multi-band fold in tier-1; the deeper
# band counts and the int8-pool multiband cells ride the slow tier to
# keep tier-1 inside the 870 s verify budget (the serving-longctx CI
# job runs the banded legs fast + slow, and serving-choreo runs this
# file unfiltered). int8 at NB=1 stays fast via the kv8 cells of
# test_decode_kernel_bitwise_vs_xla above.
@pytest.mark.parametrize(
    "kv_quant,band_pages_",
    [
        pytest.param(None, 4, id="f32-nb2"),
        pytest.param(None, 2, id="f32-nb4", marks=pytest.mark.slow),
        pytest.param(None, 1, id="f32-nb8", marks=pytest.mark.slow),
        pytest.param("int8", 4, id="kv8-nb2", marks=pytest.mark.slow),
        pytest.param("int8", 2, id="kv8-nb4", marks=pytest.mark.slow),
        pytest.param("int8", 1, id="kv8-nb8", marks=pytest.mark.slow),
    ],
)
def test_banded_kernel_bitwise_vs_banded_xla(kv_quant, band_pages_,
                                             monkeypatch):
    """Genuinely MULTI-banded streaming (ISSUE 20): force the band plan
    below the whole table (the auto-sizer picks one band at this tiny
    geometry) and re-pin kernel == XLA to the f32 bit for decode AND
    verify. Both sides slice per band and fold partials through
    banded_fold, so this exercises the whole banded contract: per-band
    masking, per-band dequant slices, and the pinned ascending fold —
    at 8, 4, and 2 pages per band against the pmax=8 table."""
    import midgpt_tpu.ops.paged_attn as pa

    monkeypatch.setattr(pa, "_FORCE_BAND_PAGES", band_pages_)
    cfg = GQA_CFG
    model, pool, bt, pooled_len, tokens = _decode_setup(cfg, kv_quant)
    l, s = cfg.n_layer, tokens.shape[0]
    rk = jnp.zeros((l, s, cfg.kv_heads, 4, cfg.head_dim), pool.row_dtype)
    rk = rk.at[:, :, :, 0, :].set(0.25)
    rv = jnp.zeros_like(rk).at[:, :, :, 0, :].set(-0.5)
    pos = pooled_len + 1
    r = jnp.asarray(1, jnp.int32)
    outs = {}
    for kern in ("xla", "pallas"):
        logits, _, _ = jax.jit(
            lambda tk, pk, pv, b_, rk_, rv_, pl_, sk, sv: decode_step_paged(
                model, tk, pos, pk, pv, b_, rk_, rv_, r, pl_,
                cfg.block_size, pool_sk=sk, pool_sv=sv, paged_kernel=kern,
            )
        )(tokens, pool.k, pool.v, bt, rk, rv, pooled_len,
          pool.scale_k, pool.scale_v)
        outs[kern] = np.asarray(logits, np.float32)
    np.testing.assert_array_equal(outs["xla"], outs["pallas"])
    cand = jax.random.randint(
        jax.random.PRNGKey(9), (s, 3), 0, cfg.vocab_size
    ).astype(jnp.int32)
    vouts = {}
    for kern in ("xla", "pallas"):
        logits, _, _ = jax.jit(
            lambda c_, pk, pv, b_, pl_, sk, sv: verify_tokens_paged(
                model, c_, pl_, pk, pv, b_, cfg.block_size,
                pool_sk=sk, pool_sv=sv, paged_kernel=kern,
            )
        )(cand, pool.k, pool.v, bt, pooled_len, pool.scale_k, pool.scale_v)
        vouts[kern] = np.asarray(logits, np.float32)
    np.testing.assert_array_equal(vouts["xla"], vouts["pallas"])


# ---------------------------------------------------------------------------
# engine token identity: the matrix with the kernel on
# ---------------------------------------------------------------------------


def _exact(model, prompt, n_new):
    return np.asarray(
        generate(
            model, jnp.asarray(prompt)[None], n_new,
            key=jax.random.PRNGKey(9), temperature=0.0,
            cache_dtype=jnp.float32,
        )
    )[0]


@pytest.fixture(scope="module")
def kernel_case():
    model = _model()
    prompts = _prompts(3)
    lens = [9, 12, 7]
    refs = [_exact(model, p, n) for p, n in zip(prompts, lens)]
    return model, prompts, lens, refs


def _run_engine(model, prompts, lens, **kw):
    eng = ServingEngine(
        model, slots=2, page_size=8, window=4, temperature=0.0,
        cache_dtype=jnp.float32, **kw,
    )
    rids = [eng.submit(p, n) for p, n in zip(prompts, lens)]
    fin = eng.run()
    eng.alloc.check()
    if eng.index is not None:
        eng.index.check(eng.alloc)
    assert eng.alloc.held_pages == 0
    return [fin[r].tokens for r in rids]


def test_engine_kernel_token_identity_matrix(kernel_case):
    """Acceptance: greedy streams with paged_kernel='pallas' are token-
    identical to the XLA path AND the exact fixed-batch sampler across
    prefix-cache x chunked-prefill x speculation (mid-run admission:
    more requests than slots)."""
    model, prompts, lens, refs = kernel_case
    base = [list(map(int, r)) for r in refs]
    for variant in [
        dict(prefix_cache=False),
        dict(prefix_cache=True, prefill_chunk=5),
        dict(prefix_cache=True, speculate=4),
    ]:
        toks = _run_engine(
            model, prompts, lens, paged_kernel="pallas", **variant
        )
        assert toks == base, f"pallas variant {variant} diverged"


def test_engine_kernel_under_eviction(kernel_case):
    """Kernel path x page pressure: eviction/re-admission keeps streams
    identical to the exact sampler (the ragged walk sees rebuilt block
    tables and re-prefilled pages)."""
    model = _model()
    prompts = _prompts(4, base_len=6, stride=0)
    refs = [_exact(model, p, 16) for p in prompts]
    eng = ServingEngine(
        model, slots=2, page_size=8, num_pages=5, window=4,
        temperature=0.0, cache_dtype=jnp.float32, prefix_cache=True,
        paged_kernel="pallas",
    )
    rids = [eng.submit(p, 16) for p in prompts]
    fin = eng.run()
    assert eng.evictions > 0, "trace was sized to force eviction"
    for i, r in enumerate(rids):
        np.testing.assert_array_equal(
            np.asarray(fin[r].tokens), refs[i], err_msg=f"request {i}"
        )


# ---------------------------------------------------------------------------
# int8 KV pool: stream invariance + scale atomicity
# ---------------------------------------------------------------------------


def test_kv_quant_stream_invariance_matrix(kernel_case):
    """Acceptance: int8-KV greedy streams are IDENTICAL across the
    feature matrix — cache on/off x chunked/monolithic x speculation x
    window size x kernel backend. (The streams legitimately differ from
    the full-precision pool — KV quantization is lossy — but they may
    not depend on any scheduling knob: page scales are a pure function
    of the token stream.)"""
    model, prompts, lens, _ = kernel_case
    base = None
    for variant in [
        dict(prefix_cache=False, paged_kernel="xla"),
        dict(prefix_cache=True, prefill_chunk=5, paged_kernel="xla"),
        dict(prefix_cache=False, speculate=4, paged_kernel="xla"),
        dict(prefix_cache=True, paged_kernel="pallas"),
        dict(prefix_cache=True, speculate=4, paged_kernel="pallas"),
    ]:
        toks = _run_engine(
            model, prompts, lens, kv_quant="int8", **variant
        )
        if base is None:
            base = toks
        else:
            assert toks == base, f"kv-quant variant {variant} diverged"


def test_kv_quant_window_size_invariance(kernel_case):
    """K=1 quantizes at every window boundary, K=4 once per window —
    in-window grid rounding makes the streams indistinguishable."""
    model, prompts, lens, _ = kernel_case
    k1 = [
        t.tolist() for t in generate_served(
            model, prompts, max(lens), window=1, page_size=8,
            cache_dtype=jnp.float32, kv_quant="int8", paged_kernel="xla",
        )
    ]
    k4 = [
        t.tolist() for t in generate_served(
            model, prompts, max(lens), window=4, page_size=8,
            cache_dtype=jnp.float32, kv_quant="int8", paged_kernel="xla",
        )
    ]
    assert k1 == k4


def test_kv_quant_prefix_cache_hit_identity():
    """Satellite regression (the silent-corruption case): a prefix-cache
    hit under kv-quant aliases int8 pages INTO a new block table — the
    dequant is only right if the per-page scales arrived with the
    payload. Cold-hit, COW partial-page copy, and decode-written pages
    are all exercised; streams must equal the cache-off run exactly."""
    model = _model()
    prompt = _prompts(1, base_len=24)[0]
    tails = _prompts(2, base_len=3, stride=2)
    # the repeat of the bare prompt is the COW trigger: its match is
    # capped at p-1, leaving a partial-page tail that aliases the
    # already-indexed full page via copy_page (payload + scale)
    reqs = [prompt] + [np.concatenate([prompt, t]) for t in tails] + [prompt]
    lens = [6, 8, 7, 5]

    def run(prefix_cache):
        eng = ServingEngine(
            model, slots=1, page_size=8, window=4, temperature=0.0,
            cache_dtype=jnp.float32, prefix_cache=prefix_cache,
            kv_quant="int8",
        )
        rids = []
        for p, n in zip(reqs, lens):
            rids.append(eng.submit(p, n))
        fin = eng.run()
        return [fin[r].tokens for r in rids], eng

    cold, _ = run(False)
    hit, eng = run(True)
    assert hit == cold, "aliased page served a stale scale"
    # the hits really happened (this test must exercise aliasing): the
    # second/third requests share prompt pages + the COW partial page
    assert eng.prompt_tokens_cached > 0
    assert eng.copy_dispatches >= 1


def test_kv_quant_eviction_cold_retire_carries_scales():
    """Evicted requests' pages retire COLD with their scales; re-
    admission re-hits them and the continuation is bit-identical to the
    never-evicted run."""
    model = _model()
    prompts = _prompts(4, base_len=6, stride=0)
    plenty = [
        _run_engine(
            model, prompts, [16] * 4, kv_quant="int8", prefix_cache=True
        )
    ][0]
    eng = ServingEngine(
        model, slots=2, page_size=8, num_pages=5, window=4,
        temperature=0.0, cache_dtype=jnp.float32, prefix_cache=True,
        kv_quant="int8",
    )
    rids = [eng.submit(p, 16) for p in prompts]
    fin = eng.run()
    assert eng.evictions > 0
    assert [fin[r].tokens for r in rids] == plenty


def test_write_prompt_pages_quantized_roundtrip():
    """The page-aligned bulk write path: rows land as int8 codes + birth
    scales, and reading them back dequantizes to exactly the grid
    rounding of the written rows (error <= scale/2 vs the originals)."""
    from midgpt_tpu.serving.paged import write_prompt_pages

    cfg = CFG
    ps, n = 8, 2
    pool = PagedKVPool.init(cfg, 6, ps, kv_quant="int8")
    ks = jax.random.normal(
        jax.random.PRNGKey(1),
        (cfg.n_layer, cfg.kv_heads, n * ps, cfg.head_dim), jnp.float32,
    )
    vs = jax.random.normal(jax.random.PRNGKey(2), ks.shape, jnp.float32)
    rows = jnp.asarray([4, 1], jnp.int32)
    pool = write_prompt_pages(pool, ks, vs, rows)
    for li in range(cfg.n_layer):
        for pi, page in enumerate([4, 1]):
            got = (
                pool.k[li, page].astype(jnp.float32)
                * pool.scale_k[li, page][:, None, None]
            )  # [Hkv, C, PS]
            page_rows = ks[li, :, pi * ps : (pi + 1) * ps, :]  # [Hkv,PS,C]
            # dequant equals the canonical grid rounding of the written
            # rows EXACTLY (incl. the +-127 clip for rows past the birth
            # row's headroom)
            s_rows = jnp.broadcast_to(
                pool.scale_k[li, page][:, None], (cfg.kv_heads, ps)
            )
            want_grid = round_kv_rows_to_grid(page_rows, s_rows)
            np.testing.assert_array_equal(
                np.asarray(jnp.transpose(got, (0, 2, 1))),
                np.asarray(want_grid.astype(jnp.float32)),
            )
            # the BIRTH row (the scale's source) is never clipped and
            # lands within scale/2 of the original
            scale = pool.scale_k[li, page]  # [Hkv]
            birth_err = jnp.abs(got[:, :, 0] - page_rows[:, 0, :])
            assert float(
                jnp.max(birth_err / scale[:, None])
            ) <= 0.5 + 1e-6


# ---------------------------------------------------------------------------
# dispatch plumbing
# ---------------------------------------------------------------------------


def test_paged_kernel_auto_resolves_to_xla_on_cpu():
    eng = ServingEngine(_model(), slots=1, page_size=8, window=2)
    assert eng.paged_kernel == "xla"  # no TPU backend in this suite
    with pytest.raises(AssertionError):
        ServingEngine(_model(), slots=1, page_size=8, paged_kernel="mosaic")


def test_kernel_supported_gates_on_vmem():
    """Band-aware gate (ISSUE 20): the working set is one band's
    double-buffered K/V stream (+ its f32 dequant views) plus the
    full-context f32 score/prob rows — O(band), not O(Pmax) — so the
    contexts the whole-pool assembly used to reject now fit, while the
    residency that CANNOT band (the flat-softmax score rows, scaled by
    the REAL group count and spec length) still rejects honestly."""
    from midgpt_tpu.ops.paged_attn import supported

    assert supported(pmax=64, page_size=16, hkv=12, c=64, itemsize=2,
                     groups=1)
    # pre-banding this overflowed (~600 MB whole-pool assembly); the
    # banded stream makes it a ~2.5 MB working set
    assert supported(pmax=4096, page_size=16, hkv=12, c=64,
                     itemsize=2, groups=1)
    # int8 pool: the per-band f32 dequant views (4 counted bytes per
    # 1-byte element) and the [Pmax] f32 scale planes still ride the
    # arithmetic — per-band now, so this fits too (PR 9's accounting
    # survives banding, applied to the band)
    assert supported(pmax=256, page_size=16, hkv=8, c=64,
                     itemsize=1, groups=8)
    # what banding CANNOT shrink: the flat-softmax f32 score + prob
    # rows are [G, T, W]-resident. Wide GQA groups scale them past the
    # budget — the gate must count the REAL group size, not a cap
    assert supported(pmax=256, page_size=16, hkv=2, c=64, itemsize=2,
                     groups=128)
    assert not supported(pmax=4096, page_size=16, hkv=2, c=64,
                         itemsize=2, groups=128)
    # ... and speculation multiplies the rows by T = speculate + 1: a
    # geometry that fits for decode can overflow for verify
    assert supported(pmax=4096, page_size=16, hkv=2, c=64, itemsize=2,
                     groups=12)
    assert not supported(pmax=4096, page_size=16, hkv=2, c=64,
                         itemsize=2, groups=12, spec_t=5)


def test_kernel_gate_accepts_100k_token_pmax():
    """Long-context decode (ISSUE 20): at a 100k-token context the
    block table spans ``pages_needed(100_000, 16) = 6250`` pages. The
    whole-pool assembly was ~0.9 GB (the old gate's rejection); the
    banded working set is band-stream + score rows, and ``supported()``
    now returns True for BOTH pool dtypes at a 12-wide GQA group. The
    byte arithmetic is pinned exactly — band auto-sizing included —
    so a regression in the plan (band too big, a dropped dequant view,
    lost scale planes) moves a literal."""
    from midgpt_tpu.ops.paged_attn import (
        BAND_VMEM_BUDGET,
        DMA_DEPTH,
        VMEM_BUDGET,
        band_pages,
        supported,
        vmem_bytes,
    )
    from midgpt_tpu.serving.paged import pages_needed

    pmax = pages_needed(100_000, 16)
    assert pmax == 6250
    w = pmax * 16  # 100_000 resident positions
    # band plan, bf16: largest divisor of 6250 whose K+V stream
    # buffers (x DMA_DEPTH) + f32 dequant views fit the band budget
    assert DMA_DEPTH == 2
    assert band_pages(pmax, 16, 64, 2) == 125  # 50 bands of 2000 pos
    band_bf16 = 2 * DMA_DEPTH * 64 * (125 * 16) * 2 \
        + 2 * 64 * (125 * 16) * 4
    assert band_bf16 == 2_048_000 <= BAND_VMEM_BUDGET
    # the residency banding cannot shrink: [G, T, W] f32 score + prob
    # rows, G=12 query heads per KV head, decode T=1
    scores = 2 * 12 * 1 * w * 4
    assert vmem_bytes(pmax, 16, 12, 64, 2, groups=12) \
        == band_bf16 + scores == 11_648_000 <= VMEM_BUDGET
    assert supported(pmax, 16, 12, 64, 2, groups=12)
    # int8 pool: thinner stream, same dequant views, plus the [Pmax]
    # f32 scale planes (K and V)
    assert band_pages(pmax, 16, 64, 1) == 125
    band_int8 = 2 * DMA_DEPTH * 64 * (125 * 16) * 1 \
        + 2 * 64 * (125 * 16) * 4
    assert vmem_bytes(pmax, 16, 12, 64, 1, groups=12) \
        == band_int8 + scores + 2 * pmax * 4 == 11_186_000
    assert supported(pmax, 16, 12, 64, 1, groups=12)
    # hkv no longer enters: the grid runs over (slot x KV head), so
    # per-program residency is head-count-free
    for hkv in (12, 6, 3, 1):
        assert vmem_bytes(pmax, 16, hkv, 64, 2, groups=1) == 2_848_000
        assert vmem_bytes(pmax, 16, hkv, 64, 1, groups=1) == 2_386_000
    # verify still gated: speculation multiplies the score rows by T
    assert not supported(pmax, 16, 12, 64, 2, groups=12, spec_t=2)
    # adversarial geometry overflowing even a ONE-page band (C so wide
    # the smallest stream buffer exceeds the band budget): band_pages
    # finds no plan and the gate reports the honest whole-table cost
    assert band_pages(pmax, 16, 16384, 2) is None
    assert not supported(pmax, 16, 1, 16384, 2)
    # pathologically-factored Pmax: a prime page count's only fitting
    # divisor is 1, which needs > MAX_BANDS bands — no plan, honest
    # whole-table fallback, rejected
    assert band_pages(6247, 16, 64, 2) is None
    assert not supported(6247, 16, 12, 64, 2, groups=12)


def test_auto_kernel_selects_pallas_at_long_context(monkeypatch):
    """``auto`` consults the band-aware gate with the LONG-context
    Pmax: with the backend forced to TPU, a 100k-block model now
    resolves to the Pallas kernel (the banded working set fits) —
    while a block size whose prime page count defeats the band plan
    still falls back to XLA honestly. Resolution gates on geometry,
    not platform alone."""
    import midgpt_tpu.utils.platform as platform

    monkeypatch.setattr(platform, "is_tpu_backend", lambda: True)
    long_cfg = dataclasses.replace(CFG, block_size=100_000)
    eng = ServingEngine(
        _model(long_cfg), slots=1, page_size=16, window=2,
        num_pages=8, paged_kernel="auto",
    )
    assert eng.paged_kernel == "pallas"
    eng_short = ServingEngine(
        _model(), slots=1, page_size=16, window=2, paged_kernel="auto"
    )
    assert eng_short.paged_kernel == "pallas"
    # 99_952 tokens -> 6247 pages (prime): no band plan fits MAX_BANDS,
    # the gate reports the whole-table cost, auto falls back
    prime_cfg = dataclasses.replace(CFG, block_size=99_952)
    eng_prime = ServingEngine(
        _model(prime_cfg), slots=1, page_size=16, window=2,
        num_pages=8, paged_kernel="auto",
    )
    assert eng_prime.paged_kernel == "xla"


def test_engine_rejects_unknown_kv_quant():
    with pytest.raises(AssertionError):
        ServingEngine(_model(), slots=1, page_size=8, kv_quant="int4")


# ---------------------------------------------------------------------------
# slow tier: sharded kernel + kv-quant
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_tp2_kernel_and_kv_quant_identity():
    """tp=2 sharded serving with the Pallas kernel (shard_map-wrapped,
    per-shard ragged walk over Hkv/tp heads) and the int8 pool (scale
    planes sharded with their heads): token-identical to the single-chip
    engine, both precisions."""
    from midgpt_tpu.serving import serving_meshes

    model = _model()
    prompts = _prompts(3)
    lens = [10, 10, 10]
    mesh = serving_meshes(tp_size=2)[0]
    base = _run_engine(model, prompts, lens, paged_kernel="xla")
    tp_pal = _run_engine(
        model, prompts, lens, mesh=mesh, paged_kernel="pallas"
    )
    assert tp_pal == base
    base_q = _run_engine(model, prompts, lens, kv_quant="int8")
    tp_q = _run_engine(
        model, prompts, lens, mesh=mesh, kv_quant="int8",
        paged_kernel="pallas",
    )
    assert tp_q == base_q


@pytest.mark.slow
def test_tp4_kernel_kv_quant_spec_identity():
    """tp=4 x kernel x int8 KV x speculation — the deep end of the
    acceptance matrix in one rung."""
    from midgpt_tpu.serving import serving_meshes

    model = _model()
    prompts = _prompts(3)
    lens = [10, 10, 10]
    mesh = serving_meshes(tp_size=4)[0]
    base_q = _run_engine(
        model, prompts, lens, kv_quant="int8", speculate=4
    )
    tp_q = _run_engine(
        model, prompts, lens, mesh=mesh, kv_quant="int8",
        paged_kernel="pallas", speculate=4,
    )
    assert tp_q == base_q
