"""Serving telemetry (midgpt_tpu.serving.telemetry): the metrics
registry (counters/gauges/fixed-bucket histograms, registry-backed
engine counter attributes), the pinned ``stats()`` key contract at
engine AND cluster level, per-request lifecycle tracing (event taxonomy,
derived queue-delay/TTFT/TBT/eviction-stall metrics under a fake clock),
the flight recorder (bounded rings, JSON dump), Chrome trace-event
export, and the two hard gates: greedy streams BITWISE identical with
tracing on vs off across the feature matrix (tracing selects the very
same cached program objects — prove_telemetry_inert), and replayed runs
producing identical event sequences with wall-clock excluded."""

import itertools
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.config import ModelConfig
from midgpt_tpu.models.gpt import GPT
from midgpt_tpu.serving import (
    CLUSTER_STATS_KEYS,
    ENGINE_STATS_KEYS,
    EngineTelemetry,
    MetricsRegistry,
    FaultEvent,
    FaultPlan,
    ServingCluster,
    ServingEngine,
    chrome_trace,
)
from midgpt_tpu.serving.telemetry import (
    EVENT_KINDS,
    Histogram,
    percentile,
)

CFG = ModelConfig(
    block_size=64, vocab_size=96, n_layer=2, n_head=4, n_embd=32,
    dropout=0.0, attn_impl="naive", remat="none",
)


@pytest.fixture(scope="module")
def model():
    return GPT.init(jax.random.PRNGKey(0), CFG)


def _prompts(n, base_len=5, stride=3):
    return [
        np.asarray(
            jax.random.randint(
                jax.random.PRNGKey(100 + i), (base_len + stride * i,), 0,
                CFG.vocab_size,
            )
        )
        for i in range(n)
    ]


_KW = dict(
    slots=2, page_size=8, window=4, temperature=0.0,
    cache_dtype=jnp.float32,
)


def _run(model, telemetry=None, n=3, n_new=8, clock=None, **kw):
    merged = dict(_KW, **kw)
    if clock is not None:
        merged["clock"] = clock
    eng = ServingEngine(model, telemetry=telemetry, **merged)
    rids = [eng.submit(p, n_new, seed=i) for i, p in enumerate(_prompts(n))]
    fin = eng.run()
    return eng, [list(map(int, fin[r].tokens)) for r in rids]


# ---------------------------------------------------------------------------
# Metrics registry units
# ---------------------------------------------------------------------------


def test_metrics_registry_units():
    reg = MetricsRegistry()
    c = reg.counter("hits")
    c.inc()
    c.inc(3)
    assert reg.counter("hits") is c and c.value == 4
    reg.gauge("depth").set(7.0)
    reg.gauge("live", fn=lambda: 42.0)
    labels = {"a": 1}
    reg.attach_labels("reasons", labels)
    labels["b"] = 2  # attached by reference: snapshot sees live mutation
    snap = reg.snapshot()
    assert snap["counters"] == {"hits": 4}
    assert snap["gauges"] == {"depth": 7.0, "live": 42.0}
    assert snap["labeled"] == {"reasons": {"a": 1, "b": 2}}
    json.dumps(snap)  # the whole snapshot must be JSON-exportable


def test_histogram_fixed_buckets():
    h = Histogram("lat", bounds=(0.1, 1.0, 10.0))
    for v in (0.05, 0.1, 0.5, 2.0, 100.0):
        h.observe(v)
    # <=0.1 catches 0.05 and the boundary 0.1; overflow catches 100
    assert h.counts == [2, 1, 1, 1]
    assert h.count == 5 and h.total == pytest.approx(102.65)
    h.reset()
    assert h.counts == [0, 0, 0, 0] and h.count == 0 and h.total == 0.0
    with pytest.raises(AssertionError):
        Histogram("bad", bounds=(1.0, 0.5))  # bounds must ascend


def test_percentile_nearest_rank():
    assert percentile([], 0.5) is None
    vals = [1.0, 2.0, 3.0, 4.0]
    assert percentile(vals, 0.0) == 1.0
    assert percentile(vals, 0.5) == 3.0
    assert percentile(vals, 0.99) == 4.0


# ---------------------------------------------------------------------------
# stats() is a documented, pinned contract (registry refactors must not
# drop a key bench_serving or the r6 queue reads)
# ---------------------------------------------------------------------------


def test_engine_stats_key_contract(model):
    eng, _ = _run(model)
    st = eng.stats()
    assert tuple(st.keys()) == ENGINE_STATS_KEYS, (
        "ServingEngine.stats() keys drifted from the "
        "telemetry.ENGINE_STATS_KEYS contract"
    )
    # the façade and the registry snapshot agree on the shared counters
    snap = eng.metrics_snapshot()
    for k in ("decode_dispatches", "prefill_dispatches",
              "tokens_generated", "evictions"):
        assert st[k] == snap["counters"][k]
    assert st["reject_reasons"] == snap["labeled"]["reject_reasons"]
    json.dumps(snap)


def test_cluster_stats_key_contract_and_aggregation(model):
    cl = ServingCluster(model, replicas=2, **_KW)
    prompts = _prompts(4)
    rids = [cl.submit(p, 8, seed=i) for i, p in enumerate(prompts)]
    cl.run()
    st = cl.stats()
    assert tuple(st.keys()) == CLUSTER_STATS_KEYS, (
        "ServingCluster.stats() keys drifted from the "
        "telemetry.CLUSTER_STATS_KEYS contract"
    )
    per = st["per_replica"]
    assert len(per) == 2
    for p in per:
        assert tuple(p.keys()) == ENGINE_STATS_KEYS
    # aggregation still sums the summable counters
    for k in ("decode_dispatches", "tokens_generated", "windows",
              "prompt_tokens_total"):
        assert st[k] == sum(p[k] for p in per)
    assert st["tokens_generated"] == sum(
        len(cl.finished[r].tokens) for r in rids
    )
    # a non-disaggregated cluster reports the disagg counters as flat
    # zeros — the keys are pinned either way
    assert st["prefill_replicas"] == 0 and st["decode_replicas"] == 0
    for k in ("handoffs", "handoff_pages_moved", "handoff_bytes",
              "handoff_failures", "prefix_affinity_hits",
              "routed_fallback"):
        assert st[k] == 0, k
    json.dumps(cl.metrics_snapshot())


def test_disagg_cluster_stats_same_contract(model):
    """A disaggregated cluster answers the SAME pinned key tuple — the
    pool split changes counter values, never the stats façade."""
    cl = ServingCluster(
        model, prefill_replicas=1, decode_replicas=1, **_KW
    )
    prompts = _prompts(3)
    [cl.submit(p, 6, seed=i) for i, p in enumerate(prompts)]
    cl.run()
    st = cl.stats()
    assert tuple(st.keys()) == CLUSTER_STATS_KEYS
    assert st["prefill_replicas"] == 1 and st["decode_replicas"] == 1
    assert st["handoffs"] == len(prompts)
    assert st["handoff_pages_moved"] > 0 and st["handoff_bytes"] > 0
    snap = cl.metrics_snapshot()
    assert snap["cluster"]["handoffs"] == st["handoffs"]
    assert snap["cluster"]["handoff_bytes"] == st["handoff_bytes"]
    json.dumps(snap)


def test_counter_attributes_are_registry_backed(model):
    eng, _ = _run(model)
    assert eng.decode_dispatches >= 1
    # the bench's warmup reset: plain attribute assignment must hit the
    # registry Counter (property setter), not shadow it
    eng.decode_dispatches = 0
    assert eng.metrics.counter("decode_dispatches").value == 0
    assert eng.stats()["decode_dispatches"] == 0
    eng.decode_dispatches += 5
    assert eng.metrics_snapshot()["counters"]["decode_dispatches"] == 5


# ---------------------------------------------------------------------------
# Lifecycle tracing + derived metrics (fake clock: derived values exact)
# ---------------------------------------------------------------------------


def test_lifecycle_event_taxonomy_and_derived_metrics(model):
    tick = itertools.count()
    eng, streams = _run(
        model, telemetry=True, clock=lambda: float(next(tick)),
        prefill_chunk=4,
    )
    tele = eng.telemetry
    kinds = {ev.kind for ev in tele.events}
    assert kinds <= set(EVENT_KINDS)
    assert {"submit", "queued", "admitted", "prefill_chunk",
            "decode_window", "tokens", "finished"} <= kinds
    for rid, toks in enumerate(streams):
        evs = tele.request_log[rid]
        order = [ev.kind for ev in evs]
        # lifecycle orders correctly: submitted, queued, admitted before
        # any tokens, finished last
        assert order[0] == "submit" and order[1] == "queued"
        assert order.index("admitted") < order.index("tokens")
        assert order[-1] == "finished"
        m = tele.request_metrics(rid)
        assert m["finished"] and m["tokens"] == len(toks)
        # fake clock: every derived value is an exact tick difference
        assert m["queue_delay_s"] >= 0 and float(m["queue_delay_s"]).is_integer()
        assert m["ttft_s"] > 0
        assert len(m["tbt_s"]) == len(toks) - 1
        assert m["dispatches"] >= 1
        assert m["tokens_per_dispatch"] == pytest.approx(
            m["tokens"] / m["dispatches"]
        )
        assert m["eviction_stall_s"] == 0.0
    # events carry the scheduler-step key space (fault_step convention)
    assert all(ev.step <= eng.fault_step for ev in tele.events)
    # the latency histograms populated from the same clock
    snap = eng.metrics_snapshot()
    assert snap["histograms"]["ttft_s"]["count"] == len(streams)
    assert snap["histograms"]["queue_delay_s"]["count"] == len(streams)
    assert snap["histograms"]["tbt_s"]["count"] == sum(
        len(s) - 1 for s in streams
    )
    assert snap["histograms"]["dispatch_s"]["count"] == eng.decode_dispatches


def test_eviction_stall_and_park_resume_events(model):
    """A scripted allocator exhaustion parks the lone request; telemetry
    must show evicted -> parked -> resumed -> admitted and account the
    outage as eviction stall."""
    plan = FaultPlan([FaultEvent(step=2, kind="exhaust", hold_steps=2)])
    kw = dict(
        slots=1, page_size=4, num_pages=4, window=4, temperature=0.0,
        cache_dtype=jnp.float32, prefix_cache=False,
        fault_hook=plan.hook(0), telemetry=True,
    )
    eng = ServingEngine(model, **kw)
    rid = eng.submit(_prompts(1, base_len=3)[0], 12)
    for _ in range(100):
        if not eng.has_work:
            break
        eng.step()
    assert rid in eng.finished
    tele = eng.telemetry
    kinds = [ev.kind for ev in tele.request_log[rid]]
    i_evict = kinds.index("evicted")
    assert kinds[i_evict + 1] == "parked"
    assert "resumed" in kinds[i_evict:]
    # re-admitted after the quarantine release (possibly bounced more
    # than once while the hold was still on)
    assert kinds.count("admitted") >= 2
    m = tele.request_metrics(rid)
    assert m["eviction_stall_s"] > 0
    assert m["evictions"] >= 1
    # the scripted injection itself is on the timeline
    faults = [ev for ev in tele.events if ev.kind == "fault"]
    assert len(faults) == 1 and faults[0].data["fault"] == "exhaust"


def test_shed_and_deferred_events(model):
    shed = ServingEngine(
        model, max_queue=1, overload_policy="shed", telemetry=True, **_KW
    )
    shed.submit(_prompts(1)[0], 4)
    with pytest.raises(Exception):
        shed.submit(_prompts(2)[1], 4)
    assert [ev.kind for ev in shed.telemetry.events
            if ev.kind in ("shed", "deferred")] == ["shed"]

    defer = ServingEngine(
        model, max_queue=1, overload_policy="defer", telemetry=True, **_KW
    )
    defer.submit(_prompts(1)[0], 4)
    with pytest.raises(Exception):
        defer.submit(_prompts(2)[1], 4)
    assert [ev.kind for ev in defer.telemetry.events
            if ev.kind in ("shed", "deferred")] == ["deferred"]


# ---------------------------------------------------------------------------
# The hard gate: tracing is inert — identical programs, bitwise streams,
# replay-deterministic event sequences
# ---------------------------------------------------------------------------


def _identity_case(model, **kw):
    eng_off, s_off = _run(model, telemetry=None, **kw)
    eng_on, s_on = _run(model, telemetry=True, **kw)
    assert s_on == s_off, f"streams diverged with tracing on ({kw})"
    # program-cache identity: tracing must select the SAME jitted
    # callables (telemetry is not a factory parameter), so the audit
    # matrix proven for the untraced programs covers the traced engine
    for attr in ("_window_fn", "_verify_fn"):
        assert getattr(eng_on, attr) is getattr(eng_off, attr), attr
    assert len(eng_on.telemetry.events) > 0
    return eng_on


def test_telemetry_identity_default(model):
    _identity_case(model)


def test_telemetry_false_means_off(model):
    """bench_serving passes the computed bool straight through —
    telemetry=False must construct a tracing-off engine, not crash
    (the r6 `serving_tele_off` overhead rung is exactly this path)."""
    eng, _ = _run(model, telemetry=False)
    assert eng.telemetry is None
    assert eng.stats()["tokens_generated"] > 0


def test_telemetry_identity_spec_chunked(model):
    _identity_case(model, speculate=4, prefill_chunk=4)


@pytest.mark.slow
@pytest.mark.parametrize(
    "kw",
    [
        dict(prefix_cache=False, layer_scan="on"),
        dict(prefill_chunk=8, kv_quant="int8"),
        dict(prefill_chunk=8, speculate=4, kv_quant="int8",
             layer_scan="on"),
        dict(prefix_cache=False, prefill_chunk=8, speculate=4,
             layer_scan="on"),
        dict(kv_quant="int8", layer_scan="on", cache_dtype=jnp.bfloat16),
    ],
    ids=["nocache-ls", "chunk-kv8", "chunk-spec-kv8-ls",
         "nocache-chunk-spec-ls", "kv8-ls-bf16"],
)
def test_telemetry_identity_matrix(model, kw):
    """Acceptance: greedy streams with telemetry on are bitwise
    identical to telemetry off across cache x chunk x spec x kv-quant x
    layer_scan."""
    _identity_case(model, **kw)


def test_replay_produces_identical_event_sequence(model):
    run1 = _identity_case(model, prefill_chunk=4)
    eng2, _ = _run(model, telemetry=True, prefill_chunk=4)
    sig1 = run1.telemetry.sequence_signature()
    sig2 = eng2.telemetry.sequence_signature()
    assert sig1 == sig2, (
        "replaying the same trace must reproduce the event sequence "
        "(wall-clock annotations excluded)"
    )
    # ... and the signatures really do exclude wall clock: the raw
    # timestamps differ between the runs
    t1 = [ev.t for ev in run1.telemetry.events]
    t2 = [ev.t for ev in eng2.telemetry.events]
    assert t1 != t2


def test_prove_telemetry_inert_harness():
    from midgpt_tpu.analysis.harness import prove_telemetry_inert

    rep = prove_telemetry_inert(speculate=4, prefill_chunk=4)
    assert rep["ok"] and rep["streams_identical"]
    assert "_verify_fn" in rep["programs_identical"]
    assert rep["events_recorded"] > 0


# ---------------------------------------------------------------------------
# Flight recorder + Chrome trace export
# ---------------------------------------------------------------------------


def test_flight_recorder_rings_bounded(model):
    tele = EngineTelemetry(ring=8, dispatch_ring=4)
    eng, _ = _run(model, telemetry=tele, n=3, n_new=8)
    assert len(tele.events) == 8, "event ring must cap at its capacity"
    assert len(tele.dispatches) <= 4
    # the ring keeps the MOST RECENT events (a flight recorder, not a
    # head sample): the last event of the run is present
    assert tele.events[-1].kind == "finished"


def test_flight_dump_structure(model, tmp_path):
    eng, streams = _run(model, telemetry=True)
    path = str(tmp_path / "flight.json")
    rec = eng.flight_dump("unit_test", path=path, extra={"replica": 7})
    on_disk = json.load(open(path))
    assert on_disk["reason"] == "unit_test" and on_disk["replica"] == 7
    assert on_disk["path"] == path
    assert on_disk["stats"]["tokens_generated"] == sum(
        len(s) for s in streams
    )
    assert on_disk["metrics"]["counters"]["decode_dispatches"] >= 1
    evs = on_disk["telemetry"]["events"]
    assert evs and {"seq", "step", "kind", "t"} <= set(evs[0])
    assert on_disk["telemetry"]["dispatches"]
    assert rec["fault_step"] == eng.fault_step
    # without tracing the dump still carries stats + metrics
    eng2, _ = _run(model, telemetry=None)
    rec2 = eng2.flight_dump("no_trace")
    assert rec2["telemetry"] is None and rec2["stats"]["windows"] >= 1


def test_chrome_trace_structure(model):
    eng, streams = _run(model, telemetry=True, prefill_chunk=4)
    trace = chrome_trace(eng.telemetry)
    json.dumps(trace)
    evs = trace["traceEvents"]
    assert evs
    for ev in evs:
        assert ev["ph"] in ("X", "i", "M")
        if ev["ph"] == "X":
            assert ev["ts"] >= 0 and ev["dur"] >= 0
    # request lanes: one active span + one tokens instant per request
    req_spans = [e for e in evs if e["ph"] == "X" and e["pid"] == 1]
    assert {e["tid"] for e in req_spans} == set(range(len(streams)))
    assert any(e["name"] == "active" for e in req_spans)
    # dispatch lanes carry the program launches
    disp = [e for e in evs if e["ph"] == "X" and e["pid"] == 2]
    assert len(disp) == len(eng.telemetry.dispatches)
    assert {e["name"] for e in disp} <= {
        "decode_window", "verify_dispatch", "prefill_chunk"
    }


def test_chrome_trace_handoff_spans(model):
    """Page handoffs render as X-phase spans on the prefill replica's
    dispatch lane (their own tid), carrying page/byte args — and the
    decode replica's lane shows decode windows only: the class split is
    visible straight off the timeline."""
    cl = ServingCluster(
        model, prefill_replicas=1, decode_replicas=1, telemetry=True,
        **_KW,
    )
    prompts = _prompts(3)
    [cl.submit(p, 6, seed=i) for i, p in enumerate(prompts)]
    cl.run()
    pre, dec = cl.engines
    evs = chrome_trace(pre.telemetry)["traceEvents"]
    spans = [e for e in evs if e["ph"] == "X" and e["pid"] == 2]
    hand = [e for e in spans if e["name"] == "handoff"]
    assert len(hand) == len(prompts)
    assert {e["tid"] for e in hand} == {3}, "handoffs get their own lane"
    for e in hand:
        assert e["args"]["pages"] > 0 and e["args"]["bytes"] > 0
    assert not any(e["name"] == "decode_window" for e in spans)
    dspans = [
        e for e in chrome_trace(dec.telemetry)["traceEvents"]
        if e["ph"] == "X" and e["pid"] == 2
    ]
    assert dspans and all(e["name"] == "decode_window" for e in dspans)


def test_chrome_trace_engine_lane_carries_ridless_events(model):
    """shed/deferred fire before any rid exists and scripted faults are
    engine-scoped — they render on the engine lane (from the recency
    ring), not silently vanish from the export."""
    eng = ServingEngine(
        model, max_queue=1, overload_policy="shed", telemetry=True, **_KW
    )
    eng.submit(_prompts(1)[0], 4)
    with pytest.raises(Exception):
        eng.submit(_prompts(2)[1], 4)
    eng.run()
    evs = chrome_trace(eng.telemetry)["traceEvents"]
    lane = [e for e in evs if e.get("pid") == 3 and e["ph"] == "i"]
    assert [e["name"] for e in lane] == ["shed"]
    assert all(e["ts"] >= 0 for e in lane)


def test_profiler_hooks_fire_at_step_window(model, tmp_path, monkeypatch):
    calls = []
    import jax.profiler as prof

    monkeypatch.setattr(
        prof, "start_trace", lambda d: calls.append(("start", d))
    )
    monkeypatch.setattr(prof, "stop_trace", lambda: calls.append(("stop",)))
    tele = EngineTelemetry(
        profile_dir=str(tmp_path), profile_steps=(2, 3)
    )
    _run(model, telemetry=tele)
    assert calls == [("start", str(tmp_path)), ("stop",)]

    # a workload draining BEFORE the configured stop step must still
    # finalize the trace (run() stops an in-flight profile at drain —
    # a dangling trace is unwritten and poisons the next start_trace)
    calls.clear()
    tele2 = EngineTelemetry(
        profile_dir=str(tmp_path), profile_steps=(2, 10_000)
    )
    _run(model, telemetry=tele2)
    assert calls == [("start", str(tmp_path)), ("stop",)]
    assert not tele2._profiling


# ---------------------------------------------------------------------------
# bench_serving record contract (slow: subprocess drive of the CLI)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_bench_serving_telemetry_record_contract(tmp_path):
    """The tiny-preset bench with chaos + --timeline_dir must emit the
    telemetry-derived record keys, the Perfetto timeline artifacts, and
    the dead-replica flight dump — the exact surface the r6 queue and
    the serving-chaos CI job consume."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = str(tmp_path / "rec.json")
    tl = str(tmp_path / "tl")
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
    )
    prom = str(tmp_path / "metrics.prom")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "bench_serving.py"),
         "--preset", "tiny", "--dp_replicas", "2",
         "--fault_plan", "1:transient@0;2:crash@0",
         "--dispatch_timeout_s", "60", "--deadline_s", "600",
         "--timeline_dir", tl, "--metrics_out", prom, "--out", out],
        capture_output=True, text=True, env=env, timeout=540,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.load(open(out))
    assert rec["status"] == "ok"
    assert rec["serve_telemetry"] == "on"
    assert rec["serve_tbt_p99_ms"] is not None
    assert rec["serve_queue_delay_p50_ms"] is not None
    # floor + attainment + MFU ride every record (PR 15 contract): the
    # static per-token floor, the measured ms/tok, their ratio, and the
    # compute-side fraction — the ledger's static/wall-clock key split
    # depends on this inventory
    assert rec["serve_floor_ms_per_tok_static"] > 0
    assert rec["serve_ms_per_tok"] > 0
    assert rec["serve_attainment_frac"] == pytest.approx(
        rec["serve_floor_ms_per_tok_static"] / rec["serve_ms_per_tok"],
        rel=1e-2,
    )
    assert rec["serve_mfu"] is not None and rec["serve_mfu"] > 0
    assert rec["serve_hbm_floor_ms_static"] > 0
    # --metrics_out: Prometheus text exposition over the cluster
    # registry, path recorded in-band
    assert rec["serve_metrics_out"] == prom
    text = open(prom).read()
    assert "# TYPE midgpt_tokens_generated_total counter" in text
    assert 'replica="0"' in text and 'replica="1"' in text
    assert 'scope="cluster"' in text
    assert rec["serve_requests_finished"] == rec["serve_requests"]
    # disagg/affinity keys ride EVERY record — flat defaults off the
    # monolithic dp=2 path (the disagg CI job asserts the live values)
    assert rec["serve_disagg"] is None
    assert rec["serve_affinity"] == "off"
    assert rec["serve_ttft_by_class"] is None
    assert rec["serve_handoff_count"] == 0
    assert rec["serve_handoff_pages"] == 0
    assert rec["serve_handoff_bytes"] == 0
    assert rec["serve_handoff_failures"] == 0
    assert rec["serve_prefix_affinity_hits"] == 0
    assert rec["serve_routed_fallback"] == 0
    for f in rec["serve_timeline_files"]:
        assert os.path.exists(f), f
    names = {os.path.basename(f) for f in rec["serve_timeline_files"]}
    assert {"timeline_replica0.json", "request_metrics.json",
            "metrics_snapshot.json"} <= names
    assert rec["serve_flight_dumps"], "the crashed replica must dump"
    dump = json.load(open(rec["serve_flight_dumps"][0]))
    assert dump["reason"] == "crashed" and dump["telemetry"]["events"]
    # the timeline is a loadable Chrome trace
    tr = json.load(open(os.path.join(tl, "timeline_replica0.json")))
    assert tr["traceEvents"]


@pytest.mark.slow
def test_bench_serving_sampled_spec_record_contract(tmp_path):
    """--temperature composed with --spec on (rejection-sampling
    verification): the record must carry the sampling shape next to the
    speculation counters — the surface the r6 queue's spec-sampled rung
    pair and the serving-choreo sampled-chat CI leg consume."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = str(tmp_path / "rec_sampled.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "bench_serving.py"),
         "--preset", "tiny", "--spec", "on", "--spec_len", "4",
         "--temperature", "0.8", "--top_k", "20", "--repetitive",
         "--window", "2", "--deadline_s", "600", "--out", out],
        capture_output=True, text=True, env=env, timeout=540,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.load(open(out))
    assert rec["status"] == "ok"
    assert rec["serve_temperature"] == 0.8
    assert rec["serve_top_k"] == 20
    # every decode dispatch IS a verify dispatch with spec on, and the
    # acceptance rate is the rejection sampler's measured accept
    # fraction (a float even when the random-init model accepts none)
    assert rec["serve_verify_dispatches"] > 0
    assert rec["serve_spec_drafted_tokens"] > 0
    assert rec["serve_spec_acceptance_rate"] is not None
    assert "T=0.8" in rec["serve_shape"]
    assert "topk=20" in rec["serve_shape"]


@pytest.mark.slow
def test_bench_serving_longctx_record_contract(tmp_path):
    """--prompt_len + --prefill_sp + --spill (the long-context serving
    rungs): the record must carry the resolved SP mode, the long-prompt
    TTFT lane, the static SP-prefill floor pair, and the spill
    counters — the exact surface the r6 sp-off/sp-on pair and the
    spill-pressure rung consume. The undersized pool must actually
    spill AND the run must still drain clean (the no-wedge contract)."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = str(tmp_path / "rec_longctx.json")
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
    )
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "bench_serving.py"),
         "--preset", "tiny", "--prompt_len", "64", "--sys_prompt_len", "64",
         "--requests", "6", "--slots", "1", "--tp", "2",
         "--prefill_chunk", "32", "--spill", "on", "--num_pages", "10",
         "--deadline_s", "600", "--out", out],
        capture_output=True, text=True, env=env, timeout=540,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.load(open(out))
    assert rec["status"] == "ok"
    # prefill_sp="auto" resolved on against the tp=2 mesh, in the
    # record AND the shape (the rung pair pins off/on explicitly)
    assert rec["serve_prefill_sp"] == "on"
    assert "sp=on" in rec["serve_shape"] and "spill" in rec["serve_shape"]
    assert rec["serve_prompt_len"] == 64
    # every prompt is long by construction, so the long lane equals the
    # overall p99 and must be populated
    assert rec["serve_ttft_long_p99"] is not None
    assert rec["serve_ttft_long_p99"] == rec["serve_ttft_p99_ms"]
    # static floor pair: sp divides the per-chip prefill compute by tp
    assert rec["serve_prefill_floor_ms_static"] > 0
    assert rec["serve_prefill_sp_floor_ms_static"] == pytest.approx(
        rec["serve_prefill_floor_ms_static"] / 2, rel=0.5
    )
    # the 10-page pool is smaller than the 6-request working set: cold
    # chains must have spilled to host RAM, and the host store's
    # cumulative residency may legitimately exceed the pool itself
    assert rec["serve_num_pages"] == 10
    assert rec["serve_spilled_pages"] > 0
    assert rec["serve_spill_resident_pages"] > 0
    for k in ("serve_spill_faultback_pages", "serve_spill_prefetch_pages",
              "serve_spill_readmissions", "serve_spill_discards"):
        assert isinstance(rec[k], int) and rec[k] >= 0, k
    # requested vs resolved kernel (ISSUE 20): the record carries BOTH —
    # a long-context row claiming pallas cannot hide an XLA fallback.
    # This CPU run requested the default "auto" and must have resolved
    # to a concrete backend (xla off-TPU).
    assert rec["serve_paged_kernel"] == "auto"
    assert rec["serve_paged_kernel_resolved"] == "xla"
    # no-wedge: everything finished, nothing shed or deferred
    assert rec["serve_requests_finished"] == rec["serve_requests"]
    assert rec["serve_shed_requests"] == 0
    assert rec["serve_error"] is None


# ---------------------------------------------------------------------------
# Shared substrate (PR 15): serving re-exports the midgpt_tpu.telemetry
# core unchanged, and the Prometheus exporter renders registry
# snapshots against the pinned stats-key contracts
# ---------------------------------------------------------------------------


def test_serving_reexports_shared_substrate():
    """The PR 15 extraction contract: every substrate name the serving
    module exposed before the split must still resolve to the SAME
    object through midgpt_tpu.serving.telemetry (engine/cluster/bench
    imports keep working verbatim), and EngineTelemetry is the
    serving-taxonomy specialization of the shared TelemetryLog."""
    import midgpt_tpu.serving.telemetry as serving_tele
    import midgpt_tpu.telemetry as core
    from midgpt_tpu.telemetry import TelemetryLog

    for name in (
        "Counter", "Gauge", "Histogram", "MetricsRegistry", "Event",
        "DispatchRecord", "percentile", "write_json",
        "LATENCY_BUCKETS_S", "prometheus_text",
    ):
        assert getattr(serving_tele, name) is getattr(core, name), name
    assert issubclass(EngineTelemetry, TelemetryLog)
    assert EngineTelemetry.event_kinds == EVENT_KINDS
    # the base rejects kinds outside the subclass taxonomy
    t = EngineTelemetry()
    with pytest.raises(AssertionError):
        t.emit("window_launch", step=0, t=0.0)


def test_prometheus_text_format_units():
    """Exposition-format details the scrape side depends on: counters
    get _total, labeled families one series per key, histograms render
    CUMULATIVE buckets + +Inf + _sum/_count, labels merge, and each
    family gets exactly one # TYPE header even across snapshots."""
    from midgpt_tpu.telemetry import prometheus_text

    reg = MetricsRegistry()
    reg.counter("hits").inc(3)
    reg.attach_labels("reasons", {"full": 2})
    reg.gauge("depth").set(1.5)
    h = reg.histogram("lat", bounds=(0.1, 1.0))
    h.observe(0.05)
    h.observe(5.0)
    reg2 = MetricsRegistry()
    reg2.counter("hits").inc(7)
    text = prometheus_text([
        ({"replica": "0"}, reg.snapshot()),
        ({"replica": "1"}, reg2.snapshot()),
    ])
    assert 'midgpt_hits_total{replica="0"} 3' in text
    assert 'midgpt_hits_total{replica="1"} 7' in text
    assert 'midgpt_reasons_total{key="full",replica="0"} 2' in text
    assert 'midgpt_depth{replica="0"} 1.5' in text
    assert 'midgpt_lat_bucket{le="0.1",replica="0"} 1' in text
    # cumulative: the 1.0 bucket includes the 0.1 bucket's observation
    assert 'midgpt_lat_bucket{le="1.0",replica="0"} 1' in text
    assert 'midgpt_lat_bucket{le="+Inf",replica="0"} 2' in text
    assert 'midgpt_lat_count{replica="0"} 2' in text
    assert text.count("# TYPE midgpt_hits_total counter") == 1


def test_prometheus_text_covers_engine_counter_contract(model):
    """Every registry-backed engine counter (the objects behind the
    pinned ENGINE_STATS_KEYS facade) must appear in the exposition —
    the exporter cannot silently drop part of the contract surface."""
    from midgpt_tpu.serving.engine import _ENGINE_COUNTERS
    from midgpt_tpu.telemetry import prometheus_text

    eng, _ = _run(model)
    text = prometheus_text(eng.metrics_snapshot())
    for name in _ENGINE_COUNTERS:
        assert f"midgpt_{name}_total" in text, name
    # always-on histograms ride along (queue delay observed per admit)
    assert "midgpt_queue_delay_s_bucket" in text
    assert "# TYPE midgpt_tokens_generated_total counter" in text


def test_prometheus_text_cluster_expands_replicas(model):
    """A cluster snapshot expands to per-replica series plus the
    cluster-level scalars as scope="cluster" gauges."""
    from midgpt_tpu.telemetry import prometheus_text

    cl = ServingCluster(model, replicas=2, **_KW)
    for i, p in enumerate(_prompts(4)):
        cl.submit(p, 8, seed=i)
    cl.run()
    text = prometheus_text(cl.metrics_snapshot())
    assert 'midgpt_tokens_generated_total{replica="0"}' in text
    assert 'midgpt_tokens_generated_total{replica="1"}' in text
    assert 'midgpt_failovers{scope="cluster"} 0' in text
    assert 'midgpt_dp_replicas{scope="cluster"} 2' in text
