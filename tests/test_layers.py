"""Layer-level unit tests, incl. the reference's rotary shift-invariance
property test (/root/reference/scripts/test_rotary.py:11-32)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.models.layers import (
    Embedding,
    LayerNorm,
    Linear,
    RMSNorm,
    apply_rotary,
    dropout,
    rope_tables,
    rotate_every_two,
)


def test_linear_init_and_apply():
    key = jax.random.PRNGKey(0)
    lin = Linear.init(key, 32, 64)
    assert lin.weight.shape == (32, 64)
    # truncated normal scaled 1/sqrt(fan_in): bounded by 2/sqrt(32)
    assert np.abs(lin.weight).max() <= 2 / np.sqrt(32) + 1e-6
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 7, 32))
    y = lin(x)
    assert y.shape == (4, 7, 64)
    np.testing.assert_allclose(y[0, 0], x[0, 0] @ lin.weight, rtol=1e-5)


def test_embedding_gather():
    emb = Embedding.init(jax.random.PRNGKey(0), 100, 16, std=0.1)
    tok = jnp.array([[1, 2], [3, 99]])
    out = emb(tok)
    assert out.shape == (2, 2, 16)
    np.testing.assert_array_equal(out[1, 1], emb.weight[99])


def test_rmsnorm_matches_formula():
    x = jax.random.normal(jax.random.PRNGKey(0), (3, 5, 16))
    norm = RMSNorm.init(16, use_weight=False)
    out = norm(x)
    expected = x * (1.0 / np.sqrt(np.mean(np.asarray(x) ** 2, -1, keepdims=True) + 1e-6))
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5)
    # weightless => no params
    assert norm.weight is None


def test_layernorm_mean_subtracting():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8)) * 3 + 5
    ln = LayerNorm.init(8)
    out = np.asarray(ln(x))
    np.testing.assert_allclose(out.mean(-1), 0.0, atol=1e-5)
    np.testing.assert_allclose(out.std(-1), 1.0, atol=1e-2)


def test_rotate_every_two():
    x = jnp.array([[1.0, 2.0, 3.0, 4.0]])
    np.testing.assert_allclose(
        np.asarray(rotate_every_two(x)), [[-2.0, 1.0, -4.0, 3.0]]
    )


def test_rotary_shift_invariance():
    """Attention scores depend only on relative position (parity:
    scripts/test_rotary.py:11-32)."""
    key = jax.random.PRNGKey(0)
    t, c, shift = 32, 16, 5
    q = jax.random.normal(key, (1, 1, t, c))
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 1, t, c))
    sin, cos = rope_tables(c, t)
    qr = apply_rotary(q, sin, cos)
    kr = apply_rotary(k, sin, cos)
    scores = np.asarray(qr @ jnp.swapaxes(kr, -1, -2))[0, 0]

    # shift q, k along T by `shift`: scores in the overlap must match
    q_s = jnp.roll(q, shift, axis=2)
    k_s = jnp.roll(k, shift, axis=2)
    qr_s = apply_rotary(q_s, sin, cos)
    kr_s = apply_rotary(k_s, sin, cos)
    scores_s = np.asarray(qr_s @ jnp.swapaxes(kr_s, -1, -2))[0, 0]

    np.testing.assert_allclose(
        scores_s[shift:, shift:], scores[:-shift, :-shift], atol=1e-4
    )


def test_rope_tables_constant_fold():
    sin, cos = rope_tables(8, 16)
    assert isinstance(sin, np.ndarray) and sin.shape == (16, 4)
    # base angle progression
    np.testing.assert_allclose(cos[0], 1.0)
    np.testing.assert_allclose(sin[0], 0.0)


def test_dropout_modes():
    x = jnp.ones((100, 100))
    # deterministic => identity
    np.testing.assert_array_equal(np.asarray(dropout(x, 0.5, None, True)), np.asarray(x))
    out = np.asarray(dropout(x, 0.5, jax.random.PRNGKey(0), False))
    frac_zero = (out == 0).mean()
    assert 0.4 < frac_zero < 0.6
    # survivors scaled by 1/keep
    assert np.allclose(out[out != 0], 2.0)
    with pytest.raises(AssertionError):
        dropout(x, 0.5, None, False)
