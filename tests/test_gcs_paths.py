"""gs:// path handling with a local fake GCS (VERDICT r1 item 10).

A real bucket isn't reachable (zero egress), so coverage is split:
- OUR gs:// branches (Checkpointer path passthrough, launch.py rundir
  setup, wandb-id persistence in utils/metrics.py) run against a fake
  ``gcsfs`` backed by a tmp directory;
- the actual byte-shipping to GCS inside Orbax/tensorstore is that
  stack's own tested territory (the reference leans on the same split:
  /root/reference/scripts/test_ckpt.py is a manual script against a real
  bucket).
"""

import os
import sys
import types

import pytest


class _FakeGCSFileSystem:
    """Minimal gcsfs.GCSFileSystem: maps gs://bucket/... to <root>/bucket/...."""

    root = None  # set by fixture

    def __init__(self, *a, **k):
        assert self.root is not None

    def _local(self, path: str) -> str:
        assert path.startswith("gs://"), path
        return os.path.join(self.root, path[len("gs://") :])

    def open(self, path, mode="r"):
        local = self._local(path)
        if "w" in mode:
            os.makedirs(os.path.dirname(local), exist_ok=True)
        return open(local, mode)

    def exists(self, path) -> bool:
        return os.path.exists(self._local(path))


@pytest.fixture()
def fake_gcs(tmp_path, monkeypatch):
    _FakeGCSFileSystem.root = str(tmp_path / "gcs")
    fake_mod = types.SimpleNamespace(GCSFileSystem=_FakeGCSFileSystem)
    monkeypatch.setitem(sys.modules, "gcsfs", fake_mod)
    return _FakeGCSFileSystem.root


def test_checkpointer_keeps_gs_path_unmangled(monkeypatch):
    """gs:// rundirs must reach Orbax verbatim — os.path.abspath would turn
    'gs://b/run' into '/...//gs:/b/run' (checkpoint.py:42)."""
    import midgpt_tpu.checkpoint as ckpt_mod

    captured = {}

    class FakeManager:
        def __init__(self, path, options=None):
            captured["path"] = path

    monkeypatch.setattr(ckpt_mod.ocp, "CheckpointManager", FakeManager)
    ckpt_mod.Checkpointer("gs://bucket/run", save_interval_steps=10)
    assert captured["path"] == "gs://bucket/run"
    # local relative paths ARE absolutized
    ckpt_mod.Checkpointer("some/rundir", save_interval_steps=10)
    assert os.path.isabs(captured["path"])


def test_wandb_id_round_trip_on_gs(fake_gcs):
    from midgpt_tpu.utils.metrics import _load_or_create_wandb_id

    wandb_stub = types.SimpleNamespace(
        util=types.SimpleNamespace(generate_id=lambda: "gsid42")
    )
    rundir = "gs://bucket/run7"
    first = _load_or_create_wandb_id(rundir, wandb_stub)
    assert first == "gsid42"
    wandb_stub2 = types.SimpleNamespace(
        util=types.SimpleNamespace(generate_id=lambda: "SHOULD-NOT-BE-USED")
    )
    assert _load_or_create_wandb_id(rundir, wandb_stub2) == "gsid42"
    assert os.path.exists(os.path.join(fake_gcs, "bucket/run7/wandb_id.txt"))


def test_sample_reads_config_from_gs_rundir(fake_gcs):
    """sample.py must read config.json via gcsfs for gs:// ckpt dirs
    (parity: /root/reference/sample.py:39-46); plain open() would crash
    on a bucket path (VERDICT r2 Missing #2)."""
    from sample import load_run_config
    from midgpt_tpu.config import get_config, to_json

    cfg = get_config("tiny")
    rundir = "gs://bucket/samplerun"
    import gcsfs

    fs = gcsfs.GCSFileSystem()
    with fs.open(os.path.join(rundir, "config.json"), "w") as f:
        f.write(to_json(cfg))

    loaded = load_run_config(rundir)
    assert loaded.model.n_layer == cfg.model.n_layer
    # local dirs still go through plain open()
    local = os.path.join(_FakeGCSFileSystem.root, "bucket/samplerun")
    assert load_run_config(local).model.n_layer == cfg.model.n_layer


def test_launch_writes_config_to_gs_rundir(fake_gcs, monkeypatch):
    """launch.py's process-0 rundir setup takes the gcsfs branch for gs://
    (parity: /root/reference/launch.py:43-53)."""
    import json

    from launch import apply_overrides  # noqa: F401  (module import side)
    from midgpt_tpu.config import get_config, to_json

    # replicate launch.py:75-84's gs:// branch against the fake fs
    cfg = get_config("tiny")
    rundir = "gs://bucket/launchrun"
    import gcsfs

    fs = gcsfs.GCSFileSystem()
    with fs.open(os.path.join(rundir, "config.json"), "w") as f:
        f.write(to_json(cfg))

    with fs.open(os.path.join(rundir, "config.json"), "r") as f:
        loaded = json.load(f)
    assert loaded["model"]["n_layer"] == cfg.model.n_layer
