"""Disaggregated prefill/decode serving + prefix-affinity routing
(midgpt_tpu.serving.cluster): the landing gates asserted directly.

- **Bit-identity**: every stream through a disaggregated cluster
  (prefill pool -> page handoff -> decode pool) equals the monolithic
  single-engine reference token for token, and is invariant to the pool
  split (1+1 / 2+1 / 2+2). Fast tier pins the greedy/cache case; the
  slow tier crosses cache x chunk x spec(greedy+sampled) x kv-quant x
  layer_scan, plus eviction-under-pressure around the handoff.
- **Handoff hygiene**: the allocator identity (free + held + cached +
  quarantined == num_pages) and the PrefixIndex structural invariants
  re-check on EVERY engine after EVERY cluster step — i.e. after every
  export/import — and the prefix chain serves hits on BOTH sides of a
  handoff (export retires the source pages cold; import re-registers
  the chain in the destination index).
- **Affinity routing**: on a deterministic zipf shared-prefix tenant
  trace, prefix-affinity admission yields a strictly higher cluster
  prefix-cache hit rate than blind least-loaded admission at EQUAL
  goodput (same streams, same token count) — the ISSUE's acceptance
  gate, enforced repo-side. The load-imbalance cap is pinned too: a
  cache hit never justifies routing to a replica more than
  ``affinity_max_imbalance`` requests deeper than the shallowest.
- **Composition**: cancellation catches a request in handoff limbo
  (exported, not yet imported) — the record drops, nothing leaks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from midgpt_tpu.config import ModelConfig
from midgpt_tpu.models.gpt import GPT
from midgpt_tpu.serving import ServingCluster, ServingEngine

CFG = ModelConfig(
    block_size=64, vocab_size=96, n_layer=2, n_head=4, n_embd=32,
    dropout=0.0, attn_impl="naive", remat="none",
)

BASE_KW = dict(slots=2, page_size=8, window=4, cache_dtype=jnp.float32)


@pytest.fixture(scope="module")
def model():
    return GPT.init(jax.random.PRNGKey(0), CFG)


def _prompts(n, base_len=5, stride=3):
    return [
        np.asarray(
            jax.random.randint(
                jax.random.PRNGKey(100 + i), (base_len + stride * i,), 0,
                CFG.vocab_size,
            )
        )
        for i in range(n)
    ]


def _check(cl):
    """Allocator + prefix-index invariants on every live engine — run
    after every cluster step, i.e. after every export/import pair."""
    for i in cl._alive():
        e = cl.engines[i]
        e.alloc.check()
        if e.index is not None:
            e.index.check(e.alloc)


def _drive(cl, max_steps=400):
    for _ in range(max_steps):
        if not cl.has_work:
            return
        cl.step()
        _check(cl)
    raise AssertionError(f"cluster did not drain in {max_steps} steps")


def _mono_ref(model, prompts, n_new, **kw):
    eng = ServingEngine(model, **kw)
    rids = [eng.submit(p, n_new, seed=i) for i, p in enumerate(prompts)]
    fin = eng.run()
    return [list(map(int, fin[r].tokens)) for r in rids]


def _disagg_run(model, prompts, n_new, split, **kw):
    p, d = split
    cl = ServingCluster(
        model, prefill_replicas=p, decode_replicas=d, **kw
    )
    rids = [cl.submit(pr, n_new, seed=i) for i, pr in enumerate(prompts)]
    _drive(cl)
    cl._harvest()
    fin = cl.finished
    assert sorted(fin) == sorted(rids), "every request must finish"
    return [list(map(int, fin[r].tokens)) for r in rids], cl


# ---------------------------------------------------------------------------
# fast tier: 1+1 greedy/cache bit-identity + handoff accounting
# ---------------------------------------------------------------------------


def test_disagg_1p1_streams_bit_identical_to_monolithic(model):
    """The tentpole gate, fast shape: chunked prefill on the prefill
    replica, page handoff, decode on the decode replica — greedy
    streams equal the monolithic engine bit for bit, each request hands
    off exactly once, and the page/byte accounting is non-trivial."""
    prompts = _prompts(4, base_len=5, stride=2)
    ref = _mono_ref(model, prompts, 8, **BASE_KW)
    got, cl = _disagg_run(model, prompts, 8, (1, 1), **BASE_KW)
    assert got == ref
    st = cl.stats()
    assert st["handoffs"] == len(prompts)
    assert st["handoff_pages_moved"] > 0
    assert st["handoff_bytes"] > 0
    assert st["handoff_failures"] == 0
    assert st["prefill_replicas"] == 1 and st["decode_replicas"] == 1
    # role split did what it says: the prefill replica never decoded,
    # the decode replica never chunk-prefilled (no evictions here)
    assert cl.engines[0].decode_dispatches == 0
    assert cl.engines[0].prefill_dispatches > 0
    assert cl.engines[1].decode_dispatches > 0
    assert cl.engines[1].prefill_dispatches == 0


def test_disagg_split_placement_invariance_fast(model):
    """1+1 vs 2+1 vs 2+2: the pool split is a latency/throughput
    decision, never a correctness one — all splits yield the same
    streams (greedy, prefix cache on)."""
    prompts = _prompts(4, base_len=5, stride=2)
    ref = _mono_ref(model, prompts, 8, **BASE_KW)
    for split in ((1, 1), (2, 1), (2, 2)):
        got, cl = _disagg_run(model, prompts, 8, split, **BASE_KW)
        assert got == ref, split
        assert cl.stats()["handoffs"] == len(prompts), split


def test_handoff_reregisters_prefix_on_both_sides(model):
    """Export retires the source chain COLD (the prefill replica keeps
    serving hits on it) and import re-registers it in the destination
    index — so the handed-off prefix is queryable on BOTH pools, and a
    repeat prompt prefills via cache hits on the prefill replica."""
    prompt = np.asarray(
        jax.random.randint(jax.random.PRNGKey(9), (33,), 0, CFG.vocab_size)
    )
    cl = ServingCluster(
        model, prefill_replicas=1, decode_replicas=1, **BASE_KW
    )
    r0 = cl.submit(prompt, 6, seed=0)
    _drive(cl)
    pre, dec = cl.engines
    probe = [int(t) for t in prompt[:-1]]
    assert pre.index.match(probe)[2] > 0, "source chain must survive export"
    assert dec.index.match(probe)[2] > 0, "import must re-register the chain"
    # the repeat prompt hits the prefill replica's cache
    saved0 = pre.prompt_tokens_cached
    r1 = cl.submit(prompt, 6, seed=0)
    _drive(cl)
    cl._harvest()
    assert pre.prompt_tokens_cached > saved0
    assert cl.finished[r1].tokens == cl.finished[r0].tokens


def test_cancel_catches_request_in_handoff_limbo(model):
    """A request exported off the prefill pool but not yet imported
    (decode slots full) lives only as the cluster's HandoffRecord;
    cancel must find it there — record dropped, outcome cancelled,
    nothing leaks, and it can never be re-served."""
    kw = dict(BASE_KW, slots=1)
    prompts = _prompts(2, base_len=5, stride=2)
    cl = ServingCluster(
        model, prefill_replicas=1, decode_replicas=1, **kw
    )
    rids = [cl.submit(p, 12, seed=i) for i, p in enumerate(prompts)]
    for _ in range(100):
        if cl._handoff:
            break
        assert cl.has_work
        cl.step()
        _check(cl)
    assert cl._handoff, "second request must park in handoff limbo"
    (grid,) = cl._handoff
    assert cl.lookup(grid) is not None  # visible to the front door
    assert cl.cancel(grid) is True
    assert grid not in cl._handoff and grid not in cl._route
    assert cl.cancelled[grid].outcome == "cancelled"
    assert cl.cancel(grid) is False  # idempotent
    _drive(cl)
    cl._harvest()
    done = [r for r in rids if r in cl.finished]
    assert done == [r for r in rids if r != grid]
    _check(cl)


# ---------------------------------------------------------------------------
# fast tier: prefix-affinity routing
# ---------------------------------------------------------------------------


def _zipf_trace(n_requests=12, n_tenants=3, sys_len=24, seed=0):
    """Deterministic zipf-tenant shared-prefix trace (the PR 13 bench
    workload, miniaturized): each request is one of ``n_tenants``
    system prompts + a unique tail token."""
    rng = np.random.default_rng(seed)
    tenants = [
        np.asarray(
            jax.random.randint(
                jax.random.PRNGKey(50 + t), (sys_len,), 0, CFG.vocab_size
            )
        )
        for t in range(n_tenants)
    ]
    w = 1.0 / np.arange(1, n_tenants + 1)
    w /= w.sum()
    return [
        np.concatenate(
            [tenants[rng.choice(n_tenants, p=w)],
             np.asarray([i % CFG.vocab_size], np.int32)]
        )
        for i in range(n_requests)
    ]


def test_affinity_beats_least_loaded_on_zipf_trace(model):
    """THE acceptance gate: on the zipf shared-prefix tenant trace,
    prefix-affinity routing yields a strictly higher cluster-wide
    prefix hit rate than least-loaded admission at EQUAL goodput (the
    streams are identical — placement never changes tokens). Arrivals
    interleave with scheduler steps so the router sees resident state,
    exactly like a live trace."""
    trace = _zipf_trace()
    kw = dict(BASE_KW, prefix_cache=True)
    results = {}
    for aff in (False, True):
        cl = ServingCluster(model, replicas=2, affinity=aff, **kw)
        rids = []
        for i, p in enumerate(trace):
            rids.append(cl.submit(p, 6, seed=i))
            cl.step()
            _check(cl)
        _drive(cl)
        cl._harvest()
        st = cl.stats()
        results[aff] = (
            [list(map(int, cl.finished[r].tokens)) for r in rids],
            st["prefill_tokens_saved"] / max(1, st["prompt_tokens_total"]),
            st["tokens_generated"],
            st,
        )
    streams_off, hit_off, toks_off, _ = results[False]
    streams_on, hit_on, toks_on, st_on = results[True]
    assert streams_on == streams_off, "placement must never change tokens"
    assert toks_on == toks_off, "equal goodput"
    assert hit_on > hit_off, (hit_on, hit_off)
    assert st_on["prefix_affinity_hits"] > 0
    # the first request of each tenant can't hit anywhere — those are
    # the fallback admissions, counted separately
    assert st_on["prefix_affinity_hits"] + st_on["routed_fallback"] == len(
        trace
    )


def test_affinity_load_imbalance_cap(model):
    """A cache hit may justify a bounded load gap, never starvation:
    with ``affinity_max_imbalance=0`` a loaded replica is ineligible
    even when it holds the whole prefix; with the default cap the same
    submission routes to the cache."""
    prompt = np.asarray(
        jax.random.randint(jax.random.PRNGKey(3), (33,), 0, CFG.vocab_size)
    )
    filler = _prompts(1, base_len=6, stride=0)[0]

    def drive_case(cap):
        cl = ServingCluster(
            model, replicas=2, affinity=True,
            affinity_max_imbalance=cap, **BASE_KW,
        )
        cl.submit(prompt, 6, seed=0)
        _drive(cl)  # replica 0 now holds the prefix, both loads 0
        cl.submit(filler, 6, seed=1)  # backlog on replica 0 (tie-break)
        rid = cl.submit(prompt, 6, seed=2)
        return cl, cl._route[rid][0]

    cl0, routed_capped = drive_case(0)
    assert routed_capped == 1, "cap 0: the loaded cache replica is barred"
    assert cl0.routed_fallback >= 1
    cl4, routed_free = drive_case(4)
    assert routed_free == 0, "cap 4: the cache hit justifies the gap"
    assert cl4.prefix_affinity_hits >= 1


# ---------------------------------------------------------------------------
# slow tier: the full feature cross + eviction pressure mid-handoff
# ---------------------------------------------------------------------------

MATRIX_SLOW = (
    dict(prefix_cache=False),
    dict(prefix_cache=True, prefill_chunk=4),
    dict(kv_quant="int8"),
    dict(speculate=2),
    dict(speculate=2, temperature=0.8, top_k=12),
    dict(layer_scan="on"),
)


@pytest.mark.slow
@pytest.mark.parametrize(
    "extra", MATRIX_SLOW,
    ids=["cache-off", "chunk", "kvq", "spec", "spec-sampled", "scan"],
)
def test_disagg_matrix_bit_identical_across_splits(model, extra):
    """The full landing gate: cache x chunk x spec(greedy+sampled) x
    kv-quant x layer_scan, each bit-identical to the monolithic engine
    across every pool split."""
    prompts = _prompts(4, base_len=5, stride=2)
    kw = dict(BASE_KW, **extra)
    ref = _mono_ref(model, prompts, 8, **kw)
    for split in ((1, 1), (2, 1), (2, 2)):
        got, cl = _disagg_run(model, prompts, 8, split, **kw)
        assert got == ref, (split, extra)
        assert cl.stats()["handoffs"] >= len(prompts), (split, extra)


@pytest.mark.slow
def test_disagg_eviction_under_pressure_mid_handoff(model):
    """A page pool too small to hold every request forces evictions on
    both pools while handoffs are in flight: evicted decode slots
    re-prefill LOCALLY (a decode-class engine is a full engine), the
    invariants hold after every step, and the streams still equal the
    monolithic engine under the same pressure."""
    prompts = _prompts(4, base_len=9, stride=3)
    kw = dict(BASE_KW, page_size=4, num_pages=8)
    ref = _mono_ref(model, prompts, 10, **kw)
    got, cl = _disagg_run(model, prompts, 10, (1, 1), **kw)
    assert got == ref
    st = cl.stats()
    assert st["evictions"] > 0, "the pressure shape must actually evict"
    assert st["handoffs"] >= len(prompts)
