"""Ring attention (sequence parallelism) vs the full-attention oracle on the
simulated 8-device mesh."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from midgpt_tpu.config import ModelConfig
from midgpt_tpu.models.gpt import GPT
from midgpt_tpu.ops.attention import naive_attention
from midgpt_tpu.compat import shard_map
from midgpt_tpu.parallel.ring import ring_attention
from midgpt_tpu.parallel.sharding import axis_rules


def _qkv(key, b, h, hkv, t, c):
    k1, k2, k3 = jax.random.split(key, 3)
    return (
        jax.random.normal(k1, (b, h, t, c)),
        jax.random.normal(k2, (b, hkv, t, c)),
        jax.random.normal(k3, (b, hkv, t, c)),
    )


def test_ring_matches_full_attention(mesh8):
    q, k, v = _qkv(jax.random.PRNGKey(0), 2, 2, 2, 64, 16)
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh8))(q, k, v)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_gqa(mesh8):
    q, k, v = _qkv(jax.random.PRNGKey(1), 1, 4, 2, 64, 16)
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh8))(q, k, v)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_grads_match(mesh8):
    q, k, v = _qkv(jax.random.PRNGKey(2), 1, 2, 2, 32, 16)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh8) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(naive_attention(q, k, v, causal=True) ** 2)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gn = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, gn, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, err_msg=f"d{name}"
        )


def test_ring_rejects_ragged(mesh8):
    q, k, v = _qkv(jax.random.PRNGKey(3), 2, 2, 2, 31, 16)
    with pytest.raises(AssertionError):
        ring_attention(q, k, v, mesh8)


def test_model_with_ring_matches_naive(mesh8):
    """Full GPT forward with attn_impl='ring' under the mesh equals the
    single-device naive forward."""
    cfg = ModelConfig(
        block_size=64, vocab_size=64, n_layer=2, n_head=4, n_embd=32,
        dropout=0.0, attn_impl="naive", remat="none",
    )
    model = GPT.init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 64), 0, cfg.vocab_size)
    expected = model(tokens)

    cfg_ring = dataclasses.replace(cfg, attn_impl="ring")
    model_ring = dataclasses.replace(model, config=cfg_ring)
    tokens_g = jax.device_put(
        tokens, NamedSharding(mesh8, P(("replica", "fsdp"), "sequence"))
    )

    @jax.jit
    def fwd(m, t):
        with axis_rules(mesh8):
            return m(t)

    got = fwd(model_ring, tokens_g)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_ring_flash_matches_full(mesh8, pallas_interpret):
    """Flash-backed ring hops (Pallas kernel per chunk pair + streaming LSE
    merge) vs the full-attention oracle."""
    q, k, v = _qkv(jax.random.PRNGKey(4), 2, 2, 2, 256, 32)
    out = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mesh8, use_flash=True)
    )(q, k, v)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_flash_grads_match(mesh8, pallas_interpret):
    """AD through flash hops: the lse cotangent folds into the kernel
    backward (delta - dlse); gradients must match the full oracle."""
    q, k, v = _qkv(jax.random.PRNGKey(5), 1, 2, 2, 256, 32)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh8, use_flash=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(naive_attention(q, k, v, causal=True) ** 2)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gn = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, gn, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, err_msg=f"d{name}"
        )


def test_ring_flash_gqa(mesh8, pallas_interpret):
    q, k, v = _qkv(jax.random.PRNGKey(6), 1, 4, 2, 256, 32)
    out = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mesh8, use_flash=True)
    )(q, k, v)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_zigzag_ring_matches_full(mesh8):
    """Zigzag schedule (device i holds chunk pair (i, 2S-1-i); constant
    work per hop) must still be exact causal attention."""
    q, k, v = _qkv(jax.random.PRNGKey(7), 2, 2, 2, 64, 16)
    out = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mesh8, schedule="zigzag")
    )(q, k, v)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_zigzag_ring_grads_match(mesh8):
    q, k, v = _qkv(jax.random.PRNGKey(8), 1, 2, 2, 64, 16)

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_attention(q, k, v, mesh8, schedule="zigzag") ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(naive_attention(q, k, v, causal=True) ** 2)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gn = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, gn, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, err_msg=f"d{name}"
        )


def test_zigzag_ring_flash(mesh8, pallas_interpret):
    """Zigzag with flash hops: half-chunks of 128 through the Pallas
    kernel."""
    q, k, v = _qkv(jax.random.PRNGKey(9), 1, 4, 2, 512, 32)
    out = jax.jit(
        lambda q, k, v: ring_attention(
            q, k, v, mesh8, schedule="zigzag", use_flash=True
        )
    )(q, k, v)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_zigzag_rejects_odd_chunking(mesh8):
    q, k, v = _qkv(jax.random.PRNGKey(10), 1, 2, 2, 34, 16)
    with pytest.raises(AssertionError):
        ring_attention(q, k, v, mesh8, schedule="zigzag")


def test_zigzag_ring_gqa_naive(mesh8):
    q, k, v = _qkv(jax.random.PRNGKey(11), 1, 4, 2, 64, 16)
    out = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mesh8, schedule="zigzag")
    )(q, k, v)
    ref = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_zigzag_relayout_matches_index_oracle(mesh8):
    """The shard-local ppermute relayout (r4 — replaces a global jnp.take
    that GSPMD lowered to a full-T all-gather per device) must equal the
    index-permutation oracle exactly, and invert cleanly."""
    from midgpt_tpu.parallel.ring import (
        _zigzag_order,
        _zigzag_relayout_in,
        _zigzag_relayout_out,
    )

    s = mesh8.shape["sequence"]
    t = 8 * s
    x = jax.random.normal(jax.random.PRNGKey(5), (2, 2, t, 4))
    xs = jax.device_put(x, NamedSharding(mesh8, P(None, None, "sequence")))

    relayout_in = jax.jit(
        shard_map(
            lambda a: _zigzag_relayout_in(a, "sequence", s),
            mesh=mesh8,
            in_specs=P(None, None, "sequence"),
            out_specs=P(None, None, "sequence"),
            check_vma=False,
        )
    )
    roundtrip = jax.jit(
        shard_map(
            lambda a: _zigzag_relayout_out(
                _zigzag_relayout_in(a, "sequence", s), "sequence", s
            ),
            mesh=mesh8,
            in_specs=P(None, None, "sequence"),
            out_specs=P(None, None, "sequence"),
            check_vma=False,
        )
    )
    idx, _ = _zigzag_order(t, s)
    np.testing.assert_array_equal(
        np.asarray(relayout_in(xs)), np.asarray(jnp.take(x, idx, axis=2))
    )
    np.testing.assert_array_equal(np.asarray(roundtrip(xs)), np.asarray(x))


def _dropout_dense_oracle(q, k, v, seed, rate):
    """Dense causal attention with the kernels' counter-hash keep mask at
    GLOBAL coordinates (ops/flash.dropout_mask_reference) — what a
    single-device flash_attention_dropout call computes, evaluated
    naively."""
    import math

    from midgpt_tpu.ops.flash import dropout_mask_reference

    b, h, t, c = q.shape
    hkv = k.shape[1]
    groups = h // hkv
    qg = q.reshape(b, hkv, groups, t, c)
    z = jnp.einsum(
        "bkgqc,bkjc->bkgqj", qg, k, preferred_element_type=jnp.float32
    ) / math.sqrt(c)
    causal = jnp.tril(jnp.ones((t, t), bool))
    z = jnp.where(causal, z, -1e30)
    p = jax.nn.softmax(z, axis=-1)
    keepm = dropout_mask_reference(seed, b, h, t, rate).reshape(
        b, hkv, groups, t, t
    )
    p = jnp.where(keepm, p / (1.0 - rate), 0.0)
    out = jnp.einsum("bkgqj,bkjc->bkgqc", p.astype(v.dtype), v)
    return out.reshape(b, h, t, c)


def test_ring_dropout_matches_single_device_mask(mesh8):
    """Ring attention dropout (r5): every hop anchors the in-kernel hash at
    its global (row, col) offsets, so the full ring pass must equal a
    SINGLE-DEVICE dropout call with the same seed — same mask, same math
    (VERDICT r4 Weak #8: dropout was asserted away under ring)."""
    q, k, v = _qkv(jax.random.PRNGKey(7), 2, 2, 2, 64, 16)
    seed = jnp.int32(12345)
    out = jax.jit(
        lambda q, k, v: ring_attention(
            q, k, v, mesh8, use_flash=False,
            dropout_rate=0.3, dropout_seed=seed,
        )
    )(q, k, v)
    ref = _dropout_dense_oracle(q, k, v, seed, 0.3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_dropout_gqa(mesh8):
    q, k, v = _qkv(jax.random.PRNGKey(8), 1, 4, 2, 64, 16)
    seed = jnp.int32(-987)
    out = jax.jit(
        lambda q, k, v: ring_attention(
            q, k, v, mesh8, use_flash=False,
            dropout_rate=0.2, dropout_seed=seed,
        )
    )(q, k, v)
    ref = _dropout_dense_oracle(q, k, v, seed, 0.2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_flash_dropout_matches_oracle(mesh8, pallas_interpret):
    """The flash backend of ring dropout: per-hop
    flash_attention_dropout_lse with global offsets == dense oracle."""
    q, k, v = _qkv(jax.random.PRNGKey(9), 1, 2, 2, 64, 16)
    seed = jnp.int32(4242)
    out = jax.jit(
        lambda q, k, v: ring_attention(
            q, k, v, mesh8, use_flash=True,
            dropout_rate=0.25, dropout_seed=seed,
        )
    )(q, k, v)
    ref = _dropout_dense_oracle(q, k, v, seed, 0.25)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)


def test_ring_dropout_grads_flow(mesh8):
    """d/dq of the ring-dropout loss is finite and nonzero (the custom
    VJP regenerates the mask in the backward kernels)."""
    q, k, v = _qkv(jax.random.PRNGKey(10), 1, 2, 2, 64, 16)
    seed = jnp.int32(55)

    def loss(q, k, v):
        return jnp.sum(
            ring_attention(
                q, k, v, mesh8, use_flash=False,
                dropout_rate=0.3, dropout_seed=seed,
            )
            ** 2
        )

    g = jax.jit(jax.grad(loss))(q, k, v)
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).max()) > 0


def test_model_ring_dropout_integration(mesh8):
    """GPT forward with attn_impl='ring' + dropout>0 non-deterministic:
    runs (the r4 assert is gone), is deterministic per key, varies across
    keys, and a zigzag schedule degrades to standard instead of failing."""
    cfg = ModelConfig(
        block_size=64, vocab_size=128, n_layer=2, n_head=4, n_embd=32,
        dropout=0.3, attn_impl="ring", ring_schedule="zigzag", remat="none",
    )
    model = GPT.init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, 128)

    def fwd(key):
        with axis_rules(mesh8):
            return jax.jit(
                lambda m, t, k: m(t, key=k, deterministic=False)
            )(model, tokens, key)

    a = fwd(jax.random.PRNGKey(2))
    b = fwd(jax.random.PRNGKey(2))
    c = fwd(jax.random.PRNGKey(3))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.allclose(np.asarray(a), np.asarray(c))


def test_ring_flash_dropout_grads_match_naive_backend(mesh8, pallas_interpret):
    """The dlse + dropout backward combination (ring flash dropout) —
    the one path no other test reaches: _core_vjp_bwd feeds BOTH the
    streaming-LSE cotangent and the regenerated global-coordinate mask
    into _flash_backward. Grads must match the naive ring backend, whose
    backward is plain autodiff of the same math."""
    q, k, v = _qkv(jax.random.PRNGKey(11), 1, 2, 2, 64, 16)
    seed = jnp.int32(777)

    def loss(backend_flash):
        def f(q, k, v):
            return jnp.sum(
                ring_attention(
                    q, k, v, mesh8, use_flash=backend_flash,
                    dropout_rate=0.25, dropout_seed=seed,
                )
                ** 2
            )

        return f

    gf = jax.jit(jax.grad(loss(True), argnums=(0, 1, 2)))(q, k, v)
    gn = jax.jit(jax.grad(loss(False), argnums=(0, 1, 2)))(q, k, v)
    for a, b, name in zip(gf, gn, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, err_msg=f"d{name}"
        )
