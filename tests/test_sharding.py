"""Parallel-layer tests on the simulated 8-device CPU mesh: mesh sizing,
param rule resolution, sharded-vs-single-device forward parity, host data
feed."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from midgpt_tpu.config import MeshConfig, ModelConfig
from midgpt_tpu.models.gpt import GPT, GPT_PARAM_RULES
from midgpt_tpu.parallel.mesh import create_mesh, single_device_mesh
from midgpt_tpu.parallel.sharding import (
    axis_rules,
    constrain_params,
    make_global_array,
    match_param_spec,
    param_shardings,
    shard_act,
)
from midgpt_tpu.pytree import tree_paths

CFG = ModelConfig(
    block_size=32, vocab_size=128, n_layer=2, n_head=4, n_embd=32,
    dropout=0.0, attn_impl="naive", remat="none",
)


def test_mesh_config_sizes():
    assert MeshConfig(replica=1, fsdp=-1, sequence=1, tensor=2).sizes(8) == (1, 1, 4, 1, 2)
    assert MeshConfig(replica=2, fsdp=2, sequence=1, tensor=2).sizes(8) == (1, 2, 2, 1, 2)
    with pytest.raises(AssertionError):
        MeshConfig(replica=3, fsdp=-1).sizes(8)  # 8 % 3 != 0


def test_create_mesh_8dev(mesh8):
    assert mesh8.axis_names == ("pipeline", "replica", "fsdp", "sequence", "tensor")
    assert mesh8.devices.size == 8


def test_param_rules_cover_model(mesh8):
    model = GPT.init(jax.random.PRNGKey(0), CFG)
    shardings = param_shardings(mesh8, model, GPT_PARAM_RULES)
    flat = dict(tree_paths(model))
    sflat = dict(tree_paths(shardings))
    # wqkv: [L, D, F] -> (None, fsdp, tensor)
    assert sflat["blocks/attn/wqkv/weight"].spec == P(None, "fsdp", "tensor")
    assert sflat["blocks/attn/wo/weight"].spec == P(None, "tensor", "fsdp")
    assert sflat["wte/weight"].spec == P("tensor", "fsdp")
    assert sflat["lm_head/weight"].spec == P("fsdp", "tensor")
    # norm scales replicated
    assert sflat["blocks/attn/q_norm/weight"].spec == P(None, None)
    for path, leaf in flat.items():
        assert len(sflat[path].spec) <= leaf.ndim


def test_match_param_spec_default_replicated():
    assert match_param_spec("unknown/leaf", GPT_PARAM_RULES) == P()


def test_sharded_forward_matches_single_device(mesh8):
    """FSDP x TP x SP sharded forward == unsharded forward (the key GSPMD
    correctness property, SURVEY.md 4)."""
    model = GPT.init(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, CFG.vocab_size)

    expected = model(tokens)  # single device, no constraints

    shardings = param_shardings(mesh8, model, GPT_PARAM_RULES)
    model_sharded = jax.device_put(model, shardings)
    tokens_g = jax.device_put(
        tokens, NamedSharding(mesh8, P(("replica", "fsdp"), None))
    )

    @jax.jit
    def fwd(m, t):
        with axis_rules(mesh8):
            return m(t)

    got = fwd(model_sharded, tokens_g)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_constrain_params_inside_jit(mesh8):
    model = GPT.init(jax.random.PRNGKey(0), CFG)

    @jax.jit
    def reshard(m):
        return constrain_params(m, mesh8, GPT_PARAM_RULES)

    out = reshard(model)
    flat = dict(tree_paths(out))
    got = flat["blocks/attn/wqkv/weight"].sharding
    assert got.spec == P(None, "fsdp", "tensor")


def test_shard_act_noop_outside_scope():
    x = jnp.ones((4, 8))
    y = shard_act(x, "batch", "embed")
    assert y is x


def test_shard_act_unknown_axis_raises(mesh8):
    x = jnp.ones((4, 8))
    with axis_rules(mesh8):
        with pytest.raises(AssertionError):
            shard_act(x, "batch", "bogus_axis")


def test_make_global_array(mesh8):
    """Single-process case: local batch == global batch."""
    local = np.arange(8 * 4, dtype=np.int32).reshape(8, 4)
    arr = make_global_array(local, mesh8, P(("replica", "fsdp"), None))
    assert arr.shape == (8, 4)
    np.testing.assert_array_equal(np.asarray(arr), local)


def test_single_device_mesh_runs_sharded_code():
    mesh1 = single_device_mesh()
    model = GPT.init(jax.random.PRNGKey(0), CFG)
    shardings = param_shardings(mesh1, model, GPT_PARAM_RULES)
    model1 = jax.device_put(model, shardings)
    tokens = jnp.zeros((2, 8), dtype=jnp.int32)
    with axis_rules(mesh1):
        logits = model1(tokens)
    assert logits.shape == (2, 8, CFG.vocab_size)
