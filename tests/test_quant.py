"""Int8 quantized serving weight path (midgpt_tpu.quant): per-channel
quantize/dequantize round-trip bounds and scale-shape units, the po2
bitwise epilogue contract at the layer and whole-engine level (quant
engine greedy token-identical to the bf16/f32 engine running the
dequantized weights, across the serving exactness matrix), real int8
accuracy bounds on a trained fixture checkpoint, checkpoint conversion
round-trip, and the no-dequant-materialization audit."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from midgpt_tpu.config import ModelConfig
from midgpt_tpu.models.gpt import GPT
from midgpt_tpu.models.layers import Linear
from midgpt_tpu.pytree import cast_floating
from midgpt_tpu.quant import (
    QuantLinear,
    dequantize,
    dequantize_model,
    is_quantized,
    quant_weight_shapes,
    quantize_model,
    quantize_per_channel,
)
from midgpt_tpu.serving import ServingEngine, generate_served

CFG = ModelConfig(
    block_size=64, vocab_size=96, n_layer=2, n_head=4, n_embd=32,
    dropout=0.0, attn_impl="naive", remat="none",
)


def _model(seed=0):
    return GPT.init(jax.random.PRNGKey(seed), CFG)


def _prompts(n, base_len=5, stride=3):
    return [
        np.asarray(
            jax.random.randint(
                jax.random.PRNGKey(100 + i), (base_len + stride * i,), 0,
                CFG.vocab_size,
            )
        )
        for i in range(n)
    ]


@pytest.fixture(scope="module")
def quant_pair():
    """(qmodel, dmodel): the quantized model and the full-precision model
    it encodes — the pair the po2 exactness contract relates."""
    qm = quantize_model(_model())
    return qm, dequantize_model(qm)


@pytest.fixture(scope="module")
def trained_case():
    """The accuracy fixture checkpoint: a tiny GPT trained ~200 Adam
    steps to memorize a tiled 17-token pattern. Random-init logits are
    near-tied noise (quantization flips ~2-4% of their argmaxes no
    matter the model size), which says nothing about serving a real
    checkpoint; a trained model has the sharp margins real traffic sees,
    so the >= 99% argmax-agreement bar is meaningful."""
    rng = np.random.default_rng(0)
    pat = rng.integers(0, CFG.vocab_size, 17)
    corpus = np.tile(pat, 200)
    model = _model()
    tx = optax.adam(3e-3)
    opt = tx.init(model)

    def loss_fn(m, x, y):
        lg = m(x)
        return optax.softmax_cross_entropy_with_integer_labels(
            lg, y
        ).mean()

    @jax.jit
    def step(m, o, x, y):
        _, g = jax.value_and_grad(loss_fn)(m, x, y)
        up, o = tx.update(g, o)
        return optax.apply_updates(m, up), o

    b, t = 8, CFG.block_size
    for _ in range(200):
        starts = rng.integers(0, len(corpus) - t - 1, b)
        x = jnp.asarray(np.stack([corpus[s : s + t] for s in starts]))
        y = jnp.asarray(np.stack([corpus[s + 1 : s + t + 1] for s in starts]))
        model, opt = step(model, opt, x, y)
    return model, corpus


# ---------------------------------------------------------------------------
# quantize/dequantize units (model-independent)
# ---------------------------------------------------------------------------


def test_scale_shapes_and_output_axis():
    """Scales index the OUTPUT channel (last axis), one row per stacked
    layer; rescaling one output column moves only its own scale."""
    w = jax.random.normal(jax.random.PRNGKey(0), (3, 16, 24))
    q, s = quantize_per_channel(w)
    assert q.shape == w.shape and q.dtype == jnp.int8
    assert s.shape == (3, 24) and s.dtype == jnp.float32
    w2 = w.at[:, :, 7].multiply(64.0)
    _, s2 = quantize_per_channel(w2)
    changed = np.nonzero(~np.isclose(np.asarray(s), np.asarray(s2)))
    assert set(changed[1].tolist()) == {7}
    # unstacked [in, out] works identically
    q1, s1 = quantize_per_channel(w[0])
    assert q1.shape == (16, 24) and s1.shape == (24,)
    np.testing.assert_array_equal(np.asarray(q1), np.asarray(q[0]))


def test_roundtrip_error_bound_and_po2_scales():
    w = jax.random.normal(jax.random.PRNGKey(1), (32, 48))
    for mode in ("po2", "absmax"):
        q, s = quantize_per_channel(w, mode=mode)
        err = jnp.abs(dequantize(q, s) - w)
        assert bool(jnp.all(err <= s[None, :] / 2 + 1e-7)), mode
    q, s = quantize_per_channel(w, mode="po2")
    # po2 scales ARE powers of two (the bitwise-epilogue precondition)
    assert bool(jnp.all(jnp.exp2(jnp.round(jnp.log2(s))) == s))
    # ... and still cover the range: no clipping beyond rounding
    assert bool(jnp.all(s >= jnp.max(jnp.abs(w), axis=0) / 127.0))


def test_all_zero_channel():
    w = jnp.zeros((8, 4)).at[:, 1].set(
        jax.random.normal(jax.random.PRNGKey(2), (8,))
    )
    for mode in ("po2", "absmax"):
        q, s = quantize_per_channel(w, mode=mode)
        assert bool(jnp.all(q[:, 0] == 0)) and float(s[0]) == 1.0
        np.testing.assert_array_equal(
            np.asarray(dequantize(q, s)[:, 0]), np.zeros(8)
        )


def test_constant_channel():
    """A constant channel maps to +-127 on the absmax grid (near-exact
    round-trip) and stays within scale/2 on the po2 grid."""
    w = jnp.concatenate(
        [
            jnp.full((16, 1), -0.73),
            jax.random.normal(jax.random.PRNGKey(3), (16, 3)),
        ],
        axis=1,
    )
    q, s = quantize_per_channel(w, mode="absmax")
    assert bool(jnp.all(q[:, 0] == -127))
    np.testing.assert_allclose(
        np.asarray(dequantize(q, s)[:, 0]), -0.73, rtol=1e-6
    )
    q, s = quantize_per_channel(w, mode="po2")
    assert bool(jnp.all(jnp.abs(dequantize(q, s)[:, 0] + 0.73) <= s[0] / 2))


def test_identity_mode_exact_on_integer_weights():
    w = jnp.asarray(
        jax.random.randint(jax.random.PRNGKey(4), (12, 8), -127, 128),
        jnp.float32,
    )
    q, s = quantize_per_channel(w, mode="identity")
    assert bool(jnp.all(s == 1.0))
    np.testing.assert_array_equal(np.asarray(dequantize(q, s)), np.asarray(w))
    x = jax.random.normal(jax.random.PRNGKey(5), (3, 12))
    lhs = QuantLinear(weight=q, scale=s)(x)
    np.testing.assert_array_equal(np.asarray(lhs), np.asarray(x @ w))


def test_quant_linear_bitwise_equals_dequant_matmul():
    """The epilogue contract at layer granularity: (x @ q) * s is
    BITWISE x @ dequant(q, s) with po2 scales — in f32 and in bf16."""
    lin = Linear.init(jax.random.PRNGKey(6), 32, 48)
    q, s = quantize_per_channel(lin.weight)
    ql = QuantLinear(weight=q, scale=s)
    dw = dequantize(q, s)
    for dt in (jnp.float32, jnp.bfloat16):
        x = jax.random.normal(jax.random.PRNGKey(7), (4, 32)).astype(dt)
        lhs = jax.jit(lambda x_: ql(x_))(x_=x)
        rhs = jax.jit(lambda x_: x_ @ dw.astype(dt))(x_=x)
        np.testing.assert_array_equal(np.asarray(lhs), np.asarray(rhs))


# ---------------------------------------------------------------------------
# model conversion
# ---------------------------------------------------------------------------


def test_quantize_model_structure(quant_pair):
    qm, dm = quant_pair
    assert is_quantized(qm) and not is_quantized(dm)
    for leaf in (
        qm.blocks.attn.wqkv, qm.blocks.attn.wo, qm.blocks.mlp.w_up,
        qm.blocks.mlp.w_down, qm.lm_head,
    ):
        assert isinstance(leaf, QuantLinear)
        assert leaf.weight.dtype == jnp.int8
    # the embedding GATHER stays full-precision; the head MATMUL streams
    # int8 even when tied (materialized from wte.T)
    assert qm.wte.weight.dtype == jnp.float32
    tied = GPT.init(
        jax.random.PRNGKey(0), dataclasses.replace(CFG, tie_embeddings=True)
    )
    qt = quantize_model(tied)
    assert isinstance(qt.lm_head, QuantLinear)
    with pytest.raises(AssertionError):
        quantize_model(qm)  # already quantized
    with pytest.raises(AssertionError):
        qm.head_weight(jnp.float32)  # would materialize the dequant


def test_po2_quantize_dequantize_is_a_fixed_point(quant_pair):
    """quantize(dequantize(Q)) == Q leaf-bitwise for po2 scales: the
    dequantized model carries exactly the information of the quantized
    one, so conversion is idempotent — no drift across save/convert
    cycles."""
    qm, dm = quant_pair
    qm2 = quantize_model(dm)
    for a, b in zip(jax.tree.leaves(qm), jax.tree.leaves(qm2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_quant_weight_shapes(quant_pair):
    qm, _ = quant_pair
    shapes = quant_weight_shapes(qm)
    l, d = CFG.n_layer, CFG.n_embd
    qkv_out = (CFG.n_head + 2 * CFG.kv_heads) * CFG.head_dim
    assert (l, d, qkv_out) in shapes  # stacked wqkv
    assert (d, qkv_out) in shapes  # its static per-layer slice
    assert (d, CFG.vocab_size) in shapes  # lm head


# ---------------------------------------------------------------------------
# engine exactness matrix: quant engine vs the bf16/f32 engine running
# the dequantized weights (the po2 contract, end to end)
# ---------------------------------------------------------------------------


def test_quant_engine_token_identity_matrix(quant_pair):
    """Acceptance: the quantized engine's greedy output is token-
    identical to the full-precision engine running dequantize_model(Q),
    across prefix-cache on/off x chunked vs monolithic prefill x
    speculation — mid-run admission included (more requests than
    slots)."""
    qm, dm = quant_pair
    prompts = _prompts(4)
    lens = [9, 12, 7, 10]

    def run(model, quant, prefix_cache, prefill_chunk, speculate):
        eng = ServingEngine(
            model, slots=2, page_size=8, window=4, temperature=0.0,
            cache_dtype=jnp.float32, prefix_cache=prefix_cache,
            prefill_chunk=prefill_chunk, speculate=speculate, quant=quant,
        )
        rids = [eng.submit(p, n) for p, n in zip(prompts, lens)]
        fin = eng.run()
        eng.alloc.check()
        assert eng.alloc.held_pages == 0
        return [fin[r].tokens for r in rids]

    base = run(dm, None, True, None, 0)
    for variant in [(True, None, 0), (False, 8, 0), (True, 8, 4)]:
        got = run(qm, None, *variant)
        assert got == base, f"variant {variant} diverged"
    # the engine-side knob quantizes the given full-precision model to
    # the same pytree (po2 fixed point) — same streams again
    assert run(dm, "int8", True, None, 0) == base


def test_quant_engine_identity_under_eviction_and_bf16_cache(quant_pair):
    """Quant x page pressure (evict/re-admit through the prefix cache)
    and quant x bf16 KV pool: the po2 contract holds in bf16 too, so
    the streams stay identical in the serving dtype configuration."""
    qm, dm = quant_pair
    prompts = _prompts(4, base_len=6, stride=0)
    n_new = 16

    def run(model, **kw):
        eng = ServingEngine(
            model, slots=2, page_size=8, window=4, temperature=0.0,
            prefix_cache=True, **kw,
        )
        rids = [eng.submit(p, n_new) for p in prompts]
        fin = eng.run()
        return [fin[r].tokens for r in rids], eng

    base, _ = run(dm, cache_dtype=jnp.float32, num_pages=5)
    got, eng = run(qm, cache_dtype=jnp.float32, num_pages=5)
    assert eng.evictions > 0, "trace was sized to force eviction"
    assert got == base
    base_bf, _ = run(dm, cache_dtype=jnp.bfloat16)
    got_bf, _ = run(qm, cache_dtype=jnp.bfloat16)
    assert got_bf == base_bf


def test_generate_served_quant_knob(quant_pair):
    _, dm = quant_pair
    prompts = _prompts(2)
    base = generate_served(
        dm, prompts, 8, window=4, page_size=8, cache_dtype=jnp.float32
    )
    got = generate_served(
        dm, prompts, 8, window=4, page_size=8, cache_dtype=jnp.float32,
        quant="int8",
    )
    for a, b in zip(base, got):
        np.testing.assert_array_equal(a, b)
    with pytest.raises(AssertionError):
        ServingEngine(dm, slots=1, quant="int4")


# ---------------------------------------------------------------------------
# real int8 accuracy on the trained fixture checkpoint
# ---------------------------------------------------------------------------


def test_real_int8_accuracy_bounds_on_trained_fixture(trained_case):
    """Acceptance: >= 99% greedy argmax agreement over >= 128 token
    positions and bounded logit error between the f32 fixture checkpoint
    and its int8 quantization — teacher-forced on held-out crops of the
    training corpus (the distribution the checkpoint actually models)."""
    model, corpus = trained_case
    qm = quantize_model(model)
    rng = np.random.default_rng(7)
    t = CFG.block_size
    starts = rng.integers(0, len(corpus) - t, 4)
    toks = jnp.asarray(np.stack([corpus[s : s + t] for s in starts]))
    lf = jax.jit(lambda m, x: m(x))(model, toks)
    lq = jax.jit(lambda m, x: m(x))(qm, toks)
    n_pos = int(toks.size)
    assert n_pos >= 128
    agree = float(jnp.mean(jnp.argmax(lq, -1) == jnp.argmax(lf, -1)))
    assert agree >= 0.99, f"argmax agreement {agree:.4f} over {n_pos} pos"
    max_err = float(jnp.max(jnp.abs(lq - lf)))
    rel = max_err / float(jnp.std(lf))
    assert rel <= 0.25, f"max logit error {max_err:.4f} = {rel:.3f} x std"


def test_quant_engine_serves_trained_fixture_greedily(trained_case):
    """End-to-end: the int8 engine generates >= 128 greedy tokens from
    the fixture checkpoint with >= 99% agreement against the f32 engine
    (the engines' streams may legitimately differ where the quantized
    model IS a different function — this bounds how much)."""
    model, corpus = trained_case
    prompt = np.asarray(corpus[:24], np.int32)
    n_new = 32
    base = generate_served(
        model, [prompt] * 4, n_new, window=4, page_size=8,
        cache_dtype=jnp.float32,
    )
    got = generate_served(
        model, [prompt] * 4, n_new, window=4, page_size=8,
        cache_dtype=jnp.float32, quant="int8",
    )
    total = sum(len(b) for b in base)
    same = sum(
        int(x == y) for b, g in zip(base, got) for x, y in zip(b, g)
    )
    assert total >= 128
    assert same / total >= 0.99, f"{same}/{total} tokens agree"


# ---------------------------------------------------------------------------
# checkpoint conversion round trip
# ---------------------------------------------------------------------------


def test_quantize_ckpt_roundtrip(tmp_path, quant_pair):
    """Checkpointer saves/restores the quantized pytree (int8 leaves and
    all) via the params_q8 item, and has_item picks the right loader."""
    from midgpt_tpu.checkpoint import Checkpointer
    from midgpt_tpu.quant import QUANT_ITEM, restore_quantized

    qm, _ = quant_pair
    d = str(tmp_path / "run-int8")
    ck = Checkpointer(d, save_interval_steps=1, async_save=False)
    ck.save(5, {QUANT_ITEM: qm}, {"step": 5, "quant_mode": "po2"}, force=True)
    ck.close()
    ck2 = Checkpointer(d, save_interval_steps=1)
    assert ck2.has_item(QUANT_ITEM) and not ck2.has_item("params")
    got = restore_quantized(ck2, CFG)
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(qm)):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# no-dequant-materialization audit
# ---------------------------------------------------------------------------


def test_no_dequant_materialization_rule_on_fixtures():
    """Rule semantics on canned HLO (jax-free, like the other rule
    units): a fused program passes; a dequantized-before-compile
    program, a smuggled full-precision weight param, a weight-shaped
    scale multiply, and a baked-in weight constant each fail."""
    from midgpt_tpu.analysis.hlo import MeshInfo
    from midgpt_tpu.analysis.rules import (
        NoDequantMaterialization,
        StepAnalysis,
    )

    mesh = MeshInfo(axis_names=("replica",), axis_sizes=(1,))
    wshapes = {(768, 2304)}
    rule = NoDequantMaterialization(wshapes)

    def analyze(hlo):
        return rule.check(StepAnalysis.from_text(hlo, mesh))

    good = """HloModule m, entry_computation_layout={(bf16[4,768]{1,0}, s8[768,2304]{1,0}, f32[2304]{0})->bf16[4,2304]{1,0}}
ENTRY %main (p0: bf16[4,768], p1: s8[768,2304], p2: f32[2304]) -> bf16[4,2304] {
  %dot = f32[4,2304]{1,0} dot(f32[4,768]{1,0} %a, f32[768,2304]{1,0} %b)
  %mul = bf16[4,2304]{1,0} multiply(bf16[4,2304]{1,0} %c, bf16[4,2304]{1,0} %d)
}
"""
    assert analyze(good) == []
    pre_dequant = good.replace("s8[768,2304]", "bf16[768,2304]")
    found = analyze(pre_dequant)
    assert len(found) == 2  # no s8 param AND an f-precision weight param
    weight_mul = good.replace(
        "%mul = bf16[4,2304]{1,0} multiply(bf16[4,2304]{1,0} %c, bf16[4,2304]{1,0} %d)",
        "%mul = f32[768,2304]{1,0} multiply(f32[768,2304]{1,0} %c, f32[768,2304]{1,0} %d)",
    )
    assert any("weight shape" in v.message for v in analyze(weight_mul))
    baked = good + "  %k = f32[768,2304]{1,0} constant({...})\n"
    assert any("constant" in v.message for v in analyze(baked))


@pytest.mark.slow
def test_quant_serving_audits_pass():
    """The three QUANTIZED serving programs pass donation-intact +
    no-host-sync + no-dequant-materialization (the CI serving-audit
    gate): int8 weights enter as s8 entry parameters and no
    full-precision weight matrix is streamed, baked in, or
    materialized by a weight-shaped scale multiply."""
    from midgpt_tpu.analysis.harness import (
        audit_decode_window,
        audit_prefill_chunk,
        audit_verify_program,
    )
    from midgpt_tpu.config import get_config

    cfg = get_config("shakespeare_char")
    for fn, kw in (
        (audit_decode_window, dict(slots=2, window=2, page_size=8)),
        (audit_prefill_chunk, dict(chunk_len=32, page_size=8)),
        (audit_verify_program, dict(slots=2, spec_len=2, page_size=8)),
    ):
        analysis, report = fn(cfg, quant=True, **kw)
        assert report.ok, report.violations
        assert any(
            r.rule == "no-dequant-materialization" for r in report.results
        )
        assert len({e.param_number for e in analysis.aliases}) >= 3
