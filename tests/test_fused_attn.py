"""Projection-natural fused attention (QK-LN + RoPE + flash) parity, via the
Pallas CPU interpreter. Real-TPU parity is exercised by
scripts/smoke_fused_attn.py (committed artifact) and bench.py."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _interpret_mode(pallas_interpret):
    yield


def _setup(b, t, h, hkv, c, dtype=jnp.float32, seed=0):
    from midgpt_tpu.models.layers import rope_tables

    ks = jax.random.split(jax.random.PRNGKey(seed), 6)
    q = jax.random.normal(ks[0], (b, t, h * c), dtype)
    k = jax.random.normal(ks[1], (b, t, hkv * c), dtype)
    v = jax.random.normal(ks[2], (b, t, hkv * c), dtype)
    wq = 1.0 + 0.1 * jax.random.normal(ks[3], (c,), jnp.float32)
    wk = 1.0 + 0.1 * jax.random.normal(ks[4], (c,), jnp.float32)
    sin_h, cos_h = rope_tables(c, t)
    # duplicated-interleaved [T, C] tables (what the kernel consumes)
    sin = jnp.asarray(np.repeat(sin_h, 2, axis=-1), jnp.float32)
    cos = jnp.asarray(np.repeat(cos_h, 2, axis=-1), jnp.float32)
    return q, k, v, wq, wk, sin, cos


@pytest.mark.parametrize(
    "h,hkv,c,t,blk",
    [
        (4, 4, 64, 256, 128),  # MHA C=64 -> two heads per 128-lane block
        (4, 2, 128, 256, 128),  # GQA C=128 -> one head per block
        (2, 2, 64, 256, 256),  # single k block (nk == 1)
    ],
)
def test_fused_forward_parity(h, hkv, c, t, blk):
    from midgpt_tpu.ops.fused_attn import (
        fused_attention,
        fused_attention_reference,
        supported,
    )

    assert supported(h, hkv, c)
    q, k, v, wq, wk, sin, cos = _setup(2, t, h, hkv, c)
    out = fused_attention(
        q, k, v, wq, wk, sin, cos, h, hkv, True, blk, blk
    )
    ref = fused_attention_reference(q, k, v, wq, wk, sin, cos, h, hkv)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize(
    "h,hkv,c,t,blk",
    [
        (4, 4, 64, 256, 128),
        (4, 2, 128, 256, 128),
    ],
)
def test_fused_grad_parity(h, hkv, c, t, blk):
    from midgpt_tpu.ops.fused_attn import (
        fused_attention,
        fused_attention_reference,
    )

    q, k, v, wq, wk, sin, cos = _setup(2, t, h, hkv, c, seed=1)
    w_out = jax.random.normal(jax.random.PRNGKey(9), (h * c,), jnp.float32)

    def loss_fused(q, k, v, wq, wk):
        out = fused_attention(q, k, v, wq, wk, sin, cos, h, hkv, True, blk, blk)
        return jnp.sum(out * w_out)

    def loss_ref(q, k, v, wq, wk):
        out = fused_attention_reference(q, k, v, wq, wk, sin, cos, h, hkv)
        return jnp.sum(out * w_out)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3, 4))(q, k, v, wq, wk)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(q, k, v, wq, wk)
    for name, a, b in zip(["dq", "dk", "dv", "dwq", "dwk"], gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4, err_msg=name
        )


def test_supported_matrix():
    from midgpt_tpu.ops.fused_attn import supported

    assert supported(12, 12, 64)  # 124M MHA
    assert supported(32, 8, 128)  # llama GQA
    assert not supported(12, 6, 64)  # GQA at C=64: pair breaks kv mapping
    assert not supported(11, 11, 64)  # odd head count can't pair
    assert not supported(12, 12, 96)  # non-128, non-64 head dim


def test_model_fused_matches_naive():
    """GPT forward+grad with attn_impl='fused' vs 'naive' — the integration
    point in models/gpt.py Attention._fused_call."""
    import dataclasses

    from midgpt_tpu.config import ModelConfig
    from midgpt_tpu.models.gpt import GPT

    cfg = ModelConfig(
        block_size=128, vocab_size=96, n_layer=2, n_head=4, n_embd=256,
        dropout=0.0, attn_impl="naive", remat="none", qk_norm=True,
    )
    model = GPT.init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, 96)

    logits_naive = model(tokens)
    model_fused = dataclasses.replace(
        model, config=dataclasses.replace(cfg, attn_impl="fused")
    )
    logits_fused = model_fused(tokens)
    np.testing.assert_allclose(
        np.asarray(logits_fused), np.asarray(logits_naive), atol=2e-4, rtol=1e-4
    )

    def loss(m, toks):
        lg = m(toks)
        return jnp.mean((lg - jax.lax.stop_gradient(lg) + 1.0) ** 2) + jnp.mean(
            lg**2
        )

    g_naive = jax.grad(loss)(model, tokens)
    g_fused = jax.grad(loss)(model_fused, tokens)
    flat_n = jax.tree.leaves(g_naive)
    flat_f = jax.tree.leaves(g_fused)
    for a, b in zip(flat_f, flat_n):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-3
        )


@pytest.mark.parametrize("h,hkv,c", [(4, 4, 64), (4, 2, 128)])
def test_packed_qkv_matches_split(h, hkv, c):
    """fused_attention_qkv (lane-offset reads from the packed projection)
    must equal the split-input entry, values and grads."""
    from midgpt_tpu.ops.fused_attn import fused_attention, fused_attention_qkv

    t = 256
    q, k, v, wq, wk, sin, cos = _setup(2, t, h, hkv, c, seed=3)
    qkv = jnp.concatenate([q, k, v], axis=-1)

    out_split = fused_attention(q, k, v, wq, wk, sin, cos, h, hkv)
    out_packed = fused_attention_qkv(qkv, wq, wk, sin, cos, h, hkv)
    np.testing.assert_allclose(
        np.asarray(out_packed), np.asarray(out_split), atol=1e-6
    )

    w_out = jax.random.normal(jax.random.PRNGKey(7), (h * c,), jnp.float32)

    def loss_packed(qkv, wq, wk):
        return jnp.sum(fused_attention_qkv(qkv, wq, wk, sin, cos, h, hkv) * w_out)

    def loss_split(q, k, v, wq, wk):
        return jnp.sum(fused_attention(q, k, v, wq, wk, sin, cos, h, hkv) * w_out)

    gp = jax.grad(loss_packed, argnums=(0, 1, 2))(qkv, wq, wk)
    gs = jax.grad(loss_split, argnums=(0, 1, 2, 3, 4))(q, k, v, wq, wk)
    dqkv_split = jnp.concatenate(gs[:3], axis=-1)
    np.testing.assert_allclose(np.asarray(gp[0]), np.asarray(dqkv_split), atol=1e-6)
    np.testing.assert_allclose(np.asarray(gp[1]), np.asarray(gs[3]), atol=1e-6)
    np.testing.assert_allclose(np.asarray(gp[2]), np.asarray(gs[4]), atol=1e-6)


@pytest.mark.slow
def test_fused_under_data_sharded_mesh():
    """The fused path under a live replica x fsdp mesh runs per-shard via
    shard_map (models/gpt.py _fused_attention_sharded): forward and grads
    — including the REPLICATED LN-weight grads, which must come back
    summed across shards — must match the unsharded fused run."""
    import dataclasses

    from jax.sharding import NamedSharding, PartitionSpec as P

    from midgpt_tpu.config import MeshConfig, ModelConfig
    from midgpt_tpu.models.gpt import GPT
    from midgpt_tpu.parallel.mesh import create_mesh
    from midgpt_tpu.parallel.sharding import axis_rules

    cfg = ModelConfig(
        block_size=128, vocab_size=96, n_layer=2, n_head=4, n_embd=256,
        dropout=0.0, attn_impl="fused", remat="none", qk_norm=True,
    )
    model = GPT.init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 128), 0, 96)

    def loss(m, toks):
        lg = m(toks)
        return jnp.mean(lg.astype(jnp.float32) ** 2)

    l_ref = jax.jit(loss)(model, tokens)
    g_ref = jax.jit(jax.grad(loss))(model, tokens)

    mesh = create_mesh(MeshConfig(replica=2, fsdp=4, sequence=1, tensor=1))
    tok_sharded = jax.device_put(
        tokens, NamedSharding(mesh, P(("replica", "fsdp")))
    )

    def sharded_loss(m, toks):
        with axis_rules(mesh):
            return loss(m, toks)

    l_sh = jax.jit(sharded_loss)(model, tok_sharded)
    g_sh = jax.jit(jax.grad(sharded_loss))(model, tok_sharded)
    np.testing.assert_allclose(float(l_sh), float(l_ref), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g_sh), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4
        )


@pytest.mark.slow
def test_fused_under_tensor_sharded_mesh():
    """TP + fused: tensor shards the head dim; each shard runs the
    split-entry kernel with H/tp heads (models/gpt.py
    _fused_attention_sharded TP branch). Forward and all grads must match
    the naive path on the SAME mesh."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from midgpt_tpu.config import MeshConfig, ModelConfig
    from midgpt_tpu.models.gpt import GPT
    from midgpt_tpu.parallel.mesh import create_mesh
    from midgpt_tpu.parallel.sharding import axis_rules

    cfg = ModelConfig(
        block_size=128, vocab_size=96, n_layer=2, n_head=4, n_embd=512,
        dropout=0.0, attn_impl="fused", remat="none", qk_norm=True,
    )  # C=128 -> per-shard supported at tp=2 (2 heads of 128)
    model = GPT.init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 128), 0, 96)

    mesh = create_mesh(MeshConfig(replica=1, fsdp=4, sequence=1, tensor=2))
    tok_sharded = jax.device_put(
        tokens, NamedSharding(mesh, P(("replica", "fsdp")))
    )

    def loss(m, toks, impl):
        with axis_rules(mesh):
            lg = m(toks, attn_impl=impl)
            return jnp.mean(lg.astype(jnp.float32) ** 2)

    l_f = jax.jit(loss, static_argnums=2)(model, tok_sharded, "fused")
    l_n = jax.jit(loss, static_argnums=2)(model, tok_sharded, "naive")
    np.testing.assert_allclose(float(l_f), float(l_n), rtol=2e-5)

    g_f = jax.jit(jax.grad(loss), static_argnums=2)(model, tok_sharded, "fused")
    g_n = jax.jit(jax.grad(loss), static_argnums=2)(model, tok_sharded, "naive")
    for a, b in zip(jax.tree.leaves(g_f), jax.tree.leaves(g_n)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-3
        )
