"""Pipeline parallelism vs sequential scan-over-layers: forward and
gradient parity on a 4-stage CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from midgpt_tpu.parallel.pipeline import pipeline_forward, stage_scan_fn

D = 16
L = 8  # layers, stacked
M = 6  # microbatches
BM = 4  # microbatch size


@pytest.fixture(scope="module")
def pipe_mesh():
    devs = jax.devices()[:4]
    return Mesh(np.asarray(devs).reshape(4), ("pipeline",))


def _block_fn(params_1layer, x):
    w, b = params_1layer
    return jnp.tanh(x @ w + b)


def _make(key):
    kw, kb, kx = jax.random.split(key, 3)
    w = 0.3 * jax.random.normal(kw, (L, D, D))
    b = 0.1 * jax.random.normal(kb, (L, D))
    x = jax.random.normal(kx, (M, BM, D))
    return (w, b), x


def _sequential(params, x):
    def body(h, layer):
        return _block_fn(layer, h), None

    flat = x.reshape(M * BM, D)
    out, _ = jax.lax.scan(body, flat, params)
    return out.reshape(M, BM, D)


def test_pipeline_forward_matches_sequential(pipe_mesh):
    params, x = _make(jax.random.PRNGKey(0))
    out = pipeline_forward(
        params, x, stage_scan_fn(_block_fn), pipe_mesh
    )
    ref = _sequential(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_grads_match_sequential(pipe_mesh):
    """The AD-derived backward (reverse ticks through ppermute transpose)
    must match the sequential gradient."""
    params, x = _make(jax.random.PRNGKey(1))

    def loss_pipe(params, x):
        out = pipeline_forward(
            params, x, stage_scan_fn(_block_fn), pipe_mesh
        )
        return jnp.sum(jnp.sin(out))

    def loss_seq(params, x):
        return jnp.sum(jnp.sin(_sequential(params, x)))

    # jit required: eager shard_map can't evaluate the remat closed_call
    (gw, gb), gx = jax.jit(jax.grad(loss_pipe, argnums=(0, 1)))(params, x)
    (ow, ob), ox = jax.jit(jax.grad(loss_seq, argnums=(0, 1)))(params, x)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(ow), atol=1e-4)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(ob), atol=1e-4)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(ox), atol=1e-4)


def test_pipeline_under_jit_with_remat(pipe_mesh):
    params, x = _make(jax.random.PRNGKey(2))
    fn = jax.jit(
        lambda p, x: pipeline_forward(
            p, x, stage_scan_fn(_block_fn), pipe_mesh, remat=True
        )
    )
    out = fn(params, x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_sequential(params, x)), atol=1e-5
    )


def test_pipeline_rejects_indivisible_layers(pipe_mesh):
    params, x = _make(jax.random.PRNGKey(3))
    bad = jax.tree.map(lambda a: a[:6], params)  # 6 layers, 4 stages
    with pytest.raises(AssertionError):
        pipeline_forward(bad, x, stage_scan_fn(_block_fn), pipe_mesh)


def _run_gpt_step(model_cfg, mesh_cfg, n_dev, x, y):
    """One train step of the given model on the given mesh; returns
    (loss, state)."""
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from midgpt_tpu.config import ExperimentConfig
    from midgpt_tpu.parallel.mesh import create_mesh
    from midgpt_tpu.parallel.sharding import make_global_array
    from midgpt_tpu.train import init_state, make_optimizer, make_train_step

    cfg = ExperimentConfig(
        model=model_cfg, mesh=mesh_cfg,
        learning_rate=1e-3, warmup_steps=2, lr_decay_steps=10, max_steps=10,
        batch_size=8, g_accum_iters=1,
    )
    mesh = create_mesh(cfg.mesh, devices=jax.devices()[:n_dev])
    tx, _ = make_optimizer(cfg)
    state = init_state(cfg, mesh, tx, jax.random.PRNGKey(0))
    step = make_train_step(cfg, tx, mesh)
    spec = P(None, ("replica", "fsdp"), "sequence")
    xg = make_global_array(x, mesh, spec)
    yg = make_global_array(y, mesh, spec)
    state, loss = step(state, xg, yg, jax.random.PRNGKey(1))
    return float(loss), state


@pytest.mark.slow
def test_gpt_pp_train_step_matches_non_pp():
    """VERDICT r1 item 4: a real GPT train step with the block stack
    pipelined over 4 stages must produce the same loss as the plain
    scan-over-layers step, to fp tolerance, with identical params."""
    import numpy as np

    from midgpt_tpu.config import MeshConfig, ModelConfig

    model_cfg = ModelConfig(
        block_size=64, vocab_size=128, n_layer=4, n_head=4, n_embd=32,
        dropout=0.0, attn_impl="naive", remat="none",
    )
    rng = np.random.default_rng(0)
    x = rng.integers(0, 128, size=(1, 8, 64), dtype=np.int32)
    y = rng.integers(0, 128, size=(1, 8, 64), dtype=np.int32)

    loss_pp, state_pp = _run_gpt_step(
        model_cfg,
        MeshConfig(pipeline=4, replica=1, fsdp=2, sequence=1, tensor=1),
        8, x, y,
    )
    loss_plain, state_plain = _run_gpt_step(
        model_cfg,
        MeshConfig(pipeline=1, replica=1, fsdp=2, sequence=1, tensor=1),
        2, x, y,
    )
    # 1e-4, not 2e-5: on jax pins without partial-auto shard_map the PP
    # region runs fully manual (compat.shard_map), which regathers the
    # fsdp-sharded operands at region entry — same math, different f32
    # reduction order across the 8 virtual devices (~5e-5 observed)
    np.testing.assert_allclose(loss_pp, loss_plain, rtol=1e-4)
    # params after one update must match too (same grads through the bubble)
    for a, b in zip(
        jax.tree.leaves(state_pp.params), jax.tree.leaves(state_plain.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


@pytest.mark.slow
def test_gpt_pp_composes_with_tensor_parallel():
    """PP x TP x FSDP on 8 devices: the partial-auto shard_map leaves the
    tensor/fsdp axes to GSPMD inside the stages; loss must still match the
    unsharded step."""
    import numpy as np

    from midgpt_tpu.config import MeshConfig, ModelConfig

    model_cfg = ModelConfig(
        block_size=64, vocab_size=128, n_layer=2, n_head=4, n_embd=32,
        dropout=0.0, attn_impl="naive", remat="none",
    )
    rng = np.random.default_rng(1)
    x = rng.integers(0, 128, size=(1, 8, 64), dtype=np.int32)
    y = rng.integers(0, 128, size=(1, 8, 64), dtype=np.int32)

    loss_pp_tp, _ = _run_gpt_step(
        model_cfg,
        MeshConfig(pipeline=2, replica=1, fsdp=2, sequence=1, tensor=2),
        8, x, y,
    )
    loss_plain, _ = _run_gpt_step(
        model_cfg,
        MeshConfig(pipeline=1, replica=1, fsdp=1, sequence=1, tensor=1),
        1, x, y,
    )
    # tensor>1 switches the embedding to the one-hot contraction and adds
    # psum reductions — different bf16 summation order, so slightly looser
    # than the PP-only parity above
    np.testing.assert_allclose(loss_pp_tp, loss_plain, rtol=5e-4)


def test_gpt_pp_with_grad_accumulation():
    """The GPipe shard_map nests inside the grad-accumulation scan."""
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from midgpt_tpu.config import ExperimentConfig, MeshConfig, ModelConfig
    from midgpt_tpu.parallel.mesh import create_mesh
    from midgpt_tpu.parallel.sharding import make_global_array
    from midgpt_tpu.train import init_state, make_optimizer, make_train_step

    model_cfg = ModelConfig(
        block_size=64, vocab_size=128, n_layer=4, n_head=4, n_embd=32,
        dropout=0.0, attn_impl="naive", remat="none",
    )
    cfg = ExperimentConfig(
        model=model_cfg,
        mesh=MeshConfig(pipeline=4, replica=1, fsdp=2, sequence=1, tensor=1),
        learning_rate=1e-3, warmup_steps=2, lr_decay_steps=10, max_steps=10,
        batch_size=8, g_accum_iters=2,
    )
    mesh = create_mesh(cfg.mesh)
    tx, _ = make_optimizer(cfg)
    state = init_state(cfg, mesh, tx, jax.random.PRNGKey(0))
    step = make_train_step(cfg, tx, mesh)
    rng = np.random.default_rng(2)
    x = rng.integers(0, 128, size=(2, 4, 64), dtype=np.int32)
    y = rng.integers(0, 128, size=(2, 4, 64), dtype=np.int32)
    spec = P(None, ("replica", "fsdp"), "sequence")
    xg = make_global_array(x, mesh, spec)
    yg = make_global_array(y, mesh, spec)
    state, loss = step(state, xg, yg, jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))


@pytest.mark.slow
def test_gpt_pp_with_dropout():
    """Dropout under PP (r3 left this deterministic-only): keys thread
    through the tick schedule next to the params. Checks: the step runs
    and is deterministic per key, different keys give different losses,
    and dropout=0 reproduces the deterministic PP loss exactly."""
    import numpy as np

    from midgpt_tpu.config import MeshConfig, ModelConfig

    rng = np.random.default_rng(1)
    x = rng.integers(0, 128, size=(1, 8, 64), dtype=np.int32)
    y = rng.integers(0, 128, size=(1, 8, 64), dtype=np.int32)
    mesh_cfg = MeshConfig(pipeline=4, replica=1, fsdp=2, sequence=1, tensor=1)

    def run(dropout, seed=1):
        model_cfg = ModelConfig(
            block_size=64, vocab_size=128, n_layer=4, n_head=4, n_embd=32,
            dropout=dropout, attn_impl="naive", remat="none",
        )
        # _run_gpt_step uses PRNGKey(1) for the step; vary via data seed
        import jax as _jax

        from midgpt_tpu.config import ExperimentConfig
        from jax.sharding import PartitionSpec as P

        from midgpt_tpu.parallel.mesh import create_mesh
        from midgpt_tpu.parallel.sharding import make_global_array
        from midgpt_tpu.train import init_state, make_optimizer, make_train_step

        cfg = ExperimentConfig(
            model=model_cfg, mesh=mesh_cfg,
            learning_rate=1e-3, warmup_steps=2, lr_decay_steps=10,
            max_steps=10, batch_size=8, g_accum_iters=1,
        )
        mesh = create_mesh(cfg.mesh)
        tx, _ = make_optimizer(cfg)
        state = init_state(cfg, mesh, tx, _jax.random.PRNGKey(0))
        step = make_train_step(cfg, tx, mesh)
        spec = P(None, ("replica", "fsdp"), "sequence")
        xg = make_global_array(x, mesh, spec)
        yg = make_global_array(y, mesh, spec)
        _, loss = step(state, xg, yg, _jax.random.PRNGKey(seed))
        return float(loss)

    l_det = run(0.0)
    l_d1 = run(0.3, seed=1)
    l_d1_again = run(0.3, seed=1)
    l_d2 = run(0.3, seed=2)
    assert np.isfinite(l_d1)
    assert l_d1 == l_d1_again  # deterministic per key
    assert l_d1 != l_d2  # keys actually reach the dropout masks
    assert l_d1 != l_det  # dropout actually perturbs the forward


@pytest.mark.slow
def test_gpt_pp_flash_runs_at_parity(pallas_interpret):
    """Flash attention inside pipeline stages (ADVICE r4): the stage region
    is check_vma=True, so the kernel's out_shapes must carry the operands'
    vma (ops/flash._struct) for pallas to type-check at all — this is the
    regression test for that. The data-axis shard_map wrap does NOT engage
    in there (Shardy rejects the nesting; see _flash_sharded's docstring),
    so this checks the bare stage-local kernel lowers and stays at parity
    on a PP x FSDP x TP mesh."""
    import numpy as np

    from midgpt_tpu.config import MeshConfig, ModelConfig

    rng = np.random.default_rng(3)
    x = rng.integers(0, 128, size=(1, 8, 128), dtype=np.int32)
    y = rng.integers(0, 128, size=(1, 8, 128), dtype=np.int32)

    def cfgm(impl):
        return ModelConfig(
            block_size=128, vocab_size=128, n_layer=2, n_head=4, n_embd=128,
            dropout=0.0, attn_impl=impl, remat="none",
        )

    loss_pp_flash, _ = _run_gpt_step(
        cfgm("flash"),
        MeshConfig(pipeline=2, replica=1, fsdp=2, sequence=1, tensor=2),
        8, x, y,
    )
    loss_plain, _ = _run_gpt_step(
        cfgm("naive"),
        MeshConfig(pipeline=1, replica=1, fsdp=1, sequence=1, tensor=1),
        1, x, y,
    )
    np.testing.assert_allclose(loss_pp_flash, loss_plain, rtol=5e-4)
