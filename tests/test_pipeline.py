"""Pipeline parallelism vs sequential scan-over-layers: forward and
gradient parity on a 4-stage CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from midgpt_tpu.parallel.pipeline import pipeline_forward, stage_scan_fn

D = 16
L = 8  # layers, stacked
M = 6  # microbatches
BM = 4  # microbatch size


@pytest.fixture(scope="module")
def pipe_mesh():
    devs = jax.devices()[:4]
    return Mesh(np.asarray(devs).reshape(4), ("pipeline",))


def _block_fn(params_1layer, x):
    w, b = params_1layer
    return jnp.tanh(x @ w + b)


def _make(key):
    kw, kb, kx = jax.random.split(key, 3)
    w = 0.3 * jax.random.normal(kw, (L, D, D))
    b = 0.1 * jax.random.normal(kb, (L, D))
    x = jax.random.normal(kx, (M, BM, D))
    return (w, b), x


def _sequential(params, x):
    def body(h, layer):
        return _block_fn(layer, h), None

    flat = x.reshape(M * BM, D)
    out, _ = jax.lax.scan(body, flat, params)
    return out.reshape(M, BM, D)


def test_pipeline_forward_matches_sequential(pipe_mesh):
    params, x = _make(jax.random.PRNGKey(0))
    out = pipeline_forward(
        params, x, stage_scan_fn(_block_fn), pipe_mesh
    )
    ref = _sequential(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_grads_match_sequential(pipe_mesh):
    """The AD-derived backward (reverse ticks through ppermute transpose)
    must match the sequential gradient."""
    params, x = _make(jax.random.PRNGKey(1))

    def loss_pipe(params, x):
        out = pipeline_forward(
            params, x, stage_scan_fn(_block_fn), pipe_mesh
        )
        return jnp.sum(jnp.sin(out))

    def loss_seq(params, x):
        return jnp.sum(jnp.sin(_sequential(params, x)))

    # jit required: eager shard_map can't evaluate the remat closed_call
    (gw, gb), gx = jax.jit(jax.grad(loss_pipe, argnums=(0, 1)))(params, x)
    (ow, ob), ox = jax.jit(jax.grad(loss_seq, argnums=(0, 1)))(params, x)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(ow), atol=1e-4)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(ob), atol=1e-4)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(ox), atol=1e-4)


def test_pipeline_under_jit_with_remat(pipe_mesh):
    params, x = _make(jax.random.PRNGKey(2))
    fn = jax.jit(
        lambda p, x: pipeline_forward(
            p, x, stage_scan_fn(_block_fn), pipe_mesh, remat=True
        )
    )
    out = fn(params, x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_sequential(params, x)), atol=1e-5
    )


def test_pipeline_rejects_indivisible_layers(pipe_mesh):
    params, x = _make(jax.random.PRNGKey(3))
    bad = jax.tree.map(lambda a: a[:6], params)  # 6 layers, 4 stages
    with pytest.raises(AssertionError):
        pipeline_forward(bad, x, stage_scan_fn(_block_fn), pipe_mesh)
