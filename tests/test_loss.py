"""Chunked cross-entropy vs the dense path: identical values and
gradients, standalone and through the sharded train step."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from midgpt_tpu.config import ExperimentConfig, MeshConfig, ModelConfig
from midgpt_tpu.models.gpt import GPT
from midgpt_tpu.ops.loss import chunked_softmax_xent
from midgpt_tpu.train import loss_fn

CFG = ModelConfig(
    block_size=64, vocab_size=96, n_layer=2, n_head=2, n_embd=32,
    dropout=0.0, attn_impl="naive", remat="none",
)


def _dense(h, w, y):
    z = (h @ w).astype(jnp.float32)
    return optax.softmax_cross_entropy_with_integer_labels(z, y).mean()


def test_chunked_xent_matches_dense_value_and_grads():
    key = jax.random.PRNGKey(0)
    k1, k2, k3 = jax.random.split(key, 3)
    h = jax.random.normal(k1, (2, 64, 32))
    w = jax.random.normal(k2, (32, 96)) * 0.2
    y = jax.random.randint(k3, (2, 64), 0, 96)

    for chunk in (16, 32, 64):
        loss_c = chunked_softmax_xent(h, w, y, chunk_t=chunk)
        np.testing.assert_allclose(
            float(loss_c), float(_dense(h, w, y)), rtol=1e-6
        )

    gc = jax.jit(
        jax.grad(lambda h, w: chunked_softmax_xent(h, w, y, chunk_t=16),
                 argnums=(0, 1))
    )(h, w)
    gd = jax.grad(lambda h, w: _dense(h, w, y), argnums=(0, 1))(h, w)
    for a, b, name in zip(gc, gd, ("dh", "dw")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, err_msg=name
        )


def test_loss_fn_chunked_matches_dense_through_model():
    model = GPT.init(jax.random.PRNGKey(1), CFG)
    x = jax.random.randint(jax.random.PRNGKey(2), (2, 64), 0, CFG.vocab_size)
    y = jax.random.randint(jax.random.PRNGKey(3), (2, 64), 0, CFG.vocab_size)

    dense = loss_fn(model, x, y, None, True, None)
    chunked = loss_fn(model, x, y, None, True, 16)
    np.testing.assert_allclose(float(chunked), float(dense), rtol=1e-6)

    gd = jax.jit(jax.grad(lambda m: loss_fn(m, x, y, None, True, None)))(model)
    gch = jax.jit(jax.grad(lambda m: loss_fn(m, x, y, None, True, 16)))(model)
    for a, b in zip(jax.tree.leaves(gd), jax.tree.leaves(gch)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.slow
def test_train_step_with_loss_chunk_sharded(mesh8):
    """One sharded train step with loss_chunk on vs off: same loss. The
    first mesh has sequence=2, so this drives the per-shard chunked path
    (partial-manual shard_map over 'sequence', ops/loss.py) against dense
    through the FULL train step on a DP x SP x TP mesh."""
    from jax.sharding import PartitionSpec as P

    from midgpt_tpu.parallel.sharding import make_global_array
    from midgpt_tpu.train import init_state, make_optimizer, make_train_step

    base = ExperimentConfig(
        model=CFG,
        learning_rate=1e-2, warmup_steps=2, lr_decay_steps=10, max_steps=10,
        batch_size=8, g_accum_iters=2,
        mesh=MeshConfig(replica=1, fsdp=2, sequence=2, tensor=2),
    )
    rng = np.random.default_rng(0)
    x = rng.integers(0, CFG.vocab_size, size=(2, 4, 64), dtype=np.int32)
    y = rng.integers(0, CFG.vocab_size, size=(2, 4, 64), dtype=np.int32)

    losses = {}
    for name, chunk in (("dense", None), ("chunked", 16)):
        cfg = dataclasses.replace(base, loss_chunk=chunk)
        from midgpt_tpu.parallel.mesh import create_mesh

        mesh = create_mesh(cfg.mesh)
        tx, _ = make_optimizer(cfg)
        state = init_state(cfg, mesh, tx, jax.random.PRNGKey(0))
        step = make_train_step(cfg, tx, mesh)
        spec = P(None, ("replica", "fsdp"), "sequence")
        xg = make_global_array(x, mesh, spec)
        yg = make_global_array(y, mesh, spec)
        state, loss = step(state, xg, yg, jax.random.PRNGKey(1))
        losses[name] = float(loss)
    np.testing.assert_allclose(losses["chunked"], losses["dense"], rtol=1e-6)

    # now with an unsharded sequence axis the chunked path actually runs
    for name, chunk in (("dense", None), ("chunked", 16)):
        cfg = dataclasses.replace(
            base,
            loss_chunk=chunk,
            mesh=MeshConfig(replica=1, fsdp=4, sequence=1, tensor=2),
        )
        from midgpt_tpu.parallel.mesh import create_mesh

        mesh = create_mesh(cfg.mesh)
        tx, _ = make_optimizer(cfg)
        state = init_state(cfg, mesh, tx, jax.random.PRNGKey(0))
        step = make_train_step(cfg, tx, mesh)
        spec = P(None, ("replica", "fsdp"), "sequence")
        xg = make_global_array(x, mesh, spec)
        yg = make_global_array(y, mesh, spec)
        state, loss = step(state, xg, yg, jax.random.PRNGKey(1))
        losses[name] = float(loss)
    np.testing.assert_allclose(
        losses["chunked"], losses["dense"], rtol=2e-5
    )


def test_chunked_xent_sequence_sharded_values_and_grads(mesh8):
    """chunked_softmax_xent under a sequence-sharded mesh (the shard_map
    path) vs the dense oracle: values and h/w grads must match — including
    a chunk_t that does NOT divide the local T/S (gcd fallback)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from midgpt_tpu.parallel.sharding import axis_rules

    key = jax.random.PRNGKey(4)
    k1, k2, k3 = jax.random.split(key, 3)
    h = jax.random.normal(k1, (4, 64, 32))
    w = jax.random.normal(k2, (32, 96)) * 0.2
    y = jax.random.randint(k3, (4, 64), 0, 96)

    ref = float(_dense(h, w, y))
    g_ref = jax.grad(lambda h, w: _dense(h, w, y), argnums=(0, 1))(h, w)

    hs = jax.device_put(h, NamedSharding(mesh8, P(("replica", "fsdp"), "sequence")))
    ys = jax.device_put(y, NamedSharding(mesh8, P(("replica", "fsdp"), "sequence")))

    for chunk in (16, 32, 48):  # 48 > T/S=32 -> largest divisor fallback (32)
        def loss(h_, w_):
            with axis_rules(mesh8):
                return chunked_softmax_xent(h_, w_, ys, chunk_t=chunk)

        got = jax.jit(loss)(hs, w)
        np.testing.assert_allclose(float(got), ref, rtol=1e-6)
        g = jax.jit(jax.grad(loss, argnums=(0, 1)))(hs, w)
        np.testing.assert_allclose(np.asarray(g[0]), np.asarray(g_ref[0]), atol=1e-5)
        np.testing.assert_allclose(np.asarray(g[1]), np.asarray(g_ref[1]), atol=1e-5)
