"""Unit tests for midgpt_tpu.analysis: HLO parser, ruleset engine, cost
report (millisecond fixture-based tests — no compilation), plus a small
set of compile-backed regression tests:

- the donated train step is FULLY aliased input->output (catches a
  silently-dropped ``donate_argnums=(0,)`` — and the partial drop of the
  Adam-moment donation this subsystem found in train.py);
- injecting a bad PartitionSpec (batch logical axis unsharded) makes the
  CLI exit non-zero with a no-batch-allgather violation.

Fixtures under tests/fixtures/ are hand-written post-optimization HLO in
the exact textual forms XLA emits (explicit + iota replica_groups,
input_output_alias header, operand shapes inline).
"""

import dataclasses
import json
import pathlib

import pytest

from midgpt_tpu.analysis import (
    MeshInfo,
    StepAnalysis,
    cost_report,
    count_entry_parameters,
    dtypes_used,
    parse_collectives,
    parse_input_output_alias,
    parse_replica_groups,
    rules_for_config,
)
from midgpt_tpu.analysis.rules import (
    CrossSliceGradAllReduce,
    DcnAllReduceOnly,
    DonationIntact,
    ExpectCollective,
    NoBatchAllGather,
    NoF64,
    NoFullSequenceGather,
    NoHostSync,
)
from midgpt_tpu.config import MeshConfig, get_config

FIXTURES = pathlib.Path(__file__).parent / "fixtures"

# the fixtures' mesh: 8 devices as (pipeline, replica, fsdp, seq, tensor)
MESH = MeshInfo(
    axis_names=("pipeline", "replica", "fsdp", "sequence", "tensor"),
    axis_sizes=(1, 2, 2, 1, 2),
)
MESH_2SLICE = dataclasses.replace(MESH, num_slices=2)

# fixture geometry: global batch 8 over replica*fsdp=4 -> b_local 2; T=256
B, T = 8, 256


def _fixture(name: str) -> str:
    return (FIXTURES / name).read_text()


def _analysis(name: str, mesh=MESH, donated=None) -> StepAnalysis:
    return StepAnalysis.from_text(
        _fixture(name), mesh, global_batch=B, block=T, donated_leaves=donated
    )


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------


def test_parse_replica_groups_explicit():
    assert parse_replica_groups("{{0,2},{1,3}}") == [[0, 2], [1, 3]]


def test_parse_replica_groups_iota():
    assert parse_replica_groups("[2,4]<=[8]") == [
        [0, 1, 2, 3], [4, 5, 6, 7],
    ]


def test_parse_replica_groups_iota_transpose():
    # [4,2]<=[2,4]T(1,0): arange(8).reshape(2,4).T.reshape(4,2)
    assert parse_replica_groups("[4,2]<=[2,4]T(1,0)") == [
        [0, 4], [1, 5], [2, 6], [3, 7],
    ]


def test_parse_collectives_good_fixture():
    colls = parse_collectives(_fixture("good_fsdp.hlo"))
    assert [c.kind for c in colls] == [
        "all-gather", "all-reduce", "collective-permute", "reduce-scatter",
    ]
    ag, ar, cp, rs = colls
    assert ag.result_shapes == (("f32", (16, 32)),)
    assert ag.operand_shapes == (("f32", (8, 32)),)
    assert ag.dims == (0,)
    assert ag.groups == ((0, 2), (1, 3), (4, 6), (5, 7))
    assert ag.channel_id == 1
    assert "fsdp_param_gather" in ag.op_name
    assert ar.groups == ((0, 4), (1, 5), (2, 6), (3, 7))
    assert cp.groups == ((0, 1), (1, 0))  # source_target_pairs
    assert rs.operand_shapes == (("f32", (16, 32)),)


def test_traffic_model():
    ag, ar, cp, rs = parse_collectives(_fixture("good_fsdp.hlo"))
    # all-gather: out 16*32*4 B over G=2 -> (G-1)/G of the result
    assert ag.traffic_bytes == 16 * 32 * 4 // 2
    # all-reduce: 2*(G-1)/G of the buffer
    assert ar.traffic_bytes == 2 * 32 * 32 * 4 // 2
    # permute: whole buffer one hop
    assert cp.traffic_bytes == 2 * 128 * 32 * 4
    # reduce-scatter: (G-1)/G of the INPUT
    assert rs.traffic_bytes == 16 * 32 * 4 // 2


def test_parse_input_output_alias_and_params():
    hlo = _fixture("good_fsdp.hlo")
    aliases = parse_input_output_alias(hlo)
    assert [(a.output_index, a.param_number, a.kind) for a in aliases] == [
        ((0,), 0, "may-alias"), ((1,), 1, "may-alias"), ((2,), 2, "may-alias"),
    ]
    assert count_entry_parameters(hlo) == 4


def test_dtypes_used():
    assert "f64" not in dtypes_used(_fixture("good_fsdp.hlo"))
    assert "f64" in dtypes_used(_fixture("bad_batch_allgather.hlo"))


# ---------------------------------------------------------------------------
# MeshInfo
# ---------------------------------------------------------------------------


def test_meshinfo_coords_and_axes():
    assert MESH.n_devices == 8
    assert MESH.coords(5) == (0, 1, 0, 0, 1)
    assert MESH.crossed_axes([0, 4]) == ("replica",)
    assert MESH.crossed_axes([0, 2]) == ("fsdp",)
    assert MESH.crossed_axes([0, 1]) == ("tensor",)
    assert MESH.crossed_axes([0, 1, 2, 3]) == ("fsdp", "tensor")
    assert MESH.crossed_axes([3]) == ()


def test_meshinfo_slices():
    # num_slices=2 on replica=2: slice == replica coordinate
    assert MESH_2SLICE.slice_of(0) == 0
    assert MESH_2SLICE.slice_of(4) == 1
    assert MESH_2SLICE.crosses_slice([0, 4])
    assert not MESH_2SLICE.crosses_slice([0, 1, 2, 3])
    # single-slice meshes never cross
    assert not MESH.crosses_slice([0, 4])


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


def test_no_batch_allgather_passes_on_good():
    assert NoBatchAllGather().check(_analysis("good_fsdp.hlo")) == []


def test_no_batch_allgather_fires_on_bad():
    vs = NoBatchAllGather().check(_analysis("bad_batch_allgather.hlo"))
    assert len(vs) == 1
    assert "opaque_boundary_gather" in vs[0].line
    # the rank-2 FSDP param gather in the same fixture is NOT flagged
    assert "fsdp_param_gather" not in vs[0].line


def test_no_batch_allgather_ignores_integer_index_gathers():
    """The [B, T, 1] s32 token-id gather an embed-dim-sharded embedding
    take emits is index plumbing (8 KB), not the activation trap."""
    hlo = _fixture("bad_batch_allgather.hlo").replace(
        "f32[8,256,64]", "s32[8,256,1]"
    ).replace("f32[2,256,64]", "s32[1,256,1]")
    a = StepAnalysis.from_text(hlo, MESH, global_batch=B, block=T)
    assert NoBatchAllGather().check(a) == []


def test_no_f64():
    assert NoF64().check(_analysis("good_fsdp.hlo")) == []
    vs = NoF64().check(_analysis("bad_batch_allgather.hlo"))
    assert len(vs) == 1 and "f64" in vs[0].message


def test_donation_intact():
    assert DonationIntact().check(_analysis("good_fsdp.hlo", donated=3)) == []
    vs = DonationIntact().check(
        _analysis("bad_batch_allgather.hlo", donated=3)
    )
    assert len(vs) == 1 and "2 of 3" in vs[0].message


def test_full_sequence_gather_rule():
    hlo = (
        "ENTRY %main {\n"
        "  %all-gather.3 = bf16[2,8,256,32]{3,2,1,0} all-gather("
        "bf16[2,8,128,32]{3,2,1,0} %p), channel_id=1, "
        "replica_groups={{0,1}}, dimensions={2}, use_global_device_ids=true\n"
        "}\n"
    )
    a = StepAnalysis.from_text(hlo, MESH, global_batch=B, block=T)
    vs = NoFullSequenceGather().check(a)
    assert len(vs) == 1
    # and a feature-dim gather that does NOT reconstitute T is fine
    ok = hlo.replace("bf16[2,8,256,32]", "bf16[2,8,128,64]").replace(
        "dimensions={2}", "dimensions={3}"
    )
    a = StepAnalysis.from_text(ok, MESH, global_batch=B, block=T)
    assert NoFullSequenceGather().check(a) == []


def test_expect_collective():
    a = _analysis("good_fsdp.hlo")
    assert ExpectCollective("collective-permute").check(a) == []
    a = _analysis("multislice_good.hlo", mesh=MESH_2SLICE)
    vs = ExpectCollective("collective-permute", "ring missing").check(a)
    assert len(vs) == 1 and "ring missing" in vs[0].message


def test_dcn_allreduce_only():
    good = _analysis("multislice_good.hlo", mesh=MESH_2SLICE)
    assert DcnAllReduceOnly().check(good) == []
    bad = _analysis("multislice_bad_dcn.hlo", mesh=MESH_2SLICE)
    vs = DcnAllReduceOnly().check(bad)
    assert len(vs) == 2
    kinds = " ".join(v.message for v in vs)
    assert "collective-permute" in kinds  # DCN permute
    assert "activation-shaped" in kinds  # (b_local, T) all-reduce


def test_cross_slice_grad_allreduce():
    good = _analysis("multislice_good.hlo", mesh=MESH_2SLICE)
    assert CrossSliceGradAllReduce().check(good) == []
    # drop the cross-slice all-reduce: the sync-missing rule must fire
    hlo = "\n".join(
        l for l in _fixture("multislice_good.hlo").splitlines()
        if "all-reduce" not in l
    )
    a = StepAnalysis.from_text(hlo, MESH_2SLICE, global_batch=B, block=T)
    vs = CrossSliceGradAllReduce().check(a)
    assert len(vs) == 1 and "divergently" in vs[0].message


def test_ruleset_report_shape():
    cfg = get_config("openwebtext_xl")
    report = rules_for_config(cfg, MESH).evaluate(
        _analysis("good_fsdp.hlo", donated=3)
    )
    assert report.ok
    d = report.to_dict()
    assert d["ok"] and {r["rule"] for r in d["rules"]} == {
        "no-f64", "no-batch-allgather", "donation-intact", "no-host-sync",
    }


def test_no_host_sync_passes_on_good():
    assert NoHostSync().check(_analysis("good_fsdp.hlo")) == []


def test_no_host_sync_fires_on_callback_and_feeds():
    """pure_callback/io_callback custom-calls, infeed/outfeed, and
    host-transfer send/recv are host round-trips; device-to-device
    send/recv and ordinary custom-calls (e.g. oneDNN matmul) are not."""
    hlo = (
        "ENTRY %main {\n"
        "  %custom-call.5 = (f32[4]{0}) custom-call(s64[] %c, f32[4]{0} %p),"
        ' custom_call_target="xla_python_cpu_callback"\n'
        "  %custom-call.9 = f32[8,8]{1,0} custom-call(f32[8,8]{1,0} %a),"
        ' custom_call_target="__onednn$matmul"\n'
        "  %infeed.1 = ((f32[4]{0}), token[]) infeed(token[] %tok)\n"
        "  %send.2 = (f32[4]{0}, u32[], token[]) send(f32[4]{0} %p, "
        "token[] %tok), channel_id=3, is_host_transfer=true\n"
        "  %send.3 = (f32[4]{0}, u32[], token[]) send(f32[4]{0} %p, "
        "token[] %tok), channel_id=4\n"
        "}\n"
    )
    a = StepAnalysis.from_text(hlo, MESH, global_batch=B, block=T)
    vs = NoHostSync().check(a)
    msgs = " | ".join(v.message for v in vs)
    assert len(vs) == 3, vs
    assert "python-callback" in msgs
    assert "infeed" in msgs
    assert "host-transfer send" in msgs


def test_rules_for_config_selects_by_parallelism():
    msl = get_config("openwebtext_xl_multislice")
    names = {r.name for r in rules_for_config(msl, MESH_2SLICE).rules}
    assert {"dcn-allreduce-only", "cross-slice-grad-allreduce"} <= names

    ring = get_config("openwebtext")
    ring = dataclasses.replace(
        ring, model=dataclasses.replace(ring.model, attn_impl="ring")
    )
    seq_mesh = dataclasses.replace(MESH, axis_sizes=(1, 1, 2, 4, 1))
    names = {r.name for r in rules_for_config(ring, seq_mesh).rules}
    assert {"seq-permute-not-gather", "expect-collective-permute"} <= names


# ---------------------------------------------------------------------------
# cost report
# ---------------------------------------------------------------------------


def test_cost_report_numbers():
    rep = cost_report(_analysis("good_fsdp.hlo"))
    assert rep["metric"] == "comms_traffic_bytes_per_step"
    assert rep["unit"] == "bytes"
    assert rep["collective_count"] == 4
    # hand-computed from the fixture (see test_traffic_model)
    assert rep["by_axis"] == {
        "fsdp": 1024 + 1024, "replica": 4096, "tensor": 32768,
    }
    assert rep["value"] == 2048 + 4096 + 32768
    assert rep["dcn_bytes"] == 0
    assert rep["ici_bytes"] == rep["value"]
    assert rep["by_kind"]["all-reduce"] == {
        "count": 1, "traffic_bytes": 4096,
    }
    media = {c["medium"] for c in rep["collectives"]}
    assert media == {"ici"}


def test_cost_report_dcn_split():
    rep = cost_report(_analysis("multislice_good.hlo", mesh=MESH_2SLICE))
    # the iota-group all-reduce crosses slices; the fsdp gather does not
    assert rep["dcn_bytes"] == 2 * 32 * 32 * 4 // 2
    assert rep["ici_bytes"] == 16 * 32 * 4 // 2


# ---------------------------------------------------------------------------
# CLI plumbing (no compilation)
# ---------------------------------------------------------------------------


def test_cli_override_parsing():
    from midgpt_tpu.analysis.__main__ import _parse_override

    assert _parse_override("batch=") == ("batch", None)
    assert _parse_override("batch=fsdp") == ("batch", "fsdp")
    assert _parse_override("batch=replica+fsdp") == (
        "batch", ("replica", "fsdp"),
    )


def test_cli_unknown_config_is_usage_error(capsys):
    from midgpt_tpu.analysis.__main__ import main

    assert main(["--config", "no_such_config", "--mesh", "8"]) == 2


def test_cli_unknown_override_axis_is_usage_error(capsys):
    """A typo'd --override-logical-rule name exits 2 (usage), not 1 —
    exit 1 is reserved for actual rule violations."""
    from midgpt_tpu.analysis.__main__ import main

    rc = main([
        "--config", "openwebtext", "--mesh", "8",
        "--override-logical-rule", "batsh=",
    ])
    assert rc == 2
    assert "unknown logical axes" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# compile-backed regression tests (seconds, not milliseconds)
# ---------------------------------------------------------------------------


def _tiny_sharded_cfg():
    cfg = get_config("tiny")
    return dataclasses.replace(
        cfg,
        batch_size=8,
        g_accum_iters=1,
        mesh=MeshConfig(replica=1, fsdp=2, sequence=2, tensor=2),
    )


def test_train_step_donation_intact():
    """Compile the real donated train step and assert via the aliasing
    audit that EVERY state buffer is reused — catches a silently-dropped
    ``donate_argnums=(0,)`` in make_train_step, and the subtler partial
    drop (un-constrained opt-state output shardings) this audit found."""
    from midgpt_tpu.analysis.harness import analyze_train_step

    a = analyze_train_step(_tiny_sharded_cfg(), shrink=False)
    assert a.donated_leaves and a.donated_leaves > 0
    assert DonationIntact().check(a) == [], (
        f"aliased {len({e.param_number for e in a.aliases})} of "
        f"{a.donated_leaves} donated buffers"
    )


def test_donation_audit_detects_undonated_jit():
    """Negative control: the same audit on a jit WITHOUT donation reports
    the drop (so a green donation test is meaningful)."""
    import jax
    import jax.numpy as jnp

    def f(state):
        return jax.tree.map(lambda a: a + 1, state)

    hlo = (
        jax.jit(f)  # no donate_argnums  # shardlint: disable=missing-donate
        .lower({"w": jnp.zeros((8, 8))})
        .compile()
        .as_text()
    )
    one = MeshInfo(axis_names=("x",), axis_sizes=(1,))
    a = StepAnalysis.from_text(hlo, one, donated_leaves=1)
    assert len(DonationIntact().check(a)) == 1


def test_train_step_comms_summary_scalars():
    """The bench.py wiring: a flat scalar summary (total/ICI/DCN
    traffic, collective count, per-axis split, window size) that rides
    the one-JSON-line BENCH record."""
    from midgpt_tpu.analysis.harness import train_step_comms_summary

    s = train_step_comms_summary(_tiny_sharded_cfg())
    fixed = {
        "comms_traffic_bytes_per_step",
        "comms_ici_bytes_per_step",
        "comms_dcn_bytes_per_step",
        "comms_collective_count",
        "comms_window_steps",
    }
    assert fixed <= set(s)
    # the only other keys are the per-mesh-axis decomposition
    assert all(
        k.startswith("comms_axis_") and k.endswith("_bytes_per_step")
        for k in set(s) - fixed
    )
    assert s["comms_traffic_bytes_per_step"] > 0  # FSDP/TP traffic exists
    assert s["comms_dcn_bytes_per_step"] == 0  # single slice
    assert s["comms_ici_bytes_per_step"] == s["comms_traffic_bytes_per_step"]
    assert s["comms_collective_count"] > 0
    assert s["comms_window_steps"] == 1  # per-step jit, no fused window
    assert sum(
        v for k, v in s.items() if k.startswith("comms_axis_")
    ) == s["comms_traffic_bytes_per_step"]
    json.dumps(s)  # JSON-serializable scalars


@pytest.mark.slow
def test_cli_injected_batch_gather_fails_audit(tmp_path, capsys):
    """Acceptance: a bad PartitionSpec (batch logical axis mapped to
    nothing — the opaque-boundary trap) makes the CLI emit a
    no-batch-allgather violation and exit non-zero; the clean run of the
    same config exits zero. Runs in-process against the session's
    8-device CPU pool."""
    from midgpt_tpu.analysis.__main__ import main

    out = tmp_path / "report.json"
    rc = main([
        "--config", "openwebtext", "--mesh", "8",
        "--override-logical-rule", "batch=",
        "--json", str(out),
    ])
    assert rc == 1
    rep = json.loads(out.read_text())
    assert rep["ok"] is False
    bad = {r["rule"] for r in rep["rules"] if not r["ok"]}
    assert "no-batch-allgather" in bad
    # the report still carries the cost section (audit != crash)
    assert rep["cost"]["metric"] == "comms_traffic_bytes_per_step"
    capsys.readouterr()  # swallow the JSON printed to stdout


@pytest.mark.slow
def test_cli_clean_config_passes(tmp_path, capsys):
    from midgpt_tpu.analysis.__main__ import main

    out = tmp_path / "report.json"
    rc = main([
        "--config", "openwebtext", "--mesh", "8", "--json", str(out),
    ])
    assert rc == 0
    rep = json.loads(out.read_text())
    assert rep["ok"] is True
    assert rep["geometry"]["aliased_buffers"] == rep["geometry"]["donated_leaves"]
    capsys.readouterr()


# ---------------------------------------------------------------------------
# cost report on the SERVING geometry (the tp=2 window's comms pattern —
# what bench_serving --tp attaches to its record as serve_comms_by_axis)
# ---------------------------------------------------------------------------

# the serving audit mesh: tp=2,replica=2 (4 devices; replica is the
# shared-nothing DP axis and must carry ZERO serving-dispatch traffic)
SERVING_MESH = MeshInfo(
    axis_names=("pipeline", "replica", "fsdp", "sequence", "tensor"),
    axis_sizes=(1, 2, 1, 1, 2),
)


def test_cost_report_serving_tp2_two_psums_per_layer():
    """Canned partitioned decode-window HLO: 2 layers x 2 activation-row
    psums (row-parallel wo + w_down) + the vocab-argmax combiner, all
    over 'tensor'. The per-axis attribution is hand-computed: an
    all-reduce moves 2*(G-1)/G of its buffer, so each bf16[4,1,768] psum
    is 2 * 6144 * 1/2 = 6144 wire bytes."""
    analysis = StepAnalysis.from_text(
        _fixture("serving_tp2_window.hlo"),
        SERVING_MESH,
        global_batch=4,
        block=256,
        donated_leaves=3,
    )
    rep = cost_report(analysis)
    assert rep["collective_count"] == 5  # 2 psums/layer x 2 + combiner
    psum = 2 * (4 * 1 * 768 * 2) // 2  # ring all-reduce, G=2
    combiner = 2 * (4 * 4 + 4 * 4) // 2  # (f32[4], s32[4]) pair
    assert rep["by_axis"] == {"tensor": 4 * psum + combiner}
    assert "replica" not in rep["by_axis"]  # shared-nothing DP: silence
    assert rep["value"] == 4 * psum + combiner
    assert rep["dcn_bytes"] == 0  # serving meshes are single-slice
    assert rep["by_kind"]["all-reduce"]["count"] == 5
    assert all(c["medium"] == "ici" for c in rep["collectives"])


def test_serving_tp2_fixture_passes_page_gather_rule():
    """The same canned window against the no-batch-allgather-in-
    page-gather rule: psums are not gathers, so the healthy pattern is
    silent; adding one pool-payload all-gather trips it."""
    from midgpt_tpu.analysis.rules import NoPageGatherAllGather

    payload = frozenset({(2, 32, 12, 64, 16), (4, 12, 64, 256)})
    text = _fixture("serving_tp2_window.hlo")
    analysis = StepAnalysis.from_text(
        text, SERVING_MESH, global_batch=4, block=256, donated_leaves=3
    )
    rule = NoPageGatherAllGather(payload, 4)
    assert rule.check(analysis) == []
    bad_line = (
        "  regather = bf16[2,32,12,64,16]{4,3,2,1,0} all-gather("
        "bf16[2,32,6,64,16]{4,3,2,1,0} %p1), replica_groups={{0,1},{2,3}}, "
        "dimensions={2}\n"
    )
    bad = StepAnalysis.from_text(
        text.replace("ENTRY main {\n", "ENTRY main {\n" + bad_line),
        SERVING_MESH, global_batch=4, block=256, donated_leaves=3,
    )
    assert len(rule.check(bad)) == 1


@pytest.mark.slow
def test_compiled_tp2_window_comms_all_on_tensor():
    """Compile the REAL tp=2 decode window (the exact call bench_serving
    --tp makes for serve_comms_by_axis) and assert the cost report's
    per-axis attribution: every wire byte crosses 'tensor' only — the
    two-psums-per-layer contract on the live program."""
    import jax

    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    from midgpt_tpu.analysis.harness import compile_decode_window

    cfg = get_config("openwebtext")
    hlo, mesh, donated, block, _, _, _ = compile_decode_window(
        cfg, slots=4, window=2, page_size=16, shrink=True,
        mesh_shape={"tensor": 2},
    )
    analysis = StepAnalysis.from_text(
        hlo, MeshInfo.from_mesh(mesh, num_slices=1),
        global_batch=4, block=block, donated_leaves=donated,
    )
    rep = cost_report(analysis)
    assert rep["collective_count"] > 0
    assert set(rep["by_axis"]) == {"tensor"}
    assert rep["dcn_bytes"] == 0
