"""bench.py's one-JSON-line contract must survive a dead TPU backend:
the driver records bench output mechanically, so a wedged/killed relay
has to produce a parseable bench_error record, never a bare traceback or
a hang (PERF.md r4 relay post-mortem)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_emits_json_error_on_dead_backend():
    code = (
        f"import sys; sys.path.insert(0, {REPO!r}); import bench; bench.main()"
    )
    env = {
        "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
        # a platform name that exists on NO machine: init raises fast
        # everywhere (a real platform name could init on target hardware
        # and run the actual benchmark ladder from inside the test)
        "JAX_PLATFORMS": "no_such_backend",
        "XLA_FLAGS": "",
        "PALLAS_AXON_POOL_IPS": "",
    }
    r = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        env=env,
        timeout=300,
    )
    assert r.returncode == 3, (r.returncode, r.stderr[-400:])
    lines = [l for l in r.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, r.stdout[-400:]
    rec = json.loads(lines[0])
    assert rec["metric"] == "bench_error"
    assert "error" in rec


def test_bench_watchdog_fires_on_hung_init():
    code = (
        f"import sys; sys.path.insert(0, {REPO!r}); import bench, time; "
        "bench._backend_watchdog(1.0); time.sleep(30); print('NOT_REACHED')"
    )
    env = {
        "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "",
        "PALLAS_AXON_POOL_IPS": "",
    }
    r = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        env=env,
        timeout=120,
    )
    assert r.returncode == 3
    assert "NOT_REACHED" not in r.stdout
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "bench_error"
