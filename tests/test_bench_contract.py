"""bench.py's one-JSON-line contract must survive a dead TPU backend:
the driver records bench output mechanically, so a wedged/killed relay
has to produce a parseable bench_error record, never a bare traceback or
a hang (PERF.md r4 relay post-mortem)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_emits_json_error_on_dead_backend():
    code = (
        f"import sys; sys.path.insert(0, {REPO!r}); import bench; bench.main()"
    )
    env = {
        "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
        # a platform name that exists on NO machine: init raises fast
        # everywhere (a real platform name could init on target hardware
        # and run the actual benchmark ladder from inside the test)
        "JAX_PLATFORMS": "no_such_backend",
        "XLA_FLAGS": "",
        "PALLAS_AXON_POOL_IPS": "",
    }
    r = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        env=env,
        timeout=300,
    )
    assert r.returncode == 3, (r.returncode, r.stderr[-400:])
    lines = [l for l in r.stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, r.stdout[-400:]
    rec = json.loads(lines[0])
    assert rec["metric"] == "bench_error"
    assert "error" in rec
    assert rec["status"] == "error", "a real failure is not a wedge"


def test_bench_watchdog_fires_on_hung_init():
    code = (
        f"import sys; sys.path.insert(0, {REPO!r}); import bench, time; "
        "bench._backend_watchdog(1.0); time.sleep(30); print('NOT_REACHED')"
    )
    env = {
        "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "",
        "PALLAS_AXON_POOL_IPS": "",
    }
    r = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        env=env,
        timeout=120,
    )
    assert r.returncode == 3
    assert "NOT_REACHED" not in r.stdout
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "bench_error"
    # structured wedge row: BENCH_r*.json trajectories separate hardware
    # wedges (r4/r5) from regressions by this field
    assert rec["status"] == "watchdog"


def test_rung_measure_falls_back_when_scan_compile_fails():
    """_rung_measure must fall back to the chained path when the scan
    program fails to COMPILE (state untouched), and re-raise when the
    state buffers were already donated (a runtime failure mid-measure
    would otherwise hand deleted arrays to the fallback)."""
    sys.path.insert(0, REPO)
    import types

    import bench

    calls = {"chain": 0}

    class FakeLeaf:
        def __init__(self, deleted=False):
            self._deleted = deleted

        def is_deleted(self):
            return self._deleted

    state = [FakeLeaf()]

    def chain(st, n):
        calls["chain"] += 1
        return 0.01 * n, st

    cfg = types.SimpleNamespace(
        batch_size=8, model=types.SimpleNamespace(block_size=64)
    )

    def make_scan_compile_fails(n):
        class M:
            def lower(self, s):
                raise RuntimeError("compile boom")

        return M()

    tps, step_ms, st, mode = bench._rung_measure(
        cfg, state, chain, make_scan_compile_fails
    )
    assert mode == "chained" and calls["chain"] >= 2

    # donated state: the fallback must NOT run; original error re-raises
    dead = [FakeLeaf(deleted=True)]
    calls["chain"] = 0
    try:
        bench._rung_measure(cfg, dead, chain, make_scan_compile_fails)
        raise AssertionError("expected the compile error to re-raise")
    except RuntimeError as e:
        assert "compile boom" in str(e)
    assert calls["chain"] == 0


def test_bench_main_record_flow_with_stubbed_rungs(monkeypatch, capsys):
    """bench.main() end to end with _run_config stubbed to a trivial CPU
    closure: every rung family must land its keys in the ONE emitted
    JSON record (this is the mechanical guard for the record-wiring bug
    class — r5's code review caught the headline loop rebinding `record`
    and orphaning the watchdog's dict)."""
    import types

    sys.path.insert(0, REPO)
    import bench

    def fake_run_config(remat, batch, base="openwebtext", n_layer=None,
                        loss_chunk=256, block_size=None):
        cfg = types.SimpleNamespace(
            batch_size=batch,
            # a full dense-model shape: the attainment helper computes
            # the analytic train floor from these fields (traffic.py)
            model=types.SimpleNamespace(
                block_size=block_size or 64, remat=remat,
                mlp="gelu", mlp_hidden=None, mlp_ratio=4,
                n_embd=64, head_dim=16, n_head=4, kv_heads=4,
                n_layer=n_layer or 2, vocab_size=256, qk_norm=False,
            ),
        )

        def chain(state, n):
            return 0.002 * n, state

        def make_scan(n):
            raise RuntimeError("no scan on the stub")  # force chained

        return cfg, [], chain, make_scan

    monkeypatch.setattr(bench, "_run_config", fake_run_config)
    monkeypatch.setattr(
        "midgpt_tpu.utils.metrics.mfu", lambda tps, m, n: 0.5
    )
    monkeypatch.setattr(
        "midgpt_tpu.utils.metrics.flops_per_token", lambda m: 1e9
    )
    # decode rung: stub the heavy measure
    import scripts.bench_decode as bd

    monkeypatch.setattr(
        bd, "measure_decode", lambda **kw: {"decode_tok_s": 1234.0}
    )

    bench.main()
    out = capsys.readouterr().out
    lines = [l for l in out.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, out
    rec = json.loads(lines[0])
    # every rung family present in the single record
    assert rec["metric"].startswith("openwebtext_xl_family")
    assert "gpt2s_mfu" in rec
    assert "llama_mfu" in rec
    assert "decode_tok_s" in rec
    assert "long_ctx_mfu" in rec
    assert rec["measure"] == "chained"
    assert rec["status"] == "ok"
    # PR 15 contract: the headline + gpt2s rungs carry the static
    # roofline floors and attainment next to their MFU (the ledger's
    # static-key gating and the "self-interpreting r6 rows" promise
    # both read these by name)
    for prefix in ("", "gpt2s_"):
        assert rec[prefix + "train_compute_floor_ms"] > 0
        assert rec[prefix + "train_hbm_floor_ms"] > 0
        assert rec[prefix + "train_attainment_frac"] > 0


def test_emit_bench_error_carries_flight_dump_in_band(tmp_path, capsys):
    """Watchdog/error rows carry the rung-lifecycle flight-dump path
    in-band when telemetry is armed — the r4/r5 wedged-run lesson
    applied to the training bench (bench_serving's rows already do
    this)."""
    sys.path.insert(0, REPO)
    import bench
    from midgpt_tpu.train_telemetry import TrainTelemetry

    tele = TrainTelemetry()
    tele.emit("run_start", step=0, t=0.0)
    tele.emit("rung_start", step=1, t=1.0, rung="xl_L8_B12")
    old = dict(bench._FLIGHT)
    try:
        bench._FLIGHT.update(tele=tele, dir=str(tmp_path))
        bench._emit_bench_error("relay wedged", status="watchdog")
    finally:
        bench._FLIGHT.update(old)
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["metric"] == "bench_error"
    assert rec["status"] == "watchdog"
    assert rec["flight_recorder"], "dump path must ride in-band"
    dump = json.load(open(rec["flight_recorder"][0]))
    assert dump["reason"] == "bench:watchdog"
    assert [e["kind"] for e in dump["telemetry"]["events"]] == [
        "run_start", "rung_start",
    ]
    # without telemetry the row stays a bare (but valid) error record
    try:
        bench._FLIGHT.update(tele=None, dir=None)
        bench._emit_bench_error("boom")
    finally:
        bench._FLIGHT.update(old)
    rec2 = json.loads(capsys.readouterr().out.strip())
    assert "flight_recorder" not in rec2
