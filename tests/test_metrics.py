"""Metrics utilities: wandb run-id persistence across resume (parity:
/root/reference/launch.py:60-67) and MFU arithmetic."""

import types

from midgpt_tpu.utils.metrics import _load_or_create_wandb_id, flops_per_token


def _fake_wandb(ids):
    it = iter(ids)
    return types.SimpleNamespace(
        util=types.SimpleNamespace(generate_id=lambda: next(it))
    )


def test_wandb_id_persisted_and_reused(tmp_path):
    rundir = str(tmp_path / "run")
    first = _load_or_create_wandb_id(rundir, _fake_wandb(["abc123", "XXX"]))
    assert first == "abc123"
    # a "resumed" process must get the stored id, not a fresh one
    second = _load_or_create_wandb_id(rundir, _fake_wandb(["YYY"]))
    assert second == "abc123"
    assert (tmp_path / "run" / "wandb_id.txt").read_text().strip() == "abc123"


def test_wandb_id_empty_rundir_is_none():
    assert _load_or_create_wandb_id("", _fake_wandb(["a"])) is None


def test_flops_per_token_gpt2_small():
    from midgpt_tpu.config import get_config

    model = get_config("openwebtext").model
    # 6 * (param matmuls) + causal attention term; ~798 MFLOP/token for
    # the 124M config (sanity: within 10% of 6 * 130M)
    f = flops_per_token(model)
    assert 7.0e8 < f < 9.0e8
