"""Test environment: force an 8-device CPU platform BEFORE jax import so
multi-device sharding (DP/FSDP/SP/TP) is exercised without TPU hardware
(SURVEY.md 4: the reference's mesh code silently assumes >= 8 devices)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # the env pins JAX_PLATFORMS=axon
jax.config.update("jax_threefry_partitionable", True)  # (train.py:16)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from midgpt_tpu.config import MeshConfig
    from midgpt_tpu.parallel.mesh import create_mesh

    return create_mesh(MeshConfig(replica=1, fsdp=2, sequence=2, tensor=2))


@pytest.fixture
def pallas_interpret(monkeypatch):
    """Run Pallas kernels through the CPU interpreter (the tests' only way
    to execute TPU kernels without hardware)."""
    import functools

    from jax.experimental import pallas as pl

    monkeypatch.setattr(
        pl, "pallas_call", functools.partial(pl.pallas_call, interpret=True)
    )
    yield
