"""Test environment: force an 8-device CPU platform BEFORE jax import so
multi-device sharding (DP/FSDP/SP/TP) is exercised without TPU hardware
(SURVEY.md 4: the reference's mesh code silently assumes >= 8 devices)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # the env pins JAX_PLATFORMS=axon
jax.config.update("jax_threefry_partitionable", True)  # (train.py:16)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from midgpt_tpu.config import MeshConfig
    from midgpt_tpu.parallel.mesh import create_mesh

    return create_mesh(MeshConfig(replica=1, fsdp=2, sequence=2, tensor=2))


@pytest.fixture
def pallas_interpret(monkeypatch):
    """Run Pallas kernels through the CPU interpreter (the tests' only way
    to execute TPU kernels without hardware)."""
    import functools

    from jax.experimental import pallas as pl

    monkeypatch.setattr(
        pl, "pallas_call", functools.partial(pl.pallas_call, interpret=True)
    )
    yield


# ---------------------------------------------------------------------------
# Smoke tier (r5, VERDICT r4 Weak #9): `pytest -m smoke` runs the
# oracle-parity + contract core in ~2 min so the build loop doesn't pay the
# full suite's ~25 min per iteration. The full suite stays the round gate.
# ---------------------------------------------------------------------------

_SMOKE_ALL = {
    "test_bench_contract",
    "test_layers",
    "test_sharding",
    "test_metrics",
    "test_gcs_paths",
    "test_data",
    "test_auto_knobs",
}
_SMOKE_TESTS = {
    "test_loss": {"test_chunked_xent_matches_dense_value_and_grads"},
    "test_flash": {
        "test_flash_forward_matches_naive",
        "test_flash_grad_matches_naive",
        "test_flash_dropout_matches_hash_oracle",
    },
    "test_ring": {
        "test_ring_matches_full_attention",
        "test_ring_dropout_matches_single_device_mask",
    },
    "test_model": {
        "test_batched_forward_matches_reference_math",
        "test_causality",
    },
    "test_pipeline": {"test_pipeline_forward_matches_sequential"},
    "test_sampling": {"test_decode_matches_full_forward"},
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        mod = item.module.__name__.rsplit(".", 1)[-1]
        name = item.name.split("[", 1)[0]
        if mod in _SMOKE_ALL or name in _SMOKE_TESTS.get(mod, ()):
            item.add_marker(pytest.mark.smoke)


# ---------------------------------------------------------------------------
# Wall-clock accounting: tier-1 runs under a hard timeout (ROADMAP.md's
# 870 s verify line), and the budget has been breached by slow boxes
# before (PR 7's CHANGES entry). Print the top-10 slowest CALL phases at
# the end of every session so a test drifting toward the ~20 s
# move-to-slow-tier threshold is visible in every run's output instead
# of discovered by a timeout. (pytest's own --durations is opt-in;
# this makes the accounting permanent.)
# ---------------------------------------------------------------------------

_CALL_DURATIONS: list = []
_DESELECTED_SLOW: dict = {}


def pytest_runtest_logreport(report):
    if report.when == "call":
        _CALL_DURATIONS.append((report.duration, report.nodeid))


def pytest_deselected(items):
    # tally slow-tier tests that were collected but deselected (the
    # `-m 'not slow'` tier-1 runs), per file — so the tier split of a
    # new test family is visible in every CI log instead of only in an
    # explicit `-m slow` collection
    for item in items:
        if item.get_closest_marker("slow") is not None:
            key = item.nodeid.split("::", 1)[0]
            _DESELECTED_SLOW[key] = _DESELECTED_SLOW.get(key, 0) + 1


def pytest_terminal_summary(terminalreporter):
    # SUITE_TIMING_OUT=path: also write the accounting as a JSON
    # artifact (CI uploads it; analysis/ledger.py ingests it via
    # --suite-timing, so tier-1 wall-time drift is tracked in the
    # perf trajectory like any other metric)
    out = os.environ.get("SUITE_TIMING_OUT")
    if out:
        import json

        top = sorted(_CALL_DURATIONS, reverse=True)[:10]
        payload = {
            "kind": "suite",
            "suite_total_call_s": round(
                sum(d for d, _ in _CALL_DURATIONS), 2
            ),
            "suite_n_calls": len(_CALL_DURATIONS),
            "slowest": [
                {"nodeid": nodeid, "s": round(dur, 2)}
                for dur, nodeid in top
            ],
            "deselected_slow": dict(sorted(_DESELECTED_SLOW.items())),
        }
        os.makedirs(
            os.path.dirname(os.path.abspath(out)), exist_ok=True
        )
        with open(out, "w") as f:
            json.dump(payload, f, indent=1)
    if _DESELECTED_SLOW:
        total_slow = sum(_DESELECTED_SLOW.values())
        terminalreporter.write_sep(
            "-",
            f"slow tier: {total_slow} collected-but-skipped test(s) "
            "this session (run with -m slow / in their CI jobs)",
        )
        for path in sorted(_DESELECTED_SLOW):
            terminalreporter.write_line(
                f"{_DESELECTED_SLOW[path]:4d}  {path}"
            )
    if not _CALL_DURATIONS:
        return
    top = sorted(_CALL_DURATIONS, reverse=True)[:10]
    total = sum(d for d, _ in _CALL_DURATIONS)
    terminalreporter.write_sep(
        "-",
        f"slowest 10 of {len(_CALL_DURATIONS)} test calls "
        f"(sum {total:.0f}s; non-slow tests >20s belong on the slow tier)",
    )
    for dur, nodeid in top:
        terminalreporter.write_line(f"{dur:8.2f}s  {nodeid}")
