"""Arithmetic-choreography prover tests (analysis/choreo.py).

The prover must (a) PASS on the shipped tree — decode window, prefill
chunk and verify program satisfy their documented dtype-choreography
contracts — and (b) FAIL on both historical bug classes, injected as
faulty attention variants:

- the PR 4 bug: a chunk-prefill variant that upcasts to f32 before the
  score einsums and keeps f32 probs through the PV contraction (the
  "cast-early" drift that flipped near-tied greedy argmaxes on a real
  checkpoint);
- the PR 5 bug: a verify variant that reuses the PREFILL choreography
  (bf16 score einsums, ``* scale``, probs rounded to the value dtype)
  instead of mirroring the decode window's arithmetic.

The faulty variants below copy the real methods' structure with exactly
the historical arithmetic flipped, and are monkeypatched onto
``Attention`` so the prover traces them through the REAL program
factories — the same route a regression would take.
"""

import math

import jax
import jax.numpy as jnp
import pytest

from midgpt_tpu.analysis.choreo import (
    attention_regions,
    extract_choreography,
    flatten_jaxpr,
    normalized_trace,
)
from midgpt_tpu.analysis.harness import prove_serving_choreography
from midgpt_tpu.models.gpt import Attention
from midgpt_tpu.parallel.sharding import shard_act
from midgpt_tpu.serving import engine as engine_mod


@pytest.fixture(scope="module")
def healthy_report():
    return prove_serving_choreography("openwebtext")


def _checks(report):
    return {c.name: c.ok for c in report.checks}


# ---------------------------------------------------------------------------
# the prover passes on the shipped tree
# ---------------------------------------------------------------------------


def test_prover_passes_on_current_tree(healthy_report):
    assert healthy_report.ok, "\n".join(
        f"{c.name}: {c.detail}"
        for c in healthy_report.checks
        if not c.ok
    )


def test_prover_passes_on_quant_path():
    rep = prove_serving_choreography("openwebtext", quant=True)
    assert rep.ok, "\n".join(
        f"{c.name}: {c.detail}" for c in rep.checks if not c.ok
    )
    # the quantized lm head must carry the dequant epilogue in ALL
    # three programs (a missing epilogue = wrong logits, an epilogue on
    # some programs only = choreography drift)
    for p in rep.programs:
        if p.name != "naive_reference":
            assert p.lm_head_epilogue, p.name


def test_decode_and_verify_traces_are_op_identical(healthy_report):
    progs = {p.name: p for p in healthy_report.programs}
    assert progs["decode_window"].attention == progs["verify"].attention
    # and the documented ASYMMETRY is real: the prefill chunk's probs
    # round to the value dtype (naive contract) while decode keeps f32
    assert progs["decode_window"].softmax.probs_dtype == {"float32"}
    assert progs["prefill_chunk"].softmax.probs_dtype == {"bfloat16"}


def test_report_serializes(healthy_report):
    d = healthy_report.to_dict()
    assert d["ok"] is True
    assert set(d["programs"]) == {
        "decode_window", "prefill_chunk", "verify", "naive_reference"
    }


# ---------------------------------------------------------------------------
# flattener units
# ---------------------------------------------------------------------------


def test_flatten_tracks_invar_origin_through_structural_ops():
    def f(w, x):
        # weight sliced + cast (the stacked-layer pattern) then matmul
        wl = jnp.transpose(w[0]).astype(jnp.bfloat16)
        return x @ wl

    g = flatten_jaxpr(
        jax.make_jaxpr(f)(
            jnp.zeros((2, 4, 8)), jnp.zeros((3, 8), jnp.bfloat16)
        )
    )
    dots = [op for op in g.ops if op.prim == "dot_general"]
    assert len(dots) == 1
    assert "invar" in dots[0].in_origins


def test_flatten_recurses_into_jitted_calls():
    @jax.jit
    def inner(x):
        return jax.nn.softmax(x)

    def f(x):
        return inner(x * 2.0)

    g = flatten_jaxpr(jax.make_jaxpr(f)(jnp.zeros((4,), jnp.float32)))
    prims = {op.prim for op in g.ops}
    assert "exp" in prims and "reduce_max" in prims


def test_attention_regions_one_per_layer(healthy_report):
    for p in healthy_report.programs:
        if p.name == "naive_reference":
            continue
        assert p.n_layers == 2  # the choreography-size trace depth


def test_normalized_trace_drops_structure_keeps_dtypes():
    def f(x):
        y = jnp.transpose(x).reshape(-1)
        return jnp.exp(y.astype(jnp.float32))

    g = flatten_jaxpr(jax.make_jaxpr(f)(jnp.zeros((2, 3), jnp.bfloat16)))
    trace = normalized_trace(g)
    assert trace == [
        ("convert_element_type", ("bfloat16",), ("float32",)),
        ("exp", ("float32",), ("float32",)),
    ]


# ---------------------------------------------------------------------------
# fault injection: the PR 4 bug (cast-early prefill chunk)
# ---------------------------------------------------------------------------


def _cast_early_prefill_paged_at(
    self, x, pool_k, pool_v, bt, layer, mask_pool, mask_self,
    sin_rows, cos_rows, **_new_kwargs,
):
    """prefill_paged_at with the HISTORICAL PR 4 drift re-injected:
    f32 upcast before the score einsums and f32 probs through the PV
    contraction (instead of mirroring naive_attention's bf16-operand /
    f32-accumulate scores and value-dtype probs)."""
    from midgpt_tpu.models.layers import apply_rotary

    b, t, d = x.shape
    h, hkv = self.n_head, self.n_kv_head
    c = d // h
    qkv = self.wqkv(x)
    q = qkv[..., : h * c].reshape(b, t, h, c)
    k = qkv[..., h * c : (h + hkv) * c].reshape(b, t, hkv, c)
    v = qkv[..., (h + hkv) * c :].reshape(b, t, hkv, c)
    if self.q_norm is not None:
        q = self.q_norm(q)
        k = self.k_norm(k)
    q = jnp.transpose(q, (0, 2, 1, 3))
    k = jnp.transpose(k, (0, 2, 1, 3))
    v = jnp.transpose(v, (0, 2, 1, 3))
    q = apply_rotary(q, sin_rows, cos_rows)
    k = apply_rotary(k, sin_rows, cos_rows)
    pk_l = jnp.take(pool_k[layer], bt, axis=0, mode="clip")
    pv_l = jnp.take(pool_v[layer], bt, axis=0, mode="clip")
    _, pmax, _, _, ps = pk_l.shape
    ck = jnp.transpose(pk_l, (0, 2, 3, 1, 4)).reshape(b, hkv, c, pmax * ps)
    cv = jnp.transpose(pv_l, (0, 2, 3, 1, 4)).reshape(b, hkv, c, pmax * ps)
    qg = q.reshape(b, hkv, h // hkv, t, c)
    # THE BUG: cast-early scores (f32 multiply operands)
    s_pool = jnp.einsum(
        "bhgtc,bhcw->bhgtw",
        qg.astype(jnp.float32), ck.astype(jnp.float32),
    )
    s_self = jnp.einsum(
        "bhgtc,bhsc->bhgts",
        qg.astype(jnp.float32), k.astype(jnp.float32),
    )
    s_all = jnp.concatenate(
        [s_pool + mask_pool, s_self + mask_self], axis=-1
    )
    scale = 1.0 / jnp.sqrt(c).astype(jnp.float32)
    probs = jax.nn.softmax(s_all * scale, axis=-1)
    # THE BUG (cont.): f32 probs straight into the PV contraction
    p_pool = probs[..., : s_pool.shape[-1]]
    p_self = probs[..., s_pool.shape[-1]:]
    o_pool = jnp.einsum(
        "bhgtw,bhcw->bhgtc", p_pool, cv.astype(jnp.float32)
    )
    o_self = jnp.einsum(
        "bhgts,bhsc->bhgtc", p_self, v.astype(jnp.float32)
    )
    out = (o_pool + o_self).reshape(b, h, t, c)
    out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, t, h * c)
    out = shard_act(out, None, None, "heads")
    return self.wo(out.astype(x.dtype)), k, v


def test_prover_catches_cast_early_prefill(monkeypatch):
    engine_mod._PROGRAM_CACHE.clear()
    monkeypatch.setattr(
        Attention, "prefill_paged_at", _cast_early_prefill_paged_at
    )
    try:
        rep = prove_serving_choreography("openwebtext")
    finally:
        engine_mod._PROGRAM_CACHE.clear()
    assert not rep.ok
    checks = _checks(rep)
    assert checks["prefill-mirrors-naive"] is False
    # the decode/verify contract is untouched by a prefill fault
    assert checks["verify-mirrors-decode"] is True


# ---------------------------------------------------------------------------
# fault injection: the PR 5 bug (prefill-choreography verify)
# ---------------------------------------------------------------------------


def _prefill_flavored_verify_paged_at(
    self, x, pool_k, pool_v, bt, layer, mask_pool, mask_self,
    sin_rows, cos_rows, **_new_kwargs,
):
    """verify_paged_at as PR 5's FIRST CUT wrote it: the prefill
    chunk's choreography (bf16 score einsums with f32 accumulation,
    ``* scale``, probs rounded to the value dtype, no cache-dtype
    rounding of the in-dispatch self K/V) instead of the decode
    window's. Flips near-tied acceptance argmaxes on bf16 checkpoints."""
    from midgpt_tpu.models.layers import apply_rotary

    b, t, d = x.shape
    h, hkv = self.n_head, self.n_kv_head
    c = d // h
    qkv = self.wqkv(x)
    q = qkv[..., : h * c].reshape(b, t, h, c)
    k = qkv[..., h * c : (h + hkv) * c].reshape(b, t, hkv, c)
    v = qkv[..., (h + hkv) * c :].reshape(b, t, hkv, c)
    if self.q_norm is not None:
        q = self.q_norm(q)
        k = self.k_norm(k)
    q = jnp.transpose(q, (0, 2, 1, 3))
    k = jnp.transpose(k, (0, 2, 1, 3))
    v = jnp.transpose(v, (0, 2, 1, 3))
    q = apply_rotary(q, sin_rows, cos_rows)
    k = apply_rotary(k, sin_rows, cos_rows)
    pk_l = jnp.take(pool_k[layer], bt, axis=0, mode="clip")
    pv_l = jnp.take(pool_v[layer], bt, axis=0, mode="clip")
    _, pmax, _, _, ps = pk_l.shape
    ck = jnp.transpose(pk_l, (0, 2, 3, 1, 4)).reshape(b, hkv, c, pmax * ps)
    cv = jnp.transpose(pv_l, (0, 2, 3, 1, 4)).reshape(b, hkv, c, pmax * ps)
    qg = q.reshape(b, hkv, h // hkv, t, c)
    # THE BUG: prefill-flavored scores (compute-dtype operands, f32
    # accumulate) instead of the decode window's f32-upcast VPU form
    s_pool = jnp.einsum(
        "bhgtc,bhcw->bhgtw", qg, ck.astype(qg.dtype),
        preferred_element_type=jnp.float32,
    )
    s_self = jnp.einsum(
        "bhgtc,bhsc->bhgts", qg, k,
        preferred_element_type=jnp.float32,
    )
    s_all = jnp.concatenate(
        [s_pool + mask_pool, s_self + mask_self], axis=-1
    )
    scale = 1.0 / jnp.sqrt(c).astype(jnp.float32)
    probs = jax.nn.softmax(s_all * scale, axis=-1)
    # THE BUG (cont.): probs rounded to the value dtype before PV
    probs = probs.astype(v.dtype)
    p_pool = probs[..., : s_pool.shape[-1]]
    p_self = probs[..., s_pool.shape[-1]:]
    o_pool = jnp.einsum("bhgtw,bhcw->bhgtc", p_pool, cv.astype(v.dtype))
    o_self = jnp.einsum("bhgts,bhsc->bhgtc", p_self, v)
    out = (o_pool + o_self).reshape(b, h, t, c)
    out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, t, h * c)
    out = shard_act(out, None, None, "heads")
    return self.wo(out.astype(x.dtype)), k, v


def test_prover_catches_prefill_flavored_verify(monkeypatch):
    engine_mod._PROGRAM_CACHE.clear()
    monkeypatch.setattr(
        Attention, "verify_paged_at", _prefill_flavored_verify_paged_at
    )
    try:
        rep = prove_serving_choreography("openwebtext")
    finally:
        engine_mod._PROGRAM_CACHE.clear()
    assert not rep.ok
    checks = _checks(rep)
    assert checks["verify-mirrors-decode"] is False
    # the prefill/naive contract is untouched by a verify fault
    assert checks["prefill-mirrors-naive"] is True


# ---------------------------------------------------------------------------
# fault injection: scale applied before the mask (ordering drift)
# ---------------------------------------------------------------------------


def _scale_before_mask_decode_paged_at(
    self, x, pool_k, pool_v, bt, rk, rv, layer, r, mask_pool, mask_rec,
    sin_rows, cos_rows, **_new_kwargs,
):
    """decode_paged_at with the softmax argument order flipped: scores
    are scaled BEFORE the additive mask lands, so the -inf mask is
    divided too — a drift the shared-arithmetic check must flag even
    though decode and verify would still agree with each other if both
    drifted (which they don't here: only decode is patched, so the
    op-for-op check fires first; the dedicated ordering check is what
    fires when BOTH paths drift together)."""
    b, one, d = x.shape
    h, hkv = self.n_head, self.n_kv_head
    c = d // h
    q, k, v = self._decode_qkv(x, sin_rows, cos_rows)
    zero = jnp.zeros((), r.dtype)
    at = (jnp.asarray(layer, r.dtype), zero, zero, r, zero)
    rk = jax.lax.dynamic_update_slice(rk, k.astype(rk.dtype)[None], at)
    rv = jax.lax.dynamic_update_slice(rv, v.astype(rv.dtype)[None], at)
    pk_l = jnp.take(pool_k[layer], bt, axis=0, mode="clip")
    pv_l = jnp.take(pool_v[layer], bt, axis=0, mode="clip")
    s_, pmax, _, _, ps = pk_l.shape
    ck = jnp.transpose(pk_l, (0, 2, 3, 1, 4)).reshape(b, hkv, c, pmax * ps)
    cv = jnp.transpose(pv_l, (0, 2, 3, 1, 4)).reshape(b, hkv, c, pmax * ps)
    rkl, rvl = rk[layer], rv[layer]
    qg = q.reshape(b, hkv, h // hkv, 1, c)
    qcw = jnp.transpose(qg, (0, 1, 2, 4, 3))
    s_pool = jnp.sum(
        qcw.astype(jnp.float32) * ck[:, :, None].astype(jnp.float32),
        axis=-2,
    )
    s_rec = jnp.sum(
        qg.astype(jnp.float32) * rkl[:, :, None].astype(jnp.float32),
        axis=-1,
    )
    # THE BUG: scale first, then add the mask
    s_all = jnp.concatenate(
        [
            s_pool / math.sqrt(c) + mask_pool[:, None, None, :],
            s_rec / math.sqrt(c) + mask_rec,
        ],
        axis=-1,
    )
    probs = jax.nn.softmax(s_all, axis=-1)
    p_pool = probs[..., : s_pool.shape[-1]]
    p_rec = probs[..., s_pool.shape[-1]:]
    o_pool = jnp.sum(
        p_pool[:, :, :, None, :] * cv[:, :, None].astype(jnp.float32),
        axis=-1,
    )
    o_rec = jnp.sum(
        p_rec[..., None] * rvl[:, :, None].astype(jnp.float32), axis=-2
    )
    out = (o_pool + o_rec).astype(x.dtype)
    out = out.reshape(b, h, 1, c)
    out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, 1, h * c)
    return self.wo(out), rk, rv


def test_prover_catches_scale_before_mask(monkeypatch):
    engine_mod._PROGRAM_CACHE.clear()
    monkeypatch.setattr(
        Attention, "decode_paged_at", _scale_before_mask_decode_paged_at
    )
    try:
        rep = prove_serving_choreography("openwebtext")
    finally:
        engine_mod._PROGRAM_CACHE.clear()
    assert not rep.ok
    checks = _checks(rep)
    # the patched decode drifts away from the (unpatched) verify, and
    # the ordering invariant itself fires
    assert (
        checks["verify-mirrors-decode"] is False
        or checks[
            "shared: mask is added before the softmax scale everywhere"
        ] is False
    )


# ---------------------------------------------------------------------------
# Pallas paged-attention kernel as a contract node (PR 9)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def kernel_report():
    return prove_serving_choreography("openwebtext", paged_kernel="pallas")


def test_prover_passes_on_kernel_path(kernel_report):
    assert kernel_report.ok, "\n".join(
        f"{c.name}: {c.detail}"
        for c in kernel_report.checks
        if not c.ok
    )
    progs = {p.name: p for p in kernel_report.programs}
    # decode and verify run INSIDE the kernel; the prefill chunk stays
    # on the XLA einsum path (compute-bound, naive-contract)
    assert progs["decode_window"].kernelized
    assert progs["verify"].kernelized
    assert not progs["prefill_chunk"].kernelized


def test_kernel_node_is_one_record_and_bodies_match_decode_contract(
    kernel_report,
):
    """The kernel appears as a single 'paged_kernel' contract node in
    the attention traces (not as inlined internals), decode == verify
    op for op across it, and the KERNEL BODY's softmax signature equals
    the XLA decode window's — same f32 accumulation, mask-before-scale,
    f32 softmax, f32 probs through PV."""
    progs = {p.name: p for p in kernel_report.programs}
    dec = progs["decode_window"]
    kinds = [rec[0] for rec in dec.attention]
    assert kinds.count("paged_kernel") == 1
    assert dec.attention == progs["verify"].attention
    xla = prove_serving_choreography("openwebtext")
    xla_dec = {p.name: p for p in xla.programs}["decode_window"]
    assert dec.softmax == xla_dec.softmax


def test_prover_proves_kv_dequant_contract():
    rep = prove_serving_choreography(
        "openwebtext", kv_quant=True, paged_kernel="pallas"
    )
    assert rep.ok, "\n".join(
        f"{c.name}: {c.detail}" for c in rep.checks if not c.ok
    )
    for p in rep.programs:
        if p.name != "naive_reference":
            assert p.kv_dequant, p.name
    # and the float-pool trace must NOT carry a stray dequant
    rep2 = prove_serving_choreography("openwebtext", paged_kernel="pallas")
    for p in rep2.programs:
        assert not p.kv_dequant, p.name


def test_prover_catches_bf16_accumulating_kernel(monkeypatch):
    """Fault injection: a kernel variant that accumulates QK scores in
    bf16 (SCORE_ACC_DTYPE is the kernels' contract point) must turn the
    prover red. The failure lands on the extraction-degeneracy guard:
    jnp silently RE-PROMOTES half-precision reductions, so the faulty
    kernel's score chain grows convert hops that break the signature
    walk — and a signature the prover can no longer read is a
    violation, never a vacuous pass (this exact fault used to slip
    through before the guard existed)."""
    from midgpt_tpu.ops import paged_attn

    engine_mod._PROGRAM_CACHE.clear()
    monkeypatch.setattr(paged_attn, "SCORE_ACC_DTYPE", jnp.bfloat16)
    try:
        rep = prove_serving_choreography(
            "openwebtext", paged_kernel="pallas"
        )
    finally:
        engine_mod._PROGRAM_CACHE.clear()
    assert not rep.ok
    checks = _checks(rep)
    assert (
        checks["shared: scores accumulate in f32 everywhere"] is False
        or checks[
            "shared: every program exposes its score contractions "
            "to the prover"
        ] is False
    )


_BAND_CLAUSE = "shared: banded PV accumulation runs in pinned ascending-band order"


@pytest.mark.parametrize("kern", ["xla", "pallas"])
def test_prover_proves_banded_fold_order_multiband(kern, monkeypatch):
    """Banded-accumulation-order clause (ISSUE 20), on a genuinely
    multi-banded plan: force 2 pages per band so the PV fold has two
    pool-band partials plus the recent/self partial, and the prover
    must extract the pinned ascending offsets (0, 32, 64) — identical
    for decode and verify, on the kernel body AND the banded XLA
    reference — with every clause green."""
    from midgpt_tpu.ops import paged_attn

    engine_mod._PROGRAM_CACHE.clear()
    monkeypatch.setattr(paged_attn, "_FORCE_BAND_PAGES", 2)
    try:
        rep = prove_serving_choreography("openwebtext", paged_kernel=kern)
    finally:
        engine_mod._PROGRAM_CACHE.clear()
    assert rep.ok, "\n".join(
        f"{c.name}: {c.detail}" for c in rep.checks if not c.ok
    )
    order = {p.name: p.band_order for p in rep.programs}
    assert order["decode_window"] == order["verify"] == (0, 32, 64)
    # einsum-contracted programs have no fold: exempt by construction
    assert order["prefill_chunk"] is None
    assert order["naive_reference"] is None


def test_prover_catches_descending_band_fold(monkeypatch):
    """Fault injection (the ISSUE 20 clause): reverse the band fold —
    banded_fold summing descending instead of the pinned ascending
    order. f32 addition is not associative, so this is a bitwise drift
    no dtype check can see; the prover must fail EXACTLY the band-order
    clause while every sibling clause stays green (kernel == XLA
    survives the flip because BOTH sides fold through banded_fold)."""
    from midgpt_tpu.ops import paged_attn

    engine_mod._PROGRAM_CACHE.clear()
    monkeypatch.setattr(paged_attn, "_FORCE_BAND_PAGES", 2)
    monkeypatch.setattr(paged_attn, "_BAND_FOLD_ORDER", "descending")
    try:
        rep = prove_serving_choreography(
            "openwebtext", paged_kernel="pallas"
        )
    finally:
        engine_mod._PROGRAM_CACHE.clear()
    assert not rep.ok
    checks = _checks(rep)
    assert checks[_BAND_CLAUSE] is False
    for name, ok in checks.items():
        if name != _BAND_CLAUSE:
            assert ok is True, name
    detail = {c.name: c.detail for c in rep.checks}[_BAND_CLAUSE]
    assert "band_order" in detail


# ---------------------------------------------------------------------------
# the sampled-verify prover (temperature > 0): the verify program's
# rejection-sampling arithmetic proven against the decode window's
# sampler, plus the acceptance-compare dtype fault injection
# ---------------------------------------------------------------------------

_SAMPLED_CHECKS = (
    "sampled: verify row-0 sampler mirrors the decode window's "
    "categorical",
    "sampled: acceptance compares run in f32",
    "sampled: residual renormalization runs in f32",
    "sampled: target softmax runs in f32 in the verify sampler",
)


@pytest.fixture(scope="module")
def sampled_report():
    return prove_serving_choreography(
        "openwebtext", temperature=0.8, top_k=20
    )


def test_sampled_prover_passes_on_current_tree(sampled_report):
    assert sampled_report.ok, "\n".join(
        f"{c.name}: {c.detail}"
        for c in sampled_report.checks
        if not c.ok
    )
    checks = _checks(sampled_report)
    for name in _SAMPLED_CHECKS:
        assert checks[name] is True, name


def test_sampled_checks_ride_next_to_the_greedy_contracts(
    healthy_report, sampled_report
):
    """The T>0 report is the greedy report's check set PLUS the four
    sampled clauses — the greedy choreography contracts (verify mirrors
    decode, f32 softmax, mask-before-scale, ...) must keep being proven
    on the sampled programs, and the greedy report must NOT grow
    sampled clauses (there is no sampler to extract at argmax)."""
    greedy = set(_checks(healthy_report))
    sampled = set(_checks(sampled_report))
    assert sampled == greedy | set(_SAMPLED_CHECKS)
    assert not greedy & set(_SAMPLED_CHECKS)


def test_sampled_prover_passes_on_quant_kernel_cell():
    """One production-precision sampled cell (int8 weights + int8 KV +
    Pallas kernel) — the composition the CI matrix proves exhaustively;
    this pins it in the suite so a local regression fails fast."""
    rep = prove_serving_choreography(
        "openwebtext", quant=True, kv_quant=True, paged_kernel="pallas",
        temperature=0.8, top_k=20,
    )
    assert rep.ok, "\n".join(
        f"{c.name}: {c.detail}" for c in rep.checks if not c.ok
    )


def test_sampled_prover_catches_bf16_acceptance_compare(monkeypatch):
    """Fault injection (the ISSUE 17 clause): re-introduce a
    drifted-dtype acceptance compare — the rejection test
    ``u * q <= p`` evaluated in bf16 — and the prover must fail EXACTLY
    the acceptance-compare clause while every sibling sampled clause
    stays green (the fault is in the compare, not in the categorical,
    the residual, or the softmax)."""
    from midgpt_tpu import sampling as sampling_mod

    def bf16_acceptance(u, q_sel, p_sel):
        return (
            u.astype(jnp.bfloat16) * q_sel.astype(jnp.bfloat16)
        ) <= p_sel.astype(jnp.bfloat16)

    engine_mod._PROGRAM_CACHE.clear()
    monkeypatch.setattr(sampling_mod, "acceptance_mask", bf16_acceptance)
    try:
        rep = prove_serving_choreography(
            "openwebtext", temperature=0.8, top_k=20
        )
    finally:
        engine_mod._PROGRAM_CACHE.clear()
    assert not rep.ok
    checks = _checks(rep)
    assert checks["sampled: acceptance compares run in f32"] is False
    for name in _SAMPLED_CHECKS:
        if name != "sampled: acceptance compares run in f32":
            assert checks[name] is True, name
    detail = {c.name: c.detail for c in rep.checks}[
        "sampled: acceptance compares run in f32"
    ]
    assert "bfloat16" in detail
